"""Legacy CamelCase operator surface for ``mx.nd`` / ``mx.sym``.

Reference parity: the 1.x generated wrappers
(python/mxnet/ndarray/register.py:115-277 code-gens a python function per
registered op; symbol/register.py does the same for Symbol) expose every
``NNVM_REGISTER_OP`` name — including the CamelCase layer ops
(FullyConnected, Convolution, BatchNorm, SliceChannel, ...) that 1.x
model scripts and serialized symbol graphs use.

TPU-native design: instead of code-gen from a C registry, a table of
thin adapters maps each legacy name + legacy kwargs (``num_hidden``,
``no_bias``, ``kernel``...) onto the mx.np / mx.npx implementations (which
lower to XLA).  Both ``mx.nd.__getattr__`` and the Symbol resolver consult
this one table, so eager and symbolic results match exactly, and symbol
json graphs written by 1.x (attrs as strings) evaluate here: every adapter
literal-parses string attrs like ``kernel="(3, 3)"``.
"""
from __future__ import annotations

import ast
import functools

from ..base import MXNetError

LEGACY_OPS: dict = {}


def register(name):
    def deco(fn):
        fn.__name__ = name
        LEGACY_OPS[name] = fn
        return fn
    return deco


_OUT_WRAPPED: dict = {}


def with_out(fn):
    """Wrap an op so ``out=`` writes through to the destination array
    (reference generated-wrapper semantics, ndarray/register.py:171)."""
    w = _OUT_WRAPPED.get(fn)
    if w is None:
        @functools.wraps(fn)
        def w(*args, **kwargs):
            out = kwargs.pop("out", None)
            res = fn(*args, **kwargs)
            if out is None:
                return res
            from ..numpy.multiarray import _writeback
            return _writeback(out, res)
        _OUT_WRAPPED[fn] = w
    return w


def get(name):
    fn = LEGACY_OPS.get(name)
    return None if fn is None else with_out(fn)


# -- legacy attr parsing -----------------------------------------------------
def _lit(v):
    """Parse legacy string attrs: "(3, 3)" -> (3, 3), "True" -> True,
    "2" -> 2.  Non-strings pass through."""
    if isinstance(v, str):
        try:
            return ast.literal_eval(v)
        except (ValueError, SyntaxError):
            return v
    return v


def _tup(v, n=None):
    v = _lit(v)
    if v is None:
        return None
    if isinstance(v, int):
        return (v,) * (n or 1)
    return tuple(v)


def _b(v):
    v = _lit(v)
    if isinstance(v, str):
        return v.lower() in ("true", "1")
    return bool(v)


def _drop_name(kw):
    kw.pop("name", None)
    kw.pop("ctx", None)
    return kw


# -- neural-network layers ---------------------------------------------------
@register("FullyConnected")
def _fully_connected(data, weight=None, bias=None, num_hidden=None,
                     no_bias=False, flatten=True, **kw):
    from .. import numpy_extension as npx
    _drop_name(kw)
    return npx.fully_connected(data, weight, bias,
                               num_hidden=int(_lit(num_hidden)),
                               no_bias=_b(no_bias), flatten=_b(flatten))


@register("Convolution")
def _convolution(data, weight=None, bias=None, kernel=None, stride=None,
                 dilate=None, pad=None, num_filter=1, num_group=1,
                 workspace=1024, no_bias=False, layout=None, **kw):
    from .. import numpy_extension as npx
    _drop_name(kw)
    kernel = _tup(kernel)
    n = len(kernel)
    return npx.convolution(data, weight, bias, kernel=kernel,
                           stride=_tup(stride, n), dilate=_tup(dilate, n),
                           pad=_tup(pad, n), num_filter=int(_lit(num_filter)),
                           num_group=int(_lit(num_group)), no_bias=_b(no_bias),
                           layout=_lit(layout))


@register("Deconvolution")
def _deconvolution(data, weight=None, bias=None, kernel=None, stride=None,
                   dilate=None, pad=None, adj=None, target_shape=None,
                   num_filter=1, num_group=1, workspace=512, no_bias=True,
                   layout=None, **kw):
    from .. import numpy_extension as npx
    _drop_name(kw)
    kernel = _tup(kernel)
    n = len(kernel)
    return npx.deconvolution(data, weight, bias, kernel=kernel,
                             stride=_tup(stride, n), dilate=_tup(dilate, n),
                             pad=_tup(pad, n), adj=_tup(adj, n),
                             num_filter=int(_lit(num_filter)),
                             num_group=int(_lit(num_group)),
                             no_bias=_b(no_bias), layout=_lit(layout))


@register("BatchNorm")
def _batch_norm(data, gamma=None, beta=None, moving_mean=None,
                moving_var=None, eps=1e-3, momentum=0.9, fix_gamma=True,
                use_global_stats=False, output_mean_var=False, axis=1, **kw):
    from .. import numpy_extension as npx
    _drop_name(kw)
    return npx.batch_norm(data, gamma, beta, moving_mean, moving_var,
                          eps=float(_lit(eps)), momentum=float(_lit(momentum)),
                          fix_gamma=_b(fix_gamma),
                          use_global_stats=_b(use_global_stats),
                          output_mean_var=_b(output_mean_var),
                          axis=int(_lit(axis)))


@register("LayerNorm")
def _layer_norm(data, gamma=None, beta=None, axis=-1, eps=1e-5, **kw):
    from .. import numpy_extension as npx
    _drop_name(kw)
    return npx.layer_norm(data, gamma, beta, axis=int(_lit(axis)),
                          eps=float(_lit(eps)))


@register("GroupNorm")
def _group_norm(data, gamma=None, beta=None, num_groups=1, eps=1e-5, **kw):
    from .. import numpy_extension as npx
    _drop_name(kw)
    return npx.group_norm(data, gamma, beta, num_groups=int(_lit(num_groups)),
                          eps=float(_lit(eps)))


@register("InstanceNorm")
def _instance_norm(data, gamma=None, beta=None, eps=1e-3, **kw):
    from .. import numpy_extension as npx
    _drop_name(kw)
    return npx.instance_norm(data, gamma, beta, eps=float(_lit(eps)))


@register("L2Normalization")
def _l2_normalization(data, eps=1e-10, mode="instance", **kw):
    from .. import numpy_extension as npx
    _drop_name(kw)
    return npx.l2_normalization(data, eps=float(_lit(eps)), mode=_lit(mode))


@register("Activation")
def _activation(data, act_type="relu", **kw):
    from .. import numpy_extension as npx
    _drop_name(kw)
    return npx.activation(data, act_type=_lit(act_type))


@register("LeakyReLU")
def _leaky_relu(data, gamma=None, act_type="leaky", slope=0.25,
                lower_bound=0.125, upper_bound=0.334, **kw):
    from .. import numpy_extension as npx
    _drop_name(kw)
    return npx.leaky_relu(data, gamma, act_type=_lit(act_type),
                          slope=float(_lit(slope)),
                          lower_bound=float(_lit(lower_bound)),
                          upper_bound=float(_lit(upper_bound)))


@register("Pooling")
def _pooling(data, kernel=1, stride=None, pad=None, pool_type="max",
             pooling_convention="valid", global_pool=False, p_value=2,
             count_include_pad=True, layout=None, **kw):
    from .. import numpy_extension as npx
    _drop_name(kw)
    n = data.ndim - 2
    kernel = _tup(kernel, n)
    return npx.pooling(data, kernel=kernel, stride=_tup(stride, n),
                       pad=_tup(pad, n), pool_type=_lit(pool_type),
                       pooling_convention=_lit(pooling_convention),
                       global_pool=_b(global_pool),
                       p_value=int(_lit(p_value)),
                       count_include_pad=_b(count_include_pad),
                       layout=_lit(layout) if layout
                       else {1: "NCW", 2: "NCHW", 3: "NCDHW"}[n])


@register("Dropout")
def _dropout(data, p=0.5, mode="training", axes=None, **kw):
    from .. import numpy_extension as npx
    _drop_name(kw)
    return npx.dropout(data, p=float(_lit(p)), mode=_lit(mode),
                       axes=_tup(axes) if axes else None)


@register("Embedding")
def _embedding(data, weight=None, input_dim=None, output_dim=None,
               dtype="float32", sparse_grad=False, **kw):
    from .. import numpy_extension as npx
    _drop_name(kw)
    return npx.embedding(data, weight, input_dim=int(_lit(input_dim)),
                         output_dim=int(_lit(output_dim)),
                         sparse_grad=_b(sparse_grad))


@register("RNN")
def _rnn(data, parameters=None, state=None, state_cell=None, mode="lstm",
         state_size=None, num_layers=1, bidirectional=False, p=0.0,
         state_outputs=False, projection_size=None, sequence_length=None,
         use_sequence_length=False, **kw):
    from .. import numpy_extension as npx
    _drop_name(kw)
    return npx.rnn(data=data, parameters=parameters, state=state,
                   state_cell=state_cell, mode=_lit(mode),
                   state_size=int(_lit(state_size)),
                   num_layers=int(_lit(num_layers)),
                   bidirectional=_b(bidirectional), p=float(_lit(p)),
                   state_outputs=_b(state_outputs),
                   projection_size=(int(_lit(projection_size))
                                    if projection_size else None),
                   use_sequence_length=_b(use_sequence_length),
                   sequence_length=sequence_length)


# -- shape / data movement ---------------------------------------------------
@register("Reshape")
def _reshape(data, shape=None, reverse=False, target_shape=None,
             keep_highest=False, **kw):
    from .. import numpy_extension as npx
    _drop_name(kw)
    if shape is None and target_shape is not None:
        # pre-1.0 attr: exact output shape, 0 = keep the input dim
        # (+ keep_highest preserving dim 0); matrix_op-inl.h legacy path
        tgt = _tup(target_shape)
        out = tuple(data.shape[i] if (s == 0 or (_b(keep_highest) and i == 0))
                    else s for i, s in enumerate(tgt))
        return data.reshape(out)
    if shape is None:
        raise MXNetError("Reshape requires shape or target_shape")
    return npx.reshape(data, _tup(shape), reverse=_b(reverse))


@register("Flatten")
def _flatten(data, **kw):
    _drop_name(kw)
    return data.reshape((data.shape[0], -1))


@register("Concat")
def _concat(*data, dim=1, num_args=None, **kw):
    from .. import numpy as _np
    _drop_name(kw)
    if len(data) == 1 and isinstance(data[0], (list, tuple)):
        data = tuple(data[0])
    return _np.concatenate(data, axis=int(_lit(dim)))


@register("SliceChannel")
def _slice_channel(data, num_outputs=1, axis=1, squeeze_axis=False, **kw):
    from .. import numpy as _np
    _drop_name(kw)
    num_outputs = int(_lit(num_outputs))
    axis = int(_lit(axis))
    parts = _np.split(data, num_outputs, axis=axis)
    if _b(squeeze_axis):
        parts = [p.squeeze(axis=axis) for p in parts]
    return parts


@register("SwapAxis")
def _swap_axis(data, dim1=0, dim2=0, **kw):
    from .. import numpy as _np
    _drop_name(kw)
    return _np.swapaxes(data, int(_lit(dim1)), int(_lit(dim2)))


@register("ExpandDims")
def _expand_dims(data, axis=0, **kw):
    from .. import numpy as _np
    _drop_name(kw)
    return _np.expand_dims(data, int(_lit(axis)))


@register("Cast")
def _cast(data, dtype=None, **kw):
    _drop_name(kw)
    return data.astype(_lit(dtype))


@register("Pad")
def _pad(data, mode="constant", pad_width=None, constant_value=0, **kw):
    from .. import numpy as _np
    _drop_name(kw)
    pw = _tup(pad_width)
    pairs = tuple((pw[2 * i], pw[2 * i + 1]) for i in range(len(pw) // 2))
    mode = _lit(mode)
    if mode == "constant":
        return _np.pad(data, pairs, mode="constant",
                       constant_values=float(_lit(constant_value)))
    return _np.pad(data, pairs, mode={"edge": "edge",
                                      "reflect": "reflect"}[mode])


@register("UpSampling")
def _up_sampling(*data, scale=1, sample_type="nearest", num_filter=0,
                 multi_input_mode="concat", num_args=1, **kw):
    from ..numpy.multiarray import _invoke
    _drop_name(kw)
    scale = int(_lit(scale))
    sample_type = _lit(sample_type)
    x = data[0]

    def fn(x_, *rest):
        import jax
        import jax.numpy as jnp
        if sample_type == "nearest":
            return jnp.repeat(jnp.repeat(x_, scale, axis=2), scale, axis=3)
        n, c, h, w = x_.shape
        return jax.image.resize(x_, (n, c, h * scale, w * scale), "bilinear")
    return _invoke(fn, (x,), name="upsampling")


@register("Crop")
def _crop(*data, offset=(0, 0), h_w=(0, 0), center_crop=False,
          num_args=1, **kw):
    _drop_name(kw)
    x = data[0]
    offset, h_w = _tup(offset, 2), _tup(h_w, 2)
    if len(data) > 1:
        h, w = data[1].shape[2], data[1].shape[3]
    else:
        h, w = h_w
    if _b(center_crop):
        oy = (x.shape[2] - h) // 2
        ox = (x.shape[3] - w) // 2
    else:
        oy, ox = offset
    return x[:, :, oy:oy + h, ox:ox + w]


# -- loss-layer ops ----------------------------------------------------------
@register("SoftmaxOutput")
def _softmax_output(data, label=None, grad_scale=1.0, ignore_label=-1,
                    multi_output=False, use_ignore=False, preserve_shape=False,
                    normalization="null", out_grad=False,
                    smooth_alpha=0.0, **kw):
    """Reference: src/operator/softmax_output.cc — forward is softmax; the
    backward IGNORES the incoming head gradient and emits
    (softmax - one_hot(label)) * grad_scale, i.e. the op is a loss layer."""
    import functools

    import jax
    import jax.numpy as jnp

    from ..numpy.multiarray import _invoke
    _drop_name(kw)
    grad_scale = float(_lit(grad_scale))
    ignore_label = float(_lit(ignore_label))
    use_ignore = _b(use_ignore)
    multi_output = _b(multi_output)
    normalization = _lit(normalization)

    @functools.partial(jax.custom_vjp, nondiff_argnums=())
    def softmax_out(x, lab):
        return _fwd(x, lab)[0]

    def _fwd(x, lab):
        axis = 1 if (multi_output and x.ndim > 2) else -1
        out = jax.nn.softmax(x, axis=axis)
        return out, (out, lab)

    def _bwd(res, dy):
        out, lab = res
        axis = 1 if (multi_output and out.ndim > 2) else -1
        nclass = out.shape[axis]
        lab_i = lab.astype(jnp.int32)
        onehot = jax.nn.one_hot(lab_i, nclass, dtype=out.dtype, axis=axis)
        g = out - onehot
        if use_ignore:
            keep = (lab != ignore_label)
            keep = jnp.expand_dims(keep, axis if axis != -1 else out.ndim - 1)
            g = jnp.where(keep, g, jnp.zeros((), g.dtype))
        scale = grad_scale
        if normalization == "batch":
            scale = scale / out.shape[0]
        elif normalization == "valid" and use_ignore:
            valid = jnp.maximum(jnp.sum(lab != ignore_label), 1)
            scale = scale / valid
        g = g * scale
        return g.astype(out.dtype), jnp.zeros_like(lab)

    softmax_out.defvjp(_fwd, _bwd)
    return _invoke(softmax_out, (data, label), name="softmax_output")


@register("LinearRegressionOutput")
def _linear_regression_output(data, label=None, grad_scale=1.0, **kw):
    import functools

    import jax
    import jax.numpy as jnp

    from ..numpy.multiarray import _invoke
    _drop_name(kw)
    grad_scale = float(_lit(grad_scale))

    @jax.custom_vjp
    def linreg(x, lab):
        return x

    def _fwd(x, lab):
        return x, (x, lab)

    def _bwd(res, dy):
        x, lab = res
        g = (x - lab.reshape(x.shape)) * grad_scale / x.shape[0]
        return g.astype(x.dtype), jnp.zeros_like(lab)
    linreg.defvjp(_fwd, _bwd)
    return _invoke(linreg, (data, label), name="linear_regression_output")


@register("LogisticRegressionOutput")
def _logistic_regression_output(data, label=None, grad_scale=1.0, **kw):
    import jax
    import jax.numpy as jnp

    from ..numpy.multiarray import _invoke
    _drop_name(kw)
    grad_scale = float(_lit(grad_scale))

    @jax.custom_vjp
    def logreg(x, lab):
        return jax.nn.sigmoid(x)

    def _fwd(x, lab):
        out = jax.nn.sigmoid(x)
        return out, (out, lab)

    def _bwd(res, dy):
        out, lab = res
        g = (out - lab.reshape(out.shape)) * grad_scale / out.shape[0]
        return g.astype(out.dtype), jnp.zeros_like(lab)
    logreg.defvjp(_fwd, _bwd)
    return _invoke(logreg, (data, label), name="logistic_regression_output")


@register("MAERegressionOutput")
def _mae_regression_output(data, label=None, grad_scale=1.0, **kw):
    import jax
    import jax.numpy as jnp

    from ..numpy.multiarray import _invoke
    _drop_name(kw)
    grad_scale = float(_lit(grad_scale))

    @jax.custom_vjp
    def mae(x, lab):
        return x

    def _fwd(x, lab):
        return x, (x, lab)

    def _bwd(res, dy):
        x, lab = res
        g = jnp.sign(x - lab.reshape(x.shape)) * grad_scale / x.shape[0]
        return g.astype(x.dtype), jnp.zeros_like(lab)
    mae.defvjp(_fwd, _bwd)
    return _invoke(mae, (data, label), name="mae_regression_output")


@register("MakeLoss")
def _make_loss(data, grad_scale=1.0, valid_thresh=0.0,
               normalization="null", **kw):
    import jax
    import jax.numpy as jnp

    from ..numpy.multiarray import _invoke
    _drop_name(kw)
    grad_scale = float(_lit(grad_scale))
    normalization = _lit(normalization)

    @jax.custom_vjp
    def make_loss(x):
        return x

    def _fwd(x):
        return x, x

    def _bwd(x, dy):
        scale = grad_scale
        if normalization == "batch":
            scale = scale / x.shape[0]
        return (jnp.full_like(x, scale),)
    make_loss.defvjp(_fwd, _bwd)
    return _invoke(make_loss, (data,), name="make_loss")


@register("BlockGrad")
def _block_grad(data, **kw):
    from jax import lax

    from ..numpy.multiarray import _invoke
    _drop_name(kw)
    return _invoke(lax.stop_gradient, (data,), name="stop_gradient")


@register("IdentityAttachKLSparseReg")
def _identity_attach_kl(data, sparseness_target=0.1, penalty=0.001,
                        momentum=0.9, **kw):
    _drop_name(kw)
    return data


@register("CTCLoss")
def _ctc_loss(data, label=None, data_lengths=None, label_lengths=None,
              use_data_lengths=False, use_label_lengths=False,
              blank_label="first", **kw):
    """Reference: src/operator/nn/ctc_loss.cc (data is (T, N, C))."""
    import jax
    import jax.numpy as jnp
    import optax

    from ..numpy.multiarray import _invoke
    _drop_name(kw)

    blank = _lit(blank_label)
    use_dl = _b(use_data_lengths)

    def fn(d, lab, *rest):
        tnc = jnp.transpose(d, (1, 0, 2))  # (N, T, C)
        n, t, c = tnc.shape
        logp = jax.nn.log_softmax(tnc, axis=-1)
        lab_i = lab.astype(jnp.int32)
        if use_dl and rest:
            dl = rest[0].astype(jnp.int32)
            logit_pad = (jnp.arange(t)[None, :] >=
                         dl[:, None]).astype(jnp.float32)
        else:
            logit_pad = jnp.zeros((n, t))
        if blank == "first":
            # blank = class 0, labels are 1-based, 0-padded (ctc_loss.cc)
            lab_pad = (lab_i <= 0).astype(jnp.float32)
            loss = optax.ctc_loss(logp, logit_pad, lab_i, lab_pad,
                                  blank_id=0)
        else:
            # blank = class C-1, labels 0-based, padded with -1
            lab_pad = (lab_i < 0).astype(jnp.float32)
            loss = optax.ctc_loss(logp, logit_pad,
                                  jnp.maximum(lab_i, 0), lab_pad,
                                  blank_id=c - 1)
        return loss

    args = (data, label) if not (use_dl and data_lengths is not None) \
        else (data, label, data_lengths)
    return _invoke(fn, args, name="ctc_loss")


# -- misc --------------------------------------------------------------------
@register("ElementWiseSum")
def _element_wise_sum(*args, num_args=None, **kw):
    _drop_name(kw)
    if len(args) == 1 and isinstance(args[0], (list, tuple)):
        args = tuple(args[0])
    out = args[0]
    for a in args[1:]:
        out = out + a
    return out


@register("LRN")
def _lrn(data, alpha=1e-4, beta=0.75, knorm=2, nsize=5, **kw):
    """Reference: src/operator/nn/lrn.cc (across-channel local response
    normalization, layout NCHW)."""
    import jax.numpy as jnp
    from jax import lax

    from ..numpy.multiarray import _invoke
    _drop_name(kw)
    alpha, beta = float(_lit(alpha)), float(_lit(beta))
    knorm, nsize = float(_lit(knorm)), int(_lit(nsize))

    def fn(x):
        sq = lax.square(x)
        half = nsize // 2
        dims = [1, nsize, 1, 1]
        win = lax.reduce_window(sq, 0.0, lax.add, dims, [1, 1, 1, 1],
                                [(0, 0), (half, half), (0, 0), (0, 0)])
        return x * lax.pow(knorm + alpha / nsize * win, -beta)
    return _invoke(fn, (data,), name="lrn")


@register("ROIPooling")
def _roi_pooling(data, rois, pooled_size=None, spatial_scale=1.0, **kw):
    """Reference: src/operator/roi_pooling.cc. rois: (n, 5) of
    [batch_idx, x1, y1, x2, y2] in image coords."""
    import jax
    import jax.numpy as jnp

    from ..numpy.multiarray import _invoke
    _drop_name(kw)
    ph, pw = _tup(pooled_size, 2)
    scale = float(_lit(spatial_scale))

    def fn(x, r):
        def one(roi):
            b = roi[0].astype(jnp.int32)
            x1 = jnp.round(roi[1] * scale).astype(jnp.int32)
            y1 = jnp.round(roi[2] * scale).astype(jnp.int32)
            x2 = jnp.round(roi[3] * scale).astype(jnp.int32)
            y2 = jnp.round(roi[4] * scale).astype(jnp.int32)
            h = x.shape[2]
            w = x.shape[3]
            fmap = jax.lax.dynamic_index_in_dim(x, b, 0, keepdims=False)
            roi_h = jnp.maximum(y2 - y1 + 1, 1)
            roi_w = jnp.maximum(x2 - x1 + 1, 1)
            bin_h = roi_h / ph
            bin_w = roi_w / pw
            iy = jnp.arange(h)
            ix = jnp.arange(w)

            def pool_bin(py, px):
                ys = y1 + jnp.floor(py * bin_h).astype(jnp.int32)
                ye = y1 + jnp.ceil((py + 1) * bin_h).astype(jnp.int32)
                xs = x1 + jnp.floor(px * bin_w).astype(jnp.int32)
                xe = x1 + jnp.ceil((px + 1) * bin_w).astype(jnp.int32)
                mask = ((iy[:, None] >= ys) & (iy[:, None] < ye) &
                        (ix[None, :] >= xs) & (ix[None, :] < xe))
                neg = jnp.finfo(x.dtype).min
                masked = jnp.where(mask[None], fmap, neg)
                return jnp.max(masked, axis=(1, 2))
            grid = [[pool_bin(py, px) for px in range(pw)]
                    for py in range(ph)]
            return jnp.stack([jnp.stack(row, -1) for row in grid], -2)
        return jax.vmap(one)(r)
    return _invoke(fn, (data, rois), name="roi_pooling")


@register("SequenceMask")
def _sequence_mask(data, sequence_length=None, use_sequence_length=False,
                   value=0.0, axis=0, **kw):
    from .. import numpy_extension as npx
    _drop_name(kw)
    if not _b(use_sequence_length) or sequence_length is None:
        return data
    return npx.sequence_mask(data, sequence_length,
                             use_sequence_length=True,
                             value=float(_lit(value)), axis=int(_lit(axis)))


@register("SequenceLast")
def _sequence_last(data, sequence_length=None, use_sequence_length=False,
                   axis=0, **kw):
    from .. import numpy_extension as npx
    _drop_name(kw)
    return npx.sequence_last(data, sequence_length,
                             use_sequence_length=_b(use_sequence_length),
                             axis=int(_lit(axis)))


@register("SequenceReverse")
def _sequence_reverse(data, sequence_length=None, use_sequence_length=False,
                      axis=0, **kw):
    from .. import numpy_extension as npx
    _drop_name(kw)
    return npx.sequence_reverse(data, sequence_length,
                                use_sequence_length=_b(use_sequence_length),
                                axis=int(_lit(axis)))


@register("Softmax")
def _softmax_legacy(data, *args, **kw):
    """1.x deprecated alias of SoftmaxOutput (reference softmax.cc alias);
    with a single input it is plain softmax."""
    from .. import numpy_extension as npx
    if args or "label" in kw:
        return _softmax_output(data, *args, **kw)
    _drop_name(kw)
    return npx.softmax(data, axis=-1)


@register("Custom")
def _custom(*inputs, op_type=None, **kw):
    from .. import operator as _op
    _drop_name(kw)
    return _op.Custom(*inputs, op_type=op_type, **kw)


# -- legacy snake_case names with no direct np analog -----------------------
def _register_broadcast_aliases():
    from .. import numpy as _np

    pairs = {
        "broadcast_add": "add", "broadcast_plus": "add",
        "broadcast_sub": "subtract", "broadcast_minus": "subtract",
        "broadcast_mul": "multiply", "broadcast_div": "divide",
        "broadcast_mod": "mod", "broadcast_power": "power",
        "broadcast_maximum": "maximum", "broadcast_minimum": "minimum",
        "broadcast_equal": "equal", "broadcast_not_equal": "not_equal",
        "broadcast_greater": "greater",
        "broadcast_greater_equal": "greater_equal",
        "broadcast_lesser": "less", "broadcast_lesser_equal": "less_equal",
        "broadcast_logical_and": "logical_and",
        "broadcast_logical_or": "logical_or",
        "broadcast_logical_xor": "logical_xor",
        "broadcast_hypot": "hypot",
        "elemwise_add": "add", "elemwise_sub": "subtract",
        "elemwise_mul": "multiply", "elemwise_div": "divide",
    }
    for legacy, np_name in pairs.items():
        def mk(np_name=np_name, legacy=legacy):
            def fn(*args, **kwargs):
                _drop_name(kwargs)
                return getattr(_np, np_name)(*args, **kwargs)
            fn.__name__ = legacy
            return fn
        LEGACY_OPS[legacy] = mk()

    def broadcast_to(data, shape=None, **kw):
        _drop_name(kw)
        shape = _tup(shape)
        # legacy: 0 in target shape keeps the source dim
        shape = tuple(s if s != 0 else data.shape[i]
                      for i, s in enumerate(shape))
        return _np.broadcast_to(data, shape)
    LEGACY_OPS["broadcast_to"] = broadcast_to

    def broadcast_axis(data, axis=None, size=None, **kw):
        _drop_name(kw)
        # reference defaults axis=()/size=(): no axes -> identity
        axes = _tup(axis) or ()
        sizes = _tup(size) or ()
        target = list(data.shape)
        for a, s in zip(axes, sizes):
            target[a] = s
        return _np.broadcast_to(data, tuple(target))
    LEGACY_OPS["broadcast_axis"] = broadcast_axis
    LEGACY_OPS["broadcast_axes"] = broadcast_axis

    def stop_gradient(data, **kw):
        return _block_grad(data, **kw)
    LEGACY_OPS["stop_gradient"] = stop_gradient

    def argmax_channel(data, **kw):
        _drop_name(kw)
        return _np.argmax(data, axis=1).astype(data.dtype)
    LEGACY_OPS["argmax_channel"] = argmax_channel

    def flatten(data, **kw):
        return _flatten(data, **kw)
    LEGACY_OPS["flatten"] = flatten

    def identity(data, **kw):
        _drop_name(kw)
        return data + 0
    LEGACY_OPS["identity"] = identity

    def zeros_like(data, **kw):
        _drop_name(kw)
        return _np.zeros_like(data)
    LEGACY_OPS["zeros_like"] = zeros_like

    def ones_like(data, **kw):
        _drop_name(kw)
        return _np.ones_like(data)
    LEGACY_OPS["ones_like"] = ones_like

    def norm(data, ord=2, axis=None, keepdims=False, **kw):  # noqa: A002
        _drop_name(kw)
        from ..numpy.multiarray import _invoke
        import jax.numpy as jnp
        o, ax = _lit(ord), _tup(axis) if axis is not None else None
        if ax is not None and len(ax) == 1:
            ax = ax[0]

        def fn(x):
            if ax is None:
                # legacy: reduce over ALL elements (never a matrix norm)
                x = x.ravel()
            return jnp.linalg.norm(x, ord=None if o == 2 else o, axis=ax,
                                   keepdims=_b(keepdims))
        return _invoke(fn, (data,), name="norm")
    LEGACY_OPS["norm"] = norm


_register_broadcast_aliases()


# -- legacy linalg_* family ---------------------------------------------------
# Reference: src/operator/tensor/la_op.cc (_linalg_gemm ... _linalg_slogdet),
# exposed to 1.x scripts as nd.linalg_gemm / nd.linalg.gemm. All ops operate
# on the last two axes and batch over the rest (jnp broadcasting native).

def _register_linalg():
    # jax imports stay lazy (inside _lin, called from op bodies) like every
    # other adapter in this file — package import must not pay jax startup
    def _lin():
        import jax.numpy as jnp
        from jax.scipy.linalg import solve_triangular

        from ..numpy.multiarray import _invoke
        return jnp, solve_triangular, _invoke

    def gemm(A, B, C=None, transpose_a=False, transpose_b=False, alpha=1.0,
             beta=1.0, **kw):
        jnp, _, _invoke = _lin()
        _drop_name(kw)
        a, b = _lit(alpha), _lit(beta)
        ta, tb = _b(transpose_a), _b(transpose_b)

        def t(x, f):
            return jnp.swapaxes(x, -1, -2) if f else x
        if C is None:
            return _invoke(lambda x, y: a * jnp.matmul(t(x, ta), t(y, tb)),
                           (A, B), name="linalg_gemm")
        return _invoke(
            lambda x, y, c: a * jnp.matmul(t(x, ta), t(y, tb)) + b * c,
            (A, B, C), name="linalg_gemm")

    def gemm2(A, B, transpose_a=False, transpose_b=False, alpha=1.0, **kw):
        jnp, _, _invoke = _lin()
        _drop_name(kw)
        a, ta, tb = _lit(alpha), _b(transpose_a), _b(transpose_b)

        def t(x, f):
            return jnp.swapaxes(x, -1, -2) if f else x
        return _invoke(lambda x, y: a * jnp.matmul(t(x, ta), t(y, tb)),
                       (A, B), name="linalg_gemm2")

    def potrf(A, **kw):
        jnp, _, _invoke = _lin()
        _drop_name(kw)
        return _invoke(jnp.linalg.cholesky, (A,), name="linalg_potrf")

    def potri(A, **kw):
        """Inverse of the SPD matrix from its Cholesky factor L:
        (L L^T)^-1 (reference: la_op.cc potri)."""
        jnp, solve_triangular, _invoke = _lin()
        _drop_name(kw)

        def fn(L):
            eye = jnp.broadcast_to(jnp.eye(L.shape[-1], dtype=L.dtype),
                                   L.shape)
            Linv = solve_triangular(L, eye, lower=True)
            return jnp.matmul(jnp.swapaxes(Linv, -1, -2), Linv)
        return _invoke(fn, (A,), name="linalg_potri")

    def trmm(A, B, transpose=False, rightside=False, lower=True, alpha=1.0,
             **kw):
        jnp, _, _invoke = _lin()
        _drop_name(kw)
        a, tr, rs, lo = _lit(alpha), _b(transpose), _b(rightside), _b(lower)

        def fn(A_, B_):
            # BLAS trmm contract: only the named triangle of A is read
            T = jnp.tril(A_) if lo else jnp.triu(A_)
            T = jnp.swapaxes(T, -1, -2) if tr else T
            return a * (jnp.matmul(B_, T) if rs else jnp.matmul(T, B_))
        return _invoke(fn, (A, B), name="linalg_trmm")

    def trsm(A, B, transpose=False, rightside=False, lower=True, alpha=1.0,
             **kw):
        jnp, solve_triangular, _invoke = _lin()
        _drop_name(kw)
        a, tr, rs, lo = _lit(alpha), _b(transpose), _b(rightside), _b(lower)

        def fn(A_, B_):
            if rs:
                # X op(A) = alpha B  <=>  op(A)^T X^T = alpha B^T; scipy's
                # trans flag applies the extra transpose without moving data
                xt = solve_triangular(A_, jnp.swapaxes(a * B_, -1, -2),
                                      lower=lo, trans=0 if tr else 1)
                return jnp.swapaxes(xt, -1, -2)
            return solve_triangular(A_, a * B_, lower=lo,
                                    trans=1 if tr else 0)
        return _invoke(fn, (A, B), name="linalg_trsm")

    def sumlogdiag(A, **kw):
        jnp, _, _invoke = _lin()
        _drop_name(kw)
        return _invoke(
            lambda x: jnp.sum(jnp.log(jnp.diagonal(x, axis1=-2, axis2=-1)),
                              axis=-1), (A,), name="linalg_sumlogdiag")

    def extractdiag(A, offset=0, **kw):
        jnp, _, _invoke = _lin()
        _drop_name(kw)
        o = int(_lit(offset))
        return _invoke(lambda x: jnp.diagonal(x, offset=o, axis1=-2,
                                              axis2=-1), (A,),
                       name="linalg_extractdiag")

    def makediag(A, offset=0, **kw):
        jnp, _, _invoke = _lin()
        _drop_name(kw)
        o = int(_lit(offset))

        def fn(x):
            n = x.shape[-1] + abs(o)
            out = jnp.zeros(x.shape[:-1] + (n, n), x.dtype)
            idx = jnp.arange(x.shape[-1])
            r = idx + max(-o, 0)
            c = idx + max(o, 0)
            return out.at[..., r, c].set(x)
        return _invoke(fn, (A,), name="linalg_makediag")

    def _trian_count(n, o, lo):
        import numpy as _onp
        tri = _onp.tril(_onp.ones((n, n)), k=o) if lo \
            else _onp.triu(_onp.ones((n, n)), k=o)
        return int(tri.sum())

    def extracttrian(A, offset=0, lower=True, **kw):
        jnp, _, _invoke = _lin()
        _drop_name(kw)
        o, lo = int(_lit(offset)), _b(lower)

        def fn(x):
            n = x.shape[-1]
            r, c = jnp.tril_indices(n, k=o) if lo else \
                jnp.triu_indices(n, k=o)
            return x[..., r, c]
        return _invoke(fn, (A,), name="linalg_extracttrian")

    def maketrian(A, offset=0, lower=True, **kw):
        jnp, _, _invoke = _lin()
        _drop_name(kw)
        o, lo = int(_lit(offset)), _b(lower)

        def fn(x):
            m = x.shape[-1]
            # invert the extracttrian packing: smallest n whose triangle
            # (with this offset) holds exactly m elements
            n = 1
            while _trian_count(n, o, lo) < m:
                n += 1
            if _trian_count(n, o, lo) != m:
                raise MXNetError(
                    f"maketrian: {m} packed elements do not form a "
                    f"triangle with offset {o}")
            r, c = jnp.tril_indices(n, k=o) if lo else \
                jnp.triu_indices(n, k=o)
            out = jnp.zeros(x.shape[:-1] + (n, n), x.dtype)
            return out.at[..., r, c].set(x)
        return _invoke(fn, (A,), name="linalg_maketrian")

    def syrk(A, transpose=False, alpha=1.0, **kw):
        jnp, _, _invoke = _lin()
        _drop_name(kw)
        a, tr = _lit(alpha), _b(transpose)

        def fn(x):
            xt = jnp.swapaxes(x, -1, -2)
            return a * (jnp.matmul(xt, x) if tr else jnp.matmul(x, xt))
        return _invoke(fn, (A,), name="linalg_syrk")

    def syevd(A, **kw):
        jnp, _, _invoke = _lin()
        _drop_name(kw)

        def fn(x):
            w, u = jnp.linalg.eigh(x)
            return jnp.swapaxes(u, -1, -2), w   # reference returns (U, L)
        return _invoke(fn, (A,), name="linalg_syevd")

    def gelqf(A, **kw):
        jnp, _, _invoke = _lin()
        _drop_name(kw)

        def fn(x):
            # LQ of (m, n), m <= n: A = L Q with Q row-orthonormal
            q, r = jnp.linalg.qr(jnp.swapaxes(x, -1, -2), mode="reduced")
            return jnp.swapaxes(r, -1, -2), jnp.swapaxes(q, -1, -2)
        return _invoke(fn, (A,), name="linalg_gelqf")

    def inverse(A, **kw):
        jnp, _, _invoke = _lin()
        _drop_name(kw)
        return _invoke(jnp.linalg.inv, (A,), name="linalg_inverse")

    def det(A, **kw):
        jnp, _, _invoke = _lin()
        _drop_name(kw)
        return _invoke(jnp.linalg.det, (A,), name="linalg_det")

    def slogdet(A, **kw):
        jnp, _, _invoke = _lin()
        _drop_name(kw)
        return _invoke(lambda x: tuple(jnp.linalg.slogdet(x)), (A,),
                       name="linalg_slogdet")

    for name, fn in [("gemm", gemm), ("gemm2", gemm2), ("potrf", potrf),
                     ("potri", potri), ("trmm", trmm), ("trsm", trsm),
                     ("sumlogdiag", sumlogdiag),
                     ("extractdiag", extractdiag), ("makediag", makediag),
                     ("extracttrian", extracttrian),
                     ("maketrian", maketrian), ("syrk", syrk),
                     ("syevd", syevd), ("gelqf", gelqf),
                     ("inverse", inverse), ("det", det),
                     ("slogdet", slogdet)]:
        fn.__name__ = f"linalg_{name}"
        LEGACY_OPS[f"linalg_{name}"] = fn


_register_linalg()


# -- spatial sampling (1.x vision ops) ---------------------------------------

@register("BilinearSampler")
def _bilinear_sampler(data, grid, **kw):
    """Reference: src/operator/bilinear_sampler.cc — sample NCHW data at
    normalized grid coords in [-1, 1]; grid (N, 2, Ho, Wo) rows (x, y).
    Out-of-range samples read 0 (same zero-padding contract as the
    deformable-conv kernel, ops/deformable.py)."""
    import jax.numpy as jnp

    from ..numpy.multiarray import _invoke

    _drop_name(kw)

    def fn(x, g):
        N, C, H, W = x.shape
        gx = (g[:, 0] + 1.0) * (W - 1) / 2.0      # (N, Ho, Wo)
        gy = (g[:, 1] + 1.0) * (H - 1) / 2.0
        x0, y0 = jnp.floor(gx), jnp.floor(gy)
        wx, wy = gx - x0, gy - y0
        flat = x.reshape(N, C, H * W)

        def corner(cy, cx):
            inside = (cy >= 0) & (cy < H) & (cx >= 0) & (cx < W)
            idx = (jnp.clip(cy, 0, H - 1).astype(jnp.int32) * W
                   + jnp.clip(cx, 0, W - 1).astype(jnp.int32))
            v = jnp.take_along_axis(
                flat, jnp.broadcast_to(idx[:, None].reshape(N, 1, -1),
                                       (N, C, idx[0].size)), axis=-1)
            return v.reshape(x.shape[:2] + cy.shape[1:]) \
                * inside[:, None].astype(x.dtype)

        v00 = corner(y0, x0)
        v01 = corner(y0, x0 + 1)
        v10 = corner(y0 + 1, x0)
        v11 = corner(y0 + 1, x0 + 1)
        wx_, wy_ = wx[:, None].astype(x.dtype), wy[:, None].astype(x.dtype)
        return (v00 * (1 - wy_) * (1 - wx_) + v01 * (1 - wy_) * wx_
                + v10 * wy_ * (1 - wx_) + v11 * wy_ * wx_)
    return _invoke(fn, (data, grid), name="BilinearSampler")


@register("GridGenerator")
def _grid_generator(data, transform_type="affine", target_shape=None, **kw):
    """Reference: src/operator/grid_generator.cc. affine: data (N, 6) ->
    grid (N, 2, H, W) of normalized (x, y); warp: data IS the flow field."""
    import jax.numpy as jnp

    from ..numpy.multiarray import _invoke

    _drop_name(kw)
    tt = _lit(transform_type)
    shape = _tup(target_shape) if target_shape is not None else None

    def fn(d):
        if tt == "warp":
            N, _two, H, W = d.shape
            xs = jnp.linspace(-1, 1, W)
            ys = jnp.linspace(-1, 1, H)
            base_x = jnp.broadcast_to(xs, (H, W))
            base_y = jnp.broadcast_to(ys[:, None], (H, W))
            gx = base_x + d[:, 0] * 2.0 / max(W - 1, 1)
            gy = base_y + d[:, 1] * 2.0 / max(H - 1, 1)
            return jnp.stack([gx, gy], axis=1)
        H, W = shape
        theta = d.reshape(-1, 2, 3)
        xs = jnp.linspace(-1, 1, W)
        ys = jnp.linspace(-1, 1, H)
        gx, gy = jnp.meshgrid(xs, ys)
        ones = jnp.ones_like(gx)
        coords = jnp.stack([gx.ravel(), gy.ravel(), ones.ravel()])  # (3, HW)
        out = jnp.einsum("nij,jk->nik", theta, coords)              # (N,2,HW)
        return out.reshape(-1, 2, H, W)
    return _invoke(fn, (data,), name="GridGenerator")


@register("SpatialTransformer")
def _spatial_transformer(data, loc, target_shape=None,
                         transform_type="affine", sampler_type="bilinear",
                         **kw):
    """Reference: src/operator/spatial_transformer.cc = GridGenerator +
    BilinearSampler."""
    _drop_name(kw)
    grid = _grid_generator(loc, transform_type=transform_type,
                           target_shape=target_shape)
    return _bilinear_sampler(data, grid)

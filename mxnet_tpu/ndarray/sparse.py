"""Sparse NDArray types: row_sparse and CSR.

Reference parity: python/mxnet/ndarray/sparse.py (RowSparseNDArray,
CSRNDArray, row_sparse_array/csr_matrix constructors, retain, sparse dot)
over include/mxnet/ndarray.h:60-64 storage types; kvstore PullRowSparse.

TPU-native design: XLA has no native sparse storage, so a sparse array is a
pair/triple of DENSE component arrays (values + indices [+ indptr]) and
sparse ops lower to gather/scatter/segment-sum — static-shaped, MXU/VPU
friendly. Conversions with data-dependent sizes (dense -> sparse, which
must discover nnz) run eagerly on host, mirroring the reference's
imperative-only conversion ops. Everything here is inference of the
reference's *semantics*, not a translation of its kernels.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as onp

from ..base import MXNetError
from ..numpy.multiarray import ndarray, _wrap

# index dtype: int64 under x64, else int32 (jax's default truncation would
# warn on every construction otherwise); reference uses int64 throughout
_IDX = jnp.int64 if jax.config.jax_enable_x64 else jnp.int32

__all__ = ["RowSparseNDArray", "CSRNDArray", "row_sparse_array",
           "csr_matrix", "zeros", "retain", "dot", "add", "BaseSparseNDArray",
           "dedupe_coo", "subtract", "multiply", "divide", "empty", "array"]


def dedupe_coo(indices, values, n_rows):
    """Sum duplicate rows of a COO batch, jit-friendly (static shapes).

    Returns (uidx, uvals) of the same static length k where the distinct
    row ids (sorted) occupy the leading slots and unused slots are padded
    with the sentinel index ``n_rows`` and zero values.  Scatter consumers
    must use out-of-range-safe modes (padding rows carry zeros, so
    clip-mode scatter-ADD is also safe).  This is the TPU-native encoding
    of the reference's "sorted unique indices" RowSparse invariant
    (include/mxnet/ndarray.h:60-64) under XLA's static-shape rule: nnz is
    data-dependent, so we keep k = len(indices) slots and mask.
    """
    indices = jnp.asarray(indices)
    values = jnp.asarray(values)
    k = indices.shape[0]
    order = jnp.argsort(indices)
    sidx = indices[order]
    svals = values[order]
    first = jnp.concatenate([jnp.ones((1,), bool),
                             sidx[1:] != sidx[:-1]]) if k else \
        jnp.ones((0,), bool)
    slot = jnp.cumsum(first.astype(_IDX)) - 1          # group id per entry
    uvals = jax.ops.segment_sum(svals, slot, num_segments=k)
    # row id of each group: scatter the first-occurrence ids to their slot
    uidx = jnp.full((k,), n_rows, sidx.dtype).at[slot].set(
        sidx, mode="drop")
    n_unique = (slot[-1] + 1) if k else jnp.zeros((), _IDX)
    valid = jnp.arange(k) < n_unique
    uidx = jnp.where(valid, uidx, n_rows)
    uvals = jnp.where(valid.reshape((-1,) + (1,) * (values.ndim - 1)),
                      uvals, jnp.zeros((), uvals.dtype))
    return uidx.astype(_IDX), uvals


def _as_raw(x, dtype=None):
    if isinstance(x, ndarray):
        x = x._data
    out = jnp.asarray(x, dtype=dtype)
    return out


class BaseSparseNDArray:
    """Common surface of the sparse types (reference: sparse.py
    BaseSparseNDArray)."""

    @property
    def stype(self):
        raise NotImplementedError

    @property
    def dtype(self):
        return self.data.dtype

    @property
    def context(self):
        return self.data.context

    ctx = context

    def asnumpy(self):
        return self.tostype("default").asnumpy()

    def wait_to_read(self):
        self.data.wait_to_read()

    def __repr__(self):
        return (f"<{type(self).__name__} {self.shape} "
                f"nnz-storage={tuple(self.data.shape)}>")


class RowSparseNDArray(BaseSparseNDArray):
    """Rows at ``indices`` hold ``data``; all other rows are zero
    (reference: sparse.py RowSparseNDArray). data: (nnz, *row_shape),
    indices: (nnz,) int64, sorted unique.

    TPU static-shape extension: indices may be padded with the sentinel
    value ``shape[0]`` (with zero rows in ``data``) so jit-produced sparse
    gradients keep a static slot count — see ``dedupe_coo``.  All consumers
    here scatter with add/drop semantics, which makes padding inert."""

    def __init__(self, data, indices, shape):
        self.data = data if isinstance(data, ndarray) else _wrap(_as_raw(data))
        self.indices = (indices if isinstance(indices, ndarray)
                        else _wrap(_as_raw(indices, _IDX)))
        self.shape = tuple(int(s) for s in shape)
        if self.data.shape[1:] != self.shape[1:]:
            raise MXNetError(
                f"row shape {self.data.shape[1:]} != array row shape "
                f"{self.shape[1:]}")

    @property
    def stype(self):
        return "row_sparse"

    def tostype(self, stype):
        if stype == "row_sparse":
            return self
        if stype != "default":
            raise MXNetError(f"cannot convert row_sparse to {stype!r}")
        dense = jnp.zeros(self.shape, self.data.dtype)
        # add + drop (not set): unique-indices invariant makes add exact,
        # and sentinel padding rows fall out of range harmlessly
        dense = dense.at[self.indices._data].add(self.data._data,
                                                 mode="drop")
        return _wrap(dense)

    def retain(self, row_ids):
        """Keep only rows in row_ids (reference: sparse.retain op)."""
        row_ids = _as_raw(row_ids, _IDX)
        keep = jnp.isin(self.indices._data, row_ids)
        # data-dependent output size: resolve eagerly (imperative-only op,
        # like the reference's sparse conversions)
        keep_np = onp.asarray(keep)
        idx_np = onp.asarray(self.indices._data)[keep_np]
        val_np = onp.asarray(self.data._data)[keep_np]
        return RowSparseNDArray(jnp.asarray(val_np), jnp.asarray(idx_np),
                                self.shape)

    def copy(self):
        return RowSparseNDArray(self.data.copy(), self.indices.copy(),
                                self.shape)

    def astype(self, dtype):
        return RowSparseNDArray(self.data.astype(dtype), self.indices,
                                self.shape)

    def __add__(self, other):
        return add(self, other)

    def __radd__(self, other):
        return add(other, self)


class CSRNDArray(BaseSparseNDArray):
    """Compressed sparse row matrix (reference: sparse.py CSRNDArray).
    data/indices: (nnz,), indptr: (m+1,)."""

    def __init__(self, data, indices, indptr, shape):
        self.data = data if isinstance(data, ndarray) else _wrap(_as_raw(data))
        self.indices = (indices if isinstance(indices, ndarray)
                        else _wrap(_as_raw(indices, _IDX)))
        self.indptr = (indptr if isinstance(indptr, ndarray)
                       else _wrap(_as_raw(indptr, _IDX)))
        if len(shape) != 2:
            raise MXNetError("CSR arrays are 2-D")
        self.shape = tuple(int(s) for s in shape)

    @property
    def stype(self):
        return "csr"

    def _row_of_nnz(self):
        """row index of each stored value: (nnz,) from indptr."""
        m = self.shape[0]
        counts = self.indptr._data[1:] - self.indptr._data[:-1]
        return jnp.repeat(jnp.arange(m, dtype=_IDX), counts,
                          total_repeat_length=self.data.shape[0])

    def tostype(self, stype):
        if stype == "csr":
            return self
        if stype != "default":
            raise MXNetError(f"cannot convert csr to {stype!r}")
        rows = self._row_of_nnz()
        dense = jnp.zeros(self.shape, self.data.dtype)
        dense = dense.at[rows, self.indices._data].set(self.data._data)
        return _wrap(dense)

    def dot(self, rhs):
        return dot(self, rhs)

    def copy(self):
        return CSRNDArray(self.data.copy(), self.indices.copy(),
                          self.indptr.copy(), self.shape)

    def astype(self, dtype):
        return CSRNDArray(self.data.astype(dtype), self.indices,
                          self.indptr, self.shape)


# -- constructors (reference: sparse.py row_sparse_array / csr_matrix) ------

def row_sparse_array(arg1, shape=None, ctx=None, dtype=None):
    if isinstance(arg1, RowSparseNDArray):
        return arg1
    if isinstance(arg1, tuple) and len(arg1) == 2:
        data, indices = arg1
        if shape is None:
            raise MXNetError("shape required with (data, indices)")
        return RowSparseNDArray(_as_raw(data, dtype), indices, shape)
    # dense input: find the non-zero rows on host (imperative conversion)
    dense = onp.asarray(arg1.asnumpy() if isinstance(arg1, ndarray)
                        else arg1, dtype=dtype)
    nz = onp.where(dense.reshape(dense.shape[0], -1).any(axis=1))[0]
    return RowSparseNDArray(jnp.asarray(dense[nz]),
                            jnp.asarray(nz, _IDX), dense.shape)


def csr_matrix(arg1, shape=None, ctx=None, dtype=None):
    if isinstance(arg1, CSRNDArray):
        return arg1
    if isinstance(arg1, tuple) and len(arg1) == 3:
        data, indices, indptr = arg1
        if shape is None:
            raise MXNetError("shape required with (data, indices, indptr)")
        return CSRNDArray(_as_raw(data, dtype), indices, indptr, shape)
    dense = onp.asarray(arg1.asnumpy() if isinstance(arg1, ndarray)
                        else arg1, dtype=dtype)
    if dense.ndim != 2:
        raise MXNetError("CSR arrays are 2-D")
    rows, cols = onp.nonzero(dense)
    indptr = onp.zeros(dense.shape[0] + 1, "int64")
    onp.add.at(indptr, rows + 1, 1)
    indptr = onp.cumsum(indptr)
    return CSRNDArray(jnp.asarray(dense[rows, cols]),
                      jnp.asarray(cols, _IDX),
                      jnp.asarray(indptr), dense.shape)


def zeros(stype, shape, ctx=None, dtype="float32"):
    """Empty sparse array (reference: sparse.zeros)."""
    if stype == "row_sparse":
        row_shape = tuple(shape[1:])
        return RowSparseNDArray(jnp.zeros((0,) + row_shape, dtype),
                                jnp.zeros((0,), _IDX), shape)
    if stype == "csr":
        return CSRNDArray(jnp.zeros((0,), dtype), jnp.zeros((0,), _IDX),
                          jnp.zeros((shape[0] + 1,), _IDX), shape)
    if stype == "default":
        return _wrap(jnp.zeros(shape, dtype))
    raise MXNetError(f"unknown stype {stype!r}")


# -- ops --------------------------------------------------------------------

def retain(rsp, row_ids):
    if not isinstance(rsp, RowSparseNDArray):
        raise MXNetError("retain expects a RowSparseNDArray")
    return rsp.retain(row_ids)


def dot(lhs, rhs, transpose_a=False):
    """csr @ dense (reference: sparse dot, src/operator/tensor/dot.cc CSR
    kernels) as a segment-sum — static shapes, VPU friendly."""
    if not isinstance(lhs, CSRNDArray):
        raise MXNetError("sparse dot expects a CSR lhs")
    rhs_raw = rhs._data if isinstance(rhs, ndarray) else jnp.asarray(rhs)
    rows = lhs._row_of_nnz()
    gathered = rhs_raw[lhs.indices._data] * lhs.data._data[:, None]
    if transpose_a:
        out = jax.ops.segment_sum(
            rhs_raw[rows] * lhs.data._data[:, None], lhs.indices._data,
            num_segments=lhs.shape[1])
    else:
        out = jax.ops.segment_sum(gathered, rows,
                                  num_segments=lhs.shape[0])
    return _wrap(out)


def add(a, b):
    """Sparse + sparse/dense. Same-stype row_sparse adds stay sparse
    (concatenate the COO slots then ``dedupe_coo`` — static shapes, jit
    safe); anything else densifies (the reference's storage-fallback path,
    src/common/exec_utils dispatch-fallback)."""
    if isinstance(a, RowSparseNDArray) and isinstance(b, RowSparseNDArray):
        if a.shape != b.shape:
            raise MXNetError("shape mismatch")
        idx = jnp.concatenate([a.indices._data.astype(_IDX),
                               b.indices._data.astype(_IDX)])
        vals = jnp.concatenate([a.data._data, b.data._data])
        uidx, uvals = dedupe_coo(idx, vals, a.shape[0])
        return RowSparseNDArray(uvals, uidx, a.shape)
    da = a.tostype("default") if isinstance(a, BaseSparseNDArray) else a
    db = b.tostype("default") if isinstance(b, BaseSparseNDArray) else b
    return da + db


def _densify_binary(public_name, op_name):
    """Elementwise ops without a sparse-preserving identity densify (the
    reference's storage-fallback dispatch, sparse.py:1282-1512 — only
    add of same-stype operands has a cheap sparse kernel; sub/mul/div
    route through dense there too unless both rsp with scalar rhs)."""
    import operator

    op = getattr(operator, op_name)

    def fn(lhs, rhs):
        dl = lhs.tostype("default") if isinstance(lhs, BaseSparseNDArray) \
            else lhs
        dr = rhs.tostype("default") if isinstance(rhs, BaseSparseNDArray) \
            else rhs
        return op(dl, dr)

    fn.__name__ = public_name
    return fn


subtract = _densify_binary("subtract", "sub")
multiply = _densify_binary("multiply", "mul")
divide = _densify_binary("divide", "truediv")


def empty(stype, shape, ctx=None, dtype=None):
    """All-zero sparse array (reference sparse.py:1564 — sparse 'empty'
    is defined as zeros; there is no uninitialized sparse storage)."""
    return zeros(stype, shape, ctx=ctx, dtype=dtype or "float32")


def array(source_array, ctx=None, dtype=None):
    """Build a sparse array from a sparse source (reference
    sparse.py:1596 — dense input is REJECTED there with a pointer to
    tostype(); same here so ported code fails at the call site)."""
    if isinstance(source_array, BaseSparseNDArray):
        out = source_array.copy()
        if dtype is not None:
            return out.astype(dtype)
        return out
    try:
        import scipy.sparse as sp  # pragma: no cover - scipy optional
        if sp.issparse(source_array):
            csr = source_array.tocsr()
            return csr_matrix((csr.data, csr.indices, csr.indptr),
                              shape=csr.shape, dtype=dtype)
    except ImportError:
        pass
    raise MXNetError(
        "sparse.array takes a sparse source (RowSparseNDArray/CSRNDArray "
        "or scipy.sparse); for dense input use mx.nd.array(...).tostype()")

"""mx.runtime — feature detection.

Reference parity: python/mxnet/runtime.py over src/libinfo.cc:37-90 (compiled
feature flags like CUDA/CUDNN/MKLDNN/DIST_KVSTORE surfaced at runtime). Here
the features describe the JAX/XLA backend actually present in the process.
"""
from __future__ import annotations

import jax


class Feature:
    def __init__(self, name, enabled):
        self.name = name
        self._enabled = enabled

    @property
    def enabled(self):
        return self._enabled

    def __repr__(self):
        return f"[{'✔' if self._enabled else '✖'} {self.name}]"


def feature_list():
    devs = jax.devices()
    accel = bool(devs) and devs[0].platform != "cpu"
    feats = {
        "TPU": accel and devs[0].platform in ("tpu", "axon"),
        "XLA": True,
        "PALLAS": accel,
        "CPU": True,
        "CUDA": False,
        "CUDNN": False,
        "NCCL": False,
        "MKLDNN": False,
        "OPENMP": False,
        "DIST_KVSTORE": True,        # mesh collectives over ICI/DCN
        "INT64_TENSOR_SIZE": True,
        "SIGNAL_HANDLER": False,
        "F16C": True,
        "BF16": True,
    }
    return [Feature(k, v) for k, v in feats.items()]


class Features(dict):
    instance = None

    def __init__(self):
        super().__init__([(f.name, f) for f in feature_list()])

    def is_enabled(self, name):
        return self[name.upper()].enabled


def libinfo_features():
    return feature_list()

"""mx.runtime — feature detection.

Reference parity: python/mxnet/runtime.py over src/libinfo.cc:37-90 (compiled
feature flags like CUDA/CUDNN/MKLDNN/DIST_KVSTORE surfaced at runtime). Here
the features describe the JAX/XLA backend actually present in the process.
"""
from __future__ import annotations

import jax


class Feature:
    def __init__(self, name, enabled):
        self.name = name
        self._enabled = enabled

    @property
    def enabled(self):
        return self._enabled

    def __repr__(self):
        return f"[{'✔' if self._enabled else '✖'} {self.name}]"


def feature_list():
    devs = jax.devices()
    accel = bool(devs) and devs[0].platform != "cpu"
    feats = {
        "TPU": accel and devs[0].platform in ("tpu", "axon"),
        "XLA": True,
        "PALLAS": accel,
        "CPU": True,
        "CUDA": False,
        "CUDNN": False,
        "NCCL": False,
        "MKLDNN": False,
        "OPENMP": False,
        "DIST_KVSTORE": True,        # mesh collectives over ICI/DCN
        "INT64_TENSOR_SIZE": True,
        "SIGNAL_HANDLER": False,
        "F16C": True,
        "BF16": True,
    }
    return [Feature(k, v) for k, v in feats.items()]


class Features(dict):
    instance = None

    def __init__(self):
        super().__init__([(f.name, f) for f in feature_list()])

    def is_enabled(self, name):
        return self[name.upper()].enabled


def libinfo_features():
    return feature_list()


def compiled_with_gcc_cxx11_abi():
    """Whether the native helper libraries use the GCC cxx11 ABI
    (reference runtime.py over MXLibInfoCompiledWithCXX11ABI). The
    on-demand g++ builds here (native/*.cc via storage/io loaders) use
    the toolchain default, which is the cxx11 ABI on every supported
    image; returns False only if no native library is loadable at all."""
    import os
    import shutil

    from . import native
    # consult already-built libs first; otherwise answer from toolchain +
    # source presence WITHOUT triggering an on-demand g++ build (an
    # introspection query must not shell out for seconds)
    if any(lib is not None for lib in native._libs.values()):
        return True
    return (shutil.which("g++") is not None
            and os.path.isdir(native._SRC_DIR))

"""Tensor-parallel layer sharding rules.

Reference parity: none (the reference has no TP — SURVEY §2.3 marks it a
build goal since GSPMD gives it nearly free). Megatron-style: column-parallel
Dense (shard units), row-parallel Dense (shard in_units, psum output) — on a
mesh, expressed purely as PartitionSpecs on the weight Parameters; XLA
inserts the all-reduces over ICI.
"""
from __future__ import annotations

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from ..gluon.nn import Dense
from ..gluon.block import HybridBlock


def shard_dense_column(dense: Dense, mesh, axis="tp"):
    """Shard a Dense's units dim over `axis` (weight is (units, in))."""
    dense.weight.shard(NamedSharding(mesh, P(axis, None)))
    if dense.bias is not None:
        dense.bias.shard(NamedSharding(mesh, P(axis)))
    return dense


def shard_dense_row(dense: Dense, mesh, axis="tp"):
    """Shard a Dense's in_units dim; XLA psums the partial matmul outputs."""
    dense.weight.shard(NamedSharding(mesh, P(None, axis)))
    if dense.bias is not None:
        dense.bias.shard(NamedSharding(mesh, P()))
    return dense


def shard_mlp(proj_in: Dense, proj_out: Dense, mesh, axis="tp"):
    """Standard Megatron MLP sharding: in=column, out=row → one allreduce."""
    shard_dense_column(proj_in, mesh, axis)
    shard_dense_row(proj_out, mesh, axis)


def auto_shard_block(block: HybridBlock, mesh, dp_axis="dp", tp_axis=None):
    """Annotate every initialized Parameter of a block:
    - replicate small params
    - if tp_axis given, shard the largest matmul dims Megatron-style
    (heuristic: alternate column/row over Dense layers in traversal order).
    """
    col = True
    for name, p in block.collect_params().items():
        if p._data is None:
            continue
        if tp_axis and p.shape is not None and len(p.shape) == 2 \
                and min(p.shape) >= mesh.shape.get(tp_axis, 1) \
                and max(p.shape) >= 128:
            spec = P(tp_axis, None) if col else P(None, tp_axis)
            col = not col
        else:
            spec = P()
        p.shard(NamedSharding(mesh, spec))
    return block

"""Device-mesh helpers.

Reference parity: none — the reference scales via KVStore/ps-lite (SURVEY
§2.3); on TPU the mesh + GSPMD sharding is the native replacement and also
unlocks TP/PP/SP the reference lacks.

Axis convention (scaling-book style): 'dp' (data, across ICI or DCN), 'tp'
(tensor/model), 'pp' (pipeline stages), 'sp' (sequence/context), 'ep'
(experts). Helpers build meshes over any subset.
"""
from __future__ import annotations

import numpy as onp

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..base import MXNetError

_current = None


def make_mesh(axes, devices=None):
    """Create a Mesh from {'dp': 4, 'tp': 2, ...} (row-major layout so the
    innermost axis maps to neighboring devices — keeps tp on the fastest ICI
    links)."""
    devices = list(devices if devices is not None else jax.devices())
    names = tuple(axes.keys())
    sizes = tuple(int(v) for v in axes.values())
    total = int(onp.prod(sizes))
    if total > len(devices):
        raise MXNetError(f"mesh {axes} needs {total} devices, "
                         f"have {len(devices)}")
    arr = onp.array(devices[:total]).reshape(sizes)
    return Mesh(arr, names)


def data_parallel_mesh(n=None):
    devs = jax.devices()
    n = n or len(devs)
    return make_mesh({"dp": n}, devs)


def set_mesh(mesh):
    global _current
    _current = mesh
    return mesh


def current_mesh():
    return _current


def shard(array, mesh, spec):
    """Place an ndarray/jax array with a PartitionSpec on a mesh."""
    from ..numpy.multiarray import ndarray, _wrap
    sharding = NamedSharding(mesh, spec if isinstance(spec, P) else P(*spec))
    raw = array._data if isinstance(array, ndarray) else array
    out = jax.device_put(raw, sharding)
    return _wrap(out) if isinstance(array, ndarray) else out


def replicate(array, mesh):
    return shard(array, mesh, P())


# -- activation sharding scope (sequence parallelism hook) ------------------
# Megatron-SP style: layers consult these rules to constrain their
# activations (residual stream sharded over ('dp', 'sp', None)); XLA then
# inserts the gather/scatter collectives around attention automatically.
_act_rules = None


class activation_sharding:
    """Scope installing activation PartitionSpec rules consulted by layers.

    with parallel.activation_sharding(mesh, residual=P('dp', 'sp', None)):
        out = net(x)            # or ShardedTrainStep built inside the scope
    """

    def __init__(self, mesh, **rules):
        self.mesh = mesh
        self.rules = rules
        self._prev = None

    def __enter__(self):
        global _act_rules
        self._prev = _act_rules
        _act_rules = (self.mesh, self.rules)
        return self

    def __exit__(self, *exc):
        global _act_rules
        _act_rules = self._prev


def constrain(x, kind):
    """Apply the active activation-sharding rule `kind` to x (ndarray or raw
    jax array); identity when no scope is active or rule missing."""
    if _act_rules is None:
        return x
    mesh, rules = _act_rules
    spec = rules.get(kind)
    if spec is None:
        return x
    from ..numpy.multiarray import ndarray, _wrap
    raw = x._data if isinstance(x, ndarray) else x
    if raw.ndim < len(spec):
        return x
    try:
        out = jax.lax.with_sharding_constraint(
            raw, NamedSharding(mesh, spec))
    except ValueError:
        return x
    return _wrap(out) if isinstance(x, ndarray) else out

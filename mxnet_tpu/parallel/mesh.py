"""Device-mesh helpers.

Reference parity: none — the reference scales via KVStore/ps-lite (SURVEY
§2.3); on TPU the mesh + GSPMD sharding is the native replacement and also
unlocks TP/PP/SP the reference lacks.

Axis convention (scaling-book style): 'dp' (data, across ICI or DCN), 'tp'
(tensor/model), 'pp' (pipeline stages), 'sp' (sequence/context), 'ep'
(experts). Helpers build meshes over any subset.
"""
from __future__ import annotations

import warnings

import numpy as onp

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..base import MXNetError
from .. import telemetry as _telemetry

_telemetry.declare_metric(
    "mesh.unused_devices", "gauge",
    "devices stranded by the last make_mesh call whose axis product "
    "undershot the device count (training silently runs on a subset)")

_current = None


def make_mesh(axes, devices=None):
    """Create a Mesh from {'dp': 4, 'tp': 2, ...} (row-major layout so the
    innermost axis maps to neighboring devices — keeps tp on the fastest ICI
    links).

    When the axis product undershoots ``len(devices)`` the leftover devices
    are NOT part of the mesh: that is sometimes deliberate (tests carve a
    2-way mesh out of the 8-device CI host), so it warns and counts
    ``mesh.unused_devices`` instead of raising — a production run scraping
    telemetry sees a non-zero gauge instead of silently training on a
    subset of the machine.
    """
    devices = list(devices if devices is not None else jax.devices())
    names = tuple(axes.keys())
    sizes = tuple(int(v) for v in axes.values())
    total = int(onp.prod(sizes))
    if total > len(devices):
        raise MXNetError(f"mesh {axes} needs {total} devices, "
                         f"have {len(devices)}")
    unused = len(devices) - total
    if unused:
        warnings.warn(
            f"mesh {axes} uses {total} of {len(devices)} devices; "
            f"{unused} stranded (pass an explicit device list, or size the "
            f"axes to the machine — MeshConfig enumerates factorizations)",
            stacklevel=2)
    if _telemetry.active():
        _telemetry.set_gauge("mesh.unused_devices", unused)
    arr = onp.array(devices[:total]).reshape(sizes)
    return Mesh(arr, names)


class MeshConfig:
    """The single entry point for composed parallelism: ``dp`` (data),
    ``tp`` (tensor/Megatron), ``pp`` (pipeline stages), ``sp`` (sequence/
    ring attention) — one config names the whole 4D layout and
    ``ShardedTrainStep`` composes the axes inside its one jitted step.

    Axis order on the physical device grid is ('dp', 'pp', 'sp', 'tp'):
    tp innermost so its allreduces ride the fastest ICI links, dp outermost
    so it can span DCN (scaling-book convention).

        cfg = MeshConfig(dp=2, tp=2, pp=2)      # 8 devices
        step = ShardedTrainStep(net, loss_fn, opt, cfg,
                                batch_specs=cfg.batch_specs(2, 2))

    All four axes always exist in the built Mesh (size-1 axes are free), so
    PartitionSpecs mentioning any of dp/tp/pp/sp are valid on every
    MeshConfig mesh — a checkpoint or batch spec written for one layout
    carries to another unchanged.
    """

    AXES = ("dp", "pp", "sp", "tp")

    def __init__(self, dp=1, tp=1, pp=1, sp=1):
        for name, v in (("dp", dp), ("tp", tp), ("pp", pp), ("sp", sp)):
            if int(v) != v or int(v) < 1:
                raise MXNetError(
                    f"MeshConfig {name}={v!r}: axis sizes are integers >= 1")
        self.dp, self.tp, self.pp, self.sp = int(dp), int(tp), int(pp), \
            int(sp)

    @property
    def shape(self):
        """Ordered {axis: size} over all four axes (size-1 included)."""
        return {a: getattr(self, a) for a in self.AXES}

    def size(self):
        return self.dp * self.tp * self.pp * self.sp

    def build(self, devices=None):
        """Build the jax Mesh (raises when the product exceeds the device
        count; warns + counts ``mesh.unused_devices`` on undershoot)."""
        devices = list(devices if devices is not None else jax.devices())
        if self.size() > len(devices):
            raise MXNetError(
                f"{self!r} needs {self.size()} devices, have "
                f"{len(devices)}")
        return make_mesh(self.shape, devices)

    def batch_spec(self, ndim):
        """PartitionSpec for one batch array: leading (batch) dim over
        'dp', second (sequence) dim over 'sp' when sp>1."""
        if ndim < 1:
            return P()
        parts = ["dp"]
        if ndim >= 2:
            parts.append("sp" if self.sp > 1 else None)
        return P(*parts)

    def batch_specs(self, *ndims):
        """Specs for a (inputs..., labels...) batch given each array's
        rank, e.g. ``cfg.batch_specs(2, 2)`` for GPT (tokens, labels)."""
        return tuple(self.batch_spec(n) for n in ndims)

    def activation_rules(self):
        """activation_sharding rules the step installs while tracing:
        the residual stream sharded (batch over dp, seq over sp) so the
        sp axis flows through the transformer layers' ``constrain`` hook
        and attention routes to ring_attention."""
        if self.sp > 1:
            return {"residual": P("dp", "sp", None)}
        return {}

    def replace(self, **axes):
        """A copy with the named axis sizes substituted, e.g.
        ``cfg.replace(dp=1)`` — how the fleet supervisor derives a
        degraded layout from the target one."""
        shape = self.shape
        for name in axes:
            if name not in shape:
                raise MXNetError(
                    f"MeshConfig.replace: unknown axis {name!r}; "
                    f"axes are {self.AXES}")
        shape.update(axes)
        return MeshConfig(**shape)

    def __repr__(self):
        return (f"MeshConfig(dp={self.dp}, tp={self.tp}, pp={self.pp}, "
                f"sp={self.sp})")

    def __eq__(self, other):
        return isinstance(other, MeshConfig) and self.shape == other.shape

    def __hash__(self):
        return hash(tuple(self.shape.items()))


def mesh_factorizations(n_devices=None, max_sp=1):
    """Enumerate every MeshConfig whose dp*tp*pp*sp product EXACTLY covers
    ``n_devices`` (no stranded devices) — the mesh axis mx.autotune
    searches over.  ``max_sp`` bounds the sequence axis (sp>1 only helps
    long-context models, so it defaults to off)."""
    if n_devices is None:
        n_devices = len(jax.devices())
    n_devices = int(n_devices)
    out = []
    for dp in range(1, n_devices + 1):
        if n_devices % dp:
            continue
        rem = n_devices // dp
        for tp in range(1, rem + 1):
            if rem % tp:
                continue
            rem2 = rem // tp
            for pp in range(1, rem2 + 1):
                if rem2 % pp:
                    continue
                sp = rem2 // pp
                if sp > max_sp:
                    continue
                out.append(MeshConfig(dp=dp, tp=tp, pp=pp, sp=sp))
    return out


def data_parallel_mesh(n=None):
    devs = jax.devices()
    n = n or len(devs)
    return make_mesh({"dp": n}, devs)


def set_mesh(mesh):
    global _current
    _current = mesh
    return mesh


def current_mesh():
    return _current


def shard(array, mesh, spec):
    """Place an ndarray/jax array with a PartitionSpec on a mesh."""
    from ..numpy.multiarray import ndarray, _wrap
    sharding = NamedSharding(mesh, spec if isinstance(spec, P) else P(*spec))
    raw = array._data if isinstance(array, ndarray) else array
    out = jax.device_put(raw, sharding)
    return _wrap(out) if isinstance(array, ndarray) else out


def replicate(array, mesh):
    return shard(array, mesh, P())


# -- activation sharding scope (sequence parallelism hook) ------------------
# Megatron-SP style: layers consult these rules to constrain their
# activations (residual stream sharded over ('dp', 'sp', None)); XLA then
# inserts the gather/scatter collectives around attention automatically.
_act_rules = None


class activation_sharding:
    """Scope installing activation PartitionSpec rules consulted by layers.

    with parallel.activation_sharding(mesh, residual=P('dp', 'sp', None)):
        out = net(x)            # or ShardedTrainStep built inside the scope
    """

    def __init__(self, mesh, **rules):
        self.mesh = mesh
        self.rules = rules
        self._prev = None

    def __enter__(self):
        global _act_rules
        self._prev = _act_rules
        _act_rules = (self.mesh, self.rules)
        return self

    def __exit__(self, *exc):
        global _act_rules
        _act_rules = self._prev


def constrain(x, kind):
    """Apply the active activation-sharding rule `kind` to x (ndarray or raw
    jax array); identity when no scope is active or rule missing."""
    if _act_rules is None:
        return x
    mesh, rules = _act_rules
    spec = rules.get(kind)
    if spec is None:
        return x
    from ..numpy.multiarray import ndarray, _wrap
    raw = x._data if isinstance(x, ndarray) else x
    if raw.ndim < len(spec):
        return x
    try:
        out = jax.lax.with_sharding_constraint(
            raw, NamedSharding(mesh, spec))
    except ValueError:
        return x
    return _wrap(out) if isinstance(x, ndarray) else out

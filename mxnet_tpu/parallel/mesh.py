"""Device-mesh helpers.

Reference parity: none — the reference scales via KVStore/ps-lite (SURVEY
§2.3); on TPU the mesh + GSPMD sharding is the native replacement and also
unlocks TP/PP/SP the reference lacks.

Axis convention (scaling-book style): 'dp' (data, across ICI or DCN), 'tp'
(tensor/model), 'pp' (pipeline stages), 'sp' (sequence/context), 'ep'
(experts). Helpers build meshes over any subset.
"""
from __future__ import annotations

import numpy as onp

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..base import MXNetError

_current = None


def make_mesh(axes, devices=None):
    """Create a Mesh from {'dp': 4, 'tp': 2, ...} (row-major layout so the
    innermost axis maps to neighboring devices — keeps tp on the fastest ICI
    links)."""
    devices = list(devices if devices is not None else jax.devices())
    names = tuple(axes.keys())
    sizes = tuple(int(v) for v in axes.values())
    total = int(onp.prod(sizes))
    if total > len(devices):
        raise MXNetError(f"mesh {axes} needs {total} devices, "
                         f"have {len(devices)}")
    arr = onp.array(devices[:total]).reshape(sizes)
    return Mesh(arr, names)


def data_parallel_mesh(n=None):
    devs = jax.devices()
    n = n or len(devs)
    return make_mesh({"dp": n}, devs)


def set_mesh(mesh):
    global _current
    _current = mesh
    return mesh


def current_mesh():
    return _current


def shard(array, mesh, spec):
    """Place an ndarray/jax array with a PartitionSpec on a mesh."""
    from ..numpy.multiarray import ndarray, _wrap
    sharding = NamedSharding(mesh, spec if isinstance(spec, P) else P(*spec))
    raw = array._data if isinstance(array, ndarray) else array
    out = jax.device_put(raw, sharding)
    return _wrap(out) if isinstance(array, ndarray) else out


def replicate(array, mesh):
    return shard(array, mesh, P())

"""Pipeline parallelism — GPipe-style microbatch pipelining over a mesh axis.

Reference parity: none (the reference has no PP — SURVEY §2.3 marks it a
TPU-native extension). Design (scaling-book recipe): each device along the
'pp' axis holds ONE stage's parameters (stacked pytree leading axis sharded
over 'pp'); microbatch activations rotate stage-to-stage with
lax.ppermute inside shard_map. The whole schedule is differentiable —
ppermute's transpose is the reverse permute, so jax.grad yields the 1F1B
communication pattern automatically instead of hand-written send/recv like
GPU frameworks need.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from .._jax_compat import shard_map


def gpipe(stage_fn, stage_params, xs, mesh, axis="pp"):
    """Run a pipeline of S identical-shape stages over M microbatches.

    stage_fn(params_slice, x) -> y        one stage's forward; x/y same shape
    stage_params: pytree whose leaves have leading dim S (stacked stages),
        sharded (or shardable) over `axis`.
    xs: (M, mb, ...) microbatched input (resident on every device; only
        stage 0 reads it).
    Returns (M, mb, ...) outputs of the last stage.

    Schedule: M + S - 1 ticks; at tick t, stage s computes microbatch
    t - s (when in range). Activations move s -> s+1 between ticks via
    ppermute; a device's compute at tick t overlaps the permute XLA issues
    for tick t+1 (latency-hiding scheduler).
    """
    n_stages = mesh.shape[axis]
    n_micro = xs.shape[0]
    leaves = jax.tree_util.tree_leaves(stage_params)
    for leaf in leaves:
        if leaf.shape[0] != n_stages:
            raise ValueError(
                f"stage_params leading dim {leaf.shape[0]} != pp axis size "
                f"{n_stages}: each device holds exactly one stage (a "
                f"divisible multiple would silently drop stages)")
    perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=(jax.tree_util.tree_map(lambda _: P(axis), stage_params,
                                         is_leaf=lambda x: x is None),
                  P()),
        out_specs=P(),
        check_vma=False)
    def _pipe(params, xs_rep):
        # params leaves arrive as (1, ...) blocks — drop the stage dim
        params = jax.tree_util.tree_map(lambda p: p[0], params)
        stage = jax.lax.axis_index(axis)
        mb_shape = xs_rep.shape[1:]

        def tick(t, carry):
            buf, ys = carry
            # stage 0 ingests microbatch t; others use the permuted carry
            x_in = jnp.where(
                stage == 0,
                xs_rep[jnp.clip(t, 0, n_micro - 1)],
                buf)
            y = stage_fn(params, x_in)
            # microbatch id this stage just computed: t - stage
            mb_id = t - stage
            is_last = stage == n_stages - 1
            valid = (mb_id >= 0) & (mb_id < n_micro) & is_last
            ys = jax.lax.cond(
                valid,
                lambda ys: jax.lax.dynamic_update_index_in_dim(
                    ys, y, jnp.clip(mb_id, 0, n_micro - 1), 0),
                lambda ys: ys, ys)
            buf = jax.lax.ppermute(y, axis, perm)
            return buf, ys

        buf0 = jnp.zeros(mb_shape, xs_rep.dtype)
        if hasattr(jax.lax, "pcast"):
            buf0 = jax.lax.pcast(buf0, (axis,), to="varying")
        ys0 = jnp.zeros((n_micro,) + mb_shape, xs_rep.dtype)
        if hasattr(jax.lax, "pcast"):
            ys0 = jax.lax.pcast(ys0, (axis,), to="varying")
        _, ys = jax.lax.fori_loop(0, n_micro + n_stages - 1, tick,
                                  (buf0, ys0))
        # every device returns ys; only the last stage's is populated —
        # psum broadcasts it (all other stages contribute zeros)
        return jax.lax.psum(ys, axis)

    return _pipe(stage_params, xs)


def stack_stage_params(param_list):
    """Stack per-stage pytrees (list of S identical-structure trees) into
    one tree with leading stage dim, ready for sharding over 'pp'."""
    return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *param_list)


def shard_stages(stacked, mesh, axis="pp"):
    sh = NamedSharding(mesh, P(axis))
    return jax.tree_util.tree_map(lambda x: jax.device_put(x, sh), stacked)

"""Ring attention — sequence/context parallelism for long sequences.

Reference parity: none (the reference has no SP/CP — SURVEY §5); this is the
TPU-native long-context capability the task brief makes first-class.

Design (Liu et al. ring attention, scaling-book recipe): shard the sequence
axis of Q/K/V over a mesh axis ('sp'). Each device holds one Q block and
iterates over all K/V blocks, which rotate around the ring via
lax.ppermute (ICI neighbor exchange) while the device accumulates
flash-attention-style online-softmax partial results — comm overlaps compute
because the permute for step i+1 is issued alongside the matmuls of step i
(XLA latency-hiding scheduler).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from .._jax_compat import shard_map


def _block_attn(q, k, v, m_prev, l_prev, acc, scale, mask=None):
    """One online-softmax accumulation step.
    q: (b, h, sq, d); k/v: (b, h, sk, d); m/l: (b, h, sq, 1); acc like q."""
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    if mask is not None:
        s = jnp.where(mask, s, -jnp.inf)
    m_cur = jnp.max(s, axis=-1, keepdims=True)
    m_new = jnp.maximum(m_prev, m_cur)
    # guard fully-masked rows (m_new == -inf)
    m_safe = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
    p = jnp.exp(s - m_safe)
    if mask is not None:
        p = jnp.where(mask, p, 0.0)
    correction = jnp.exp(jnp.where(jnp.isfinite(m_prev), m_prev - m_safe,
                                   -jnp.inf))
    correction = jnp.where(jnp.isfinite(m_prev), correction, 0.0)
    l_new = correction * l_prev + jnp.sum(p, axis=-1, keepdims=True)
    acc_new = correction * acc + jnp.einsum("bhqk,bhkd->bhqd",
                                            p.astype(v.dtype), v)
    return m_new, l_new, acc_new


def ring_attention(q, k, v, mesh, axis="sp", causal=False, scale=None):
    """Sequence-sharded attention.

    q, k, v: (batch, heads, seq, head_dim) jax arrays (or mx ndarrays),
    sharded (or shardable) over `axis` on the seq dimension. Returns the
    attention output with the same sharding.
    """
    from ..numpy.multiarray import ndarray, _wrap
    wrap = isinstance(q, ndarray)
    if wrap:
        q, k, v = q._data, k._data, v._data
    d = q.shape[-1]
    scale = scale if scale is not None else 1.0 / (d ** 0.5)
    n = mesh.shape[axis]
    perm = [(i, (i + 1) % n) for i in range(n)]

    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=(P(None, None, axis, None),) * 3,
        out_specs=P(None, None, axis, None))
    def _ring(qb, kb, vb):
        my = jax.lax.axis_index(axis)
        sq = qb.shape[2]

        def step(i, carry):
            kc, vc, m, l, acc = carry
            if causal:
                src = (my - i) % n  # ring shifts K/V forward each step
                q_pos = my * sq + jax.lax.broadcasted_iota(
                    jnp.int32, (sq, sq), 0)
                k_pos = src * sq + jax.lax.broadcasted_iota(
                    jnp.int32, (sq, sq), 1)
                mask = (q_pos >= k_pos)[None, None]
            else:
                mask = None
            m, l, acc = _block_attn(qb, kc, vc, m, l, acc, scale, mask)
            kc = jax.lax.ppermute(kc, axis, perm)
            vc = jax.lax.ppermute(vc, axis, perm)
            return kc, vc, m, l, acc

        b, h = qb.shape[0], qb.shape[1]
        m0 = jnp.full((b, h, sq, 1), -jnp.inf, jnp.float32)
        l0 = jnp.zeros((b, h, sq, 1), jnp.float32)
        acc0 = jnp.zeros(qb.shape, jnp.float32)
        # constants start unvarying over the mesh axis; the loop makes them
        # varying — cast up front so the scan carry types match
        if hasattr(jax.lax, "pcast"):
            m0, l0, acc0 = (jax.lax.pcast(t, (axis,), to="varying")
                            for t in (m0, l0, acc0))
        _, _, m, l, acc = jax.lax.fori_loop(
            0, n, step, (kb, vb, m0, l0, acc0))
        return (acc / jnp.maximum(l, 1e-20)).astype(qb.dtype)

    out = _ring(q, k, v)
    return _wrap(out) if wrap else out

"""Sharded training step — the whole Trainer.step path as one XLA program.

Reference parity: python/mxnet/gluon/trainer.py:334 (step = backward grads →
kvstore pushpull allreduce → optimizer update, overlapped by the dependency
engine) and the KVStore reduce machinery (src/kvstore/comm.h). TPU-native:
forward + backward + gradient allreduce + optimizer update compile into ONE
jit program over a jax.sharding.Mesh — XLA inserts the collectives from the
shardings (data-parallel psum over 'dp', Megatron tensor-parallel
allreduces over 'tp', sequence sharding over 'sp') and its latency-hiding
scheduler overlaps comm with compute, which is the engine's compute/comm
overlap re-created at compile time.
"""
from __future__ import annotations

import functools
import re

import numpy as onp

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .. import blackbox as _blackbox
from .. import config as _config
from .. import functional
from .. import insight as _insight
from .. import pipeline as _pipeline
from .. import telemetry as _telemetry
from ..amp import fp8 as _fp8
from ..base import MXNetError
from ..numpy.multiarray import ndarray, _wrap
from .mesh import MeshConfig, activation_sharding

_telemetry.declare_metric(
    "zero.reduce_scatter_bytes_total", "counter",
    "logical bytes reduce-scattered over the dp axis by ZeRO gradient "
    "partitioning (per optimizer update, padded flat layout)")
_telemetry.declare_metric(
    "zero.all_gather_bytes_total", "counter",
    "logical bytes all-gathered over the dp axis re-assembling ZeRO-updated "
    "parameters")
_telemetry.declare_metric(
    "mesh.dp_gradient_bytes_total", "counter",
    "logical gradient bytes reduced over the dp axis per optimizer update "
    "(total trainable bytes; overlaps the zero.* counters when ZeRO folds "
    "the reduction into its reduce-scatter)")
_telemetry.declare_metric(
    "mesh.tp_allreduce_bytes_total", "counter",
    "estimated activation bytes allreduced over the tp axis per step "
    "(row-parallel layer outputs x tokens; logical estimate for "
    "token-shaped inputs)")
_telemetry.declare_metric(
    "mesh.pp_stage_transfer_bytes_total", "counter",
    "estimated residual-stream bytes handed stage-to-stage over the pp "
    "axis per step (forward + backward; logical estimate)")
_telemetry.declare_metric(
    "mesh.collective_bytes_total", "counter",
    "per-axis breakdown of logical collective bytes moved by the training "
    "step, labeled axis=dp|tp|pp; the dp sample counts WIRE bytes at the "
    "compressed width when gradient compression is on, so the >=2x dp cut "
    "is directly observable against mesh.dp_gradient_bytes_total")
_telemetry.declare_metric(
    "zero.collective_bytes_total", "counter",
    "per-op breakdown of the ZeRO dp collectives, labeled "
    "op=reduce_scatter|all_gather (same logical bytes the unlabeled "
    "zero.*_bytes_total counters accumulate)")
_telemetry.declare_metric(
    "comm.compressed_bytes_total", "counter",
    "dp gradient bytes actually placed on the wire by error-feedback "
    "compression (int8 payload + one fp32 scale per bucket per rank)")
_telemetry.declare_metric(
    "comm.uncompressed_bytes_total", "counter",
    "dp gradient bytes that WOULD have moved without compression (fp32 "
    "per-microbatch reduce) — the denominator of the compression ratio")

# params whose structural name matches <prefix>layer<i>.<suffix> with
# identical shapes across i are the pipeline-stackable layer family
_PP_LAYER_RE = re.compile(r"^(?P<pre>.*\blayer)(?P<idx>\d+)\.(?P<suf>.+)$")


def _pp_layer_groups(names):
    """Group param names by (prefix, suffix) around a 'layerN.' segment:
    {(pre, suf): {idx: name}}."""
    groups = {}
    for n in names:
        m = _PP_LAYER_RE.match(n)
        if m:
            key = (m.group("pre"), m.group("suf"))
            groups.setdefault(key, {})[int(m.group("idx"))] = n
    return groups


def _insert_dp(spec, shape, dp_axis, dp_n):
    """Optimizer-state spec for a tensor-sharded param under ZeRO: the
    param's spec with ``dp_axis`` partitioning its largest free
    (replicated, evenly divisible) dimension — the reduce-scatter target.
    None when no dimension can take the dp axis (state then shards like
    the weight)."""
    entries = list(spec) + [None] * (len(shape) - len(spec))
    flat = []
    for e in entries:
        flat.extend(e if isinstance(e, tuple) else (e,))
    if dp_axis in flat:
        return None
    free = [i for i, e in enumerate(entries)
            if e is None and shape[i] % dp_n == 0 and shape[i] >= dp_n]
    if not free:
        return None
    best = max(free, key=lambda i: shape[i])
    entries[best] = dp_axis
    return P(*entries)

# name-pattern Megatron rules for the transformer family
# (column-parallel: shard Dense units; row-parallel: shard in_units, psum)
_COLUMN_SUFFIXES = ("query_proj.weight", "key_proj.weight",
                    "value_proj.weight", "ffn_1.weight")
_ROW_SUFFIXES = ("out_proj.weight", "ffn_2.weight")
_COLUMN_BIAS = ("query_proj.bias", "key_proj.bias", "value_proj.bias",
                "ffn_1.bias")


def megatron_specs(param_shapes, tp_axis="tp"):
    """PartitionSpecs for transformer params by structural-name pattern."""
    specs = {}
    for name, shape in param_shapes.items():
        if any(name.endswith(s) for s in _COLUMN_SUFFIXES) and len(shape) == 2:
            specs[name] = P(tp_axis, None)
        elif any(name.endswith(s) for s in _ROW_SUFFIXES) and len(shape) == 2:
            specs[name] = P(None, tp_axis)
        elif any(name.endswith(s) for s in _COLUMN_BIAS):
            specs[name] = P(tp_axis)
        else:
            specs[name] = P()
    return specs


class FunctionalOptimizer:
    """Pure-functional adapter over a mxnet_tpu Optimizer instance so its
    update rule can run inside a jit/pjit trace (the analog of the fused
    multi-tensor update ops, src/operator/optimizer_op.cc:352)."""

    def __init__(self, optimizer):
        self.opt = optimizer

    def init(self, raw_params):
        states = {}
        for name in raw_params:
            # states/settings key by STRUCTURAL NAME, not position: dict
            # ordering through a jit boundary is canonicalized, so a
            # positional index could bind lr_mult/wd_mult to the wrong
            # parameter vs the eager Trainer (collect_params order)
            s = self.opt.create_state(name, _wrap(raw_params[name]))
            states[name] = jax.tree_util.tree_map(
                lambda x: x._data if isinstance(x, ndarray) else x, s,
                is_leaf=lambda x: isinstance(x, ndarray))
        return states

    def update(self, raw_params, raw_grads, states, lr=None, t=None):
        new_p, new_s = {}, {}
        saved_count = self.opt.num_update
        if t is not None:
            # thread the (traced) step count into the update rules so
            # Adam-family bias correction advances inside the compiled step;
            # restored below so host-side bookkeeping never sees a tracer
            self.opt.num_update = t
        try:
            for name in raw_params:
                if name not in raw_grads:
                    new_p[name] = raw_params[name]
                    new_s[name] = states[name]
                    continue
                wd = self.opt._get_wd(name)
                lr_i = lr if lr is not None else self.opt._get_lr(name)
                wrapped = jax.tree_util.tree_map(
                    _wrap, states[name],
                    is_leaf=lambda x: x is None)
                w, s = self.opt._update_impl(
                    raw_params[name], raw_grads[name], wrapped, lr_i, wd)
                new_p[name] = w.astype(raw_params[name].dtype)
                new_s[name] = jax.tree_util.tree_map(
                    lambda x: x._data if isinstance(x, ndarray) else x, s,
                    is_leaf=lambda x: isinstance(x, ndarray))
        finally:
            if t is not None:
                self.opt.num_update = saved_count
        return new_p, new_s


def scan_steps(step_fn, n_state):
    """Fuse K training steps into one compiled program with ``lax.scan``.

    ``step_fn(*state, *batch) -> (*state', metric)`` becomes
    ``loop(*state, *stacked) -> (*state', metric_mean)`` where each array
    in ``stacked`` carries a leading steps axis.  One executable launch
    then performs K steps — amortizing per-launch dispatch latency, the
    step-level analog of the reference engine's op bulking
    (src/engine/threaded_engine.h:433; there ops are batched into one
    engine op, here whole steps into one XLA program).
    """
    from jax import lax

    def loop(*args):
        state, batches = args[:n_state], args[n_state:]

        def body(carry, xs):
            out = step_fn(*carry, *xs)
            return tuple(out[:n_state]), out[-1]

        state, metrics = lax.scan(body, tuple(state), tuple(batches))
        return (*state, jnp.mean(metrics))

    return loop


class ShardedTrainStep:
    """Compiled data/tensor/sequence-parallel training step for a Block.

    block: initialized (Hybrid)Block.
    loss_fn(outputs, *labels) -> scalar (raw jax values).
    optimizer: mxnet_tpu Optimizer instance (or name via opt.create).
    mesh: a MeshConfig (the composed dp×tp×pp×sp entry point — builds
        the Mesh, derives activation rules for sp, and turns on layer
        stacking for pp) or a raw jax.sharding.Mesh; dp_axis must exist
        for zero>0; tp/pp/sp optional.
    batch_specs: PartitionSpec per batch arg (inputs then labels),
        e.g. (P('dp', 'sp'), P('dp',)) — or ``cfg.batch_specs(...)``.
    param_specs: dict name -> PartitionSpec; defaults to megatron_specs
        when the mesh has a tp axis else fully replicated.
    zero: ZeRO optimizer-state partitioning level over the dp axis.
        0 — state shards like its weight (replicated under pure dp).
        1 — optimizer state lives in 1/dp flat shards; each step
        reduce-scatters grads, updates the local shard, all-gathers the
        new params — all inside the one jitted program so XLA overlaps
        the collectives with compute.  Params that are already tensor-
        sharded (tp/ep/pp) partition their REPLICATED sub-axis instead:
        the optimizer state carries the param's spec with 'dp' inserted
        into a free dimension, grads reduce-scatter onto it, the
        elementwise update runs on the (tp×dp)-sharded chunk, and the
        new params gather back to the tp-sharded layout — ZeRO×TP in
        one program.
        2 — additionally keeps reduced gradients (incl. the grad_accum
        accumulator) laid out in the same dp shards, so full gradients
        never materialize replicated.
    grad_accum: accumulate gradients over K lax.scan microbatches before
        ONE optimizer update (batch arrays gain a leading K axis).
        Distinct from steps_per_call, which applies an update every step.
    remat: activation rematerialization for the fwd/bwd inside the step —
        same values as ``HybridBlock.hybridize(remat=...)`` (True,
        'dots', a policy callable); None inherits the block's hybridize
        flag.
    precision: "fp32" (default) or "fp8" — fp8 runs eligible Dense
        matmuls e4m3-forward / e5m2-backward with per-tensor delayed
        scaling (mx.amp.fp8); the amax histories thread through the step
        as donated state and checkpoint with the optimizer bundle.
        Master weights, accumulation and the optimizer update stay fp32.
    grad_compress: None (read the ``comm.compress`` knob), "none",
        "int8" or "bf16" — error-feedback compression of the per-
        microbatch dp gradient all-reduce.  Gradients flatten into
        ``comm.bucket_mb`` buckets; each bucket quantizes (shared scale
        = pmax over ranks), psums at the wire width and carries the
        quantization error into the next step's gradient (EF-SGD), so
        the compression error telescopes instead of accumulating.  The
        independent per-bucket collectives are what XLA's latency-hiding
        scheduler overlaps with backward compute.  Requires a pure-dp
        mesh (tp=pp=sp=1) and every batch arg sharded over dp; silently
        off at dp=1.
    """

    def __init__(self, block, loss_fn, optimizer, mesh, batch_specs,
                 n_labels=1, param_specs=None, donate=True,
                 steps_per_call=1, zero=0, grad_accum=1, remat=None,
                 dp_axis="dp", precision="fp32", grad_compress=None):
        from ..optimizer import optimizer as opt_mod
        from ..gluon.block import resolve_remat_policy, _REMAT_OFF
        if isinstance(optimizer, str):
            optimizer = opt_mod.create(optimizer)
        self.block = block
        self.loss_fn = loss_fn
        self.mesh_config = mesh if isinstance(mesh, MeshConfig) else None
        if self.mesh_config is not None:
            mesh = self.mesh_config.build()
        self.mesh = mesh
        # sp flows through the activation_sharding scope: the rules are
        # installed around every _step call so layer `constrain` hooks and
        # the ring-attention routing see them at trace time
        self._act_rules = (self.mesh_config.activation_rules()
                           if self.mesh_config is not None else {})
        if _blackbox._active and self.mesh_config is not None:
            # postmortems answer "what mesh was this host running?"
            _blackbox.note_mesh(self.mesh_config)
        self.n_labels = n_labels
        self.dp_axis = dp_axis
        # per-update specs as given (before the grad_accum/steps_per_call
        # lead axes are folded in below) — autotune() rebuilds steps with
        # different lead-axis geometry from these
        self.batch_specs = tuple(batch_specs)
        self.zero = int(zero)
        self.grad_accum = int(grad_accum)
        self.steps_per_call = int(steps_per_call)
        if self.zero not in (0, 1, 2):
            raise MXNetError(f"zero must be 0, 1 or 2, got {zero}")
        if self.grad_accum < 1:
            raise MXNetError(f"grad_accum must be >= 1, got {grad_accum}")
        self.precision = str(precision)
        if self.precision not in ("fp32", "fp8"):
            raise MXNetError(
                f"precision must be 'fp32' or 'fp8', got {precision!r}")
        self._fp8 = self.precision == "fp8"
        if grad_compress is None:
            grad_compress = _config.get("comm.compress")
        self._compress = str(grad_compress or "none").lower()
        if self._compress not in ("none", "int8", "bf16"):
            raise MXNetError(
                "grad_compress must be 'none', 'int8' or 'bf16', got "
                f"{grad_compress!r}")
        if self._compress != "none":
            others = {a: s for a, s in dict(mesh.shape).items()
                      if a != dp_axis and int(s) > 1}
            if others:
                raise MXNetError(
                    f"grad_compress='{self._compress}' needs a pure-dp "
                    f"mesh (the compressed reduce runs in a shard_map "
                    f"over '{dp_axis}' only); mesh also has {others}")
            for s in self.batch_specs:
                flat = []
                for e in tuple(s):
                    flat.extend(e if isinstance(e, tuple) else (e,))
                if dp_axis not in flat:
                    raise MXNetError(
                        f"grad_compress='{self._compress}' requires every "
                        f"batch arg sharded over '{dp_axis}'; got spec {s}")
            if int(mesh.shape.get(dp_axis, 1)) <= 1:
                self._compress = "none"   # nothing to reduce: plain path
        if remat is None and isinstance(getattr(block, "_flags", None), dict):
            remat = block._flags.get("remat")
        # kept as given so rebuild() can re-construct an equivalent step
        # around a different MeshConfig (fleet degrade/re-expand)
        self._donate = bool(donate)
        self._remat_arg = remat
        self._remat_policy = resolve_remat_policy(remat)
        self._remat_on = self._remat_policy is not _REMAT_OFF
        trainable, aux = functional.split_params(block)
        shapes = {n: v.shape for n, v in trainable.items()}
        shapes.update({n: v.shape for n, v in aux.items()})
        if param_specs is None:
            if "tp" in mesh.shape:
                param_specs = megatron_specs(shapes)
            else:
                param_specs = {n: P() for n in shapes}

        # -- pipeline stacking: layer families become one (S*k, ...) leaf --
        # Each repeated `<prefix>layerN.<suffix>` family stacks into a
        # single leaf whose leading (layer) dim shards over 'pp': every pp
        # group stores only its contiguous block of layers, and the static
        # per-layer index in the model's forward loop is the stage handoff
        # GSPMD lowers to a collective-permute — gpipe's ppermute schedule
        # expressed as sharding instead of shard_map, so it composes with
        # dp/tp/sp and the grad_accum microbatch scan.
        pp_n = int(mesh.shape.get("pp", 1))
        self._pp_groups = {}
        if pp_n > 1:
            param_specs = dict(param_specs)
            for d in (trainable, aux):
                for (pre, suf), idx_map in _pp_layer_groups(d).items():
                    L = len(idx_map)
                    if sorted(idx_map) != list(range(L)):
                        continue   # holes in the index range: not a family
                    members = [idx_map[i] for i in range(L)]
                    if len({tuple(d[m].shape) for m in members}) != 1:
                        continue
                    if L % pp_n:
                        raise MXNetError(
                            f"pp={pp_n}: layer family '{pre}N.{suf}' has "
                            f"{L} layers — not divisible into {pp_n} "
                            f"pipeline stages")
                    sname = f"{pre}*.{suf}"
                    d[sname] = jnp.stack([d.pop(m) for m in members])
                    base = param_specs.get(members[0], P())
                    param_specs[sname] = P("pp", *tuple(base))
                    self._pp_groups[sname] = {"members": members}
            if not self._pp_groups:
                raise MXNetError(
                    f"pp={pp_n} needs repeated 'layerN.' parameter "
                    "families of identical shape to place on pipeline "
                    "stages; none found in this block")
        self.param_specs = param_specs
        self.fopt = FunctionalOptimizer(optimizer)

        def sh(spec):
            return NamedSharding(mesh, spec)

        self.trainable = {
            n: jax.device_put(v, sh(param_specs.get(n, P())))
            for n, v in trainable.items()}
        self.aux = {
            n: jax.device_put(v, sh(param_specs.get(n, P())))
            for n, v in aux.items()}

        # -- ZeRO layout: which params get dp-partitioned optimizer state --
        if self.zero and dp_axis not in mesh.shape:
            raise MXNetError(
                f"zero={self.zero} requires a '{dp_axis}' mesh axis; "
                f"mesh has {tuple(mesh.shape)}")
        if self.zero and not type(self.fopt.opt)._zero_partitionable:
            raise MXNetError(
                f"{type(self.fopt.opt).__name__} is not elementwise "
                "(layer-wise norms / per-tensor RNG); it cannot run on "
                "ZeRO shards — use zero=0")
        dp_n = int(mesh.shape[dp_axis]) if self.zero else 1
        # Two ZeRO layouts:
        #   _zero: name -> (shape, size, padded_size) — fully-replicated
        #     params partition into flat 1/dp shards (padded ravel).
        #   _zero_tp: name -> state PartitionSpec — tensor-sharded
        #     (tp/ep/pp) params partition their REPLICATED sub-axis: the
        #     state carries the param spec with dp inserted into a free
        #     dim, grads reduce-scatter onto it, the elementwise update
        #     runs on the chunk and the new params gather back to the
        #     tensor-sharded layout (ZeRO x TP).
        self._zero = {}
        self._zero_tp = {}
        if self.zero:
            for n, v in self.trainable.items():
                spec = param_specs.get(n, P())
                if any(e is not None for e in spec):
                    sspec = _insert_dp(spec, v.shape, dp_axis, dp_n)
                    if sspec is not None:
                        self._zero_tp[n] = sspec
                    continue
                size = int(v.size)
                padded = -(-size // dp_n) * dp_n
                self._zero[n] = (tuple(v.shape), size, padded)

        states = {}
        for n, v in self.trainable.items():
            zinfo = self._zero.get(n)
            if zinfo is None:
                tspec = self._zero_tp.get(n)
                s = self.fopt.init({n: v})[n]
                if tspec is not None:
                    bad = [l.shape for l in jax.tree_util.tree_leaves(s)
                           if l.shape != v.shape]
                    if bad:
                        raise MXNetError(
                            f"{type(self.fopt.opt).__name__} state for "
                            f"'{n}' is not elementwise (leaf shapes "
                            f"{bad}); zero>0 unsupported")
                states[n] = jax.tree_util.tree_map(
                    lambda x: jax.device_put(
                        x, sh(tspec if tspec is not None
                              else param_specs.get(n, P())))
                    if x is not None else None, s,
                    is_leaf=lambda x: x is None)
                continue
            shape, size, padded = zinfo
            flat = jnp.pad(jnp.ravel(v), (0, padded - size)) \
                if padded != size else jnp.ravel(v)
            s = self.fopt.init({n: flat})[n]
            bad = [l.shape for l in jax.tree_util.tree_leaves(s)
                   if l.shape != (padded,)]
            if bad:
                raise MXNetError(
                    f"{type(self.fopt.opt).__name__} state for '{n}' is not "
                    f"elementwise (leaf shapes {bad}); zero>0 unsupported")
            states[n] = jax.tree_util.tree_map(
                lambda x: jax.device_put(x, sh(P(dp_axis)))
                if x is not None else None, s, is_leaf=lambda x: x is None)
        self.states = states

        # -- fp8 delayed-scaling state (amax histories per eligible site) --
        self._fp8_sites = []
        self._fp8_margin = 1.0
        fp8_state = {}
        if self._fp8:
            tshapes = {n: tuple(v.shape) for n, v in self.trainable.items()}
            self._fp8_sites = _fp8.select_sites(tshapes)
            if not self._fp8_sites:
                raise MXNetError(
                    "precision='fp8' found no eligible sites (2-D "
                    "'*.weight' params with >= amp.fp8_min_elems "
                    f"elements) among {sorted(tshapes)}")
            self._fp8_margin = float(_config.get("amp.fp8_margin"))
            fp8_state = {
                site: {k: jax.device_put(v, sh(P())) for k, v in h.items()}
                for site, h in _fp8.init_state(self._fp8_sites).items()}
            # serve-side engines key quantization guards off this tag
            # (it also rides save_states metadata for cold loads)
            block._fp8_trained = True

        # -- error-feedback compression buckets over the dp axis --
        self._buckets = []
        resid_state = {}
        if self._compress != "none":
            dp_n_c = int(mesh.shape[dp_axis])
            bucket_elems = max(1, int(
                float(_config.get("comm.bucket_mb")) * (1 << 20) / 4))
            cur, cur_sz = [], 0
            for n in sorted(self.trainable):
                v = self.trainable[n]
                size = int(v.size)
                if cur and cur_sz + size > bucket_elems:
                    self._buckets.append(cur)
                    cur, cur_sz = [], 0
                cur.append((n, tuple(v.shape), size))
                cur_sz += size
            if cur:
                self._buckets.append(cur)
            # residuals live as one (dp, bucket) row per rank so the EF
            # error stays rank-local across steps (and across elastic
            # resizes via the canonical sum in state_dict)
            for i, members in enumerate(self._buckets):
                bsz = sum(s for _, _, s in members)
                resid_state[f"bucket{i}"] = jax.device_put(
                    jnp.zeros((dp_n_c, bsz), jnp.float32), sh(P(dp_axis)))
        self.extra = {"fp8": fp8_state, "resid": resid_state}

        # dp wire bytes per UPDATE (for the axis="dp" counter): plain
        # training reduces the full fp32 gradient once per update;
        # compression reduces int8/bf16 payload + one fp32 scale per
        # bucket PER MICROBATCH (EF must apply before accumulation)
        if self._compress == "none":
            self._dp_wire_bytes = sum(
                int(v.size) * jnp.dtype(v.dtype).itemsize
                for v in self.trainable.values())
        else:
            width = 1 if self._compress == "int8" else 2
            payload = sum(sum(s for _, _, s in m) for m in self._buckets)
            self._dp_wire_bytes = (
                (payload * width + 4 * len(self._buckets)) * self.grad_accum)

        param_sh = {n: sh(param_specs.get(n, P())) for n in trainable}
        aux_sh = {n: sh(param_specs.get(n, P())) for n in aux}
        state_sh = {
            n: jax.tree_util.tree_map(
                lambda x: sh(P(dp_axis)) if n in self._zero
                else sh(self._zero_tp[n]) if n in self._zero_tp
                else sh(param_specs.get(n, P())),
                self.states[n], is_leaf=lambda x: x is None)
            for n in self.states}
        # None states have no sharding
        state_sh = {
            n: jax.tree_util.tree_map(
                lambda x, s: None if x is None else s,
                self.states[n], state_sh[n], is_leaf=lambda x: x is None)
            for n in self.states}

        if self._zero:
            self._build_zero_update()
            itemsz = {n: jnp.dtype(self.trainable[n].dtype).itemsize
                      for n in self._zero}
            self._zero_bytes = sum(
                info[2] * itemsz[n] for n, info in self._zero.items())
        else:
            self._zero_bytes = 0
        self._zero_tp_bytes = sum(
            int(self.trainable[n].size)
            * jnp.dtype(self.trainable[n].dtype).itemsize
            for n in self._zero_tp)
        # analytic per-axis traffic (the mesh.* counters __call__ feeds)
        self._trainable_bytes = sum(
            int(v.size) * jnp.dtype(v.dtype).itemsize
            for v in self.trainable.values())
        self._tp_row_out_units = []
        if int(mesh.shape.get("tp", 1)) > 1:
            for n, v in self.trainable.items():
                if not any(n.endswith(s) for s in _ROW_SUFFIXES):
                    continue
                if n in self._pp_groups:
                    self._tp_row_out_units.append(
                        (int(v.shape[0]), int(v.shape[1])))
                else:
                    self._tp_row_out_units.append((1, int(v.shape[0])))
        self._pp_width = 0
        for n, v in self.trainable.items():
            if n in self._pp_groups and n.endswith("ln.gamma"):
                self._pp_width = int(v.shape[-1])
                break

        def base_step(trainable, aux, states, extra, rng, lr, t, *batch):
            inputs = batch[:len(batch) - self.n_labels]
            labels = batch[len(batch) - self.n_labels:]
            scales = (_fp8.scales_from_state(extra["fp8"], self._fp8_margin)
                      if self._fp8 else {})
            loss, mutated, grads, fwd_amax, g_amax, resid = self._fwd_bwd(
                trainable, aux, rng, inputs, labels, scales, extra["resid"])
            new_fp8 = (_fp8.roll_state(extra["fp8"], fwd_amax, g_amax)
                       if self._fp8 else extra["fp8"])
            new_tr, new_states = self._apply_updates(
                trainable, grads, states, lr, t)
            return (new_tr, {**aux, **mutated}, new_states,
                    {"fp8": new_fp8, "resid": resid}, loss)

        spec_list = list(batch_specs)
        step = base_step

        if self.grad_accum > 1:
            from jax import lax
            K = self.grad_accum
            zero2 = self._zero if self.zero >= 2 else {}
            zero2tp = self._zero_tp if self.zero >= 2 else {}

            def step(trainable, aux, states, extra, rng, lr, t, *batches):
                # microbatches carry a leading K axis; ONE update at the end.
                # At zero>=2 the accumulator holds flat dp shards — the
                # long-lived gradient memory is 1/dp per device and each
                # microbatch grad reduce-scatters straight into it.
                # (tensor-sharded params accumulate in their dp-inserted
                # state layout instead of the flat one.)
                def g_init(n, v):
                    if n in zero2:
                        return self._dp_constrain(
                            jnp.zeros((self._zero[n][2],), v.dtype))
                    if n in zero2tp:
                        return self._ztp_constrain(
                            n, jnp.zeros(v.shape, v.dtype))
                    return jnp.zeros(v.shape, v.dtype)

                acc0 = {n: g_init(n, v) for n, v in trainable.items()}
                # scales come from the PRE-update histories once for all
                # microbatches; the history rolls ONCE per update with the
                # max amax over the scan (delayed scaling's contract)
                scales = (_fp8.scales_from_state(
                    extra["fp8"], self._fp8_margin) if self._fp8 else {})
                zf32 = jnp.zeros((), jnp.float32)
                fwd0 = {s: (zf32, zf32) for s in self._fp8_sites}
                g0 = {s: zf32 for s in self._fp8_sites}

                def body(carry, xs):
                    aux_c, acc, resid, fa, ga, i = carry
                    inputs = xs[:len(xs) - self.n_labels]
                    labels = xs[len(xs) - self.n_labels:]
                    loss, mutated, grads, fwd_amax, g_amax, resid = (
                        self._fwd_bwd(
                            trainable, aux_c, jax.random.fold_in(rng, i),
                            inputs, labels, scales, resid))

                    def add(n):
                        g = grads[n]
                        if n in zero2:
                            g = self._dp_constrain(self._flat_pad(n, g))
                        elif n in zero2tp:
                            g = self._ztp_constrain(n, g)
                        return acc[n] + g

                    acc = {n: add(n) for n in acc}
                    fa = _fp8.merge_amax(fa, fwd_amax)
                    ga = _fp8.merge_amax(ga, g_amax)
                    return ({**aux_c, **mutated}, acc, resid, fa, ga,
                            i + 1), loss

                (aux, acc, resid, fa, ga, _), losses = lax.scan(
                    body, (aux, acc0, extra["resid"], fwd0, g0, 0),
                    tuple(batches))
                grads = {n: a / K for n, a in acc.items()}
                zflat = {n: grads.pop(n) for n in zero2} or None
                new_fp8 = (_fp8.roll_state(extra["fp8"], fa, ga)
                           if self._fp8 else extra["fp8"])
                new_tr, new_states = self._apply_updates(
                    trainable, grads, states, lr, t, zero_flat_grads=zflat)
                return (new_tr, aux, new_states,
                        {"fp8": new_fp8, "resid": resid}, jnp.mean(losses))

            spec_list = [P(None, *s) for s in spec_list]

        if self.steps_per_call > 1:
            inner = step

            def step(trainable, aux, states, extra, rng, lr, t, *batches):
                # batches carry a leading steps axis; one launch = K steps
                # (implementation shared with the free function scan_steps)
                def one(tr, ax, st, ex, i, *xs):
                    tr, ax, st, ex, loss = inner(
                        tr, ax, st, ex, jax.random.fold_in(rng, i), lr,
                        t + i, *xs)
                    return tr, ax, st, ex, i + 1, loss

                out = scan_steps(one, n_state=5)(
                    trainable, aux, states, extra, 0, *batches)
                return out[0], out[1], out[2], out[3], out[5]

            spec_list = [P(None, *s) for s in spec_list]

        self.batch_shardings = tuple(sh(s) for s in spec_list)

        extra_sh = {
            "fp8": {site: {k: sh(P()) for k in h}
                    for site, h in self.extra["fp8"].items()},
            "resid": {n: sh(P(dp_axis)) for n in self.extra["resid"]},
        }
        donate_argnums = (0, 1, 2, 3) if donate else ()
        self._step = jax.jit(
            step,
            in_shardings=(param_sh, aux_sh, state_sh, extra_sh, sh(P()),
                          sh(P()), sh(P())) + self.batch_shardings,
            out_shardings=(param_sh, aux_sh, state_sh, extra_sh, sh(P())),
            donate_argnums=donate_argnums)
        self._n_step = 0

    # -- step internals -----------------------------------------------------
    def _expand_pp(self, params):
        """Unstack pipeline families back to per-layer names for the
        block's forward: each static slice of the pp-sharded stack is one
        layer's weights, and consuming it on the next stage's microbatch
        is the stage handoff GSPMD lowers to a collective-permute."""
        if not self._pp_groups:
            return params
        out = dict(params)
        for sname, g in self._pp_groups.items():
            if sname not in out:
                continue
            stacked = out.pop(sname)
            for i, member in enumerate(g["members"]):
                out[member] = stacked[i]
        return out

    def _collapse_pp(self, updates):
        """Inverse of _expand_pp for the mutated-aux dict the forward
        returns (BatchNorm running stats inside pipelined layers)."""
        if not self._pp_groups or not updates:
            return updates
        out = dict(updates)
        for sname, g in self._pp_groups.items():
            members = g["members"]
            hit = [m for m in members if m in out]
            if not hit:
                continue
            if len(hit) != len(members):
                raise MXNetError(
                    f"pipeline family {sname}: forward mutated only "
                    f"{len(hit)}/{len(members)} member layers — stages "
                    "must update aux state uniformly")
            out[sname] = jnp.stack([out.pop(m) for m in members])
        return out

    def _loss_and_grad(self, trainable, aux, rng, inputs, labels):
        def lossf(tr):
            out, mutated = functional.functional_call(
                self.block, self._expand_pp({**tr, **aux}), *inputs,
                train=True, rng_key=rng)
            return self.loss_fn(out, *labels), self._collapse_pp(mutated)

        if self._remat_on:
            lossf = jax.checkpoint(lossf, policy=self._remat_policy)
        return jax.value_and_grad(lossf, has_aux=True)(trainable)

    def _fp8_loss_and_grad(self, trainable, aux, rng, inputs, labels,
                           scales):
        """fp8 forward/backward: the loss closure runs under the fp8
        scope (Dense routes matching sites through amp.fp8.dense_fp8) and
        differentiates w.r.t. BOTH the params and the per-site g_scales —
        the g_scale "cotangents" are the measured gradient amaxes the
        delayed-scaling history roll consumes (see amp/fp8.py)."""
        gsc = {s: scales[s][2] for s in scales}

        def lossf(tr, g):
            sc = {s: (scales[s][0], scales[s][1], g[s]) for s in g}
            with _fp8.scope(sc) as ctx:
                out, mutated = functional.functional_call(
                    self.block, self._expand_pp({**tr, **aux}), *inputs,
                    train=True, rng_key=rng)
                loss = self.loss_fn(out, *labels)
                amax = dict(ctx.amax)
            return loss, (self._collapse_pp(mutated), amax)

        if self._remat_on:
            lossf = jax.checkpoint(lossf, policy=self._remat_policy)
        (loss, (mutated, fwd_amax)), (grads, g_amax) = jax.value_and_grad(
            lossf, argnums=(0, 1), has_aux=True)(trainable, gsc)
        # fixed pytree structure for scan carries: sites the forward never
        # reached this trace report amax 0 (roll_state treats 0 as "no
        # observation growth")
        zf32 = jnp.zeros((), jnp.float32)
        fwd_amax = {s: fwd_amax.get(s, (zf32, zf32)) for s in gsc}
        return loss, mutated, grads, fwd_amax, g_amax

    def _fwd_bwd(self, trainable, aux, rng, inputs, labels, scales, resid):
        """One microbatch forward+backward; returns
        ``(loss, mutated, grads, fwd_amax, g_amax, new_resid)`` with the
        amax dicts empty unless fp8 and ``new_resid`` passed through
        unchanged unless compression is on."""
        if self._compress != "none":
            return self._compressed_fwd_bwd(
                trainable, aux, rng, inputs, labels, scales, resid)
        if self._fp8:
            loss, mutated, grads, fwd_amax, g_amax = (
                self._fp8_loss_and_grad(
                    trainable, aux, rng, inputs, labels, scales))
            return loss, mutated, grads, fwd_amax, g_amax, resid
        (loss, mutated), grads = self._loss_and_grad(
            trainable, aux, rng, inputs, labels)
        return loss, mutated, grads, {}, {}, resid

    def _compressed_fwd_bwd(self, trainable, aux, rng, inputs, labels,
                            scales, resid):
        """Error-feedback compressed dp gradient reduction.

        A shard_map over the dp axis makes the per-rank gradient explicit
        (outside shard_map the dp reduction is implicit in XLA's psum of
        the batch-sharded backward): each rank runs loss+grad on its
        local microbatch shard, flattens grads into the configured
        buckets, adds its carried residual, quantizes against a SHARED
        scale (pmax over ranks — so dequantization is exact w.r.t. what
        was sent) and psums the int8/bf16 payload.  The residual
        ``c - dequant(sent)`` carries to the next microbatch (EF-SGD),
        so the quantization error telescopes instead of biasing the
        trajectory.  Each bucket's psum is an independent collective —
        exactly the granularity XLA's latency-hiding scheduler overlaps
        with the remaining backward compute.
        """
        from .._jax_compat import shard_map
        dpx = self.dp_axis
        dp_n = int(self.mesh.shape[dpx])
        mode = self._compress
        buckets = self._buckets
        n_in = len(inputs)

        def local(tr, ax, rngv, res, sc, *batch):
            ins = batch[:n_in]
            labs = batch[n_in:]
            # decorrelate dropout across ranks: outside shard_map the
            # same key spans the global batch, so fold in the rank
            rngl = jax.random.fold_in(rngv, jax.lax.axis_index(dpx))
            if self._fp8:
                loss, mutated, grads, fwd_amax, g_amax = (
                    self._fp8_loss_and_grad(tr, ax, rngl, ins, labs, sc))
            else:
                (loss, mutated), grads = self._loss_and_grad(
                    tr, ax, rngl, ins, labs)
                fwd_amax, g_amax = {}, {}
            pmean = functools.partial(jax.lax.pmean, axis_name=dpx)
            pmax = functools.partial(jax.lax.pmax, axis_name=dpx)
            loss = pmean(loss)
            mutated = jax.tree_util.tree_map(pmean, mutated)
            fwd_amax = jax.tree_util.tree_map(pmax, fwd_amax)
            g_amax = jax.tree_util.tree_map(pmax, g_amax)
            new_res, out_g = {}, {}
            for i, members in enumerate(buckets):
                flat = jnp.concatenate([
                    jnp.ravel(grads[n]).astype(jnp.float32)
                    for n, _, _ in members])
                c = flat + res[f"bucket{i}"][0]
                if mode == "int8":
                    s = pmax(jnp.max(jnp.abs(c))) / 127.0
                    s = jnp.where(s > 0.0, s, jnp.float32(1.0))
                    q = jnp.clip(jnp.round(c / s), -127.0, 127.0)
                    # int8 payload on the wire; the f32 psum of integer
                    # values is exact below 2^24, so dequant-after-reduce
                    # equals the mean of per-rank dequants bitwise
                    sent = q * s
                    red = jax.lax.psum(q, dpx) * s / dp_n
                else:   # bf16: value-snap through bf16, reduce in f32
                    sent = c.astype(jnp.bfloat16).astype(jnp.float32)
                    red = jax.lax.psum(sent, dpx) / dp_n
                new_res[f"bucket{i}"] = (c - sent)[None]
                off = 0
                for n, shape, size in members:
                    out_g[n] = red[off:off + size].reshape(shape).astype(
                        grads[n].dtype)
                    off += size
            return loss, mutated, out_g, fwd_amax, g_amax, new_res

        fn = shard_map(
            local, mesh=self.mesh,
            in_specs=(P(), P(), P(), P(dpx), P()) + tuple(self.batch_specs),
            out_specs=(P(), P(), P(), P(), P(), P(dpx)),
            check_vma=False)
        return fn(trainable, aux, rng, resid, scales, *inputs, *labels)

    def _flat_pad(self, n, v):
        _, size, padded = self._zero[n]
        flat = jnp.ravel(v)
        return jnp.pad(flat, (0, padded - size)) if padded != size else flat

    def _dp_constrain(self, x):
        return jax.lax.with_sharding_constraint(
            x, NamedSharding(self.mesh, P(self.dp_axis)))

    def _ztp_constrain(self, n, x):
        """Pin x to param n's ZeRO x TP optimizer-state layout (the
        param spec with dp inserted) — on gradients this IS the
        reduce-scatter over dp of the tensor-sharded leaf."""
        return jax.lax.with_sharding_constraint(
            x, NamedSharding(self.mesh, self._zero_tp[n]))

    def _param_constrain(self, n, x):
        return jax.lax.with_sharding_constraint(
            x, NamedSharding(self.mesh, self.param_specs.get(n, P())))

    def _build_zero_update(self):
        from .._jax_compat import shard_map
        dpx = self.dp_axis
        fopt = self.fopt
        names = list(self._zero)

        # in_spec P(dp) on the (logically fully-reduced) grads IS the
        # reduce-scatter: GSPMD fuses the backward psum with the dp
        # partition into one collective. Params/state arrive as the local
        # 1/dp chunk, the elementwise update runs on it, and the explicit
        # all_gather re-assembles the full params (check_vma=False as in
        # collectives.allgather: the output is replicated but the static
        # varying-axes check can't infer it).
        @functools.partial(shard_map, mesh=self.mesh,
                           in_specs=(P(dpx), P(dpx), P(dpx), P(), P()),
                           out_specs=(P(), P(dpx)), check_vma=False)
        def _zupd(w_flat, g_flat, zstates, lr, t):
            new_w, new_s = fopt.update(w_flat, g_flat, zstates, lr=lr, t=t)
            gathered = {n: jax.lax.all_gather(new_w[n], dpx, tiled=True)
                        for n in names}
            return gathered, new_s

        self._zero_update = _zupd

    def _apply_updates(self, trainable, grads, states, lr, t,
                       zero_flat_grads=None):
        """Optimizer update dispatch: flat-ZeRO params go through the
        shard_map path, ZeRO x TP params through a sharding-constrained
        elementwise update (reduce-scatter over dp, update the chunk,
        gather back to the tensor-sharded layout), everything else
        through the plain fused update."""
        if not self._zero and not self._zero_tp:
            return self.fopt.update(trainable, grads, states, lr=lr, t=t)
        new_tr, new_st = {}, {}
        rest = {n: v for n, v in trainable.items()
                if n not in self._zero and n not in self._zero_tp}
        if rest:
            p, s = self.fopt.update(
                rest, {n: g for n, g in grads.items() if n in rest},
                {n: states[n] for n in rest}, lr=lr, t=t)
            new_tr.update(p)
            new_st.update(s)
        if self._zero_tp:
            names = list(self._zero_tp)
            tpw = {n: self._ztp_constrain(n, trainable[n]) for n in names}
            tpg = {n: self._ztp_constrain(n, grads[n]) for n in names}
            p, s = self.fopt.update(
                tpw, tpg, {n: states[n] for n in names}, lr=lr, t=t)
            # new weights gather back to the tensor-sharded layout; the
            # state keeps the dp-inserted spec (jit out_shardings pin it)
            new_tr.update({n: self._param_constrain(n, p[n])
                           for n in names})
            new_st.update(s)
        if not self._zero:
            return new_tr, new_st
        if zero_flat_grads is None:
            zero_flat_grads = {n: self._flat_pad(n, grads[n])
                               for n in self._zero}
            if self.zero >= 2:
                # ZeRO-2: pin the flat grads to the dp shards so the full
                # gradient never materializes replicated
                zero_flat_grads = {n: self._dp_constrain(g)
                                   for n, g in zero_flat_grads.items()}
        w_flat = {n: self._flat_pad(n, trainable[n]) for n in self._zero}
        zstates = {n: states[n] for n in self._zero}
        gathered, new_zs = self._zero_update(
            w_flat, zero_flat_grads, zstates, lr, t)
        for n, (shape, size, _) in self._zero.items():
            w = gathered[n][:size].reshape(shape)
            new_tr[n] = w.astype(trainable[n].dtype)
            new_st[n] = new_zs[n]
        return new_tr, new_st

    def __call__(self, *batch):
        """Run one step; returns the (replicated) scalar loss as ndarray."""
        from .. import random as _random
        raws = [b._data if isinstance(b, ndarray) else jnp.asarray(b)
                for b in batch]
        # ensure_sharded skips the re-put when a DevicePrefetcher (see
        # .prefetch) already laid the batch out on the step's shardings —
        # the common case in an overlapped input pipeline
        raws = [_pipeline.ensure_sharded(r, s)
                for r, s in zip(raws, self.batch_shardings)]
        rng = _random._next_key()
        opt = self.fopt.opt
        # advance the update count on host (lr schedules / warmup / bias
        # correction used to be frozen at step 0 in the compiled path); the
        # schedule evaluates here in python and the results ride into the
        # jitted step as traced scalars, so no retrace
        base = opt.num_update
        opt.num_update = base + self.steps_per_call
        if _blackbox._active:
            # keep the flight recorder's step current so a crash bundle
            # is named for (and attributes evidence to) the right step
            _blackbox.set_context(step=int(base) + self.steps_per_call)
        lr_val = opt.lr_scheduler(base + 1) if opt.lr_scheduler else opt.lr
        lr = jnp.asarray(lr_val, jnp.float32)
        t = jnp.asarray(base + 1, jnp.float32)
        if _insight._active and not getattr(self, "_insight_done", False):
            # one-time attribution capture BEFORE dispatch (donation
            # deletes the input buffers): trace-only .lower(), no
            # backend compile and no note_compile, so the recompile
            # detector and compile counters stay untouched
            self._insight_done = True
            label = getattr(self, "_insight_label", "parallel.train_step")
            cap = (self.trainable, self.aux, self.states, self.extra, rng,
                   lr, t, *raws)
            if self._act_rules:
                with activation_sharding(self.mesh, **self._act_rules):
                    _insight.capture_jit(label, self._step, cap,
                                         kind="train")
            else:
                _insight.capture_jit(label, self._step, cap, kind="train")
        if self._act_rules:
            # sp: install the activation rules around the call so the
            # layers' constrain() hooks and the ring-attention routing see
            # them while jit traces (first call) — no-op afterwards
            with activation_sharding(self.mesh, **self._act_rules):
                out = self._step(
                    self.trainable, self.aux, self.states, self.extra,
                    rng, lr, t, *raws)
        else:
            out = self._step(
                self.trainable, self.aux, self.states, self.extra, rng,
                lr, t, *raws)
        self.trainable, self.aux, self.states, self.extra, loss = out
        self._n_step += self.steps_per_call
        if (self._zero or self._zero_tp) and _telemetry.active():
            rs_per_update = self.grad_accum if self.zero >= 2 else 1
            zb = self._zero_bytes + self._zero_tp_bytes
            _telemetry.inc("zero.reduce_scatter_bytes_total",
                           zb * self.steps_per_call * rs_per_update)
            _telemetry.inc("zero.all_gather_bytes_total",
                           zb * self.steps_per_call)
            _telemetry.inc("zero.collective_bytes_total",
                           zb * self.steps_per_call * rs_per_update,
                           op="reduce_scatter")
            _telemetry.inc("zero.collective_bytes_total",
                           zb * self.steps_per_call, op="all_gather")
        if _telemetry.active():
            # analytic per-axis mesh traffic (logical estimates, same
            # spirit as the zero.* counters) for the bench mesh rows
            shape = dict(self.mesh.shape)
            if shape.get(self.dp_axis, 1) > 1:
                _telemetry.inc("mesh.dp_gradient_bytes_total",
                               self._trainable_bytes * self.steps_per_call)
                wire = self._dp_wire_bytes * self.steps_per_call
                _telemetry.inc("mesh.collective_bytes_total", wire,
                               axis="dp")
                if self._compress != "none":
                    _telemetry.inc("comm.compressed_bytes_total", wire)
                    _telemetry.inc(
                        "comm.uncompressed_bytes_total",
                        self._trainable_bytes * self.grad_accum
                        * self.steps_per_call)
            tokens = int(raws[0].size) if raws else 0
            if self._tp_row_out_units and tokens:
                act = sum(L * u for L, u in self._tp_row_out_units)
                _telemetry.inc("mesh.tp_allreduce_bytes_total",
                               tokens * act * 4)
                _telemetry.inc("mesh.collective_bytes_total",
                               tokens * act * 4, axis="tp")
            pp_n = shape.get("pp", 1)
            if pp_n > 1 and self._pp_width and tokens:
                pp_bytes = (tokens * self._pp_width * 4
                            * (pp_n - 1) * 2)
                _telemetry.inc("mesh.pp_stage_transfer_bytes_total",
                               pp_bytes)
                _telemetry.inc("mesh.collective_bytes_total", pp_bytes,
                               axis="pp")
        if _insight._active:
            # steady-state loop time from call inter-arrival: measured
            # on wall clocks the caller already pays, no device sync
            _insight.note_step(
                getattr(self, "_insight_label", "parallel.train_step"))
        return _wrap(loss)

    def prefetch(self, batches, depth=None, stall_timeout=None):
        """Wrap a batch iterable in a DevicePrefetcher targeting this
        step's batch shardings: jax.device_put runs on a background
        thread while the previous step computes, and __call__'s
        ensure_sharded detects the layout match and skips the re-put.

            for batch in step.prefetch(loader):
                loss = step(*batch)
        """
        return _pipeline.DevicePrefetcher(
            iter(batches), shardings=self.batch_shardings, depth=depth,
            stall_timeout=stall_timeout)

    def autotune(self, batches=None, sample_batch=None, space=None, **kw):
        """Search the step-config grid around THIS step's model, loss,
        optimizer and mesh (mx.autotune.search) and return
        ``(tuned_step, result)``.

        ``batches`` lends ONE sample batch (shaped like ``__call__``'s
        per-update batch, no lead axes) and is released via
        ``pipeline.take``; pass ``sample_batch=`` to skip the loader.
        Current weights sync to the block first so trials — and the
        returned tuned step — start from this step's training state.  The
        tuned step reuses the caller's optimizer (schedule position
        included); trials only ever run on hermetic clones.  Keyword args
        flow to ``mx.autotune.search`` (space=, hbm_budget=, force=, ...).
        """
        from .. import autotune as _autotune
        if sample_batch is None:
            if batches is None:
                raise MXNetError(
                    "autotune needs `batches` (a loader to borrow one "
                    "batch from) or an explicit `sample_batch`")
            sample_batch = next(iter(_pipeline.take(batches, 1)), None)
            if sample_batch is None:
                raise MXNetError("autotune: batches yielded nothing")
        sample = tuple(onp.asarray(b._data) if isinstance(b, ndarray)
                       else onp.asarray(b) for b in sample_batch)
        self.sync_to_block()
        result = _autotune.search(
            self.block, self.loss_fn, self.fopt.opt, self.mesh,
            self.batch_specs, sample, n_labels=self.n_labels,
            param_specs=self.param_specs, dp_axis=self.dp_axis,
            space=space, **kw)
        cfg = result.config
        if cfg is None:  # every trial failed: keep the caller's config
            return self, result
        mesh = self.mesh_config or self.mesh
        batch_specs, param_specs, dp_axis = (
            self.batch_specs, self.param_specs, self.dp_axis)
        if cfg.get("mesh"):
            # a mesh-axis search won on a different layout: rebuild the
            # step around the winning MeshConfig (specs re-derive)
            mesh = MeshConfig(**cfg["mesh"])
            batch_specs = mesh.batch_specs(
                *[len(s) if s is not None else 2 for s in self.batch_specs])
            param_specs = None
            dp_axis = "dp"
        precision = cfg.get("precision", "fp32")
        tuned = ShardedTrainStep(
            self.block, self.loss_fn, self.fopt.opt, mesh,
            batch_specs, n_labels=self.n_labels,
            param_specs=param_specs,
            steps_per_call=cfg["steps_per_call"], zero=cfg["zero"],
            grad_accum=cfg["grad_accum"], remat=cfg["remat"],
            dp_axis=dp_axis,
            precision=precision if precision in ("fp32", "fp8")
            else self.precision,
            grad_compress=self._compress)
        tuned._n_step = self._n_step
        return tuned, result

    def rebuild(self, mesh=None, sync=True):
        """Re-construct this step around a :class:`MeshConfig` (same
        block / loss / optimizer / zero / grad_accum / remat) — the
        fleet supervisor's degrade/re-expand primitive.  Batch and param
        specs re-derive from the new layout, so the result accepts the
        same per-update batches at a different dp size.

        ``mesh=None`` rebuilds on this step's own mesh — a re-jit in
        place, which is how the autotune Retuner makes freshly published
        kernel block shapes take effect at a checkpoint boundary without
        changing the layout.

        ``sync=True`` writes the current sharded weights back into the
        block first, so the rebuilt step starts from this step's live
        training state; the fleet path passes ``sync=False`` because a
        bitwise bundle restore immediately follows and the dying layout's
        device buffers may no longer be gatherable.
        """
        if mesh is None:
            mesh = self.mesh_config
            if mesh is None:
                raise MXNetError(
                    "rebuild() without a mesh needs a step built from a "
                    "MeshConfig (this one was built from a raw mesh)")
        if not isinstance(mesh, MeshConfig):
            raise MXNetError(
                f"rebuild needs a MeshConfig, got {type(mesh).__name__}")
        if sync:
            self.sync_to_block()
        else:
            # the block may still hold buffers the old step donated away;
            # revive them as zeros of the right shape — the bundle restore
            # that follows supplies the real values
            for p in self.block.collect_params().values():
                if p._data is None:
                    continue
                raw = p._data._data
                if getattr(raw, "is_deleted", lambda: False)():
                    p._data._rebind(jnp.zeros(raw.shape, raw.dtype))
        batch_specs = mesh.batch_specs(
            *[len(s) if s is not None else 2 for s in self.batch_specs])
        rebuilt = ShardedTrainStep(
            self.block, self.loss_fn, self.fopt.opt, mesh,
            batch_specs, n_labels=self.n_labels, param_specs=None,
            donate=self._donate, steps_per_call=self.steps_per_call,
            zero=self.zero, grad_accum=self.grad_accum,
            remat=self._remat_arg, dp_axis="dp",
            precision=self.precision, grad_compress=self._compress)
        rebuilt._n_step = self._n_step
        return rebuilt

    def sync_to_block(self):
        """Write current sharded weights back into the Block's Parameters
        (for save_parameters / eager eval after training)."""
        params = self.block.collect_params()
        for n, v in self._expand_pp({**self.trainable, **self.aux}).items():
            params[n]._data._rebind(v)

    # -- checkpoint / resume ------------------------------------------------
    def state_dict(self):
        """Gather weights + optimizer state to host numpy in a CANONICAL
        topology-independent layout: dp-partitioned (zero>0) state leaves
        are all-gathered, un-padded and reshaped back to their weight's
        shape, tp/sp shards gather to the full weight, and pp-stacked
        layer families unstack back to their per-layer names — a bundle
        saved at one (dp, tp, pp) layout restores bitwise at any other."""
        arrays = {}
        for n, v in self._expand_pp(dict(self.trainable)).items():
            arrays[f"trainable/{n}"] = onp.asarray(v)
        for n, v in self._expand_pp(dict(self.aux)).items():
            arrays[f"aux/{n}"] = onp.asarray(v)
        for n, s in self.states.items():
            zinfo = self._zero.get(n)
            grp = self._pp_groups.get(n)
            for i, leaf in enumerate(jax.tree_util.tree_leaves(s)):
                a = onp.asarray(leaf)
                if zinfo is not None:
                    shape, size, _ = zinfo
                    a = a[:size].reshape(shape)
                if grp is not None:
                    for j, member in enumerate(grp["members"]):
                        arrays[f"state/{member}/{i}"] = a[j]
                else:
                    arrays[f"state/{n}/{i}"] = a
        for site, hist in self.extra["fp8"].items():
            for k, v in hist.items():
                arrays[f"fp8/{site}/{k}"] = onp.asarray(v)
        for bname, v in self.extra["resid"].items():
            # canonical EF residual = the SUM over dp ranks: what the sum
            # of rank-local errors still owes the trajectory.  Restoring
            # it into one rank (load_state_dict) preserves the total
            # exactly at any dp size — f32 x + 0.0 is bitwise x.
            a = onp.asarray(v)
            arrays[f"efresid/{bname}"] = a.sum(axis=0, dtype=a.dtype)
        return {"arrays": arrays, "n_step": int(self._n_step)}

    def load_state_dict(self, bundle):
        """Restore from ``state_dict()``: values re-shard per THIS step's
        param_specs / zero / pipeline layout (which may differ from the
        saving run's — resume on a different (dp, tp, pp) re-stacks,
        re-pads and re-partitions the canonical arrays here)."""
        arrays = bundle["arrays"]

        def sh(n):
            return NamedSharding(self.mesh, self.param_specs.get(n, P()))

        def gather(prefix, n):
            # pp-stacked names re-stack from their canonical per-layer
            # entries; everything else reads directly
            grp = self._pp_groups.get(n)
            if grp is not None:
                return onp.stack([arrays[f"{prefix}/{m}"]
                                  for m in grp["members"]])
            return arrays[f"{prefix}/{n}"]

        for n in self.trainable:
            self.trainable[n] = jax.device_put(gather("trainable", n), sh(n))
        for n in self.aux:
            self.aux[n] = jax.device_put(gather("aux", n), sh(n))
        for n, s in self.states.items():
            leaves, treedef = jax.tree_util.tree_flatten(s)
            zinfo = self._zero.get(n)
            grp = self._pp_groups.get(n)
            tspec = self._zero_tp.get(n)
            new = []
            for i in range(len(leaves)):
                if grp is not None:
                    a = onp.stack([arrays[f"state/{m}/{i}"]
                                   for m in grp["members"]])
                else:
                    a = arrays[f"state/{n}/{i}"]
                if zinfo is not None:
                    _, size, padded = zinfo
                    flat = onp.ravel(a)
                    if padded != size:
                        flat = onp.pad(flat, (0, padded - size))
                    new.append(jax.device_put(
                        flat, NamedSharding(self.mesh, P(self.dp_axis))))
                elif tspec is not None:
                    new.append(jax.device_put(
                        a, NamedSharding(self.mesh, tspec)))
                else:
                    new.append(jax.device_put(a, sh(n)))
            self.states[n] = jax.tree_util.tree_unflatten(treedef, new)
        # fp8 amax histories: replicated scalars, read back directly.
        # Tolerate missing keys (resuming a pre-fp8 bundle into an fp8
        # step keeps the fresh zero history) and a changed history length
        # (clip newest-first / zero-pad oldest).
        fp8_new = {}
        for site, hist in self.extra["fp8"].items():
            fp8_new[site] = {}
            for k, v in hist.items():
                key = f"fp8/{site}/{k}"
                if key not in arrays:
                    fp8_new[site][k] = v
                    continue
                a = onp.asarray(arrays[key]).astype(onp.float32)
                h = int(v.shape[0])
                if a.shape[0] >= h:
                    a = a[:h]
                else:
                    a = onp.pad(a, (0, h - a.shape[0]))
                fp8_new[site][k] = jax.device_put(
                    a, NamedSharding(self.mesh, P()))
        resid_new = {}
        for bname, v in self.extra["resid"].items():
            key = f"efresid/{bname}"
            if key not in arrays:
                resid_new[bname] = v
                continue
            # canonical sum restores into rank 0; other ranks start with
            # zero error debt (bucket layout depends only on param names
            # and comm.bucket_mb, so it is dp-size invariant)
            a = onp.zeros(v.shape, onp.float32)
            a[0] = onp.asarray(arrays[key])
            resid_new[bname] = jax.device_put(
                a, NamedSharding(self.mesh, P(self.dp_axis)))
        self.extra = {"fp8": fp8_new, "resid": resid_new}
        self._n_step = int(bundle["n_step"])
        # keep lr schedules / bias correction on the restored timeline
        self.fopt.opt.num_update = self._n_step

    def save_states(self, fname):
        """Checkpoint weights + optimizer state + step count to one
        safetensors file (reference: Trainer.save_states, trainer.py:482;
        sharded arrays are gathered to host in canonical layout — the
        resume side re-shards them, even at a different dp size).
        safetensors rather than npz so bfloat16 params/state round-trip
        exactly."""
        from .. import serialization
        bundle = self.state_dict()
        return serialization.save_safetensors(
            fname, bundle["arrays"],
            metadata={"n_step": bundle["n_step"], "zero": self.zero,
                      "precision": self.precision,
                      "grad_compress": self._compress})

    def load_states(self, fname):
        """Resume from save_states: values re-sharded per param_specs
        (reference: Trainer.load_states, trainer.py:511)."""
        from .. import serialization
        loaded, meta = serialization.load_safetensors(
            fname, return_metadata=True)
        if str(meta.get("precision", "")) == "fp8":
            # tag survives cold loads so serve engines can apply their
            # quantization interaction guard (serve/engine.py)
            self.block._fp8_trained = True
        self.load_state_dict(
            {"arrays": loaded, "n_step": int(meta.get("n_step", 0))})

"""Sharded training step — the whole Trainer.step path as one XLA program.

Reference parity: python/mxnet/gluon/trainer.py:334 (step = backward grads →
kvstore pushpull allreduce → optimizer update, overlapped by the dependency
engine) and the KVStore reduce machinery (src/kvstore/comm.h). TPU-native:
forward + backward + gradient allreduce + optimizer update compile into ONE
jit program over a jax.sharding.Mesh — XLA inserts the collectives from the
shardings (data-parallel psum over 'dp', Megatron tensor-parallel
allreduces over 'tp', sequence sharding over 'sp') and its latency-hiding
scheduler overlaps comm with compute, which is the engine's compute/comm
overlap re-created at compile time.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .. import functional
from .. import pipeline as _pipeline
from ..numpy.multiarray import ndarray, _wrap

# name-pattern Megatron rules for the transformer family
# (column-parallel: shard Dense units; row-parallel: shard in_units, psum)
_COLUMN_SUFFIXES = ("query_proj.weight", "key_proj.weight",
                    "value_proj.weight", "ffn_1.weight")
_ROW_SUFFIXES = ("out_proj.weight", "ffn_2.weight")
_COLUMN_BIAS = ("query_proj.bias", "key_proj.bias", "value_proj.bias",
                "ffn_1.bias")


def megatron_specs(param_shapes, tp_axis="tp"):
    """PartitionSpecs for transformer params by structural-name pattern."""
    specs = {}
    for name, shape in param_shapes.items():
        if any(name.endswith(s) for s in _COLUMN_SUFFIXES) and len(shape) == 2:
            specs[name] = P(tp_axis, None)
        elif any(name.endswith(s) for s in _ROW_SUFFIXES) and len(shape) == 2:
            specs[name] = P(None, tp_axis)
        elif any(name.endswith(s) for s in _COLUMN_BIAS):
            specs[name] = P(tp_axis)
        else:
            specs[name] = P()
    return specs


class FunctionalOptimizer:
    """Pure-functional adapter over a mxnet_tpu Optimizer instance so its
    update rule can run inside a jit/pjit trace (the analog of the fused
    multi-tensor update ops, src/operator/optimizer_op.cc:352)."""

    def __init__(self, optimizer):
        self.opt = optimizer

    def init(self, raw_params):
        states = {}
        for name in raw_params:
            # states/settings key by STRUCTURAL NAME, not position: dict
            # ordering through a jit boundary is canonicalized, so a
            # positional index could bind lr_mult/wd_mult to the wrong
            # parameter vs the eager Trainer (collect_params order)
            s = self.opt.create_state(name, _wrap(raw_params[name]))
            states[name] = jax.tree_util.tree_map(
                lambda x: x._data if isinstance(x, ndarray) else x, s,
                is_leaf=lambda x: isinstance(x, ndarray))
        return states

    def update(self, raw_params, raw_grads, states, lr=None):
        new_p, new_s = {}, {}
        for name in raw_params:
            if name not in raw_grads:
                new_p[name] = raw_params[name]
                new_s[name] = states[name]
                continue
            wd = self.opt._get_wd(name)
            lr_i = lr if lr is not None else self.opt._get_lr(name)
            wrapped = jax.tree_util.tree_map(
                _wrap, states[name],
                is_leaf=lambda x: x is None)
            w, s = self.opt._update_impl(
                raw_params[name], raw_grads[name], wrapped, lr_i, wd)
            new_p[name] = w.astype(raw_params[name].dtype)
            new_s[name] = jax.tree_util.tree_map(
                lambda x: x._data if isinstance(x, ndarray) else x, s,
                is_leaf=lambda x: isinstance(x, ndarray))
        return new_p, new_s


def scan_steps(step_fn, n_state):
    """Fuse K training steps into one compiled program with ``lax.scan``.

    ``step_fn(*state, *batch) -> (*state', metric)`` becomes
    ``loop(*state, *stacked) -> (*state', metric_mean)`` where each array
    in ``stacked`` carries a leading steps axis.  One executable launch
    then performs K steps — amortizing per-launch dispatch latency, the
    step-level analog of the reference engine's op bulking
    (src/engine/threaded_engine.h:433; there ops are batched into one
    engine op, here whole steps into one XLA program).
    """
    from jax import lax

    def loop(*args):
        state, batches = args[:n_state], args[n_state:]

        def body(carry, xs):
            out = step_fn(*carry, *xs)
            return tuple(out[:n_state]), out[-1]

        state, metrics = lax.scan(body, tuple(state), tuple(batches))
        return (*state, jnp.mean(metrics))

    return loop


class ShardedTrainStep:
    """Compiled data/tensor/sequence-parallel training step for a Block.

    block: initialized (Hybrid)Block.
    loss_fn(outputs, *labels) -> scalar (raw jax values).
    optimizer: mxnet_tpu Optimizer instance (or name via opt.create).
    mesh: jax.sharding.Mesh; dp_axis must exist; tp/sp optional.
    batch_specs: PartitionSpec per batch arg (inputs then labels),
        e.g. (P('dp', 'sp'), P('dp',)).
    param_specs: dict name -> PartitionSpec; defaults to megatron_specs
        when the mesh has a tp axis else fully replicated.
    """

    def __init__(self, block, loss_fn, optimizer, mesh, batch_specs,
                 n_labels=1, param_specs=None, donate=True,
                 steps_per_call=1):
        from ..optimizer import optimizer as opt_mod
        if isinstance(optimizer, str):
            optimizer = opt_mod.create(optimizer)
        self.block = block
        self.loss_fn = loss_fn
        self.mesh = mesh
        self.n_labels = n_labels
        trainable, aux = functional.split_params(block)
        shapes = {n: v.shape for n, v in trainable.items()}
        shapes.update({n: v.shape for n, v in aux.items()})
        if param_specs is None:
            if "tp" in mesh.shape:
                param_specs = megatron_specs(shapes)
            else:
                param_specs = {n: P() for n in shapes}
        self.param_specs = param_specs
        self.fopt = FunctionalOptimizer(optimizer)

        def sh(spec):
            return NamedSharding(mesh, spec)

        self.trainable = {
            n: jax.device_put(v, sh(param_specs.get(n, P())))
            for n, v in trainable.items()}
        self.aux = {
            n: jax.device_put(v, sh(param_specs.get(n, P())))
            for n, v in aux.items()}
        states = self.fopt.init(self.trainable)
        # optimizer state shards like its weight
        self.states = jax.tree_util.tree_map(
            lambda x: x, states)
        self.states = {
            n: jax.tree_util.tree_map(
                lambda x: jax.device_put(x, sh(param_specs.get(n, P())))
                if x is not None else None, s, is_leaf=lambda x: x is None)
            for n, s in states.items()}
        self.batch_shardings = tuple(sh(s) for s in batch_specs)

        param_sh = {n: sh(param_specs.get(n, P())) for n in trainable}
        aux_sh = {n: sh(param_specs.get(n, P())) for n in aux}
        state_sh = {
            n: jax.tree_util.tree_map(
                lambda x: sh(param_specs.get(n, P())), self.states[n],
                is_leaf=lambda x: x is None)
            for n in self.states}
        # None states have no sharding
        state_sh = {
            n: jax.tree_util.tree_map(
                lambda x, s: None if x is None else s,
                self.states[n], state_sh[n], is_leaf=lambda x: x is None)
            for n in self.states}

        def step(trainable, aux, states, rng, lr, *batch):
            inputs = batch[:len(batch) - self.n_labels]
            labels = batch[len(batch) - self.n_labels:]

            def lossf(tr):
                out, mutated = functional.functional_call(
                    self.block, {**tr, **aux}, *inputs, train=True,
                    rng_key=rng)
                return self.loss_fn(out, *labels), mutated

            (loss, mutated), grads = jax.value_and_grad(
                lossf, has_aux=True)(trainable)
            new_tr, new_states = self.fopt.update(trainable, grads, states,
                                                  lr=lr)
            return new_tr, {**aux, **mutated}, new_states, loss

        self.steps_per_call = int(steps_per_call)
        if self.steps_per_call > 1:
            from jax import lax
            inner = step

            def step(trainable, aux, states, rng, lr, *batches):
                # batches carry a leading steps axis; one launch = K steps
                def body(carry, xs):
                    tr, ax, st, i = carry
                    rngi = jax.random.fold_in(rng, i)
                    tr, ax, st, loss = inner(tr, ax, st, rngi, lr, *xs)
                    return (tr, ax, st, i + 1), loss
                (trainable, aux, states, _), losses = lax.scan(
                    body, (trainable, aux, states, 0), tuple(batches))
                return trainable, aux, states, jnp.mean(losses)

            self.batch_shardings = tuple(
                sh(P(None, *s)) for s in batch_specs)

        donate_argnums = (0, 1, 2) if donate else ()
        self._step = jax.jit(
            step,
            in_shardings=(param_sh, aux_sh, state_sh, sh(P()), sh(P()))
            + self.batch_shardings,
            out_shardings=(param_sh, aux_sh, state_sh, sh(P())),
            donate_argnums=donate_argnums)
        self._n_step = 0

    def __call__(self, *batch):
        """Run one step; returns the (replicated) scalar loss as ndarray."""
        from .. import random as _random
        raws = [b._data if isinstance(b, ndarray) else jnp.asarray(b)
                for b in batch]
        # ensure_sharded skips the re-put when a DevicePrefetcher (see
        # .prefetch) already laid the batch out on the step's shardings —
        # the common case in an overlapped input pipeline
        raws = [_pipeline.ensure_sharded(r, s)
                for r, s in zip(raws, self.batch_shardings)]
        rng = _random._next_key()
        lr = jnp.asarray(self.fopt.opt.learning_rate, jnp.float32)
        self.trainable, self.aux, self.states, loss = self._step(
            self.trainable, self.aux, self.states, rng, lr, *raws)
        self._n_step += self.steps_per_call
        return _wrap(loss)

    def prefetch(self, batches, depth=None, stall_timeout=None):
        """Wrap a batch iterable in a DevicePrefetcher targeting this
        step's batch shardings: jax.device_put runs on a background
        thread while the previous step computes, and __call__'s
        ensure_sharded detects the layout match and skips the re-put.

            for batch in step.prefetch(loader):
                loss = step(*batch)
        """
        return _pipeline.DevicePrefetcher(
            iter(batches), shardings=self.batch_shardings, depth=depth,
            stall_timeout=stall_timeout)

    def sync_to_block(self):
        """Write current sharded weights back into the Block's Parameters
        (for save_parameters / eager eval after training)."""
        params = self.block.collect_params()
        for n, v in {**self.trainable, **self.aux}.items():
            params[n]._data._rebind(v)

    # -- checkpoint / resume ------------------------------------------------
    def save_states(self, fname):
        """Checkpoint weights + optimizer state + step count to one
        safetensors file (reference: Trainer.save_states, trainer.py:482;
        sharded arrays are gathered to host — the resume side re-shards
        them).  safetensors rather than npz so bfloat16 params/state
        round-trip exactly."""
        import numpy as onp
        from .. import serialization
        arrays = {}
        for n, v in self.trainable.items():
            arrays[f"trainable/{n}"] = onp.asarray(v)
        for n, v in self.aux.items():
            arrays[f"aux/{n}"] = onp.asarray(v)
        for n, s in self.states.items():
            for i, leaf in enumerate(jax.tree_util.tree_leaves(s)):
                arrays[f"state/{n}/{i}"] = onp.asarray(leaf)
        return serialization.save_safetensors(
            fname, arrays, metadata={"n_step": self._n_step})

    def load_states(self, fname):
        """Resume from save_states: values re-sharded per param_specs
        (reference: Trainer.load_states, trainer.py:511)."""
        from .. import serialization
        loaded, meta = serialization.load_safetensors(
            fname, return_metadata=True)
        self._n_step = int(meta.get("n_step", 0))

        def sh(n):
            return NamedSharding(self.mesh, self.param_specs.get(n, P()))

        for n in self.trainable:
            self.trainable[n] = jax.device_put(
                loaded[f"trainable/{n}"], sh(n))
        for n in self.aux:
            self.aux[n] = jax.device_put(loaded[f"aux/{n}"], sh(n))
        for n, s in self.states.items():
            leaves, treedef = jax.tree_util.tree_flatten(s)
            new = [jax.device_put(loaded[f"state/{n}/{i}"], sh(n))
                   for i in range(len(leaves))]
            self.states[n] = jax.tree_util.tree_unflatten(treedef, new)

"""XLA collectives layer.

Reference parity: src/kvstore/comm.h (CommCPU/CommDevice reduce+broadcast),
comm_tree.h (topology-aware tree allreduce), kvstore_nccl.h, and ps-lite's
cross-host path — all collapsed into XLA AllReduce/AllGather/ReduceScatter/
CollectivePermute over mesh axes: ICI within a slice, DCN across slices.
Topology solving (gpu_topology.h) is the ICI fabric's job; nothing to port.

These free functions are the standalone/kvstore entry points.  The ZeRO
update in ``train.ShardedTrainStep`` uses the same shard_map idioms but
keeps its reduce-scatter/all-gather INSIDE the jitted step (an in_spec
``P(dp)`` on logically-reduced grads is the reduce-scatter under GSPMD;
``jax.lax.all_gather(..., tiled=True)`` with ``check_vma=False``
re-assembles params, exactly as :func:`allgather` below) so XLA can
overlap them with compute; traffic is counted by the
``zero.reduce_scatter_bytes_total`` / ``zero.all_gather_bytes_total``
telemetry counters.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .._jax_compat import shard_map


def allreduce(x, mesh, axis="dp", op="sum"):
    """AllReduce x (replicated per-device values as a leading-axis stack or a
    sharded array) over a mesh axis via psum inside shard_map."""
    reducer = {"sum": jax.lax.psum, "max": jax.lax.pmax,
               "min": jax.lax.pmin, "mean": jax.lax.pmean}[op]

    @functools.partial(shard_map, mesh=mesh, in_specs=P(axis),
                       out_specs=P(axis))
    def _ar(v):
        return reducer(v, axis)
    return _ar(x)


def allgather(x, mesh, axis="dp", tiled=True):
    # check_vma=False: all_gather output IS replicated over `axis`, but the
    # static varying-mesh-axes check can't infer that
    @functools.partial(shard_map, mesh=mesh, in_specs=P(axis), out_specs=P(),
                       check_vma=False)
    def _ag(v):
        return jax.lax.all_gather(v, axis, tiled=tiled)
    return _ag(x)


def reduce_scatter(x, mesh, axis="dp"):
    @functools.partial(shard_map, mesh=mesh, in_specs=P(), out_specs=P(axis))
    def _rs(v):
        return jax.lax.psum_scatter(v, axis, tiled=True)
    return _rs(x)


def compressed_allreduce(x, mesh, axis="dp", mode="int8", residual=None):
    """Error-feedback compressed mean-allreduce of per-rank values.

    ``x`` is a per-rank stack (leading dim = mesh axis size, as in
    :func:`allreduce`): each rank's contribution plus its carried
    ``residual`` quantizes against a SHARED scale (pmax of the absmax
    over ranks, so dequantization after the reduce is exact w.r.t. what
    was sent) and psums at the wire width — int8 payload (4x narrower
    than fp32) or bf16 (2x).  Returns ``(mean, new_residual)`` where
    ``new_residual`` (same per-rank stack layout) carries the
    quantization error into the next call — EF-SGD: the error
    telescopes across steps instead of biasing the trajectory.

    The standalone/kvstore entry point for the same arithmetic
    ``ShardedTrainStep(grad_compress=...)`` fuses into its jitted step
    (train.py ``_compressed_fwd_bwd``), where per-bucket psums overlap
    with backward compute.
    """
    if mode not in ("int8", "bf16"):
        raise ValueError(f"mode must be 'int8' or 'bf16', got {mode!r}")
    n = int(mesh.shape[axis])
    if residual is None:
        residual = jnp.zeros(x.shape, jnp.float32)

    @functools.partial(shard_map, mesh=mesh, in_specs=(P(axis), P(axis)),
                       out_specs=(P(), P(axis)), check_vma=False)
    def _car(v, res):
        c = v[0].astype(jnp.float32) + res[0]
        if mode == "int8":
            s = jax.lax.pmax(jnp.max(jnp.abs(c)), axis) / 127.0
            s = jnp.where(s > 0.0, s, jnp.float32(1.0))
            q = jnp.clip(jnp.round(c / s), -127.0, 127.0)
            sent = q * s
            red = jax.lax.psum(q, axis) * s / n
        else:
            sent = c.astype(jnp.bfloat16).astype(jnp.float32)
            red = jax.lax.psum(sent, axis) / n
        return red, (c - sent)[None]

    return _car(x, residual)


def ppermute(x, mesh, axis, perm):
    @functools.partial(shard_map, mesh=mesh, in_specs=P(axis),
                       out_specs=P(axis))
    def _pp(v):
        return jax.lax.ppermute(v, axis, perm)
    return _pp(x)


def allreduce_across_processes(x):
    """Cross-host sum of per-process values (the DCN path of KVStoreDist;
    jax.distributed replaces the ps-lite scheduler rendezvous).

    Each process contributes its local x; result is the sum over processes,
    replicated. Implementation: every local device holds x / local_device_count
    as one shard of a global (n_devices, *shape) array sharded over a 1-d
    global mesh; a shard_map psum over that axis rides DCN between hosts and
    ICI within a host.
    """
    import numpy as onp
    devs = jax.devices()
    n = len(devs)
    if n == 1 and jax.process_count() == 1:
        return x
    mesh = Mesh(onp.array(devs), ("dcn",))
    local = jax.local_devices()
    contrib = (x / len(local))[None]
    shards = [jax.device_put(contrib, d) for d in local]
    global_arr = jax.make_array_from_single_device_arrays(
        (n,) + tuple(x.shape), NamedSharding(mesh, P("dcn")), shards)

    @functools.partial(shard_map, mesh=mesh, in_specs=P("dcn"), out_specs=P())
    def _ar(v):
        return jax.lax.psum(v, "dcn")

    out = _ar(global_arr)
    # the psum result is replicated across ALL processes' devices; callers
    # feed it back into single-process eager ops, so hand back this
    # process's own copy (fully addressable) rather than the global array
    return out.addressable_data(0)[0]

"""Data-parallel weak-scaling harness (KVStore scaling-efficiency artifact).

BASELINE.md north star #3 is KVStore data-parallel scaling efficiency over
1->32 chips; the reference measures it with tools/bandwidth/measure.py over
kvstore push/pull. Here the measured object is the framework's actual DP
path — ShardedTrainStep (mesh-psum gradient reduction, the KVStore('device')
substrate) — run at n = 1, 2, 4, ... devices with FIXED per-device batch
(weak scaling: ideal = constant step time, efficiency_n = t_1 / t_n).

The same harness serves both regimes:
- virtual CPU mesh (CI / dryrun): meshes are built over sublists of the
  existing devices — honest wall-clock, but all virtual devices share host
  cores, so efficiency UNDERESTIMATES real-chip scaling (collectives are
  simulated serially). The numbers bound overhead, not ICI throughput.
- real hardware: pass ``devices=jax.devices()`` (or any sublist); meshes
  ride the actual ICI and the efficiencies are the headline metric.
"""
from __future__ import annotations

import time


def weak_scaling_table(ns=None, devices=None, per_device_batch=4,
                       image=24, classes=10, iters=8, warmup=3):
    """Run the DP ShardedTrainStep at each n in ``ns``; return a list of
    rows {n, ms_per_step, images_per_s, efficiency}.

    devices: device list to slice (default jax.devices()). ns defaults to
    powers of two up to len(devices).
    """
    import jax
    import jax.numpy as jnp
    import numpy as onp
    from jax.sharding import Mesh, PartitionSpec as P

    import mxnet_tpu as mx
    from mxnet_tpu.gluon.model_zoo.vision import get_resnet
    from mxnet_tpu.parallel.train import ShardedTrainStep

    devices = list(devices) if devices is not None else jax.devices()
    if ns is None:
        ns = []
        n = 1
        while n <= len(devices):
            ns.append(n)
            n *= 2

    def ce_loss(logits, y):
        from ..ops.xent import sparse_softmax_xent
        return jnp.mean(sparse_softmax_xent(logits, y))

    rows = []
    t1 = None
    for n in ns:
        mesh = Mesh(onp.array(devices[:n]).reshape(n), ("dp",))
        net = get_resnet(1, 18, classes=classes)
        net.initialize()
        net(mx.np.zeros((2, 3, image, image), dtype="float32"))
        step = ShardedTrainStep(
            net, ce_loss,
            mx.optimizer.create("sgd", learning_rate=0.05, momentum=0.9),
            mesh, batch_specs=(P("dp"), P("dp")), n_labels=1)
        bs = per_device_batch * n
        x = onp.random.RandomState(0).uniform(
            size=(bs, 3, image, image)).astype("float32")
        y = onp.zeros((bs,), "int32")
        for _ in range(max(warmup, 1)):   # >=1: excludes compile from timing
            loss = step(x, y)
        loss.wait_to_read()
        t0 = time.perf_counter()
        for _ in range(iters):
            loss = step(x, y)
        loss.wait_to_read()
        dt = (time.perf_counter() - t0) / iters
        if t1 is None:
            t1 = dt
        rows.append({
            "n": n,
            "global_batch": bs,
            "ms_per_step": round(dt * 1e3, 2),
            "images_per_s": round(bs / dt, 1),
            "efficiency": round(t1 / dt, 3),
            # isolated collective cost at this n: a bare jitted psum of a
            # gradient-sized vector over the same mesh. On the virtual
            # mesh this is the number a reader can extrapolate from —
            # step-time growth beyond (compute_n1 + collective) is host
            # core contention, not communication.
            "collective_ms": round(_time_allreduce(mesh, net) * 1e3, 3),
        })
    if rows:
        rows[0]["decomposition"] = (
            "ms_per_step(n=1) is pure compute; collective_ms isolates the "
            "gradient-allreduce at each n; the remainder of the step-time "
            "growth on a virtual mesh is host-core contention")
    return rows


def _time_allreduce(mesh, net, iters=10):
    """Time one jitted gradient-sized psum over the mesh's 'dp' axis."""
    import functools
    import time as _t

    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P
    from jax.experimental.shard_map import shard_map

    n = mesh.devices.size
    nparams = sum(int(onp_prod(p.shape)) for p in
                  net.collect_params().values() if p._data is not None)

    @jax.jit
    @functools.partial(shard_map, mesh=mesh, in_specs=P("dp"),
                       out_specs=P("dp"))
    def ar(v):
        return jax.lax.psum(v, "dp")

    v = jnp.ones((n, max(nparams // max(n, 1), 1)), jnp.float32)
    v = jax.device_put(v, NamedSharding(mesh, P("dp")))
    ar(v).block_until_ready()  # compile
    t0 = _t.perf_counter()
    for _ in range(iters):
        out = ar(v)
    out.block_until_ready()
    return (_t.perf_counter() - t0) / iters


def onp_prod(shape):
    out = 1
    for s in shape:
        out *= int(s)
    return out


def multiprocess_overhead_table(ns=(2, 4), timeout=420):
    """Launch n real processes (tools/launch.py, one core-set each) and
    measure the DCN-path collective in isolation: per-rank jitted matmul
    compute vs allreduce_across_processes latency at two payload sizes.

    Separates process-collective overhead from the shared-core contention
    that dominates the virtual in-process mesh (reference anchor:
    tests/nightly/dist_sync_kvstore.py launch taxonomy). Rows come from
    rank 0 of each run; failures degrade to an {'n', 'error'} row.
    """
    import json
    import os
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    script = os.path.join(repo, "benchmark", "scaling_proc.py")
    rows = []
    for n in ns:
        env = dict(os.environ)
        env.pop("JAX_PLATFORMS", None)
        env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
        try:
            r = subprocess.run(
                [sys.executable, os.path.join(repo, "tools", "launch.py"),
                 "-n", str(n), sys.executable, script],
                capture_output=True, text=True, timeout=timeout, env=env,
                cwd=repo)
        except subprocess.TimeoutExpired:
            rows.append({"n": n, "error": f"timeout {timeout}s"})
            continue
        row = None
        for line in r.stdout.splitlines():
            if line.startswith("PROC_SCALING "):
                cand = json.loads(line[len("PROC_SCALING "):])
                if cand.get("rank") == 0:
                    row = cand
        if row is None:
            rows.append({"n": n, "error":
                         (r.stderr or r.stdout)[-300:] or "no output"})
        else:
            row.pop("rank", None)
            if (os.cpu_count() or 1) < n:
                row["shared_cores"] = True  # pinning impossible: ranks
                # contend for cores, so allreduce_ms includes contention
            rows.append(row)
    return rows

"""Data-parallel weak-scaling harness (KVStore scaling-efficiency artifact).

BASELINE.md north star #3 is KVStore data-parallel scaling efficiency over
1->32 chips; the reference measures it with tools/bandwidth/measure.py over
kvstore push/pull. Here the measured object is the framework's actual DP
path — ShardedTrainStep (mesh-psum gradient reduction, the KVStore('device')
substrate) — run at n = 1, 2, 4, ... devices with FIXED per-device batch
(weak scaling: ideal = constant step time, efficiency_n = t_1 / t_n).

The same harness serves both regimes:
- virtual CPU mesh (CI / dryrun): meshes are built over sublists of the
  existing devices — honest wall-clock, but all virtual devices share host
  cores, so efficiency UNDERESTIMATES real-chip scaling (collectives are
  simulated serially). The numbers bound overhead, not ICI throughput.
- real hardware: pass ``devices=jax.devices()`` (or any sublist); meshes
  ride the actual ICI and the efficiencies are the headline metric.
"""
from __future__ import annotations

import time


def weak_scaling_table(ns=None, devices=None, per_device_batch=4,
                       image=24, classes=10, iters=8, warmup=3):
    """Run the DP ShardedTrainStep at each n in ``ns``; return a list of
    rows {n, ms_per_step, images_per_s, efficiency}.

    devices: device list to slice (default jax.devices()). ns defaults to
    powers of two up to len(devices).
    """
    import jax
    import jax.numpy as jnp
    import numpy as onp
    from jax.sharding import Mesh, PartitionSpec as P

    import mxnet_tpu as mx
    from mxnet_tpu.gluon.model_zoo.vision import get_resnet
    from mxnet_tpu.parallel.train import ShardedTrainStep

    devices = list(devices) if devices is not None else jax.devices()
    if ns is None:
        ns = []
        n = 1
        while n <= len(devices):
            ns.append(n)
            n *= 2

    def ce_loss(logits, y):
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        return -jnp.mean(jnp.take_along_axis(logp, y[:, None], axis=-1))

    rows = []
    t1 = None
    for n in ns:
        mesh = Mesh(onp.array(devices[:n]).reshape(n), ("dp",))
        net = get_resnet(1, 18, classes=classes)
        net.initialize()
        net(mx.np.zeros((2, 3, image, image), dtype="float32"))
        step = ShardedTrainStep(
            net, ce_loss,
            mx.optimizer.create("sgd", learning_rate=0.05, momentum=0.9),
            mesh, batch_specs=(P("dp"), P("dp")), n_labels=1)
        bs = per_device_batch * n
        x = onp.random.RandomState(0).uniform(
            size=(bs, 3, image, image)).astype("float32")
        y = onp.zeros((bs,), "int32")
        for _ in range(max(warmup, 1)):   # >=1: excludes compile from timing
            loss = step(x, y)
        loss.wait_to_read()
        t0 = time.perf_counter()
        for _ in range(iters):
            loss = step(x, y)
        loss.wait_to_read()
        dt = (time.perf_counter() - t0) / iters
        if t1 is None:
            t1 = dt
        rows.append({
            "n": n,
            "global_batch": bs,
            "ms_per_step": round(dt * 1e3, 2),
            "images_per_s": round(bs / dt, 1),
            "efficiency": round(t1 / dt, 3),
        })
    return rows

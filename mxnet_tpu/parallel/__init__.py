"""mxnet_tpu.parallel — meshes, shardings, collectives, sequence parallelism.

TPU-native distributed layer (SURVEY §2.3 / §5 mapping): one collectives
module over jax.sharding meshes replaces the reference's CommCPU/CommDevice/
CommDeviceTree/NCCL/ps-lite stack. Also home of the capabilities the
reference lacks that are first-class here: tensor parallelism (tp.py) and
ring-attention sequence parallelism (ring_attention.py).
"""
from .mesh import (  # noqa: F401
    make_mesh, data_parallel_mesh, set_mesh, current_mesh, shard, replicate,
    activation_sharding, MeshConfig, mesh_factorizations,
)
from .collectives import (  # noqa: F401
    allreduce, allgather, reduce_scatter, ppermute,
    allreduce_across_processes, compressed_allreduce,
)
from .ring_attention import ring_attention  # noqa: F401
from . import tp  # noqa: F401
from . import pp  # noqa: F401
from .pp import gpipe, stack_stage_params, shard_stages  # noqa: F401
from .train import ShardedTrainStep, megatron_specs, scan_steps  # noqa: F401
from .scaling import weak_scaling_table  # noqa: F401

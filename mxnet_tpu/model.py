"""mx.model — 1.x-style checkpoint helpers.

Reference parity: python/mxnet/model.py (save_checkpoint/load_checkpoint:
`prefix-symbol.json` + `prefix-NNNN.params` with arg:/aux: name
prefixes).  Files interchange with Apache MXNet: the params side uses the
legacy binary format (mxnet_tpu.serialization) and the symbol side the
graph json schema.
"""
from __future__ import annotations

from .base import MXNetError

__all__ = ["save_checkpoint", "load_checkpoint"]


def _raw_dict(d, prefix):
    import numpy as onp
    out = {}
    for k, v in (d or {}).items():
        arr = v.asnumpy() if hasattr(v, "asnumpy") else onp.asarray(v)
        out[f"{prefix}:{k}"] = arr
    return out


def save_checkpoint(prefix, epoch, symbol, arg_params, aux_params,
                    remove_amp_cast=True):
    """Write `prefix-symbol.json` + `prefix-{epoch:04d}.params`
    (reference: model.py save_checkpoint)."""
    from . import serialization
    if symbol is not None:
        symbol.save(f"{prefix}-symbol.json")
    tensors = {**_raw_dict(arg_params, "arg"), **_raw_dict(aux_params, "aux")}
    path = f"{prefix}-{epoch:04d}.params"
    serialization.save_legacy_params(path, tensors)
    return path


def load_checkpoint(prefix, epoch):
    """-> (symbol or None, arg_params, aux_params) as mx ndarrays
    (reference: model.py load_checkpoint)."""
    import os
    from . import serialization
    from . import symbol as sym_mod
    from .numpy import array

    sym = None
    sym_path = f"{prefix}-symbol.json"
    if os.path.exists(sym_path):
        sym = sym_mod.load(sym_path)
    path = f"{prefix}-{epoch:04d}.params"
    loaded = serialization.load_legacy_params(path)
    if isinstance(loaded, list):
        raise MXNetError(f"{path} has no parameter names")
    arg_params, aux_params = {}, {}
    for k, v in loaded.items():
        if k.startswith("arg:"):
            arg_params[k[4:]] = array(v)
        elif k.startswith("aux:"):
            aux_params[k[4:]] = array(v)
        else:
            arg_params[k] = array(v)
    return sym, arg_params, aux_params

"""Functional (pure) execution of Gluon blocks.

Reference parity: this is the TPU-native replacement for binding an NNVM
graph's inputs to NDArrays before CachedOp execution
(src/imperative/cached_op.cc:384-445 StaticAllocMemory binds the memory
plan; python/mxnet/gluon/block.py:1223 _call_cached_op passes params as
inputs). In JAX terms: a Block's forward becomes a pure function of
``(param dict, inputs)`` so it can be jit/pjit/grad-transformed — the basis
for `__graft_entry__`, the sharded training step in
``mxnet_tpu.parallel.train``, and AOT export.
"""
from __future__ import annotations

import jax

from . import autograd
from . import random as _random
from .numpy.multiarray import ndarray, _wrap


def _raw(x):
    return x._data if isinstance(x, ndarray) else x


def param_arrays(block, trainable_only=False):
    """dict structural-name -> raw jax.Array for all initialized params."""
    out = {}
    for name, p in block.collect_params().items():
        if p._data is None:
            continue
        if trainable_only and p.grad_req == "null":
            continue
        out[name] = p.data()._data
    return out


def split_params(block):
    """(trainable, aux) raw-array dicts. aux = grad_req=='null' state such
    as BatchNorm running mean/var (the reference's aux_params split,
    gluon/block.py export writes arg/aux separately)."""
    trainable, aux = {}, {}
    for name, p in block.collect_params().items():
        if p._data is None:
            continue
        (aux if p.grad_req == "null" else trainable)[name] = p.data()._data
    return trainable, aux


class Packer:
    """Pack the small (1-D) leaves of a name->array dict into one vector.

    TPU-native analog of the reference's fused multi-tensor optimizer ops
    (src/operator/optimizer_op.cc multi_sgd_update / multi_lamb): a model
    like ResNet-50 has ~160 tiny BatchNorm vectors; carrying, casting and
    updating each as its own HLO costs more in per-op overhead and loop
    boundary copies than the math itself (profiled at ~0.5 ms/step).
    Packing them into one contiguous vector turns cast + momentum + update
    into three large fused ops and shrinks the scan carry to O(1) arrays.

    pack(d)   -> (vec, big) where vec concatenates all 1-D leaves (sorted
                 by name) and big holds the remaining leaves.
    unpack(vec, big) -> dict with the original structure (slices are views
                 compiled to zero-copy when layouts allow).
    """

    def __init__(self, d):
        import numpy as onp

        import jax.numpy as jnp

        def _packable(a):
            # fp32-only contract: the packed carrier is one f32 vector, so
            # only f32 leaves pack; f16/bf16/int/bool leaves stay in `big`
            # with their own dtype rather than silently promoting
            return getattr(a, "ndim", 0) == 1 and a.dtype == jnp.float32

        self.small = sorted(n for n, a in d.items() if _packable(a))
        small = set(self.small)
        self.big_names = sorted(n for n in d if n not in small)
        self.sizes = [int(d[n].size) for n in self.small]
        self.offsets = onp.cumsum([0] + self.sizes).tolist()

    def pack(self, d):
        import jax.numpy as jnp

        big = {n: _raw(d[n]) for n in self.big_names}
        if not self.small:
            return jnp.zeros((0,)), big
        vec = jnp.concatenate(
            [_raw(d[n]).astype(jnp.float32) for n in self.small])
        return vec, big

    def unpack(self, vec, big):
        """Rebuild the dict; slices keep ``vec``'s dtype so the caller can
        cast the whole vector once (e.g. to bf16) instead of per-leaf."""
        from jax import lax

        out = dict(big)
        for n, off, size in zip(self.small, self.offsets, self.sizes):
            out[n] = lax.dynamic_slice(vec, (off,), (size,))
        return out


def _wrap_arg_tree(args):
    """Wrap every array leaf of ``args`` (which may contain nested pytrees
    such as KV-cache lists) into mx ndarrays; non-array leaves (python
    ints, None) pass through untouched."""
    import numpy as onp

    def wrap_leaf(a):
        if isinstance(a, ndarray):
            return a
        if isinstance(a, (jax.Array, onp.ndarray)) or hasattr(a, "aval"):
            return _wrap(a)
        return a

    return jax.tree_util.tree_map(
        wrap_leaf, args, is_leaf=lambda x: isinstance(x, ndarray))


def functional_call(block, params, *args, train=False, rng_key=None,
                    method="forward"):
    """Run a block method (default ``forward``) as a pure function.

    params: dict structural-name -> raw jax.Array (or mx ndarray).
    args: inputs (raw arrays, mx ndarrays, or pytrees of them — the serve
    engine passes nested KV-cache lists).
    method: name of the method to call — ``"forward"``, or a serving
    surface such as ``"prefill"``/``"decode_step"``.
    Returns ``(outputs, mutated)`` where outputs is the forward result with
    raw jax.Arrays as leaves and mutated is a dict of aux-state values the
    forward updated (BatchNorm running stats) — the caller threads them to
    the next step, the analog of CachedOp mutable inputs.

    Safe to call inside jit/grad traces: Parameter storage is swapped in
    and restored around the forward.
    """
    block_params = block.collect_params()
    saved = {}
    if rng_key is None:
        rng_key = _random._next_key()
    fn = block.forward if method == "forward" else getattr(block, method)
    try:
        for n, v in params.items():
            p = block_params[n]
            if p._data is None:
                raise ValueError(f"parameter {n} not initialized")
            saved[n] = p._data._data
            p._data._data = _raw(v)
        markers = {n: block_params[n]._data._data for n in params}
        nd_args = _wrap_arg_tree(args)
        with autograd._RecordingStateScope(False, train), \
                _random.trace_key_scope(rng_key):
            out = fn(*nd_args)
        out = jax.tree_util.tree_map(
            _raw, out, is_leaf=lambda x: isinstance(x, ndarray))
        mutated = {n: block_params[n]._data._data for n in params
                   if block_params[n]._data._data is not markers[n]}
        return out, mutated
    finally:
        for n, raw in saved.items():
            block_params[n]._data._data = raw

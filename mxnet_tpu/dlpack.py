"""mx.dlpack — zero-copy tensor exchange.

Reference parity: python/mxnet/dlpack.py (ndarray_to_dlpack_for_read/
write, ndarray_from_dlpack over 3rdparty/dlpack).  jax.Array implements
the DLPack protocol natively; these helpers keep the reference's module
surface.
"""
from __future__ import annotations

from .numpy.multiarray import ndarray, _wrap

__all__ = ["ndarray_to_dlpack_for_read", "ndarray_to_dlpack_for_write",
           "ndarray_from_dlpack", "from_dlpack", "to_dlpack_for_read",
           "to_dlpack_for_write"]


def ndarray_to_dlpack_for_read(data: ndarray):
    """Export a capsule; the consumer must treat it as read-only."""
    data.wait_to_read()
    return data.__dlpack__()


def ndarray_to_dlpack_for_write(data: ndarray):
    """XLA buffers are immutable: writable export is the same capsule;
    in-place mutation semantics are emulated at the ndarray layer."""
    return data.__dlpack__()


def ndarray_from_dlpack(capsule_or_array):
    """Import anything speaking DLPack (torch/numpy/jax/...)."""
    import jax
    arr = jax.dlpack.from_dlpack(capsule_or_array) \
        if not hasattr(capsule_or_array, "__dlpack__") \
        else jax.numpy.from_dlpack(capsule_or_array)
    return _wrap(arr)


to_dlpack_for_read = ndarray_to_dlpack_for_read
to_dlpack_for_write = ndarray_to_dlpack_for_write
from_dlpack = ndarray_from_dlpack

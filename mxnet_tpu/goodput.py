"""mx.goodput — fleet-wide wall-clock goodput ledger with badput
attribution and SLO error-budget burn rates.

Three planes (docs/OBSERVABILITY.md "Goodput & SLO budgets"):

- **Ledger** — a per-host non-overlapping interval ledger attributes
  every wall-clock second of a run to exactly one state (``compute``,
  ``input_stall``, ``h2d``, ``compile``, ``checkpoint_save``,
  ``restore``, ``restart``, ``parked``, ``retune``, ``drain``,
  ``rollover``, ``idle``) plus a capacity axis (``degraded_capacity``: running at
  dp2 when the target layout is dp4 counts 50% of every wall-second
  as badput, scaled from the live/target ``MeshConfig`` sizes).
  Feeds are the planes that already exist: the step-time and
  input-stall histograms (via :func:`telemetry.add_sample_listener`),
  ``TrainState.save``/``load_latest_valid`` brackets,
  ``FleetSupervisor`` degrade/park/re-expand transitions, ``Retuner``
  re-searches, the serve drain path, and ``mx.servefleet`` rolling
  weight updates (``rollover`` brackets the whole drain → reload →
  re-warmup → canary window per replica, outranking the nested drain
  and compile claims so update downtime is attributed, not lost).
  Overlaps are resolved by a
  fixed priority order (:data:`PRIORITY`) and un-claimed time is
  ``idle``, so the **conservation oracle** — sum of buckets ==
  elapsed wall clock — holds by construction, epsilon-bounded only by
  float accumulation and late-arriving claims (counted separately).
- **Fleet view** — each host publishes an atomic ``goodput-<rank>.json``
  snapshot next to the mx.fleet heartbeat leases (riding
  ``HealthPlane.beat`` like insight's); :func:`merge_snapshots` turns
  them into capacity-weighted fleet *device-second* totals served at
  ``GET /goodput`` and as the ``goodput`` plane in
  ``TrainingTelemetry`` run reports.
- **SLO layer** — a declared ``goodput.target`` ratio turns the ledger
  into multi-window (5m/1h) error-budget burn-rate gauges wired into
  ``telemetry.register_health``: a sustained burn past
  ``goodput.burn_threshold`` flips ``/healthz`` 503 — the signal the
  serve autoscaler (ROADMAP item 1) consumes.  The serving-side twin
  (``serve.slo_ttft_ms``/``serve.slo_tpot_ms``) lives in the engine.

Cost discipline matches telemetry/trace/fault/insight: disabled (the
default), every hook is one module-attribute read — re-gated by
benchmark/telemetry_overhead.py in the ``goodput`` CI stage.
"""
from __future__ import annotations

import collections
import contextlib
import json
import os
import threading
import time

from . import config as _config
from . import telemetry as _telemetry

__all__ = [
    "PRIORITY", "STATES",
    "enable", "disable", "configure", "active", "reset",
    "note", "begin", "end", "phase", "set_capacity", "set_devices",
    "resolve_claims", "summary", "last_summary", "bench_fields",
    "burn_rates", "healthz",
    "write_snapshot", "maybe_snapshot", "read_snapshots",
    "merge_snapshots", "endpoint_report",
]

_telemetry.declare_metric(
    "goodput.fraction", "gauge",
    "Fraction of elapsed wall clock attributed to compute by the "
    "goodput ledger (capacity-weighted; 1.0 means every paid second "
    "produced training/serving progress).")
_telemetry.declare_metric(
    "goodput.state_seconds", "gauge",
    "Cumulative wall-clock seconds the goodput ledger attributes to "
    "each state, by state — the badput waterfall behind "
    "goodput.fraction.")
_telemetry.declare_metric(
    "goodput.burn_rate", "gauge",
    "Error-budget burn rate against goodput.target, by trailing "
    "window (5m/1h): 1.0 spends the budget exactly, >1 burns it "
    "faster; both windows past goodput.burn_threshold flips /healthz "
    "503.")
_telemetry.declare_metric(
    "goodput.snapshots_written_total", "counter",
    "Fleet goodput ledger snapshots atomically published next to the "
    "heartbeat leases.")

#: Overlap resolution order, highest priority first.  When two claims
#: cover the same instant (a checkpoint save inside a restart bracket,
#: a compile sample under a retune), the second counts the wall clock
#: once, to the highest-priority state.  ``idle`` is the residual —
#: never claimed, it is whatever no feed accounted for — and
#: ``degraded_capacity`` is the capacity axis, split off every state
#: but ``parked`` while the live mesh is smaller than the target.
PRIORITY = ("restart", "restore", "rollover", "checkpoint_save", "parked",
            "retune", "drain", "compile", "input_stall", "h2d", "compute")

#: Every bucket a summary can contain.
STATES = PRIORITY + ("degraded_capacity", "idle")

_RANK = {s: i for i, s in enumerate(PRIORITY)}

#: settle claims into the compacted buckets once this many accumulate
_CLAIM_CAP = 4096
#: never settle time closer than this to "now" (late samples still land)
_SETTLE_GRACE = 30.0
#: burn-rate windows, seconds (multi-window: page only when both burn)
BURN_WINDOWS = (("5m", 300.0), ("1h", 3600.0))

_lock = threading.RLock()
_active = False
_snap_last = 0.0

SNAPSHOT_PREFIX = "goodput-"


def resolve_claims(claims, t0, t1, cap_marks=None):
    """Pure sweep-line resolution of ``(start, stop, state)`` claims
    over the window ``[t0, t1]``: each elementary segment goes to the
    highest-priority covering state, un-claimed segments to ``idle``,
    and while the capacity step function (``cap_marks``: sorted
    ``(time, ratio)`` pairs) is below 1.0 the lost share of every
    non-``parked`` second goes to ``degraded_capacity``.  Returns a
    bucket dict whose values sum to exactly ``t1 - t0`` (up to float
    accumulation) — the conservation oracle holds by construction.
    """
    buckets: dict[str, float] = {}
    if t1 <= t0:
        return buckets
    marks = sorted(cap_marks) if cap_marks else [(t0, 1.0)]
    pts = {t0, t1}
    clipped = []
    for (a, b, s) in claims:
        a, b = max(a, t0), min(b, t1)
        if b <= a:
            continue
        clipped.append((a, b, s))
        pts.add(a)
        pts.add(b)
    for (t, _ratio) in marks:
        if t0 < t < t1:
            pts.add(t)
    edges = sorted(pts)
    for a, b in zip(edges, edges[1:]):
        mid = (a + b) / 2.0
        dt = b - a
        best = None
        for (ca, cb, s) in clipped:
            if ca <= mid < cb and (best is None or _RANK[s] < _RANK[best]):
                best = s
        ratio = 1.0
        for (t, r) in marks:
            if t <= mid:
                ratio = r
            else:
                break
        state = "idle" if best is None else best
        if state == "parked" or ratio >= 1.0:
            buckets[state] = buckets.get(state, 0.0) + dt
        else:
            buckets[state] = buckets.get(state, 0.0) + dt * ratio
            buckets["degraded_capacity"] = \
                buckets.get("degraded_capacity", 0.0) + dt * (1.0 - ratio)
    return buckets


class _Ledger:
    """Per-host claim store.  Claims accumulate unsettled (so late,
    retroactive samples still resolve against concurrent brackets) and
    are periodically compacted into ``settled`` buckets behind a safe
    frontier; :meth:`summary` resolves the live tail on demand."""

    __slots__ = ("t0", "frontier", "settled", "claims", "open",
                 "next_token", "capacity", "cap_marks", "devices",
                 "target_devices", "late_dropped_s", "history",
                 "hist_last")

    def __init__(self, now=None):
        now = time.monotonic() if now is None else now
        self.t0 = now
        self.frontier = now          # settled up to here
        self.settled: dict[str, float] = {}
        self.claims: list[tuple] = []    # (start, stop, state), unsettled
        self.open: dict[int, tuple] = {}  # token -> (start, state)
        self.next_token = 0
        self.capacity = 1.0
        self.cap_marks: list[tuple] = [(now, 1.0)]
        self.devices = 1
        self.target_devices = 1
        self.late_dropped_s = 0.0    # claims fully behind the frontier
        self.history = collections.deque(maxlen=4096)  # (t, elapsed, compute)
        self.hist_last = 0.0

    def claim(self, state, start, stop, now=None):
        if state not in _RANK:
            raise ValueError(f"unknown goodput state {state!r}; "
                             f"expected one of {PRIORITY}")
        if stop <= self.frontier:
            self.late_dropped_s += max(0.0, stop - start)
            return
        self.claims.append((max(start, self.frontier), stop, state))
        if len(self.claims) > _CLAIM_CAP:
            self.compact(time.monotonic() if now is None else now)

    def compact(self, now):
        """Settle everything behind ``min(open brackets, now - grace)``
        into the cumulative buckets and drop the resolved claims."""
        safe = now - _SETTLE_GRACE
        if self.open:
            safe = min(safe, min(t for (t, _s) in self.open.values()))
        if safe <= self.frontier:
            return
        part = resolve_claims(self.claims, self.frontier, safe,
                              self.cap_marks)
        for s, v in part.items():
            self.settled[s] = self.settled.get(s, 0.0) + v
        self.claims = [(max(a, safe), b, s) for (a, b, s) in self.claims
                       if b > safe]
        base = 1.0
        keep = []
        for (t, r) in self.cap_marks:
            if t <= safe:
                base = r
            else:
                keep.append((t, r))
        self.cap_marks = [(safe, base)] + keep
        self.frontier = safe

    def resolve(self, now):
        """Settled + live buckets as of ``now`` (no state mutated)."""
        live = list(self.claims)
        live.extend((t, now, s) for (t, s) in self.open.values())
        buckets = dict(self.settled)
        for s, v in resolve_claims(live, self.frontier, now,
                                   self.cap_marks).items():
            buckets[s] = buckets.get(s, 0.0) + v
        return buckets


_ledger = _Ledger()


# -- switches ----------------------------------------------------------------

def active():
    return _active


def _compute_samples(value):
    note("compute", value)


def _stall_samples(value):
    note("input_stall", value)


def _compile_samples(value):
    note("compile", value)


def enable(on=True):
    """Flip the goodput plane.  Enabling resets the ledger origin to
    "now", registers the ``goodput`` /healthz provider and the
    raw-sample listeners that feed ``compute`` / ``input_stall`` /
    ``compile`` from histograms the stack already records."""
    global _active, _ledger
    was = _active
    _active = bool(on)
    if _active and not was:
        with _lock:
            _ledger = _Ledger()
        _telemetry.register_health("goodput", healthz)
        _telemetry.add_sample_listener("trainer.step_seconds",
                                       _compute_samples, tag="goodput")
        _telemetry.add_sample_listener("serve.step_seconds",
                                       _compute_samples, tag="goodput")
        _telemetry.add_sample_listener("pipeline.input_stall_seconds",
                                       _stall_samples, tag="goodput")
        _telemetry.add_sample_listener("cached_graph.compile_seconds",
                                       _compile_samples, tag="goodput")
    elif was and not _active:
        _telemetry.unregister_health("goodput")
        _telemetry.remove_sample_listener("trainer.step_seconds",
                                          tag="goodput")
        _telemetry.remove_sample_listener("serve.step_seconds",
                                          tag="goodput")
        _telemetry.remove_sample_listener("pipeline.input_stall_seconds",
                                          tag="goodput")
        _telemetry.remove_sample_listener("cached_graph.compile_seconds",
                                          tag="goodput")
    return _active


def disable():
    return enable(False)


def configure():
    """Re-arm from the knob/environment state (MXNET_GOODPUT)."""
    return enable(bool(_config.get("goodput.enable")))


def reset():
    """Fresh ledger (origin = now); the enabled/disabled state and
    listener registrations are kept."""
    global _ledger, _snap_last
    with _lock:
        _ledger = _Ledger()
        _snap_last = 0.0


# -- recording ---------------------------------------------------------------

def note(state, seconds, end_time=None):
    """Record a retroactive claim: the ``seconds`` leading up to
    ``end_time`` (default now) were spent in ``state``.  This is the
    sample-listener feed — a step-time histogram observation arrives
    *after* the interval it measures.  No-op while disabled."""
    if not _active or seconds <= 0.0:
        return
    now = time.monotonic() if end_time is None else end_time
    with _lock:
        _ledger.claim(state, now - seconds, now, now=now)


def begin(state):
    """Open a bracket: wall clock from now until :func:`end` is claimed
    for ``state``.  Returns an opaque token (None while disabled — safe
    to pass straight back to :func:`end`)."""
    if not _active:
        return None
    now = time.monotonic()
    with _lock:
        tok = _ledger.next_token
        _ledger.next_token += 1
        _ledger.open[tok] = (now, state)
    return tok


def end(token):
    """Close a bracket opened by :func:`begin` (no-op for None or after
    a :func:`reset`)."""
    if token is None:
        return
    now = time.monotonic()
    with _lock:
        opened = _ledger.open.pop(token, None)
        if opened is not None:
            _ledger.claim(opened[1], opened[0], now, now=now)


@contextlib.contextmanager
def phase(state):
    """Context-manager form of :func:`begin`/:func:`end`; free when
    disabled."""
    tok = begin(state)
    try:
        yield
    finally:
        end(tok)


def set_capacity(current, target):
    """Record a capacity transition: the live mesh now has ``current``
    of ``target`` devices.  While the ratio is below 1.0 the lost share
    of every wall-second is attributed to ``degraded_capacity`` (dp2
    when the target layout is dp4 -> 50% of device-seconds badput)."""
    if not _active:
        return
    ratio = 1.0
    if target and target > 0:
        ratio = max(0.0, min(1.0, float(current) / float(target)))
    now = time.monotonic()
    with _lock:
        _ledger.capacity = ratio
        _ledger.cap_marks.append((now, ratio))
        _ledger.target_devices = int(target) if target else 1


def set_devices(n):
    """This host's device count — the weight :func:`merge_snapshots`
    uses to turn per-host wall-seconds into fleet device-seconds."""
    if not _active:
        return
    with _lock:
        _ledger.devices = max(1, int(n))


# -- summaries ---------------------------------------------------------------

def _badput_top(buckets, k=2):
    bad = [(s, v) for s, v in buckets.items()
           if s not in ("compute", "idle") and v > 0.0]
    bad.sort(key=lambda kv: kv[1], reverse=True)
    return [[s, round(v, 4)] for s, v in bad[:k]]


def summary(now=None):
    """Resolve the ledger into its bucket waterfall.  The conservation
    oracle — ``attributed_s == elapsed_s`` within epsilon, zero
    overlaps — is structural: test_goodput.py holds it through every
    chaos drill."""
    now = time.monotonic() if now is None else now
    with _lock:
        led = _ledger
        buckets = led.resolve(now)
        elapsed = max(0.0, now - led.t0)
        compute = buckets.get("compute", 0.0)
        if now - led.hist_last >= 1.0:
            led.hist_last = now
            led.history.append((now, elapsed, compute))
        devices = led.devices
        capacity = led.capacity
        late = led.late_dropped_s
    attributed = sum(buckets.values())
    frac = compute / elapsed if elapsed > 0 else 0.0
    out = {
        "elapsed_s": round(elapsed, 6),
        "attributed_s": round(attributed, 6),
        "conservation_error_s": round(abs(elapsed - attributed), 6),
        "late_dropped_s": round(late, 6),
        "goodput_fraction": round(frac, 6),
        "buckets": {s: round(v, 6) for s, v in sorted(buckets.items())},
        "badput_top": _badput_top(buckets),
        "capacity_ratio": capacity,
        "devices": devices,
    }
    target = float(_config.get("goodput.target"))
    if 0.0 < target < 1.0:
        out["slo"] = {"target": target, "burn": burn_rates(now=now)}
    if _telemetry._active:
        _telemetry.set_gauge("goodput.fraction", round(frac, 6))
        for s, v in buckets.items():
            _telemetry.set_gauge("goodput.state_seconds", round(v, 4),
                                 state=s)
    return out


def last_summary():
    """The run-report plane: :func:`summary` when the ledger is armed
    and has attributed anything, else None (the report stays clean on
    runs that never enabled goodput)."""
    if not _active:
        return None
    with _lock:
        led = _ledger
        empty = not (led.settled or led.claims or led.open)
    if empty:
        return None
    return summary()


def bench_fields():
    """Per-row fields for bench.py train rows: the measured goodput
    fraction plus the top-2 badput causes.  {} while disabled so the
    bench schema is unchanged unless the ledger is armed."""
    if not _active:
        return {}
    s = summary()
    return {"goodput_fraction": s["goodput_fraction"],
            "badput_top": s["badput_top"]}


# -- SLO layer ---------------------------------------------------------------

def burn_rates(now=None):
    """Error-budget burn per trailing window against ``goodput.target``:
    ``(1 - windowed_goodput) / (1 - target)``.  1.0 spends the budget
    exactly; the classic multi-window page is both windows > threshold.
    {} until a target is declared."""
    target = float(_config.get("goodput.target"))
    if not (0.0 < target < 1.0):
        return {}
    now = time.monotonic() if now is None else now
    with _lock:
        led = _ledger
        compute_now = led.resolve(now).get("compute", 0.0)
        elapsed_now = max(0.0, now - led.t0)
        if now - led.hist_last >= 1.0:
            led.hist_last = now
            led.history.append((now, elapsed_now, compute_now))
        hist = list(led.history)
    budget = 1.0 - target
    out = {}
    for label, window in BURN_WINDOWS:
        cut = now - window
        base_t, base_elapsed, base_compute = led.t0, 0.0, 0.0
        for (t, e, c) in hist:
            if t <= cut:
                base_t, base_elapsed, base_compute = t, e, c
            else:
                break
        d_elapsed = elapsed_now - base_elapsed
        if d_elapsed <= 0:
            continue
        g = max(0.0, min(1.0, (compute_now - base_compute) / d_elapsed))
        burn = (1.0 - g) / budget
        out[label] = round(burn, 4)
        if _telemetry._active:
            _telemetry.set_gauge("goodput.burn_rate", round(burn, 4),
                                 window=label)
    return out


def healthz():
    """/healthz provider: unhealthy when the error budget burns past
    ``goodput.burn_threshold`` on *every* window (multi-window rule, so
    a 5-minute blip alone never pages).  Vacuously healthy until
    ``goodput.target`` is declared."""
    burn = burn_rates()
    thresh = float(_config.get("goodput.burn_threshold"))
    breach = bool(burn) and all(b > thresh for b in burn.values())
    return {"ok": not breach, "burn": burn, "threshold": thresh}


# -- fleet snapshots & merge -------------------------------------------------

def _snapshot_path(lease_dir, rank):
    return os.path.join(lease_dir, f"{SNAPSHOT_PREFIX}{int(rank)}.json")


def write_snapshot(lease_dir=None, rank=0):
    """Atomically publish this host's ledger summary as
    ``goodput-<rank>.json`` next to the heartbeat leases (tmp +
    ``os.replace``, so readers never see a torn file).  Returns the
    path, or None without a lease dir."""
    lease_dir = lease_dir or _config.get("fleet.lease_dir")
    if not lease_dir:
        return None
    payload = {"rank": int(rank), "pid": os.getpid(),
               "time": time.time(), "summary": summary()}
    os.makedirs(lease_dir, exist_ok=True)
    path = _snapshot_path(lease_dir, rank)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        f.write(json.dumps(payload))
    os.replace(tmp, path)
    if _telemetry._active:
        _telemetry.inc("goodput.snapshots_written_total")
    return path


def maybe_snapshot(lease_dir=None, rank=0, interval=None):
    """Rate-limited :func:`write_snapshot` — the fleet heartbeat hook
    (rides ``HealthPlane.beat``, so snapshot cadence needs no thread
    of its own)."""
    global _snap_last
    if not _active:
        return None
    if interval is None:
        interval = float(_config.get("goodput.snapshot_interval"))
    now = time.monotonic()
    with _lock:
        if _snap_last and now - _snap_last < interval:
            return None
        _snap_last = now
    try:
        return write_snapshot(lease_dir, rank)
    except OSError:
        return None


def read_snapshots(lease_dir=None):
    """{rank: payload} for every well-formed ``goodput-*.json``
    snapshot in the lease dir (torn/foreign files skipped)."""
    lease_dir = lease_dir or _config.get("fleet.lease_dir")
    out = {}
    if not lease_dir or not os.path.isdir(lease_dir):
        return out
    for name in sorted(os.listdir(lease_dir)):
        if not (name.startswith(SNAPSHOT_PREFIX) and
                name.endswith(".json")):
            continue
        try:
            with open(os.path.join(lease_dir, name)) as f:
                payload = json.load(f)
            out[int(payload["rank"])] = payload
        except (OSError, ValueError, KeyError, TypeError):
            continue
    return out


def merge_snapshots(snaps):
    """Merge per-host ledgers into capacity-weighted fleet
    *device-second* totals: each host's wall-second buckets scale by
    its device count, so a dp2-of-dp4 fleet's lost half shows up with
    the same weight as the half that ran."""
    device_seconds: dict[str, float] = {}
    elapsed_dev = 0.0
    by_host = {}
    for rank, payload in sorted(snaps.items()):
        s = payload.get("summary") or {}
        dev = max(1, int(s.get("devices", 1)))
        elapsed_dev += float(s.get("elapsed_s", 0.0)) * dev
        for state, sec in (s.get("buckets") or {}).items():
            device_seconds[state] = \
                device_seconds.get(state, 0.0) + float(sec) * dev
        by_host[str(rank)] = {
            "devices": dev,
            "elapsed_s": s.get("elapsed_s", 0.0),
            "goodput_fraction": s.get("goodput_fraction", 0.0),
            "age_s": max(0.0, time.time() - float(payload.get("time", 0))),
        }
    compute = device_seconds.get("compute", 0.0)
    frac = compute / elapsed_dev if elapsed_dev > 0 else 0.0
    return {
        "hosts": len(snaps),
        "elapsed_device_seconds": round(elapsed_dev, 4),
        "device_seconds": {s: round(v, 4)
                           for s, v in sorted(device_seconds.items())},
        "goodput_fraction": round(frac, 6),
        "badput_top": _badput_top(device_seconds),
        "by_host": by_host,
    }


def endpoint_report(lease_dir=None):
    """The ``GET /goodput`` payload: this host's ledger plus the merged
    fleet view when heartbeat-lease snapshots are present."""
    snaps = read_snapshots(lease_dir)
    return {"enabled": _active,
            "local": last_summary(),
            "fleet": merge_snapshots(snaps) if snaps else None}


if _config.get("goodput.enable"):
    enable()

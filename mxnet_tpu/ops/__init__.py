"""mxnet_tpu.ops — op registry + TPU kernels.

The analog of src/operator/'s registration layer (NNVM_REGISTER_OP +
op_attr_types.h attributes): ops register metadata (name, impl, optional
Pallas kernel) and become visible to mx.np/mx.npx dispatch. Pallas kernels
live in ops/pallas/ with jnp fallbacks for CPU tests.
"""
from . import registry  # noqa: F401
from . import attention  # noqa: F401

"""SSD multibox operators.

Reference parity: src/operator/contrib/multibox_prior.cc (anchor
generation), multibox_target.cc (training target assignment: greedy
bipartite + threshold matching + hard-negative mining), and
multibox_detection.cc (decode + per-class NMS) — the operator family under
the reference's SSD example (example/ssd).

TPU-native design: everything is static-shaped jnp/lax.  The reference's
per-sample C++ loops with early exits become masked whole-array passes
vmapped over the batch: invalid ground-truths are masked (contiguous
prefix of label rows whose class is not -1), the sequential bipartite
stage is a ``lax.fori_loop`` of global argmax rounds (M rounds, each a
reduction over the A×M overlap matrix — MXU/VPU friendly), and NMS is the
same O(A^2) masked triangular pass as ``ops.bbox``.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..numpy.multiarray import _invoke
from .bbox import _iou_impl

__all__ = ["multibox_prior", "multibox_target", "multibox_detection"]


def multibox_prior(data, sizes=(1.0,), ratios=(1.0,), clip=False,
                   steps=(-1.0, -1.0), offsets=(0.5, 0.5)):
    """Generate prior (anchor) boxes (reference: multibox_prior.cc
    MultiBoxPriorForward).

    data: (N, C, H, W) — only H, W are used. Returns (1, H*W*K, 4) corner
    boxes with K = len(sizes) + len(ratios) - 1: per location, all sizes
    at ratios[0], then ratios[1:] at sizes[0]. Box half-width is
    ``size * H/W * sqrt(ratio) / 2`` (sizes are normalized to height),
    half-height ``size / sqrt(ratio) / 2``.
    """
    sizes = tuple(float(s) for s in sizes) or (1.0,)
    ratios = tuple(float(r) for r in ratios) or (1.0,)

    def fn(d):
        h, w = d.shape[-2], d.shape[-1]
        step_y = steps[0] if steps[0] > 0 else 1.0 / h
        step_x = steps[1] if steps[1] > 0 else 1.0 / w
        cy = (jnp.arange(h, dtype=jnp.float32) + offsets[0]) * step_y
        cx = (jnp.arange(w, dtype=jnp.float32) + offsets[1]) * step_x
        # per-location half extents, reference order
        hw, hh = [], []
        r0 = jnp.sqrt(jnp.float32(ratios[0]))
        for s in sizes:
            hw.append(s * h / w * r0 / 2.0)
            hh.append(s / r0 / 2.0)
        for r in ratios[1:]:
            rs = jnp.sqrt(jnp.float32(r))
            hw.append(sizes[0] * h / w * rs / 2.0)
            hh.append(sizes[0] / rs / 2.0)
        hw = jnp.stack([jnp.asarray(v, jnp.float32) for v in hw])  # (K,)
        hh = jnp.stack([jnp.asarray(v, jnp.float32) for v in hh])
        cyg, cxg = jnp.meshgrid(cy, cx, indexing="ij")      # (H, W)
        cxg = cxg[..., None]                                # (H, W, 1)
        cyg = cyg[..., None]
        out = jnp.stack([cxg - hw, cyg - hh, cxg + hw, cyg + hh],
                        axis=-1)                            # (H, W, K, 4)
        out = out.reshape(1, h * w * hw.shape[0], 4)
        if clip:
            out = jnp.clip(out, 0.0, 1.0)
        return out

    return _invoke(fn, (data,), name="multibox_prior")


def _target_one(anchors, label, cls_pred, overlap_threshold, ignore_label,
                negative_mining_ratio, negative_mining_thresh,
                minimum_negative_samples, variances):
    """One sample of MultiBoxTargetForward (multibox_target.cc:54-260).

    anchors (A, 4) corner, label (M, W) [cls, x1, y1, x2, y2, ...],
    cls_pred (C, A) raw scores. Returns loc_target (A*4,), loc_mask
    (A*4,), cls_target (A,).
    """
    A = anchors.shape[0]
    M = label.shape[0]
    f32 = jnp.float32
    # valid gts: contiguous prefix with class != -1 (reference breaks at
    # the first -1 row)
    valid_gt = jnp.cumprod(label[:, 0] != -1.0).astype(bool)       # (M,)
    num_valid = valid_gt.sum()
    gt_boxes = label[:, 1:5]
    overlaps = _iou_impl(anchors, gt_boxes)                        # (A, M)
    overlaps = jnp.where(valid_gt[None, :], overlaps, -1.0)

    # ---- stage 1: greedy bipartite matching (one gt per round) ----------
    def bip_round(_, carry):
        match_iou, match_gt, anchor_matched, gt_matched = carry
        work = jnp.where(anchor_matched[:, None] | gt_matched[None, :],
                         -1.0, overlaps)
        flat = jnp.argmax(work)
        i, k = flat // M, flat % M
        good = work[i, k] > 1e-6
        match_iou = jnp.where(good, match_iou.at[i].set(work[i, k]),
                              match_iou)
        match_gt = jnp.where(good, match_gt.at[i].set(k), match_gt)
        anchor_matched = jnp.where(good, anchor_matched.at[i].set(True),
                                   anchor_matched)
        gt_matched = jnp.where(good, gt_matched.at[k].set(True), gt_matched)
        return match_iou, match_gt, anchor_matched, gt_matched

    match_iou = jnp.full((A,), -1.0, f32)
    match_gt = jnp.full((A,), -1, jnp.int32)
    anchor_matched = jnp.zeros((A,), bool)
    gt_matched = ~valid_gt  # invalid gts count as already matched
    match_iou, match_gt, anchor_matched, _ = lax.fori_loop(
        0, M, bip_round,
        (match_iou, match_gt, anchor_matched, gt_matched))

    # ---- stage 2: per-anchor best gt; threshold matching ----------------
    best_gt = jnp.argmax(overlaps, axis=1).astype(jnp.int32)       # (A,)
    best_iou = jnp.take_along_axis(overlaps, best_gt[:, None], 1)[:, 0]
    has_gt = num_valid > 0
    thresh_pos = (~anchor_matched) & has_gt & (best_iou > overlap_threshold) \
        if overlap_threshold > 0 else jnp.zeros((A,), bool)
    positive = anchor_matched | thresh_pos
    match_gt = jnp.where(anchor_matched, match_gt, best_gt)
    match_iou = jnp.where(anchor_matched, match_iou, best_iou)

    # ---- stage 3: negatives --------------------------------------------
    if negative_mining_ratio > 0:
        num_positive = positive.sum()
        num_negative = jnp.minimum(
            (num_positive * negative_mining_ratio).astype(jnp.int32),
            A - num_positive.astype(jnp.int32))
        num_negative = jnp.maximum(num_negative,
                                   jnp.int32(minimum_negative_samples))
        # candidate negatives: unmatched anchors whose best overlap is
        # below the mining threshold; rank by background softmax prob
        # ascending (hardest negatives = least-confident background)
        mx = cls_pred.max(axis=0)
        prob_bg = jnp.exp(cls_pred[0] - mx) / \
            jnp.exp(cls_pred - mx[None, :]).sum(axis=0)
        cand = (~positive) & (match_iou < negative_mining_thresh) & has_gt
        # stable sort by descending (-prob) == ascending prob
        order = jnp.argsort(jnp.where(cand, prob_bg, jnp.inf),
                            stable=True)
        rank = jnp.empty_like(order).at[order].set(jnp.arange(A))
        negative = cand & (rank < num_negative)
    else:
        negative = (~positive) & has_gt

    # ---- assign targets -------------------------------------------------
    g = gt_boxes[match_gt]                                          # (A, 4)
    gw, gh = g[:, 2] - g[:, 0], g[:, 3] - g[:, 1]
    gx, gy = (g[:, 0] + g[:, 2]) * 0.5, (g[:, 1] + g[:, 3]) * 0.5
    aw, ah = anchors[:, 2] - anchors[:, 0], anchors[:, 3] - anchors[:, 1]
    ax, ay = (anchors[:, 0] + anchors[:, 2]) * 0.5, \
        (anchors[:, 1] + anchors[:, 3]) * 0.5
    enc = jnp.stack([
        (gx - ax) / aw / variances[0],
        (gy - ay) / ah / variances[1],
        jnp.log(jnp.maximum(gw / aw, 1e-12)) / variances[2],
        jnp.log(jnp.maximum(gh / ah, 1e-12)) / variances[3]], axis=1)
    loc_target = jnp.where(positive[:, None], enc, 0.0).reshape(-1)
    loc_mask = jnp.where(positive[:, None],
                         jnp.ones((A, 4), f32), 0.0).reshape(-1)
    cls_target = jnp.where(
        positive, label[match_gt, 0] + 1.0,
        jnp.where(negative, 0.0, f32(ignore_label)))
    return loc_target, loc_mask, cls_target


def multibox_target(anchor, label, cls_pred, overlap_threshold=0.5,
                    ignore_label=-1.0, negative_mining_ratio=-1.0,
                    negative_mining_thresh=0.5, minimum_negative_samples=0,
                    variances=(0.1, 0.1, 0.2, 0.2)):
    """Compute SSD training targets (reference: _contrib_MultiBoxTarget).

    anchor (1, A, 4); label (N, M, 5+) with -1-padded rows; cls_pred
    (N, num_classes, A). Returns [loc_target (N, A*4), loc_mask (N, A*4),
    cls_target (N, A)] — cls 0 is background, ignore_label marks don't-care
    anchors.
    """
    def fn(a, l, c):
        anchors = a.reshape(-1, 4)
        one = lambda lb, cp: _target_one(
            anchors, lb, cp, float(overlap_threshold), float(ignore_label),
            float(negative_mining_ratio), float(negative_mining_thresh),
            int(minimum_negative_samples), tuple(variances))
        return jax.vmap(one)(l, c)
    return _invoke(fn, (anchor, label, cls_pred), name="multibox_target")


def _detect_one(cls_prob, loc_pred, anchors, threshold, clip, variances,
                nms_threshold, force_suppress, nms_topk):
    """One sample of MultiBoxDetectionForward (multibox_detection.cc:40)."""
    C, A = cls_prob.shape
    f32 = jnp.float32
    # argmax over foreground classes (reference starts j at 1)
    fg = cls_prob[1:]                                            # (C-1, A)
    score = fg.max(axis=0)
    cid = fg.argmax(axis=0).astype(f32)                          # 0-based
    keep_id = score >= threshold
    # decode locations (TransformLocations)
    aw = anchors[:, 2] - anchors[:, 0]
    ah = anchors[:, 3] - anchors[:, 1]
    ax = (anchors[:, 0] + anchors[:, 2]) * 0.5
    ay = (anchors[:, 1] + anchors[:, 3]) * 0.5
    p = loc_pred.reshape(-1, 4)
    ox = p[:, 0] * variances[0] * aw + ax
    oy = p[:, 1] * variances[1] * ah + ay
    ow = jnp.exp(p[:, 2] * variances[2]) * aw / 2
    oh = jnp.exp(p[:, 3] * variances[3]) * ah / 2
    boxes = jnp.stack([ox - ow, oy - oh, ox + ow, oy + oh], axis=1)
    if clip:
        boxes = jnp.clip(boxes, 0.0, 1.0)
    rows = jnp.concatenate(
        [jnp.where(keep_id, cid, -1.0)[:, None], score[:, None], boxes], 1)

    # compact valid rows to the front then stable-sort by descending score:
    # one stable argsort with invalid rows keyed to -inf reproduces both
    key = jnp.where(keep_id, score, -jnp.inf)
    order = jnp.argsort(-key, stable=True)
    rows = rows[order]
    valid = keep_id[order]
    if nms_topk > 0:
        valid = valid & (jnp.arange(A) < nms_topk)
    rows = jnp.where(valid[:, None], rows, -1.0)

    if nms_threshold <= 0 or nms_threshold > 1:
        return rows
    iou = _iou_impl(rows[:, 2:6], rows[:, 2:6])
    same = (rows[:, 0][:, None] == rows[:, 0][None, :]) | bool(force_suppress)

    def body(i, keep):
        sup = (iou[i] >= nms_threshold) & same[i] & \
            (jnp.arange(A) > i) & keep[i]
        return keep & ~sup

    keep = lax.fori_loop(0, A, body, valid)
    return jnp.where(keep[:, None], rows,
                     rows.at[:, 0].set(-1.0))


def multibox_detection(cls_prob, loc_pred, anchor, clip=True,
                       threshold=0.01, background_id=0, nms_threshold=0.5,
                       force_suppress=False,
                       variances=(0.1, 0.1, 0.2, 0.2), nms_topk=-1):
    """Convert predictions to detections (reference:
    _contrib_MultiBoxDetection).

    cls_prob (N, C, A) softmax class probabilities (class 0 background),
    loc_pred (N, A*4), anchor (1, A, 4). Returns (N, A, 6) rows
    [class_id, score, x1, y1, x2, y2], class_id -1 for invalid/suppressed,
    rows sorted by descending score.
    """
    if background_id != 0:
        raise NotImplementedError("background_id must be 0 (reference "
                                  "kernel has the same restriction)")

    def fn(c, lp, a):
        anchors = a.reshape(-1, 4)
        one = lambda cp, l: _detect_one(
            cp, l, anchors, float(threshold), bool(clip), tuple(variances),
            float(nms_threshold), bool(force_suppress), int(nms_topk))
        return jax.vmap(one)(c, lp)
    return _invoke(fn, (cls_prob, loc_pred, anchor),
                   name="multibox_detection")

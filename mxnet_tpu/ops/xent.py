"""Fused sparse softmax cross-entropy.

Reference parity: ``softmax_cross_entropy`` (src/operator/loss_binary_op.cc:29,
-log softmax(data)[label]) and the sparse path of
gluon ``SoftmaxCrossEntropyLoss`` (python/mxnet/gluon/loss.py).

TPU-first design: the naive formulation ``-pick(log_softmax(x), label)``
materializes a full (N, V) float32 log-softmax — at BERT-pretraining scale
(4096 tokens x 30522 vocab) that intermediate alone is ~500 MB of HBM
traffic per step, and its VJP writes the same again. Here the loss is
computed as ``logsumexp(x) - x[label]``: two fused XLA reductions that
read the logits ONCE in their storage dtype (bf16 under AMP) with f32
accumulation inside the reduction, plus an N-element gather. The custom
VJP emits the one-pass cotangent ``(softmax(x) - onehot(label)) * g``
directly in the input dtype, so no f32 (N, V) array ever exists in
either direction. Measured on TPU v5lite this removes ~1.7 ms from a
27.5 ms BERT-base bs32 step (tools/tpu_ab.py round-5 session).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as onp


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def sparse_softmax_xent(logits, labels, axis=-1):
    """Per-element ``-log softmax(logits)[labels]`` along ``axis``.

    logits: (..., V, ...) float array; labels: integer array of
    ``logits.shape`` minus ``axis``. Returns float32 losses of the label
    shape. Gradients flow to ``logits`` only.
    """
    return _xent_fwd(logits, labels, axis)[0]


def _xent_fwd(logits, labels, axis):
    xf = logits.astype(jnp.float32)      # fuses into the reductions below
    m = jnp.max(xf, axis=axis, keepdims=True)
    lse = jnp.log(jnp.sum(jnp.exp(xf - m), axis=axis)) + jnp.squeeze(m, axis)
    # clip into a local: the residual must keep the ORIGINAL labels so the
    # bwd rule sees their true dtype (float labels need a float cotangent)
    idx = jnp.expand_dims(_clip_labels(labels, logits, axis), axis)
    # gather from the ORIGINAL array: N elements move, not a cast of (N, V)
    picked = jnp.squeeze(jnp.take_along_axis(logits, idx, axis), axis)
    loss = lse - picked.astype(jnp.float32)
    return loss, (logits, labels, lse)


def _clip_labels(labels, logits, axis):
    """npx.pick(mode='clip') parity: out-of-range labels clamp to the
    nearest valid class instead of poisoning the loss with NaN (negative
    indices would otherwise wrap to the LAST class via gather)."""
    v = logits.shape[axis]
    return jnp.clip(labels.astype(jnp.int32), 0, v - 1)


def _xent_bwd(axis, res, g):
    logits, labels, lse = res
    xf = logits.astype(jnp.float32)
    p = jnp.exp(xf - jnp.expand_dims(lse, axis))
    ax = axis if axis >= 0 else logits.ndim + axis
    iota = jax.lax.broadcasted_iota(jnp.int32, logits.shape, ax)
    onehot = iota == jnp.expand_dims(_clip_labels(labels, logits, axis), axis)
    dx = (p - onehot.astype(jnp.float32)) * jnp.expand_dims(g, axis)
    # labels carry no gradient; the cotangent's dtype must still match the
    # primal's: float0 for integer labels, zeros for float labels (MXNet
    # data iters conventionally ship labels as float32)
    if jnp.issubdtype(labels.dtype, jnp.inexact):
        dlab = jnp.zeros(labels.shape, labels.dtype)
    else:
        dlab = onp.zeros(labels.shape, dtype=jax.dtypes.float0)
    return dx.astype(logits.dtype), dlab


sparse_softmax_xent.defvjp(_xent_fwd, _xent_bwd)

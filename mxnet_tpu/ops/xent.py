"""Fused sparse softmax cross-entropy.

Reference parity: ``softmax_cross_entropy`` (src/operator/loss_binary_op.cc:29,
-log softmax(data)[label]) and the sparse path of
gluon ``SoftmaxCrossEntropyLoss`` (python/mxnet/gluon/loss.py).

TPU-first design: the naive formulation ``-pick(log_softmax(x), label)``
materializes a full (N, V) float32 log-softmax — at BERT-pretraining scale
(4096 tokens x 30522 vocab) that intermediate alone is ~500 MB of HBM
traffic per step, and its VJP writes the same again. Here the loss is
computed as ``logsumexp(x) - x[label]``: two fused XLA reductions that
read the logits ONCE in their storage dtype (bf16 under AMP) with f32
accumulation inside the reduction, plus an N-element gather. The custom
VJP emits the one-pass cotangent ``(softmax(x) - onehot(label)) * g``
directly in the input dtype, so no f32 (N, V) array ever exists in
either direction. Measured on TPU v5lite this removes ~1.7 ms from a
27.5 ms BERT-base bs32 step (tools/tpu_ab.py round-5 session).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as onp


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def sparse_softmax_xent(logits, labels, axis=-1):
    """Per-element ``-log softmax(logits)[labels]`` along ``axis``.

    logits: (..., V, ...) float array; labels: integer array of
    ``logits.shape`` minus ``axis``. Returns float32 losses of the label
    shape. Gradients flow to ``logits`` only.
    """
    return _xent_fwd(logits, labels, axis)[0]


def _xent_fwd(logits, labels, axis):
    xf = logits.astype(jnp.float32)      # fuses into the reductions below
    m = jnp.max(xf, axis=axis, keepdims=True)
    lse = jnp.log(jnp.sum(jnp.exp(xf - m), axis=axis)) + jnp.squeeze(m, axis)
    # clip into a local: the residual must keep the ORIGINAL labels so the
    # bwd rule sees their true dtype (float labels need a float cotangent)
    idx = jnp.expand_dims(_clip_labels(labels, logits, axis), axis)
    # gather from the ORIGINAL array: N elements move, not a cast of (N, V)
    picked = jnp.squeeze(jnp.take_along_axis(logits, idx, axis), axis)
    loss = lse - picked.astype(jnp.float32)
    return loss, (logits, labels, lse)


def _clip_labels(labels, logits, axis):
    """npx.pick(mode='clip') parity: out-of-range labels clamp to the
    nearest valid class instead of poisoning the loss with NaN (negative
    indices would otherwise wrap to the LAST class via gather)."""
    v = logits.shape[axis]
    return jnp.clip(labels.astype(jnp.int32), 0, v - 1)


def _xent_bwd(axis, res, g):
    logits, labels, lse = res
    xf = logits.astype(jnp.float32)
    p = jnp.exp(xf - jnp.expand_dims(lse, axis))
    ax = axis if axis >= 0 else logits.ndim + axis
    iota = jax.lax.broadcasted_iota(jnp.int32, logits.shape, ax)
    onehot = iota == jnp.expand_dims(_clip_labels(labels, logits, axis), axis)
    dx = (p - onehot.astype(jnp.float32)) * jnp.expand_dims(g, axis)
    # labels carry no gradient; the cotangent's dtype must still match the
    # primal's: float0 for integer labels, zeros for float labels (MXNet
    # data iters conventionally ship labels as float32)
    if jnp.issubdtype(labels.dtype, jnp.inexact):
        dlab = jnp.zeros(labels.shape, labels.dtype)
    else:
        dlab = onp.zeros(labels.shape, dtype=jax.dtypes.float0)
    return dx.astype(logits.dtype), dlab


sparse_softmax_xent.defvjp(_xent_fwd, _xent_bwd)


# ---------------------------------------------------------------------------
# chunked-vocab LM cross-entropy: the logits NEVER materialize
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def chunked_lm_xent(h, w, labels, chunk=8192):
    """``-log softmax(h @ w.T)[labels]`` without the (N, V) logits.

    The tied LM head's logits tensor is the long-context memory wall:
    at seq 8192 x vocab 50257 it alone is ~823 MB bf16 and OOMs one v5e
    even under whole-model remat (ROUND5_NOTES). This computes the loss
    by streaming ``lax.scan`` over vocab chunks — per chunk one
    (N, D) @ (D, chunk) matmul feeds a running online-logsumexp (the
    flash-attention trick applied to the classifier axis) and the picked
    label logits; the VJP re-streams the chunks, emitting dh and dw
    per-chunk so peak extra memory is O(N*chunk + chunk*D).

    h: (N, D); w: (V, D); labels: (N,) int. Returns f32 losses (N,).
    Gradients flow to h and w.
    """
    loss, _ = _chunked_fwd_core(h, w, labels, chunk)
    return loss


def _chunk_w(w, chunk):
    v, d = w.shape
    pad = -v % chunk
    if pad:
        w = jnp.pad(w, ((0, pad), (0, 0)))
    return w.reshape(-1, chunk, d), v


def _chunked_fwd_core(h, w, labels, chunk):
    n, d = h.shape
    wc, v = _chunk_w(w, chunk)
    # out-of-range labels clip to the last valid class, matching
    # sparse_softmax_xent's _clip_labels parity contract
    lab = jnp.clip(labels.astype(jnp.int32), 0, v - 1)
    hf = h  # keep storage dtype on the MXU; accumulate f32 below

    def body(carry, xs):
        m, s, picked = carry
        w_c, c0 = xs
        logits = jax.lax.dot_general(
            hf, w_c, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)          # (N, chunk)
        col = c0 + jax.lax.broadcasted_iota(jnp.int32, logits.shape, 1)
        logits = jnp.where(col < v, logits, -jnp.inf)
        m_new = jnp.maximum(m, jnp.max(logits, -1))
        s = s * jnp.exp(m - m_new) + jnp.sum(
            jnp.exp(logits - m_new[:, None]), -1)
        in_chunk = (lab >= c0) & (lab < c0 + chunk)
        local = jnp.clip(lab - c0, 0, chunk - 1)
        got = jnp.take_along_axis(logits, local[:, None], 1)[:, 0]
        picked = jnp.where(in_chunk, got, picked)
        return (m_new, s, picked), None

    nc = wc.shape[0]
    starts = jnp.arange(nc, dtype=jnp.int32) * chunk
    init = (jnp.full((n,), -jnp.inf, jnp.float32),
            jnp.zeros((n,), jnp.float32),
            jnp.zeros((n,), jnp.float32))
    (m, s, picked), _ = jax.lax.scan(body, init, (wc, starts))
    lse = m + jnp.log(s)
    return lse - picked, lse


def _chunked_lm_fwd(h, w, labels, chunk):
    loss, lse = _chunked_fwd_core(h, w, labels, chunk)
    return loss, (h, w, labels.astype(jnp.int32), lse)


def _chunked_lm_bwd(chunk, res, g):
    h, w, lab, lse = res
    lab = jnp.clip(lab, 0, w.shape[0] - 1)  # same clip as forward
    n, d = h.shape
    wc, v = _chunk_w(w, chunk)
    nc = wc.shape[0]
    gf = g.astype(jnp.float32)

    def body(dh, xs):
        w_c, c0 = xs
        logits = jax.lax.dot_general(
            h, w_c, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        col = c0 + jax.lax.broadcasted_iota(jnp.int32, logits.shape, 1)
        p = jnp.where(col < v, jnp.exp(logits - lse[:, None]), 0.0)
        onehot = (col == lab[:, None]).astype(jnp.float32)
        dlogits = ((p - onehot) * gf[:, None]).astype(h.dtype)  # (N, chunk)
        dh = dh + jax.lax.dot_general(
            dlogits, w_c, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dw_c = jax.lax.dot_general(
            dlogits, h, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)          # (chunk, D)
        return dh, dw_c

    starts = jnp.arange(nc, dtype=jnp.int32) * chunk
    dh, dwc = jax.lax.scan(body, jnp.zeros((n, d), jnp.float32),
                           (wc, starts))
    dw = dwc.reshape(-1, d)[:v]
    return dh.astype(h.dtype), dw.astype(w.dtype), None


chunked_lm_xent.defvjp(_chunked_lm_fwd, _chunked_lm_bwd)

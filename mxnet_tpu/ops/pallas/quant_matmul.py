"""Fused low-bit matmul Pallas kernels (TPU).

Reference parity: the role of src/operator/quantization/'s cuDNN int8
kernels (quantized_fully_connected.cc, quantized_conv.cc) — the hand-
written path the reference keeps because compiler fusion alone does not
reach the int8 peak. BENCH_r05 showed the same thing here: the composed
quantize_v2 → dot_general(int32) → dequantize chain loses to bf16
(12,012 vs 12,790 img/s) because XLA materializes the int8 activations
and the fp32 epilogue in HBM between ops. This kernel streams one
(block_m, K) activation tile through VMEM ONCE: quantize in registers,
int8×int8 dot on the MXU with int32 accumulation, dequant + bias +
activation in the epilogue, write the finished fp tile.

Scheme (matches ops/quantization.py): symmetric int8, zero-point 0,
per-tensor activation scale (calibrated threshold), per-output-channel
weight scales. The epilogue computes ``acc * (x_scale * w_scale) + bias``
in fp32 — bitwise the same expression as the XLA fallback, which the
parity tests in tests/test_quantization.py hold as an oracle.

The fp8 variant keeps the same structure with e4m3/e5m2 operands and
fp32 MXU accumulation; it is gated on device capability
(:func:`fp8_capable` — v5+ MXUs take fp8 natively, v4 and CPU do not).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

__all__ = ["quantized_matmul", "fp8_matmul", "fp8_capable", "FP8_FORMATS"]

_INT8_MAX = 127.0

#: fp8 storage formats: name -> (dtype, absmax of the format)
FP8_FORMATS = {
    "e4m3": (jnp.float8_e4m3fn, 448.0),
    "e5m2": (jnp.float8_e5m2, 57344.0),
}

_ACTS = {
    None: lambda z: z,
    "relu": lambda z: jnp.maximum(z, 0.0),
    "sigmoid": jax.nn.sigmoid,
    "tanh": jnp.tanh,
    "gelu": jax.nn.gelu,
}


def fp8_capable(device=None):
    """fp8 matmuls hit the MXU natively from TPU v5 on; v4 and earlier
    emulate (slower than bf16), so the fp8 path is gated off there."""
    if device is None:
        devs = jax.devices()
        if not devs:
            return False
        device = devs[0]
    if device.platform not in ("tpu", "axon"):
        return False
    kind = getattr(device, "device_kind", "").lower()
    return not any(old in kind for old in ("v2", "v3", "v4"))


def _round_up(n, m):
    return (n + m - 1) // m * m


def _pad2(a, rows, cols):
    pr, pc = rows - a.shape[0], cols - a.shape[1]
    if pr == 0 and pc == 0:
        return a
    return jnp.pad(a, ((0, pr), (0, pc)))


def _int8_kernel(xs_ref, x_ref, w_ref, ws_ref, b_ref, o_ref, *, act):
    """One (block_m, block_n) output tile: quantize the activation tile
    in registers, int8×int8 dot (int32 MXU accumulation), fp32 dequant
    epilogue with bias + activation."""
    x_scale = xs_ref[0, 0]
    x = x_ref[...].astype(jnp.float32)
    xq = jnp.clip(jnp.round(x / x_scale), -_INT8_MAX, _INT8_MAX
                  ).astype(jnp.int8)
    acc = lax.dot_general(xq, w_ref[...], (((1,), (1,)), ((), ())),
                          preferred_element_type=jnp.int32)
    out = acc.astype(jnp.float32) * (x_scale * ws_ref[...])
    out = out + b_ref[...]
    o_ref[...] = _ACTS[act](out).astype(o_ref.dtype)


def quantized_matmul(x, w_q, w_scale, x_scale, bias=None, act=None,
                     block_m=None, block_n=None, interpret=False):
    """``dequant(quantize(x) @ w_q.T) + bias`` fused in one VMEM pass.

    x: (M, K) float; w_q: (N, K) int8 (per-output-channel quantized);
    w_scale: (N,) fp32; x_scale: scalar fp32 (calibrated threshold / 127).
    bias: (N,) fp32 or None; act: one of None/'relu'/'sigmoid'/'tanh'/
    'gelu', applied in the epilogue. Returns (M, N) fp32.

    K rides whole through VMEM per tile (one (block_n, K) int8 weight
    tile is K bytes * block_n — 256x4096 = 1 MB, comfortably resident);
    M/N are tiled and zero-padded to Mosaic-aligned blocks. Zero padding
    is exact: padded activations quantize to 0 and contribute nothing to
    the int32 dot.
    """
    if act not in _ACTS:
        raise ValueError(f"unsupported fused activation {act!r}; "
                         f"one of {sorted(k for k in _ACTS if k)}")
    m, k = x.shape
    n = w_q.shape[0]
    if block_m is None or block_n is None:
        from ...autotune.kernels import resolve_blocks
        tb = resolve_blocks("quantized_matmul", (m, n, k))
        block_m = tb["block_m"] if block_m is None else block_m
        block_n = tb["block_n"] if block_n is None else block_n
    # int8 tiles are (32, 128); the fp32 output tile needs lane 128
    bm = min(block_m, _round_up(m, 32))
    bn = min(block_n, _round_up(n, 128))
    grid_m, grid_n = pl.cdiv(m, bm), pl.cdiv(n, bn)
    mp, np_, kp = grid_m * bm, grid_n * bn, _round_up(k, 128)
    xp = _pad2(x, mp, kp)
    wp = _pad2(w_q, np_, kp)
    wsp = _pad2(w_scale.astype(jnp.float32)[None, :], 1, np_)
    b = (jnp.zeros((n,), jnp.float32) if bias is None
         else bias.astype(jnp.float32))
    bp = _pad2(b[None, :], 1, np_)
    xs = jnp.asarray(x_scale, jnp.float32).reshape(1, 1)
    out = pl.pallas_call(
        functools.partial(_int8_kernel, act=act),
        grid=(grid_m, grid_n),
        in_specs=[
            pl.BlockSpec((1, 1), lambda i, j: (0, 0)),
            pl.BlockSpec((bm, kp), lambda i, j: (i, 0)),
            pl.BlockSpec((bn, kp), lambda i, j: (j, 0)),
            pl.BlockSpec((1, bn), lambda i, j: (0, j)),
            pl.BlockSpec((1, bn), lambda i, j: (0, j)),
        ],
        out_specs=pl.BlockSpec((bm, bn), lambda i, j: (i, j)),
        out_shape=jax.ShapeDtypeStruct((mp, np_), jnp.float32),
        interpret=interpret,
    )(xs, xp, wp, wsp, bp)
    return out[:m, :n]


def _fp8_kernel(xs_ref, x_ref, w_ref, ws_ref, b_ref, o_ref, *, act, fmt):
    dtype, _ = FP8_FORMATS[fmt]
    x_scale = xs_ref[0, 0]
    xq = (x_ref[...].astype(jnp.float32) / x_scale).astype(dtype)
    acc = lax.dot_general(xq, w_ref[...], (((1,), (1,)), ((), ())),
                          preferred_element_type=jnp.float32)
    out = acc * (x_scale * ws_ref[...]) + b_ref[...]
    o_ref[...] = _ACTS[act](out).astype(o_ref.dtype)


def fp8_matmul(x, w_q, w_scale, x_scale, bias=None, act=None, fmt="e4m3",
               block_m=None, block_n=None, interpret=False):
    """fp8×fp8 variant of :func:`quantized_matmul`.

    w_q: (N, K) in the chosen fp8 format (per-output-channel scaled so
    each row uses the format's full range); accumulation is fp32 on the
    MXU. Same tiling/padding story as the int8 kernel.
    """
    if fmt not in FP8_FORMATS:
        raise ValueError(f"unknown fp8 format {fmt!r}; "
                         f"one of {sorted(FP8_FORMATS)}")
    if act not in _ACTS:
        raise ValueError(f"unsupported fused activation {act!r}")
    m, k = x.shape
    n = w_q.shape[0]
    if block_m is None or block_n is None:
        from ...autotune.kernels import resolve_blocks
        tb = resolve_blocks("fp8_matmul", (m, n, k))
        block_m = tb["block_m"] if block_m is None else block_m
        block_n = tb["block_n"] if block_n is None else block_n
    bm = min(block_m, _round_up(m, 32))
    bn = min(block_n, _round_up(n, 128))
    grid_m, grid_n = pl.cdiv(m, bm), pl.cdiv(n, bn)
    mp, np_, kp = grid_m * bm, grid_n * bn, _round_up(k, 128)
    xp = _pad2(x, mp, kp)
    wp = _pad2(w_q, np_, kp)
    wsp = _pad2(w_scale.astype(jnp.float32)[None, :], 1, np_)
    b = (jnp.zeros((n,), jnp.float32) if bias is None
         else bias.astype(jnp.float32))
    bp = _pad2(b[None, :], 1, np_)
    xs = jnp.asarray(x_scale, jnp.float32).reshape(1, 1)
    out = pl.pallas_call(
        functools.partial(_fp8_kernel, act=act, fmt=fmt),
        grid=(grid_m, grid_n),
        in_specs=[
            pl.BlockSpec((1, 1), lambda i, j: (0, 0)),
            pl.BlockSpec((bm, kp), lambda i, j: (i, 0)),
            pl.BlockSpec((bn, kp), lambda i, j: (j, 0)),
            pl.BlockSpec((1, bn), lambda i, j: (0, j)),
            pl.BlockSpec((1, bn), lambda i, j: (0, j)),
        ],
        out_specs=pl.BlockSpec((bm, bn), lambda i, j: (i, j)),
        out_shape=jax.ShapeDtypeStruct((mp, np_), jnp.float32),
        interpret=interpret,
    )(xs, xp, wp, wsp, bp)
    return out[:m, :n]

"""Pallas TPU kernels — the hot-op layer.

Reference parity: the roles of src/operator/contrib/transformer.cc (fused
attention), src/operator/fusion/ (RTC pointwise fusion) and the fused
optimizer kernels (src/operator/optimizer_op.cc) — everywhere the reference
hand-writes CUDA because compiler fusion isn't enough, we hand-write Pallas.
Everything else rides XLA fusion.
"""

"""Flash attention Pallas kernel (TPU).

Reference parity: src/operator/contrib/transformer.cc:675-828 — MXNet's
fastest attention path is interleaved cuBLAS batched matmuls that still
materialize the (seq, seq) score matrix in HBM. TPU-native design: one
Pallas kernel per (batch*head, q-block) grid cell streams K/V blocks through
VMEM with an online-softmax accumulator, so scores never hit HBM and the
matmuls stay on the MXU. Backward is a recompute VJP (flash-style: saves
only out + logsumexp residuals, rebuilds P per block).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = -1e30


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, block_k, seq_k,
                causal, scale, block_q):
    qi = pl.program_id(1)
    # keep MXU operands in the input dtype (bf16 on TPU): fp32 matmul
    # costs ~8x the MXU passes; accumulation is fp32 regardless via
    # preferred_element_type. Softmax math stays fp32.
    q = q_ref[0]                                      # (bq, d)
    bq, d = q.shape
    nk = pl.cdiv(seq_k, block_k)

    def body(j, carry):
        m_prev, l_prev, acc = carry
        k = k_ref[0, pl.ds(j * block_k, block_k), :]
        v = v_ref[0, pl.ds(j * block_k, block_k), :]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        k_pos = j * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (bq, block_k), 1)
        # dynamic-slice loads clamp at the array end, so a partial final
        # block would re-read earlier keys — mask beyond seq_k explicitly
        s = jnp.where(k_pos < seq_k, s, _NEG_INF)
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (bq, block_k), 0)
            s = jnp.where(q_pos >= k_pos, s, _NEG_INF)
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new)
        corr = jnp.exp(m_prev - m_new)
        l_new = corr * l_prev + jnp.sum(p, axis=-1, keepdims=True)
        acc = corr * acc + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return m_new, l_new, acc

    m0 = jnp.full((bq, 1), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((bq, 1), jnp.float32)
    acc0 = jnp.zeros((bq, d), jnp.float32)
    if causal:
        # only blocks with k_start <= q_end contribute
        nk_eff = jnp.minimum(nk, (qi + 1) * block_q // block_k
                             + (1 if block_q % block_k else 0) + 1)
        nk_eff = jnp.minimum(nk_eff, nk)
    else:
        nk_eff = nk
    m, l, acc = jax.lax.fori_loop(0, nk_eff, body, (m0, l0, acc0))
    l_safe = jnp.maximum(l, 1e-30)
    o_ref[0] = (acc / l_safe).astype(o_ref.dtype)
    lse_ref[0] = m + jnp.log(l_safe)


def _fwd(q, k, v, causal, scale, block_q, block_k, interpret):
    bh, sq, d = q.shape
    sk = k.shape[1]
    block_q = min(block_q, sq)
    block_k = min(block_k, sk)
    # pad to block multiples: in-kernel pl.ds loads clamp at the array end,
    # which would silently misalign a partial final block; padded keys are
    # masked out via seq_k inside the kernel, padded queries sliced off below
    sq_pad = -sq % block_q
    sk_pad = -sk % block_k
    if sq_pad:
        q = jnp.pad(q, ((0, 0), (0, sq_pad), (0, 0)))
    if sk_pad:
        k = jnp.pad(k, ((0, 0), (0, sk_pad), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, sk_pad), (0, 0)))
    sq_full, sk_full = sq + sq_pad, sk + sk_pad
    grid = (bh, pl.cdiv(sq_full, block_q))
    kernel = functools.partial(
        _fwd_kernel, block_k=block_k, seq_k=sk, causal=causal, scale=scale,
        block_q=block_q)
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((1, sk_full, d), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((1, sk_full, d), lambda i, j: (i, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((1, block_q, 1), lambda i, j: (i, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, sq_full, d), q.dtype),
            jax.ShapeDtypeStruct((bh, sq_full, 1), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v)
    if sq_pad:
        out = out[:, :sq]
        lse = lse[:, :sq]
    return out, lse


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8, 9))
def _flash(q, k, v, causal, scale, block_q, block_k, bwd_block_q,
           bwd_block_k, interpret):
    out, _ = _fwd(q, k, v, causal, scale, block_q, block_k, interpret)
    return out


def _flash_fwd(q, k, v, causal, scale, block_q, block_k, bwd_block_q,
               bwd_block_k, interpret):
    out, lse = _fwd(q, k, v, causal, scale, block_q, block_k, interpret)
    return out, (q, k, v, out, lse)


def _bwd_block(q, do, lse, delta, kb, vb, q0, k0, seq_q, seq_k, causal,
               scale):
    """Shared recompute for one (q-block, k-block) tile: returns (p, ds).

    p = exp(s - lse) rebuilt from saved logsumexp; ds = p*(dp - delta)*scale
    (standard flash-attention backward tile math). MXU operands stay in
    the input dtype with fp32 accumulation; only the softmax algebra is
    fp32.
    """
    bq, bk = q.shape[0], kb.shape[0]
    s = jax.lax.dot_general(q, kb, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * scale
    q_pos = q0 + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
    k_pos = k0 + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
    valid = (q_pos < seq_q) & (k_pos < seq_k)
    if causal:
        valid &= q_pos >= k_pos
    s = jnp.where(valid, s, _NEG_INF)
    p = jnp.where(valid, jnp.exp(s - lse), 0.0)
    dp = jax.lax.dot_general(do, vb, (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
    ds = p * (dp - delta) * scale
    return p, ds


def _bwd_dkv_kernel(q_ref, do_ref, lse_ref, delta_ref, k_ref, v_ref,
                    dk_ref, dv_ref, *, block_q, block_k, seq_q, seq_k,
                    causal, scale):
    """dK/dV for one k-block, accumulated over sequential q-block steps
    (grid (bh, nk, nq): last axis revisits the same output block)."""
    j, qi = pl.program_id(1), pl.program_id(2)

    @pl.when(qi == 0)
    def _init():
        dk_ref[...] = jnp.zeros_like(dk_ref)
        dv_ref[...] = jnp.zeros_like(dv_ref)

    def _compute():
        q = q_ref[0]
        do = do_ref[0]
        lse, delta = lse_ref[0], delta_ref[0]
        kb = k_ref[0]
        vb = v_ref[0]
        p, ds = _bwd_block(q, do, lse, delta, kb, vb, qi * block_q,
                           j * block_k, seq_q, seq_k, causal, scale)
        dv_ref[0] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dk_ref[0] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    if causal:
        # the tile is all-masked when every q_pos < the k block start
        pl.when((qi + 1) * block_q - 1 >= j * block_k)(_compute)
    else:
        _compute()


def _bwd_dq_kernel(q_ref, do_ref, lse_ref, delta_ref, k_ref, v_ref, dq_ref,
                   *, block_q, block_k, seq_q, seq_k, causal, scale):
    """dQ for one q-block, accumulated over sequential k-block steps."""
    qi, j = pl.program_id(1), pl.program_id(2)

    @pl.when(j == 0)
    def _init():
        dq_ref[...] = jnp.zeros_like(dq_ref)

    def _compute():
        q = q_ref[0]
        do = do_ref[0]
        lse, delta = lse_ref[0], delta_ref[0]
        kb = k_ref[0]
        vb = v_ref[0]
        _, ds = _bwd_block(q, do, lse, delta, kb, vb, qi * block_q,
                           j * block_k, seq_q, seq_k, causal, scale)
        dq_ref[0] += jax.lax.dot_general(
            ds.astype(kb.dtype), kb, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    if causal:
        pl.when((qi + 1) * block_q - 1 >= j * block_k)(_compute)
    else:
        _compute()


def _flash_bwd(causal, scale, block_q, block_k, bwd_block_q, bwd_block_k,
               interpret, res, do):
    """Blocked Pallas backward (flash-style residuals: out + logsumexp).

    Memory is O(seq): P is rebuilt per (q-block, k-block) tile in VMEM from
    the saved lse, never materialized in HBM — the training-side completion
    of the forward kernel's claim (round-1 VJP materialized (s, s) scores).
    Tiles independently of the forward (bwd_block_q/bwd_block_k): the
    backward holds ~2x the forward's accumulators per tile, so its tuned
    optimum is usually smaller.
    """
    q, k, v, out, lse = res
    bh, sq, d = q.shape
    sk = k.shape[1]
    bq = min(bwd_block_q, sq)
    bk = min(bwd_block_k, sk)
    delta = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1, keepdims=True)

    sq_pad, sk_pad = -sq % bq, -sk % bk
    if sq_pad:
        pad = ((0, 0), (0, sq_pad), (0, 0))
        q, do = jnp.pad(q, pad), jnp.pad(do, pad)
        lse, delta = (jnp.pad(lse, ((0, 0), (0, sq_pad), (0, 0))),
                      jnp.pad(delta, ((0, 0), (0, sq_pad), (0, 0))))
    if sk_pad:
        pad = ((0, 0), (0, sk_pad), (0, 0))
        k, v = jnp.pad(k, pad), jnp.pad(v, pad)
    sq_full, sk_full = sq + sq_pad, sk + sk_pad
    nq, nk = sq_full // bq, sk_full // bk

    q_spec = pl.BlockSpec((1, bq, d), lambda i, a, b: (i, a, 0))
    r_spec = pl.BlockSpec((1, bq, 1), lambda i, a, b: (i, a, 0))
    k_spec = pl.BlockSpec((1, bk, d), lambda i, a, b: (i, b, 0))

    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, block_q=bq, block_k=bk,
                          seq_q=sq, seq_k=sk, causal=causal, scale=scale),
        grid=(bh, nk, nq),
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda i, b, a: (i, a, 0)),
            pl.BlockSpec((1, bq, d), lambda i, b, a: (i, a, 0)),
            pl.BlockSpec((1, bq, 1), lambda i, b, a: (i, a, 0)),
            pl.BlockSpec((1, bq, 1), lambda i, b, a: (i, a, 0)),
            pl.BlockSpec((1, bk, d), lambda i, b, a: (i, b, 0)),
            pl.BlockSpec((1, bk, d), lambda i, b, a: (i, b, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, bk, d), lambda i, b, a: (i, b, 0)),
            pl.BlockSpec((1, bk, d), lambda i, b, a: (i, b, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, sk_full, d), jnp.float32),
            jax.ShapeDtypeStruct((bh, sk_full, d), jnp.float32),
        ],
        interpret=interpret,
    )(q, do, lse, delta, k, v)

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, block_q=bq, block_k=bk,
                          seq_q=sq, seq_k=sk, causal=causal, scale=scale),
        grid=(bh, nq, nk),
        in_specs=[q_spec, q_spec, r_spec, r_spec, k_spec, k_spec],
        out_specs=q_spec,
        out_shape=jax.ShapeDtypeStruct((bh, sq_full, d), jnp.float32),
        interpret=interpret,
    )(q, do, lse, delta, k, v)

    if sq_pad:
        dq = dq[:, :sq]
    if sk_pad:
        dk, dv = dk[:, :sk], dv[:, :sk]
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(q, k, v, causal=False, scale=None, block_q=None,
                    block_k=None, bwd_block_q=None, bwd_block_k=None,
                    interpret=False):
    """Multi-head attention, scores never materialized in HBM.

    q: (batch, heads, seq_q, head_dim); k/v: (batch, heads, seq_k, head_dim).
    Returns (batch, heads, seq_q, head_dim).

    Block shapes default to ``mx.autotune.resolve_blocks`` — the tuned
    winner for this (seq_q, seq_k, head_dim) bucket when one is loaded,
    else the per-device static table (CPU row keeps the historical
    1024/512).  The backward tiles independently via bwd_block_q /
    bwd_block_k.  Explicit values always win.
    """
    b, h, sq, d = q.shape
    sk = k.shape[2]
    if scale is None:
        scale = 1.0 / (d ** 0.5)
    if block_q is None or block_k is None:
        from ...autotune.kernels import resolve_blocks
        fb = resolve_blocks("flash_attention", (sq, sk, d))
        block_q = fb["block_q"] if block_q is None else block_q
        block_k = fb["block_k"] if block_k is None else block_k
    if bwd_block_q is None or bwd_block_k is None:
        from ...autotune.kernels import resolve_blocks
        bb = resolve_blocks("flash_attention_bwd", (sq, sk, d))
        bwd_block_q = bb["block_q"] if bwd_block_q is None else bwd_block_q
        bwd_block_k = bb["block_k"] if bwd_block_k is None else bwd_block_k
    qr = q.reshape(b * h, sq, d)
    kr = k.reshape(b * h, sk, d)
    vr = v.reshape(b * h, sk, d)
    # TPU lanes are 128 wide: a 64-dim head halves every load/store and
    # forces relayouts. Zero-pad head_dim to the lane width — zeros add
    # nothing to q·k^T and the padded tail of out is exactly zero.
    d_pad = -d % 128 if d < 128 else 0
    if d_pad:
        pad = ((0, 0), (0, 0), (0, d_pad))
        qr, kr, vr = (jnp.pad(qr, pad), jnp.pad(kr, pad), jnp.pad(vr, pad))
    out = _flash(qr, kr, vr, causal, scale, block_q, block_k, bwd_block_q,
                 bwd_block_k, interpret)
    if d_pad:
        out = out[..., :d]
    return out.reshape(b, h, sq, d)

"""Bounding-box ops.

Reference parity: src/operator/contrib/bounding_box.cc (_contrib_box_iou,
_contrib_box_nms, _contrib_box_encode, _contrib_box_decode,
_contrib_bipartite_matching) — the op layer under the reference's
detection models and gluon/contrib/data/vision bbox transforms.

TPU-native design: every op is static-shaped and jit/vmap-friendly.  NMS
returns the reference's in-place convention (suppressed boxes keep their
slot with score -1) instead of a data-dependent-size output, which is
exactly what maps onto XLA: an O(N^2) IoU matrix plus a
``lax.fori_loop`` greedy pass over sorted candidates.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..numpy.multiarray import _invoke

__all__ = ["box_iou", "box_nms", "box_encode", "box_decode",
           "bipartite_matching"]


def _corner(boxes, fmt):
    """-> (x1, y1, x2, y2)."""
    if fmt == "corner":
        return boxes
    # center: (cx, cy, w, h)
    cx, cy, w, h = jnp.split(boxes, 4, axis=-1)
    return jnp.concatenate(
        [cx - w / 2, cy - h / 2, cx + w / 2, cy + h / 2], axis=-1)


def _iou_impl(lhs, rhs):
    """(..., N, 4) x (..., M, 4) corner boxes -> (..., N, M) IoU."""
    lt = jnp.maximum(lhs[..., :, None, :2], rhs[..., None, :, :2])
    rb = jnp.minimum(lhs[..., :, None, 2:], rhs[..., None, :, 2:])
    wh = jnp.maximum(rb - lt, 0)
    inter = wh[..., 0] * wh[..., 1]
    area_l = jnp.prod(jnp.maximum(lhs[..., 2:] - lhs[..., :2], 0), axis=-1)
    area_r = jnp.prod(jnp.maximum(rhs[..., 2:] - rhs[..., :2], 0), axis=-1)
    union = area_l[..., :, None] + area_r[..., None, :] - inter
    return jnp.where(union > 0, inter / union, 0.0)


def box_iou(lhs, rhs, format="corner"):  # noqa: A002 - reference kwarg name
    """Pairwise IoU (reference: _contrib_box_iou)."""
    def fn(a, b):
        return _iou_impl(_corner(a, format), _corner(b, format))
    return _invoke(fn, (lhs, rhs), name="box_iou")


def _nms_one(data, overlap_thresh, valid_thresh, topk, coord_start,
             score_index, id_index, force_suppress, in_format):
    """NMS over one (N, K) box set, matching the reference output
    convention (src/operator/contrib/bounding_box-inl.h BoxNMSForward):
    rows sorted by descending score, suppressed/invalid rows entirely
    filled with -1."""
    n = data.shape[0]
    scores = data[:, score_index]
    valid = scores > valid_thresh
    order = jnp.argsort(-jnp.where(valid, scores, -jnp.inf))
    sorted_data = data[order]
    boxes = _corner(sorted_data[:, coord_start:coord_start + 4], in_format)
    iou = _iou_impl(boxes, boxes)
    if id_index >= 0 and not force_suppress:
        ids = sorted_data[:, id_index]
        same = ids[:, None] == ids[None, :]
        iou = jnp.where(same, iou, 0.0)
    valid_sorted = valid[order]
    if topk > 0:
        valid_sorted = valid_sorted & (jnp.arange(n) < topk)

    def body(i, keep):
        # suppress j>i overlapping box i, if box i itself is kept
        sup = (iou[i] > overlap_thresh) & (jnp.arange(n) > i) & keep[i]
        return keep & ~sup

    keep = lax.fori_loop(0, n, body, valid_sorted)
    return jnp.where(keep[:, None], sorted_data, -1.0)


def box_nms(data, overlap_thresh=0.5, valid_thresh=0.0, topk=-1,
            coord_start=2, score_index=1, id_index=-1,
            force_suppress=False, in_format="corner"):
    """Non-maximum suppression (reference: _contrib_box_nms).

    data: (..., N, K) where each row holds [.., score, .., x1,y1,x2,y2 ..]
    per ``score_index``/``coord_start``.  Output follows the reference:
    rows sorted by descending score with suppressed/invalid rows filled
    with -1 (static output shape — TPU/jit friendly).
    """
    def fn(d):
        flat = d.reshape((-1,) + d.shape[-2:])
        out = jax.vmap(lambda one: _nms_one(
            one, overlap_thresh, valid_thresh, topk, coord_start,
            score_index, id_index, force_suppress, in_format))(flat)
        return out.reshape(d.shape)
    return _invoke(fn, (data,), name="box_nms")


def box_encode(samples, matches, anchors, refs, means=(0., 0., 0., 0.),
               stds=(0.1, 0.1, 0.2, 0.2)):
    """SSD-style target encoding (reference: _contrib_box_encode):
    corner anchors/refs -> normalized (dx, dy, dw, dh) targets + masks.

    samples: (B, N) in {+1 pos, -1 neg, 0 ignore}; matches: (B, N)
    indices into refs; anchors (B, N, 4), refs (B, M, 4) corner format.
    """
    def fn(s, m, a, r):
        ref = jnp.take_along_axis(r, m[..., None].astype(jnp.int32), axis=1)
        ax1, ay1, ax2, ay2 = jnp.split(a, 4, -1)
        rx1, ry1, rx2, ry2 = jnp.split(ref, 4, -1)
        aw, ah = ax2 - ax1, ay2 - ay1
        acx, acy = ax1 + aw / 2, ay1 + ah / 2
        rw, rh = rx2 - rx1, ry2 - ry1
        rcx, rcy = rx1 + rw / 2, ry1 + rh / 2
        t = jnp.concatenate([
            ((rcx - acx) / aw - means[0]) / stds[0],
            ((rcy - acy) / ah - means[1]) / stds[1],
            (jnp.log(rw / aw) - means[2]) / stds[2],
            (jnp.log(rh / ah) - means[3]) / stds[3]], axis=-1)
        mask = (s > 0.5)[..., None].astype(t.dtype) * jnp.ones_like(t)
        return jnp.where(mask > 0, t, 0.0), mask
    return _invoke(fn, (samples, matches, anchors, refs), name="box_encode")


def box_decode(data, anchors, std0=0.1, std1=0.1, std2=0.2, std3=0.2,
               clip=-1.0, format="center"):  # noqa: A002
    """Decode (dx,dy,dw,dh) predictions against anchors (reference:
    _contrib_box_decode); anchors given in `format`, output corner."""
    def fn(d, a):
        if format == "corner":
            x1, y1, x2, y2 = jnp.split(a, 4, -1)
            aw, ah = x2 - x1, y2 - y1
            acx, acy = x1 + aw / 2, y1 + ah / 2
        else:
            acx, acy, aw, ah = jnp.split(a, 4, -1)
        dx, dy, dw, dh = jnp.split(d, 4, -1)
        cx = dx * std0 * aw + acx
        cy = dy * std1 * ah + acy
        # the reference clips the scaled log-delta BEFORE exp
        # (bounding_box-inl.h BoxDecode; GluonCV NormalizedBoxCenterDecoder);
        # clip <= 0 means no clipping at all
        dw_s, dh_s = dw * std2, dh * std3
        if clip > 0:
            dw_s = jnp.minimum(dw_s, clip)
            dh_s = jnp.minimum(dh_s, clip)
        w = jnp.exp(dw_s) * aw
        h = jnp.exp(dh_s) * ah
        return jnp.concatenate(
            [cx - w / 2, cy - h / 2, cx + w / 2, cy + h / 2], axis=-1)
    return _invoke(fn, (data, anchors), name="box_decode")


def bipartite_matching(data, threshold=1e-12, is_ascend=False, topk=-1):
    """Greedy bipartite matching on a (..., N, M) affinity matrix
    (reference: _contrib_bipartite_matching): each round picks the global
    best pair, removing its row and column.  Returns (row_match, col_match)
    where row_match[i] = matched column or -1.
    """
    def one(mat):
        n, m = mat.shape
        k = min(n, m) if topk <= 0 else min(topk, n, m)
        big = jnp.inf if is_ascend else -jnp.inf

        def body(_, carry):
            work, rows, cols = carry
            flat = jnp.argmin(work) if is_ascend else jnp.argmax(work)
            i, j = flat // m, flat % m
            val = work[i, j]
            good = (val < threshold) if is_ascend else (val > threshold)
            rows = jnp.where(good, rows.at[i].set(j.astype(jnp.float32)),
                             rows)
            cols = jnp.where(good, cols.at[j].set(i.astype(jnp.float32)),
                             cols)
            work = work.at[i, :].set(big)
            work = work.at[:, j].set(big)
            return work, rows, cols

        rows = jnp.full((n,), -1.0)
        cols = jnp.full((m,), -1.0)
        _, rows, cols = lax.fori_loop(0, k, body, (mat, rows, cols))
        return rows, cols

    def fn(d):
        flat = d.reshape((-1,) + d.shape[-2:])
        rows, cols = jax.vmap(one)(flat)
        return (rows.reshape(d.shape[:-1]),
                cols.reshape(d.shape[:-2] + (d.shape[-1],)))
    return _invoke(fn, (data,), name="bipartite_matching")

"""Fused conv3x3 + BatchNorm + ReLU backward — Pallas TPU mega-kernel.

Round-3 profiling (ROUND3_NOTES.md §1) localized the ResNet-50 training
wall: backward convs sit AT the HBM roofline because the standard
decomposition reads the conv-output cotangent dy three times (BN-backward
reductions, dgrad, wgrad) and materializes it once. This kernel changes the
decomposition for the hot 3x3 / stride-1 / SAME blocks:

  XLA baseline per layer (big-tensor passes):
     stats:  R(da) R(y)            (fused dz + reductions)
     dy:     R(da) R(y) W(dy)
     dgrad:  R(dy)         W(dx)
     wgrad:  R(dy) R(x)    W(dw)       => 7 reads + 2 big writes
  here:
     stats:  R(da) R(y)            (XLA, one fused pass)
     kernel: R(da) R(y) R(x) W(dx)     (dy recomputed in VMEM, never
                                         materialized; dgrad + wgrad both
                                         consume the same VMEM tiles)
                                        => 5 reads + 1 big write  (~33% less)

Layout: NHWC with C on lanes (MXU-native). The convolutions become 9
shifted (M, O) x (O, C) / (C, M) x (M, O) MXU dots over spatially
zero-padded VMEM scratch — the standard Pallas conv formulation
(pallas_guide.md: Grid/BlockSpec + scratch patterns).

Reference parity: replaces the backward of src/operator/nn/convolution.cc +
batch_norm.cc + activation.cc for this shape class; forward is unchanged
(XLA's conv is already MXU-optimal there).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _bwd_kernel(vec_ref, da_ref, y_ref, x_ref, wf_ref, dx_ref, dw_ref,
                dyp_ref, xp_ref, *, H, W, C, O, NB):
    """One grid step: NB images. Recompute dy in VMEM, emit dx and
    accumulate dw.

    vec: (8, O) f32 rows = [mu, inv, gamma, beta, c1, c2, s1, 0]
    da/y: (NB, H, W, O); x: (NB, H, W, C); wf: (9*O, C) flipped weights
    dx: (NB, H, W, C); dw out: (9*C, O) f32, constant index map — the block
    stays VMEM-resident across the sequential grid and is accumulated in
    place (standard Pallas reduction pattern).
    scratch: dyp (NB, H+2, W+2, O), xp (NB, H+2, W+2, C).
    """
    step = pl.program_id(0)
    mu = vec_ref[0, :]
    inv = vec_ref[1, :]
    gamma = vec_ref[2, :]
    beta = vec_ref[3, :]
    c1 = vec_ref[4, :]
    c2 = vec_ref[5, :]
    s1 = vec_ref[6, :]

    da = da_ref[:].astype(jnp.float32)
    y = y_ref[:].astype(jnp.float32)
    xhat = (y - mu) * inv
    mask = (gamma * xhat + beta) > 0.0
    dz = jnp.where(mask, da, 0.0)
    dy = (s1 * (dz - c1 - xhat * c2)).astype(da_ref.dtype)

    # zero-padded copies in VMEM (SAME padding for both convolutions)
    dyp_ref[:] = jnp.zeros_like(dyp_ref)
    xp_ref[:] = jnp.zeros_like(xp_ref)
    dyp_ref[:, 1:H + 1, 1:W + 1, :] = dy
    xp_ref[:, 1:H + 1, 1:W + 1, :] = x_ref[:]

    @pl.when(step == 0)
    def _init():
        dw_ref[:] = jnp.zeros_like(dw_ref)

    M = NB * H * W
    acc = jnp.zeros((M, C), jnp.float32)
    dyf = dy.reshape(M, O)
    for kh in range(3):
        for kw in range(3):
            k = kh * 3 + kw
            # dgrad: dx = sum_k shift_k(dy) @ wflip_k   ((M,O) x (O,C))
            dsh = dyp_ref[:, kh:kh + H, kw:kw + W, :].reshape(M, O)
            acc += jax.lax.dot_general(
                dsh, wf_ref[k * O:(k + 1) * O, :],
                (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            # wgrad: dw_k = shift_k(x)^T @ dy            ((C,M) x (M,O))
            xsh = xp_ref[:, kh:kh + H, kw:kw + W, :].reshape(M, C)
            dw_ref[k * C:(k + 1) * C, :] += jax.lax.dot_general(
                xsh, dyf, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
    dx_ref[:] = acc.reshape(NB, H, W, C).astype(dx_ref.dtype)


def fused_conv3x3_bn_relu_bwd(da, x, y, w, gamma, beta, mean, var,
                              eps=1e-5, interpret=False):
    """Backward of relu(bn(conv3x3_s1_same(x, w))) through batch statistics.

    da, x, y: (N, H, W, C_in/out) NHWC; w: (3, 3, C, O) HWIO.
    Returns (dx, dw, dgamma, dbeta). dgamma/dbeta are the BN parameter
    grads; dx/dw come from the Pallas kernel with dy recomputed in VMEM.
    """
    N, H, W, O = da.shape
    C = x.shape[-1]
    M = N * H * W

    # ---- stats pass (XLA: one fused read of da, y) -----------------------
    inv = jax.lax.rsqrt(var.astype(jnp.float32) + eps)
    daf = da.astype(jnp.float32)
    yf = y.astype(jnp.float32)
    xhat = (yf - mean.astype(jnp.float32)) * inv
    mask = (gamma.astype(jnp.float32) * xhat + beta.astype(jnp.float32)) > 0
    dz = jnp.where(mask, daf, 0.0)
    dbeta = jnp.sum(dz, axis=(0, 1, 2))
    dgamma = jnp.sum(dz * xhat, axis=(0, 1, 2))

    gf = gamma.astype(jnp.float32)
    vec = jnp.stack([
        mean.astype(jnp.float32), inv, gf, beta.astype(jnp.float32),
        dbeta / M, dgamma / M, gf * inv,
        jnp.zeros_like(inv)])                                  # (8, O)

    # flipped weights for dgrad: wf[kh,kw] = w[2-kh, 2-kw].T  (O, C)
    wf = jnp.flip(w, axis=(0, 1)).transpose(0, 1, 3, 2).reshape(9 * O, C)

    # pick NB so each grid step has >=256 spatial rows for the MXU
    NB = 1
    while NB < N and NB * H * W < 256:
        NB *= 2
    while N % NB:
        NB //= 2
    grid = N // NB

    kernel = functools.partial(_bwd_kernel, H=H, W=W, C=C, O=O, NB=NB)
    dx, dw9 = pl.pallas_call(
        kernel,
        grid=(grid,),
        in_specs=[
            pl.BlockSpec((8, O), lambda i: (0, 0)),
            pl.BlockSpec((NB, H, W, O), lambda i: (i, 0, 0, 0)),
            pl.BlockSpec((NB, H, W, O), lambda i: (i, 0, 0, 0)),
            pl.BlockSpec((NB, H, W, C), lambda i: (i, 0, 0, 0)),
            pl.BlockSpec((9 * O, C), lambda i: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((NB, H, W, C), lambda i: (i, 0, 0, 0)),
            pl.BlockSpec((9 * C, O), lambda i: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((N, H, W, C), x.dtype),
            jax.ShapeDtypeStruct((9 * C, O), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((NB, H + 2, W + 2, O), da.dtype),
            pltpu.VMEM((NB, H + 2, W + 2, C), x.dtype),
        ],
        interpret=interpret,
    )(vec, da, y, x, wf)

    dw = dw9.reshape(3, 3, C, O).astype(w.dtype)
    return dx, dw, dgamma.astype(gamma.dtype), dbeta.astype(beta.dtype)


def conv3x3_bn_relu_ref(x, w, gamma, beta, eps=1e-5):
    """Reference forward (training-mode BN over batch statistics), used by
    the oracle tests and as the residual-producing forward."""
    y = jax.lax.conv_general_dilated(
        x, w, (1, 1), "SAME", dimension_numbers=("NHWC", "HWIO", "NHWC"))
    yf = y.astype(jnp.float32)
    mean = jnp.mean(yf, axis=(0, 1, 2))
    var = jnp.mean(jnp.square(yf - mean), axis=(0, 1, 2))
    inv = jax.lax.rsqrt(var + eps)
    z = (yf - mean) * inv * gamma.astype(jnp.float32) \
        + beta.astype(jnp.float32)
    return jax.nn.relu(z).astype(x.dtype), y, mean, var


# ---------------------------------------------------------------------------
# custom-VJP composite: forward stays XLA, backward is the Pallas kernel
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5))
def fused_cbr_train(x, w, gamma, beta, eps=1e-5, interpret=False):
    """relu(bn_train(conv3x3_s1_same(x, w))) over NHWC.

    Returns (activation, batch_mean, batch_var); mean/var feed the
    running-stat update (stop-gradient there — their cotangents are
    discarded in the bwd rule, matching the reference's BN aux semantics).
    """
    a, _y, mean, var = conv3x3_bn_relu_ref(x, w, gamma, beta, eps)
    return a, mean, var


def _fused_cbr_fwd(x, w, gamma, beta, eps, interpret):
    a, y, mean, var = conv3x3_bn_relu_ref(x, w, gamma, beta, eps)
    return (a, mean, var), (x, w, gamma, beta, y, mean, var)


def _fused_cbr_bwd(eps, interpret, res, cts):
    da, _dmean, _dvar = cts   # mean/var only feed stop-gradient stat updates
    x, w, gamma, beta, y, mean, var = res
    dx, dw, dgamma, dbeta = fused_conv3x3_bn_relu_bwd(
        da, x, y, w, gamma, beta, mean, var, eps=eps, interpret=interpret)
    return dx, dw, dgamma, dbeta


fused_cbr_train.defvjp(_fused_cbr_fwd, _fused_cbr_bwd)


def eligible(kernel, strides, padding, dilation, groups, use_bias):
    """Shape class the kernel covers: 3x3, stride 1, SAME, dense, no bias."""
    return (tuple(kernel) == (3, 3) and tuple(strides) == (1, 1)
            and tuple(padding) == (1, 1) and tuple(dilation) == (1, 1)
            and groups == 1 and not use_bias)


def fits_vmem(n, h, w, c, o, itemsize=2, budget=12 * 2 ** 20):
    """Conservative VMEM estimate for one grid step (incl. the double
    buffering Pallas adds for HBM<->VMEM pipelining). Over-budget shapes
    (e.g. the 512-channel 7x7 stage, dominated by the 9*C*O f32 dw block)
    fall back to XLA — which handles that compute-dense stage well; the
    kernel's bandwidth win lives in the high-spatial stages anyway."""
    nb = 1
    while nb < n and nb * h * w < 256:
        nb *= 2
    while n % nb:
        nb //= 2
    m = nb * h * w
    blocks = nb * h * w * (2 * o + 2 * c) * itemsize      # da, y, x, dx
    halo = nb * (h + 2) * (w + 2) * (o + c) * itemsize    # dyp, xp scratch
    weights = 9 * o * c * itemsize + 9 * c * o * 4        # wf + dw (f32)
    live = m * c * 4 + m * o * itemsize                   # acc + dy flat
    return 2 * blocks + halo + weights + live <= budget

"""Operator registry.

Reference parity: NNVM_REGISTER_OP + include/mxnet/op_attr_types.h:217-315
(FCompute/FInferShape/FInferType/FGradient attributes). TPU-native: an op is
a jnp/lax/Pallas callable; shape/dtype inference is jax.eval_shape (no
hand-written inference rules needed), gradients come from jax AD. The
registry exists for: op listing/introspection (mx.np coverage reports),
custom-op registration (mx.library extensions), and kernel substitution
(e.g. swapping a Pallas flash-attention in for the jnp composition).
"""
from __future__ import annotations

import jax

from ..base import MXNetError


class OpInfo:
    __slots__ = ("name", "fn", "backward_fn", "doc", "source")

    def __init__(self, name, fn, backward_fn=None, doc="", source="builtin"):
        self.name = name
        self.fn = fn
        self.backward_fn = backward_fn
        self.doc = doc
        self.source = source


_ops = {}


def register(name, fn=None, backward_fn=None, doc="", source="custom"):
    """Register an operator; usable as decorator or call."""
    def _do(f):
        _ops[name] = OpInfo(name, f, backward_fn, doc or f.__doc__ or "",
                            source)
        return f
    if fn is not None:
        return _do(fn)
    return _do


def get(name):
    if name not in _ops:
        raise MXNetError(f"op {name!r} not registered")
    return _ops[name]


def list_ops():
    return sorted(_ops)


def infer_shape(name, *avals, **kwargs):
    """Shape/dtype inference via abstract evaluation (replaces the
    reference's per-op FInferShape/FInferType)."""
    op = get(name)
    out = jax.eval_shape(lambda *a: op.fn(*a, **kwargs), *avals)
    return jax.tree_util.tree_map(lambda s: (s.shape, s.dtype), out)

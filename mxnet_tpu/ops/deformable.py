"""Deformable convolution (DCN v1/v2) — TPU-native implementation.

Reference parity: src/operator/contrib/deformable_convolution.cc and
modulated_deformable_convolution.cc (CUDA deformable_im2col kernels), exposed
in Gluon via nn.DeformableConvolution / ModulatedDeformableConvolution
(python/mxnet/gluon/nn/conv_layers.py:1277,1501).

TPU-native design: the CUDA kernel walks output pixels one thread each and
bilinearly samples; here the whole sampling grid is built as dense index
tensors, the four bilinear corner reads are four batched gathers
(take_along_axis over a flattened H*W axis — XLA lowers this to a fast
dynamic-gather), and the kernel-position reduction becomes ONE einsum
(MXU matmul) over (C_in/groups * K). No scalar loops; fully jittable and
differentiable via JAX AD (the reference hand-writes the backward im2col).

Offset channel layout matches the reference's deformable_im2col: for
deformable group ``dg`` and kernel position ``k = i*kw + j``, channel
``2*(dg*K + k)`` is the y-offset and ``2*(dg*K + k) + 1`` the x-offset
(src/operator/contrib/nn/deformable_im2col.cuh). Mask channels (v2) are
``dg*K + k``. Out-of-bounds samples read as zero, like the reference.
"""
from __future__ import annotations

import jax.numpy as jnp


def _out_size(size, k, stride, pad, dilate):
    eff = dilate * (k - 1) + 1
    return (size + 2 * pad - eff) // stride + 1


def deformable_conv2d(x, offset, weight, bias=None, *, kernel, stride=(1, 1),
                      pad=(0, 0), dilate=(1, 1), num_group=1,
                      num_deformable_group=1, mask=None):
    """2-D deformable convolution on raw jnp arrays (NCHW).

    x: (N, C, H, W); offset: (N, 2*ndg*K, Ho, Wo);
    weight: (O, C//num_group, kh, kw); mask (v2): (N, ndg*K, Ho, Wo).
    """
    N, C, H, W = x.shape
    kh, kw = kernel
    K = kh * kw
    g, ndg = num_group, num_deformable_group
    Ho = _out_size(H, kh, stride[0], pad[0], dilate[0])
    Wo = _out_size(W, kw, stride[1], pad[1], dilate[1])
    dt = x.dtype

    # base sampling positions: (K, Ho, Wo)
    ky = (jnp.arange(kh) * dilate[0]).repeat(kw)            # (K,)
    kx = jnp.tile(jnp.arange(kw) * dilate[1], kh)           # (K,)
    oy = jnp.arange(Ho) * stride[0] - pad[0]                # (Ho,)
    ox = jnp.arange(Wo) * stride[1] - pad[1]                # (Wo,)
    base_y = ky[:, None, None] + oy[None, :, None]          # (K, Ho, 1)
    base_x = kx[:, None, None] + ox[None, None, :]          # (K, 1, Wo)

    off = offset.reshape(N, ndg, K, 2, Ho, Wo).astype(jnp.float32)
    y = base_y[None, None] + off[:, :, :, 0]                # (N, ndg, K, Ho, Wo)
    xx = base_x[None, None] + off[:, :, :, 1]

    y0 = jnp.floor(y)
    x0 = jnp.floor(xx)
    wy1 = (y - y0)[:, :, None]          # (N, ndg, 1, K, Ho, Wo)
    wx1 = (xx - x0)[:, :, None]
    wy0, wx0 = 1.0 - wy1, 1.0 - wx1

    xg = x.reshape(N, ndg, C // ndg, H * W)

    def corner(cy, cx):
        inside = ((cy >= 0) & (cy < H) & (cx >= 0) & (cx < W))
        idx = (jnp.clip(cy, 0, H - 1).astype(jnp.int32) * W
               + jnp.clip(cx, 0, W - 1).astype(jnp.int32))   # (N,ndg,K,Ho,Wo)
        flat = idx.reshape(N, ndg, 1, K * Ho * Wo)
        v = jnp.take_along_axis(xg, jnp.broadcast_to(
            flat, (N, ndg, C // ndg, K * Ho * Wo)), axis=-1)
        v = v.reshape(N, ndg, C // ndg, K, Ho, Wo)
        return v * inside[:, :, None].astype(dt)

    v00 = corner(y0, x0)
    v01 = corner(y0, x0 + 1)
    v10 = corner(y0 + 1, x0)
    v11 = corner(y0 + 1, x0 + 1)
    sampled = (v00 * (wy0 * wx0).astype(dt) + v01 * (wy0 * wx1).astype(dt)
               + v10 * (wy1 * wx0).astype(dt) + v11 * (wy1 * wx1).astype(dt))

    if mask is not None:
        m = mask.reshape(N, ndg, 1, K, Ho, Wo).astype(dt)
        sampled = sampled * m

    # contraction: (N, g, C/g, K, P) x (g, O/g, C/g, K) -> (N, g, O/g, P)
    O = weight.shape[0]
    sampled = sampled.reshape(N, g, C // g, K, Ho * Wo)
    w = weight.reshape(g, O // g, C // g, K).astype(dt)
    out = jnp.einsum("ngckp,gock->ngop", sampled, w,
                     preferred_element_type=jnp.float32)
    out = out.reshape(N, O, Ho, Wo).astype(dt)
    if bias is not None:
        out = out + bias.reshape(1, O, 1, 1).astype(dt)
    return out

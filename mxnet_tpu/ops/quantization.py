"""INT8 quantization ops.

Reference parity: src/operator/quantization/ (quantize_v2-inl.h,
dequantize-inl.h, quantized_fully_connected.cc, quantized_conv.cc, ~7.1k
LoC of CPU/GPU kernels).  TPU-native design: int8 tensors feed
``lax.dot_general`` / ``lax.conv_general_dilated`` with
``preferred_element_type=int32`` — XLA lowers these to the MXU's native
int8 matmul path — and the scale/zero-point arithmetic is plain jnp that
XLA fuses around the matmul.  The reference's `requantize` op and its
quantize/dequantize-elimination graph passes are subsumed by XLA fusion:
we always dequantize to fp32 after accumulation and let the compiler fuse
adjacent quantize(dequantize(x)) chains.

Quantization scheme: symmetric int8 (zero-point 0), per-tensor for
activations (calibrated range), per-output-channel for weights — the
scheme the reference uses for its int8 conv/FC path with
``MXNET_QUANTIZATION_*`` defaults.
"""
from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from .. import config as _config
from ..numpy.multiarray import _invoke

__all__ = ["quantize_v2", "dequantize", "quantized_fully_connected",
           "quantized_conv", "quantized_dense_fused", "quantized_conv_fused",
           "fp8_dense_fused"]

_INT8_MAX = 127.0

#: fused-epilogue activations jnp can express inside one traced op (the
#: Pallas kernel supports the same set — see ops/pallas/quant_matmul.py)
FUSED_ACTS = (None, "relu", "sigmoid", "tanh", "gelu")


def _apply_act(out, act):
    import jax
    if act is None:
        return out
    if act == "relu":
        return jnp.maximum(out, 0.0)
    if act == "sigmoid":
        return jax.nn.sigmoid(out)
    if act == "tanh":
        return jnp.tanh(out)
    if act == "gelu":
        return jax.nn.gelu(out)
    raise ValueError(f"activation {act!r} cannot be fused; "
                     f"supported: {FUSED_ACTS}")


def _route_fused():
    """(use_pallas, interpret) per the ``quantize.fused_matmul`` knob:
    'auto' = Pallas on TPU only, 'on' = Pallas everywhere (interpret
    off-TPU — the CI parity oracle), 'off' = the XLA dot_general chain."""
    mode = str(_config.get("quantize.fused_matmul")).lower()
    if mode == "off":
        return False, False
    import jax
    devs = jax.devices()
    on_tpu = bool(devs) and devs[0].platform in ("tpu", "axon")
    if mode == "on":
        return True, not on_tpu
    return on_tpu, False


def _scale_from_range(min_range, max_range):
    return jnp.maximum(jnp.abs(min_range), jnp.abs(max_range)) / _INT8_MAX


def quantize_v2(data, min_calib_range=None, max_calib_range=None,
                out_type="int8"):
    """float32 -> (int8, min_range, max_range).

    Reference: src/operator/quantization/quantize_v2-inl.h — when calib
    ranges are given they are used directly; otherwise the runtime min/max
    of `data` is used.  Symmetric: zero maps to zero.
    """
    if out_type != "int8":
        raise NotImplementedError("TPU path quantizes to int8 only")

    def fn(x):
        if min_calib_range is None or max_calib_range is None:
            mx_ = jnp.max(jnp.abs(x))
            mn, mx = -mx_, mx_
        else:
            mn = jnp.asarray(min_calib_range, jnp.float32)
            mx = jnp.asarray(max_calib_range, jnp.float32)
        scale = _scale_from_range(mn, mx)
        q = jnp.clip(jnp.round(x / scale), -_INT8_MAX, _INT8_MAX)
        return q.astype(jnp.int8), mn, mx

    return _invoke(fn, (data,), name="quantize_v2")


def dequantize(data, min_range, max_range, out_type="float32"):
    """int8 -> float32 (reference: dequantize-inl.h)."""
    def fn(q, mn, mx):
        return q.astype(jnp.float32) * _scale_from_range(mn, mx)
    return _invoke(fn, (data, min_range, max_range), name="dequantize")


def quantized_fully_connected(data, weight, x_scale, w_scale, bias=None,
                              flatten=True):
    """int8 x int8 -> fp32 dense layer.

    Reference: src/operator/quantization/quantized_fully_connected.cc.
    TPU-native signature: instead of the reference's 9-input
    (min/max per operand) form, scales are passed directly —
    ``x_scale`` scalar, ``w_scale`` per-output-channel (units,) — and the
    output is dequantized fp32 (accumulation in int32 on the MXU).
    """
    def fn(x, w, xs, ws, *rest):
        b = rest[0] if rest else None
        h = x.reshape(x.shape[0], -1) if flatten else x
        acc = lax.dot_general(h, w, (((h.ndim - 1,), (1,)), ((), ())),
                              preferred_element_type=jnp.int32)
        out = acc.astype(jnp.float32) * (jnp.asarray(xs, jnp.float32) * ws)
        if b is not None:
            out = out + b
        return out

    args = (data, weight, x_scale, w_scale)
    if bias is not None:
        args += (bias,)
    return _invoke(fn, args, name="quantized_fully_connected")


def quantized_conv(data, weight, x_scale, w_scale, bias=None, kernel=None,
                   stride=None, dilate=None, pad=None, num_filter=1,
                   num_group=1, layout="NCHW"):
    """int8 x int8 -> fp32 convolution.

    Reference: src/operator/quantization/quantized_conv.cc (cuDNN int8
    path, NHWC-only there; here any layout the fp conv supports).
    Accumulates int32 on the MXU, dequantizes with per-channel w_scale.
    """
    nd = data.ndim - 2
    spatial = "DHW"[3 - nd:]
    lhs_spec = layout
    rhs_spec = "OI" + spatial
    out_spec = layout
    strides = tuple(stride or (1,) * nd)
    dilation = tuple(dilate or (1,) * nd)
    padding = tuple((p, p) for p in (pad or (0,) * nd))
    c_axis = layout.index("C")

    def fn(x, w, xs, ws, *rest):
        b = rest[0] if rest else None
        dn = lax.conv_dimension_numbers(x.shape, w.shape,
                                        (lhs_spec, rhs_spec, out_spec))
        acc = lax.conv_general_dilated(
            x, w, window_strides=strides, padding=padding,
            rhs_dilation=dilation, dimension_numbers=dn,
            feature_group_count=num_group,
            preferred_element_type=jnp.int32)
        shape = [1] * acc.ndim
        shape[c_axis] = -1
        sc = jnp.asarray(xs, jnp.float32) * jnp.reshape(ws, shape)
        out = acc.astype(jnp.float32) * sc
        if b is not None:
            out = out + jnp.reshape(b, shape)
        return out

    args = (data, weight, x_scale, w_scale)
    if bias is not None:
        args += (bias,)
    return _invoke(fn, args, name="quantized_conv")


def quantized_dense_fused(data, weight, x_scale, w_scale, bias=None,
                          act=None, flatten=True):
    """Fused quantize -> int8 x int8 dot -> dequant+bias+act dense layer.

    One traced op end to end: the separate quantize_v2 /
    quantized_fully_connected pair costs an HBM round-trip for the int8
    activations between the two ops (BENCH_r05: int8 resnet50 *slower*
    than bf16).  Routing per ``quantize.fused_matmul``: the Pallas kernel
    (ops/pallas/quant_matmul.py) on TPU / when forced 'on' (interpret
    mode off-TPU), else the same ``lax.dot_general(preferred=int32)``
    expression as :func:`quantized_fully_connected` inside one jit so XLA
    fuses the chain.  ``weight`` is pre-quantized int8 (units, in_units),
    ``w_scale`` per-output-channel, ``x_scale`` the calibrated
    threshold / 127.
    """
    if act not in FUSED_ACTS:
        raise ValueError(f"activation {act!r} cannot be fused; "
                         f"supported: {FUSED_ACTS}")
    use_pallas, interpret = _route_fused()

    def fn(x, w, xs, ws, *rest):
        b = rest[0] if rest else None
        h = x.reshape(x.shape[0], -1) if flatten else x
        lead = h.shape[:-1]
        h2 = h.reshape(-1, h.shape[-1])
        if use_pallas:
            from .pallas.quant_matmul import quantized_matmul
            out = quantized_matmul(h2, w, ws, xs, bias=b, act=act,
                                   interpret=interpret)
        else:
            xs32 = jnp.asarray(xs, jnp.float32)
            xq = jnp.clip(jnp.round(h2 / xs32), -_INT8_MAX, _INT8_MAX
                          ).astype(jnp.int8)
            acc = lax.dot_general(xq, w, (((1,), (1,)), ((), ())),
                                  preferred_element_type=jnp.int32)
            out = acc.astype(jnp.float32) * (xs32 * ws)
            if b is not None:
                out = out + b
            out = _apply_act(out, act)
        return out.reshape(lead + (w.shape[0],))

    args = (data, weight, x_scale, w_scale)
    if bias is not None:
        args += (bias,)
    return _invoke(fn, args, name="quantized_dense_fused")


def fp8_dense_fused(data, weight, x_scale, w_scale, bias=None, act=None,
                    flatten=True, fmt=None):
    """fp8-activation variant of :func:`quantized_dense_fused`.

    ``weight`` is pre-cast to the fp8 format (per-output-channel scaled),
    accumulation is fp32.  Gated on device capability by the caller via
    :func:`mxnet_tpu.ops.pallas.quant_matmul.fp8_capable`; the fallback
    (fp8 operands into ``lax.dot_general`` with fp32 preferred type)
    runs anywhere XLA supports the dtype, including CPU.
    """
    if act not in FUSED_ACTS:
        raise ValueError(f"activation {act!r} cannot be fused; "
                         f"supported: {FUSED_ACTS}")
    fmt = fmt or _config.get("quantize.fp8_format")
    use_pallas, interpret = _route_fused()

    def fn(x, w, xs, ws, *rest):
        from .pallas.quant_matmul import FP8_FORMATS, fp8_matmul
        if fmt not in FP8_FORMATS:
            raise ValueError(f"unknown fp8 format {fmt!r}")
        b = rest[0] if rest else None
        h = x.reshape(x.shape[0], -1) if flatten else x
        lead = h.shape[:-1]
        h2 = h.reshape(-1, h.shape[-1])
        if use_pallas:
            out = fp8_matmul(h2, w, ws, xs, bias=b, act=act, fmt=fmt,
                             interpret=interpret)
        else:
            xs32 = jnp.asarray(xs, jnp.float32)
            xq = (h2.astype(jnp.float32) / xs32).astype(FP8_FORMATS[fmt][0])
            acc = lax.dot_general(xq, w, (((1,), (1,)), ((), ())),
                                  preferred_element_type=jnp.float32)
            out = acc * (xs32 * ws)
            if b is not None:
                out = out + b
            out = _apply_act(out, act)
        return out.reshape(lead + (w.shape[0],))

    args = (data, weight, x_scale, w_scale)
    if bias is not None:
        args += (bias,)
    return _invoke(fn, args, name="fp8_dense_fused")


def quantized_conv_fused(data, weight, x_scale, w_scale, bias=None,
                         act=None, kernel=None, stride=None, dilate=None,
                         pad=None, num_filter=1, num_group=1, layout="NCHW"):
    """Fused quantize -> int8 conv -> dequant+bias+act convolution.

    Same contract as :func:`quantized_conv` but the activation quantize
    and the epilogue live inside ONE traced op, so XLA keeps the int8
    activations in registers/VMEM instead of round-tripping them through
    HBM between quantize_v2 and the conv (there is no Pallas conv kernel;
    on TPU XLA's own int8 ``conv_general_dilated`` hits the MXU).
    """
    if act not in FUSED_ACTS:
        raise ValueError(f"activation {act!r} cannot be fused; "
                         f"supported: {FUSED_ACTS}")
    nd = data.ndim - 2
    spatial = "DHW"[3 - nd:]
    lhs_spec = layout
    rhs_spec = "OI" + spatial
    out_spec = layout
    strides = tuple(stride or (1,) * nd)
    dilation = tuple(dilate or (1,) * nd)
    padding = tuple((p, p) for p in (pad or (0,) * nd))
    c_axis = layout.index("C")

    def fn(x, w, xs, ws, *rest):
        b = rest[0] if rest else None
        xs32 = jnp.asarray(xs, jnp.float32)
        xq = jnp.clip(jnp.round(x / xs32), -_INT8_MAX, _INT8_MAX
                      ).astype(jnp.int8)
        dn = lax.conv_dimension_numbers(x.shape, w.shape,
                                        (lhs_spec, rhs_spec, out_spec))
        acc = lax.conv_general_dilated(
            xq, w, window_strides=strides, padding=padding,
            rhs_dilation=dilation, dimension_numbers=dn,
            feature_group_count=num_group,
            preferred_element_type=jnp.int32)
        shape = [1] * acc.ndim
        shape[c_axis] = -1
        sc = xs32 * jnp.reshape(ws, shape)
        out = acc.astype(jnp.float32) * sc
        if b is not None:
            out = out + jnp.reshape(b, shape)
        return _apply_act(out, act)

    args = (data, weight, x_scale, w_scale)
    if bias is not None:
        args += (bias,)
    return _invoke(fn, args, name="quantized_conv_fused")

"""INT8 quantization ops.

Reference parity: src/operator/quantization/ (quantize_v2-inl.h,
dequantize-inl.h, quantized_fully_connected.cc, quantized_conv.cc, ~7.1k
LoC of CPU/GPU kernels).  TPU-native design: int8 tensors feed
``lax.dot_general`` / ``lax.conv_general_dilated`` with
``preferred_element_type=int32`` — XLA lowers these to the MXU's native
int8 matmul path — and the scale/zero-point arithmetic is plain jnp that
XLA fuses around the matmul.  The reference's `requantize` op and its
quantize/dequantize-elimination graph passes are subsumed by XLA fusion:
we always dequantize to fp32 after accumulation and let the compiler fuse
adjacent quantize(dequantize(x)) chains.

Quantization scheme: symmetric int8 (zero-point 0), per-tensor for
activations (calibrated range), per-output-channel for weights — the
scheme the reference uses for its int8 conv/FC path with
``MXNET_QUANTIZATION_*`` defaults.
"""
from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from ..numpy.multiarray import _invoke

__all__ = ["quantize_v2", "dequantize", "quantized_fully_connected",
           "quantized_conv"]

_INT8_MAX = 127.0


def _scale_from_range(min_range, max_range):
    return jnp.maximum(jnp.abs(min_range), jnp.abs(max_range)) / _INT8_MAX


def quantize_v2(data, min_calib_range=None, max_calib_range=None,
                out_type="int8"):
    """float32 -> (int8, min_range, max_range).

    Reference: src/operator/quantization/quantize_v2-inl.h — when calib
    ranges are given they are used directly; otherwise the runtime min/max
    of `data` is used.  Symmetric: zero maps to zero.
    """
    if out_type != "int8":
        raise NotImplementedError("TPU path quantizes to int8 only")

    def fn(x):
        if min_calib_range is None or max_calib_range is None:
            mx_ = jnp.max(jnp.abs(x))
            mn, mx = -mx_, mx_
        else:
            mn = jnp.asarray(min_calib_range, jnp.float32)
            mx = jnp.asarray(max_calib_range, jnp.float32)
        scale = _scale_from_range(mn, mx)
        q = jnp.clip(jnp.round(x / scale), -_INT8_MAX, _INT8_MAX)
        return q.astype(jnp.int8), mn, mx

    return _invoke(fn, (data,), name="quantize_v2")


def dequantize(data, min_range, max_range, out_type="float32"):
    """int8 -> float32 (reference: dequantize-inl.h)."""
    def fn(q, mn, mx):
        return q.astype(jnp.float32) * _scale_from_range(mn, mx)
    return _invoke(fn, (data, min_range, max_range), name="dequantize")


def quantized_fully_connected(data, weight, x_scale, w_scale, bias=None,
                              flatten=True):
    """int8 x int8 -> fp32 dense layer.

    Reference: src/operator/quantization/quantized_fully_connected.cc.
    TPU-native signature: instead of the reference's 9-input
    (min/max per operand) form, scales are passed directly —
    ``x_scale`` scalar, ``w_scale`` per-output-channel (units,) — and the
    output is dequantized fp32 (accumulation in int32 on the MXU).
    """
    def fn(x, w, xs, ws, *rest):
        b = rest[0] if rest else None
        h = x.reshape(x.shape[0], -1) if flatten else x
        acc = lax.dot_general(h, w, (((h.ndim - 1,), (1,)), ((), ())),
                              preferred_element_type=jnp.int32)
        out = acc.astype(jnp.float32) * (jnp.asarray(xs, jnp.float32) * ws)
        if b is not None:
            out = out + b
        return out

    args = (data, weight, x_scale, w_scale)
    if bias is not None:
        args += (bias,)
    return _invoke(fn, args, name="quantized_fully_connected")


def quantized_conv(data, weight, x_scale, w_scale, bias=None, kernel=None,
                   stride=None, dilate=None, pad=None, num_filter=1,
                   num_group=1, layout="NCHW"):
    """int8 x int8 -> fp32 convolution.

    Reference: src/operator/quantization/quantized_conv.cc (cuDNN int8
    path, NHWC-only there; here any layout the fp conv supports).
    Accumulates int32 on the MXU, dequantizes with per-channel w_scale.
    """
    nd = data.ndim - 2
    spatial = "DHW"[3 - nd:]
    lhs_spec = layout
    rhs_spec = "OI" + spatial
    out_spec = layout
    strides = tuple(stride or (1,) * nd)
    dilation = tuple(dilate or (1,) * nd)
    padding = tuple((p, p) for p in (pad or (0,) * nd))
    c_axis = layout.index("C")

    def fn(x, w, xs, ws, *rest):
        b = rest[0] if rest else None
        dn = lax.conv_dimension_numbers(x.shape, w.shape,
                                        (lhs_spec, rhs_spec, out_spec))
        acc = lax.conv_general_dilated(
            x, w, window_strides=strides, padding=padding,
            rhs_dilation=dilation, dimension_numbers=dn,
            feature_group_count=num_group,
            preferred_element_type=jnp.int32)
        shape = [1] * acc.ndim
        shape[c_axis] = -1
        sc = jnp.asarray(xs, jnp.float32) * jnp.reshape(ws, shape)
        out = acc.astype(jnp.float32) * sc
        if b is not None:
            out = out + jnp.reshape(b, shape)
        return out

    args = (data, weight, x_scale, w_scale)
    if bias is not None:
        args += (bias,)
    return _invoke(fn, args, name="quantized_conv")

"""Fused attention dispatch.

Reference parity: src/operator/contrib/transformer.cc:675-828 (interleaved
matmul attention ops, the reference's fastest attention path).

TPU-native design: a single multi_head_attention entry that routes to the
Pallas flash-attention kernel on TPU (ops/pallas/flash_attention.py) and to
an XLA dot_general composition elsewhere — the composition alone already
fuses well (softmax rides the MXU output), flash-attention additionally
avoids materializing the (seq, seq) scores in HBM for long sequences.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..numpy.multiarray import _invoke


def _reference_attention(q, k, v, heads, mask=None, causal=False, scale=None,
                         dropout_p=0.0):
    """(batch, seq, heads*dim) XLA composition."""
    b, sq, hd = q.shape
    sk = k.shape[1]
    d = hd // heads
    scale = scale if scale is not None else 1.0 / (d ** 0.5)
    qh = q.reshape(b, sq, heads, d).transpose(0, 2, 1, 3)
    kh = k.reshape(b, sk, heads, d).transpose(0, 2, 1, 3)
    vh = v.reshape(b, sk, heads, d).transpose(0, 2, 1, 3)
    scores = jnp.einsum("bhqd,bhkd->bhqk", qh, kh) * scale
    if causal:
        cm = jnp.tril(jnp.ones((sq, sk), dtype=bool))
        scores = jnp.where(cm, scores, -1e30)
    if mask is not None:
        scores = jnp.where(mask.astype(bool), scores, -1e30)
    att = jax.nn.softmax(scores.astype(jnp.float32), axis=-1).astype(q.dtype)
    if dropout_p:
        from .. import random as _random
        keep = 1.0 - dropout_p
        att = att * jax.random.bernoulli(
            _random._next_key(), keep, att.shape).astype(att.dtype) / keep
    out = jnp.einsum("bhqk,bhkd->bhqd", att, vh)
    return out.transpose(0, 2, 1, 3).reshape(b, sq, heads * d)


def _use_pallas():
    devs = jax.devices()
    return devs and devs[0].platform in ("tpu", "axon")


def multi_head_attention(query, key, value, heads, mask=None, dropout_p=0.0,
                         causal=False):
    """Fused MHA on (batch, seq, heads*dim) ndarrays. Attention-prob dropout
    (applied only in training mode, reference: transformer attention cells)
    forces the XLA path; the flash kernel handles the pure case."""
    from .. import autograd
    if not autograd.is_training():
        dropout_p = 0.0
    use_flash = _use_pallas() and mask is None and dropout_p == 0.0

    def fn(q, k, v):
        if use_flash:
            try:
                from .pallas.flash_attention import flash_attention
                b, sq, hd = q.shape
                d = hd // heads
                qh = q.reshape(b, sq, heads, d).transpose(0, 2, 1, 3)
                kh = k.reshape(b, k.shape[1], heads, d).transpose(0, 2, 1, 3)
                vh = v.reshape(b, v.shape[1], heads, d).transpose(0, 2, 1, 3)
                out = flash_attention(qh, kh, vh, causal=causal)
                return out.transpose(0, 2, 1, 3).reshape(b, sq, heads * d)
            except Exception:  # pallas unavailable/shape-unsupported
                pass
        m = mask._data if hasattr(mask, "_data") else mask
        return _reference_attention(q, k, v, heads, m, causal, None,
                                    dropout_p)

    return _invoke(fn, (query, key, value), name="multi_head_attention")

"""Fused attention dispatch.

Reference parity: src/operator/contrib/transformer.cc:675-828 (interleaved
matmul attention ops, the reference's fastest attention path).

TPU-native design: a single multi_head_attention entry that routes to the
Pallas flash-attention kernel on TPU (ops/pallas/flash_attention.py) and to
an XLA dot_general composition elsewhere — the composition alone already
fuses well (softmax rides the MXU output), flash-attention additionally
avoids materializing the (seq, seq) scores in HBM for long sequences.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..numpy.multiarray import _invoke


def _reference_attention(q, k, v, heads, mask=None, causal=False, scale=None,
                         dropout_p=0.0):
    """(batch, seq, heads*dim) XLA composition."""
    b, sq, hd = q.shape
    sk = k.shape[1]
    d = hd // heads
    scale = scale if scale is not None else 1.0 / (d ** 0.5)
    qh = q.reshape(b, sq, heads, d).transpose(0, 2, 1, 3)
    kh = k.reshape(b, sk, heads, d).transpose(0, 2, 1, 3)
    vh = v.reshape(b, sk, heads, d).transpose(0, 2, 1, 3)
    scores = jnp.einsum("bhqd,bhkd->bhqk", qh, kh) * scale
    if causal:
        cm = jnp.tril(jnp.ones((sq, sk), dtype=bool))
        scores = jnp.where(cm, scores, -1e30)
    if mask is not None:
        scores = jnp.where(mask.astype(bool), scores, -1e30)
    att = jax.nn.softmax(scores.astype(jnp.float32), axis=-1).astype(q.dtype)
    if dropout_p:
        from .. import random as _random
        keep = 1.0 - dropout_p
        att = att * jax.random.bernoulli(
            _random._next_key(), keep, att.shape).astype(att.dtype) / keep
    out = jnp.einsum("bhqk,bhkd->bhqd", att, vh)
    return out.transpose(0, 2, 1, 3).reshape(b, sq, heads * d)


def _use_pallas():
    devs = jax.devices()
    return devs and devs[0].platform in ("tpu", "axon")


# measured on TPU v5e (bf16 operands, bq1024/bk512 blocks, interleaved
# A/B at bs8-16 h12 d64): causal flash wins from seq 512 (5.3 vs 7.7 ms
# at 512, ~6.0 vs ~7.6 at 1024 — the tril mask makes XLA materialize and
# mask the full (s, s) scores); non-causal XLA keeps its fused-softmax
# edge until ~2k where O(s^2) HBM takes over
_FLASH_MIN_SEQ = 2048
_FLASH_MIN_SEQ_CAUSAL = 512


def _sp_mesh():
    """Active sequence-parallel mesh from an activation_sharding scope, or
    None. The 'sp' axis is the ring-attention ring (parallel/ring_attention
    — the long-context path the brief makes first-class)."""
    from ..parallel import mesh as _pmesh
    rules = _pmesh._act_rules
    if rules is None:
        return None
    mesh = rules[0]
    if "sp" in mesh.shape and mesh.shape["sp"] > 1:
        return mesh
    return None


def write_prefill_kv(k_cache, v_cache, key, value, slot, heads):
    """Write a whole prompt's projected K/V into one cache slot.

    ``key``/``value`` are (1, L, heads*dim) projections; the caches are
    (max_slots, max_seq, heads, dim). Rows [slot, :L] are overwritten (rows
    beyond L keep stale values — they are never attended because the decode
    mask is bounded by the slot's position counter and every row below it
    is rewritten in order before it becomes visible). ``slot`` may be a
    traced scalar, so one compiled prefill serves every slot.
    """
    def fn(kc, vc, k, v, s):
        _, seq_len, hd = k.shape
        d = hd // heads
        kh = k.reshape(1, seq_len, heads, d).astype(kc.dtype)
        vh = v.reshape(1, seq_len, heads, d).astype(vc.dtype)
        start = (s.astype(jnp.int32) if hasattr(s, "astype") else
                 jnp.int32(s), 0, 0, 0)
        return (jax.lax.dynamic_update_slice(kc, kh, start),
                jax.lax.dynamic_update_slice(vc, vh, start))

    return _invoke(fn, (k_cache, v_cache, key, value, slot),
                   name="write_prefill_kv")


def _quantize_kv_rows(x, int8_max=127.0):
    """Symmetric int8 over the last (head_dim) axis: one scale per
    (slot, row, head) — each written row computes its own scale, so the
    fixed-footprint cache never needs requantization."""
    xf = x.astype(jnp.float32)
    scale = jnp.max(jnp.abs(xf), axis=-1, keepdims=True) / int8_max
    scale = jnp.where(scale == 0, 1.0, scale)
    q = jnp.clip(jnp.round(xf / scale), -int8_max, int8_max)
    return q.astype(jnp.int8), scale


def write_prefill_kv_q8(k_cache, k_scale, v_cache, v_scale, key, value,
                        slot, heads):
    """int8-cache variant of :func:`write_prefill_kv`: quantizes the
    prompt's projected K/V per (row, head) and writes values + scales.
    Caches are (max_slots, max_seq, heads, dim) int8; scales
    (max_slots, max_seq, heads, 1) float32."""
    def fn(kc, ks, vc, vs, k, v, s):
        _, seq_len, hd = k.shape
        d = hd // heads
        kq, ksc = _quantize_kv_rows(k.reshape(1, seq_len, heads, d))
        vq, vsc = _quantize_kv_rows(v.reshape(1, seq_len, heads, d))
        start = (s.astype(jnp.int32) if hasattr(s, "astype") else
                 jnp.int32(s), 0, 0, 0)
        return (jax.lax.dynamic_update_slice(kc, kq, start),
                jax.lax.dynamic_update_slice(ks, ksc, start),
                jax.lax.dynamic_update_slice(vc, vq, start),
                jax.lax.dynamic_update_slice(vs, vsc, start))

    return _invoke(fn, (k_cache, k_scale, v_cache, v_scale, key, value,
                        slot), name="write_prefill_kv_q8")


def copy_cache_rows(cache, src_slot, src_row, dst_slot, dst_row, rows):
    """Copy ``rows`` cache rows (one prefix-cache block) between slots.

    ``cache`` is any pytree whose leaves are (max_slots, max_seq, ...)
    arrays — the fp32 (k, v) pairs and the int8 ((values, scales), ...)
    layout alike, since the per-(slot, row, head) scales share the
    leading two axes and copy with their rows.  Slot/row operands may be
    traced scalars, so ONE compiled executable serves every (src, dst)
    pair; ``rows`` must be static (the serve.prefix_block bucket).  The
    engine's block-copy executable is this function jitted with the
    caches donated."""
    def one(leaf):
        tail = (0,) * (leaf.ndim - 2)
        sizes = (1, rows) + leaf.shape[2:]
        blk = jax.lax.dynamic_slice(
            leaf, (src_slot, src_row) + tail, sizes)
        return jax.lax.dynamic_update_slice(
            leaf, blk, (dst_slot, dst_row) + tail)

    return jax.tree_util.tree_map(one, cache)


def gather_cache_rows(cache, src_slots, src_rows, dst_slot):
    """Rebuild one destination slot from per-row source coordinates:
    row ``r`` of ``dst_slot`` becomes row ``src_rows[r]`` of slot
    ``src_slots[r]``, for every leaf of ``cache`` (same pytree contract
    as :func:`copy_cache_rows`).  ONE gather plus ONE slot-sized write
    per leaf — a whole matched prefix path (blocks scattered across
    donor slots) lands in a single pass, where a per-block
    dynamic_update_slice chain would rewrite the full cache buffer once
    per block.  Rows the caller wants untouched are encoded as identity
    coordinates (``dst_slot``, own row); the gather reads them back
    unchanged.  All operands may be traced; shapes are static."""
    def one(leaf):
        rows = leaf[src_slots, src_rows]
        return jax.lax.dynamic_update_slice(
            leaf, rows[None], (dst_slot,) + (0,) * (leaf.ndim - 1))

    return jax.tree_util.tree_map(one, cache)


def suffix_prefill_attention(q, k, v, k_cache, v_cache, slot, start, heads):
    """Prefix-cache suffix prefill: causal attention of a prompt
    *suffix* (1, Ls, heads*dim) over cache slot ``slot`` whose rows
    [0, start) already hold a copied prefix.  Writes the suffix K/V at
    rows [start, start + Ls) and lets query i attend every cache row
    <= start + i — the copied prefix plus the causal suffix.  ``slot``
    and ``start`` may be traced; the caller guarantees
    start + Ls <= max_seq (the engine falls back to full prefill
    otherwise)."""
    def fn(q, k, v, kc, vc, s, st):
        _, ls, hd = q.shape
        d = hd // heads
        max_seq = kc.shape[1]
        s32 = jnp.int32(s) if not hasattr(s, "astype") else \
            s.astype(jnp.int32)
        st32 = jnp.int32(st) if not hasattr(st, "astype") else \
            st.astype(jnp.int32)
        kh = k.reshape(1, ls, heads, d).astype(kc.dtype)
        vh = v.reshape(1, ls, heads, d).astype(vc.dtype)
        kc = jax.lax.dynamic_update_slice(kc, kh, (s32, st32, 0, 0))
        vc = jax.lax.dynamic_update_slice(vc, vh, (s32, st32, 0, 0))
        kslot = jax.lax.dynamic_slice(
            kc, (s32, 0, 0, 0), (1, max_seq, heads, d))[0]
        vslot = jax.lax.dynamic_slice(
            vc, (s32, 0, 0, 0), (1, max_seq, heads, d))[0]
        qh = q.reshape(ls, heads, d)
        scale = 1.0 / (d ** 0.5)
        scores = jnp.einsum("qhd,shd->hqs", qh,
                            kslot.astype(q.dtype)) * scale
        visible = (jnp.arange(max_seq)[None, :]
                   <= (st32 + jnp.arange(ls))[:, None])
        scores = jnp.where(visible[None, :, :], scores, -1e30)
        att = jax.nn.softmax(scores.astype(jnp.float32),
                             axis=-1).astype(q.dtype)
        out = jnp.einsum("hqs,shd->qhd", att, vslot.astype(q.dtype))
        return out.reshape(1, ls, hd), kc, vc

    return _invoke(fn, (q, k, v, k_cache, v_cache, slot, start),
                   name="suffix_prefill_attention")


def suffix_prefill_attention_q8(q, k, v, k_cache, k_scale, v_cache,
                                v_scale, slot, start, heads):
    """int8-cache variant of :func:`suffix_prefill_attention`: the
    suffix rows quantize with their own per-(row, head) scales before
    the write (scales land beside the copied prefix's scales), and the
    slot's cached K/V dequantizes into the score/value einsums."""
    def fn(q, k, v, kc, ks, vc, vs, s, st):
        _, ls, hd = q.shape
        d = hd // heads
        max_seq = kc.shape[1]
        s32 = jnp.int32(s) if not hasattr(s, "astype") else \
            s.astype(jnp.int32)
        st32 = jnp.int32(st) if not hasattr(st, "astype") else \
            st.astype(jnp.int32)
        kq, ksc = _quantize_kv_rows(k.reshape(1, ls, heads, d))
        vq, vsc = _quantize_kv_rows(v.reshape(1, ls, heads, d))
        kc = jax.lax.dynamic_update_slice(kc, kq, (s32, st32, 0, 0))
        ks = jax.lax.dynamic_update_slice(ks, ksc, (s32, st32, 0, 0))
        vc = jax.lax.dynamic_update_slice(vc, vq, (s32, st32, 0, 0))
        vs = jax.lax.dynamic_update_slice(vs, vsc, (s32, st32, 0, 0))
        kslot = jax.lax.dynamic_slice(
            kc, (s32, 0, 0, 0), (1, max_seq, heads, d))[0].astype(q.dtype)
        kssl = jax.lax.dynamic_slice(
            ks, (s32, 0, 0, 0), (1, max_seq, heads, 1))[0].astype(q.dtype)
        vslot = jax.lax.dynamic_slice(
            vc, (s32, 0, 0, 0), (1, max_seq, heads, d))[0].astype(q.dtype)
        vssl = jax.lax.dynamic_slice(
            vs, (s32, 0, 0, 0), (1, max_seq, heads, 1))[0].astype(q.dtype)
        qh = q.reshape(ls, heads, d)
        scale = 1.0 / (d ** 0.5)
        scores = jnp.einsum("qhd,shd->hqs", qh, kslot * kssl) * scale
        visible = (jnp.arange(max_seq)[None, :]
                   <= (st32 + jnp.arange(ls))[:, None])
        scores = jnp.where(visible[None, :, :], scores, -1e30)
        att = jax.nn.softmax(scores.astype(jnp.float32),
                             axis=-1).astype(q.dtype)
        out = jnp.einsum("hqs,shd->qhd", att, vslot * vssl)
        return out.reshape(1, ls, hd), kc, ks, vc, vs

    return _invoke(fn, (q, k, v, k_cache, k_scale, v_cache, v_scale,
                        slot, start), name="suffix_prefill_attention_q8")


def decode_multi_attention(query, key, value, k_cache, v_cache, positions,
                           heads):
    """k-token cached attention — the speculative-decoding verify step.

    ``query``/``key``/``value`` are (slots, t, heads*dim) projections of
    t tokens per slot; slot i's token j lands at cache row
    positions[i] + j (scatter rows clip at max_seq - 1 like
    :func:`decode_attention` — clipped writes only ever touch rows above
    the slot's position counter, which are rewritten before becoming
    visible).  Query j attends rows <= positions + j, so the t tokens
    verify causally in ONE batched call."""
    def fn(q, k, v, kc, vc, pos):
        n, t, hd = q.shape
        d = hd // heads
        max_seq = kc.shape[1]
        rows = jnp.clip(pos.astype(jnp.int32)[:, None] + jnp.arange(t),
                        0, max_seq - 1)
        lane = jnp.arange(n)[:, None]
        kc = kc.at[lane, rows].set(k.reshape(n, t, heads, d)
                                   .astype(kc.dtype))
        vc = vc.at[lane, rows].set(v.reshape(n, t, heads, d)
                                   .astype(vc.dtype))
        qh = q.reshape(n, t, heads, d)
        scale = 1.0 / (d ** 0.5)
        scores = jnp.einsum("nqhd,nshd->nhqs", qh,
                            kc.astype(q.dtype)) * scale
        limit = pos.astype(jnp.int32)[:, None] + jnp.arange(t)
        visible = (jnp.arange(max_seq)[None, None, :]
                   <= limit[:, :, None])[:, None, :, :]
        scores = jnp.where(visible, scores, -1e30)
        att = jax.nn.softmax(scores.astype(jnp.float32),
                             axis=-1).astype(q.dtype)
        out = jnp.einsum("nhqs,nshd->nqhd", att, vc.astype(q.dtype))
        return out.reshape(n, t, hd), kc, vc

    return _invoke(fn, (query, key, value, k_cache, v_cache, positions),
                   name="decode_multi_attention")


def decode_multi_attention_q8(query, key, value, k_cache, k_scale, v_cache,
                              v_scale, positions, heads):
    """int8-cache variant of :func:`decode_multi_attention`: each of the
    t written rows quantizes with its own (slot, row, head) scale, the
    dequant fusing into the einsums exactly like
    :func:`decode_attention_q8`."""
    def fn(q, k, v, kc, ks, vc, vs, pos):
        n, t, hd = q.shape
        d = hd // heads
        max_seq = kc.shape[1]
        rows = jnp.clip(pos.astype(jnp.int32)[:, None] + jnp.arange(t),
                        0, max_seq - 1)
        lane = jnp.arange(n)[:, None]
        kq, ksc = _quantize_kv_rows(k.reshape(n, t, heads, d))
        vq, vsc = _quantize_kv_rows(v.reshape(n, t, heads, d))
        kc = kc.at[lane, rows].set(kq)
        ks = ks.at[lane, rows].set(ksc)
        vc = vc.at[lane, rows].set(vq)
        vs = vs.at[lane, rows].set(vsc)
        qh = q.reshape(n, t, heads, d)
        scale = 1.0 / (d ** 0.5)
        kf = kc.astype(q.dtype) * ks.astype(q.dtype)
        scores = jnp.einsum("nqhd,nshd->nhqs", qh, kf) * scale
        limit = pos.astype(jnp.int32)[:, None] + jnp.arange(t)
        visible = (jnp.arange(max_seq)[None, None, :]
                   <= limit[:, :, None])[:, None, :, :]
        scores = jnp.where(visible, scores, -1e30)
        att = jax.nn.softmax(scores.astype(jnp.float32),
                             axis=-1).astype(q.dtype)
        vf = vc.astype(q.dtype) * vs.astype(q.dtype)
        out = jnp.einsum("nhqs,nshd->nqhd", att, vf)
        return out.reshape(n, t, hd), kc, ks, vc, vs

    return _invoke(fn, (query, key, value, k_cache, k_scale, v_cache,
                        v_scale, positions),
                   name="decode_multi_attention_q8")


def decode_attention_q8(query, key, value, k_cache, k_scale, v_cache,
                        v_scale, positions, heads):
    """int8-cache variant of :func:`decode_attention`: the cache crosses
    HBM as int8 + per-(slot, row, head) scales and the dequant
    (``astype * scale``) fuses into the score/value einsums, so decode —
    memory-bound on the cache at long contexts — moves a quarter of the
    fp32 bytes. The current token's K/V is quantized with its own row
    scale before the write; attention math itself stays in the query
    dtype with an f32 softmax, exactly like the fp path."""
    def fn(q, k, v, kc, ks, vc, vs, pos):
        n, _, hd = q.shape
        d = hd // heads
        max_seq = kc.shape[1]
        row = jnp.clip(pos.astype(jnp.int32), 0, max_seq - 1)
        lane = jnp.arange(n)
        kq, ksc = _quantize_kv_rows(k.reshape(n, heads, d))
        vq, vsc = _quantize_kv_rows(v.reshape(n, heads, d))
        kc = kc.at[lane, row].set(kq)
        ks = ks.at[lane, row].set(ksc)
        vc = vc.at[lane, row].set(vq)
        vs = vs.at[lane, row].set(vsc)
        qh = q.reshape(n, heads, d)
        scale = 1.0 / (d ** 0.5)
        kf = kc.astype(q.dtype) * ks.astype(q.dtype)
        scores = jnp.einsum("nhd,nshd->nhs", qh, kf) * scale
        visible = (jnp.arange(max_seq)[None, :] <= row[:, None])[:, None, :]
        scores = jnp.where(visible, scores, -1e30)
        att = jax.nn.softmax(scores.astype(jnp.float32),
                             axis=-1).astype(q.dtype)
        vf = vc.astype(q.dtype) * vs.astype(q.dtype)
        out = jnp.einsum("nhs,nshd->nhd", att, vf)
        return out.reshape(n, 1, hd), kc, ks, vc, vs

    return _invoke(fn, (query, key, value, k_cache, k_scale, v_cache,
                        v_scale, positions), name="decode_attention_q8")


def decode_attention(query, key, value, k_cache, v_cache, positions, heads):
    """Single-token cached attention for continuous-batching decode.

    ``query``/``key``/``value`` are (slots, 1, heads*dim) projections of the
    current token in every slot; caches are (slots, max_seq, heads, dim);
    ``positions`` (slots,) is the row each slot's new K/V lands in. Writes
    the new K/V, attends rows <= positions (static shapes — the mask, not
    the extent, varies), and returns (out, k_cache, v_cache). Score
    materialization is (slots, heads, max_seq) — tiny, so no flash path.
    """
    def fn(q, k, v, kc, vc, pos):
        n, _, hd = q.shape
        d = hd // heads
        max_seq = kc.shape[1]
        row = jnp.clip(pos.astype(jnp.int32), 0, max_seq - 1)
        lane = jnp.arange(n)
        kc = kc.at[lane, row].set(k.reshape(n, heads, d).astype(kc.dtype))
        vc = vc.at[lane, row].set(v.reshape(n, heads, d).astype(vc.dtype))
        qh = q.reshape(n, heads, d)
        scale = 1.0 / (d ** 0.5)
        scores = jnp.einsum("nhd,nshd->nhs", qh,
                            kc.astype(q.dtype)) * scale
        visible = (jnp.arange(max_seq)[None, :] <= row[:, None])[:, None, :]
        scores = jnp.where(visible, scores, -1e30)
        att = jax.nn.softmax(scores.astype(jnp.float32),
                             axis=-1).astype(q.dtype)
        out = jnp.einsum("nhs,nshd->nhd", att, vc.astype(q.dtype))
        return out.reshape(n, 1, hd), kc, vc

    return _invoke(fn, (query, key, value, k_cache, v_cache, positions),
                   name="decode_attention")


def multi_head_attention(query, key, value, heads, mask=None, dropout_p=0.0,
                         causal=False):
    """Fused MHA on (batch, seq, heads*dim) ndarrays.

    Routing: sp-sharded scope -> ring attention (sequence parallelism over
    ICI); long unmasked sequences on TPU -> Pallas flash kernel; otherwise
    the XLA dot_general composition. Attention-prob dropout (training only,
    reference: transformer attention cells) forces the XLA path.
    """
    from .. import autograd
    if not autograd.is_training():
        dropout_p = 0.0
    pure = mask is None and dropout_p == 0.0
    sp_mesh = _sp_mesh() if pure else None

    def fn(q, k, v):
        b, sq, hd = q.shape
        sk = k.shape[1]
        d = hd // heads
        if sp_mesh is not None and sq == sk:
            try:
                from ..parallel.ring_attention import ring_attention
                qh = q.reshape(b, sq, heads, d).transpose(0, 2, 1, 3)
                kh = k.reshape(b, sk, heads, d).transpose(0, 2, 1, 3)
                vh = v.reshape(b, sk, heads, d).transpose(0, 2, 1, 3)
                out = ring_attention(qh, kh, vh, sp_mesh, axis="sp",
                                     causal=causal)
                return out.transpose(0, 2, 1, 3).reshape(b, sq, hd)
            except Exception:  # seq not divisible by ring, etc.
                pass
        min_seq = _FLASH_MIN_SEQ_CAUSAL if causal else _FLASH_MIN_SEQ
        if _use_pallas() and pure and sk >= min_seq:
            try:
                from .pallas.flash_attention import flash_attention
                qh = q.reshape(b, sq, heads, d).transpose(0, 2, 1, 3)
                kh = k.reshape(b, sk, heads, d).transpose(0, 2, 1, 3)
                vh = v.reshape(b, sk, heads, d).transpose(0, 2, 1, 3)
                out = flash_attention(qh, kh, vh, causal=causal)
                return out.transpose(0, 2, 1, 3).reshape(b, sq, hd)
            except Exception:  # pallas unavailable/shape-unsupported
                pass
        m = mask._data if hasattr(mask, "_data") else mask
        return _reference_attention(q, k, v, heads, m, causal, None,
                                    dropout_p)

    return _invoke(fn, (query, key, value), name="multi_head_attention")

"""Device contexts.

Reference parity: python/mxnet/context.py (Context class, cpu()/gpu()/
cpu_pinned(), thread-local default ctx via `with ctx:`). TPU-native mapping:
a Context names a jax.Device (or the host CPU); arrays are placed with
jax.device_put. ``gpu()`` maps to the accelerator backend so that reference
scripts written against mx.gpu() run unchanged on TPU.
"""
from __future__ import annotations

import threading

import jax

from .base import MXNetError


class Context:
    """A device context. devtype in {'cpu', 'tpu', 'gpu', 'cpu_pinned', 'cpu_shared'}.

    'gpu' and 'tpu' both resolve to the default jax accelerator backend (on a
    TPU machine that is the TPU); 'cpu' resolves to the host platform.
    """

    _default_ctx = threading.local()
    devtype2id = {"cpu": 1, "gpu": 2, "cpu_pinned": 3, "cpu_shared": 5, "tpu": 6}
    devid2type = {v: k for k, v in devtype2id.items()}

    def __init__(self, device_type, device_id=0):
        if isinstance(device_type, Context):
            device_type, device_id = device_type.device_type, device_type.device_id
        if device_type not in self.devtype2id:
            raise MXNetError(f"unknown device type {device_type!r}")
        self.device_type = device_type
        self.device_id = int(device_id)

    # -- jax integration ---------------------------------------------------
    @property
    def jax_device(self):
        """Resolve to a concrete jax.Device."""
        if self.device_type in ("cpu", "cpu_pinned", "cpu_shared"):
            devs = jax.devices("cpu") if _has_platform("cpu") else jax.devices()
        else:
            devs = _accelerator_devices()
        if not devs:
            raise MXNetError(f"no devices for context {self}")
        return devs[self.device_id % len(devs)]

    # -- context manager (thread-local default) ----------------------------
    def __enter__(self):
        if not hasattr(Context._default_ctx, "stack"):
            Context._default_ctx.stack = []
        Context._default_ctx.stack.append(self)
        return self

    def __exit__(self, *exc):
        Context._default_ctx.stack.pop()

    def __eq__(self, other):
        return (isinstance(other, Context)
                and self.device_type == other.device_type
                and self.device_id == other.device_id)

    def __hash__(self):
        return hash((self.device_type, self.device_id))

    def __repr__(self):
        return f"{self.device_type}({self.device_id})"

    def __str__(self):
        return repr(self)

    def empty_cache(self):
        """Release cached device memory (reference: Context.empty_cache).

        XLA/PJRT manages its own allocator; this triggers a GC + live-buffer
        donation sweep best-effort.
        """
        import gc
        gc.collect()


def _has_platform(name):
    try:
        return bool(jax.devices(name))
    except RuntimeError:
        return False


def _accelerator_devices():
    """Devices of the default (accelerator-first) backend."""
    devs = jax.devices()
    if devs and devs[0].platform != "cpu":
        return devs
    return devs  # cpu-only machine: accelerators alias to cpu


def cpu(device_id=0):
    return Context("cpu", device_id)


def cpu_pinned(device_id=0):
    return Context("cpu_pinned", device_id)


def gpu(device_id=0):
    """Accelerator context. On a TPU host this is the TPU chip (the reference's
    mx.gpu() scripts then run unchanged)."""
    return Context("gpu", device_id)


def tpu(device_id=0):
    return Context("tpu", device_id)


def device(dev_type, device_id=0):
    return Context(dev_type, device_id)


def num_gpus():
    """Count of accelerator devices (reference: mx.context.num_gpus)."""
    devs = jax.devices()
    return len(devs) if devs and devs[0].platform != "cpu" else 0


def num_tpus():
    return num_gpus()


def gpu_memory_info(device_id=0):
    """(free, total) device memory bytes (reference context.py:249 over
    cudaMemGetInfo). PJRT exposes per-device stats where the runtime
    supports them; otherwise this raises like the reference does on a
    CPU-only build."""
    from .base import MXNetError
    devs = [d for d in jax.devices() if d.platform != "cpu"]
    if not 0 <= device_id < len(devs):
        raise MXNetError(f"no accelerator device {device_id} "
                         f"({len(devs)} available)")
    stats = devs[device_id].memory_stats()
    if not stats or "bytes_limit" not in stats:
        raise MXNetError("device memory stats unavailable on this runtime")
    total = stats["bytes_limit"]
    return total - stats.get("bytes_in_use", 0), total


def current_context():
    """Thread-local default context (reference: context.py current_context)."""
    stack = getattr(Context._default_ctx, "stack", None)
    if stack:
        return stack[-1]
    devs = jax.devices()
    if devs and devs[0].platform != "cpu":
        return Context("tpu", 0)
    return Context("cpu", 0)

"""mx.blackbox — always-on flight recorder + crash-triggered postmortems.

The three live observability planes — ``mx.telemetry`` (metrics),
``mx.trace`` (spans), ``mx.insight`` (attribution/drift) — are all
in-memory: the moment ``mx.fault`` kills a worker, ``mx.fleet`` declares
a ``WorkerLost`` or a real SIGKILL/OOM lands, the evidence dies with the
process.  This module is the durable flipside: a bounded flight recorder
that, on any terminal trigger, freezes the last-N window of evidence into
ONE crash-atomic checksummed postmortem bundle a *surviving* host can
read.

- **Gate**: the same one-attr-read disabled design as ``fault._active`` /
  ``telemetry._active`` — every hook is ``if blackbox._active: ...``, and
  benchmark/telemetry_overhead.py re-gates the <2% disabled budget with a
  blackbox probe.
- **Triggers**: uncaught exceptions (chained ``sys.excepthook`` +
  ``threading.excepthook``, so a loader thread's death is captured too),
  ``resilience`` SIGTERM/SIGINT preemption (SystemExit never reaches an
  excepthook, so the exit-75 path dumps explicitly), ``WorkerLost``,
  trainer non-finite-grad escalation, ``insight.drift`` firing, and
  explicit :func:`dump`.
- **Bundle**: ``blackbox-<rank>-<step>.json`` under ``blackbox.dir``
  (default: next to the fleet heartbeat leases, so peers can read a dead
  host's bundle) written via ``serialization.atomic_write_bytes`` + a
  ``.sha256`` sidecar; torn bundles are detectable and skipped.  Content:
  the newest-N ``mx.trace`` spans (shared CLOCK_MONOTONIC base, so
  per-host bundles interleave into one fleet timeline), a full
  ``telemetry.snapshot()`` plus a counter delta since arming, the bounded
  telemetry event ring (python warnings + log records >= WARNING),
  ``fault.stats()``, the insight attribution/drift state, sync_guard
  per-site counts, every resolved config knob, and the caller-fed context
  (active MeshConfig, last checkpoint generation).
- **SIGKILL/OOM**: no hook runs, so a low-frequency shadow snapshot
  (``blackbox.checkpoint_interval``) rides ``HealthPlane.beat`` — the
  fleet always holds a <=interval-stale bundle per host.
- **Read side**: ``FleetSupervisor`` attaches the dead host's latest
  bundle to its ``fleet.degrade`` decision, ``tools/postmortem.py``
  merges per-host bundles into one causal timeline (first-anomaly host
  highlighted), and the ops endpoint serves ``/postmortem?last=N``.

Enable via ``mx.blackbox.enable()`` or ``MXNET_BLACKBOX=1`` (the
``blackbox.enable`` knob, read at import like ``MXNET_FAULT_SPEC``).
Docs: docs/OBSERVABILITY.md "Postmortem forensics".
"""
from __future__ import annotations

import json
import logging
import os
import re
import sys
import threading
import time
import traceback
import warnings as _warnings

from . import config as _config
from . import fault as _fault
from . import telemetry as _telemetry
from . import trace as _trace
from .base import MXNetError

__all__ = ["enable", "disable", "configure", "active", "dump", "collect",
           "maybe_checkpoint", "set_context", "note_mesh",
           "note_checkpoint", "bundle_dir", "list_bundles", "latest_bundle",
           "read_bundle", "endpoint_report", "BUNDLE_SCHEMA", "TRIGGERS"]

#: bundle format tag — readers reject documents without it
BUNDLE_SCHEMA = "mx.blackbox/1"

#: terminal trigger classes a bundle's ``meta.trigger`` may carry
TRIGGERS = ("excepthook", "thread_excepthook", "preempt", "worker_lost",
            "nonfinite", "drift", "shadow", "manual")

_lock = threading.Lock()
#: hot-path gate — trigger sites read this one attribute; False keeps
#: every hook a single no-op branch (same design as fault._active)
_active = False

#: caller-fed forensic context (rank, step, MeshConfig, checkpoint
#: generation, ...) embedded verbatim in every bundle
_context: dict = {}
#: counter values at enable() time — bundles carry the delta, so "what
#: happened during THIS run" survives a long-lived registry
_baseline: dict = {}
_snap_last = 0.0
_last_exc_id = None

_prev_excepthook = None
_prev_threading_hook = None
_prev_showwarning = None
_log_handler = None

_telemetry.declare_metric(
    "blackbox.bundles_written_total", "counter",
    "postmortem bundles written by the flight recorder, by trigger")
_telemetry.declare_metric(
    "blackbox.dump_errors_total", "counter",
    "bundle writes that failed (best-effort: a dying process must not "
    "die harder)")
_telemetry.declare_metric(
    "blackbox.last_dump_unix", "gauge",
    "wall-clock time of the last postmortem bundle written")


# -- capture hooks ----------------------------------------------------------

class _RingHandler(logging.Handler):
    """Routes framework log records >= WARNING into the bounded
    telemetry event ring, so bundles carry the log lines that preceded
    the crash."""

    def emit(self, record):
        try:
            _telemetry.note_event("log", record.getMessage(),
                                  logger=record.name,
                                  level=record.levelname)
        except Exception:   # noqa: BLE001 - logging must never raise
            pass


def _showwarning(message, category, filename, lineno,
                 file=None, line=None):
    try:
        _telemetry.note_event("warning", message,
                              category=category.__name__,
                              filename=filename, lineno=lineno)
    except Exception:   # noqa: BLE001 - warning capture must never raise
        pass
    if _prev_showwarning is not None:
        _prev_showwarning(message, category, filename, lineno,
                          file=file, line=line)


def _dump_exc(trigger, exc_type, exc, tb, **extra):
    """One bundle per exception object, no matter how many hooks see
    it (sys.excepthook and threading.excepthook can chain)."""
    global _last_exc_id
    if not _active:
        return
    with _lock:
        if exc is not None and id(exc) == _last_exc_id:
            return
        _last_exc_id = id(exc)
    name = getattr(exc_type, "__name__", str(exc_type))
    reason = f"{name}: {exc}"
    if extra:
        reason += " (" + ", ".join(
            f"{k}={v}" for k, v in sorted(extra.items())) + ")"
    dump(trigger=trigger, reason=reason, exc=exc)


def _excepthook(exc_type, exc, tb):
    _dump_exc("excepthook", exc_type, exc, tb)
    if _prev_excepthook is not None:
        _prev_excepthook(exc_type, exc, tb)


def _threading_hook(args):
    if args.exc_type is not SystemExit:
        _dump_exc("thread_excepthook", args.exc_type, args.exc_value,
                  args.exc_traceback,
                  thread=getattr(args.thread, "name", None))
    if _prev_threading_hook is not None:
        _prev_threading_hook(args)


# -- switches ---------------------------------------------------------------

def enable(on=True):
    """Arm/disarm the recorder.  Arming chains the sys/threading
    excepthooks, installs the warnings/log capture into the telemetry
    event ring, snapshots the counter baseline, and arms the pipeline
    sync-site counter; disarming restores the previous hooks."""
    global _active, _prev_excepthook, _prev_threading_hook, \
        _prev_showwarning, _log_handler, _baseline
    on = bool(on)
    with _lock:
        if on == _active:
            return _active
        _active = on
        if on:
            _baseline = _telemetry.counters()
            _prev_excepthook = sys.excepthook
            sys.excepthook = _excepthook
            _prev_threading_hook = threading.excepthook
            threading.excepthook = _threading_hook
            _prev_showwarning = _warnings.showwarning
            _warnings.showwarning = _showwarning
            _log_handler = _RingHandler(level=logging.WARNING)
            logging.getLogger("mxnet_tpu").addHandler(_log_handler)
        else:
            if sys.excepthook is _excepthook:
                sys.excepthook = _prev_excepthook
            if threading.excepthook is _threading_hook:
                threading.excepthook = _prev_threading_hook
            if _warnings.showwarning is _showwarning:
                _warnings.showwarning = _prev_showwarning
            if _log_handler is not None:
                logging.getLogger("mxnet_tpu").removeHandler(_log_handler)
                _log_handler = None
            _prev_excepthook = None
            _prev_threading_hook = None
            _prev_showwarning = None
    from . import pipeline as _pipeline   # lazy: pipeline imports telemetry
    _pipeline.arm_site_counts("blackbox", on)
    return _active


def disable():
    enable(False)


def configure():
    """Re-read the ``blackbox.enable`` knob / ``MXNET_BLACKBOX`` alias."""
    return enable(_config.get("blackbox.enable"))


def active():
    return _active


# -- forensic context -------------------------------------------------------

def set_context(**fields):
    """Merge caller-known facts (rank=, step=, ...) into the context
    block every subsequent bundle embeds.  ``None`` deletes a key."""
    with _lock:
        for k, v in fields.items():
            if v is None:
                _context.pop(k, None)
            else:
                _context[k] = v
        return dict(_context)


def note_mesh(cfg):
    """Record the active parallelism layout (a MeshConfig or any object
    with dp/tp/pp attrs) — bundles answer 'what mesh was this host
    running?' without the supervisor."""
    if not _active:
        return
    mesh = {}
    for attr in ("dp", "tp", "pp", "sp", "zero"):
        v = getattr(cfg, attr, None)
        if v is not None:
            mesh[attr] = v
    set_context(mesh=mesh or repr(cfg))


def note_checkpoint(path, step, generation=None):
    """Record the last TrainState bundle written — the postmortem names
    the exact checkpoint a replacement host will restore."""
    if not _active:
        return
    set_context(checkpoint={"path": str(path), "step": int(step),
                            "generation": generation})


# -- bundle writing ---------------------------------------------------------

def _json_safe(v):
    if isinstance(v, (bool, int, float, str)) or v is None:
        return v
    return repr(v)


def collect(trigger="manual", reason=None, exc=None, step=None, rank=None,
            shadow=False):
    """Assemble (without writing) one postmortem bundle dict: the
    last-N evidence window across every observability plane."""
    window = max(1, int(_config.get("blackbox.window")))
    with _lock:
        ctx = dict(_context)
        baseline = dict(_baseline)
    if rank is None:
        rank = int(ctx.get("rank", 0))
    if step is None:
        step = int(ctx.get("step", 0))
    snap = _telemetry.snapshot()
    delta = {}
    for k, v in snap["counters"].items():
        d = v - baseline.get(k, 0)
        if d:
            delta[k] = d
    from . import insight as _insight   # lazy: insight imports telemetry
    try:
        insight_state = {"summary": _insight.last_summary(),
                         "drift_events": _insight.drift_events()}
    except Exception:   # noqa: BLE001 - evidence is best-effort
        insight_state = {"summary": None, "drift_events": []}
    from . import pipeline as _pipeline
    bundle = {
        "schema": BUNDLE_SCHEMA,
        "meta": {"trigger": trigger, "reason": reason,
                 "shadow": bool(shadow), "rank": int(rank),
                 "step": int(step), "pid": os.getpid(),
                 "time": time.time(), "clock_us": _trace.clock_us()},
        "exception": None,
        "spans": _trace.spans(last=window),
        "trace_stats": _trace.stats(),
        "telemetry": snap,
        "counters_delta": delta,
        "events": _telemetry.events(last=window),
        "fault": _fault.stats(),
        "insight": insight_state,
        "sync_sites": _pipeline.sync_site_counts(),
        "config": {name: _json_safe(k.value())
                   for name, k in sorted(_config.knobs().items())},
        "context": ctx,
    }
    if exc is not None:
        bundle["exception"] = {
            "type": type(exc).__name__, "message": str(exc),
            "traceback": traceback.format_exception(
                type(exc), exc, exc.__traceback__)}
    return bundle


def bundle_dir():
    """The resolved bundle directory: ``blackbox.dir``, else the fleet
    lease dir (so surviving hosts can read a dead peer's bundle), else
    '' (dumps are skipped)."""
    return _config.get("blackbox.dir") or _config.get("fleet.lease_dir") \
        or ""


def dump(trigger="manual", reason=None, exc=None, step=None, rank=None,
         shadow=False, dir=None):
    """Write ONE crash-atomic checksummed postmortem bundle
    ``blackbox-<rank>-<step>.json`` and run per-rank retention
    (``blackbox.keep``).  Returns the path, or None without a resolvable
    directory.  Never raises — a dying process must not die harder."""
    global _last_exc_id
    d = dir or bundle_dir()
    if not d:
        return None
    if exc is not None:
        # an explicit dump for this exception supersedes the excepthook
        # one it would otherwise get when it escapes (e.g. WorkerLost
        # after the restart budget is exhausted)
        with _lock:
            _last_exc_id = id(exc)
    try:
        bundle = collect(trigger=trigger, reason=reason, exc=exc,
                         step=step, rank=rank, shadow=shadow)
        rank = bundle["meta"]["rank"]
        step = bundle["meta"]["step"]
        os.makedirs(d, exist_ok=True)
        path = os.path.join(d, f"blackbox-{rank}-{step:08d}.json")
        from . import serialization as _ser
        _ser.atomic_write_bytes(
            path, (json.dumps(bundle) + "\n").encode("utf-8"))
        _ser.write_checksum(path)
        if _fault.fire("blackbox.torn_bundle", step=step):
            # crash mid-write analog: the data file is truncated AFTER
            # its checksum landed, so verify_checksum must reject it
            with open(path, "r+b") as f:
                f.truncate(max(1, os.path.getsize(path) // 2))
        _gc(d, rank)
        if _telemetry._active:
            _telemetry.inc("blackbox.bundles_written_total",
                           trigger=trigger)
            _telemetry.set_gauge("blackbox.last_dump_unix",
                                 bundle["meta"]["time"])
        return path
    except Exception:   # noqa: BLE001 - best-effort by contract
        try:
            if _telemetry._active:
                _telemetry.inc("blackbox.dump_errors_total")
        except Exception:   # noqa: BLE001
            pass
        return None


def _gc(d, rank):
    """Keep the newest ``blackbox.keep`` bundles for ``rank`` (plus
    sidecars); 0 keeps everything."""
    keep = int(_config.get("blackbox.keep"))
    if keep <= 0:
        return
    from . import serialization as _ser
    mine = list_bundles(d, rank=rank)
    for p in mine[:-keep]:
        for victim in (p, p + _ser.CHECKSUM_SUFFIX):
            try:
                os.remove(victim)
            except OSError:
                pass


def maybe_checkpoint(lease_dir=None, rank=0, step=None, interval=None):
    """Rate-limited shadow :func:`dump` — the ``HealthPlane.beat`` hook
    (no thread of its own).  SIGKILL/OOM run no excepthook; this keeps a
    <=``blackbox.checkpoint_interval``-stale bundle per host anyway."""
    global _snap_last
    if not _active:
        return None
    if interval is None:
        interval = float(_config.get("blackbox.checkpoint_interval"))
    if interval <= 0:
        return None
    now = time.monotonic()
    with _lock:
        if now - _snap_last < interval:
            return None
        _snap_last = now
    d = _config.get("blackbox.dir") or lease_dir or \
        _config.get("fleet.lease_dir")
    return dump(trigger="shadow", step=step, rank=rank, shadow=True,
                dir=d)


# -- bundle reading ---------------------------------------------------------

_BUNDLE_RE = re.compile(r"^blackbox-(\d+)-(\d+)\.json$")


def list_bundles(dir=None, rank=None):
    """Bundle paths in ``dir`` (default: the resolved bundle dir),
    oldest first by (mtime, name); ``rank`` filters to one host.  No
    integrity check — see :func:`latest_bundle` / :func:`read_bundle`."""
    d = dir or bundle_dir()
    if not d or not os.path.isdir(d):
        return []
    out = []
    for name in os.listdir(d):
        m = _BUNDLE_RE.match(name)
        if not m:
            continue
        if rank is not None and int(m.group(1)) != int(rank):
            continue
        out.append(os.path.join(d, name))
    out.sort(key=lambda p: (os.path.getmtime(p), p))
    return out


def read_bundle(path):
    """Parse one bundle with integrity checks: the ``.sha256`` sidecar
    must verify, the JSON must parse, and the schema tag must match.
    Raises :class:`MXNetError` on a torn or foreign file."""
    from . import serialization as _ser
    _ser.verify_checksum(path, required=True)
    try:
        with open(path, encoding="utf-8") as f:
            doc = json.load(f)
    except ValueError as e:
        raise MXNetError(f"torn postmortem bundle {path}: {e}") from e
    if not isinstance(doc, dict) or doc.get("schema") != BUNDLE_SCHEMA \
            or "meta" not in doc:
        raise MXNetError(
            f"{path} is not a {BUNDLE_SCHEMA} postmortem bundle")
    return doc


def latest_bundle(dir=None, rank=None):
    """Path of the newest bundle for ``rank`` that passes integrity
    checks (torn bundles are skipped, not fatal); None when the host
    left no readable evidence."""
    for path in reversed(list_bundles(dir, rank=rank)):
        try:
            read_bundle(path)
        except (MXNetError, OSError):
            continue
        return path
    return None


def endpoint_report(last=None, dir=None):
    """The ``/postmortem?last=N`` document: newest-first metadata of
    the bundles in the resolved directory (torn ones flagged, never
    fatal)."""
    d = dir or bundle_dir()
    out = {"active": _active, "dir": d or None, "bundles": []}
    paths = list_bundles(d) if d else []
    if last is not None:
        paths = paths[-max(0, int(last)):]
    for path in reversed(paths):
        entry = {"path": path}
        try:
            entry["bytes"] = os.path.getsize(path)
        except OSError:
            entry["bytes"] = None
        try:
            meta = read_bundle(path)["meta"]
            entry["valid"] = True
            entry.update({k: meta.get(k) for k in
                          ("trigger", "reason", "rank", "step", "time",
                           "shadow")})
        except (MXNetError, OSError) as e:
            entry["valid"] = False
            entry["error"] = str(e)
        out["bundles"].append(entry)
    return out


# arm from the environment at import (MXNET_BLACKBOX=1), mirroring
# fault.py, so spawned workers and plain scripts inherit the switch
if _config.get("blackbox.enable"):
    enable()

"""Opt-in JAX persistent compilation cache (mx.config.compilation_cache_dir).

Reference parity: the reference ships compiled-op caches keyed on op
signatures in-process; on a compiler-backed stack the expensive artifact
is the XLA executable, and JAX can persist those to disk so *repeated
runs* — the CI re-run, the resumed preemptible job, the hyperparameter
sweep over one model — skip compilation entirely.  This module arms that
cache from the ``compilation_cache_dir`` knob (env alias
``MXNET_COMPILE_CACHE``) and mirrors JAX's cache activity into
``mx.telemetry``'s ``compile.*`` metrics, next to the in-process
recompile detector (telemetry.note_compile).

Threshold note: JAX by default only persists programs that took >1s to
compile and are >minimal size; we zero both thresholds — an opted-in
cache directory should cache everything, tiny test programs included,
or the knob looks broken on small models.
"""
from __future__ import annotations

import os

from . import config as _config
from . import telemetry as _telemetry

__all__ = ["configure"]

_telemetry.declare_metric(
    "compile.persistent_cache_requests_total", "counter",
    "XLA compilations that consulted the persistent cache")
_telemetry.declare_metric(
    "compile.persistent_cache_hits_total", "counter",
    "XLA compilations served from the persistent cache (miss count = "
    "requests - hits)")
_telemetry.declare_metric(
    "compile.persistent_cache_retrieval_seconds", "histogram",
    "time to load one cached executable from disk",
    buckets=_telemetry.TIME_BUCKETS)

_listener_installed = False

_EVENT_COUNTERS = {
    "/jax/compilation_cache/cache_hits":
        "compile.persistent_cache_hits_total",
    "/jax/compilation_cache/compile_requests_use_cache":
        "compile.persistent_cache_requests_total",
}


def _install_listeners():
    global _listener_installed
    if _listener_installed:
        return
    try:
        from jax import monitoring
    except ImportError:
        return

    def on_event(event, *args, **kwargs):
        if not _telemetry._active:
            return
        name = _EVENT_COUNTERS.get(event)
        if name is not None:
            _telemetry.inc(name)

    def on_duration(event, duration, *args, **kwargs):
        if not _telemetry._active:
            return
        if event == "/jax/compilation_cache/cache_retrieval_time_sec":
            _telemetry.observe("compile.persistent_cache_retrieval_seconds",
                               duration)

    monitoring.register_event_listener(on_event)
    monitoring.register_event_duration_secs_listener(on_duration)
    _listener_installed = True


def configure(path=None):
    """Point JAX's persistent compilation cache at ``path`` (default: the
    ``compilation_cache_dir`` knob).  Returns the armed directory, or
    None when the knob is empty.  Idempotent; safe to call after arrays
    exist (only future compilations consult the cache)."""
    if path is None:
        path = _config.get("compilation_cache_dir")
    if not path:
        return None
    path = os.path.abspath(os.path.expanduser(path))
    os.makedirs(path, exist_ok=True)
    import jax
    jax.config.update("jax_compilation_cache_dir", path)
    for knob, value in (("jax_persistent_cache_min_compile_time_secs", 0.0),
                        ("jax_persistent_cache_min_entry_size_bytes", -1)):
        try:
            jax.config.update(knob, value)
        except (AttributeError, ValueError):  # older/newer jax: keep defaults
            pass
    _install_listeners()
    return path

"""Base utilities: error type, registry, dtype tables.

Reference parity: python/mxnet/base.py (error class, registry plumbing) and
include/mxnet/tensor_blob.h / tuple.h (dtype + shape metadata). Here dtype and
shape metadata ride on jax/numpy dtypes directly; this module keeps the small
amount of framework-global glue.
"""
from __future__ import annotations

import os
import threading

import numpy as onp


class MXNetError(RuntimeError):
    """Framework error type (reference: python/mxnet/base.py MXNetError)."""


# dtype aliases accepted across the API (reference: mshadow type switch /
# python/mxnet/base.py _DTYPE_NP_TO_MX).
_DTYPE_ALIASES = {
    "float32": onp.float32, "float64": onp.float64, "float16": onp.float16,
    "bfloat16": "bfloat16", "uint8": onp.uint8, "int8": onp.int8,
    "int32": onp.int32, "int64": onp.int64, "bool": onp.bool_,
    "uint16": onp.uint16, "uint32": onp.uint32, "uint64": onp.uint64,
    "int16": onp.int16,
}


def np_dtype(dtype):
    """Normalize a user-provided dtype spec to a numpy/jax dtype."""
    if dtype is None:
        return None
    if isinstance(dtype, str):
        if dtype == "bfloat16":
            import jax.numpy as jnp
            return jnp.bfloat16
        if dtype in _DTYPE_ALIASES:
            return _DTYPE_ALIASES[dtype]
    return onp.dtype(dtype) if not hasattr(dtype, "dtype") else dtype


class _Registry:
    """Name -> object registry with alias support.

    Reference parity: dmlc registry pattern (dmlc::Registry) used for ops,
    optimizers, initializers, kvstores, metrics.
    """

    def __init__(self, kind):
        self.kind = kind
        self._map = {}
        self._lock = threading.Lock()

    def register(self, name=None):
        def _reg(cls):
            key = (name or cls.__name__).lower()
            with self._lock:
                self._map[key] = cls
            return cls
        return _reg

    def get(self, name):
        key = name.lower()
        if key not in self._map:
            raise MXNetError(
                f"Unknown {self.kind} '{name}'. Registered: {sorted(self._map)}")
        return self._map[key]

    def find(self, name):
        return self._map.get(name.lower())

    def list(self):
        return sorted(self._map)


def get_env(name, default=None, typ=str):
    """Typed environment-variable read.

    Reference parity: dmlc::GetEnv — MXNet configures itself through ~72 env
    vars (docs/.../env_var.md); we keep the same override mechanism.
    """
    val = os.environ.get(name)
    if val is None:
        return default
    if typ is bool:
        return val not in ("0", "false", "False", "")
    return typ(val)


def classproperty(fn):
    class _CP:
        def __get__(self, obj, owner):
            return fn(owner)
    return _CP()

"""mxnet_tpu — a TPU-native deep learning framework with Apache MXNet 2.0's
capabilities.

This is NOT a port of MXNet: the compute path is JAX/XLA (eager dispatch +
``hybridize()``-to-``jax.jit`` tracing), parallelism is ``jax.sharding`` meshes
with XLA collectives over ICI/DCN, and hot kernels are Pallas. The *API surface*
mirrors MXNet (reference: ``python/mxnet/__init__.py`` of apache/incubator-mxnet
2.0) so that Gluon user code carries over:

    import mxnet_tpu as mx
    from mxnet_tpu import gluon, autograd, np, npx

Layer map vs the reference (see SURVEY.md):
  - MXNet ThreadedEngine (src/engine/)      -> PJRT async dispatch (jax arrays
    are futures; ``wait_to_read`` = block_until_ready)
  - NDArray/Chunk/Storage (src/ndarray/)    -> ndarray over jax.Array (+sharding)
  - deferred-compute trace -> CachedOp      -> trace -> jax.jit executable cache
  - KVStore (src/kvstore/)                  -> XLA collectives on a device mesh
  - src/operator/** kernels                 -> jnp/lax lowering + Pallas kernels
"""

__version__ = "2.0.0a1"

# must run before anything touches the JAX backend (see _dist_init docstring)
from ._dist_init import ensure_distributed as _ensure_distributed
_ensure_distributed()

from . import base
from .base import MXNetError
from . import config
from . import telemetry
from . import fault
from . import trace
from . import insight
from . import blackbox
from . import goodput
from . import context
from .context import Context, cpu, gpu, tpu, cpu_pinned, current_context, device, num_gpus, num_tpus
from . import engine
from . import pipeline
from . import _compile_cache
from . import numpy as np  # noqa: F401
from . import numpy_extension as npx  # noqa: F401
from . import ndarray
from . import ndarray as nd
from . import autograd
from . import random
from . import initializer
from . import initializer as init
from . import optimizer
from . import lr_scheduler
from . import kvstore
from .kvstore import KVStore
from . import gluon
from . import parallel
from . import amp
from . import profiler
from . import util
from . import runtime
from . import library
from . import log
from . import registry
from . import test_utils
from . import symbol
from . import symbol as sym
from . import recordio
from . import io
from . import image
from . import contrib
from . import serialization
from . import resilience
from . import stream
from . import fleet
from . import serve
from . import servefleet
from . import autotune
from . import storage
from . import callback
from . import model
from . import operator
from . import name
from . import attribute
from . import error
from . import dlpack
from . import libinfo
from . import rtc
from . import executor
from . import visualization

viz = visualization
try:
    from . import onnx
except ImportError:  # protobuf missing: degrade the feature, not the package
    import types as _types

    class _OnnxUnavailable(_types.ModuleType):
        def __getattr__(self, name):
            raise ImportError(
                "mx.onnx requires the 'protobuf' package (pip install "
                "protobuf)")
    onnx = _OnnxUnavailable("mxnet_tpu.onnx")

kv = kvstore

if config.get("profiler.autostart"):
    profiler.set_state("run")

if config.get("compilation_cache_dir"):
    _compile_cache.configure()


def waitall():
    """Block until all pending device computation is done.

    Reference parity: ``mx.nd.waitall`` / ``Engine::WaitForAll``
    (include/mxnet/engine.h:255). On TPU, pending work is the set of
    undelivered jax.Arrays; the engine module tracks live arrays.
    """
    engine.wait_all()

"""npx.random — extension random samplers.

Reference parity: python/mxnet/numpy_extension/random.py
(__all__ = seed/bernoulli/normal_n/uniform_n). The implementations live
at npx top level; this module is the documented submodule spelling.
Other sampler names fall through to mx.np.random (the reference routes
them the same way).
"""
from . import bernoulli, normal_n, seed, uniform_n  # noqa: F401

__all__ = ["seed", "bernoulli", "normal_n", "uniform_n"]


def __getattr__(name):
    from ..numpy import random as _np_random
    return getattr(_np_random, name)

"""mx.npx — NumPy-extension (neural-network) operators.

Reference parity: python/mxnet/numpy_extension/ over the C++ op library
src/operator/nn/* (convolution, batch_norm, layer_norm, softmax, pooling,
dropout, fully_connected, rnn-inl.h fused RNN), src/operator/contrib/
transformer.cc:675-828 (interleaved multi-head-attention matmuls) and
src/operator/npx_control_flow.cc (foreach/while_loop/cond subgraph ops).

TPU-native design: every op is a jnp/lax composition dispatched through
``_invoke`` (async + autograd-recorded); XLA fuses the elementwise tails into
the MXU matmuls/convs. Convolution/pooling lower to
``lax.conv_general_dilated`` / ``lax.reduce_window`` — the XLA ops the TPU
compiler tiles onto the MXU directly (replacing the cuDNN paths). The fused
RNN op is a ``lax.scan`` (compiler-friendly loop), and the control-flow ops
are ``lax.cond`` / ``lax.while_loop`` / ``lax.scan`` so they stay jittable.
"""
from __future__ import annotations

import functools
import threading

import jax
import jax.numpy as jnp
from jax import lax

from ..base import MXNetError, np_dtype
from ..numpy.multiarray import ndarray, _invoke, _wrap, _wrap_out

# ---------------------------------------------------------------------------
# numpy-mode toggles (reference: npx.set_np / util.py scopes). The new
# framework is numpy-semantics-only, so these are compatibility facades.
# ---------------------------------------------------------------------------

_np_state = threading.local()


def set_np(shape=True, array=True, dtype=False):
    _np_state.active = True


def reset_np():
    _np_state.active = False


def is_np_array():
    return True


def is_np_shape():
    return True


def is_np_default_dtype():
    return getattr(_np_state, "np_dtype", False)


def use_np(func):
    return func


def use_np_array(func):
    return func


def use_np_shape(func):
    return func


def waitall():
    from .. import engine
    engine.wait_all()


def cpu(i=0):
    from ..context import cpu as _cpu
    return _cpu(i)


def gpu(i=0):
    from ..context import gpu as _gpu
    return _gpu(i)


def num_gpus():
    from ..context import num_gpus as _n
    return _n()


# ---------------------------------------------------------------------------
# activations / softmax
# ---------------------------------------------------------------------------

_ACTS = {
    "relu": jax.nn.relu,
    "sigmoid": jax.nn.sigmoid,
    "tanh": jnp.tanh,
    "softrelu": jax.nn.softplus,
    "softsign": jax.nn.soft_sign,
    "gelu": jax.nn.gelu,
    "silu": jax.nn.silu,
    "log_sigmoid": jax.nn.log_sigmoid,
    "mish": lambda x: x * jnp.tanh(jax.nn.softplus(x)),
}


def activation(data, act_type="relu", **kwargs):
    """Reference: src/operator/nn/activation.cc."""
    if act_type not in _ACTS:
        raise MXNetError(f"unknown act_type {act_type!r}")
    return _invoke(_ACTS[act_type], (data,), name=f"activation:{act_type}")


def relu(data):
    return _invoke(jax.nn.relu, (data,))


def sigmoid(data):
    return _invoke(jax.nn.sigmoid, (data,))


def leaky_relu(data, gamma=None, act_type="leaky", slope=0.25,
               lower_bound=0.125, upper_bound=0.334, **kwargs):
    """Reference: src/operator/leaky_relu.cc (leaky/prelu/elu/selu/gelu/rrelu)."""
    nm = f"leaky_relu:{act_type}"  # attr-suffixed for AMP conditional lists
    if act_type == "leaky":
        return _invoke(lambda x: jax.nn.leaky_relu(x, slope), (data,), name=nm)
    if act_type == "prelu":
        return _invoke(lambda x, g: jnp.where(x >= 0, x, g * x),
                       (data, gamma), name=nm)
    if act_type == "elu":
        return _invoke(lambda x: jax.nn.elu(x, slope), (data,), name=nm)
    if act_type == "selu":
        return _invoke(jax.nn.selu, (data,), name=nm)
    if act_type == "gelu":
        return _invoke(lambda x: jax.nn.gelu(x, approximate=False), (data,),
                       name=nm)
    if act_type == "rrelu":
        mid = (lower_bound + upper_bound) / 2.0
        return _invoke(lambda x: jax.nn.leaky_relu(x, mid), (data,), name=nm)
    raise MXNetError(f"unknown leaky_relu act_type {act_type!r}")


def _length_mask(h, ln, axis):
    """Positions-beyond-length mask (reference: softmax-inl.h:132 — length
    has the data's shape minus the softmax axis; a 1-D length broadcasts
    over the middle dims)."""
    ax = axis % h.ndim
    pos = jnp.arange(h.shape[ax])
    shape = [1] * h.ndim
    shape[ax] = h.shape[ax]
    if ln.ndim == h.ndim - 1:
        ln_b = jnp.expand_dims(ln, ax)
    else:
        ln_b = ln.reshape((ln.shape[0],) + (1,) * (h.ndim - 1))
    return pos.reshape(shape) < ln_b


def softmax(data, length=None, axis=-1, temperature=None, use_length=False,
            dtype=None):
    """Reference: src/operator/nn/softmax.cc (with optional length masking;
    masked positions write 0.0, softmax-inl.h:142)."""
    def fn(x, ln=None):
        h = x / temperature if temperature else x
        if ln is not None:
            mask = _length_mask(h, ln, axis)
            out = jax.nn.softmax(jnp.where(mask, h, -jnp.inf), axis)
            return jnp.where(mask, out, 0.0).astype(np_dtype(dtype) or x.dtype)
        return jax.nn.softmax(h, axis).astype(np_dtype(dtype) or x.dtype)
    if length is not None or use_length:
        return _invoke(fn, (data, length), name="softmax")
    return _invoke(fn, (data,), name="softmax")


def log_softmax(data, axis=-1, temperature=None, dtype=None, use_length=False,
                length=None):
    """Reference: src/operator/nn/softmax.cc log variant; masked positions
    write 0.0 like the softmax kernel (same OType(0.0f) store)."""
    def fn(x, ln=None):
        h = x / temperature if temperature else x
        if ln is not None:
            mask = _length_mask(h, ln, axis)
            out = jax.nn.log_softmax(jnp.where(mask, h, -jnp.inf), axis)
            return jnp.where(mask, out, 0.0).astype(np_dtype(dtype) or x.dtype)
        return jax.nn.log_softmax(h, axis).astype(np_dtype(dtype) or x.dtype)
    if length is not None or use_length:
        return _invoke(fn, (data, length), name="log_softmax")
    return _invoke(fn, (data,), name="log_softmax")


def masked_softmax(data, mask, axis=-1, temperature=1.0):
    def fn(x, m):
        h = x / temperature if temperature else x
        h = jnp.where(m, h, -jnp.inf)
        return jnp.where(m, jax.nn.softmax(h, axis), 0.0)
    return _invoke(fn, (data, mask), name="masked_softmax")


def masked_log_softmax(data, mask, axis=-1, temperature=1.0):
    def fn(x, m):
        h = x / temperature if temperature else x
        h = jnp.where(m, h, -jnp.inf)
        return jnp.where(m, jax.nn.log_softmax(h, axis), -jnp.inf)
    return _invoke(fn, (data, mask), name="masked_log_softmax")


def softmin(data, axis=-1, temperature=None, dtype=None):
    return softmax(-data if not isinstance(data, ndarray) else data * -1,
                   axis=axis, temperature=temperature, dtype=dtype)


# ---------------------------------------------------------------------------
# dense / conv / pooling / norm  (the MXU path)
# ---------------------------------------------------------------------------

def fully_connected(x, weight, bias=None, num_hidden=None, no_bias=False,
                    flatten=True):
    """Reference: src/operator/nn/fully_connected.cc. weight is (units, in)."""
    def fn(x_, w, b=None):
        h = x_.reshape(x_.shape[0], -1) if flatten else x_
        out = jnp.matmul(h, w.T)
        if b is not None:
            out = out + b
        return out
    if bias is None or no_bias:
        return _invoke(fn, (x, weight), name="fully_connected")
    return _invoke(fn, (x, weight, bias), name="fully_connected")


def convolution(data=None, weight=None, bias=None, kernel=None, stride=None,
                dilate=None, pad=None, num_filter=1, num_group=1,
                workspace=1024, no_bias=False, cudnn_tune=None,
                cudnn_off=False, layout=None):
    """Reference: src/operator/nn/convolution.cc (cuDNN path rnn-inl style).

    Lowers to lax.conv_general_dilated — XLA maps this straight onto the MXU.
    Layouts supported: NCW / NCHW / NCDHW (MXNet defaults) and NWC/NHWC/NDHWC.
    """
    nd = data.ndim - 2
    if layout is None:
        layout = {1: "NCW", 2: "NCHW", 3: "NCDHW"}[nd]
    channel_last = layout[-1] == "C"
    spatial = "DHW"[3 - nd:]
    lhs_spec = layout
    rhs_spec = "OI" + spatial
    out_spec = layout
    stride = tuple(stride) if stride else (1,) * nd
    dilate = tuple(dilate) if dilate else (1,) * nd
    pad = tuple(pad) if pad else (0,) * nd
    padding = [(p, p) for p in pad]

    def fn(x, w, b=None):
        dn = lax.conv_dimension_numbers(x.shape, w.shape,
                                        (lhs_spec, rhs_spec, out_spec))
        out = lax.conv_general_dilated(
            x, w, window_strides=stride, padding=padding,
            rhs_dilation=dilate, dimension_numbers=dn,
            feature_group_count=num_group)
        if b is not None:
            shape = [1] * out.ndim
            shape[out_spec.index("C")] = b.shape[0]
            out = out + b.reshape(shape)
        return out

    if bias is None or no_bias:
        return _invoke(fn, (data, weight), name="convolution")
    return _invoke(fn, (data, weight, bias), name="convolution")


def deconvolution(data=None, weight=None, bias=None, kernel=None, stride=None,
                  dilate=None, pad=None, adj=None, target_shape=None,
                  num_filter=1, num_group=1, workspace=512, no_bias=True,
                  cudnn_tune=None, cudnn_off=False, layout=None):
    """Reference: src/operator/nn/deconvolution.cc (transposed conv)."""
    nd = data.ndim - 2
    layout = layout or {1: "NCW", 2: "NCHW", 3: "NCDHW"}[nd]
    spatial = "DHW"[3 - nd:]
    stride = tuple(stride) if stride else (1,) * nd
    dilate = tuple(dilate) if dilate else (1,) * nd
    pad = tuple(pad) if pad else (0,) * nd

    def fn(x, w, b=None):
        dn = lax.conv_dimension_numbers(x.shape, w.shape,
                                        (layout, "IO" + spatial, layout))
        k = [(w.shape[2 + i] - 1) * dilate[i] + 1 for i in range(nd)]
        padding = [(k[i] - 1 - pad[i], k[i] - 1 - pad[i]) for i in range(nd)]
        # transposed conv = fractionally-strided conv with spatially-flipped
        # kernel read as (I, O, spatial)
        w_flipped = jnp.flip(w, axis=tuple(range(2, 2 + nd)))
        if num_group > 1:
            # weight is (C_in, C_out/g, *k); lax wants I=C_in/g with O=C_out
            # blocked by group: regroup (g, C_in/g, C_out/g) -> (C_in/g, g*C_out/g)
            cin, cog = w.shape[0], w.shape[1]
            ksp = w.shape[2:]
            w_flipped = (w_flipped
                         .reshape((num_group, cin // num_group, cog) + ksp)
                         .transpose((1, 0, 2) + tuple(range(3, 3 + nd)))
                         .reshape((cin // num_group, num_group * cog) + ksp))
        out = lax.conv_general_dilated(
            x, w_flipped, window_strides=(1,) * nd, padding=padding,
            lhs_dilation=stride, rhs_dilation=dilate, dimension_numbers=dn,
            feature_group_count=num_group)
        if b is not None:
            shape = [1] * out.ndim
            shape[layout.index("C")] = b.shape[0]
            out = out + b.reshape(shape)
        return out

    if bias is None or no_bias:
        return _invoke(fn, (data, weight), name="deconvolution")
    return _invoke(fn, (data, weight, bias), name="deconvolution")


def deformable_convolution(data=None, offset=None, weight=None, bias=None,
                           kernel=None, stride=None, dilate=None, pad=None,
                           num_filter=1, num_group=1, num_deformable_group=1,
                           workspace=1024, no_bias=False, layout=None,
                           **kwargs):
    """DCN v1 (reference: src/operator/contrib/deformable_convolution.cc).

    Bilinear grid-sampling gathers + one MXU einsum; see ops/deformable.py.
    NCHW only (the reference CUDA kernel is also NCHW-only).
    """
    from ..ops.deformable import deformable_conv2d
    if layout not in (None, "NCHW"):
        raise MXNetError("deformable_convolution supports NCHW only")
    kernel = tuple(kernel)
    kw = dict(kernel=kernel, stride=tuple(stride) if stride else (1, 1),
              pad=tuple(pad) if pad else (0, 0),
              dilate=tuple(dilate) if dilate else (1, 1),
              num_group=num_group,
              num_deformable_group=num_deformable_group)
    if bias is None or no_bias:
        return _invoke(lambda x, o, w: deformable_conv2d(x, o, w, **kw),
                       (data, offset, weight), name="deformable_convolution")
    return _invoke(lambda x, o, w, b: deformable_conv2d(x, o, w, b, **kw),
                   (data, offset, weight, bias),
                   name="deformable_convolution")


def modulated_deformable_convolution(data=None, offset=None, mask=None,
                                     weight=None, bias=None, kernel=None,
                                     stride=None, dilate=None, pad=None,
                                     num_filter=1, num_group=1,
                                     num_deformable_group=1, workspace=1024,
                                     no_bias=False, layout=None, **kwargs):
    """DCN v2 (reference: src/operator/contrib/modulated_deformable_convolution.cc).
    The mask input multiplies each sampled value (caller applies sigmoid*2,
    matching the reference Gluon block)."""
    from ..ops.deformable import deformable_conv2d
    if layout not in (None, "NCHW"):
        raise MXNetError("modulated_deformable_convolution supports NCHW only")
    kw = dict(kernel=tuple(kernel),
              stride=tuple(stride) if stride else (1, 1),
              pad=tuple(pad) if pad else (0, 0),
              dilate=tuple(dilate) if dilate else (1, 1),
              num_group=num_group,
              num_deformable_group=num_deformable_group)
    if bias is None or no_bias:
        return _invoke(
            lambda x, o, m, w: deformable_conv2d(x, o, w, mask=m, **kw),
            (data, offset, mask, weight),
            name="modulated_deformable_convolution")
    return _invoke(
        lambda x, o, m, w, b: deformable_conv2d(x, o, w, b, mask=m, **kw),
        (data, offset, mask, weight, bias),
        name="modulated_deformable_convolution")


def pooling(data, kernel=1, stride=None, pad=None, pool_type="max",
            pooling_convention="valid", global_pool=False, p_value=2,
            count_include_pad=True, layout="NCHW", cudnn_off=False):
    """Reference: src/operator/nn/pooling.cc. lax.reduce_window lowering."""
    nd = data.ndim - 2
    if isinstance(kernel, int):
        kernel = (kernel,) * nd
    kernel = tuple(kernel)
    stride = tuple(stride) if stride else kernel
    pad = tuple(pad) if pad else (0,) * nd
    ch_axis = layout.index("C")
    sp_axes = [i for i in range(data.ndim) if i not in (0, ch_axis)]

    def fn(x):
        if global_pool:
            if pool_type == "max":
                return jnp.max(x, axis=tuple(sp_axes), keepdims=True)
            if pool_type == "avg":
                return jnp.mean(x, axis=tuple(sp_axes), keepdims=True)
            if pool_type == "sum":
                return jnp.sum(x, axis=tuple(sp_axes), keepdims=True)
            return jnp.power(jnp.sum(jnp.power(jnp.abs(x), p_value),
                                     axis=tuple(sp_axes), keepdims=True),
                             1.0 / p_value)
        dims, strides, padding = [1] * x.ndim, [1] * x.ndim, [(0, 0)] * x.ndim
        for i, ax in enumerate(sp_axes):
            dims[ax], strides[ax] = kernel[i], stride[i]
            padding[ax] = (pad[i], pad[i])
        if pool_type == "max":
            init = -jnp.inf if jnp.issubdtype(x.dtype, jnp.floating) else jnp.iinfo(x.dtype).min
            return lax.reduce_window(x, init, lax.max, dims, strides, padding)
        s = lax.reduce_window(
            x if pool_type != "lp" else jnp.power(jnp.abs(x), p_value),
            0.0, lax.add, dims, strides, padding)
        if pool_type == "sum":
            return s
        if pool_type == "lp":
            return jnp.power(s, 1.0 / p_value)
        if count_include_pad:
            denom = 1
            for i in range(nd):
                denom *= kernel[i]
            return s / denom
        ones = jnp.ones_like(x)
        cnt = lax.reduce_window(ones, 0.0, lax.add, dims, strides, padding)
        return s / cnt

    return _invoke(fn, (data,), name=f"pooling:{pool_type}")


def batch_norm(x, gamma, beta, running_mean, running_var, eps=1e-3,
               momentum=0.9, fix_gamma=True, use_global_stats=False,
               output_mean_var=False, axis=1, cudnn_off=False,
               min_calib_range=None, max_calib_range=None):
    """Reference: src/operator/nn/batch_norm.cc.

    Training mode (autograd.is_training and not use_global_stats) uses batch
    statistics and updates the running-stat arrays *in place* (version bump on
    the same wrappers — the Gluon layer passes its aux Parameters here, which
    is how the reference's mutable aux states behave).
    """
    from .. import autograd as _ag
    training = _ag.is_training() and not use_global_stats

    def fn(x_, g, b):
        red = tuple(i for i in range(x_.ndim) if i != axis)
        shape = [1] * x_.ndim
        shape[axis] = x_.shape[axis]
        if training:
            # Single-pass statistics (E[x^2] - E[x]^2, fp32 accumulation):
            # both reductions share one read of x, which matters because the
            # training step is HBM-bandwidth-bound on TPU (profiled: the
            # two-pass mean/var formulation costs ~8% of a ResNet-50 step).
            xf = x_.astype(jnp.float32)
            mean = jnp.mean(xf, axis=red)
            # clamp: E[x^2]-E[x]^2 can go slightly negative by cancellation
            var = jnp.maximum(jnp.mean(xf * xf, axis=red) - mean * mean, 0.0)
        else:
            mean = running_mean._data
            var = running_var._data
        g_ = jnp.ones_like(g) if fix_gamma else g
        inv = lax.rsqrt((var + eps).astype(jnp.float32))
        # fold (mean, inv, gamma, beta) into a per-channel scale/shift so the
        # apply pass is one fused multiply-add in the compute dtype
        scale = (inv * g_).astype(x_.dtype).reshape(shape)
        shift = (b - mean * inv * g_).astype(x_.dtype).reshape(shape)
        out = x_ * scale + shift
        return (out, mean, var) if (training or output_mean_var) else out

    res = _invoke(fn, (x, gamma, beta), name="batch_norm")
    if training:
        out, mean, var = res
        m = momentum
        running_mean._rebind(
            (m * running_mean._data
             + (1 - m) * lax.stop_gradient(mean._data)).astype(running_mean.dtype))
        running_var._rebind(
            (m * running_var._data
             + (1 - m) * lax.stop_gradient(var._data)).astype(running_var.dtype))
        return (out, mean, var) if output_mean_var else out
    return res


def fused_conv_bn_relu(x, weight, gamma, beta, running_mean, running_var,
                       momentum=0.9, eps=1e-5, interpret=None):
    """Training-mode relu(bn(conv3x3_s1(x, w))) with the Pallas fused
    backward (ops/pallas_conv_bwd.py — dy recomputed in VMEM, dgrad+wgrad
    share one read of the saved tensors).

    NCHW in/out (transposed to the kernel's NHWC inside the traced fn so
    XLA folds the relayout into its own layout assignment); weight OIHW.
    Running stats update exactly like npx.batch_norm.
    """
    from ..ops.pallas_conv_bwd import fused_cbr_train
    if interpret is None:
        import jax as _jax
        interpret = _jax.default_backend() != "tpu"

    def fn(x_, w, g, b):
        xh = jnp.transpose(x_, (0, 2, 3, 1))          # NCHW -> NHWC
        wh = jnp.transpose(w, (2, 3, 1, 0))           # OIHW -> HWIO
        a, mean, var = fused_cbr_train(xh, wh, g, b, eps, interpret)
        return jnp.transpose(a, (0, 3, 1, 2)), mean, var

    out, mean, var = _invoke(fn, (x, weight, gamma, beta),
                             name="fused_conv_bn_relu")
    m = momentum
    running_mean._rebind(
        (m * running_mean._data
         + (1 - m) * lax.stop_gradient(mean._data)).astype(running_mean.dtype))
    running_var._rebind(
        (m * running_var._data
         + (1 - m) * lax.stop_gradient(var._data)).astype(running_var.dtype))
    return out


def layer_norm(data, gamma=None, beta=None, axis=-1, eps=1e-5):
    """Reference: src/operator/nn/layer_norm.cc."""
    def fn(x, g, b):
        mean = jnp.mean(x, axis=axis, keepdims=True)
        var = jnp.var(x, axis=axis, keepdims=True)
        out = (x - mean) * lax.rsqrt(var + eps)
        shape = [1] * x.ndim
        shape[axis] = x.shape[axis]
        return out * g.reshape(shape) + b.reshape(shape)
    return _invoke(fn, (data, gamma, beta), name="layer_norm")


def group_norm(data, gamma=None, beta=None, num_groups=1, eps=1e-5):
    """Reference: src/operator/nn/group_norm.cc (N, C, ...) layout."""
    def fn(x, g, b):
        n, c = x.shape[0], x.shape[1]
        xg = x.reshape((n, num_groups, c // num_groups) + x.shape[2:])
        red = tuple(range(2, xg.ndim))
        mean = jnp.mean(xg, axis=red, keepdims=True)
        var = jnp.var(xg, axis=red, keepdims=True)
        out = ((xg - mean) * lax.rsqrt(var + eps)).reshape(x.shape)
        shape = [1, c] + [1] * (x.ndim - 2)
        return out * g.reshape(shape) + b.reshape(shape)
    return _invoke(fn, (data, gamma, beta), name="group_norm")


def instance_norm(data, gamma=None, beta=None, eps=1e-3):
    """Reference: src/operator/instance_norm.cc."""
    def fn(x, g, b):
        red = tuple(range(2, x.ndim))
        mean = jnp.mean(x, axis=red, keepdims=True)
        var = jnp.var(x, axis=red, keepdims=True)
        out = (x - mean) * lax.rsqrt(var + eps)
        shape = [1, x.shape[1]] + [1] * (x.ndim - 2)
        return out * g.reshape(shape) + b.reshape(shape)
    return _invoke(fn, (data, gamma, beta), name="instance_norm")


def l2_normalization(data, eps=1e-10, mode="instance"):
    def fn(x):
        if mode == "channel":
            norm = jnp.sqrt(jnp.sum(x * x, axis=1, keepdims=True) + eps)
        elif mode == "spatial":
            norm = jnp.sqrt(jnp.sum(x * x, axis=tuple(range(2, x.ndim)),
                                    keepdims=True) + eps)
        else:
            norm = jnp.sqrt(jnp.sum(x.reshape(x.shape[0], -1) ** 2, axis=1)
                            + eps).reshape((-1,) + (1,) * (x.ndim - 1))
        return x / norm
    return _invoke(fn, (data,), name="l2_normalization")


def dropout(data, p=0.5, mode="training", axes=(), cudnn_off=False):
    """Reference: src/operator/nn/dropout.cc. Keys from mx.random's global
    threefry stream; identity outside autograd.train_mode."""
    from .. import autograd as _ag
    from .. import random as _r
    if p == 0:
        return data
    if mode != "always" and not _ag.is_training():
        return data
    key = _r._next_key()

    def fn(x):
        shape = list(x.shape)
        for ax in (axes or ()):
            shape[ax] = 1
        keep = jax.random.bernoulli(key, 1.0 - p, tuple(shape))
        return jnp.where(keep, x / (1.0 - p), 0.0).astype(x.dtype)
    return _invoke(fn, (data,), name="dropout")


# ---------------------------------------------------------------------------
# embedding / indexing ops
# ---------------------------------------------------------------------------

def embedding(data, weight, input_dim=None, output_dim=None, dtype="float32",
              sparse_grad=False):
    """Reference: src/operator/tensor/indexing_op.cc (Embedding).

    ``sparse_grad=True`` records a backward that yields a
    ``RowSparseNDArray`` cotangent for the weight (the reference's
    row_sparse gradient path feeding lazy_update optimizers and kvstore
    row_sparse push) — O(batch) rows instead of an O(vocab) dense scatter.
    """
    idx = data._data if isinstance(data, ndarray) else jnp.asarray(data)
    if not sparse_grad:
        return _invoke(lambda w: jnp.take(w, idx.astype(jnp.int32), axis=0),
                       (weight,), name="embedding")

    from .. import autograd as _ag
    from ..ndarray.sparse import RowSparseNDArray, dedupe_coo
    from ..numpy.multiarray import _wrap
    w_nd = weight if isinstance(weight, ndarray) else _wrap(jnp.asarray(weight))
    idx32 = idx.astype(jnp.int32)
    out = _wrap(jnp.take(w_nd._data, idx32, axis=0))
    if _ag.is_recording() and w_nd._entry is not None:
        vocab = int(w_nd.shape[0])

        def vjp_sparse(cots):
            dy = cots[0] if isinstance(cots, (tuple, list)) else cots
            dim = dy.shape[-1]
            flat_idx = idx32.reshape(-1)
            flat_dy = dy.reshape(-1, dim)
            uidx, uvals = dedupe_coo(flat_idx, flat_dy, vocab)
            return (RowSparseNDArray(_wrap(uvals), _wrap(uidx),
                                     (vocab, dim)),)

        _ag._record_op(vjp_sparse, [w_nd], [out], "embedding_sparse")
    return out


def one_hot(data, depth, on_value=1.0, off_value=0.0, dtype="float32"):
    idx = data._data if isinstance(data, ndarray) else jnp.asarray(data)
    return _wrap_out(jax.nn.one_hot(idx, depth, dtype=np_dtype(dtype))
                     * (on_value - off_value) + off_value)


def pick(data, index, axis=-1, mode="clip", keepdims=False):
    """Reference: src/operator/tensor/broadcast_reduce_op_index.cc (pick)."""
    def fn(x, idx=None):
        i = (idx if idx is not None else
             (index._data if isinstance(index, ndarray) else jnp.asarray(index)))
        i = i.astype(jnp.int32)
        if mode == "clip":
            i = jnp.clip(i, 0, x.shape[axis] - 1)
        else:
            i = i % x.shape[axis]
        picked = jnp.take_along_axis(x, jnp.expand_dims(i, axis), axis)
        return picked if keepdims else jnp.squeeze(picked, axis)
    return _invoke(fn, (data,), name="pick")


def topk(data, axis=-1, k=1, ret_typ="indices", is_ascend=False, dtype="float32"):
    """Reference: src/operator/tensor/ordering_op.cc."""
    def fn(x):
        xm = jnp.moveaxis(x, axis, -1)
        vals, idx = lax.top_k(-xm if is_ascend else xm, k)
        if is_ascend:
            vals = -vals
        vals = jnp.moveaxis(vals, -1, axis)
        idx = jnp.moveaxis(idx, -1, axis).astype(np_dtype(dtype))
        if ret_typ == "value":
            return vals
        if ret_typ == "both":
            return (vals, idx)
        return idx
    return _invoke(fn, (data,), name="topk")


def gather_nd(data, indices):
    idx = indices._data if isinstance(indices, ndarray) else jnp.asarray(indices)
    idx = tuple(idx.astype(jnp.int32))
    return _invoke(lambda x: x[idx], (data,), name="gather_nd")


def scatter_nd(data, indices, shape):
    idx = indices._data if isinstance(indices, ndarray) else jnp.asarray(indices)
    idx = tuple(idx.astype(jnp.int32))
    return _invoke(lambda d: jnp.zeros(shape, d.dtype).at[idx].add(d),
                   (data,), name="scatter_nd")


def index_update(data, indices, value):
    idx = indices._data if isinstance(indices, ndarray) else jnp.asarray(indices)
    idx = tuple(idx.astype(jnp.int32))
    return _invoke(lambda d, v: d.at[idx].set(v), (data, value))


def index_add(data, indices, value):
    idx = indices._data if isinstance(indices, ndarray) else jnp.asarray(indices)
    idx = tuple(idx.astype(jnp.int32))
    return _invoke(lambda d, v: d.at[idx].add(v), (data, value))


def sequence_mask(data, sequence_length=None, use_sequence_length=False,
                  value=0.0, axis=0):
    """Reference: src/operator/sequence_mask.cc. axis is the sequence axis
    (0: (seq, batch, ...), 1: (batch, seq, ...))."""
    if not use_sequence_length or sequence_length is None:
        return data

    def fn(x, ln):
        pos = jnp.arange(x.shape[axis])
        if axis == 0:
            mask = pos[:, None] < ln[None, :]
        else:
            mask = pos[None, :] < ln[:, None]
        mask = mask.reshape(mask.shape + (1,) * (x.ndim - 2))
        return jnp.where(mask, x, value)
    return _invoke(fn, (data, sequence_length), name="sequence_mask")


def sequence_last(data, sequence_length=None, use_sequence_length=False, axis=0):
    def fn(x, ln=None):
        if ln is None:
            return jnp.take(x, -1, axis)
        idx = (ln - 1).astype(jnp.int32)
        xm = jnp.moveaxis(x, axis, 0)  # (seq, batch, ...)
        return jnp.take_along_axis(
            xm, idx.reshape((1, -1) + (1,) * (xm.ndim - 2)), 0)[0]
    if use_sequence_length and sequence_length is not None:
        return _invoke(fn, (data, sequence_length), name="sequence_last")
    return _invoke(fn, (data,), name="sequence_last")


def sequence_reverse(data, sequence_length=None, use_sequence_length=False, axis=0):
    def fn(x, ln=None):
        if ln is None:
            return jnp.flip(x, axis)
        seq = x.shape[0]
        pos = jnp.arange(seq)[:, None]
        rev = jnp.where(pos < ln[None, :], ln[None, :] - 1 - pos, pos)
        return jnp.take_along_axis(
            x, rev.reshape(rev.shape + (1,) * (x.ndim - 2)).astype(jnp.int32), 0)
    if use_sequence_length and sequence_length is not None:
        return _invoke(fn, (data, sequence_length), name="sequence_reverse")
    return _invoke(fn, (data,), name="sequence_reverse")


def reshape_like(lhs, rhs):
    return _invoke(lambda a: jnp.reshape(a, rhs.shape), (lhs,), name="reshape_like")


def arange_like(data, start=0.0, step=1.0, repeat=1, ctx=None, axis=None):
    n = data.size if axis is None else data.shape[axis]
    return _wrap(jnp.arange(start, start + step * n, step, jnp.float32))


def broadcast_like(lhs, rhs, lhs_axes=None, rhs_axes=None):
    return _invoke(lambda a: jnp.broadcast_to(a, rhs.shape), (lhs,),
                   name="broadcast_like")


def slice(data, begin, end, step=None):  # noqa: A001 - reference op name
    import builtins
    step = step or (None,) * len(begin)
    key = tuple(builtins.slice(b, e, s) for b, e, s in zip(begin, end, step))
    return data[key]


def slice_axis(data, axis, begin, end):
    import builtins
    key = [builtins.slice(None)] * data.ndim
    key[axis] = builtins.slice(begin, end)
    return data[tuple(key)]


def slice_like(data, shape_like, axes=None):
    import builtins
    key = [builtins.slice(None)] * data.ndim
    for ax in (axes if axes is not None else range(data.ndim)):
        key[ax] = builtins.slice(0, shape_like.shape[ax])
    return data[tuple(key)]


def where(condition, x, y):
    return _invoke(jnp.where, (condition, x, y), name="where")


def erf(data):
    return _invoke(jax.scipy.special.erf, (data,))


def erfinv(data):
    return _invoke(jax.scipy.special.erfinv, (data,))


def gamma(data):
    return _invoke(lambda x: jnp.exp(jax.scipy.special.gammaln(x)), (data,))


def gammaln(data):
    return _invoke(jax.scipy.special.gammaln, (data,))


def digamma(data):
    return _invoke(jax.scipy.special.digamma, (data,))


def clip_global_norm(arrays, max_norm, check_isfinite=True):
    from ..gluon.utils import clip_global_norm as _cgn
    return _cgn(arrays, max_norm, check_isfinite)


# ---------------------------------------------------------------------------
# fused RNN op (reference: src/operator/rnn-inl.h:601-699, cuDNN fused path)
# ---------------------------------------------------------------------------

def rnn(data=None, parameters=None, state=None, state_cell=None, mode="lstm",
        state_size=None, num_layers=1, bidirectional=False, p=0.0,
        state_outputs=True, projection_size=None, lstm_state_clip_min=None,
        lstm_state_clip_max=None, lstm_state_clip_nan=False,
        use_sequence_length=False, sequence_length=None):
    """Fused multi-layer RNN as lax.scan over time.

    data: (seq, batch, input). parameters: flat vector packed cuDNN-style
    (layer-major: [Wx, Wh, bx, bh] per layer-direction). Returns output
    (seq, batch, num_dir*state_size) and final states when state_outputs.
    """
    ngates = {"rnn_relu": 1, "rnn_tanh": 1, "gru": 3, "lstm": 4}[mode]
    ndir = 2 if bidirectional else 1
    input_size = data.shape[-1]

    # cuDNN packing: all weights layer-major first, then all biases
    # (rnn-inl.h GetRnnParamSize). Compute static slice offsets up front.
    w_slices, b_slices = [], []
    off = 0
    for layer in range(num_layers):
        cur_in = input_size if layer == 0 else state_size * ndir
        for _ in range(ndir):
            wx_n = ngates * state_size * cur_in
            wh_n = ngates * state_size * state_size
            w_slices.append((off, wx_n, cur_in, off + wx_n, wh_n))
            off += wx_n + wh_n
    for _ in range(num_layers * ndir):
        b_slices.append((off, off + ngates * state_size))
        off += 2 * ngates * state_size

    def cell_step(h, c, x, wx, wh, bx, bh):
        if mode == "gru":
            wxr, wxz, wxn = jnp.split(wx, 3, 0)
            whr, whz, whn = jnp.split(wh, 3, 0)
            bxr, bxz, bxn = jnp.split(bx, 3)
            bhr, bhz, bhn = jnp.split(bh, 3)
            r = jax.nn.sigmoid(x @ wxr.T + bxr + h @ whr.T + bhr)
            z = jax.nn.sigmoid(x @ wxz.T + bxz + h @ whz.T + bhz)
            n = jnp.tanh(x @ wxn.T + bxn + r * (h @ whn.T + bhn))
            return (1 - z) * n + z * h, None
        g = x @ wx.T + h @ wh.T + bx + bh
        if mode == "rnn_relu":
            return jax.nn.relu(g), None
        if mode == "rnn_tanh":
            return jnp.tanh(g), None
        i, f, g_, o = jnp.split(g, 4, axis=-1)
        i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
        c_new = f * c + i * jnp.tanh(g_)
        if lstm_state_clip_min is not None:
            c_new = jnp.clip(c_new, lstm_state_clip_min, lstm_state_clip_max)
        return o * jnp.tanh(c_new), c_new

    def fn(x, params, h0, c0=None):
        outputs = x
        h_fin, c_fin = [], []
        for layer in range(num_layers):
            layer_outs = []
            for d in range(ndir):
                li = layer * ndir + d
                woff, wx_n, cur_in, hoff, wh_n = w_slices[li]
                wx = params[woff:woff + wx_n].reshape(ngates * state_size, cur_in)
                wh = params[hoff:hoff + wh_n].reshape(ngates * state_size, state_size)
                bxo, bho = b_slices[li]
                bx = params[bxo:bxo + ngates * state_size]
                bh = params[bho:bho + ngates * state_size]
                h = h0[li]
                c = c0[li] if c0 is not None else None
                xs = outputs if d == 0 else jnp.flip(outputs, 0)

                def step(carry, xt, wx=wx, wh=wh, bx=bx, bh=bh):
                    h_, c_ = carry
                    h2, c2 = cell_step(h_, c_, xt, wx, wh, bx, bh)
                    return (h2, c2 if c2 is not None else h2), h2

                (hT, cT), ys = lax.scan(step, (h, c if c is not None else h), xs)
                if d == 1:
                    ys = jnp.flip(ys, 0)
                layer_outs.append(ys)
                h_fin.append(hT)
                if mode == "lstm":
                    c_fin.append(cT)
            outputs = (jnp.concatenate(layer_outs, -1)
                       if ndir == 2 else layer_outs[0])
        hT = jnp.stack(h_fin)
        if mode == "lstm":
            return outputs, hT, jnp.stack(c_fin)
        return outputs, hT

    args = ((data, parameters, state) if mode != "lstm"
            else (data, parameters, state, state_cell))
    res = _invoke(fn, args, name=f"rnn:{mode}")
    if state_outputs:
        return res
    return res[0]


# ---------------------------------------------------------------------------
# multi-head attention ops (reference: src/operator/contrib/transformer.cc:675-828)
# ---------------------------------------------------------------------------

def interleaved_matmul_selfatt_qk(queries_keys_values, heads):
    """scores = Q @ K^T from interleaved QKV (seq, batch, 3*heads*dim).

    Reference: _contrib_interleaved_matmul_selfatt_qk (transformer.cc:675).
    Output: (batch*heads, seq, seq), scaled by 1/sqrt(dim).
    """
    def fn(qkv):
        seq, batch, three_hd = qkv.shape
        dim = three_hd // (3 * heads)
        x = qkv.reshape(seq, batch, heads, 3, dim)
        q = x[..., 0, :].transpose(1, 2, 0, 3).reshape(batch * heads, seq, dim)
        k = x[..., 1, :].transpose(1, 2, 0, 3).reshape(batch * heads, seq, dim)
        return jnp.einsum("bqd,bkd->bqk", q, k) / jnp.sqrt(dim).astype(qkv.dtype)
    return _invoke(fn, (queries_keys_values,), name="interleaved_matmul_selfatt_qk")


def interleaved_matmul_selfatt_valatt(queries_keys_values, attention, heads):
    """out = att @ V, back to (seq, batch, heads*dim).

    Reference: _contrib_interleaved_matmul_selfatt_valatt (transformer.cc:715).
    """
    def fn(qkv, att):
        seq, batch, three_hd = qkv.shape
        dim = three_hd // (3 * heads)
        v = qkv.reshape(seq, batch, heads, 3, dim)[..., 2, :]
        v = v.transpose(1, 2, 0, 3).reshape(batch * heads, seq, dim)
        out = jnp.einsum("bqk,bkd->bqd", att, v)
        return out.reshape(batch, heads, seq, dim).transpose(2, 0, 1, 3) \
            .reshape(seq, batch, heads * dim)
    return _invoke(fn, (queries_keys_values, attention),
                   name="interleaved_matmul_selfatt_valatt")


def interleaved_matmul_encdec_qk(queries, keys_values, heads):
    """Reference: _contrib_interleaved_matmul_encdec_qk (transformer.cc:752)."""
    def fn(q, kv):
        qlen, batch, hd = q.shape
        dim = hd // heads
        klen = kv.shape[0]
        qh = q.reshape(qlen, batch, heads, dim).transpose(1, 2, 0, 3) \
            .reshape(batch * heads, qlen, dim)
        k = kv.reshape(klen, batch, heads, 2, dim)[..., 0, :] \
            .transpose(1, 2, 0, 3).reshape(batch * heads, klen, dim)
        return jnp.einsum("bqd,bkd->bqk", qh, k) / jnp.sqrt(dim).astype(q.dtype)
    return _invoke(fn, (queries, keys_values), name="interleaved_matmul_encdec_qk")


def interleaved_matmul_encdec_valatt(keys_values, attention, heads):
    """Reference: _contrib_interleaved_matmul_encdec_valatt (transformer.cc:795)."""
    def fn(kv, att):
        klen, batch, two_hd = kv.shape
        dim = two_hd // (2 * heads)
        v = kv.reshape(klen, batch, heads, 2, dim)[..., 1, :] \
            .transpose(1, 2, 0, 3).reshape(batch * heads, klen, dim)
        out = jnp.einsum("bqk,bkd->bqd", att, v)
        qlen = att.shape[1]
        return out.reshape(batch, heads, qlen, dim).transpose(2, 0, 1, 3) \
            .reshape(qlen, batch, heads * dim)
    return _invoke(fn, (keys_values, attention),
                   name="interleaved_matmul_encdec_valatt")


def multi_head_attention(query, key, value, heads, mask=None, dropout_p=0.0,
                         causal=False):
    """Batch-first fused attention: (batch, seq, heads*dim) -> same.

    TPU-native addition: routes to the Pallas flash-attention kernel when
    available (mxnet_tpu.ops.pallas.flash_attention), else an XLA dot_general
    composition.
    """
    from ..ops import attention as _att
    return _att.multi_head_attention(query, key, value, heads, mask=mask,
                                     dropout_p=dropout_p, causal=causal)


# ---------------------------------------------------------------------------
# control flow (reference: src/operator/npx_control_flow.cc:1149-1318)
# ---------------------------------------------------------------------------

def foreach(body, data, init_states):
    """npx.foreach: scan body over axis 0 of data (subgraph op analog).

    body(data_slice, states) -> (out, new_states). Under autograd.record
    the loop runs eagerly with per-op recording — gradients flow to data,
    states AND parameters the body closes over, exactly like the
    reference's contrib.foreach imperative path. Outside recording (and
    inside hybridize/jit traces) it lowers to ONE lax.scan.
    """
    from .. import autograd as _ag
    from ..numpy.multiarray import _wrap
    from .. import numpy as _np
    single_data = isinstance(data, ndarray)
    single_state = isinstance(init_states, ndarray)

    length = (data.shape[0] if single_data else data[0].shape[0])
    if _ag.is_recording() and length > 0:
        # eager recorded loop (reference: contrib/control_flow foreach);
        # length 0 falls through to the scan path, whose empty (0, ...)
        # outputs match the non-recorded behavior
        states = init_states
        outs = []
        for t in range(length):
            x_t = data[t] if single_data else [d[t] for d in data]
            out, states = body(x_t, states)
            outs.append(out)
        if isinstance(outs[0], ndarray):
            stacked = _np.stack(outs)
        else:
            stacked = [_np.stack([o[i] for o in outs])
                       for i in range(len(outs[0]))]
        return stacked, states

    def fn(xs_raw, carry0):
        def scan_body(carry, x_raw):
            st = (_wrap(carry) if single_state
                  else [_wrap(c) for c in carry])
            xin = _wrap(x_raw) if single_data else [_wrap(r) for r in x_raw]
            out, new_st = body(xin, st)
            out_raw = (out._data if isinstance(out, ndarray)
                       else [o._data for o in out])
            new_raw = (new_st._data if isinstance(new_st, ndarray)
                       else [s._data for s in new_st])
            return new_raw, out_raw

        final, outs = lax.scan(scan_body, carry0, xs_raw)
        return outs, final

    xs_arg = data if single_data else list(data)
    st_arg = init_states if single_state else list(init_states)
    outs_w, final_w = _invoke(fn, (xs_arg, st_arg), name="foreach")
    return outs_w, final_w


def while_loop(cond, func, loop_vars, max_iterations=None):
    """npx.while_loop analog; eager python loop (matches reference dynamic
    semantics; use lax.while_loop directly for jit paths)."""
    steps = 0
    outputs = []
    vars_ = list(loop_vars)
    while bool(cond(*vars_)) and (max_iterations is None or steps < max_iterations):
        out, vars_ = func(*vars_)
        outputs.append(out)
        vars_ = list(vars_) if isinstance(vars_, (list, tuple)) else [vars_]
        steps += 1
    from .. import numpy as _np
    stacked = (_np.stack(outputs) if outputs and isinstance(outputs[0], ndarray)
               else outputs)
    return stacked, vars_


def cond(pred, then_func, else_func, inputs=None):
    """npx.cond analog."""
    if inputs is None:
        inputs = []
    if bool(pred(*inputs) if callable(pred) else pred):
        return then_func(*inputs)
    return else_func(*inputs)


# ---------------------------------------------------------------------------
# save / load (reference: npx.save/load over src/serialization/cnpy.cc)
# ---------------------------------------------------------------------------

def save(file, arr_dict):
    """Save dict of arrays as .npz (reference: cnpy zip-of-npy)."""
    import numpy as onp
    if isinstance(arr_dict, ndarray):
        arr_dict = {"arr_0": arr_dict}
    if isinstance(arr_dict, (list, tuple)):
        arr_dict = {f"arr_{i}": a for i, a in enumerate(arr_dict)}
    onp.savez(file, **{k: v.asnumpy() if isinstance(v, ndarray) else onp.asarray(v)
                       for k, v in arr_dict.items()})


def load(file):
    import numpy as onp
    from ..numpy import array
    with onp.load(file, allow_pickle=False) as data:
        return {k: array(data[k]) for k in data.files}


def softmax_cross_entropy(data, label, sparse_label=True, axis=-1):
    """Reference: src/operator/loss_binary_op.cc softmax_cross_entropy —
    scalar sum of -log softmax(data)[label]. The sparse path routes
    through the fused logsumexp-minus-pick op (ops/xent.py), which never
    materializes an (N, V) float32 log-softmax."""
    from ..ops.xent import sparse_softmax_xent

    if sparse_label:
        # own dispatch name: amp lists "softmax_cross_entropy" as FP32,
        # which would cast and re-materialize the (N, V) array the fused
        # op avoids (it accumulates in f32 internally already)
        return _invoke(lambda x, l: sparse_softmax_xent(x, l, axis).sum(),
                       (data, label), name="sparse_softmax_xent")
    return _invoke(lambda x, l: -(l * jax.nn.log_softmax(x, axis)).sum(),
                   (data, label), name="softmax_cross_entropy")


def smooth_l1(data, scalar=1.0):
    def fn(x):
        s2 = scalar * scalar
        return jnp.where(jnp.abs(x) < 1.0 / s2, 0.5 * s2 * x * x,
                         jnp.abs(x) - 0.5 / s2)
    return _invoke(fn, (data,), name="smooth_l1")


def batch_dot(lhs, rhs, transpose_a=False, transpose_b=False,
              forward_stype=None):
    """Reference: src/operator/tensor/dot.cc batch_dot — (b, m, k) x
    (b, k, n) batched matmul, the building block the reference's attention
    ops are made of; lowers to one MXU dot_general."""
    def fn(a, b):
        if transpose_a:
            a = jnp.swapaxes(a, -1, -2)
        if transpose_b:
            b = jnp.swapaxes(b, -1, -2)
        return jnp.matmul(a, b)
    return _invoke(fn, (lhs, rhs), name="batch_dot")


def reshape(data, newshape, reverse=False, order="C"):
    """Reference: _npx_reshape (src/operator/numpy/np_matrix_op.cc) with
    MXNet's special codes: -1 infer, -2 copy remaining dims, -3 merge two
    consecutive dims, -4 split a dim (followed by the two factors), 0 keep."""
    def fn(x):
        shape = list(newshape) if isinstance(newshape, (list, tuple)) \
            else [newshape]
        src = list(x.shape)
        out, si, i = [], 0, 0
        while i < len(shape):
            s = shape[i]
            if s == 0:
                out.append(src[si]); si += 1
            elif s == -1:
                out.append(-1); si += 1
            elif s == -2:
                out.extend(src[si:]); si = len(src)
            elif s == -3:
                out.append(src[si] * src[si + 1]); si += 2
            elif s == -4:
                f1, f2 = shape[i + 1], shape[i + 2]
                d = src[si]
                if f1 == -1:
                    f1 = d // f2
                if f2 == -1:
                    f2 = d // f1
                out.extend([f1, f2]); si += 1; i += 2
            else:
                out.append(s); si += 1
            i += 1
        return jnp.reshape(x, tuple(out))
    return _invoke(fn, (data,), name="npx_reshape")


def constraint_check(data, msg="Constraint violated!"):
    """Reference: _npx_constraint_check (src/operator/numpy/
    np_constraint_check.cc): all(data) must hold; used by
    gluon.probability distributions. Functional form: returns True and
    raises at sync time via checkify-style where supported; eager path
    checks immediately."""
    def fn(x):
        return jnp.all(x)
    out = _invoke(fn, (data,), name="constraint_check")
    try:
        ok = bool(out.asnumpy())
        if not ok:
            raise ValueError(msg)
    except (ValueError, TypeError) as e:
        if isinstance(e, ValueError) and str(e) == msg:
            raise
        # traced (inside jit): defer — return the boolean for lax.cond use
    return out


def amp_cast(data, dtype=None):
    """Reference: amp_cast op (src/operator/tensor/amp_cast.cc) — dtype
    cast inserted by the AMP graph rewrite (amp.convert_symbol).  Like
    the reference op, non-floating inputs pass through unchanged (an AMP
    rewrite must not alter integer/bool semantics)."""
    dt = np_dtype(dtype)

    def fn(x):
        if not (jnp.issubdtype(x.dtype, jnp.floating)
                or x.dtype == jnp.bfloat16):
            return x
        return x.astype(dt) if x.dtype != dt else x
    return _invoke(fn, (data,), name="amp_cast")


def amp_multicast(*data, num_outputs=None):
    """Reference: amp_multicast — cast all inputs to the widest
    *floating* dtype among them (integer inputs never win, so float data
    is not truncated)."""
    import numpy as onp
    widest = None
    for d in data:
        dt = onp.dtype(str(d.dtype))
        if dt.kind != "f" and str(dt) != "bfloat16":
            continue
        if widest is None or dt.itemsize > widest.itemsize:
            widest = dt
    if widest is None:
        return tuple(data)
    return tuple(amp_cast(d, dtype=widest) for d in data)


from ..ops.quantization import (  # noqa: E402
    quantize_v2, dequantize, quantized_fully_connected, quantized_conv,
    quantized_dense_fused, quantized_conv_fused, fp8_dense_fused)
from ..ops.bbox import (  # noqa: E402
    box_iou, box_nms, box_encode, box_decode, bipartite_matching)
from ..ops.multibox import (  # noqa: E402
    multibox_prior, multibox_target, multibox_detection)


def nonzero(data):
    """Reference: _npx_nonzero — returns (N, ndim) int64 indices (unlike
    np.nonzero's tuple). Eager-only (data-dependent shape)."""
    import numpy as onp
    arr = data.asnumpy() if hasattr(data, "asnumpy") else onp.asarray(data)
    idx = onp.argwhere(arr)
    from ..numpy.multiarray import array as _array
    return _array(idx.astype("int64"))


from . import image  # noqa: E402,F401  (npx.image.* operator namespace)


def savez(file, *args, **kwargs):
    """Save arrays to .npz (reference numpy_extension/utils.py savez over
    cnpy): positional arrays become arr_0.. keys, keyword arrays keep
    their names."""
    merged = {f"arr_{i}": a for i, a in enumerate(args)}
    clash = sorted(set(merged) & set(kwargs))
    if clash:  # numpy.savez raises for exactly this
        raise MXNetError(
            f"cannot use un-named arrays with keyword(s) {clash}; "
            "rename the keyword or name every array")
    merged.update(kwargs)
    save(file, merged)


def seed(seed, ctx="all"):  # noqa: A002,ARG001 — parity signature
    """Seed the global RNG stream (reference numpy_extension/random.py:27
    — per-ctx seeding collapses to one splittable key stream here)."""
    from .. import random as _random
    _random.seed(seed)


def bernoulli(prob=None, logit=None, size=None, dtype=None, ctx=None,
              out=None):
    """Binary samples from probs or logits, exactly one given
    (reference numpy_extension/random.py:77). Hardened front door over
    mx.np.random.bernoulli: validates the prob/logit exclusivity and
    dispatches through _invoke (async + autograd-recorded)."""
    from .. import random as _r
    from ..numpy.multiarray import _invoke, _writeback

    if (prob is None) == (logit is None):
        raise MXNetError("pass exactly one of prob or logit")
    key = _r._next_key()

    def fn(p_or_l):
        p = jax.nn.sigmoid(p_or_l) if prob is None else p_or_l
        shape = jnp.shape(p) if size is None else size
        s = jax.random.bernoulli(key, p, shape)
        return s.astype(dtype or "float32")

    res = _invoke(fn, (prob if logit is None else logit,),
                  name="bernoulli")
    return _writeback(out, res)


def _sample_n(name, draw, a, b, batch_shape, dtype):
    """Shared sample_n scaffold: output shape is batch_shape PREPENDED to
    broadcast(a, b).shape (reference numpy_extension/random.py:130,187);
    64-bit dtypes run under the scoped x64 mode like every other op."""
    from .. import random as _r
    from ..numpy.multiarray import _invoke, _wants_x64
    from ..numpy.random import _shape

    key = _r._next_key()
    bshape = _shape(batch_shape)
    dt = dtype or "float32"

    def fn(a_, b_):
        pshape = jnp.broadcast_shapes(jnp.shape(a_), jnp.shape(b_))
        return draw(key, bshape + pshape, jnp.dtype(dt), a_, b_)

    return _invoke(fn, (a, b), name=name, x64=_wants_x64(dt))


def uniform_n(low=0.0, high=1.0, batch_shape=None, dtype=None, ctx=None):
    """Uniform samples of shape batch_shape + broadcast(low, high).shape
    (reference numpy_extension/random.py:130)."""
    return _sample_n(
        "uniform_n",
        lambda key, shape, dt, lo, hi:
            lo + jax.random.uniform(key, shape, dt) * (hi - lo),
        low, high, batch_shape, dtype)


def normal_n(loc=0.0, scale=1.0, batch_shape=None, dtype=None, ctx=None):
    """Normal samples of shape batch_shape + broadcast(loc, scale).shape
    (reference numpy_extension/random.py:187)."""
    return _sample_n(
        "normal_n",
        lambda key, shape, dt, mu, sigma:
            mu + sigma * jax.random.normal(key, shape, dt),
        loc, scale, batch_shape, dtype)


from . import random  # noqa: E402,F401 — npx.random submodule (must
# import after the sampler defs above; reference exposes both spellings)


def rsqrt(data):
    """1/sqrt (reference: src/operator/tensor/elemwise_unary_op_pow.cc
    rsqrt) — lax has the fused primitive."""
    return _invoke(lax.rsqrt, (data,), name="rsqrt")


def rcbrt(data):
    """1/cbrt (reference: elemwise_unary_op_pow.cc rcbrt)."""
    return _invoke(lambda x: 1.0 / jnp.cbrt(x), (data,), name="rcbrt")


def shape_array(data):
    """Shape as an int64 host-meaning array (reference:
    src/operator/tensor/matrix_op.cc shape_array; shapes are static
    under XLA so this is a constant)."""
    return _invoke(
        lambda x: jnp.asarray(jnp.shape(x), jnp.int64)
        if jax.config.read("jax_enable_x64")
        else jnp.asarray(jnp.shape(x), jnp.int32),
        (data,), name="shape_array")


def size_array(data):
    """Total element count as a 1-element array (reference:
    matrix_op.cc size_array)."""
    return _invoke(lambda x: jnp.asarray([x.size], jnp.int32), (data,),
                   name="size_array")


def split_v2(data, indices_or_sections, axis=0, squeeze_axis=False):
    """np.split with the reference's squeeze_axis flag (reference:
    matrix_op.cc _split_v2)."""
    def fn(x):
        parts = jnp.split(x, indices_or_sections, axis=axis)
        if squeeze_axis:
            parts = [jnp.squeeze(p, axis=axis) for p in parts]
        return tuple(parts)
    return _invoke(fn, (data,), name="split_v2")


def space_to_depth(data, block_size):
    """NCHW (N,C,H,W) -> (N, C*b*b, H/b, W/b) (reference:
    src/operator/tensor/matrix_op.cc space_to_depth, DCR mode)."""
    b = int(block_size)

    def fn(x):
        n, c, h, w = x.shape
        if h % b or w % b:
            raise MXNetError(
                f"H and W must be divisible by block_size {b}, "
                f"got H={h} W={w}")
        x = x.reshape(n, c, h // b, b, w // b, b)
        x = x.transpose(0, 3, 5, 1, 2, 4)
        return x.reshape(n, c * b * b, h // b, w // b)
    return _invoke(fn, (data,), name="space_to_depth")


def depth_to_space(data, block_size):
    """Inverse of space_to_depth (reference: matrix_op.cc
    depth_to_space)."""
    b = int(block_size)

    def fn(x):
        n, c, h, w = x.shape
        if c % (b * b):
            raise MXNetError(
                f"C must be divisible by block_size^2 = {b * b}, got C={c}")
        x = x.reshape(n, b, b, c // (b * b), h, w)
        x = x.transpose(0, 3, 4, 1, 5, 2)
        return x.reshape(n, c // (b * b), h * b, w * b)
    return _invoke(fn, (data,), name="depth_to_space")

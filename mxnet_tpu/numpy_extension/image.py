"""npx.image — the image operator namespace.

Reference parity: src/operator/image/ (`_image_to_tensor`,
`_image_normalize`, `_image_resize`, `_image_crop`, `_image_random_crop`,
`_image_random_resized_crop`, flips, random color ops, lighting —
image_random.cc, resize.cc, crop.cc) backing
``gluon.data.vision.transforms``.

TPU-native: every op accepts HWC (3-D) or NHWC (4-D batch) input and
lowers to the batched kernels in ``mxnet_tpu.image`` (affine crop/resize
gather, luminance blends, Rodrigues hue rotation).  Randomness draws from
the mx.random key stream, per sample.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..base import MXNetError
from ..numpy.multiarray import _wrap, ndarray

__all__ = ["to_tensor", "normalize", "resize", "crop", "random_crop",
           "random_resized_crop", "flip_left_right", "flip_top_bottom",
           "random_flip_left_right", "random_flip_top_bottom",
           "random_brightness", "random_contrast", "random_saturation",
           "random_hue", "random_color_jitter", "adjust_lighting",
           "random_lighting"]


def _raw(x):
    return x._data if isinstance(x, ndarray) else jnp.asarray(x)


def _batched(x):
    """(raw NHWC batch, had_batch_dim)."""
    r = _raw(x)
    if r.ndim == 3:
        return r[None], False
    if r.ndim == 4:
        return r, True
    raise MXNetError(f"image ops expect HWC or NHWC input, got {r.shape}")


def _debatch(out, batched):
    return _wrap(out if batched else out[0])


def _key():
    from .. import random as _random
    return _random._next_key()


def to_tensor(data):
    """HWC uint8 [0,255] -> CHW float32 [0,1] (reference:
    image_random.cc _image_to_tensor; NHWC -> NCHW for batches)."""
    r = _raw(data)
    scaled = r.astype(jnp.float32) / 255.0
    if r.ndim == 3:
        return _wrap(jnp.transpose(scaled, (2, 0, 1)))
    return _wrap(jnp.transpose(scaled, (0, 3, 1, 2)))


def normalize(data, mean=0.0, std=1.0):
    """Channel-wise normalize on CHW/NCHW float input (reference:
    _image_normalize)."""
    r = _raw(data)
    mean_a = jnp.asarray(_raw(mean) if isinstance(mean, ndarray) else mean,
                         jnp.float32)
    std_a = jnp.asarray(_raw(std) if isinstance(std, ndarray) else std,
                        jnp.float32)
    c_axis = r.ndim - 3  # CHW -> 0, NCHW -> 1
    shape = [1] * r.ndim
    shape[c_axis] = -1
    return _wrap((r - mean_a.reshape(shape)) / std_a.reshape(shape))


def resize(data, size, keep_ratio=False, interp=1):
    """Reference: resize.cc _image_resize. size: int or (w, h)."""
    from ..image import _batch_resize
    r, batched = _batched(data)
    h, w = r.shape[1], r.shape[2]
    if isinstance(size, int):
        if keep_ratio:
            if h > w:
                out_hw = (int(h * size / w), size)
            else:
                out_hw = (size, int(w * size / h))
        else:
            out_hw = (size, size)
    else:
        out_hw = (size[1], size[0])
    dt = r.dtype
    out = _batch_resize(r.astype(jnp.float32), out_hw,
                        bilinear=bool(interp))
    if jnp.issubdtype(dt, jnp.integer):
        out = jnp.clip(jnp.round(out), 0, 255)
    return _debatch(out.astype(dt), batched)


def crop(data, x, y, width, height):
    """Reference: crop.cc _image_crop (x, y = top-left corner)."""
    r, batched = _batched(data)
    out = r[:, y:y + height, x:x + width]
    return _debatch(out, batched)


def random_crop(data, xrange=(0.0, 1.0), yrange=(0.0, 1.0), width=None,
                height=None, interp=1):
    """Crop `width`x`height` at a fractional position drawn from
    xrange/yrange (reference: crop-inl.h RandomCrop; CenterCrop passes
    (0.5, 0.5)).  Upsamples when the source is smaller than the target."""
    from ..image import _affine_crop_resize
    if width is None or height is None:
        raise MXNetError("random_crop requires width and height")
    r, batched = _batched(data)
    n, h, w = r.shape[0], r.shape[1], r.shape[2]
    dt = r.dtype
    kx, ky = jax.random.split(_key())
    fx = jax.random.uniform(kx, (n,), minval=xrange[0], maxval=xrange[1])
    fy = jax.random.uniform(ky, (n,), minval=yrange[0], maxval=yrange[1])
    cw, ch = min(width, w), min(height, h)
    x0 = jnp.floor(fx * (w - cw + 1))
    y0 = jnp.floor(fy * (h - ch + 1))
    out = _affine_crop_resize(r.astype(jnp.float32), y0, x0,
                              jnp.full((n,), float(ch)),
                              jnp.full((n,), float(cw)),
                              (height, width), bilinear=bool(interp))
    if jnp.issubdtype(dt, jnp.integer):
        out = jnp.clip(jnp.round(out), 0, 255)
    return _debatch(out.astype(dt), batched)


def random_resized_crop(data, width=None, height=None, area=(0.08, 1.0),
                        ratio=(3 / 4.0, 4 / 3.0), interp=1, max_trial=10):
    """Inception-style random area/aspect crop resized to (width, height)
    (reference: crop-inl.h RandomResizedCrop), batched as an affine
    resample."""
    from ..image import RandomSizedCropAug
    r, batched = _batched(data)
    dt = r.dtype
    if isinstance(area, (int, float)):
        area = (area, 1.0)
    aug = RandomSizedCropAug((width, height), area, ratio, interp)
    out = aug.batch_apply(r.astype(jnp.float32), _key())
    if jnp.issubdtype(dt, jnp.integer):
        out = jnp.clip(jnp.round(out), 0, 255)
    return _debatch(out.astype(dt), batched)


def flip_left_right(data):
    r, batched = _batched(data)
    return _debatch(r[:, :, ::-1], batched)


def flip_top_bottom(data):
    r, batched = _batched(data)
    return _debatch(r[:, ::-1], batched)


def _random_flip(data, axis, p=0.5):
    r, batched = _batched(data)
    flip = jax.random.bernoulli(_key(), p, (r.shape[0],))
    flipped = r[:, :, ::-1] if axis == 2 else r[:, ::-1]
    out = jnp.where(flip[:, None, None, None], flipped, r)
    return _debatch(out, batched)


def random_flip_left_right(data, p=0.5):
    return _random_flip(data, 2, p)


def random_flip_top_bottom(data, p=0.5):
    return _random_flip(data, 1, p)


def _enhance(data, mode, min_factor, max_factor):
    # factor drawn in [min,max] (the Augmenter classes use symmetric
    # jitter ranges, so the blend is applied here with explicit bounds)
    from ..image import _rgb_luma
    if mode not in ("brightness", "contrast", "saturation"):
        raise MXNetError(f"unknown enhance mode {mode!r}")
    r, batched = _batched(data)
    dt = r.dtype
    n = r.shape[0]
    alpha = jax.random.uniform(_key(), (n, 1, 1, 1), minval=min_factor,
                               maxval=max_factor)
    x = r.astype(jnp.float32)
    if mode == "brightness":
        out = x * alpha
    elif mode == "contrast":
        mean_luma = _rgb_luma(x).mean(axis=(1, 2), keepdims=True)
        out = x * alpha + mean_luma * (1.0 - alpha)
    else:  # saturation
        out = x * alpha + _rgb_luma(x) * (1.0 - alpha)
    if jnp.issubdtype(dt, jnp.integer):
        out = jnp.clip(jnp.round(out), 0, 255)
    return _debatch(out.astype(dt), batched)


def random_brightness(data, min_factor, max_factor):
    return _enhance(data, "brightness", min_factor, max_factor)


def random_contrast(data, min_factor, max_factor):
    return _enhance(data, "contrast", min_factor, max_factor)


def random_saturation(data, min_factor, max_factor):
    return _enhance(data, "saturation", min_factor, max_factor)


def random_hue(data, min_factor, max_factor):
    """Hue rotation with factor drawn in [min,max] (reference:
    image_random.cc RandomHue); 1.0 = identity.  theta = (f - 1) * pi,
    so the requested (possibly asymmetric) range is honored exactly."""
    from ..image import HueJitterAug
    r, batched = _batched(data)
    dt = r.dtype
    n = r.shape[0]
    f = jax.random.uniform(_key(), (n,), minval=min_factor,
                           maxval=max_factor)
    theta = (f - 1.0) * jnp.pi
    aug = HueJitterAug(0.0)
    out = aug._rotate(r.astype(jnp.float32), theta)
    if jnp.issubdtype(dt, jnp.integer):
        out = jnp.clip(jnp.round(out), 0, 255)
    return _debatch(out.astype(dt), batched)


def random_color_jitter(data, brightness=0, contrast=0, saturation=0, hue=0):
    from ..image import ColorJitterAug, HueJitterAug
    r, batched = _batched(data)
    dt = r.dtype
    x = r.astype(jnp.float32)
    x = ColorJitterAug(brightness, contrast, saturation).batch_apply(
        x, _key())
    if hue:
        x = HueJitterAug(hue).batch_apply(x, _key())
    if jnp.issubdtype(dt, jnp.integer):
        x = jnp.clip(jnp.round(x), 0, 255)
    return _debatch(x.astype(dt), batched)


def adjust_lighting(data, alpha):
    """AlexNet-PCA lighting with FIXED alpha (reference:
    image_random.cc _image_adjust_lighting)."""
    from ..image import LightingAug
    import numpy as onp
    aug = LightingAug(1.0, onp.array([55.46, 4.794, 1.148]),
                      onp.array([[-0.5675, 0.7192, 0.4009],
                                 [-0.5808, -0.0045, -0.8140],
                                 [-0.5836, -0.6948, 0.4203]]))
    r, batched = _batched(data)
    dt = r.dtype
    a = jnp.broadcast_to(jnp.asarray(alpha, jnp.float32), (r.shape[0], 3))
    rgb = (a * jnp.asarray(aug.eigval)) @ jnp.asarray(aug.eigvec).T
    out = r.astype(jnp.float32) + rgb[:, None, None, :]
    if jnp.issubdtype(dt, jnp.integer):
        out = jnp.clip(jnp.round(out), 0, 255)
    return _debatch(out.astype(dt), batched)


def random_lighting(data, alpha_std=0.05):
    from ..image import LightingAug
    import numpy as onp
    aug = LightingAug(alpha_std, onp.array([55.46, 4.794, 1.148]),
                      onp.array([[-0.5675, 0.7192, 0.4009],
                                 [-0.5808, -0.0045, -0.8140],
                                 [-0.5836, -0.6948, 0.4203]]))
    r, batched = _batched(data)
    dt = r.dtype
    out = aug.batch_apply(r.astype(jnp.float32), _key())
    if jnp.issubdtype(dt, jnp.integer):
        out = jnp.clip(jnp.round(out), 0, 255)
    return _debatch(out.astype(dt), batched)

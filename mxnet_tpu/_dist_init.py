"""Process-group bring-up at import time.

Reference parity: importing mxnet in a DMLC-launched job connects the
ps-lite van using DMLC_* env vars before any work happens (src/kvstore/
kvstore_dist.h, tools/launch.py tracker). Here the coordination service is
jax.distributed, which must initialize BEFORE the first backend touch —
so mxnet_tpu/__init__ calls this first thing. No-op without launcher env.
"""
from __future__ import annotations

import os


def _env_int(*names):
    for n in names:
        v = os.environ.get(n)
        if v is not None:
            return int(v)
    return None


def ensure_distributed():
    """Initialize jax.distributed from DMLC-style or native env vars."""
    import jax

    coord = (os.environ.get("JAX_COORDINATOR_ADDRESS")
             or os.environ.get("DMLC_PS_ROOT_URI"))
    nproc = _env_int("DMLC_NUM_WORKER", "JAX_NUM_PROCESSES")
    pid = _env_int("DMLC_WORKER_ID", "JAX_PROCESS_ID")
    if not (coord and nproc and nproc > 1):
        return
    from jax._src import distributed
    if distributed.global_state.client is not None:
        return  # already connected
    if pid is None:
        # `process_id=pid or 0` would silently make EVERY worker rank 0 —
        # N processes each claiming rank 0 corrupts the reduce instead of
        # failing the launch.
        from .base import MXNetError
        raise MXNetError(
            f"distributed launch env is incomplete: coordinator={coord!r} "
            f"and num_processes={nproc} are set but this process has no "
            "rank. Set DMLC_WORKER_ID (DMLC-style) or JAX_PROCESS_ID "
            "(native) to this worker's 0-based index — tools/launch.py "
            "does this automatically.")
    if os.environ.get("MXTPU_DIST_DEVICE", "") == "cpu":
        # local-launcher mode (tools/launch.py --launcher local): force the
        # CPU platform (the axon/TPU plugin pins JAX_PLATFORMS otherwise)
        # and gloo collectives so N processes on one box can psum.
        jax.config.update("jax_platforms", "cpu")
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    port = os.environ.get("DMLC_PS_ROOT_PORT", "1234")
    addr = coord if ":" in coord else f"{coord}:{port}"
    jax.distributed.initialize(coordinator_address=addr,
                               num_processes=nproc,
                               process_id=pid)

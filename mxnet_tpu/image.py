"""mx.image — image codecs + augmenters.

Reference parity: python/mxnet/image/ (imdecode/imread/imresize via OpenCV,
ImageIter augmenter chain) over src/io/image_io.cc.

This environment has no OpenCV; codecs use PIL when importable and a raw
numpy .npy/.ppm fallback otherwise (sufficient for RecordIO pipelines that
pack raw arrays). Resize/crop augmenters run via jax.image on device.
"""
from __future__ import annotations

import io as _io
import os

import numpy as onp

from .base import MXNetError
from .numpy.multiarray import _wrap, ndarray


def _pil():
    try:
        from PIL import Image
        return Image
    except ImportError:
        return None


def imdecode(buf, flag=1, to_rgb=True, out=None):
    """Decode image bytes to HWC ndarray (reference: image.py imdecode)."""
    import jax.numpy as jnp
    if isinstance(buf, ndarray):
        buf = bytes(buf.asnumpy().astype(onp.uint8))
    Image = _pil()
    if buf[:6] == b"\x93NUMPY":
        arr = onp.load(_io.BytesIO(buf), allow_pickle=False)
    elif Image is not None:
        img = Image.open(_io.BytesIO(buf))
        img = img.convert("RGB" if flag else "L")
        arr = onp.asarray(img)
        if not flag:
            arr = arr[..., None]
    else:
        raise MXNetError("no image codec available (PIL missing); pack raw "
                         ".npy payloads instead")
    if arr.ndim == 2:
        arr = arr[..., None]
    return _wrap(jnp.asarray(arr))


def imencode(img, fmt=".jpg", quality=95):
    if isinstance(img, ndarray):
        img = img.asnumpy()
    Image = _pil()
    if Image is None or fmt == ".npy":
        bio = _io.BytesIO()
        onp.save(bio, onp.asarray(img))
        return bio.getvalue()
    bio = _io.BytesIO()
    Image.fromarray(onp.asarray(img).squeeze().astype(onp.uint8)).save(
        bio, format=fmt.strip(".").upper().replace("JPG", "JPEG"),
        quality=quality)
    return bio.getvalue()


def imread(filename, flag=1, to_rgb=True):
    """Reference: image.py imread."""
    if filename.endswith(".npy"):
        import jax.numpy as jnp
        return _wrap(jnp.asarray(onp.load(filename)))
    with open(filename, "rb") as f:
        return imdecode(f.read(), flag, to_rgb)


def imresize(src, w, h, interp=1):
    import jax
    import jax.numpy as jnp
    raw = src._data if isinstance(src, ndarray) else jnp.asarray(src)
    out = jax.image.resize(raw.astype(jnp.float32),
                           (h, w) + tuple(raw.shape[2:]),
                           method="bilinear" if interp else "nearest")
    return _wrap(out.astype(raw.dtype))


def fixed_crop(src, x0, y0, w, h, size=None, interp=2):
    out = src[y0:y0 + h, x0:x0 + w]
    if size is not None and (w, h) != size:
        out = imresize(out, size[0], size[1], interp)
    return out


def center_crop(src, size, interp=2):
    H, W = src.shape[0], src.shape[1]
    w, h = size
    x0, y0 = (W - w) // 2, (H - h) // 2
    return fixed_crop(src, x0, y0, w, h), (x0, y0, w, h)


def random_crop(src, size, interp=2):
    H, W = src.shape[0], src.shape[1]
    w, h = size
    x0 = onp.random.randint(0, max(W - w, 0) + 1)
    y0 = onp.random.randint(0, max(H - h, 0) + 1)
    return fixed_crop(src, x0, y0, w, h), (x0, y0, w, h)


def color_normalize(src, mean, std=None):
    src = src - mean
    if std is not None:
        src = src / std
    return src

"""mx.image — image codecs + batch-first augmentation.

Reference parity: python/mxnet/image/ (imdecode/imread/imresize via
OpenCV, the Augmenter/CreateAugmenter chain, ImageIter) over
src/io/image_io.cc.  The *surface* (class names, CreateAugmenter
signature and augmenter ordering, per-image helpers) is the
compatibility contract; the execution model is redesigned for TPU:

- every augmenter implements ``batch_apply(x, key)`` over an (N, H, W, C)
  float32 device batch with jax.random per-sample randomness (vmapped),
  so one DataLoader batch is one fused XLA program instead of N python
  loops fighting the GIL;
- variable-size crops (random crop / Inception-style random-sized crop)
  are expressed as fixed-output-shape affine resampling — per-sample
  scale/offset into a bilinear gather — because data-dependent shapes
  don't compile; this is the standard TPU formulation (crop-and-resize),
  not a translation of the reference's per-image numpy slicing;
- the per-image ``__call__`` API remains and simply runs the batch path
  on a singleton batch.

Codecs use PIL when importable and a raw numpy .npy fallback otherwise
(no OpenCV in this environment).
"""
from __future__ import annotations

import io as _io
import os

import numpy as onp

from .base import MXNetError
from .numpy.multiarray import _wrap, ndarray


def _pil():
    try:
        from PIL import Image
        return Image
    except ImportError:
        return None


# ---------------------------------------------------------------------------
# codecs
# ---------------------------------------------------------------------------

def imdecode(buf, flag=1, to_rgb=True, out=None):
    """Decode image bytes to HWC ndarray (reference: image.py imdecode)."""
    import jax.numpy as jnp
    if isinstance(buf, ndarray):
        buf = bytes(buf.asnumpy().astype(onp.uint8))
    return _wrap(jnp.asarray(imdecode_np(buf, flag)))


def imdecode_np(buf, flag=1, try_native=True):
    """Host-side decode to a numpy HWC array (no device transfer) — the
    ImageIter batch path decodes all samples first, then ships ONE batch.

    JPEG payloads prefer the native libjpeg codec (native/mxtpu_decode.cc,
    the reference's src/io/image_io.cc role); everything else uses PIL,
    raw .npy payloads load directly."""
    if buf[:6] == b"\x93NUMPY":
        arr = onp.load(_io.BytesIO(buf), allow_pickle=False)
    else:
        arr = None
        if try_native and buf[:2] == b"\xff\xd8":   # JPEG magic
            from . import native as _native
            arr = _native.jpeg_decode(buf, gray=not flag)
        if arr is None:
            Image = _pil()
            if Image is None:
                raise MXNetError("no image codec available (PIL missing); "
                                 "pack raw .npy payloads instead")
            img = Image.open(_io.BytesIO(buf)).convert("RGB" if flag else "L")
            arr = onp.asarray(img)
            if not flag:
                arr = arr[..., None]
    if arr.ndim == 2:
        arr = arr[..., None]
    return arr


def imdecode_batch_np(bufs, flag=1, n_threads=None):
    """Decode a list of image payloads to HWC uint8 arrays, JPEGs in
    parallel native threads (GIL-free — the reference decodes an
    ImageRecordIter batch across its thread pool the same way)."""
    from . import native as _native
    out = [None] * len(bufs)
    jpeg_idx = [i for i, b in enumerate(bufs) if b[:2] == b"\xff\xd8"]
    if jpeg_idx:
        decoded = _native.jpeg_decode_batch([bufs[i] for i in jpeg_idx],
                                            gray=not flag,
                                            n_threads=n_threads)
        if decoded is not None:
            for i, arr in zip(jpeg_idx, decoded):
                out[i] = arr
    for i in range(len(bufs)):
        if out[i] is None:
            # the native codec already rejected this payload — go straight
            # to the PIL/npy path instead of retrying libjpeg
            out[i] = imdecode_np(bufs[i], flag, try_native=False)
    return out


def imencode(img, fmt=".jpg", quality=95):
    if isinstance(img, ndarray):
        img = img.asnumpy()
    Image = _pil()
    if Image is None or fmt == ".npy":
        bio = _io.BytesIO()
        onp.save(bio, onp.asarray(img))
        return bio.getvalue()
    bio = _io.BytesIO()
    Image.fromarray(onp.asarray(img).squeeze().astype(onp.uint8)).save(
        bio, format=fmt.strip(".").upper().replace("JPG", "JPEG"),
        quality=quality)
    return bio.getvalue()


def imread(filename, flag=1, to_rgb=True):
    """Reference: image.py imread."""
    if filename.endswith(".npy"):
        import jax.numpy as jnp
        return _wrap(jnp.asarray(onp.load(filename)))
    with open(filename, "rb") as f:
        return imdecode(f.read(), flag, to_rgb)


# ---------------------------------------------------------------------------
# batched geometric kernels
# ---------------------------------------------------------------------------

def _interp_weights(coords, size, bilinear):
    """(N, out) fractional source coords -> (N, out, size) weight matrix
    with <=2 nonzeros per row (tent kernel), edge-clamped."""
    import jax.numpy as jnp
    c = jnp.clip(coords, 0.0, size - 1.0)
    if not bilinear:
        c = jnp.round(c)
    grid = jnp.arange(size, dtype=jnp.float32)
    return jnp.maximum(0.0, 1.0 - jnp.abs(c[..., None] - grid))


def _affine_crop_resize(batch, y0, x0, hs, ws, out_hw, bilinear=True):
    """Per-sample window (y0, x0, hs, ws) resampled to out_hw.

    All windows share the static output shape; the varying geometry lives
    in per-sample separable interpolation-weight matrices, applied as two
    einsum contractions — MXU-tiled matmuls instead of per-element
    gathers (which lower to slow scalar gathers on TPU).
    """
    import jax.numpy as jnp
    n, H, W, _ = batch.shape
    oh, ow = out_hw
    gy = (jnp.arange(oh) + 0.5) / oh      # normalized output grid
    gx = (jnp.arange(ow) + 0.5) / ow
    ys = y0[:, None] + gy[None, :] * hs[:, None] - 0.5   # (N, oh)
    xs = x0[:, None] + gx[None, :] * ws[:, None] - 0.5   # (N, ow)
    wy = _interp_weights(ys, H, bilinear)                # (N, oh, H)
    wx = _interp_weights(xs, W, bilinear)                # (N, ow, W)
    rows = jnp.einsum("noh,nhwc->nowc", wy, batch)
    return jnp.einsum("nxw,nowc->noxc", wx, rows)


def _batch_resize(batch, out_hw, bilinear=True):
    import jax.numpy as jnp
    n = batch.shape[0]
    z = jnp.zeros((n,))
    return _affine_crop_resize(
        batch, z, z, jnp.full((n,), batch.shape[1], jnp.float32),
        jnp.full((n,), batch.shape[2], jnp.float32), out_hw, bilinear)


# ---------------------------------------------------------------------------
# per-image helpers (reference surface; singleton-batch shims)
# ---------------------------------------------------------------------------

def _as_batch(src):
    import jax.numpy as jnp
    raw = src._data if isinstance(src, ndarray) else jnp.asarray(src)
    return raw.astype(jnp.float32)[None], raw.dtype


def imresize(src, w, h, interp=1):
    out, dt = _as_batch(src)
    out = _batch_resize(out, (h, w), bilinear=bool(interp))
    return _wrap(out[0].astype(dt))


def fixed_crop(src, x0, y0, w, h, size=None, interp=2):
    out = src[y0:y0 + h, x0:x0 + w]
    if size is not None and (w, h) != size:
        out = imresize(out, size[0], size[1], interp)
    return out


def center_crop(src, size, interp=2):
    H, W = src.shape[0], src.shape[1]
    w, h = size
    x0, y0 = (W - w) // 2, (H - h) // 2
    return fixed_crop(src, x0, y0, w, h), (x0, y0, w, h)


def random_crop(src, size, interp=2):
    H, W = src.shape[0], src.shape[1]
    w, h = size
    x0 = onp.random.randint(0, max(W - w, 0) + 1)
    y0 = onp.random.randint(0, max(H - h, 0) + 1)
    return fixed_crop(src, x0, y0, w, h), (x0, y0, w, h)


def scale_down(src_size, size):
    """Shrink a crop size to fit inside the image, keeping aspect
    (reference image.py:214: (640,480),(720,120) -> (640,106))."""
    w, h = size
    sw, sh = src_size
    if sh < h:
        w, h = float(w * sh) / h, sh
    if sw < w:
        w, h = sw, float(h * sw) / w
    return int(w), int(h)


# OpenCV border-type codes (reference copyMakeBorder forwards type=
# straight to cv2: CONSTANT=0, REPLICATE=1, REFLECT=2, WRAP=3, 101=4)
BORDER_CONSTANT, BORDER_REPLICATE, BORDER_REFLECT = 0, 1, 2
BORDER_WRAP, BORDER_REFLECT_101 = 3, 4
_BORDER_MODES = {0: "constant", 1: "edge", 2: "symmetric", 3: "wrap",
                 4: "reflect"}


def copyMakeBorder(src, top, bot, left, right, type=0, value=0.0):  # noqa: A002,N802
    """Pad an HWC image's borders (reference image.py:249 over OpenCV
    copyMakeBorder; here a jnp pad — constant/reflect/replicate/wrap/
    reflect-101 map to numpy pad modes)."""
    import jax.numpy as jnp

    from .numpy.multiarray import _invoke

    mode = _BORDER_MODES.get(type)
    if mode is None:
        raise MXNetError(f"unknown border type {type}")

    def fn(x):
        widths = ((top, bot), (left, right)) + ((0, 0),) * (x.ndim - 2)
        if mode == "constant":
            return jnp.pad(x, widths, mode="constant",
                           constant_values=value)
        return jnp.pad(x, widths, mode=mode)

    return _invoke(fn, (src,), name="copyMakeBorder")


def color_normalize(src, mean, std=None):
    if mean is not None:
        src = src - mean
    if std is not None:
        src = src / std
    return src


def resize_short(src, size, interp=2):
    """Resize so the shorter edge is `size` (reference: image.py
    resize_short)."""
    H, W = src.shape[0], src.shape[1]
    if H > W:
        new_w, new_h = size, int(H * size / W)
    else:
        new_w, new_h = int(W * size / H), size
    return imresize(src, new_w, new_h, interp)


def random_size_crop(src, size, area, ratio, interp=2):
    """Random area/aspect crop (reference surface: image.py
    random_size_crop; Inception-style training crop)."""
    H, W = src.shape[0], src.shape[1]
    src_area = H * W
    if isinstance(area, (int, float)):
        area = (area, 1.0)
    for _ in range(10):
        target_area = onp.random.uniform(*area) * src_area
        log_ratio = (onp.log(ratio[0]), onp.log(ratio[1]))
        aspect = onp.exp(onp.random.uniform(*log_ratio))
        w = int(round((target_area * aspect) ** 0.5))
        h = int(round((target_area / aspect) ** 0.5))
        if w <= W and h <= H:
            x0 = onp.random.randint(0, W - w + 1)
            y0 = onp.random.randint(0, H - h + 1)
            return fixed_crop(src, x0, y0, w, h, size, interp), \
                (x0, y0, w, h)
    return center_crop(src, size, interp)


def _rotate_grid_sample(img, rad, zoom_in, zoom_out):
    """Rotate one CHW fp32 image by `rad` (bilinear, zero-pad outside).

    jnp math mirrors the reference's grid construction
    (image/image.py:618-725: rotate a centered grid, normalize AFTER
    rotation to keep aspect, zoom scale from the rotated corner extents,
    BilinearSampler with zero padding); the sampler here is a vectorized
    gather instead of the reference's GPU kernel.
    """
    import jax.numpy as jnp

    c, h, w = img.shape
    hs, ws = (h - 1) / 2.0, (w - 1) / 2.0
    hm = jnp.arange(h, dtype=jnp.float32)[:, None] - hs
    wm = jnp.arange(w, dtype=jnp.float32)[None, :] - ws
    ca, sa = jnp.cos(rad), jnp.sin(rad)
    gx = (wm * ca - hm * sa) / ws
    gy = (wm * sa + hm * ca) / hs
    if zoom_in or zoom_out:
        rho = jnp.sqrt(jnp.asarray(float(h * h + w * w)))
        ang = jnp.arctan(h / w)
        c1x = jnp.abs(rho * jnp.cos(ang + jnp.abs(rad)))
        c1y = jnp.abs(rho * jnp.sin(ang + jnp.abs(rad)))
        c2x = jnp.abs(rho * jnp.cos(ang - jnp.abs(rad)))
        c2y = jnp.abs(rho * jnp.sin(ang - jnp.abs(rad)))
        mx_, my = jnp.maximum(c1x, c2x), jnp.maximum(c1y, c2y)
        if zoom_out:
            scale = jnp.maximum(mx_ / w, my / h)
        else:
            scale = jnp.minimum(w / mx_, h / my)
        gx, gy = gx * scale, gy * scale
    # [-1,1] -> pixel coords, bilinear gather with zero outside
    x = (gx + 1.0) * ws
    y = (gy + 1.0) * hs
    x0, y0 = jnp.floor(x), jnp.floor(y)
    wx, wy = x - x0, y - y0

    def gather(yy, xx):
        valid = (xx >= 0) & (xx <= w - 1) & (yy >= 0) & (yy <= h - 1)
        xc = jnp.clip(xx, 0, w - 1).astype(jnp.int32)
        yc = jnp.clip(yy, 0, h - 1).astype(jnp.int32)
        return jnp.where(valid[None], img[:, yc, xc], 0.0)

    return (gather(y0, x0) * (1 - wx) * (1 - wy)
            + gather(y0, x0 + 1) * wx * (1 - wy)
            + gather(y0 + 1, x0) * (1 - wx) * wy
            + gather(y0 + 1, x0 + 1) * wx * wy)


def imrotate(src, rotation_degrees, zoom_in=False, zoom_out=False):
    """Rotate CHW / NCHW float32 image(s) (reference image.py:618).

    Batch input takes a per-image angle vector or a scalar; `zoom_in`
    crops so no padding shows, `zoom_out` shrinks so the whole source
    stays visible.
    """
    import jax
    import jax.numpy as jnp

    from .base import MXNetError
    from .numpy.multiarray import _invoke, ndarray

    if zoom_in and zoom_out:
        raise MXNetError("`zoom_in` and `zoom_out` cannot be both True")
    raw = src._data if isinstance(src, ndarray) else jnp.asarray(src)
    if raw.dtype != jnp.float32:
        raise MXNetError("imrotate supports float32 only (call after "
                         "ToTensor); got " + str(raw.dtype))
    single = raw.ndim == 3
    if raw.ndim not in (3, 4):
        raise MXNetError("imrotate takes CHW or NCHW input")
    n = 1 if single else raw.shape[0]
    if onp.isscalar(rotation_degrees):
        deg = onp.full((n,), rotation_degrees, "float32")
    else:
        deg = onp.asarray(
            rotation_degrees.asnumpy()
            if isinstance(rotation_degrees, ndarray) else rotation_degrees,
            "float32").reshape(-1)
        if single:
            raise MXNetError("single image takes a scalar angle")
    if len(deg) != n:
        raise MXNetError(f"{n} images but {len(deg)} angles")

    def fn(x):
        rad = jnp.asarray(deg) * (onp.pi / 180.0)
        batch = x[None] if single else x
        out = jax.vmap(_rotate_grid_sample,
                       in_axes=(0, 0, None, None))(batch, rad,
                                                   zoom_in, zoom_out)
        return out[0] if single else out

    return _invoke(fn, (src,), name="imrotate")


def random_rotate(src, angle_limits, zoom_in=False, zoom_out=False):
    """Rotate by angle(s) drawn uniformly from `angle_limits`
    (reference image.py:727)."""
    from .base import MXNetError
    lo, hi = angle_limits
    if lo >= hi:
        raise MXNetError("`angle_limits` must be an ordered tuple")
    nd = getattr(src, "ndim", 3)
    if nd == 3:
        angle = float(onp.random.uniform(lo, hi))
    else:
        angle = onp.random.uniform(lo, hi, size=(src.shape[0],)) \
            .astype("float32")
    return imrotate(src, angle, zoom_in, zoom_out)


# ---------------------------------------------------------------------------
# augmenters: one batched XLA program per step
# ---------------------------------------------------------------------------

def _rgb_luma(x):
    """Batch luminance (N,H,W,1), ITU-R BT.601 weights."""
    import jax.numpy as jnp
    w = jnp.asarray([0.299, 0.587, 0.114], x.dtype)
    return (x * w).sum(-1, keepdims=True)


class Augmenter:
    """Augmenter base (reference surface: image.py Augmenter).

    Subclasses implement ``batch_apply(x, key) -> x`` on an (N,H,W,C)
    float32 batch.  ``out_hw(in_hw)`` reports the static output spatial
    shape so chains can be composed and jitted shape-stably.
    """

    def __init__(self, **kwargs):
        self._kwargs = kwargs

    def dumps(self):
        import json
        return json.dumps([type(self).__name__, self._kwargs])

    def out_hw(self, in_hw):
        return in_hw

    def batch_apply(self, x, key):
        raise NotImplementedError

    def __call__(self, src):
        from . import random as _random
        import jax.numpy as jnp
        batch, dt = _as_batch(src)
        out = self.batch_apply(batch, _random._next_key())
        out = out[0]
        if dt == jnp.uint8:
            out = jnp.clip(jnp.round(out), 0, 255)
        return _wrap(out.astype(dt))


class SequentialAug(Augmenter):
    def __init__(self, ts):
        super().__init__()
        self.ts = list(ts)

    def out_hw(self, in_hw):
        for t in self.ts:
            in_hw = t.out_hw(in_hw)
        return in_hw

    def batch_apply(self, x, key):
        import jax
        for t in self.ts:
            key, sub = jax.random.split(key)
            x = t.batch_apply(x, sub)
        return x

    def __call__(self, src):
        for t in self.ts:
            src = t(src)
        return src


class RandomOrderAug(Augmenter):
    """Applies children in a random order.

    Batch path: for <= 4 children, the order is drawn from ``key`` with
    ``lax.switch`` over all permutations, so it stays random per call even
    under jit (host RNG would freeze at trace time).  Larger lists fall
    back to a host-drawn order (random per call only when not jitted)."""

    def __init__(self, ts):
        super().__init__()
        self.ts = list(ts)

    def batch_apply(self, x, key):
        import itertools

        import jax
        n = len(self.ts)
        if n == 0:
            return x
        korder, key = jax.random.split(key)
        subs = jax.random.split(key, n)
        if n <= 4:
            perms = list(itertools.permutations(range(n)))

            def branch(perm):
                def run(x):
                    for j in perm:
                        nonlocal_subs = subs[j]
                        x = self.ts[j].batch_apply(x, nonlocal_subs)
                    return x
                return run
            idx = jax.random.randint(korder, (), 0, len(perms))
            return jax.lax.switch(idx, [branch(p) for p in perms], x)
        order = onp.random.permutation(n)
        for i in order:
            x = self.ts[int(i)].batch_apply(x, subs[int(i)])
        return x

    def __call__(self, src):
        order = onp.random.permutation(len(self.ts))
        for i in order:
            src = self.ts[int(i)](src)
        return src


class ResizeAug(Augmenter):
    """Short-edge resize."""

    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size, self.interp = size, interp

    def out_hw(self, in_hw):
        h, w = in_hw
        if h > w:
            return (int(h * self.size / w), self.size)
        return (self.size, int(w * self.size / h))

    def batch_apply(self, x, key):
        return _batch_resize(x, self.out_hw(x.shape[1:3]),
                             bilinear=bool(self.interp))


class ForceResizeAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size, self.interp = size, interp  # (w, h)

    def out_hw(self, in_hw):
        return (self.size[1], self.size[0])

    def batch_apply(self, x, key):
        return _batch_resize(x, (self.size[1], self.size[0]),
                             bilinear=bool(self.interp))


class RandomCropAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size, self.interp = size, interp  # (w, h)

    def out_hw(self, in_hw):
        return (self.size[1], self.size[0])

    def batch_apply(self, x, key):
        import jax
        import jax.numpy as jnp
        n, H, W, _ = x.shape
        w, h = self.size
        ky, kx = jax.random.split(key)
        # inclusive upper corner, like the reference's randint(0, H-h+1)
        y0 = jax.random.randint(ky, (n,), 0, max(H - h, 0) + 1)
        x0 = jax.random.randint(kx, (n,), 0, max(W - w, 0) + 1)
        hs = jnp.full((n,), float(h))
        ws = jnp.full((n,), float(w))
        return _affine_crop_resize(x, y0.astype(jnp.float32),
                                   x0.astype(jnp.float32), hs, ws,
                                   (h, w), bilinear=bool(self.interp))


class CenterCropAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size, self.interp = size, interp

    def out_hw(self, in_hw):
        return (self.size[1], self.size[0])

    def batch_apply(self, x, key):
        import jax.numpy as jnp
        n, H, W, _ = x.shape
        w, h = self.size
        y0 = jnp.full((n,), float((H - h) // 2))
        x0 = jnp.full((n,), float((W - w) // 2))
        return _affine_crop_resize(x, y0, x0, jnp.full((n,), float(h)),
                                   jnp.full((n,), float(w)), (h, w),
                                   bilinear=bool(self.interp))


class RandomSizedCropAug(Augmenter):
    """Inception-style area/aspect crop, batched: per-sample (area,
    aspect) drawn on device, realized as an affine resample to the fixed
    output size (no data-dependent shapes)."""

    def __init__(self, size, area, ratio, interp=2):
        super().__init__(size=size, area=area, ratio=ratio, interp=interp)
        if isinstance(area, (int, float)):
            area = (area, 1.0)
        self.size, self.area, self.ratio, self.interp = \
            size, area, ratio, interp

    def out_hw(self, in_hw):
        return (self.size[1], self.size[0])

    def batch_apply(self, x, key):
        import jax
        import jax.numpy as jnp
        n, H, W, _ = x.shape
        ka, kr, ky, kx = jax.random.split(key, 4)
        area = jax.random.uniform(ka, (n,), minval=self.area[0],
                                  maxval=self.area[1]) * (H * W)
        logr = jax.random.uniform(
            kr, (n,), minval=onp.log(self.ratio[0]),
            maxval=onp.log(self.ratio[1]))
        aspect = jnp.exp(logr)
        ws = jnp.sqrt(area * aspect)
        hs = jnp.sqrt(area / aspect)
        # clamp to the image (the reference retries then center-crops;
        # clamping is the batched equivalent)
        ws = jnp.minimum(ws, W)
        hs = jnp.minimum(hs, H)
        y0 = jax.random.uniform(ky, (n,)) * (H - hs)
        x0 = jax.random.uniform(kx, (n,)) * (W - ws)
        return _affine_crop_resize(x, y0, x0, hs, ws,
                                   (self.size[1], self.size[0]),
                                   bilinear=bool(self.interp))


class HorizontalFlipAug(Augmenter):
    def __init__(self, p):
        super().__init__(p=p)
        self.p = p

    def batch_apply(self, x, key):
        import jax
        import jax.numpy as jnp
        n = x.shape[0]
        flip = jax.random.bernoulli(key, self.p, (n,))
        return jnp.where(flip[:, None, None, None], x[:, :, ::-1], x)

    def __call__(self, src):
        if onp.random.random() < self.p:
            return src[:, ::-1]
        return src


class CastAug(Augmenter):
    def __init__(self, typ="float32"):
        super().__init__(typ=typ)
        self.typ = typ

    def batch_apply(self, x, key):
        return x  # batch path already runs in float32

    def __call__(self, src):
        return src.astype(self.typ)


class BrightnessJitterAug(Augmenter):
    def __init__(self, brightness):
        super().__init__(brightness=brightness)
        self.brightness = brightness

    def batch_apply(self, x, key):
        import jax
        n = x.shape[0]
        alpha = 1.0 + jax.random.uniform(
            key, (n, 1, 1, 1), minval=-self.brightness,
            maxval=self.brightness)
        return x * alpha


class ContrastJitterAug(Augmenter):
    """Blend with the per-image mean luminance."""

    def __init__(self, contrast):
        super().__init__(contrast=contrast)
        self.contrast = contrast

    def batch_apply(self, x, key):
        import jax
        n = x.shape[0]
        alpha = 1.0 + jax.random.uniform(
            key, (n, 1, 1, 1), minval=-self.contrast, maxval=self.contrast)
        mean_luma = _rgb_luma(x).mean(axis=(1, 2), keepdims=True)
        return x * alpha + mean_luma * (1.0 - alpha)


class SaturationJitterAug(Augmenter):
    """Blend each pixel with its own luminance."""

    def __init__(self, saturation):
        super().__init__(saturation=saturation)
        self.saturation = saturation

    def batch_apply(self, x, key):
        import jax
        n = x.shape[0]
        alpha = 1.0 + jax.random.uniform(
            key, (n, 1, 1, 1), minval=-self.saturation,
            maxval=self.saturation)
        return x * alpha + _rgb_luma(x) * (1.0 - alpha)


class HueJitterAug(Augmenter):
    """Hue rotation about the RGB gray axis.

    Built from Rodrigues' rotation of the color cube around (1,1,1)/√3 —
    constructed with jnp per sample (batched), rather than a fixed
    YIQ-basis matrix product."""

    def __init__(self, hue):
        super().__init__(hue=hue)
        self.hue = hue

    @staticmethod
    def _rotate(x, theta):
        """Rotate (N,H,W,3) batch colors by per-sample angles theta."""
        import jax.numpy as jnp
        c = jnp.cos(theta)[:, None, None]
        s = jnp.sin(theta)[:, None, None]
        eye = jnp.eye(3)
        axis = jnp.ones((3, 3)) / 3.0               # uu^T for u = gray axis
        k = jnp.asarray([[0.0, -1.0, 1.0],
                         [1.0, 0.0, -1.0],
                         [-1.0, 1.0, 0.0]]) / jnp.sqrt(3.0)  # cross matrix
        rot = c * eye + (1 - c) * axis + s * k       # (n, 3, 3)
        return jnp.einsum("nhwc,ncd->nhwd", x, rot)

    def batch_apply(self, x, key):
        import jax
        import jax.numpy as jnp
        n = x.shape[0]
        theta = jax.random.uniform(key, (n,), minval=-self.hue,
                                   maxval=self.hue) * jnp.pi
        return self._rotate(x, theta)


class ColorJitterAug(RandomOrderAug):
    def __init__(self, brightness, contrast, saturation):
        ts = []
        if brightness > 0:
            ts.append(BrightnessJitterAug(brightness))
        if contrast > 0:
            ts.append(ContrastJitterAug(contrast))
        if saturation > 0:
            ts.append(SaturationJitterAug(saturation))
        super().__init__(ts)


class LightingAug(Augmenter):
    """PCA (AlexNet-style) lighting noise, per-sample."""

    def __init__(self, alphastd, eigval, eigvec):
        super().__init__(alphastd=alphastd)
        self.alphastd = alphastd
        self.eigval = onp.asarray(eigval, "float32")
        self.eigvec = onp.asarray(eigvec, "float32")

    def batch_apply(self, x, key):
        import jax
        import jax.numpy as jnp
        n = x.shape[0]
        alpha = jax.random.normal(key, (n, 3)) * self.alphastd
        rgb = (alpha * self.eigval) @ jnp.asarray(self.eigvec).T
        return x + rgb[:, None, None, :]


class ColorNormalizeAug(Augmenter):
    def __init__(self, mean, std):
        super().__init__()
        self.mean = None if mean is None else onp.asarray(mean, "float32")
        self.std = None if std is None else onp.asarray(std, "float32")

    def batch_apply(self, x, key):
        import jax.numpy as jnp
        if self.mean is not None:
            x = x - jnp.asarray(self.mean)
        if self.std is not None:
            x = x / jnp.asarray(self.std)
        return x

    def __call__(self, src):
        return color_normalize(src, self.mean, self.std)


class RandomGrayAug(Augmenter):
    def __init__(self, p):
        super().__init__(p=p)
        self.p = p

    def batch_apply(self, x, key):
        import jax
        import jax.numpy as jnp
        n = x.shape[0]
        gray = jnp.broadcast_to(_rgb_luma(x), x.shape)
        pick = jax.random.bernoulli(key, self.p, (n,))
        return jnp.where(pick[:, None, None, None], gray, x)


def CreateAugmenter(data_shape, resize=0, rand_crop=False, rand_resize=False,
                    rand_mirror=False, mean=None, std=None, brightness=0,
                    contrast=0, saturation=0, hue=0, pca_noise=0,
                    rand_gray=0, inter_method=2):
    """Standard augmenter list factory — the ordering (resize, crop,
    mirror, cast, color, hue, pca, gray, normalize) is the reference's
    documented pipeline contract (image.py CreateAugmenter)."""
    auglist = []
    if resize > 0:
        auglist.append(ResizeAug(resize, inter_method))
    crop_size = (data_shape[2], data_shape[1])
    if rand_resize:
        auglist.append(RandomSizedCropAug(crop_size, (0.08, 1.0),
                                          (3 / 4.0, 4 / 3.0), inter_method))
    elif rand_crop:
        auglist.append(RandomCropAug(crop_size, inter_method))
    else:
        auglist.append(CenterCropAug(crop_size, inter_method))
    if rand_mirror:
        auglist.append(HorizontalFlipAug(0.5))
    auglist.append(CastAug())
    if brightness or contrast or saturation:
        auglist.append(ColorJitterAug(brightness, contrast, saturation))
    if hue:
        auglist.append(HueJitterAug(hue))
    if pca_noise > 0:
        auglist.append(LightingAug(
            pca_noise,
            onp.array([55.46, 4.794, 1.148]),
            onp.array([[-0.5675, 0.7192, 0.4009],
                       [-0.5808, -0.0045, -0.8140],
                       [-0.5836, -0.6948, 0.4203]])))
    if rand_gray > 0:
        auglist.append(RandomGrayAug(rand_gray))
    if mean is True:
        mean = onp.array([123.68, 116.28, 103.53])
    if std is True:
        std = onp.array([58.395, 57.12, 57.375])
    if mean is not None or std is not None:
        auglist.append(ColorNormalizeAug(mean, std))
    return auglist


def apply_batch(auglist, batch, key=None):
    """Run an augmenter list over an (N,H,W,C) batch in one device pass.

    Uniform-shape batches go through each augmenter's ``batch_apply``
    (jax.random key per stage).  Returns float32 (N,H,W,C).
    """
    from . import random as _random
    import jax
    import jax.numpy as jnp
    x = batch._data if isinstance(batch, ndarray) else jnp.asarray(batch)
    x = x.astype(jnp.float32)
    if key is None:
        key = _random._next_key()
    for aug in auglist:
        key, sub = jax.random.split(key)
        x = aug.batch_apply(x, sub)
    return _wrap(x)


class ImageIter:
    """Image data iterator over RecordIO or an image list (reference:
    image.py ImageIter: decode -> augment -> batch, NCHW output).

    Batch-first: samples are decoded on host, stacked once, and the whole
    augmenter chain runs as device batch ops (``apply_batch``).  Mixed
    source sizes fall back to the per-image path for the geometric prefix
    until shapes unify."""

    def __init__(self, batch_size, data_shape, label_width=1,
                 path_imgrec=None, path_imglist=None, path_root="",
                 shuffle=False, aug_list=None, imglist=None,
                 data_name="data", label_name="softmax_label", **kwargs):
        from .recordio import MXIndexedRecordIO, MXRecordIO
        self.batch_size = batch_size
        self.data_shape = tuple(data_shape)
        self.label_width = label_width
        self.aug_list = (aug_list if aug_list is not None
                         else CreateAugmenter(data_shape))
        self.shuffle = shuffle
        self.record = None
        self.imglist = None
        self.path_root = path_root
        if path_imgrec:
            idx_path = os.path.splitext(path_imgrec)[0] + ".idx"
            if os.path.exists(idx_path):
                self.record = MXIndexedRecordIO(idx_path, path_imgrec, "r")
                self.seq = list(self.record.keys)
            else:
                self.record = MXRecordIO(path_imgrec, "r")
                self.seq = None
        elif path_imglist or imglist is not None:
            entries = []
            if path_imglist:
                with open(path_imglist) as f:
                    for line in f:
                        parts = line.strip().split("\t")
                        entries.append((
                            onp.array([float(x) for x in parts[1:-1]]),
                            parts[-1]))
            else:
                for item in imglist:
                    entries.append((onp.asarray(item[:-1], "float32"),
                                    item[-1]))
            self.imglist = entries
            self.seq = list(range(len(entries)))
        else:
            raise MXNetError("ImageIter needs path_imgrec, path_imglist or "
                             "imglist")
        self._cursor = 0
        self.reset()

    def reset(self):
        self._cursor = 0
        if self.seq is not None and self.shuffle:
            onp.random.shuffle(self.seq)
        if self.record is not None and self.seq is None:
            self.record.reset()

    def _next_sample(self):
        from . import recordio as rio
        if self.record is not None:
            if self.seq is not None:
                if self._cursor >= len(self.seq):
                    raise StopIteration
                s = self.record.read_idx(self.seq[self._cursor])
                self._cursor += 1
            else:
                s = self.record.read()
                if s is None:
                    raise StopIteration
            header, img = rio.unpack(s)
            label = onp.array(header.label)
            return label, img
        if self._cursor >= len(self.seq):
            raise StopIteration
        label, fname = self.imglist[self.seq[self._cursor]]
        self._cursor += 1
        with open(os.path.join(self.path_root, fname), "rb") as f:
            return onp.asarray(label), f.read()

    def __iter__(self):
        return self

    def __next__(self):
        return self.next()

    def next(self):
        from .io import DataBatch
        import jax.numpy as jnp
        c, h, w = self.data_shape
        labels = onp.zeros((self.batch_size, self.label_width), "float32")
        bufs = []
        i = 0
        try:
            while i < self.batch_size:
                label, buf = self._next_sample()
                bufs.append(buf)
                labels[i] = onp.asarray(label).reshape(-1)[:self.label_width]
                i += 1
        except StopIteration:
            if i == 0:
                raise
        # whole-batch decode: JPEGs fan out over native libjpeg threads
        raws = imdecode_batch_np(bufs, flag=1 if c == 3 else 0)
        pad = self.batch_size - i

        shapes = {r.shape for r in raws}
        if len(shapes) == 1:
            # uniform batch: one stack, one fused device augment pass
            stacked = onp.stack(raws).astype("float32")
            out = apply_batch(self.aug_list, stacked)._data
        else:
            # mixed sizes: per-image until the chain's first shape-
            # unifying stage, then there's nothing left to batch
            imgs = []
            for r in raws:
                img = _wrap(jnp.asarray(r))
                for aug in self.aug_list:
                    img = aug(img)
                imgs.append(img._data.astype(jnp.float32))
            out = jnp.stack(imgs)
        if pad:
            fill = jnp.zeros((pad,) + tuple(out.shape[1:]), out.dtype)
            out = jnp.concatenate([out, fill])
        out = jnp.transpose(out, (0, 3, 1, 2))  # NHWC -> NCHW API contract
        lab = labels[:, 0] if self.label_width == 1 else labels
        return DataBatch([_wrap(out)], [_wrap(jnp.asarray(lab))], pad=pad)


# detection pipeline (reference surfaces these in mx.image as well:
# python/mxnet/image/__init__.py re-exports image/detection.py)
from .image_detection import (  # noqa: E402,F401
    DetAugmenter, DetBorrowAug, DetRandomSelectAug, DetHorizontalFlipAug,
    DetRandomCropAug, DetRandomPadAug, CreateMultiRandCropAugmenter,
    CreateDetAugmenter, ImageDetIter)

"""mx.image — image codecs + augmenters.

Reference parity: python/mxnet/image/ (imdecode/imread/imresize via OpenCV,
ImageIter augmenter chain) over src/io/image_io.cc.

This environment has no OpenCV; codecs use PIL when importable and a raw
numpy .npy/.ppm fallback otherwise (sufficient for RecordIO pipelines that
pack raw arrays). Resize/crop augmenters run via jax.image on device.
"""
from __future__ import annotations

import io as _io
import os

import numpy as onp

from .base import MXNetError
from .numpy.multiarray import _wrap, ndarray


def _pil():
    try:
        from PIL import Image
        return Image
    except ImportError:
        return None


def imdecode(buf, flag=1, to_rgb=True, out=None):
    """Decode image bytes to HWC ndarray (reference: image.py imdecode)."""
    import jax.numpy as jnp
    if isinstance(buf, ndarray):
        buf = bytes(buf.asnumpy().astype(onp.uint8))
    Image = _pil()
    if buf[:6] == b"\x93NUMPY":
        arr = onp.load(_io.BytesIO(buf), allow_pickle=False)
    elif Image is not None:
        img = Image.open(_io.BytesIO(buf))
        img = img.convert("RGB" if flag else "L")
        arr = onp.asarray(img)
        if not flag:
            arr = arr[..., None]
    else:
        raise MXNetError("no image codec available (PIL missing); pack raw "
                         ".npy payloads instead")
    if arr.ndim == 2:
        arr = arr[..., None]
    return _wrap(jnp.asarray(arr))


def imencode(img, fmt=".jpg", quality=95):
    if isinstance(img, ndarray):
        img = img.asnumpy()
    Image = _pil()
    if Image is None or fmt == ".npy":
        bio = _io.BytesIO()
        onp.save(bio, onp.asarray(img))
        return bio.getvalue()
    bio = _io.BytesIO()
    Image.fromarray(onp.asarray(img).squeeze().astype(onp.uint8)).save(
        bio, format=fmt.strip(".").upper().replace("JPG", "JPEG"),
        quality=quality)
    return bio.getvalue()


def imread(filename, flag=1, to_rgb=True):
    """Reference: image.py imread."""
    if filename.endswith(".npy"):
        import jax.numpy as jnp
        return _wrap(jnp.asarray(onp.load(filename)))
    with open(filename, "rb") as f:
        return imdecode(f.read(), flag, to_rgb)


def imresize(src, w, h, interp=1):
    import jax
    import jax.numpy as jnp
    raw = src._data if isinstance(src, ndarray) else jnp.asarray(src)
    out = jax.image.resize(raw.astype(jnp.float32),
                           (h, w) + tuple(raw.shape[2:]),
                           method="bilinear" if interp else "nearest")
    return _wrap(out.astype(raw.dtype))


def fixed_crop(src, x0, y0, w, h, size=None, interp=2):
    out = src[y0:y0 + h, x0:x0 + w]
    if size is not None and (w, h) != size:
        out = imresize(out, size[0], size[1], interp)
    return out


def center_crop(src, size, interp=2):
    H, W = src.shape[0], src.shape[1]
    w, h = size
    x0, y0 = (W - w) // 2, (H - h) // 2
    return fixed_crop(src, x0, y0, w, h), (x0, y0, w, h)


def random_crop(src, size, interp=2):
    H, W = src.shape[0], src.shape[1]
    w, h = size
    x0 = onp.random.randint(0, max(W - w, 0) + 1)
    y0 = onp.random.randint(0, max(H - h, 0) + 1)
    return fixed_crop(src, x0, y0, w, h), (x0, y0, w, h)


def color_normalize(src, mean, std=None):
    if mean is not None:
        src = src - mean
    if std is not None:
        src = src / std
    return src


def resize_short(src, size, interp=2):
    """Resize so the shorter edge is `size` (reference: image.py
    resize_short)."""
    H, W = src.shape[0], src.shape[1]
    if H > W:
        new_w, new_h = size, int(H * size / W)
    else:
        new_w, new_h = int(W * size / H), size
    return imresize(src, new_w, new_h, interp)


def random_size_crop(src, size, area, ratio, interp=2):
    """Random area/aspect crop (reference: image.py random_size_crop —
    the Inception-style training crop)."""
    H, W = src.shape[0], src.shape[1]
    src_area = H * W
    if isinstance(area, (int, float)):
        area = (area, 1.0)
    for _ in range(10):
        target_area = onp.random.uniform(*area) * src_area
        log_ratio = (onp.log(ratio[0]), onp.log(ratio[1]))
        aspect = onp.exp(onp.random.uniform(*log_ratio))
        w = int(round((target_area * aspect) ** 0.5))
        h = int(round((target_area / aspect) ** 0.5))
        if w <= W and h <= H:
            x0 = onp.random.randint(0, W - w + 1)
            y0 = onp.random.randint(0, H - h + 1)
            return fixed_crop(src, x0, y0, w, h, size, interp), \
                (x0, y0, w, h)
    return center_crop(src, size, interp)


# -- augmenter chain (reference: python/mxnet/image/image.py Augmenter
#    classes + CreateAugmenter) ---------------------------------------------

class Augmenter:
    """Image augmenter base (reference: image.py:~1000 Augmenter)."""

    def __init__(self, **kwargs):
        self._kwargs = kwargs

    def dumps(self):
        import json
        return json.dumps([type(self).__name__, self._kwargs])

    def __call__(self, src):
        raise NotImplementedError


class SequentialAug(Augmenter):
    def __init__(self, ts):
        super().__init__()
        self.ts = list(ts)

    def __call__(self, src):
        for t in self.ts:
            src = t(src)
        return src


class RandomOrderAug(Augmenter):
    def __init__(self, ts):
        super().__init__()
        self.ts = list(ts)

    def __call__(self, src):
        order = onp.random.permutation(len(self.ts))
        for i in order:
            src = self.ts[i](src)
        return src


class ResizeAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size, self.interp = size, interp

    def __call__(self, src):
        return resize_short(src, self.size, self.interp)


class ForceResizeAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size, self.interp = size, interp

    def __call__(self, src):
        return imresize(src, self.size[0], self.size[1], self.interp)


class RandomCropAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size, self.interp = size, interp

    def __call__(self, src):
        return random_crop(src, self.size, self.interp)[0]


class CenterCropAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size, self.interp = size, interp

    def __call__(self, src):
        return center_crop(src, self.size, self.interp)[0]


class RandomSizedCropAug(Augmenter):
    def __init__(self, size, area, ratio, interp=2):
        super().__init__(size=size, area=area, ratio=ratio, interp=interp)
        self.size, self.area, self.ratio, self.interp = \
            size, area, ratio, interp

    def __call__(self, src):
        return random_size_crop(src, self.size, self.area, self.ratio,
                                self.interp)[0]


class HorizontalFlipAug(Augmenter):
    def __init__(self, p):
        super().__init__(p=p)
        self.p = p

    def __call__(self, src):
        if onp.random.random() < self.p:
            return src[:, ::-1]
        return src


class CastAug(Augmenter):
    def __init__(self, typ="float32"):
        super().__init__(typ=typ)
        self.typ = typ

    def __call__(self, src):
        return src.astype(self.typ)


class BrightnessJitterAug(Augmenter):
    def __init__(self, brightness):
        super().__init__(brightness=brightness)
        self.brightness = brightness

    def __call__(self, src):
        alpha = 1.0 + onp.random.uniform(-self.brightness, self.brightness)
        return src * alpha


class ContrastJitterAug(Augmenter):
    _coef = onp.array([[[0.299, 0.587, 0.114]]], "float32")

    def __init__(self, contrast):
        super().__init__(contrast=contrast)
        self.contrast = contrast

    def __call__(self, src):
        alpha = 1.0 + onp.random.uniform(-self.contrast, self.contrast)
        import jax.numpy as jnp
        raw = src._data if isinstance(src, ndarray) else jnp.asarray(src)
        gray = (raw.astype(jnp.float32) * jnp.asarray(self._coef)).sum()
        gray = gray * (3.0 / raw.size) * (1.0 - alpha)
        return _wrap((raw * alpha + gray).astype(raw.dtype))


class SaturationJitterAug(Augmenter):
    _coef = onp.array([[[0.299, 0.587, 0.114]]], "float32")

    def __init__(self, saturation):
        super().__init__(saturation=saturation)
        self.saturation = saturation

    def __call__(self, src):
        alpha = 1.0 + onp.random.uniform(-self.saturation, self.saturation)
        import jax.numpy as jnp
        raw = src._data if isinstance(src, ndarray) else jnp.asarray(src)
        gray = (raw.astype(jnp.float32)
                * jnp.asarray(self._coef)).sum(-1, keepdims=True)
        return _wrap((raw * alpha + gray * (1.0 - alpha)).astype(raw.dtype))


class HueJitterAug(Augmenter):
    """Hue jitter via the YIQ rotation trick (reference: image.py
    HueJitterAug cites the same approximation)."""

    def __init__(self, hue):
        super().__init__(hue=hue)
        self.hue = hue
        self.tyiq = onp.array([[0.299, 0.587, 0.114],
                               [0.596, -0.274, -0.321],
                               [0.211, -0.523, 0.311]], "float32")
        self.ityiq = onp.array([[1.0, 0.956, 0.621],
                                [1.0, -0.272, -0.647],
                                [1.0, -1.107, 1.705]], "float32")

    def __call__(self, src):
        import jax.numpy as jnp
        alpha = onp.random.uniform(-self.hue, self.hue)
        u, w = onp.cos(alpha * onp.pi), onp.sin(alpha * onp.pi)
        bt = onp.array([[1.0, 0.0, 0.0], [0.0, u, -w], [0.0, w, u]],
                       "float32")
        t = onp.dot(onp.dot(self.ityiq, bt), self.tyiq).T
        raw = src._data if isinstance(src, ndarray) else jnp.asarray(src)
        return _wrap(jnp.einsum("hwc,cd->hwd", raw.astype(jnp.float32),
                                jnp.asarray(t)).astype(raw.dtype))


class ColorJitterAug(RandomOrderAug):
    def __init__(self, brightness, contrast, saturation):
        ts = []
        if brightness > 0:
            ts.append(BrightnessJitterAug(brightness))
        if contrast > 0:
            ts.append(ContrastJitterAug(contrast))
        if saturation > 0:
            ts.append(SaturationJitterAug(saturation))
        super().__init__(ts)


class LightingAug(Augmenter):
    """PCA (AlexNet-style) lighting noise."""

    def __init__(self, alphastd, eigval, eigvec):
        super().__init__(alphastd=alphastd)
        self.alphastd = alphastd
        self.eigval = onp.asarray(eigval, "float32")
        self.eigvec = onp.asarray(eigvec, "float32")

    def __call__(self, src):
        alpha = onp.random.normal(0, self.alphastd, size=(3,))
        rgb = onp.dot(self.eigvec * alpha, self.eigval)
        return src + rgb.astype("float32")


class ColorNormalizeAug(Augmenter):
    def __init__(self, mean, std):
        super().__init__()
        self.mean = None if mean is None else onp.asarray(mean, "float32")
        self.std = None if std is None else onp.asarray(std, "float32")

    def __call__(self, src):
        return color_normalize(src, self.mean, self.std)


class RandomGrayAug(Augmenter):
    _coef = onp.array([[[0.299], [0.587], [0.114]]], "float32").reshape(1, 1, 3)

    def __init__(self, p):
        super().__init__(p=p)
        self.p = p

    def __call__(self, src):
        if onp.random.random() < self.p:
            import jax.numpy as jnp
            raw = src._data if isinstance(src, ndarray) else jnp.asarray(src)
            gray = (raw.astype(jnp.float32)
                    * jnp.asarray(self._coef)).sum(-1, keepdims=True)
            return _wrap(jnp.broadcast_to(gray, raw.shape).astype(raw.dtype))
        return src


def CreateAugmenter(data_shape, resize=0, rand_crop=False, rand_resize=False,
                    rand_mirror=False, mean=None, std=None, brightness=0,
                    contrast=0, saturation=0, hue=0, pca_noise=0,
                    rand_gray=0, inter_method=2):
    """Standard augmenter list factory (reference: image.py
    CreateAugmenter)."""
    auglist = []
    if resize > 0:
        auglist.append(ResizeAug(resize, inter_method))
    crop_size = (data_shape[2], data_shape[1])
    if rand_resize:
        auglist.append(RandomSizedCropAug(crop_size, (0.08, 1.0),
                                          (3 / 4.0, 4 / 3.0), inter_method))
    elif rand_crop:
        auglist.append(RandomCropAug(crop_size, inter_method))
    else:
        auglist.append(CenterCropAug(crop_size, inter_method))
    if rand_mirror:
        auglist.append(HorizontalFlipAug(0.5))
    auglist.append(CastAug())
    if brightness or contrast or saturation:
        auglist.append(ColorJitterAug(brightness, contrast, saturation))
    if hue:
        auglist.append(HueJitterAug(hue))
    if pca_noise > 0:
        auglist.append(LightingAug(
            pca_noise,
            onp.array([55.46, 4.794, 1.148]),
            onp.array([[-0.5675, 0.7192, 0.4009],
                       [-0.5808, -0.0045, -0.8140],
                       [-0.5836, -0.6948, 0.4203]])))
    if rand_gray > 0:
        auglist.append(RandomGrayAug(rand_gray))
    if mean is True:
        mean = onp.array([123.68, 116.28, 103.53])
    if std is True:
        std = onp.array([58.395, 57.12, 57.375])
    if mean is not None or std is not None:
        auglist.append(ColorNormalizeAug(mean, std))
    return auglist


class ImageIter:
    """Image data iterator over RecordIO or an image list (reference:
    image.py ImageIter: decode -> augment -> batch, NCHW output)."""

    def __init__(self, batch_size, data_shape, label_width=1,
                 path_imgrec=None, path_imglist=None, path_root="",
                 shuffle=False, aug_list=None, imglist=None,
                 data_name="data", label_name="softmax_label", **kwargs):
        from .recordio import MXIndexedRecordIO, MXRecordIO
        self.batch_size = batch_size
        self.data_shape = tuple(data_shape)
        self.label_width = label_width
        self.aug_list = (aug_list if aug_list is not None
                         else CreateAugmenter(data_shape))
        self.shuffle = shuffle
        self.record = None
        self.imglist = None
        self.path_root = path_root
        if path_imgrec:
            idx_path = os.path.splitext(path_imgrec)[0] + ".idx"
            if os.path.exists(idx_path):
                self.record = MXIndexedRecordIO(idx_path, path_imgrec, "r")
                self.seq = list(self.record.keys)
            else:
                self.record = MXRecordIO(path_imgrec, "r")
                self.seq = None
        elif path_imglist or imglist is not None:
            entries = []
            if path_imglist:
                with open(path_imglist) as f:
                    for line in f:
                        parts = line.strip().split("\t")
                        entries.append((
                            onp.array([float(x) for x in parts[1:-1]]),
                            parts[-1]))
            else:
                for item in imglist:
                    entries.append((onp.asarray(item[:-1], "float32"),
                                    item[-1]))
            self.imglist = entries
            self.seq = list(range(len(entries)))
        else:
            raise MXNetError("ImageIter needs path_imgrec, path_imglist or "
                             "imglist")
        self._cursor = 0
        self.reset()

    def reset(self):
        self._cursor = 0
        if self.seq is not None and self.shuffle:
            onp.random.shuffle(self.seq)
        if self.record is not None and self.seq is None:
            self.record.reset()

    def _next_sample(self):
        from . import recordio as rio
        if self.record is not None:
            if self.seq is not None:
                if self._cursor >= len(self.seq):
                    raise StopIteration
                s = self.record.read_idx(self.seq[self._cursor])
                self._cursor += 1
            else:
                s = self.record.read()
                if s is None:
                    raise StopIteration
            header, img = rio.unpack(s)
            label = onp.array(header.label)
            return label, img
        if self._cursor >= len(self.seq):
            raise StopIteration
        label, fname = self.imglist[self.seq[self._cursor]]
        self._cursor += 1
        with open(os.path.join(self.path_root, fname), "rb") as f:
            return onp.asarray(label), f.read()

    def __iter__(self):
        return self

    def __next__(self):
        return self.next()

    def next(self):
        from .io import DataBatch
        from .numpy import zeros as np_zeros
        import jax.numpy as jnp
        c, h, w = self.data_shape
        batch = onp.zeros((self.batch_size, c, h, w), "float32")
        labels = onp.zeros((self.batch_size, self.label_width), "float32")
        i = 0
        try:
            while i < self.batch_size:
                label, buf = self._next_sample()
                img = imdecode(buf, flag=1 if c == 3 else 0)
                for aug in self.aug_list:
                    img = aug(img)
                arr = img.asnumpy() if isinstance(img, ndarray) \
                    else onp.asarray(img)
                batch[i] = arr.transpose(2, 0, 1)
                labels[i] = onp.asarray(label).reshape(-1)[:self.label_width]
                i += 1
        except StopIteration:
            if i == 0:
                raise
        pad = self.batch_size - i
        lab = labels[:, 0] if self.label_width == 1 else labels
        return DataBatch([_wrap(jnp.asarray(batch))],
                         [_wrap(jnp.asarray(lab))], pad=pad)

"""RecordIO format.

Reference parity: python/mxnet/recordio.py (MXRecordIO/MXIndexedRecordIO over
dmlc-core recordio; pack/unpack with IRHeader for image records). Binary
format kept bit-compatible: magic 0xced7230a, 32-bit LE kmagic + lrecord
(upper 3 bits cflag, lower 29 length), 4-byte alignment padding — existing
.rec datasets load unchanged. A C++ reader (src/native) accelerates bulk
scanning when built; this python implementation is the always-available path.
"""
from __future__ import annotations

import collections
import os
import struct

import numpy as onp

from .base import MXNetError

_MAGIC = 0xced7230a
_CFLAG_BITS = 29
_LEN_MASK = (1 << _CFLAG_BITS) - 1


class RecordIOCorrupt(MXNetError):
    """Structured corruption report from a record stream.

    ``kind`` distinguishes the two failure classes a reader meets:

    - ``"torn_tail"`` — the file ends mid-record (a writer died between
      the header and the payload, or the payload itself was truncated).
      Everything before ``offset`` is intact: the file is *resumable* —
      re-open for append at ``offset``, or stop reading there.
    - ``"bad_magic"`` — framing lost mid-file (bit rot, a seek into the
      middle of a payload). Not resumable; the bytes from ``offset`` on
      cannot be trusted.

    ``offset`` is always the position of the last good record boundary.
    """

    def __init__(self, uri, offset, kind, detail):
        self.uri = uri
        self.offset = int(offset)
        self.kind = kind
        self.resumable = kind == "torn_tail"
        super().__init__(
            f"recordio corruption in {uri!r} at offset {offset}: "
            f"{detail} [{kind}]")


class MXRecordIO:
    """Sequential record file reader/writer (reference: recordio.py:34)."""

    def __init__(self, uri, flag):
        self.uri = uri
        self.flag = flag
        self.record = None
        self.open()

    def open(self):
        if self.flag == "w":
            self.record = open(self.uri, "wb")
            self.writable = True
        elif self.flag == "r":
            self.record = open(self.uri, "rb")
            self.writable = False
        else:
            raise MXNetError(f"invalid flag {self.flag!r}")
        self.is_open = True

    def close(self):
        if self.is_open:
            self.record.close()
            self.is_open = False

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass

    def __getstate__(self):
        d = dict(self.__dict__)
        d["record"] = None
        d["is_open"] = False
        return d

    def __setstate__(self, d):
        self.__dict__.update(d)
        self.open()

    def reset(self):
        self.close()
        self.open()

    def write(self, buf):
        assert self.writable
        self.record.write(struct.pack("<II", _MAGIC, len(buf) & _LEN_MASK))
        self.record.write(buf)
        pad = (4 - (len(buf) % 4)) % 4
        if pad:
            self.record.write(b"\x00" * pad)

    def read(self):
        assert not self.writable
        start = self.record.tell()
        header = self.record.read(8)
        if not header:
            return None          # clean EOF on a record boundary
        if len(header) < 8:
            raise RecordIOCorrupt(
                self.uri, start, "torn_tail",
                f"{len(header)}-byte header fragment at EOF")
        magic, lrec = struct.unpack("<II", header)
        if magic != _MAGIC:
            raise RecordIOCorrupt(
                self.uri, start, "bad_magic",
                f"invalid record magic 0x{magic:08x}")
        length = lrec & _LEN_MASK
        buf = self.record.read(length)
        if len(buf) < length:
            raise RecordIOCorrupt(
                self.uri, start, "torn_tail",
                f"payload truncated: {len(buf)} of {length} bytes")
        pad = (4 - (length % 4)) % 4
        if pad:
            # a short pad is still a complete record: the torn bytes are
            # alignment filler, so tolerate it (next read() reports EOF
            # or the tear, whichever the tail holds)
            self.record.read(pad)
        return buf

    def tell(self):
        return self.record.tell()


class MXIndexedRecordIO(MXRecordIO):
    """Random-access record file with .idx (reference: recordio.py:141)."""

    def __init__(self, idx_path, uri, flag, key_type=int):
        self.idx_path = idx_path
        self.idx = {}
        self.keys = []
        self.key_type = key_type
        self.fidx = None
        super().__init__(uri, flag)

    def open(self):
        super().open()
        self.idx = {}
        self.keys = []
        if self.flag == "r" and os.path.exists(self.idx_path):
            with open(self.idx_path) as f:
                for line in f:
                    parts = line.strip().split("\t")
                    if len(parts) >= 2:
                        key = self.key_type(parts[0])
                        self.idx[key] = int(parts[1])
                        self.keys.append(key)
        elif self.flag == "w":
            self.fidx = open(self.idx_path, "w")

    def close(self):
        if getattr(self, "fidx", None) is not None:
            self.fidx.close()
            self.fidx = None
        super().close()

    def seek(self, idx):
        self.record.seek(self.idx[idx])

    def read_idx(self, idx):
        self.seek(idx)
        return self.read()

    def write_idx(self, idx, buf):
        key = self.key_type(idx)
        pos = self.tell()
        self.write(buf)
        self.fidx.write(f"{key}\t{pos}\n")
        self.idx[key] = pos
        self.keys.append(key)


IndexedRecordIO = MXIndexedRecordIO

# image record header (reference: recordio.py IRHeader)
IRHeader = collections.namedtuple("IRHeader", ["flag", "label", "id", "id2"])
_IR_FORMAT = "<IfQQ"
_IR_SIZE = struct.calcsize(_IR_FORMAT)


def pack(header, s):
    """Pack IRHeader + payload bytes (reference: recordio.py pack)."""
    header = IRHeader(*header)
    if isinstance(header.label, (list, tuple, onp.ndarray)):
        label = onp.asarray(header.label, dtype=onp.float32)
        header = header._replace(flag=label.size, label=0)
        s = label.tobytes() + s
        return struct.pack(_IR_FORMAT, *header) + s
    return struct.pack(_IR_FORMAT, header.flag, float(header.label),
                       header.id, header.id2) + s


def unpack(s):
    """Unpack a record into (IRHeader, payload) (reference: recordio.py
    unpack)."""
    header = IRHeader(*struct.unpack(_IR_FORMAT, s[:_IR_SIZE]))
    s = s[_IR_SIZE:]
    if header.flag > 0:
        label = onp.frombuffer(s[:header.flag * 4], dtype=onp.float32)
        header = header._replace(label=label)
        s = s[header.flag * 4:]
    return header, s


def unpack_img(s, iscolor=-1):
    header, img_bytes = unpack(s)
    from .image import imdecode
    return header, imdecode(img_bytes, flag=1 if iscolor != 0 else 0).asnumpy()


def pack_img(header, img, quality=95, img_fmt=".jpg"):
    from .image import imencode
    return pack(header, imencode(img, img_fmt, quality))

"""mx.trace — end-to-end causal tracing with Perfetto export.

Where ``mx.telemetry`` aggregates (counters/histograms answer "how much,
on average"), ``mx.trace`` records *spans*: named, timed intervals with
parent/child links, so one slow serve request or one stalled train step
can be read end-to-end (enqueue → prefill → decode steps → drain;
data wait → h2d → dispatch → deferred drain).

Design points, mirroring the rest of the observability plane:

- **One-attr-read disabled fast path.** Every hook left in hot code is
  gated on the module-level ``_active`` bool; disabled, the cost is one
  attribute read (<2% budget, enforced by benchmark/telemetry_overhead.py
  and the CI ``trace`` stage).
- **Bounded ring buffer.** Finished spans land in a per-process deque
  capped by the ``trace.buffer`` knob; overflow drops oldest-first and
  counts into ``trace.dropped_total``.
- **One clock.** Timestamps are ``profiler.now_us()`` — the same
  CLOCK_MONOTONIC microsecond epoch ``profiler.record_event`` uses, so a
  trace export and a profiler dump line up, and (Linux) spans built in
  DataLoader worker processes land on the parent's timeline too.
- **Context propagation.** ``current_context()`` yields a portable
  ``(trace_id, span_id)`` pair; ``adopt``/``attach`` rebind it on
  background threads (DevicePrefetcher), and ``make_span``/``ingest``
  carry spans across process boundaries (DataLoader workers).
- **Perfetto/Chrome export.** The ring already holds Chrome trace-event
  dicts (``ph: "X"``); ``export(path)`` wraps them in ``traceEvents`` —
  load in ``ui.perfetto.dev`` or ``chrome://tracing`` as-is.  While the
  profiler is running, finished spans also mirror into its aggregate
  table (``profiler.dumps()``) under ``trace:<category>``, and when a
  device trace is armed (``set_config(tensorboard_dir=...)`` +
  ``set_state('run')``) ``span()`` brackets itself with
  ``jax.profiler.TraceAnnotation`` so host spans align with the XLA
  device timeline in Xprof.
"""
from __future__ import annotations

import collections
import contextlib
import itertools
import json
import os
import threading

from . import config as _config
from . import profiler as _profiler
from . import telemetry as _telemetry

__all__ = ["enable", "disable", "active", "configure", "span", "begin",
           "emit", "make_span", "ingest", "current_context", "adopt",
           "attach", "spans", "clear", "stats", "export", "clock_us",
           "SpanHandle"]

_telemetry.declare_metric(
    "trace.dropped_total", "counter",
    "spans evicted from the trace ring buffer (raise trace.buffer or "
    "export more often)")

#: the one-attr-read gate every instrumentation site checks first
_active = False

_lock = threading.Lock()
_events: collections.deque = collections.deque()
_capacity = max(1, int(_config.get("trace.buffer")))
_dropped = 0
_ids = itertools.count(1)
_tls = threading.local()

#: shared monotonic clock (μs) — the profiler's epoch, valid across
#: processes on Linux (CLOCK_MONOTONIC is system-wide).
clock_us = _profiler.now_us


def _new_id():
    return f"{os.getpid():x}.{next(_ids):x}"


def _stack():
    s = getattr(_tls, "stack", None)
    if s is None:
        s = _tls.stack = []
    return s


def current_context():
    """Portable ``(trace_id, span_id)`` of this thread's innermost span
    (None outside any span) — pass to ``adopt``/``attach``/``begin(
    parent=...)``/``make_span`` to parent work on another thread or in
    another process."""
    s = getattr(_tls, "stack", None)
    return tuple(s[-1]) if s else None


def adopt(ctx):
    """Make ``ctx`` the base trace context of the *current* thread (for
    the lifetime of the thread — background workers whose every span
    should parent to the consumer that spawned them)."""
    if ctx:
        _stack().append((ctx[0], ctx[1]))


@contextlib.contextmanager
def attach(ctx):
    """Scoped form of :func:`adopt`: spans opened inside parent to
    ``ctx``; the previous context is restored on exit."""
    if not ctx:
        yield
        return
    s = _stack()
    s.append((ctx[0], ctx[1]))
    try:
        yield
    finally:
        s.pop()


def _record(ev):
    global _dropped
    dropped = 0
    with _lock:
        _events.append(ev)
        while len(_events) > _capacity:
            _events.popleft()
            dropped += 1
        if dropped:
            _dropped += dropped
    if dropped and _telemetry._active:
        _telemetry.inc("trace.dropped_total", dropped)


def _finish(name, category, start_us, dur_us, trace_id, span_id,
            parent_id, attrs):
    args = dict(attrs) if attrs else {}
    args["trace_id"] = trace_id
    args["span_id"] = span_id
    if parent_id is not None:
        args["parent_id"] = parent_id
    _record({"name": name, "cat": category, "ph": "X", "ts": start_us,
             "dur": dur_us, "pid": os.getpid(),
             "tid": threading.get_ident(), "args": args})
    if _profiler.is_running():
        _profiler.record_event(name, "trace:" + category, start_us,
                               dur_us, dict(attrs) if attrs else None)


class _NoopSpan:
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def set(self, **attrs):
        return self


_NOOP = _NoopSpan()


class _Span:
    """Context-manager span: nests via the thread-local context stack."""

    __slots__ = ("name", "category", "attrs", "trace_id", "span_id",
                 "parent_id", "_t0", "_jax", "_onstack")

    def __init__(self, name, category, attrs):
        self.name = name
        self.category = category
        self.attrs = attrs
        self.span_id = _new_id()
        self.trace_id = None
        self.parent_id = None
        self._jax = None
        self._onstack = False

    def set(self, **attrs):
        self.attrs.update(attrs)
        return self

    def __enter__(self):
        s = _stack()
        if s:
            self.trace_id, self.parent_id = s[-1]
        else:
            self.trace_id = self.span_id
        s.append((self.trace_id, self.span_id))
        self._onstack = True
        if _profiler._state.get("device_trace_dir"):
            import jax
            self._jax = jax.profiler.TraceAnnotation(self.name)
            self._jax.__enter__()
        self._t0 = _profiler.now_us()
        return self

    def __exit__(self, *exc):
        t1 = _profiler.now_us()
        if self._jax is not None:
            self._jax.__exit__(*exc)
            self._jax = None
        if self._onstack:
            st = getattr(_tls, "stack", None)
            if st:
                st.pop()
            self._onstack = False
        _finish(self.name, self.category, self._t0,
                max(0, t1 - self._t0), self.trace_id, self.span_id,
                self.parent_id, self.attrs)
        return False


def span(name, category="app", **attrs):
    """``with trace.span("train.step", step=n): ...`` — nested spans
    parent automatically through the thread-local context stack.  A
    cheap no-op object is returned while tracing is disabled."""
    if not _active:
        return _NOOP
    return _Span(name, category, attrs)


class SpanHandle:
    """Explicit begin/end span for async lifetimes (a serve request
    lives across many engine steps and ends on a different code path
    than it began).  Does not touch the thread-local stack; children
    parent to it via ``parent=handle.context``."""

    __slots__ = ("name", "category", "attrs", "trace_id", "span_id",
                 "parent_id", "_t0", "_done")

    def __init__(self, name, category, attrs, parent):
        self.name = name
        self.category = category
        self.attrs = attrs
        self.span_id = _new_id()
        if parent:
            self.trace_id, self.parent_id = parent
        else:
            self.trace_id, self.parent_id = self.span_id, None
        self._t0 = _profiler.now_us()
        self._done = False

    @property
    def context(self):
        return (self.trace_id, self.span_id)

    def set(self, **attrs):
        self.attrs.update(attrs)
        return self

    def end(self, **attrs):
        if self._done:
            return
        self._done = True
        if attrs:
            self.attrs.update(attrs)
        t1 = _profiler.now_us()
        _finish(self.name, self.category, self._t0,
                max(0, t1 - self._t0), self.trace_id, self.span_id,
                self.parent_id, self.attrs)


def begin(name, category="app", parent=None, **attrs):
    """Open an async span; returns a :class:`SpanHandle` (call
    ``.end()``), or None while tracing is disabled.  ``parent`` is a
    ``(trace_id, span_id)`` context (default: the current thread's)."""
    if not _active:
        return None
    return SpanHandle(name, category, attrs,
                      parent if parent is not None else current_context())


def emit(name, start_us, dur_us, parent=None, category="app", **attrs):
    """Record an already-timed span directly (per-decode-step spans whose
    wall time was measured anyway — no context-stack traffic)."""
    if not _active:
        return
    sid = _new_id()
    if parent is None:
        parent = current_context()
    if parent:
        trace_id, parent_id = parent
    else:
        trace_id, parent_id = sid, None
    _finish(name, category, int(start_us), max(0, int(dur_us)),
            trace_id, sid, parent_id, attrs)


def make_span(name, start_us, dur_us, parent, category="app", **attrs):
    """Build (without recording) one Chrome-trace span dict — for worker
    processes, which ship spans back to the parent in their result tuple
    for :func:`ingest`.  ``parent`` is the consumer's ``(trace_id,
    span_id)`` context; perf_counter is system-wide on Linux, so the
    timestamps land on the parent's timeline unadjusted."""
    sid = _new_id()
    args = dict(attrs)
    if parent:
        args["trace_id"], args["parent_id"] = parent[0], parent[1]
    else:
        args["trace_id"] = sid
    args["span_id"] = sid
    return {"name": name, "cat": category, "ph": "X", "ts": int(start_us),
            "dur": max(0, int(dur_us)), "pid": os.getpid(),
            "tid": threading.get_ident(), "args": args}


def ingest(spans_):
    """Append pre-built span dicts (from :func:`make_span` in another
    process) to this process's ring.  Returns the count ingested."""
    if not _active or not spans_:
        return 0
    for ev in spans_:
        _record(ev)
    return len(spans_)


def spans(last=None, category=None):
    """Snapshot of recorded spans, oldest first.  ``last=N`` keeps the
    newest N; ``category=`` filters on the span category first — the
    reader behind ``/trace?last=N&category=C``."""
    with _lock:
        out = list(_events)
    if category is not None:
        out = [ev for ev in out if ev.get("cat") == category]
    if last is not None and last >= 0:
        out = out[len(out) - min(last, len(out)):]
    return out


def clear():
    global _dropped
    with _lock:
        _events.clear()
        _dropped = 0


def stats():
    with _lock:
        n = len(_events)
    return {"active": _active, "recorded": n, "dropped": _dropped,
            "capacity": _capacity}


def export(path=None, last=None):
    """Write the ring as Chrome trace-event / Perfetto JSON.  Open the
    file in ui.perfetto.dev (or chrome://tracing); span links live in
    ``args`` (trace_id/span_id/parent_id)."""
    path = path or "mxtrace.json"
    with open(path, "w") as f:
        json.dump({"traceEvents": spans(last), "displayTimeUnit": "ms"},
                  f)
    return path


def enable(on=True, buffer=None):
    """Switch the recorder on (or off with ``on=False``); ``buffer``
    resizes the ring."""
    global _active, _capacity
    if buffer is not None:
        _capacity = max(1, int(buffer))
    _active = bool(on)
    return _active


def disable():
    return enable(False)


def active():
    return _active


def configure():
    """Re-read the ``trace.*`` knobs (after mx.config.set or an env
    change) — the spawn-worker arming path."""
    global _capacity
    _capacity = max(1, int(_config.get("trace.buffer")))
    return enable(_config.get("trace.enable"))


# Arm from the environment at import: spawned DataLoader workers inherit
# os.environ, so MXNET_TRACE=1 traces them too (same pattern as
# telemetry/fault).
if _config.get("trace.enable"):
    _active = True

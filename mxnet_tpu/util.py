"""mx.util — numpy-mode scopes and misc helpers.

Reference parity: python/mxnet/util.py (np_shape/np_array scopes, use_np
decorators, getenv wrappers). The new framework always has numpy semantics,
so the scopes are identity context managers kept for API compatibility.
"""
from __future__ import annotations

import contextlib
import functools

from .base import get_env  # noqa: F401


def is_np_shape():
    return True


def is_np_array():
    return True


def is_np_default_dtype():
    return False


@contextlib.contextmanager
def np_shape(active=True):
    yield active


@contextlib.contextmanager
def np_array(active=True):
    yield active


def use_np_shape(func):
    return func


def use_np_array(func):
    return func


def use_np(func):
    return func


def use_np_default_dtype(func):
    return func


def set_np(shape=True, array=True, dtype=False):
    pass


def reset_np():
    pass


def wrap_np_unary_func(func):
    return func


def wrap_np_binary_func(func):
    return func


def default_array(source_array, ctx=None, dtype=None):
    from .numpy import array
    return array(source_array, dtype=dtype, ctx=ctx)


def get_gpu_count():
    from .context import num_gpus
    return num_gpus()


def get_gpu_memory(gpu_dev_id=0):
    import jax
    try:
        stats = jax.devices()[gpu_dev_id].memory_stats()
        return stats.get("bytes_in_use", 0), stats.get("bytes_limit", 0)
    except Exception:
        return (0, 0)

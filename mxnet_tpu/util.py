"""mx.util — numpy-mode scopes and misc helpers.

Reference parity: python/mxnet/util.py (np_shape/np_array scopes, use_np
decorators, getenv wrappers). The new framework always has numpy semantics,
so the scopes are identity context managers kept for API compatibility.
"""
from __future__ import annotations

import contextlib
import functools

from .base import get_env  # noqa: F401


def is_np_shape():
    return True


def is_np_array():
    return True


def is_np_default_dtype():
    return False


@contextlib.contextmanager
def np_shape(active=True):
    yield active


@contextlib.contextmanager
def np_array(active=True):
    yield active


def use_np_shape(func):
    return func


def use_np_array(func):
    return func


def use_np(func):
    return func


def use_np_default_dtype(func):
    return func


def set_np(shape=True, array=True, dtype=False):
    pass


def reset_np():
    pass


def wrap_np_unary_func(func):
    return func


def wrap_np_binary_func(func):
    return func


def default_array(source_array, ctx=None, dtype=None):
    from .numpy import array
    return array(source_array, dtype=dtype, ctx=ctx)


def get_gpu_count():
    from .context import num_gpus
    return num_gpus()


def get_gpu_memory(gpu_dev_id=0):
    import jax
    try:
        stats = jax.devices()[gpu_dev_id].memory_stats()
        return stats.get("bytes_in_use", 0), stats.get("bytes_limit", 0)
    except Exception:
        return (0, 0)


def int64_enabled():
    """Whether 64-bit tensor sizes/dtypes are active.

    Analog of the reference's MXNET_USE_INT64_TENSOR_SIZE build flag
    (docs env_var.md; tests/nightly/test_large_array.py relies on it).
    Here it maps to JAX's x64 mode.
    """
    import jax
    return bool(jax.config.jax_enable_x64)


@contextlib.contextmanager
def int64_tensor_size(active=True):
    """Scope enabling true int64 dtypes/indices (jax x64 mode).

    Arrays created inside the scope keep 64-bit dtypes; outside it JAX's
    default 32-bit truncation applies (a startup-time choice in the
    reference, a scope here).
    """
    from ._jax_compat import enable_x64
    with enable_x64(active):
        yield


def getenv(name):
    """Read an MXNET_* environment variable (reference util.py getenv
    over MXGetEnv); returns None when unset. Alias of base.get_env."""
    return get_env(name)


def setenv(name, value):
    """Set an MXNET_* environment variable for THIS process (reference
    util.py setenv over MXSetEnv). Config knobs read env at use time via
    mx.config, so changes take effect on the next read."""
    import os
    if value is None:
        os.environ.pop(name, None)
    else:
        os.environ[name] = str(value)


def set_np_shape(active=True):
    """1.x toggle for numpy shape semantics (reference util.py:set_np_shape).
    This build is numpy-semantics-only; disabling raises like MXNet 2.0
    does once npx.set_np has been called."""
    if not active:
        from .base import MXNetError
        raise MXNetError(
            "legacy (non-numpy) shape semantics are not supported; "
            "this framework is numpy-first (reference: deprecation in 2.0)")
    return True


def np_default_dtype():
    """Default float dtype for mx.np creation funcs (reference
    util.py np_default_dtype): float32 here (TPU-native), float64 when
    is_np_default_dtype() — kept False permanently; use explicit
    dtype= or util.int64_tensor_size for 64-bit work."""
    return "float32"


def set_np_default_dtype(is_np_default_dtype=False):  # noqa: ARG001
    """1.x toggle for float64 creation defaults (reference
    util.py set_np_default_dtype). This build is float32-default
    permanently (TPU-native); requesting float64 defaults raises, the
    matching False state is a no-op."""
    if is_np_default_dtype:
        from .base import MXNetError
        raise MXNetError(
            "float64 creation defaults are not supported on the TPU "
            "path; pass dtype='float64' explicitly where needed "
            "(runs under a scoped x64 mode)")
    return False


def np_ufunc_legal_option(key, value):
    """Whether a ufunc kwarg is supported (reference util.py:550 — the
    dispatch protocol uses it to reject unsupported options)."""
    import numpy as _onp
    if key == "where":
        return True
    if key == "casting":
        return value in ("no", "equiv", "safe", "same_kind", "unsafe")
    if key == "order":
        return isinstance(value, str)
    if key == "dtype":
        return value in (_onp.int8, _onp.uint8, _onp.int32, _onp.int64,
                         _onp.float16, _onp.float32, _onp.float64,
                         "int8", "uint8", "int32", "int64",
                         "float16", "float32", "float64")
    if key == "subok":
        return isinstance(value, bool)
    return False


def set_module(module):
    """Decorator overriding __module__ for doc rendering (reference
    util.py set_module)."""
    def decorator(obj):
        if module is not None:
            obj.__module__ = module
        return obj
    return decorator


def set_flush_denorms(value=True):  # noqa: ARG001 — parity signature
    """Reference util.py set_flush_denorms sets CPU FTZ via SSE; XLA/TPU
    flushes denormals by hardware design, so this is a documented no-op
    returning False (the reference also returns False on unsupported
    hardware)."""
    return False

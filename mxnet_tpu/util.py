"""mx.util — numpy-mode scopes and misc helpers.

Reference parity: python/mxnet/util.py (np_shape/np_array scopes, use_np
decorators, getenv wrappers). The new framework always has numpy semantics,
so the scopes are identity context managers kept for API compatibility.
"""
from __future__ import annotations

import contextlib
import functools

from .base import get_env  # noqa: F401


def is_np_shape():
    return True


def is_np_array():
    return True


def is_np_default_dtype():
    return False


@contextlib.contextmanager
def np_shape(active=True):
    yield active


@contextlib.contextmanager
def np_array(active=True):
    yield active


def use_np_shape(func):
    return func


def use_np_array(func):
    return func


def use_np(func):
    return func


def use_np_default_dtype(func):
    return func


def set_np(shape=True, array=True, dtype=False):
    pass


def reset_np():
    pass


def wrap_np_unary_func(func):
    return func


def wrap_np_binary_func(func):
    return func


def default_array(source_array, ctx=None, dtype=None):
    from .numpy import array
    return array(source_array, dtype=dtype, ctx=ctx)


def get_gpu_count():
    from .context import num_gpus
    return num_gpus()


def get_gpu_memory(gpu_dev_id=0):
    import jax
    try:
        stats = jax.devices()[gpu_dev_id].memory_stats()
        return stats.get("bytes_in_use", 0), stats.get("bytes_limit", 0)
    except Exception:
        return (0, 0)


def int64_enabled():
    """Whether 64-bit tensor sizes/dtypes are active.

    Analog of the reference's MXNET_USE_INT64_TENSOR_SIZE build flag
    (docs env_var.md; tests/nightly/test_large_array.py relies on it).
    Here it maps to JAX's x64 mode.
    """
    import jax
    return bool(jax.config.jax_enable_x64)


@contextlib.contextmanager
def int64_tensor_size(active=True):
    """Scope enabling true int64 dtypes/indices (jax x64 mode).

    Arrays created inside the scope keep 64-bit dtypes; outside it JAX's
    default 32-bit truncation applies (a startup-time choice in the
    reference, a scope here).
    """
    import jax
    with jax.enable_x64(active):
        yield

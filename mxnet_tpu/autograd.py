"""Autograd: imperative tape + backward.

Reference parity: python/mxnet/autograd.py (record/pause/train_mode/
predict_mode/backward/grad/mark_variables, custom Function) over
src/imperative/imperative.cc (RecordOp tape at :235, Backward at :438).

TPU-native design: instead of taping NNVM nodes and running an nnvm Gradient
pass, every recorded op captures a VJP closure at dispatch time via
``jax.vjp`` (the linearization runs on-device, async, alongside the forward).
``backward()`` walks the tape in reverse creation order — tape order is a
valid topological order — feeding output cotangents through each node's VJP
and accumulating into marked variables per their ``grad_req``. A hybridized
block's whole compiled forward is one tape node, exactly like the reference's
``_CachedOp`` tape entry (src/imperative/cached_op.cc:968).
"""
from __future__ import annotations

import contextlib
import threading

import jax
import jax.numpy as jnp

from ._jax_compat import enable_x64 as _enable_x64
from .base import MXNetError

# ---------------------------------------------------------------------------
# thread-local modes (reference: Imperative thread-local is_train_/is_recording_
# src/imperative/imperative.cc:33-41)
# ---------------------------------------------------------------------------

_state = threading.local()


def _st():
    if not hasattr(_state, "recording"):
        _state.recording = False
        _state.training = False
        _state.tape = []          # list of _TapeNode in creation order
        _state.counter = 0
    return _state


def is_recording():
    return _st().recording


def is_training():
    return _st().training


def set_recording(is_rec):
    st = _st()
    prev, st.recording = st.recording, bool(is_rec)
    return prev


def set_training(train_mode):
    st = _st()
    prev, st.training = st.training, bool(train_mode)
    return prev


class _RecordingStateScope:
    def __init__(self, is_record, train_mode):
        self._rec, self._train = is_record, train_mode
        self._prev = None

    def __enter__(self):
        st = _st()
        self._prev = (st.recording, st.training)
        if self._rec is not None:
            st.recording = self._rec
        if self._train is not None:
            st.training = self._train
        return self

    def __exit__(self, *exc):
        st = _st()
        st.recording, st.training = self._prev


def record(train_mode=True):
    """Scope in which ops are taped (reference: autograd.py:121)."""
    return _RecordingStateScope(True, train_mode)


def pause(train_mode=False):
    return _RecordingStateScope(False, train_mode)


def train_mode():
    return _RecordingStateScope(None, True)


def predict_mode():
    return _RecordingStateScope(None, False)


# ---------------------------------------------------------------------------
# tape structure
# ---------------------------------------------------------------------------

class _TapeNode:
    """One recorded op: VJP closure + links to input entries.

    parents[i] is the _Entry the i-th differentiable input carried (or None
    for constants); vjp_fn maps output cotangents -> input cotangents.
    """
    __slots__ = ("vjp_fn", "parents", "n_out", "out_shapes", "out_dtypes",
                 "seq", "name", "saved", "out_treedef", "fun", "raw_args",
                 "x64")

    def __init__(self, vjp_fn, parents, outputs, name, out_treedef=None,
                 fun=None, raw_args=None, x64=False):
        st = _st()
        self.vjp_fn = vjp_fn
        self.parents = parents
        self.n_out = len(outputs)
        self.out_shapes = [o.shape for o in outputs]
        self.out_dtypes = [o.dtype for o in outputs]
        self.seq = st.counter
        st.counter += 1
        self.name = name
        self.saved = None
        # pytree structure of the primal output (list/tuple/dict containers):
        # the VJP's cotangent argument must match it exactly
        self.out_treedef = out_treedef
        # pure function of the raw differentiable inputs + those inputs:
        # kept so create_graph=True can re-linearize (jax.vjp of the vjp)
        # for higher-order gradients (reference: Imperative::Backward with
        # create_graph, src/imperative/imperative.cc:438).
        self.fun = fun
        self.raw_args = raw_args
        self.x64 = x64
        st.tape.append(self)


class _Entry:
    """Autograd entry attached to an ndarray (reference: NDArray
    autograd_entry_, include/mxnet/ndarray.h:84). node None => leaf variable
    (holds weakly the variable ndarray for grad writeback)."""
    __slots__ = ("node", "index", "variable")

    def __init__(self, node, index, variable=None):
        self.node = node
        self.index = index
        self.variable = variable


def mark_variables(variables, gradients, grad_reqs="write"):
    """Attach grad buffers; start of the tape (reference: autograd.py:356,
    Imperative::MarkVariables imperative.cc)."""
    if isinstance(grad_reqs, str):
        grad_reqs = [grad_reqs] * len(variables)
    for var, grad, req in zip(variables, gradients, grad_reqs):
        var._mark_variable(grad, req)


def _record_op(vjp_fn, array_inputs, outputs, name, out_treedef=None,
               fun=None, raw_args=None, x64=False):
    """Called by the dispatcher for every op executed under record()."""
    parents = [getattr(a, "_entry", None) for a in array_inputs]
    node = _TapeNode(vjp_fn, parents, outputs, name, out_treedef,
                     fun=fun, raw_args=raw_args, x64=x64)
    for i, o in enumerate(outputs):
        o._entry = _Entry(node, i)
    return node


# ---------------------------------------------------------------------------
# backward
# ---------------------------------------------------------------------------

def backward(heads, head_grads=None, retain_graph=False, train_mode=True):
    """Compute gradients of heads w.r.t. every marked variable on the tape.

    Reference: autograd.py:245 -> Imperative::Backward (imperative.cc:438).
    """
    from .numpy.multiarray import ndarray as _nd  # late import (cycle)
    if isinstance(heads, _nd):
        heads = [heads]
    if head_grads is None:
        head_grads = [None] * len(heads)
    elif isinstance(head_grads, _nd):
        head_grads = [head_grads]

    _run_backward(heads, head_grads, retain_graph, accumulate_to_vars=True)


def grad(heads, variables, head_grads=None, retain_graph=None,
         create_graph=False, train_mode=True):
    """Return grads of heads wrt variables without touching their .grad
    (reference: autograd.py:303). ``create_graph`` (higher order) is supported
    by re-recording the VJP computation onto the tape."""
    from .numpy.multiarray import ndarray as _nd
    single = isinstance(variables, _nd)
    if single:
        variables = [variables]
    if isinstance(heads, _nd):
        heads = [heads]
    if head_grads is None:
        head_grads = [None] * len(heads)
    elif isinstance(head_grads, _nd):
        head_grads = [head_grads]
    if retain_graph is None:
        retain_graph = create_graph

    if create_graph:
        # the backward pass itself is recorded: every VJP application and
        # cotangent accumulation becomes a taped op, so the returned grads
        # are differentiable (reference: imperative.cc:438 create_graph)
        with _RecordingStateScope(True, train_mode):
            grads = _run_backward(heads, head_grads, retain_graph,
                                  accumulate_to_vars=False, wrt=variables,
                                  create_graph=True)
    else:
        grads = _run_backward(heads, head_grads, retain_graph,
                              accumulate_to_vars=False, wrt=variables,
                              create_graph=False)
    return grads[0] if single else grads


def _run_backward(heads, head_grads, retain_graph, accumulate_to_vars,
                  wrt=None, create_graph=False):
    from .numpy.multiarray import ndarray as _nd, _wrap
    st = _st()

    # seed cotangents keyed by id(entry)
    cot = {}
    roots = []
    for h, hg in zip(heads, head_grads):
        entry = getattr(h, "_entry", None)
        if entry is None:
            raise MXNetError(
                "cannot differentiate a head that is not the output of a "
                "recorded computation (did you forget autograd.record()?)")
        seed = (jnp.ones(h.shape, h.dtype) if hg is None
                else (hg._data if isinstance(hg, _nd) else jnp.asarray(hg)))
        key = (_outkey(entry.node, entry.index) if entry.node is not None
               else id(entry))
        cot[key] = cot[key] + seed if key in cot else seed
        roots.append(entry)

    # collect reachable nodes
    reachable = {}
    stack = [e.node for e in roots if e.node is not None]
    while stack:
        node = stack.pop()
        if node is None or node.seq in reachable:
            continue
        reachable[node.seq] = node
        for p in node.parents:
            if p is not None and p.node is not None:
                stack.append(p.node)

    # entry-indexed cotangent store; process nodes in reverse creation order
    var_grads = {}  # id(entry of leaf) -> (variable, grad)
    for seq in sorted(reachable, reverse=True):
        node = reachable[seq]
        # gather output cotangents for this node
        outs = []
        has_any = False
        for i in range(node.n_out):
            # entries of outputs are unique per (node, i): we key by node+index
            key = _outkey(node, i)
            g = cot.pop(key, None)
            if g is None:
                g = _zero_cot(node.out_shapes[i], node.out_dtypes[i])
            else:
                has_any = True
            outs.append(g)
        if not has_any:
            continue
        in_cots = _apply_vjp(node, outs, create_graph)
        for p, ig in zip(node.parents, in_cots):
            if p is None or ig is None:
                continue
            if _is_float0(ig):
                continue
            if p.node is None:
                # leaf variable
                key = id(p)
                if key in var_grads:
                    var_grads[key] = (p, _accum(var_grads[key][1], ig))
                else:
                    var_grads[key] = (p, ig)
            else:
                key = _outkey(p.node, p.index)
                cot[key] = _accum(cot[key], ig) if key in cot else ig
        if not retain_graph:
            node.vjp_fn = None   # free residuals
            node.fun = None      # and the re-linearization closure
            node.raw_args = None  # and the pinned primal buffers

    # head that is itself a leaf variable
    for e, h in zip(roots, heads):
        if e.node is None:
            key = id(e)
            seedkey = id(e)
            g = cot.get(seedkey)
            if g is not None:
                if key in var_grads:
                    var_grads[key] = (e, var_grads[key][1] + g)
                else:
                    var_grads[key] = (e, g)

    if accumulate_to_vars:
        for entry, g in var_grads.values():
            var = entry.variable() if callable(entry.variable) else entry.variable
            if var is None:
                continue
            var._write_grad(g)
        if not retain_graph:
            st.tape.clear()
        return None

    # grad() path: return requested grads
    results = []
    for v in wrt:
        e = getattr(v, "_entry", None)
        leaf_e = e if (e is not None and e.node is None) else None
        g = None
        if leaf_e is not None and id(leaf_e) in var_grads:
            g = var_grads[id(leaf_e)][1]
        elif e is not None and e.node is not None:
            g = cot.get(_outkey(e.node, e.index))
        if g is None:
            g = jnp.zeros(v.shape, _float_or(v.dtype))
        # create_graph cotangents are already recorded ndarrays
        results.append(g if isinstance(g, _nd) else _wrap(g))
    if not retain_graph:
        st.tape.clear()
    return results


def _apply_vjp(node, out_cots, create_graph):
    if node.vjp_fn is None:
        raise MXNetError(
            "backward through a freed graph: pass retain_graph=True to keep "
            "intermediate state for a second backward")
    if create_graph:
        return _apply_vjp_create_graph(node, out_cots)
    if node.out_treedef is not None:
        cots = jax.tree_util.tree_unflatten(node.out_treedef, list(out_cots))
    else:
        cots = tuple(out_cots) if node.n_out > 1 else out_cots[0]
    return node.vjp_fn(cots)


def _apply_vjp_create_graph(node, out_cots):
    """Apply a node's VJP as a *recorded* op so grad-of-grad works.

    Reference semantics: ``autograd.grad(..., create_graph=True)`` records the
    backward pass itself so its outputs are differentiable
    (python/mxnet/autograd.py:303 over src/imperative/imperative.cc:438).

    TPU-native mechanism: the node kept its pure forward ``fun`` and raw
    primal inputs, so the whole input-cotangent computation
    ``h(primals, cots) = vjp(fun at primals)(cots)`` is itself a pure jax
    function.  We run ``jax.vjp(h, ...)`` — giving exact second-order
    linearization wrt BOTH the primals (residual dependence) and the incoming
    cotangents (chain dependence) — and tape one node whose parents are the
    original op's parents plus the cotangents' entries.  Because the new node
    also stores ``h`` as its own ``fun``, third and higher orders compose.

    ``out_cots`` entries are ndarrays (recorded or leaf), raw jax arrays
    (seed cotangents), or float0 numpy arrays (non-inexact outputs, treated
    as non-differentiable constants).
    """
    from .numpy import multiarray as M
    if node.fun is None:
        raise MXNetError(
            f"create_graph=True is not supported through op '{node.name}': "
            "it was recorded without a re-differentiable pure function "
            "(custom autograd.Function or a vjp-only fallback). Use "
            "first-order grad(), or express the op with built-in operators.")
    raw_cots = [c._data if isinstance(c, M.ndarray) else c for c in out_cots]
    # differentiable cotangent slots (float0 => constant)
    diff_idx = [i for i, c in enumerate(raw_cots) if not _is_float0(c)]
    n_primal = len(node.raw_args)
    fun, out_treedef, n_out = node.fun, node.out_treedef, node.n_out

    def h(*flat):
        primals = flat[:n_primal]
        dcots = flat[n_primal:]
        cs = list(raw_cots)
        for j, i in enumerate(diff_idx):
            cs[i] = dcots[j]
        if out_treedef is not None:
            cstruct = jax.tree_util.tree_unflatten(out_treedef, cs)
        else:
            cstruct = tuple(cs) if n_out > 1 else cs[0]
        _, vjp = jax.vjp(fun, *primals)
        return tuple(vjp(cstruct))

    h_args = tuple(node.raw_args) + tuple(raw_cots[i] for i in diff_idx)
    x64_scope = _enable_x64(True) if node.x64 else contextlib.nullcontext()
    with x64_scope:
        in_cots, h_vjp = jax.vjp(h, *h_args)
    if node.x64:
        _inner = h_vjp

        def h_vjp(ct, _i=_inner):
            with _enable_x64(True):
                return _i(ct)

    out_nds = [M._wrap(r) for r in in_cots]
    cot_parents = [
        out_cots[i]._entry if isinstance(out_cots[i], M.ndarray) else None
        for i in diff_idx]
    _record_op(h_vjp, [], out_nds, "grad_" + node.name,
               out_treedef=jax.tree_util.tree_structure(tuple(in_cots)),
               fun=h, raw_args=h_args, x64=node.x64)
    # _record_op derived parents from an empty input list; install the true
    # parent entries (primal entries + cotangent entries) directly — the
    # primal wrappers may be gone but their _Entry objects live on the node.
    new_node = out_nds[0]._entry.node if out_nds else None
    if new_node is not None:
        new_node.parents = list(node.parents) + cot_parents
    return out_nds


def _outkey(node, i):
    return (node.seq << 8) | i if i < 256 else (node.seq, i)


def _float_or(dt):
    return dt if jnp.issubdtype(dt, jnp.floating) or jnp.issubdtype(dt, jnp.complexfloating) else jnp.float32


def _zero_cot(shape, dt):
    """Zero cotangent matching jax.vjp's expectation: float0 for non-inexact
    outputs (e.g. argmax), same-dtype zeros otherwise."""
    import numpy as onp
    if jnp.issubdtype(dt, jnp.inexact):
        return jnp.zeros(shape, dt)
    return onp.zeros(shape, jax.dtypes.float0)


def _is_float0(x):
    return getattr(x, "dtype", None) == jax.dtypes.float0


def _accum(a, b):
    """Cotangent accumulation; row-sparse cotangents (embedding
    sparse_grad) merge through sparse.add instead of jnp +."""
    from .ndarray import sparse as _sp
    if isinstance(a, _sp.BaseSparseNDArray) or \
            isinstance(b, _sp.BaseSparseNDArray):
        out = _sp.add(a, b)
        return out if isinstance(out, _sp.BaseSparseNDArray) else \
            (out._data if hasattr(out, "_data") else out)
    from .numpy.multiarray import ndarray as _nd, _wrap
    if isinstance(a, _nd) != isinstance(b, _nd):
        # create_graph mode mixes recorded ndarray cotangents with raw seed
        # arrays; wrap the raw side so + dispatches through _invoke (taped)
        # instead of jax coercing the ndarray wrapper to a constant
        a = a if isinstance(a, _nd) else _wrap(jnp.asarray(a))
        b = b if isinstance(b, _nd) else _wrap(jnp.asarray(b))
    return a + b


def get_symbol(x):
    """Reference autograd.get_symbol returns the traced graph; here the tape
    has no symbolic form — use HybridBlock/hybridize for graph extraction."""
    raise MXNetError("get_symbol: use hybridize()/jax tracing for graphs")


# ---------------------------------------------------------------------------
# custom Function (reference: autograd.py:369 class Function)
# ---------------------------------------------------------------------------

class Function:
    """User-defined differentiable function with explicit backward.

    Subclass and implement forward(self, *inputs) and backward(self, *ograds),
    both taking/returning ndarrays. Reference: python/mxnet/autograd.py:369.
    """

    def __init__(self):
        self._saved = None

    def save_for_backward(self, *args):
        self._saved = args

    @property
    def saved_tensors(self):
        return self._saved

    def __call__(self, *inputs):
        from .numpy.multiarray import ndarray as _nd
        with pause():
            outputs = self.forward(*inputs)
        single = isinstance(outputs, _nd)
        outs = [outputs] if single else list(outputs)
        if is_recording():
            fn = self

            def vjp_fn(out_cots):
                cots = out_cots if isinstance(out_cots, tuple) else (out_cots,)
                from .numpy.multiarray import _wrap
                with pause():
                    igrads = fn.backward(*[_wrap(c) for c in cots])
                if isinstance(igrads, _nd):
                    igrads = (igrads,)
                return tuple(g._data if isinstance(g, _nd) else g for g in igrads)

            arr_inputs = [a for a in inputs if isinstance(a, _nd)]
            _record_op(vjp_fn, arr_inputs, outs, type(self).__name__)
        return outputs if single else tuple(outs)

    def forward(self, *inputs):
        raise NotImplementedError

    def backward(self, *output_grads):
        raise NotImplementedError

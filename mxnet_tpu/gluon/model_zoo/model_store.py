"""Pretrained weight cache (reference: gluon/model_zoo/model_store.py —
get_model_file with sha1-checked download into MXNET_HOME/models, purge).

TPU-native build ships no weights and this environment has no egress, so
the cache-first mechanism is the deliverable: weights found under the
cache root load immediately; otherwise a download from
``MXNET_GLUON_REPO`` is attempted and a clear, actionable error names the
exact path to provision offline.
"""
from __future__ import annotations

import hashlib
import os
import zipfile

from ...base import MXNetError

_REPO_ENV = "MXNET_GLUON_REPO"
_DEFAULT_REPO = "https://apache-mxnet.s3-accelerate.dualstack.amazonaws.com/"

# name -> sha1 of the reference release archives (model_store.py
# _model_sha1); entries are added as archives are provisioned locally.
_model_sha1 = {}


def data_dir():
    from ... import config
    return os.path.expanduser(config.get("home"))


def _default_root():
    return os.path.join(data_dir(), "models")


def short_hash(name):
    if name in _model_sha1:
        return _model_sha1[name][:8]
    return None


def _check_sha1(filename, sha1_hash):
    sha1 = hashlib.sha1()
    with open(filename, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            sha1.update(chunk)
    return sha1.hexdigest() == sha1_hash


def get_model_file(name, root=None):
    """Path to the cached params file for ``name``, downloading if a repo is
    reachable (reference: model_store.py get_model_file)."""
    root = os.path.expanduser(root or _default_root())
    candidates = [os.path.join(root, f"{name}.params"),
                  os.path.join(root, f"{name}.params.npz")]
    h = short_hash(name)
    if h:
        candidates.insert(0, os.path.join(root, f"{name}-{h}.params"))
    for c in candidates:
        if os.path.exists(c):
            if h and c.endswith(f"{h}.params") and \
                    not _check_sha1(c, _model_sha1[name]):
                raise MXNetError(f"checksum mismatch for {c}; delete and "
                                 "re-provision")
            return c

    os.makedirs(root, exist_ok=True)
    repo = os.environ.get(_REPO_ENV, _DEFAULT_REPO)
    url = f"{repo.rstrip('/')}/gluon/models/{name}.zip"
    zip_path = os.path.join(root, f"{name}.zip")
    try:
        from urllib.request import urlretrieve
        urlretrieve(url, zip_path)
        with zipfile.ZipFile(zip_path) as zf:
            zf.extractall(root)
        os.remove(zip_path)
    except Exception as e:
        raise MXNetError(
            f"pretrained weights for {name!r} are not cached and could not "
            f"be downloaded from {url} ({type(e).__name__}). Provision the "
            f"file offline as {candidates[-1]} (Block.save_parameters "
            "format) or set MXNET_GLUON_REPO to a reachable mirror."
        ) from e
    for c in candidates:
        if os.path.exists(c):
            return c
    raise MXNetError(f"downloaded archive for {name!r} did not contain a "
                     "params file")


def load_pretrained(net, name, root=None, ctx=None):
    """Load cached weights into ``net`` (helper used by model factories)."""
    net.load_parameters(get_model_file(name, root), ctx=ctx)
    return net


def purge(root=None):
    """Remove cached model files (reference: model_store.purge)."""
    root = os.path.expanduser(root or _default_root())
    if not os.path.isdir(root):
        return
    for f in os.listdir(root):
        if f.endswith((".params", ".params.npz", ".zip")):
            os.remove(os.path.join(root, f))

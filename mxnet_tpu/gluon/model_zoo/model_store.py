"""Pretrained weight cache (reference: gluon/model_zoo/model_store.py —
get_model_file with sha1-checked download into MXNET_HOME/models, purge).

TPU-native build ships no weights and this environment has no egress, so
the cache-first mechanism is the deliverable: weights found under the
cache root load immediately; otherwise a download from
``MXNET_GLUON_REPO`` is attempted and a clear, actionable error names the
exact path to provision offline.
"""
from __future__ import annotations

import hashlib
import os
import zipfile

from ...base import MXNetError

_REPO_ENV = "MXNET_GLUON_REPO"
_DEFAULT_REPO = "https://apache-mxnet.s3-accelerate.dualstack.amazonaws.com/"

# name -> sha1 of the published release archives — the reference's table
# (gluon/model_zoo/model_store.py:30-64) verbatim: these identify the
# official Apache MXNet artifacts so provisioned files are integrity-checked
# against the same checksums 1.x users have.
_model_sha1 = {name: checksum for checksum, name in [
    ("44335d1f0046b328243b32a26a4fbd62d9057b45", "alexnet"),
    ("f27dbf2dbd5ce9a80b102d89c7483342cd33cb31", "densenet121"),
    ("b6c8a95717e3e761bd88d145f4d0a214aaa515dc", "densenet161"),
    ("2603f878403c6aa5a71a124c4a3307143d6820e9", "densenet169"),
    ("1cdbc116bc3a1b65832b18cf53e1cb8e7da017eb", "densenet201"),
    ("ed47ec45a937b656fcc94dabde85495bbef5ba1f", "inceptionv3"),
    ("9f83e440996887baf91a6aff1cccc1c903a64274", "mobilenet0.25"),
    ("8e9d539cc66aa5efa71c4b6af983b936ab8701c3", "mobilenet0.5"),
    ("529b2c7f4934e6cb851155b22c96c9ab0a7c4dc2", "mobilenet0.75"),
    ("6b8c5106c730e8750bcd82ceb75220a3351157cd", "mobilenet1.0"),
    ("36da4ff1867abccd32b29592d79fc753bca5a215", "mobilenetv2_1.0"),
    ("e2be7b72a79fe4a750d1dd415afedf01c3ea818d", "mobilenetv2_0.75"),
    ("aabd26cd335379fcb72ae6c8fac45a70eab11785", "mobilenetv2_0.5"),
    ("ae8f9392789b04822cbb1d98c27283fc5f8aa0a7", "mobilenetv2_0.25"),
    ("a0666292f0a30ff61f857b0b66efc0228eb6a54b", "resnet18_v1"),
    ("48216ba99a8b1005d75c0f3a0c422301a0473233", "resnet34_v1"),
    ("0aee57f96768c0a2d5b23a6ec91eb08dfb0a45ce", "resnet50_v1"),
    ("d988c13d6159779e907140a638c56f229634cb02", "resnet101_v1"),
    ("671c637a14387ab9e2654eafd0d493d86b1c8579", "resnet152_v1"),
    ("a81db45fd7b7a2d12ab97cd88ef0a5ac48b8f657", "resnet18_v2"),
    ("9d6b80bbc35169de6b6edecffdd6047c56fdd322", "resnet34_v2"),
    ("ecdde35339c1aadbec4f547857078e734a76fb49", "resnet50_v2"),
    ("18e93e4f48947e002547f50eabbcc9c83e516aa6", "resnet101_v2"),
    ("f2695542de38cf7e71ed58f02893d82bb409415e", "resnet152_v2"),
    ("264ba4970a0cc87a4f15c96e25246a1307caf523", "squeezenet1.0"),
    ("33ba0f93753c83d86e1eb397f38a667eaf2e9376", "squeezenet1.1"),
    ("dd221b160977f36a53f464cb54648d227c707a05", "vgg11"),
    ("ee79a8098a91fbe05b7a973fed2017a6117723a8", "vgg11_bn"),
    ("6bc5de58a05a5e2e7f493e2d75a580d83efde38c", "vgg13"),
    ("7d97a06c3c7a1aecc88b6e7385c2b373a249e95e", "vgg13_bn"),
    ("e660d4569ccb679ec68f1fd3cce07a387252a90a", "vgg16"),
    ("7f01cf050d357127a73826045c245041b0df7363", "vgg16_bn"),
    ("ad2f660d101905472b83590b59708b71ea22b2e5", "vgg19"),
    ("f360b758e856f1074a85abd5fd873ed1d98297c3", "vgg19_bn"),
]}


def data_dir():
    from ... import config
    return os.path.expanduser(config.get("home"))


def _default_root():
    return os.path.join(data_dir(), "models")


def short_hash(name):
    if name in _model_sha1:
        return _model_sha1[name][:8]
    return None


def _check_sha1(filename, sha1_hash):
    sha1 = hashlib.sha1()
    with open(filename, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            sha1.update(chunk)
    return sha1.hexdigest() == sha1_hash


def get_model_file(name, root=None):
    """Path to the cached params file for ``name``, downloading if a repo is
    reachable (reference: model_store.py get_model_file)."""
    root = os.path.expanduser(root or _default_root())
    candidates = [os.path.join(root, f"{name}.params"),
                  os.path.join(root, f"{name}.params.npz")]
    h = short_hash(name)
    if h:
        candidates.insert(0, os.path.join(root, f"{name}-{h}.params"))
    for c in candidates:
        if os.path.exists(c):
            if h and c.endswith(f"{h}.params") and \
                    not _check_sha1(c, _model_sha1[name]):
                raise MXNetError(f"checksum mismatch for {c}; delete and "
                                 "re-provision")
            return c

    os.makedirs(root, exist_ok=True)
    repo = os.environ.get(_REPO_ENV, _DEFAULT_REPO)
    url = f"{repo.rstrip('/')}/gluon/models/{name}.zip"
    zip_path = os.path.join(root, f"{name}.zip")
    try:
        from urllib.request import urlretrieve
        urlretrieve(url, zip_path)
        with zipfile.ZipFile(zip_path) as zf:
            zf.extractall(root)
        os.remove(zip_path)
    except Exception as e:
        raise MXNetError(
            f"pretrained weights for {name!r} are not cached and could not "
            f"be downloaded from {url} ({type(e).__name__}). Provision the "
            f"file offline as {candidates[-1]} (Block.save_parameters "
            "format) or set MXNET_GLUON_REPO to a reachable mirror."
        ) from e
    for c in candidates:
        if os.path.exists(c):
            return c
    raise MXNetError(f"downloaded archive for {name!r} did not contain a "
                     "params file")


def load_pretrained(net, name, root=None, ctx=None):
    """Load cached weights into ``net`` (helper used by model factories)."""
    net.load_parameters(get_model_file(name, root), ctx=ctx)
    return net


def purge(root=None):
    """Remove cached model files (reference: model_store.purge)."""
    root = os.path.expanduser(root or _default_root())
    if not os.path.isdir(root):
        return
    for f in os.listdir(root):
        if f.endswith((".params", ".params.npz", ".zip")):
            os.remove(os.path.join(root, f))

"""gluon.model_zoo.vision (reference: model_zoo/vision/__init__.py get_model
registry)."""
from __future__ import annotations

from ....base import MXNetError
from .resnet import *  # noqa: F401,F403
from .alexnet import *  # noqa: F401,F403
from .vgg import *  # noqa: F401,F403
from .mobilenet import *  # noqa: F401,F403
from .squeezenet import *  # noqa: F401,F403
from .densenet import *  # noqa: F401,F403
from .inception import *  # noqa: F401,F403
import importlib as _importlib

_models = {}
for _mod_name in ("resnet", "alexnet", "vgg", "mobilenet", "squeezenet",
                  "densenet", "inception"):
    _mod = _importlib.import_module(f".{_mod_name}", __name__)
    for _name in _mod.__all__:
        _obj = getattr(_mod, _name)
        if callable(_obj) and _name[0].islower():
            _models[_name.replace("_", ".", 0)] = _obj
            _models[_name] = _obj


def get_model(name, **kwargs):
    """Reference: model_zoo/vision get_model(name). ``pretrained=True``
    loads cached weights through model_store (get_model_file)."""
    name = name.lower().replace(".", "_")
    if name not in _models:
        raise MXNetError(
            f"unknown model {name!r}; available: {sorted(set(_models))}")
    fn = _models[name]
    if name.startswith(("resnet", "vgg", "alexnet", "inception")):
        return fn(**kwargs)  # factory handles pretrained natively
    pretrained = kwargs.pop("pretrained", False)
    root = kwargs.pop("root", None)
    ctx = kwargs.pop("ctx", None)
    net = fn(**kwargs)
    if pretrained:
        from ..model_store import load_pretrained
        load_pretrained(net, name, root, ctx)
    return net

"""SqueezeNet 1.0/1.1 (reference: model_zoo/vision/squeezenet.py)."""
from __future__ import annotations

from .... import numpy as _np
from ...block import HybridBlock
from ... import nn

__all__ = ["SqueezeNet", "squeezenet1_0", "squeezenet1_1"]


class _Fire(HybridBlock):
    def __init__(self, squeeze_channels, expand1x1_channels,
                 expand3x3_channels):
        super().__init__()
        self.squeeze = nn.Conv2D(squeeze_channels, 1, activation="relu")
        self.expand1x1 = nn.Conv2D(expand1x1_channels, 1, activation="relu")
        self.expand3x3 = nn.Conv2D(expand3x3_channels, 3, padding=1,
                                   activation="relu")

    def forward(self, x):
        x = self.squeeze(x)
        return _np.concatenate([self.expand1x1(x), self.expand3x3(x)], axis=1)


class SqueezeNet(HybridBlock):
    def __init__(self, version="1.0", classes=1000):
        super().__init__()
        self.features = nn.HybridSequential()
        if version == "1.0":
            self.features.add(nn.Conv2D(96, 7, 2, activation="relu"))
            self.features.add(nn.MaxPool2D(3, 2, ceil_mode=True))
            self.features.add(_Fire(16, 64, 64))
            self.features.add(_Fire(16, 64, 64))
            self.features.add(_Fire(32, 128, 128))
            self.features.add(nn.MaxPool2D(3, 2, ceil_mode=True))
            self.features.add(_Fire(32, 128, 128))
            self.features.add(_Fire(48, 192, 192))
            self.features.add(_Fire(48, 192, 192))
            self.features.add(_Fire(64, 256, 256))
            self.features.add(nn.MaxPool2D(3, 2, ceil_mode=True))
            self.features.add(_Fire(64, 256, 256))
        else:
            self.features.add(nn.Conv2D(64, 3, 2, activation="relu"))
            self.features.add(nn.MaxPool2D(3, 2, ceil_mode=True))
            self.features.add(_Fire(16, 64, 64))
            self.features.add(_Fire(16, 64, 64))
            self.features.add(nn.MaxPool2D(3, 2, ceil_mode=True))
            self.features.add(_Fire(32, 128, 128))
            self.features.add(_Fire(32, 128, 128))
            self.features.add(nn.MaxPool2D(3, 2, ceil_mode=True))
            self.features.add(_Fire(48, 192, 192))
            self.features.add(_Fire(48, 192, 192))
            self.features.add(_Fire(64, 256, 256))
            self.features.add(_Fire(64, 256, 256))
        self.features.add(nn.Dropout(0.5))
        self.output = nn.HybridSequential()
        self.output.add(nn.Conv2D(classes, 1, activation="relu"))
        self.output.add(nn.GlobalAvgPool2D())
        self.output.add(nn.Flatten())

    def forward(self, x):
        return self.output(self.features(x))


def squeezenet1_0(**kwargs):
    return SqueezeNet("1.0", **kwargs)


def squeezenet1_1(**kwargs):
    return SqueezeNet("1.1", **kwargs)

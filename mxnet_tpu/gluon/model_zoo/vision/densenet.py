"""DenseNet 121/161/169/201 (reference: model_zoo/vision/densenet.py)."""
from __future__ import annotations

from .... import numpy as _np
from ...block import HybridBlock
from ... import nn

__all__ = ["DenseNet", "densenet121", "densenet161", "densenet169",
           "densenet201"]


class _DenseLayer(HybridBlock):
    def __init__(self, growth_rate, bn_size, dropout):
        super().__init__()
        self.body = nn.HybridSequential()
        self.body.add(nn.BatchNorm())
        self.body.add(nn.Activation("relu"))
        self.body.add(nn.Conv2D(bn_size * growth_rate, 1, use_bias=False))
        self.body.add(nn.BatchNorm())
        self.body.add(nn.Activation("relu"))
        self.body.add(nn.Conv2D(growth_rate, 3, padding=1, use_bias=False))
        if dropout:
            self.body.add(nn.Dropout(dropout))

    def forward(self, x):
        out = self.body(x)
        return _np.concatenate([x, out], axis=1)


def _make_dense_block(num_layers, bn_size, growth_rate, dropout):
    out = nn.HybridSequential()
    for _ in range(num_layers):
        out.add(_DenseLayer(growth_rate, bn_size, dropout))
    return out


def _make_transition(num_output_features):
    out = nn.HybridSequential()
    out.add(nn.BatchNorm())
    out.add(nn.Activation("relu"))
    out.add(nn.Conv2D(num_output_features, 1, use_bias=False))
    out.add(nn.AvgPool2D(2, 2))
    return out


densenet_spec = {
    121: (64, 32, [6, 12, 24, 16]),
    161: (96, 48, [6, 12, 36, 24]),
    169: (64, 32, [6, 12, 32, 32]),
    201: (64, 32, [6, 12, 48, 32]),
}


class DenseNet(HybridBlock):
    def __init__(self, num_init_features, growth_rate, block_config,
                 bn_size=4, dropout=0, classes=1000):
        super().__init__()
        self.features = nn.HybridSequential()
        self.features.add(nn.Conv2D(num_init_features, 7, 2, 3,
                                    use_bias=False))
        self.features.add(nn.BatchNorm())
        self.features.add(nn.Activation("relu"))
        self.features.add(nn.MaxPool2D(3, 2, 1))
        num_features = num_init_features
        for i, num_layers in enumerate(block_config):
            self.features.add(_make_dense_block(num_layers, bn_size,
                                                growth_rate, dropout))
            num_features += num_layers * growth_rate
            if i != len(block_config) - 1:
                num_features //= 2
                self.features.add(_make_transition(num_features))
        self.features.add(nn.BatchNorm())
        self.features.add(nn.Activation("relu"))
        self.features.add(nn.GlobalAvgPool2D())
        self.features.add(nn.Flatten())
        self.output = nn.Dense(classes)

    def forward(self, x):
        return self.output(self.features(x))


def densenet121(**kwargs):
    return DenseNet(*densenet_spec[121], **kwargs)


def densenet161(**kwargs):
    return DenseNet(*densenet_spec[161], **kwargs)


def densenet169(**kwargs):
    return DenseNet(*densenet_spec[169], **kwargs)


def densenet201(**kwargs):
    return DenseNet(*densenet_spec[201], **kwargs)

"""Inception v3 (reference: model_zoo/vision/inception.py)."""
from __future__ import annotations

from .... import numpy as _np
from ...block import HybridBlock
from ... import nn

__all__ = ["Inception3", "inception_v3"]


def _make_basic_conv(channels, **kwargs):
    out = nn.HybridSequential()
    out.add(nn.Conv2D(channels, use_bias=False, **kwargs))
    out.add(nn.BatchNorm(epsilon=0.001))
    out.add(nn.Activation("relu"))
    return out


def _make_branch(use_pool, *conv_settings):
    out = nn.HybridSequential()
    if use_pool == "avg":
        out.add(nn.AvgPool2D(3, 1, 1))
    elif use_pool == "max":
        out.add(nn.MaxPool2D(3, 2))
    for setting in conv_settings:
        channels, kernel, stride, padding = setting
        kw = {"kernel_size": kernel}
        if stride is not None:
            kw["strides"] = stride
        if padding is not None:
            kw["padding"] = padding
        out.add(_make_basic_conv(channels, **kw))
    return out


class _Concurrent(HybridBlock):
    """Parallel branches concatenated on channel axis (reference:
    gluon/contrib/nn HybridConcurrent)."""

    def __init__(self, axis=1):
        super().__init__()
        self._axis = axis
        self._branches = []

    def add(self, block):
        self._branches.append(block)
        self.register_child(block, str(len(self._branches) - 1))

    def forward(self, x):
        return _np.concatenate([b(x) for b in self._children.values()],
                               axis=self._axis)


def _make_A(pool_features):
    out = _Concurrent()
    out.add(_make_branch(None, (64, 1, None, None)))
    out.add(_make_branch(None, (48, 1, None, None), (64, 5, None, 2)))
    out.add(_make_branch(None, (64, 1, None, None), (96, 3, None, 1),
                         (96, 3, None, 1)))
    out.add(_make_branch("avg", (pool_features, 1, None, None)))
    return out


def _make_B():
    out = _Concurrent()
    out.add(_make_branch(None, (384, 3, 2, None)))
    out.add(_make_branch(None, (64, 1, None, None), (96, 3, None, 1),
                         (96, 3, 2, None)))
    out.add(_make_branch("max"))
    return out


def _make_C(channels_7x7):
    out = _Concurrent()
    out.add(_make_branch(None, (192, 1, None, None)))
    out.add(_make_branch(None, (channels_7x7, 1, None, None),
                         (channels_7x7, (1, 7), None, (0, 3)),
                         (192, (7, 1), None, (3, 0))))
    out.add(_make_branch(None, (channels_7x7, 1, None, None),
                         (channels_7x7, (7, 1), None, (3, 0)),
                         (channels_7x7, (1, 7), None, (0, 3)),
                         (channels_7x7, (7, 1), None, (3, 0)),
                         (192, (1, 7), None, (0, 3))))
    out.add(_make_branch("avg", (192, 1, None, None)))
    return out


def _make_D():
    out = _Concurrent()
    out.add(_make_branch(None, (192, 1, None, None), (320, 3, 2, None)))
    out.add(_make_branch(None, (192, 1, None, None),
                         (192, (1, 7), None, (0, 3)),
                         (192, (7, 1), None, (3, 0)), (192, 3, 2, None)))
    out.add(_make_branch("max"))
    return out


class _InceptionE(HybridBlock):
    def __init__(self):
        super().__init__()
        self.branch1 = _make_branch(None, (320, 1, None, None))
        self.branch2_stem = _make_branch(None, (384, 1, None, None))
        self.branch2_a = _make_branch(None, (384, (1, 3), None, (0, 1)))
        self.branch2_b = _make_branch(None, (384, (3, 1), None, (1, 0)))
        self.branch3_stem = _make_branch(None, (448, 1, None, None),
                                         (384, 3, None, 1))
        self.branch3_a = _make_branch(None, (384, (1, 3), None, (0, 1)))
        self.branch3_b = _make_branch(None, (384, (3, 1), None, (1, 0)))
        self.branch4 = _make_branch("avg", (192, 1, None, None))

    def forward(self, x):
        b2 = self.branch2_stem(x)
        b3 = self.branch3_stem(x)
        return _np.concatenate([
            self.branch1(x), self.branch2_a(b2), self.branch2_b(b2),
            self.branch3_a(b3), self.branch3_b(b3), self.branch4(x)], axis=1)


class Inception3(HybridBlock):
    def __init__(self, classes=1000):
        super().__init__()
        self.features = nn.HybridSequential()
        self.features.add(_make_basic_conv(32, kernel_size=3, strides=2))
        self.features.add(_make_basic_conv(32, kernel_size=3))
        self.features.add(_make_basic_conv(64, kernel_size=3, padding=1))
        self.features.add(nn.MaxPool2D(3, 2))
        self.features.add(_make_basic_conv(80, kernel_size=1))
        self.features.add(_make_basic_conv(192, kernel_size=3))
        self.features.add(nn.MaxPool2D(3, 2))
        self.features.add(_make_A(32))
        self.features.add(_make_A(64))
        self.features.add(_make_A(64))
        self.features.add(_make_B())
        self.features.add(_make_C(128))
        self.features.add(_make_C(160))
        self.features.add(_make_C(160))
        self.features.add(_make_C(192))
        self.features.add(_make_D())
        self.features.add(_InceptionE())
        self.features.add(_InceptionE())
        self.features.add(nn.AvgPool2D(8))
        self.features.add(nn.Dropout(0.5))
        self.output = nn.Dense(classes)

    def forward(self, x):
        return self.output(self.features(x))


def inception_v3(pretrained=False, ctx=None, root=None, **kwargs):
    net = Inception3(**kwargs)
    if pretrained:
        from ..model_store import load_pretrained
        load_pretrained(net, "inceptionv3", root, ctx)
    return net

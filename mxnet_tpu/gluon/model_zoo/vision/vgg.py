"""VGG 11/13/16/19 (+BN variants).

Reference parity: python/mxnet/gluon/model_zoo/vision/vgg.py.
"""
from __future__ import annotations

from ....base import MXNetError
from ...block import HybridBlock
from ... import nn

__all__ = ["VGG", "vgg11", "vgg13", "vgg16", "vgg19", "vgg11_bn", "vgg13_bn",
           "vgg16_bn", "vgg19_bn", "get_vgg"]

vgg_spec = {
    11: ([1, 1, 2, 2, 2], [64, 128, 256, 512, 512]),
    13: ([2, 2, 2, 2, 2], [64, 128, 256, 512, 512]),
    16: ([2, 2, 3, 3, 3], [64, 128, 256, 512, 512]),
    19: ([2, 2, 4, 4, 4], [64, 128, 256, 512, 512]),
}


class VGG(HybridBlock):
    def __init__(self, layers, filters, classes=1000, batch_norm=False):
        super().__init__()
        assert len(layers) == len(filters)
        self.features = nn.HybridSequential()
        for i, num in enumerate(layers):
            for _ in range(num):
                self.features.add(nn.Conv2D(filters[i], 3, padding=1))
                if batch_norm:
                    self.features.add(nn.BatchNorm())
                self.features.add(nn.Activation("relu"))
            self.features.add(nn.MaxPool2D(2, 2))
        self.features.add(nn.Flatten())
        self.features.add(nn.Dense(4096, activation="relu"))
        self.features.add(nn.Dropout(0.5))
        self.features.add(nn.Dense(4096, activation="relu"))
        self.features.add(nn.Dropout(0.5))
        self.output = nn.Dense(classes)

    def forward(self, x):
        return self.output(self.features(x))


def get_vgg(num_layers, pretrained=False, ctx=None, root=None, **kwargs):
    if num_layers not in vgg_spec:
        raise MXNetError(f"invalid vgg depth {num_layers}")
    layers, filters = vgg_spec[num_layers]
    net = VGG(layers, filters, **kwargs)
    if pretrained:
        from ..model_store import load_pretrained
        load_pretrained(net, f"vgg{num_layers}", root, ctx)
    return net


def vgg11(**kwargs):
    return get_vgg(11, **kwargs)


def vgg13(**kwargs):
    return get_vgg(13, **kwargs)


def vgg16(**kwargs):
    return get_vgg(16, **kwargs)


def vgg19(**kwargs):
    return get_vgg(19, **kwargs)


def vgg11_bn(**kwargs):
    return get_vgg(11, batch_norm=True, **kwargs)


def vgg13_bn(**kwargs):
    return get_vgg(13, batch_norm=True, **kwargs)


def vgg16_bn(**kwargs):
    return get_vgg(16, batch_norm=True, **kwargs)


def vgg19_bn(**kwargs):
    return get_vgg(19, batch_norm=True, **kwargs)

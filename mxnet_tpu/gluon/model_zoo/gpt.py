"""Decoder-only LLM family (GPT-2 layout).

Reference parity: the reference's transformer story is the fused attention
ops (src/operator/contrib/transformer.cc:675-828) consumed by gluon-nlp
models (model/gpt.py: GPT2Model/gpt2_117m/gpt2_345m). This is that family
TPU-native: pre-norm causal blocks whose attention routes through the
Pallas flash kernel at long sequence (ops/attention.py — no (s, s) score
materialization in HBM), learned positions, tied LM head; shard with
mxnet_tpu.parallel (tp specs on the projections, sp ring for very long
context).
"""
from __future__ import annotations

from ... import numpy as np
from ..block import HybridBlock
from ..nn import Dropout, Embedding, LayerNorm
from ..nn.transformer import TransformerEncoder

__all__ = ["GPTModel", "GPTForCausalLM", "gpt2_124m", "gpt2_355m"]


class GPTModel(HybridBlock):
    """Causal pre-norm transformer decoder stack (GPT-2 layout).

    forward(inputs (b, s) int) -> hidden states (b, s, units)
    """

    def __init__(self, vocab_size=50257, units=768, hidden_size=3072,
                 num_layers=12, num_heads=12, max_length=1024,
                 dropout=0.1, embed_dropout=0.1):
        super().__init__()
        self._units = units
        self._max_length = max_length
        self.word_embed = Embedding(vocab_size, units)
        self.position_embed = Embedding(max_length, units)
        self.embed_dropout = Dropout(embed_dropout) if embed_dropout else None
        self.decoder = TransformerEncoder(
            num_layers, units, hidden_size, num_heads, dropout=dropout,
            attention_dropout=dropout, activation="gelu", pre_norm=True,
            causal=True)
        self.final_ln = LayerNorm(epsilon=1e-5)

    def forward(self, inputs):
        b, s = inputs.shape
        pos = np.arange(s, dtype="int32").reshape(1, s)
        x = self.word_embed(inputs) + self.position_embed(pos)
        if self.embed_dropout is not None:
            x = self.embed_dropout(x)
        return self.final_ln(self.decoder(x))

    # -- KV-cache serving surface (mx.serve) ---------------------------

    @property
    def max_length(self):
        return self._max_length

    def init_cache(self, max_slots, max_seq=None, dtype="float32"):
        """Fixed-footprint decode cache: per layer one
        (max_slots, max_seq, heads, head_dim) K and V pair."""
        max_seq = self._max_length if max_seq is None else max_seq
        if max_seq > self._max_length:
            raise ValueError(
                f"max_seq {max_seq} exceeds the learned position table "
                f"({self._max_length})")
        return self.decoder.init_cache(max_slots, max_seq, dtype)

    def prefill(self, inputs, caches, slot):
        """Run one prompt (1, L) through the stack, writing K/V into
        cache slot ``slot``. Returns (hidden (1, L, units), caches)."""
        b, s = inputs.shape
        pos = np.arange(s, dtype="int32").reshape(1, s)
        x = self.word_embed(inputs) + self.position_embed(pos)
        x, caches = self.decoder.prefill(x, caches, slot)
        return self.final_ln(x), caches

    def decode_step(self, tokens, caches, positions):
        """Advance every slot one token: tokens (slots, 1) int32,
        positions (slots,) int32 cache rows. Returns
        (hidden (slots, 1, units), caches)."""
        x = self.word_embed(tokens) \
            + self.position_embed(positions.reshape(-1, 1))
        x, caches = self.decoder.decode_step(x, caches, positions)
        return self.final_ln(x), caches

    def prefill_suffix(self, inputs, caches, slot, start):
        """Prefix-cache suffix prefill: ``inputs`` (1, Ls) is the
        prompt suffix; rows [0, start) of cache slot ``slot`` already
        hold a copied prefix, so positions offset by ``start`` and the
        suffix attends the cached rows.  Returns
        (hidden (1, Ls, units), caches)."""
        b, s = inputs.shape
        pos = np.arange(s, dtype="int32").reshape(1, s) + start
        pos = np.minimum(pos, self._max_length - 1)
        x = self.word_embed(inputs) + self.position_embed(pos)
        x, caches = self.decoder.prefill_suffix(x, caches, slot, start)
        return self.final_ln(x), caches

    def decode_multi(self, tokens, caches, positions):
        """Advance every slot t tokens at once (the speculative-decode
        verify): tokens (slots, t) int32, slot i's token j landing at
        cache row positions[i] + j.  Returns
        (hidden (slots, t, units), caches)."""
        n, t = tokens.shape
        pos = np.arange(t, dtype="int32").reshape(1, t) \
            + positions.reshape(-1, 1)
        pos = np.minimum(pos, self._max_length - 1)
        x = self.word_embed(tokens) + self.position_embed(pos)
        x, caches = self.decoder.decode_multi(x, caches, positions)
        return self.final_ln(x), caches

    def copy_cache_rows(self, caches, src_slot, src_row, dst_slot,
                        dst_row, rows):
        """Copy ``rows`` KV rows between slots in every layer's cache —
        the prefix-cache block-copy surface."""
        return self.decoder.copy_cache_rows(
            caches, src_slot, src_row, dst_slot, dst_row, rows)


class GPTForCausalLM(HybridBlock):
    """Next-token LM head over GPTModel, weight-tied to the embedding.

    forward -> logits (b, s, vocab)
    """

    def __init__(self, backbone=None, **kwargs):
        super().__init__()
        self.backbone = backbone if backbone is not None \
            else GPTModel(**kwargs)

    def forward(self, inputs):
        h = self.backbone(inputs)
        w = self.backbone.word_embed.weight.data()
        return np.dot(h, w.T)

    # -- KV-cache serving surface (mx.serve) ---------------------------

    @property
    def max_length(self):
        return self.backbone.max_length

    def init_cache(self, max_slots, max_seq=None, dtype="float32"):
        return self.backbone.init_cache(max_slots, max_seq, dtype)

    def prefill(self, inputs, caches, slot):
        h, caches = self.backbone.prefill(inputs, caches, slot)
        w = self.backbone.word_embed.weight.data()
        return np.dot(h, w.T), caches

    def decode_step(self, tokens, caches, positions):
        h, caches = self.backbone.decode_step(tokens, caches, positions)
        w = self.backbone.word_embed.weight.data()
        return np.dot(h[:, 0], w.T), caches

    def prefill_suffix(self, inputs, caches, slot, start):
        h, caches = self.backbone.prefill_suffix(inputs, caches, slot,
                                                 start)
        w = self.backbone.word_embed.weight.data()
        return np.dot(h, w.T), caches

    def decode_multi(self, tokens, caches, positions):
        h, caches = self.backbone.decode_multi(tokens, caches, positions)
        w = self.backbone.word_embed.weight.data()
        return np.dot(h, w.T), caches

    def copy_cache_rows(self, caches, src_slot, src_row, dst_slot,
                        dst_row, rows):
        return self.backbone.copy_cache_rows(
            caches, src_slot, src_row, dst_slot, dst_row, rows)


def gpt2_124m(vocab_size=50257, **kwargs):
    """GPT-2 small: 12 layers, 768 units, 12 heads (117M-class)."""
    return GPTModel(vocab_size=vocab_size, units=768, hidden_size=3072,
                    num_layers=12, num_heads=12, **kwargs)


def gpt2_355m(vocab_size=50257, **kwargs):
    """GPT-2 medium: 24 layers, 1024 units, 16 heads (345M-class)."""
    return GPTModel(vocab_size=vocab_size, units=1024, hidden_size=4096,
                    num_layers=24, num_heads=16, **kwargs)

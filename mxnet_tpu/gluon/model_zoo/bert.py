"""BERT model family.

Reference parity: BASELINE config #3 "BERT-base pretraining (gluon-nlp)".
The reference repo ships the attention ops (src/operator/contrib/
transformer.cc) while the model lived in gluon-nlp (model/bert.py:
BERTEncoder/BERTModel, bert_12_768_12 / bert_24_1024_16). This is that
model, TPU-native: attention via the Pallas flash kernel, everything else
XLA-fused; shard with mxnet_tpu.parallel for tp/sp.
"""
from __future__ import annotations

from ... import numpy as np
from ..block import HybridBlock
from ..nn import Dense, Dropout, Embedding, LayerNorm
from ..nn.transformer import TransformerEncoder, valid_length_mask

__all__ = ["BERTModel", "BERTForPretraining", "bert_12_768_12",
           "bert_24_1024_16", "bert_base", "bert_large"]


class BERTModel(HybridBlock):
    """BERT encoder with pooler (gluon-nlp BERTModel layout).

    forward(inputs, token_types, valid_length) ->
        (sequence_output (b, s, units), pooled_output (b, units))
    """

    def __init__(self, vocab_size=30522, units=768, hidden_size=3072,
                 num_layers=12, num_heads=12, max_length=512,
                 token_type_vocab_size=2, dropout=0.1, embed_dropout=0.1):
        super().__init__()
        self._units = units
        self.word_embed = Embedding(vocab_size, units)
        self.token_type_embed = Embedding(token_type_vocab_size, units)
        self.position_embed = Embedding(max_length, units)
        self.embed_ln = LayerNorm(epsilon=1e-12)
        self.embed_dropout = Dropout(embed_dropout) if embed_dropout else None
        self.encoder = TransformerEncoder(
            num_layers, units, hidden_size, num_heads, dropout=dropout,
            attention_dropout=dropout, activation="gelu", pre_norm=False)
        self.pooler = Dense(units, activation="tanh", flatten=False)

    def forward(self, inputs, token_types=None, valid_length=None):
        b, s = inputs.shape
        if token_types is None:
            token_types = np.zeros((b, s), dtype="int32")
        pos = np.arange(s, dtype="int32").reshape(1, s)
        x = (self.word_embed(inputs) + self.token_type_embed(token_types)
             + self.position_embed(pos))
        x = self.embed_ln(x)
        if self.embed_dropout is not None:
            x = self.embed_dropout(x)
        mask = None
        if valid_length is not None:
            mask = valid_length_mask(valid_length, s)
        seq = self.encoder(x, mask=mask)
        pooled = self.pooler(seq[:, 0, :])
        return seq, pooled


class BERTForPretraining(HybridBlock):
    """MLM + NSP heads on BERTModel (gluon-nlp BERTForPretraining).

    forward -> (mlm_scores (b, s, vocab), nsp_scores (b, 2))
    """

    def __init__(self, backbone=None, **kwargs):
        super().__init__()
        self.backbone = backbone if backbone is not None \
            else BERTModel(**kwargs)
        units = self.backbone._units
        self.mlm_dense = Dense(units, activation=None, flatten=False)
        self.mlm_ln = LayerNorm(epsilon=1e-12)
        # decoder projection: weight tied to word_embed in forward, with its
        # own per-vocab bias (reference: gluon-nlp tied Dense(vocab) + bias)
        from ..parameter import Parameter
        vocab = self.backbone.word_embed._input_dim
        self.mlm_bias = Parameter("mlm_bias", shape=(vocab,), init="zeros")
        self.nsp_classifier = Dense(2, flatten=False)

    def forward(self, inputs, token_types=None, valid_length=None):
        from ... import numpy_extension as npx
        seq, pooled = self.backbone(inputs, token_types, valid_length)
        h = npx.leaky_relu(self.mlm_dense(seq), act_type="gelu")
        h = self.mlm_ln(h)
        # tied decoder: logits = h @ word_embed.weight.T + bias
        if self.mlm_bias._data is None:
            self.mlm_bias._finish_deferred_init()
        w = self.backbone.word_embed.weight.data()
        mlm_scores = np.dot(h, w.T) + self.mlm_bias.data()
        nsp_scores = self.nsp_classifier(pooled)
        return mlm_scores, nsp_scores


def bert_12_768_12(vocab_size=30522, **kwargs):
    """BERT-base (gluon-nlp model name)."""
    return BERTModel(vocab_size=vocab_size, units=768, hidden_size=3072,
                     num_layers=12, num_heads=12, **kwargs)


def bert_24_1024_16(vocab_size=30522, **kwargs):
    """BERT-large (gluon-nlp model name)."""
    return BERTModel(vocab_size=vocab_size, units=1024, hidden_size=4096,
                     num_layers=24, num_heads=16, **kwargs)


bert_base = bert_12_768_12
bert_large = bert_24_1024_16

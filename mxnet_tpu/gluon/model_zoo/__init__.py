"""gluon.model_zoo (reference: python/mxnet/gluon/model_zoo/)."""
from . import vision  # noqa: F401
from . import bert  # noqa: F401
from . import gpt  # noqa: F401
from . import model_store  # noqa: F401

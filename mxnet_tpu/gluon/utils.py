"""gluon.utils (reference: python/mxnet/gluon/utils.py).

split_and_load / split_data are the reference's multi-device batch scatter
(utils.py:87). TPU-native: with a device mesh you normally shard one global
array instead; these helpers are kept for KVStore-style per-device code and
return sharded views when given a mesh.
"""
from __future__ import annotations

import numpy as onp

from .. import numpy as _np
from ..base import MXNetError
from ..numpy.multiarray import ndarray


def split_data(data, num_slice, batch_axis=0, even_split=True):
    """Reference: utils.py split_data."""
    size = data.shape[batch_axis]
    if even_split and size % num_slice != 0:
        raise MXNetError(
            f"batch size {size} not divisible by {num_slice} slices")
    step = size // num_slice
    slices = []
    for i in range(num_slice):
        lo = i * step
        hi = (i + 1) * step if i < num_slice - 1 else size
        idx = [slice(None)] * data.ndim
        idx[batch_axis] = slice(lo, hi)
        slices.append(data[tuple(idx)])
    return slices


def split_and_load(data, ctx_list, batch_axis=0, even_split=True):
    """Reference: utils.py:87 — scatter a batch across contexts."""
    if not isinstance(data, ndarray):
        data = _np.array(data)
    if len(ctx_list) == 1:
        return [data.as_in_ctx(ctx_list[0])]
    slices = split_data(data, len(ctx_list), batch_axis, even_split)
    return [s.as_in_ctx(ctx) for s, ctx in zip(slices, ctx_list)]


def clip_global_norm(arrays, max_norm, check_isfinite=True):
    """Reference: utils.py clip_global_norm (used with Trainer)."""
    assert len(arrays) > 0
    total = 0.0
    for a in arrays:
        total = total + float((a._data.astype("float32") ** 2).sum())
    total_norm = total ** 0.5
    if check_isfinite and not onp.isfinite(total_norm):
        import warnings
        warnings.warn("nan or inf in clip_global_norm")
        return total_norm
    scale = max_norm / (total_norm + 1e-8)
    if scale < 1.0:
        for a in arrays:
            a._rebind(a._data * scale)
    return total_norm


def check_sha1(filename, sha1_hash):
    import hashlib
    sha1 = hashlib.sha1()
    with open(filename, "rb") as f:
        while True:
            data = f.read(1048576)
            if not data:
                break
            sha1.update(data)
    return sha1.hexdigest() == sha1_hash


def download(url, path=None, overwrite=False, sha1_hash=None, retries=5,
             verify_ssl=True):
    """Reference: utils.py download. This environment has no egress; only
    file:// URLs and existing local files are supported."""
    import shutil
    fname = path or url.split("/")[-1]
    if url.startswith("file://"):
        src = url[len("file://"):]
        if src != fname:
            shutil.copyfile(src, fname)
        return fname
    import os
    if os.path.exists(fname) and not overwrite:
        return fname
    raise MXNetError(f"download of {url} unavailable (no network egress); "
                     "place the file at the target path manually")


def shape_is_known(shape):
    if shape is None:
        return False
    return all(s > 0 for s in shape)

"""gluon.Trainer.

Reference parity: python/mxnet/gluon/trainer.py:31-520 (optimizer + kvstore
orchestration: _allreduce_grads pushes/pullpulls per-param with priority
-param_index so first-needed params reduce first; _update applies fused
optimizer ops per device).

TPU-native design: gradients are jax Arrays; allreduce is the KVStore's
device/mesh psum; compute/comm overlap comes from PJRT async dispatch — the
python thread never blocks, matching the reference's engine overlap.
"""
from __future__ import annotations

import math
import time

import jax
import jax.numpy as jnp

from .. import optimizer as opt
from .. import pipeline as _pipeline
from .. import telemetry as _telemetry
from .. import trace as _trace
from ..base import MXNetError
from ..kvstore import create as create_kvstore, KVStoreBase
from .parameter import Parameter


class _FusedUpdate:
    """All parameter updates as ONE jitted multi-tensor XLA program.

    Reference analog: aggregate_num batching into multi_sgd_update /
    multi_mp_sgd_update / multi_lamb (src/operator/optimizer_op.cc:352-1130)
    — one kernel for many tensors instead of one dispatch per parameter.
    Here lr/wd/t arrive as traced arrays, so lr schedules and Adam's
    per-step bias correction do NOT retrace; the program recompiles only
    when shapes or static hyperparameters (momentum/betas/clip) change.
    """

    def __init__(self, optimizer):
        self.opt = optimizer
        self._jit = None
        self._static = None

    def applicable(self):
        o = self.opt
        return (getattr(o, "_FUSED_FAMILY", None) in ("sgd", "adam")
                and not o.multi_precision)

    def _build(self, family, static):
        rule = type(self.opt)._rule

        if family == "sgd":
            momentum, rescale_ignored, clip = static

            def run(ws, gs, ss, lrs, wds, ts, rescale):
                outs = [rule(w, g, s[0] if s else None, lrs[j], wds[j],
                             momentum, rescale, clip)
                        for j, (w, g, s) in enumerate(zip(ws, gs, ss))]
                return ([o[0] for o in outs],
                        [(o[1],) if o[1] is not None else () for o in outs])
        else:  # adam family
            beta1, beta2, eps, clip = static

            def run(ws, gs, ss, lrs, wds, ts, rescale):
                outs = [rule(w, g, s[0], s[1], lrs[j], wds[j], ts[j],
                             beta1, beta2, eps, rescale, clip)
                        for j, (w, g, s) in enumerate(zip(ws, gs, ss))]
                return ([o[0] for o in outs],
                        [(o[1], o[2]) for o in outs])

        return jax.jit(run, donate_argnums=(0, 2))

    def __call__(self, work, states):
        """work: list of (index, Parameter); states: Updater.states dict."""
        o = self.opt
        family = o._FUSED_FAMILY
        clip = o.clip_gradient or -1.0
        static = ((o.momentum, None, clip) if family == "sgd"
                  else (o.beta1, o.beta2, o.epsilon, clip))
        if self._jit is None or self._static != (family, static):
            self._jit = self._build(family, static)
            self._static = (family, static)

        lrs, wds, ts = [], [], []
        ws, gs, ss, state_nds = [], [], [], []
        for i, p in work:
            o._update_count(i)
            lrs.append(o._get_lr(i))
            wds.append(o._get_wd(i))
            ts.append(float(max(o._index_update_count[i], 1)))
            ws.append(p.data()._data)
            gs.append(p.grad()._data)
            s = states[i]
            nds = (() if s is None
                   else tuple(s) if isinstance(s, tuple) else (s,))
            state_nds.append(nds)
            ss.append(tuple(nd._data for nd in nds))

        new_ws, new_ss = self._jit(
            ws, gs, ss, jnp.asarray(lrs, jnp.float32),
            jnp.asarray(wds, jnp.float32), jnp.asarray(ts, jnp.float32),
            jnp.asarray(o.rescale_grad, jnp.float32))

        for (i, p), nw, nss, nds in zip(work, new_ws, new_ss, state_nds):
            p.data()._rebind(nw.astype(p.data().dtype))
            for nd, raw in zip(nds, nss):
                nd._rebind(raw)


class Trainer:
    def __init__(self, params, optimizer, optimizer_params=None,
                 kvstore="device", compression_params=None,
                 update_on_kvstore=None):
        if isinstance(params, dict):
            self._param_names = list(params.keys())
            params = list(params.values())
        else:
            self._param_names = [p.name for p in params]
        if not params:
            raise MXNetError("no parameters to optimize")
        self._params = []
        self._param2idx = {}
        for i, p in enumerate(params):
            if not isinstance(p, Parameter):
                raise MXNetError(f"expected Parameter, got {type(p)}")
            self._param2idx[id(p)] = i
            self._params.append(p)
        self._compression_params = compression_params
        optimizer_params = optimizer_params or {}
        self._init_optimizer(optimizer, optimizer_params)
        self._scale = self._optimizer.rescale_grad
        self._kvstore_type = kvstore
        self._update_on_kvstore = update_on_kvstore
        self._kvstore = None
        self._kv_initialized = False
        self._params_to_init = []
        self._contains_sparse_grad = False
        self._fused_update = None
        self._finite_check = None
        self._grad_norm_fn = None
        self._norm_window = None  # mx.pipeline.DeferredWindow, built lazily
        #: steps skipped by the non-finite grad guard (see step())
        self.nonfinite_steps = 0

    def _init_optimizer(self, optimizer, optimizer_params):
        param_dict = {i: p for i, p in enumerate(self._params)}
        if isinstance(optimizer, opt.Optimizer):
            if optimizer_params:
                raise MXNetError(
                    "optimizer_params must be None when optimizer is an "
                    "Optimizer instance")
            self._optimizer = optimizer
            self._optimizer.param_dict = param_dict
        else:
            self._optimizer = opt.create(optimizer, param_dict=param_dict,
                                         **optimizer_params)
        self._updaters = [opt.get_updater(self._optimizer)]

    def _init_kvstore(self):
        if self._kvstore_type is None or self._kvstore_type == "":
            self._kvstore = None
            self._update_on_kvstore = False
        else:
            kv = (self._kvstore_type
                  if isinstance(self._kvstore_type, KVStoreBase)
                  else create_kvstore(self._kvstore_type))
            self._kvstore = kv
            if self._compression_params and hasattr(kv, "set_gradient_compression"):
                kv.set_gradient_compression(self._compression_params)
            has_sparse = any(getattr(p, "_grad_stype", "default") ==
                             "row_sparse" for p in self._params)
            if self._update_on_kvstore is None:
                # env/config override first (reference: MXNET_UPDATE_ON_KVSTORE,
                # trainer.py:36); default False — fused local update is faster.
                # Row-sparse gradients force optimizer-on-store, like the
                # reference (trainer.py: contains_sparse check).
                from .. import config
                forced = config.get("update_on_kvstore")
                self._update_on_kvstore = (bool(forced)
                                           if forced is not None
                                           else has_sparse)
            elif has_sparse and not self._update_on_kvstore:
                raise MXNetError(
                    "update_on_kvstore=False is not supported with "
                    "row_sparse gradients (reference trainer.py raises too)")
            if self._update_on_kvstore:
                kv.set_optimizer(self._optimizer)
            for i, p in enumerate(self._params):
                if p._data is not None:
                    self._kvstore.init(i, p.data())
        self._kv_initialized = True

    @property
    def learning_rate(self):
        return self._optimizer.learning_rate

    @property
    def optimizer(self):
        return self._optimizer

    def set_learning_rate(self, lr):
        self._optimizer.set_learning_rate(lr)

    def allreduce_grads(self):
        """Reduce gradients across devices/workers (reference:
        trainer.py:363 _allreduce_grads)."""
        if not self._kv_initialized:
            self._init_kvstore()
        self._allreduce_grads()

    def _allreduce_grads(self):
        if self._kvstore is None:
            return
        for i, p in enumerate(self._params):
            if p.grad_req != "null" and p._data is not None:
                grads = p.list_grad()
                if self._update_on_kvstore:
                    # optimizer runs in the store; weights pulled in _update
                    self._kvstore.push(i, grads, priority=-i)
                else:
                    self._kvstore.pushpull(i, grads, out=grads, priority=-i)

    # -- non-finite grad guard (resilience layer; see docs/FAULT_TOLERANCE) --
    def _guard_active(self):
        """The guard runs when opted in (mx.config trainer.skip_nonfinite)
        or automatically once an AMP loss scaler is attached (reference:
        amp's skip-on-overflow contract, python/mxnet/amp/loss_scaler.py)."""
        from .. import config
        return (getattr(self, "_amp_loss_scaler", None) is not None
                or bool(config.get("trainer.skip_nonfinite")))

    def _grads_finite(self):
        """One fused XLA reduction over every gradient -> scalar bool."""
        raws = [p.grad()._data for p in self._params
                if p.grad_req != "null" and p._data is not None]
        if not raws:
            return True
        if self._finite_check is None:
            self._finite_check = jax.jit(
                lambda gs: jnp.all(jnp.asarray(
                    [jnp.isfinite(g).all() for g in gs])))
        if _pipeline._guard_depth:
            _pipeline.note_host_sync("trainer.finite_check")
        return bool(self._finite_check(raws))

    def _grad_norm_device(self):
        """Global gradient L2 norm as ONE fused XLA reduction, returned as
        an UNFETCHED device scalar so callers choose when (if ever) to pay
        the host sync."""
        raws = [p.grad()._data for p in self._params
                if p.grad_req != "null" and p._data is not None]
        if not raws:
            return None
        if self._grad_norm_fn is None:
            self._grad_norm_fn = jax.jit(
                lambda gs: jnp.sqrt(sum(
                    jnp.sum(jnp.square(g.astype(jnp.float32)))
                    for g in gs)))
        return self._grad_norm_fn(raws)

    def _grad_norm(self):
        """Global gradient L2 norm as a host float (telemetry: the
        per-step health signal operators watch for divergence).  This is
        a host sync — the step loop uses ``_note_grad_norm`` instead,
        which defers the fetch through a bounded window."""
        dev = self._grad_norm_device()
        if dev is None:
            return 0.0
        if _pipeline._guard_depth:
            _pipeline.note_host_sync("trainer.grad_norm")
        return float(dev)

    @staticmethod
    def _observe_grad_norm(norm):
        if math.isfinite(norm):
            _telemetry.observe("trainer.grad_norm", norm)

    def _note_grad_norm(self):
        """Record the step's grad norm without syncing: the device scalar
        is pushed into a bounded DeferredWindow and fetched only when the
        window overflows or ``drain_telemetry()`` runs (epoch boundaries,
        snapshots)."""
        dev = self._grad_norm_device()
        if dev is None:
            return
        if self._norm_window is None:
            self._norm_window = _pipeline.DeferredWindow()
        self._norm_window.push(dev, self._observe_grad_norm)

    def drain_telemetry(self):
        """Fetch every deferred grad-norm into the telemetry histogram and
        refresh the per-device ``memory.*`` gauges.  Call at epoch
        boundaries / before ``mx.telemetry.snapshot()`` for up-to-the-step
        numbers; the estimator's TelemetryHandler does."""
        if _trace._active:
            with _trace.span("train.drain", category="train",
                             pending=(len(self._norm_window)
                                      if self._norm_window is not None
                                      else 0)):
                if self._norm_window is not None:
                    self._norm_window.drain()
                if _telemetry._active:
                    _telemetry.record_memory()
            return
        if self._norm_window is not None:
            self._norm_window.drain()
        if _telemetry._active:
            _telemetry.record_memory()

    def _skip_step(self):
        """Count and absorb a non-finite step: weights untouched, the AMP
        scale backs off, accumulated ('add') grads are cleared so the
        poison cannot leak into the next step."""
        from .. import fault
        self.nonfinite_steps += 1
        fault.record("trainer.nonfinite_skip")
        if _telemetry._active:
            _telemetry.inc("trainer.nonfinite_total")
        from .. import blackbox as _blackbox
        if _blackbox._active:
            # non-finite escalation is a terminal-class anomaly: freeze
            # the evidence window while the poisoned state is still live
            _blackbox.dump(trigger="nonfinite",
                           reason=f"non-finite gradients skipped "
                                  f"(count={self.nonfinite_steps})")
        scaler = getattr(self, "_amp_loss_scaler", None)
        if scaler is not None:
            scaler.update_scale(True)
        for p in self._params:
            if p.grad_req == "add" and p._data is not None:
                p.zero_grad()

    def step(self, batch_size, ignore_stale_grad=False):
        """Reference: trainer.py:334.

        With the non-finite guard active, a step whose gradients contain
        inf/NaN is skipped (counted in ``nonfinite_steps`` and
        ``mx.fault.stats()``) instead of poisoning the weights.  The check
        runs *after* the cross-worker reduce where possible so every rank
        takes the same decision; with ``update_on_kvstore`` the optimizer
        runs inside the push, so there the local gradient is checked
        before pushing."""
        if not _telemetry._active:
            return self._step_impl(batch_size, ignore_stale_grad)
        # metrics wrapper: wall time, step count, and the global grad norm
        # (observed pre-update so a skipped step still reports what blew up)
        t0 = time.perf_counter()
        self._note_grad_norm()
        try:
            return self._step_impl(batch_size, ignore_stale_grad)
        finally:
            _telemetry.inc("trainer.steps_total")
            _telemetry.observe("trainer.step_seconds",
                               time.perf_counter() - t0)

    def _step_impl(self, batch_size, ignore_stale_grad=False):
        if not self._kv_initialized:
            self._init_kvstore()
        self._optimizer.rescale_grad = self._scale / batch_size
        guard = self._guard_active()
        if guard and self._update_on_kvstore and not self._grads_finite():
            self._skip_step()
            return
        self._allreduce_grads()
        if guard and not self._update_on_kvstore and not self._grads_finite():
            self._skip_step()
            return
        if guard and getattr(self, "_amp_loss_scaler", None) is not None:
            self._amp_loss_scaler.update_scale(False)
        self._update(ignore_stale_grad)

    def update(self, batch_size, ignore_stale_grad=False):
        """Apply optimizer without allreduce (assumes grads already reduced;
        reference: trainer.py update)."""
        if not self._kv_initialized:
            self._init_kvstore()
        self._optimizer.rescale_grad = self._scale / batch_size
        self._update(ignore_stale_grad)

    def _update(self, ignore_stale_grad=False):
        updater = self._updaters[0]
        if self._update_on_kvstore:
            for i, p in enumerate(self._params):
                if p.grad_req != "null" and p._data is not None:
                    # weights were updated inside the store: pull them back
                    self._kvstore.pull(i, out=p.data(), priority=-i)
            return
        from ..ndarray.sparse import BaseSparseNDArray
        work, sparse_work = [], []
        for i, p in enumerate(self._params):
            if p.grad_req == "null" or p._data is None:
                continue
            if isinstance(p.grad(), BaseSparseNDArray):
                sparse_work.append((i, p))  # row-wise lazy/densified update
            else:
                work.append((i, p))
        for i, p in sparse_work:
            updater(i, p.grad(), p.data())
        if not work:
            return
        if self._fused_update is None:
            fu = _FusedUpdate(self._optimizer)
            self._fused_update = fu if fu.applicable() else False
        if self._fused_update:
            for i, p in work:
                if i not in updater.states:
                    updater.states[i] = \
                        self._optimizer.create_state_multi_precision(i, p.data())
            self._fused_update(work, updater.states)
        else:
            for i, p in work:
                updater(i, p.grad(), p.data())

    # -- elastic resume (docs/FAULT_TOLERANCE.md "Preemption & elastic
    # resume"): everything save_states misses — the AMP loss scale and its
    # backoff window, the non-finite skip counter — plus the optimizer/
    # updater states as bytes, so a TrainState bundle restores the trainer
    # to the exact step it was preempted at -------------------------------
    def state_dict(self):
        scaler = getattr(self, "_amp_loss_scaler", None)
        if not self._kv_initialized:
            self._init_kvstore()
        if self._update_on_kvstore and self._kvstore is not None:
            opt_blob = self._kvstore._updater.get_states(dump_optimizer=True)
        else:
            opt_blob = self._updaters[0].get_states(dump_optimizer=True)
        return {"optimizer": opt_blob,
                "nonfinite_steps": self.nonfinite_steps,
                "loss_scaler": None if scaler is None
                else scaler.state_dict()}

    def load_state_dict(self, state):
        self.nonfinite_steps = int(state.get("nonfinite_steps", 0))
        scaler_state = state.get("loss_scaler")
        if scaler_state is not None:
            if getattr(self, "_amp_loss_scaler", None) is None:
                from ..amp.loss_scaler import LossScaler
                self._amp_loss_scaler = LossScaler()
            self._amp_loss_scaler.load_state_dict(scaler_state)
        blob = state.get("optimizer")
        if blob is None:
            return
        if not self._kv_initialized:
            self._init_kvstore()
        if self._update_on_kvstore and self._kvstore is not None:
            self._kvstore._updater.set_states(blob)
            self._optimizer = (self._kvstore._updater.optimizer
                               or self._optimizer)
        else:
            self._updaters[0].set_states(blob)
            self._optimizer = self._updaters[0].optimizer
        self._optimizer.param_dict = {i: p
                                      for i, p in enumerate(self._params)}
        self._fused_update = None  # rebuilt against the restored optimizer

    def save_states(self, fname):
        """Reference: trainer.py:482.  Crash-atomic like
        Block.save_parameters (temp + fsync + os.replace)."""
        if not self._kv_initialized:
            self._init_kvstore()
        if self._update_on_kvstore:
            self._kvstore.save_optimizer_states(fname, dump_optimizer=True)
        else:
            from .. import serialization
            serialization.atomic_write_bytes(
                fname, self._updaters[0].get_states(dump_optimizer=True))

    def load_states(self, fname):
        """Reference: trainer.py:511.  Validates a ``.sha256`` sidecar
        when present (CheckpointHandler writes one)."""
        from .. import serialization
        serialization.verify_checksum(fname)
        if not self._kv_initialized:
            self._init_kvstore()
        if self._update_on_kvstore:
            self._kvstore.load_optimizer_states(fname)
            self._optimizer = self._kvstore._optimizer or self._optimizer
        else:
            with open(fname, "rb") as f:
                self._updaters[0].set_states(f.read())
            self._optimizer = self._updaters[0].optimizer
        self._optimizer.param_dict = {i: p for i, p in enumerate(self._params)}
        self._fused_update = None  # rebuilt against the (possibly new) optimizer

"""gluon.Trainer.

Reference parity: python/mxnet/gluon/trainer.py:31-520 (optimizer + kvstore
orchestration: _allreduce_grads pushes/pullpulls per-param with priority
-param_index so first-needed params reduce first; _update applies fused
optimizer ops per device).

TPU-native design: gradients are jax Arrays; allreduce is the KVStore's
device/mesh psum; compute/comm overlap comes from PJRT async dispatch — the
python thread never blocks, matching the reference's engine overlap.
"""
from __future__ import annotations

from .. import optimizer as opt
from ..base import MXNetError
from ..kvstore import create as create_kvstore, KVStoreBase
from .parameter import Parameter


class Trainer:
    def __init__(self, params, optimizer, optimizer_params=None,
                 kvstore="device", compression_params=None,
                 update_on_kvstore=None):
        if isinstance(params, dict):
            self._param_names = list(params.keys())
            params = list(params.values())
        else:
            self._param_names = [p.name for p in params]
        if not params:
            raise MXNetError("no parameters to optimize")
        self._params = []
        self._param2idx = {}
        for i, p in enumerate(params):
            if not isinstance(p, Parameter):
                raise MXNetError(f"expected Parameter, got {type(p)}")
            self._param2idx[id(p)] = i
            self._params.append(p)
        self._compression_params = compression_params
        optimizer_params = optimizer_params or {}
        self._init_optimizer(optimizer, optimizer_params)
        self._scale = self._optimizer.rescale_grad
        self._kvstore_type = kvstore
        self._update_on_kvstore = update_on_kvstore
        self._kvstore = None
        self._kv_initialized = False
        self._params_to_init = []
        self._contains_sparse_grad = False

    def _init_optimizer(self, optimizer, optimizer_params):
        param_dict = {i: p for i, p in enumerate(self._params)}
        if isinstance(optimizer, opt.Optimizer):
            if optimizer_params:
                raise MXNetError(
                    "optimizer_params must be None when optimizer is an "
                    "Optimizer instance")
            self._optimizer = optimizer
            self._optimizer.param_dict = param_dict
        else:
            self._optimizer = opt.create(optimizer, param_dict=param_dict,
                                         **optimizer_params)
        self._updaters = [opt.get_updater(self._optimizer)]

    def _init_kvstore(self):
        if self._kvstore_type is None or self._kvstore_type == "":
            self._kvstore = None
            self._update_on_kvstore = False
        else:
            kv = (self._kvstore_type
                  if isinstance(self._kvstore_type, KVStoreBase)
                  else create_kvstore(self._kvstore_type))
            self._kvstore = kv
            if self._compression_params and hasattr(kv, "set_gradient_compression"):
                kv.set_gradient_compression(self._compression_params)
            if self._update_on_kvstore is None:
                self._update_on_kvstore = False
            if self._update_on_kvstore:
                kv.set_optimizer(self._optimizer)
            for i, p in enumerate(self._params):
                if p._data is not None:
                    self._kvstore.init(i, p.data())
        self._kv_initialized = True

    @property
    def learning_rate(self):
        return self._optimizer.learning_rate

    @property
    def optimizer(self):
        return self._optimizer

    def set_learning_rate(self, lr):
        self._optimizer.set_learning_rate(lr)

    def allreduce_grads(self):
        """Reduce gradients across devices/workers (reference:
        trainer.py:363 _allreduce_grads)."""
        if not self._kv_initialized:
            self._init_kvstore()
        self._allreduce_grads()

    def _allreduce_grads(self):
        if self._kvstore is None:
            return
        for i, p in enumerate(self._params):
            if p.grad_req != "null" and p._data is not None:
                grads = p.list_grad()
                if self._update_on_kvstore:
                    # optimizer runs in the store; weights pulled in _update
                    self._kvstore.push(i, grads, priority=-i)
                else:
                    self._kvstore.pushpull(i, grads, out=grads, priority=-i)

    def step(self, batch_size, ignore_stale_grad=False):
        """Reference: trainer.py:334."""
        if not self._kv_initialized:
            self._init_kvstore()
        self._optimizer.rescale_grad = self._scale / batch_size
        self._allreduce_grads()
        self._update(ignore_stale_grad)

    def update(self, batch_size, ignore_stale_grad=False):
        """Apply optimizer without allreduce (assumes grads already reduced;
        reference: trainer.py update)."""
        if not self._kv_initialized:
            self._init_kvstore()
        self._optimizer.rescale_grad = self._scale / batch_size
        self._update(ignore_stale_grad)

    def _update(self, ignore_stale_grad=False):
        updater = self._updaters[0]
        for i, p in enumerate(self._params):
            if p.grad_req == "null" or p._data is None:
                continue
            if self._update_on_kvstore:
                # weights were updated inside the store: pull them back
                self._kvstore.pull(i, out=p.data(), priority=-i)
            else:
                updater(i, p.grad(), p.data())

    def save_states(self, fname):
        """Reference: trainer.py:482."""
        if not self._kv_initialized:
            self._init_kvstore()
        if self._update_on_kvstore:
            self._kvstore.save_optimizer_states(fname, dump_optimizer=True)
        else:
            with open(fname, "wb") as f:
                f.write(self._updaters[0].get_states(dump_optimizer=True))

    def load_states(self, fname):
        """Reference: trainer.py:511."""
        if not self._kv_initialized:
            self._init_kvstore()
        if self._update_on_kvstore:
            self._kvstore.load_optimizer_states(fname)
            self._optimizer = self._kvstore._optimizer or self._optimizer
        else:
            with open(fname, "rb") as f:
                self._updaters[0].set_states(f.read())
            self._optimizer = self._updaters[0].optimizer
        self._optimizer.param_dict = {i: p for i, p in enumerate(self._params)}

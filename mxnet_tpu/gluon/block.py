"""gluon.Block / HybridBlock.

Reference parity: python/mxnet/gluon/block.py (Block :202, HybridBlock :997,
SymbolBlock :1638). The reference traces a hybridized block with deferred
compute into an NNVM graph and replays it through CachedOp
(src/imperative/cached_op.cc); shape-specialized re-planning happens in
SetForwardGraph (cached_op.cc:169).

TPU-native design: ``hybridize()`` makes ``__call__`` run the user's
``forward`` inside ``jax.jit`` — the trace *is* the graph, XLA does memory
planning/fusion, and the executable cache keyed by input shapes/dtypes is the
CachedOp shape-signature cache. Mutable aux state (BatchNorm running stats)
is handled functionally: the traced function returns the set of parameters it
mutated, and the wrapper writes them back — the analog of CachedOp's mutable
input handling. Under ``autograd.record()`` the whole compiled forward is one
tape node (reference: CachedOp registers itself as one ``_CachedOp`` tape
node, cached_op.cc:968,1276).
"""
from __future__ import annotations

import hashlib
import json
import os
import re
import threading
import time

import jax
import jax.numpy as jnp

from .. import autograd
from .. import insight as _insight
from .. import telemetry as _telemetry
from ..base import MXNetError
from ..numpy.multiarray import ndarray, _wrap
from .parameter import Parameter, DeferredInitializationError
from .. import random as _random

#: per-thread _CachedGraph call depth — telemetry records only the
#: outermost hybridized call (children traced inside a parent are part
#: of that one compile)
_tele_tls = threading.local()


def _is_nd(x):
    return isinstance(x, ndarray)


#: sentinel for "rematerialization disabled" (a policy of None is meaningful
#: to jax.checkpoint: it means save nothing, i.e. full remat)
_REMAT_OFF = object()

_REMAT_POLICIES = {
    "dots": "dots_saveable",
    "dots_saveable": "dots_saveable",
    "dots_with_no_batch_dims": "dots_with_no_batch_dims_saveable",
    "dots_with_no_batch_dims_saveable": "dots_with_no_batch_dims_saveable",
    "nothing": "nothing_saveable",
    "everything": "everything_saveable",
}


def resolve_remat_policy(remat):
    """Map a ``hybridize(remat=...)`` value onto a ``jax.checkpoint`` policy.

    ``False``/``None`` — off. ``True`` — full rematerialization (only the
    inputs are saved; everything recomputes in the backward pass).
    ``'dots'`` — selective: matmul/einsum outputs are saved, cheap
    elementwise ops recompute (``jax.checkpoint_policies.dots_saveable``,
    the usual sweet spot for transformer blocks).
    ``'dots_with_no_batch_dims'`` — save only weight-stationary matmuls.
    A callable is used as the policy directly.
    """
    if remat is None or remat is False:
        return _REMAT_OFF
    if remat is True:
        return None
    if callable(remat):
        return remat
    attr = _REMAT_POLICIES.get(remat)
    if attr is None or not hasattr(jax.checkpoint_policies, attr):
        raise MXNetError(
            f"unknown remat policy {remat!r}: expected True/False, one of "
            f"{sorted(set(_REMAT_POLICIES))}, or a policy callable")
    return getattr(jax.checkpoint_policies, attr)


def _flatten_args(args):
    leaves, treedef = jax.tree_util.tree_flatten(args, is_leaf=_is_nd)
    return leaves, treedef


class Block:
    """Base neural-network container (reference: gluon/block.py:202).

    Child blocks and Parameters are discovered through attribute assignment,
    MXNet-2.0-style (no name_scope); structural names are attribute paths.
    """

    def __init__(self, prefix=None, params=None):
        self._children = {}
        self._reg_params = {}
        self._forward_hooks = []
        self._forward_pre_hooks = []

    # -- registration ------------------------------------------------------
    def __setattr__(self, name, value):
        if isinstance(value, Block):
            existing = self.__dict__.get("_children")
            if existing is not None:
                existing[name] = value
        elif isinstance(value, Parameter):
            existing = self.__dict__.get("_reg_params")
            if existing is not None:
                existing[name] = value
        super().__setattr__(name, value)

    def register_child(self, block, name=None):
        self._children[name or str(len(self._children))] = block

    def register_forward_hook(self, hook):
        self._forward_hooks.append(hook)
        return hook

    def register_forward_pre_hook(self, hook):
        self._forward_pre_hooks.append(hook)
        return hook

    # -- parameter management ----------------------------------------------
    def collect_params(self, select=None):
        """dict structural-name -> Parameter (reference: block.py
        collect_params; select is a regex like '.*weight')."""
        out = {}
        self._collect_params(out, "")
        if select is not None:
            pattern = re.compile(select)
            out = {k: v for k, v in out.items() if pattern.match(k)}
        return out

    def _collect_params(self, out, prefix):
        for name, p in self._reg_params.items():
            full = f"{prefix}{name}"
            p._structure_name = full
            out[full] = p
        for cname, child in self._children.items():
            child._collect_params(out, f"{prefix}{cname}.")

    @property
    def params(self):
        return dict(self._reg_params)

    def initialize(self, init=None, ctx=None, verbose=False,
                   force_reinit=False, device=None):
        """Initialize all parameters (reference: block.py initialize)."""
        for p in self.collect_params().values():
            p.initialize(init=p.init, ctx=device if device is not None else ctx,
                         default_init=init, force_reinit=force_reinit)
        return self

    def setattr(self, name, value):
        for p in self.collect_params().values():
            setattr(p, name, value)

    def apply(self, fn):
        for child in self._children.values():
            child.apply(fn)
        fn(self)
        return self

    def cast(self, dtype):
        """Cast parameters (+ future inputs) to dtype (reference: block.py
        cast; the AMP bf16 path uses this)."""
        for p in self.collect_params().values():
            p.cast(dtype)
        self._dtype = dtype
        return self

    def reset_ctx(self, ctx):
        for p in self.collect_params().values():
            p.reset_ctx(ctx)

    reset_device = reset_ctx

    def zero_grad(self):
        for p in self.collect_params().values():
            p.zero_grad()

    def share_parameters(self, shared):
        """Reference: block.py share_parameters (dict name->Parameter)."""
        mine = self.collect_params()
        for name, p in shared.items():
            if name in mine:
                self._set_param_by_path(name, p)
        return self

    def _set_param_by_path(self, path, p):
        parts = path.split(".")
        obj = self
        for part in parts[:-1]:
            obj = obj._children[part] if part in obj._children else getattr(obj, part)
        setattr(obj, parts[-1], p)

    # -- save / load -------------------------------------------------------
    def save_parameters(self, filename, deduplicate=False):
        """npz of structural-name -> value (reference: block.py:340 over
        src/serialization/cnpy.cc); ``.safetensors`` filenames write the
        portable safetensors format (mxnet_tpu.serialization).

        Writes are crash-atomic (same-dir temp + fsync + ``os.replace``,
        stale temps from earlier crashes cleaned up): a crash mid-save
        can never tear an existing checkpoint."""
        import io
        import numpy as onp
        from .. import serialization
        params = self.collect_params()
        arrays = {}
        for name, p in params.items():
            if p._data is not None:
                arrays[name] = p.data().asnumpy()
        if filename.endswith(".safetensors"):
            serialization.save_safetensors(filename, arrays)
            return
        buf = io.BytesIO()
        onp.savez(buf, **arrays)
        serialization.atomic_write_bytes(filename, buf.getvalue())

    def load_parameters(self, filename, ctx=None, allow_missing=False,
                        ignore_extra=False, cast_dtype=False,
                        dtype_source="current", device=None):
        """Reference: block.py:378.

        When a ``.sha256`` sidecar exists (CheckpointHandler writes one
        per checkpoint), the file is validated against it first, so a
        torn/corrupt checkpoint raises instead of silently loading
        garbage weights."""
        import numpy as onp
        from ..numpy import array
        from .. import serialization
        real = filename if os.path.exists(filename) else filename + ".npz"
        if os.path.exists(real):
            serialization.verify_checksum(real)
        if filename.endswith(".safetensors"):
            loaded = serialization.load_safetensors(filename)
        elif os.path.exists(filename) \
                and serialization.is_legacy_params(filename):
            # a .params file written by Apache MXNet (legacy binary);
            # 1.x prefixes names with 'arg:'/'aux:' — strip them
            loaded = serialization.load_legacy_params(filename)
            if isinstance(loaded, list):
                raise MXNetError(
                    f"{filename} holds unnamed arrays; parameters need "
                    "names to load into a Block (save with a dict)")
            stripped = {}
            for k, v in loaded.items():
                base = k.split(":", 1)[1] if k.startswith(("arg:", "aux:")) \
                    else k
                if base in stripped:
                    # the reference keeps arg/aux as separate dicts; a name
                    # in both would silently lose one here — refuse
                    raise MXNetError(
                        f"{filename}: parameter {base!r} appears as both "
                        "arg: and aux:; cannot merge into one namespace")
                stripped[base] = v
            loaded = stripped
        else:
            path = filename if os.path.exists(filename) \
                else filename + ".npz"
            with onp.load(path, allow_pickle=False) as data:
                loaded = {k: data[k] for k in data.files}
        params = self.collect_params()
        for name, p in params.items():
            if name in loaded:
                p.set_data(array(loaded[name]))
            elif not allow_missing:
                raise MXNetError(f"parameter {name} missing in {filename}")
        extra = set(loaded) - set(params)
        if extra and not ignore_extra:
            raise MXNetError(f"file {filename} has extra parameters {sorted(extra)}")
        if ctx is not None or device is not None:
            self.reset_ctx(device if device is not None else ctx)

    def save(self, prefix):
        """Structural checkpoint (reference: block.py:576)."""
        self.save_parameters(prefix + "-model.params")

    def load(self, prefix):
        self.load_parameters(prefix + "-model.params")

    # -- execution ---------------------------------------------------------
    def __call__(self, *args, **kwargs):
        for hook in self._forward_pre_hooks:
            hook(self, args)
        out = self.forward(*args, **kwargs)
        for hook in self._forward_hooks:
            hook(self, args, out)
        return out

    def forward(self, *args, **kwargs):
        raise NotImplementedError

    def hybridize(self, active=True, **kwargs):
        """No-op on plain Blocks except recursing into children (reference:
        block.py Block.hybridize)."""
        for child in self._children.values():
            child.hybridize(active, **kwargs)

    def summary(self, *inputs):
        params = self.collect_params()
        lines = [f"{type(self).__name__}:"]
        total = 0
        for name, p in params.items():
            n = 1
            for s in (p.shape or ()):
                n *= max(s, 0)
            total += n
            lines.append(f"  {name:60s} {str(p.shape):20s} {n}")
        lines.append(f"Total params: {total}")
        print("\n".join(lines))

    def __repr__(self):
        s = f"{type(self).__name__}("
        for name, child in self._children.items():
            s += f"\n  ({name}): {repr(child)}"
        return s + ("\n)" if self._children else ")")


class _CachedGraph:
    """Compiled forward for one (block, train_mode): the CachedOp analog.

    One jax.jit'd pure function; XLA's executable cache keyed on input
    shapes/dtypes replaces CachedOp::SetForwardGraph shape re-planning.
    """

    def __init__(self, block, train_mode):
        self.block = block
        self.train_mode = train_mode
        params = block.collect_params()
        self.param_names = [n for n, p in params.items() if p._data is not None]
        self.params = {n: params[n] for n in self.param_names}
        self.trainable = [n for n in self.param_names
                          if self.params[n].grad_req != "null"]
        self.aux = [n for n in self.param_names
                    if self.params[n].grad_req == "null"]
        pure = self._pure
        if getattr(block, "_backend", None):
            # subgraph backend transform (reference: optimize_for partition
            # hook, block.py:1160; here a rewrite of the traced forward)
            from .. import library
            transform = library.subgraph_backend(block._backend)
            pure = transform(pure, block,
                             **(block._flags.get("backend_opts") or {}))
        policy = resolve_remat_policy(block._flags.get("remat")) \
            if getattr(block, "_flags", None) else _REMAT_OFF
        if policy is not _REMAT_OFF:
            # selective rematerialization: under autograd the whole forward
            # replays per the policy instead of saving every activation
            import functools as _ft
            inner_pure = pure

            def pure(trainable_raws, aux_raws, input_raws, rng_key,
                     sig_key):
                fn = _ft.partial(inner_pure, sig_key=sig_key)
                return jax.checkpoint(fn, policy=policy)(
                    trainable_raws, aux_raws, input_raws, rng_key)
        self._jit = jax.jit(pure, static_argnames=("sig_key",))
        self._signatures = {}  # sig_key -> (treedef, static_leaves)
        self._out_trees = {}   # sig_key -> output treedef (set at trace time)
        # guards the two trace-time side channels above: the reference ships
        # a dedicated thread-safe executor (src/imperative/
        # cached_op_threadsafe.cc); here the jit itself is thread-safe and
        # only the signature bookkeeping needs the lock
        self._sig_lock = threading.Lock()
        # trace (param-buffer rebinding) vs replay isolation
        self._rw = _RWLock()
        # sig_key -> number of calls currently using it: a cache flush must
        # not evict the trace state of a call in progress
        self._inflight = {}


    def _pure(self, trainable_raws, aux_raws, input_raws, rng_key, sig_key):
        if self._rw._readers:
            # tracing rebinds the shared Parameter buffers to tracers; doing
            # that while replays hold the read lock (including our own
            # reader slot — we mispredicted a cache hit) would leak tracers
            # into other threads. Abort; the caller retries as a writer.
            raise _SignatureEvicted(sig_key)
        sig = self._signatures.get(sig_key)
        if sig is None:
            # evicted between registration and (re-)trace — caller retries
            raise _SignatureEvicted(sig_key)
        treedef, static_leaves = sig
        saved = {}
        try:
            for n in self.param_names:
                p = self.params[n]
                saved[n] = p._data._data
                p._data._data = (trainable_raws[n] if n in trainable_raws
                                 else aux_raws[n])
            markers = {n: self.params[n]._data._data for n in self.aux}
            leaves = list(static_leaves)
            it = iter(input_raws)
            for i, l in enumerate(leaves):
                if l is _ARR:
                    leaves[i] = _wrap(next(it))
            fargs, fkwargs = jax.tree_util.tree_unflatten(treedef, leaves)
            with autograd._RecordingStateScope(False, self.train_mode), \
                    _random.trace_key_scope(rng_key):
                out = self.block.forward(*fargs, **fkwargs)
            out_leaves, out_tree = _flatten_args(out)
            out_raws = [l._data if _is_nd(l) else l for l in out_leaves]
            with self._sig_lock:  # serialize vs cache-flush dict swaps
                self._out_trees[sig_key] = out_tree
            mutated = {n: self.params[n]._data._data for n in self.aux
                       if self.params[n]._data._data is not markers[n]}
            return out_raws, mutated
        finally:
            for n, raw in saved.items():
                self.params[n]._data._data = raw

    def __call__(self, args):
        from .. import profiler as _profiler
        if _profiler._state["running"] and \
                _profiler._config["profile_symbolic"]:
            # one span per compiled-forward replay (the reference profiles
            # CachedOp as a single engine op)
            with _profiler.span(f"CachedOp:{type(self.block).__name__}",
                                "symbolic"):
                return self._call_impl(args)
        return self._call_impl(args)

    def _call_impl(self, args):
        import numpy as onp
        leaves, treedef = _flatten_args(args)
        input_raws, static_leaves = [], []
        for i, l in enumerate(leaves):
            if isinstance(l, (jax.Array, onp.ndarray)) and not _is_nd(l):
                # raw arrays (e.g. kwarg masks) must be traced inputs —
                # keyed by repr() they would silently bake in as constants
                leaves[i] = l = _wrap(jnp.asarray(l))
            if _is_nd(l):
                input_raws.append(l._data)
                static_leaves.append(_ARR)
            else:
                static_leaves.append(l)
        from .. import amp as _amp
        from .. import config as _config
        # the full tuple (not its hash) is the key: equality comparison
        # makes collisions impossible (long static reprs are digested — a
        # 128-bit collision is not a realistic event); jax.jit's own cache
        # grows with the same signatures, so this adds no asymptotic memory
        sig_key = (str(treedef),
                   tuple("A" if l is _ARR else _static_repr(l)
                         for l in static_leaves),
                   tuple((tuple(r.shape), str(r.dtype)) for r in input_raws),
                   # dtype policy is applied inside _invoke at trace time, so
                   # a policy change must invalidate the cached trace
                   (_amp.is_active(), str(_amp.target_dtype())))
        with self._sig_lock:
            self._inflight[sig_key] = self._inflight.get(sig_key, 0) + 1
            is_new_sig = sig_key not in self._signatures
            if is_new_sig and \
                    len(self._signatures) >= \
                    _config.get("cached_graph.max_signatures"):
                # flush executables, out-trees and signatures together so
                # they stay consistent (reference: CachedOp bounds this
                # blowup via config, cached_op.h:412-459) — but keep the
                # entries of calls currently in flight on other threads
                keep = set(self._inflight)
                self._signatures = {k: v for k, v in self._signatures.items()
                                    if k in keep}
                self._out_trees = {k: v for k, v in self._out_trees.items()
                                   if k in keep}
                self._jit.clear_cache()
            self._signatures[sig_key] = (treedef, static_leaves)

        rng = _random._next_key()

        nd_leaves = [l for l in leaves if _is_nd(l)]
        arr_inputs = [l for l in nd_leaves
                      if jnp.issubdtype(l.dtype, jnp.inexact)]
        param_arrays = [self.params[n]._data for n in self.trainable]
        recording = autograd.is_recording() and (
            any(a._entry is not None for a in arr_inputs)
            or any(a._entry is not None for a in param_arrays))
        diff_input_raws = [l._data for l in arr_inputs]

        # an untraced signature means the next jit call traces, and tracing
        # temporarily rebinds the shared Parameter buffers to tracers —
        # exclusive (writer). Replays only read the param raws — shared.
        # _out_trees membership == "trace completed" (set at trace time).
        need_trace = is_new_sig or sig_key not in self._out_trees
        # telemetry covers only the OUTERMOST hybridized call on this
        # thread: children re-tracing inside a parent's trace are an
        # implementation detail of that one user-visible compile, and
        # per-child recompile warnings would be noise for one root cause
        outermost = not getattr(_tele_tls, "depth", 0)
        if _telemetry._active and outermost:
            # per-signature compile/cache accounting + the recompilation
            # detector (shape-polymorphism pitfall: every new signature
            # costs a full XLA compile on TPU)
            _telemetry.inc("cached_graph.cache_miss_total" if need_trace
                           else "cached_graph.cache_hit_total",
                           block=type(self.block).__name__)
        _tele_tls.depth = getattr(_tele_tls, "depth", 0) + 1
        try:
            for _attempt in (0, 1):
                acquired_write = need_trace
                if acquired_write:
                    self._rw.acquire_write()
                else:
                    self._rw.acquire_read()
                _t_trace = (time.perf_counter()
                            if acquired_write and outermost
                            and _telemetry._active
                            else None)
                try:
                    trainable_raws = {n: self.params[n]._data._data
                                      for n in self.trainable}
                    aux_raws = {n: self.params[n]._data._data
                                for n in self.aux}
                    if recording:
                        def fn(tr, diff_inp):
                            raws, di = list(input_raws), 0
                            for i, l in enumerate(nd_leaves):
                                if jnp.issubdtype(l.dtype, jnp.inexact):
                                    raws[i] = diff_inp[di]
                                    di += 1
                            return self._jit(tr, aux_raws, raws, rng,
                                             sig_key=sig_key)

                        (out_raws, mutated), vjp_fn = jax.vjp(
                            fn, trainable_raws, diff_input_raws)
                    else:
                        out_raws, mutated = self._jit(
                            trainable_raws, aux_raws, input_raws, rng,
                            sig_key=sig_key)
                    out_tree = self._out_trees.get(sig_key)
                    if out_tree is None:
                        # executable survived a flush that dropped its
                        # out-tree: force a clean re-trace
                        self._jit.clear_cache()
                        raise _SignatureEvicted(sig_key)
                    if _t_trace is not None:
                        _telemetry.note_compile(
                            self.block, type(self.block).__name__,
                            time.perf_counter() - _t_trace,
                            signatures=len(self._signatures))
                    if _insight._active and acquired_write:
                        # attribution for the fresh signature: trace-only
                        # re-lower (HLO cost analysis), no second backend
                        # compile and no note_compile
                        _insight.capture_jit(
                            f"cached_graph.{type(self.block).__name__}",
                            self._jit,
                            (trainable_raws, aux_raws, input_raws, rng),
                            kind="cached_graph", sig_key=sig_key)
                    break
                except _SignatureEvicted:
                    if _attempt:
                        raise MXNetError(
                            "compiled-forward signature cache thrashing: "
                            "raise mx.config cached_graph.max_signatures")
                    with self._sig_lock:
                        self._signatures[sig_key] = (treedef, static_leaves)
                    need_trace = True
                finally:
                    if acquired_write:
                        self._rw.release_write()
                    else:
                        self._rw.release_read()
        finally:
            _tele_tls.depth -= 1
            with self._sig_lock:
                self._inflight[sig_key] -= 1
                if not self._inflight[sig_key]:
                    del self._inflight[sig_key]

        # write back mutated aux state (BatchNorm running stats etc.) — the
        # analog of CachedOp mutable inputs
        for n, raw in mutated.items():
            self.params[n]._data._rebind(raw)

        out_wrapped = [_wrap(r) for r in out_raws]
        out = jax.tree_util.tree_unflatten(out_tree, out_wrapped)

        if recording:
            mut_shapes = {n: (raw.shape, raw.dtype) for n, raw in mutated.items()}
            trainable_names = list(self.trainable)

            def node_vjp(cots, _vjp=vjp_fn):
                cots = cots if isinstance(cots, tuple) else (cots,)
                mut_zeros = {n: jnp.zeros(s, d) for n, (s, d) in mut_shapes.items()}
                tr_cots, inp_cots = _vjp((list(cots), mut_zeros))
                return tuple(tr_cots[n] for n in trainable_names) + tuple(inp_cots)

            n_tr = len(trainable_names)

            def fun_flat(*flat, _fn=fn, _sig=sig_key, _td=treedef,
                         _sl=static_leaves):
                # flat = trainable raws + diff input raws; re-runs the jitted
                # forward so create_graph can jax.vjp through the whole
                # graph. This runs outside _call_impl's retry loop, so it
                # must re-register the signature (a flush may have evicted
                # it) and hold the write lock in case the re-entry traces.
                tr = dict(zip(trainable_names, flat[:n_tr]))
                for _attempt in (0, 1):
                    with self._sig_lock:
                        self._signatures[_sig] = (_td, _sl)
                    self._rw.acquire_write()
                    try:
                        out_raws2, _mut = _fn(tr, list(flat[n_tr:]))
                        return tuple(out_raws2)
                    except _SignatureEvicted:
                        if _attempt:
                            raise MXNetError(
                                "signature cache thrashing during "
                                "create_graph backward: raise mx.config "
                                "cached_graph.max_signatures")
                    finally:
                        self._rw.release_write()

            autograd._record_op(
                node_vjp, param_arrays + arr_inputs, out_wrapped,
                f"CachedOp:{type(self.block).__name__}",
                out_treedef=jax.tree_util.tree_structure(tuple(out_raws)),
                fun=fun_flat,
                raw_args=tuple(trainable_raws[n] for n in trainable_names)
                + tuple(diff_input_raws))
        return out


class _ArrSentinel:
    pass


_ARR = _ArrSentinel()


class _SignatureEvicted(Exception):
    """Trace-time side channel lost its entry (cache flush race); retry."""


class _RWLock:
    """Minimal readers-writer lock: traces are writers (exclusive — they
    temporarily rebind shared Parameter buffers to tracers), compiled
    replays are readers (shared). The reference isolates this class of race
    in a dedicated executor (src/imperative/cached_op_threadsafe.cc)."""

    def __init__(self):
        self._cond = threading.Condition()
        self._readers = 0
        self._writer = False

    def acquire_read(self):
        with self._cond:
            while self._writer:
                self._cond.wait()
            self._readers += 1

    def release_read(self):
        with self._cond:
            self._readers -= 1
            if not self._readers:
                self._cond.notify_all()

    def acquire_write(self):
        with self._cond:
            while self._writer or self._readers:
                self._cond.wait()
            self._writer = True

    def release_write(self):
        with self._cond:
            self._writer = False
            self._cond.notify_all()


def _static_repr(l):
    """Signature token for a static (non-array) call leaf; long reprs are
    digested so one huge python literal doesn't bloat every key."""
    r = repr(l)
    if len(r) > 128:
        # sha256: FIPS-approved (md5 raises on FIPS-enabled builds)
        return "H" + hashlib.sha256(r.encode()).hexdigest()
    return r


def _hashable(x):
    try:
        hash(x)
        return True
    except TypeError:
        return False


class HybridBlock(Block):
    """Traceable block (reference: gluon/block.py:997)."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix, params)
        self._active = False
        self._cached_graphs = {}
        self._flags = {}
        self._backend = None
        self._last_input_sig = None

    def __deepcopy__(self, memo):
        """Copies drop the compiled cache: _CachedGraph holds locks and
        jit executables that are process-local, and a copied net must
        re-trace against its OWN (copied) parameters anyway. The
        reference rebuilds CachedOp on copy the same way; quantize_net
        deep-copies hybridized nets through here."""
        import copy as _copy
        cls = self.__class__
        new = cls.__new__(cls)
        memo[id(self)] = new
        for k, v in self.__dict__.items():
            new.__dict__[k] = {} if k == "_cached_graphs" \
                else _copy.deepcopy(v, memo)
        return new

    def hybridize(self, active=True, backend=None, backend_opts=None,
                  clear=True, static_alloc=False, static_shape=False,
                  **kwargs):
        """Reference: block.py hybridize. static_alloc/static_shape map to
        XLA buffer donation/compiled executables — both are automatic here;
        the flags are accepted for compatibility.

        ``remat=`` selects activation rematerialization for the compiled
        forward under autograd: True (full), 'dots' / another name from
        ``resolve_remat_policy``, or a ``jax.checkpoint`` policy callable.
        ``parallel.ShardedTrainStep`` honors the same flag.
        """
        resolve_remat_policy(kwargs.get("remat"))  # fail fast on bad values
        self._active = active
        if backend is not None:
            from .. import library
            library.subgraph_backend(backend)  # fail fast on unknown names
        self._backend = backend
        self._flags = dict(static_alloc=static_alloc,
                           static_shape=static_shape,
                           backend_opts=backend_opts, **kwargs)
        if clear:
            self._cached_graphs = {}
        super().hybridize(active, backend=backend, backend_opts=backend_opts,
                          static_alloc=static_alloc,
                          static_shape=static_shape, **kwargs)

    def optimize_for(self, x, *args, backend=None, clear=True, **kwargs):
        """Reference: block.py optimize_for — compiles for a backend then
        runs once. XLA is the only backend; equivalent to hybridize+call."""
        self.hybridize(True, backend=backend, clear=clear, **kwargs)
        return self(x, *args)

    def _ensure_init(self, *args):
        """Run deferred shape inference by executing forward eagerly once."""
        params = self.collect_params()
        pending = [p for p in params.values()
                   if p._data is None and p._deferred_init is not None]
        uninit = [p for p in params.values()
                  if p._data is None and p._deferred_init is None]
        if uninit:
            raise MXNetError(
                f"parameters {[p.name for p in uninit]} not initialized; "
                "call .initialize()")
        return bool(pending)

    def __call__(self, *args, **kwargs):
        if not kwargs and all(_is_nd(a) for a in args):
            # remembered for export(): the traced input signature
            self._last_input_sig = [(tuple(a.shape), str(a.dtype))
                                    for a in args]
        if not self._active:
            return super().__call__(*args, **kwargs)
        if self._ensure_init(*args):
            # first call: eager, triggers deferred init (the reference's
            # _build_cache also runs a traced forward first, block.py:1095)
            return super().__call__(*args, **kwargs)
        key = self._train_key()
        graph = self._cached_graphs.get(key)
        if graph is None:
            graph = _CachedGraph(self, key)
            self._cached_graphs[key] = graph
        # (args, kwargs) form one pytree: keyword names land in the treedef
        # and therefore in the trace-cache key, so keyword calls compile
        # exactly like positional ones (the reference's _build_cache is
        # positional-only and errors; block.py:1095)
        return graph((args, kwargs))

    @staticmethod
    def _train_key():
        return autograd.is_training()

    # -- export (reference: block.py:1471 export to json+params) -----------
    def export(self, path, epoch=0, remove_amp_cast=True):
        """Save a graph-only model artifact: params npz + serialized
        StableHLO + a manifest json.

        The reference writes NNVM json reloadable by SymbolBlock without the
        python class (gluon/block.py:1471,1638); the TPU-native equivalent
        is a jax.export StableHLO artifact (cross-lowered for cpu+tpu, with
        first-order VJP so the reload stays trainable). Requires at least
        one prior forward call (to know the input signature) — same
        precondition as the reference's deferred-compute export.
        """
        from .. import functional
        from ..base import np_dtype

        params_file = f"{path}-{epoch:04d}.params.npz"
        self.save_parameters(params_file)
        meta = {
            "format": "mxnet_tpu-hybrid-v2",
            "block_class": f"{type(self).__module__}.{type(self).__name__}",
            "params": os.path.basename(params_file),
        }
        if self._last_input_sig is None:
            raise MXNetError(
                "export requires a prior forward call so the input "
                "signature is known (reference: hybridize+forward before "
                "export)")
        from jax import export as jax_export

        params = functional.param_arrays(self)

        def fwd(params, *inputs):
            out, _ = functional.functional_call(self, params, *inputs,
                                                train=False)
            return out

        param_specs = {n: jax.ShapeDtypeStruct(a.shape, a.dtype)
                       for n, a in params.items()}
        in_specs = tuple(jax.ShapeDtypeStruct(s, np_dtype(d))
                         for s, d in self._last_input_sig)
        exported = jax_export.export(
            jax.jit(fwd), platforms=["cpu", "tpu"])(param_specs, *in_specs)
        hlo_file = f"{path}-{epoch:04d}.stablehlo"
        with open(hlo_file, "wb") as f:
            f.write(exported.serialize(vjp_order=1))
        meta["stablehlo"] = os.path.basename(hlo_file)
        meta["inputs"] = self._last_input_sig
        json_file = f"{path}-symbol.json"
        with open(json_file, "w") as f:
            json.dump(meta, f, indent=2)
        return json_file, params_file

    def infer_shape(self, *args):
        """Trigger deferred-shape inference without full compute where
        possible (falls back to an eager forward)."""
        with autograd.pause():
            self(*args)

    def forward(self, *args, **kwargs):
        raise NotImplementedError

    def hybrid_forward(self, F, *args, **kwargs):
        raise MXNetError(
            "hybrid_forward(F, ...) is the MXNet 1.x API; implement "
            "forward(self, x) (MXNet 2.0 / Gluon 2 style) instead")


class SymbolBlock(HybridBlock):
    """Run an exported model WITHOUT its python class (reference:
    block.py:1638): the serialized StableHLO artifact from
    ``HybridBlock.export`` is the graph, the params npz is the state.
    Forward dispatches the deserialized program through ``_invoke`` so
    autograd records it (the artifact carries a first-order VJP), making
    reloaded models trainable like the reference's SymbolBlock."""

    def __init__(self, outputs=None, inputs=None, params=None,
                 exported=None):
        """Two construction forms, matching the reference:

        - ``SymbolBlock(outputs_symbol, inputs_symbol(s), params=...)``
          runs a Symbol DAG (reference block.py:1638 primary form; pairs
          with ``mx.model.load_checkpoint``). ``params`` values may be
          ndarrays or Parameters.
        - ``SymbolBlock(exported=...)`` wraps a deserialized StableHLO
          artifact (``SymbolBlock.imports``).
        """
        super().__init__()
        self._symbol = None
        self._input_names = []
        if outputs is not None:
            if not hasattr(outputs, "_eval_with"):
                raise MXNetError(
                    "SymbolBlock outputs must be a Symbol; to wrap a "
                    "StableHLO artifact pass exported= (or use "
                    "SymbolBlock.imports)")
            self._symbol = outputs
            ins = inputs if isinstance(inputs, (list, tuple)) else [inputs]
            self._input_names = [getattr(s, "name", s) for s in ins]
            fixed = {}
            for n, v in (params or {}).items():
                if n in self._input_names:
                    continue   # inputs are bound at call time, never stored
                if isinstance(v, Parameter):
                    fixed[n] = v
                else:
                    # trainable by default, like the reference's arg_params
                    p = Parameter(n, shape=tuple(v.shape),
                                  dtype=str(v.dtype), grad_req="write")
                    p.set_data(v if isinstance(v, ndarray)
                               else _wrap(jnp.asarray(v)))
                    fixed[n] = p
            params = fixed
        self._exported = exported
        self._sym_params = dict(params or {})

    def collect_params(self, select=None):
        if select is None:
            return dict(self._sym_params)
        pat = re.compile(select)
        return {n: p for n, p in self._sym_params.items() if pat.search(n)}

    def forward(self, *args):
        if self._symbol is not None:
            if len(args) != len(self._input_names):
                raise MXNetError(
                    f"SymbolBlock expects {len(self._input_names)} inputs "
                    f"{self._input_names}, got {len(args)}")
            bindings = {n: p.data() for n, p in self._sym_params.items()}
            bindings.update(zip(self._input_names, args))  # inputs win
            return self._symbol._eval_with(bindings)
        if self._exported is None:
            raise MXNetError("SymbolBlock has no graph; use SymbolBlock."
                             "imports(symbol_file, ...)")
        from ..numpy.multiarray import _invoke
        names = sorted(self._sym_params)
        pdict = {n: self._sym_params[n].data() for n in names}

        def run(pdict_raw, *input_raws):
            return self._exported.call(pdict_raw, *input_raws)

        return _invoke(run, (pdict, *args), name="SymbolBlock")

    @staticmethod
    def imports(symbol_file, input_names=None, param_file=None, ctx=None,
                allow_class_fallback=False):
        """Reload an exported artifact. ``input_names`` is accepted for
        reference-API parity (the artifact embeds its signature)."""
        with open(symbol_file) as f:
            meta = json.load(f)
        base = os.path.dirname(os.path.abspath(symbol_file))
        if meta.get("stablehlo"):
            from jax import export as jax_export
            with open(os.path.join(base, meta["stablehlo"]), "rb") as f:
                exported = jax_export.deserialize(bytearray(f.read()))
            params = {}
            pfile = (param_file
                     or os.path.join(base, meta.get("params", "")))
            if pfile and os.path.exists(pfile):
                import numpy as onp
                from ..numpy import array
                with onp.load(pfile) as data:
                    for name in data.files:
                        p = Parameter(name, shape=data[name].shape)
                        p.set_data(array(data[name]))
                        params[name] = p
            return SymbolBlock(exported=exported, params=params)
        if allow_class_fallback and meta.get("block_class"):
            # v1 manifests (no graph artifact): reconstruct via the class
            mod_name, cls_name = meta["block_class"].rsplit(".", 1)
            import importlib
            cls = getattr(importlib.import_module(mod_name), cls_name)
            block = cls()
            if param_file:
                block.load_parameters(param_file, ctx=ctx)
            return block
        raise MXNetError(
            f"{symbol_file} has no stablehlo graph artifact; re-export with "
            "HybridBlock.export (or pass allow_class_fallback=True)")

"""gluon.contrib.nn (reference: gluon/contrib/nn/basic_layers.py)."""
from __future__ import annotations

from ... import nn as _nn
from ....numpy.multiarray import ndarray
from .... import numpy as _np
from ...block import HybridBlock


class HybridConcurrent(HybridBlock):
    """Parallel branches concatenated (reference: contrib/nn
    HybridConcurrent)."""

    def __init__(self, axis=-1):
        super().__init__()
        self._axis = axis
        self._n = 0

    def add(self, *blocks):
        for b in blocks:
            self.register_child(b, str(self._n))
            self._n += 1

    def forward(self, x):
        return _np.concatenate([b(x) for b in self._children.values()],
                               axis=self._axis)


class Concurrent(HybridConcurrent):
    pass


class Identity(HybridBlock):
    def forward(self, x):
        return x


class SparseEmbedding(_nn.Embedding):
    """Dense-gradient embedding (sparse grads are dense on TPU)."""

"""gluon.contrib.data (reference: python/mxnet/gluon/contrib/data/)."""
from . import vision  # noqa: F401

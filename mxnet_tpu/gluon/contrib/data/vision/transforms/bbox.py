"""Joint image+bbox transforms.

Reference parity: python/mxnet/gluon/contrib/data/vision/transforms/bbox/
(bbox.py ImageBboxRandomFlipLeftRight/ImageBboxCrop/ImageBboxResize and
utils.py bbox_crop/bbox_flip/bbox_resize/bbox_translate).  Host-side
numpy transforms for detection pipelines; boxes are (N, 4+) corner format
``[x1, y1, x2, y2, ...extra columns preserved]``.
"""
from __future__ import annotations

import numpy as onp

from ......base import MXNetError
from ......numpy.multiarray import ndarray


def _np(x):
    return x.asnumpy() if isinstance(x, ndarray) else onp.asarray(x)


def bbox_crop(bbox, crop_box=None, allow_outside_center=True):
    """Crop boxes to a (x, y, w, h) window, translating to its origin;
    boxes whose center leaves the window are dropped when
    allow_outside_center=False (reference: utils.py bbox_crop)."""
    bbox = _np(bbox).copy()
    if crop_box is None:
        return bbox
    if len(crop_box) != 4:
        raise MXNetError("crop_box must be (x, y, w, h)")
    x, y, w, h = crop_box
    lim = onp.asarray([x, y, x + w, y + h], bbox.dtype)
    if not allow_outside_center:
        centers = (bbox[:, :2] + bbox[:, 2:4]) / 2
        mask = ((centers >= lim[:2]) & (centers <= lim[2:])).all(axis=1)
        bbox = bbox[mask]
    bbox[:, :2] = onp.maximum(bbox[:, :2], lim[:2])
    bbox[:, 2:4] = onp.minimum(bbox[:, 2:4], lim[2:])
    bbox[:, :2] -= lim[:2]
    bbox[:, 2:4] -= lim[:2]
    keep = ((bbox[:, 2] > bbox[:, 0]) & (bbox[:, 3] > bbox[:, 1]))
    return bbox[keep]


def bbox_flip(bbox, size, flip_x=False, flip_y=False):
    """Flip boxes within an image of (width, height) = size
    (reference: utils.py bbox_flip)."""
    bbox = _np(bbox).copy()
    w, h = size
    if flip_x:
        x1 = bbox[:, 0].copy()
        bbox[:, 0] = w - bbox[:, 2]
        bbox[:, 2] = w - x1
    if flip_y:
        y1 = bbox[:, 1].copy()
        bbox[:, 1] = h - bbox[:, 3]
        bbox[:, 3] = h - y1
    return bbox


def bbox_resize(bbox, in_size, out_size):
    """Rescale boxes from in_size=(w,h) to out_size=(w,h)
    (reference: utils.py bbox_resize)."""
    bbox = _np(bbox).astype("float32").copy()
    sx = out_size[0] / in_size[0]
    sy = out_size[1] / in_size[1]
    bbox[:, [0, 2]] *= sx
    bbox[:, [1, 3]] *= sy
    return bbox


def bbox_translate(bbox, x_offset=0, y_offset=0):
    bbox = _np(bbox).copy()
    bbox[:, [0, 2]] += x_offset
    bbox[:, [1, 3]] += y_offset
    return bbox


class ImageBboxRandomFlipLeftRight:
    """Random horizontal flip of (image, bbox) pairs
    (reference: bbox.py ImageBboxRandomFlipLeftRight)."""

    def __init__(self, p=0.5):
        self.p = p

    def __call__(self, img, bbox):
        arr = _np(img)
        if onp.random.rand() < self.p:
            arr = arr[:, ::-1]
            bbox = bbox_flip(bbox, (arr.shape[1], arr.shape[0]),
                             flip_x=True)
        return arr, _np(bbox)


class ImageBboxCrop:
    """Fixed crop of (image, bbox) (reference: bbox.py ImageBboxCrop);
    crop is (x, y, w, h) in pixels."""

    def __init__(self, crop, allow_outside_center=False):
        self.crop = crop
        self.allow = allow_outside_center

    def __call__(self, img, bbox):
        arr = _np(img)
        x, y, w, h = self.crop
        return (arr[y:y + h, x:x + w],
                bbox_crop(bbox, self.crop, self.allow))


class ImageBboxResize:
    """Resize image to (width, height) and rescale boxes
    (reference: bbox.py ImageBboxResize)."""

    def __init__(self, width, height, interp=1):
        self.size = (width, height)
        self.interp = interp

    def __call__(self, img, bbox):
        from ...... import image as img_mod
        arr = _np(img)
        in_size = (arr.shape[1], arr.shape[0])
        out = img_mod.imresize(arr, self.size[0], self.size[1],
                               interp=self.interp)
        return _np(out), bbox_resize(bbox, in_size, self.size)

from . import bbox  # noqa: F401

"""gluon.contrib.data.vision (reference:
python/mxnet/gluon/contrib/data/vision/__init__.py)."""
from .transforms import bbox  # noqa: F401
from .transforms.bbox import (  # noqa: F401
    bbox_crop, bbox_flip, bbox_resize, bbox_translate,
    ImageBboxRandomFlipLeftRight, ImageBboxCrop, ImageBboxResize,
)

"""gluon.contrib.estimator — keras-like fit loop.

Reference parity: python/mxnet/gluon/contrib/estimator/ (Estimator with
event handlers; CheckpointHandler at event_handler.py:336, EarlyStopping
:614, ValidationHandler :160) — the reference's only automatic periodic
checkpointing lives here (SURVEY §5 checkpoint/resume).
"""
from .batch_processor import BatchProcessor  # noqa: F401
from .estimator import Estimator  # noqa: F401
from .event_handler import (  # noqa: F401
    EventHandler, TrainBegin, TrainEnd, EpochBegin, EpochEnd, BatchBegin,
    BatchEnd, StoppingHandler, MetricHandler, ValidationHandler,
    LoggingHandler, CheckpointHandler, EarlyStoppingHandler,
    GradientUpdateHandler, TelemetryHandler, ResilienceHandler,
)

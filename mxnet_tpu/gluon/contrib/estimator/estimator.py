"""Estimator fit loop (reference: gluon/contrib/estimator/estimator.py)."""
from __future__ import annotations

from .... import autograd
from ...metric import Accuracy, Loss as LossMetric
from ...trainer import Trainer
from .event_handler import (
    BatchBegin, BatchEnd, EpochBegin, EpochEnd, MetricHandler,
    StoppingHandler, TrainBegin, TrainEnd,
)


class Estimator:
    def __init__(self, net, loss, train_metrics=None, val_metrics=None,
                 trainer=None, context=None, device=None):
        self.net = net
        self.loss = loss
        self.train_metrics = train_metrics or [Accuracy()]
        if not isinstance(self.train_metrics, list):
            self.train_metrics = [self.train_metrics]
        self.train_metrics.append(LossMetric("train_loss"))
        self.trainer = trainer or Trainer(
            net.collect_params(), "adam", {"learning_rate": 1e-3})

    def _handlers(self, event_handlers, epochs, batches):
        handlers = list(event_handlers or [])
        stop = StoppingHandler(epochs, batches)
        handlers.append(stop)
        handlers.append(MetricHandler(self.train_metrics))
        return handlers, stop

    def fit(self, train_data, val_data=None, epochs=None, event_handlers=None,
            batches=None, batch_axis=0):
        epochs = epochs or (None if batches else 1)
        handlers, stop = self._handlers(event_handlers, epochs, batches)

        def _dispatch(kind, *args, **kwargs):
            for h in handlers:
                fn = getattr(h, kind, None)
                if fn is not None:
                    fn(self, *args, **kwargs)

        _dispatch("train_begin")
        while not stop.stop_training:
            _dispatch("epoch_begin")
            for batch in train_data:
                if stop.stop_training:
                    break
                data, label = batch[0], batch[1]
                _dispatch("batch_begin")
                with autograd.record():
                    pred = self.net(data)
                    loss = self.loss(pred, label)
                loss.backward()
                self.trainer.step(data.shape[batch_axis])
                _dispatch("batch_end", pred=[pred], label=[label],
                          loss=[loss])
            _dispatch("epoch_end")
            if epochs is None and batches is None:
                break
        _dispatch("train_end")
        return self

    def evaluate(self, val_data, val_metrics=None, batch_axis=0):
        metrics = val_metrics or self.train_metrics
        for m in metrics:
            m.reset()
        for batch in val_data:
            data, label = batch[0], batch[1]
            pred = self.net(data)
            for m in metrics:
                if not isinstance(m, LossMetric):
                    m.update([label], [pred])
        return metrics

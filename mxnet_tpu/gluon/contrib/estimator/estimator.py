"""Estimator fit loop (reference: gluon/contrib/estimator/estimator.py).

Architecture mirrors the reference: the minibatch step lives in a
pluggable BatchProcessor (batch_processor.py), the optimizer step in
GradientUpdateHandler at batch_end, and handlers run in ascending
``priority`` order per event (sorted once per fit, not per dispatch).
"""
from __future__ import annotations

from .... import pipeline as _pipeline
from .... import trace as _trace
from ...metric import Accuracy, Loss as LossMetric
from ...trainer import Trainer
from .batch_processor import BatchProcessor
from .event_handler import (
    GradientUpdateHandler, MetricHandler, StoppingHandler,
)

_EVENTS = ("train_begin", "train_end", "epoch_begin", "epoch_end",
           "batch_begin", "batch_end")


def _place_batch(batch):
    """Ensure every array leaf of ``batch`` is device-resident.  Leaves
    already on device pass through untouched (the sync-free common
    case); only genuinely host-side leaves pay a device_put — this is
    the h2d phase the ``train.step`` span tree times."""
    if isinstance(batch, (tuple, list)):
        return type(batch)(_place_batch(b) for b in batch)
    raw = getattr(batch, "_data", None)
    if raw is not None:
        out, moved = _pipeline.maybe_device_put(raw)
        if not moved:
            return batch
        from ....numpy.multiarray import _wrap
        return _wrap(out)
    if hasattr(batch, "__array__"):
        out, _ = _pipeline.maybe_device_put(batch)
        return out
    return batch


class Estimator:
    def __init__(self, net, loss, train_metrics=None, val_metrics=None,
                 trainer=None, context=None, device=None,
                 batch_processor=None, val_net=None, val_loss=None):
        self.net = net
        self.loss = loss
        self.val_net = val_net or net
        self.val_loss = val_loss or loss
        self.train_metrics = train_metrics or [Accuracy()]
        if not isinstance(self.train_metrics, list):
            self.train_metrics = [self.train_metrics]
        self.train_metrics.append(LossMetric("train_loss"))
        self.val_metrics = val_metrics
        if self.val_metrics is not None and \
                not isinstance(self.val_metrics, list):
            self.val_metrics = [self.val_metrics]
        self.trainer = trainer or Trainer(
            net.collect_params(), "adam", {"learning_rate": 1e-3})
        self.batch_processor = batch_processor or BatchProcessor()

    def _handlers(self, event_handlers, epochs, batches):
        handlers = list(event_handlers or [])
        stop = StoppingHandler(epochs, batches)
        handlers.append(stop)
        handlers.append(MetricHandler(self.train_metrics))
        if not any(isinstance(h, GradientUpdateHandler) for h in handlers):
            handlers.append(GradientUpdateHandler())
        # per-event dispatch lists, priority-sorted once (the handler set
        # is fixed for the whole fit)
        by_event = {
            ev: sorted((h for h in handlers if getattr(h, ev, None)),
                       key=lambda h: getattr(h, "priority", 0))
            for ev in _EVENTS}
        return by_event, stop

    def fit(self, train_data, val_data=None, epochs=None, event_handlers=None,
            batches=None, batch_axis=0, autotune=False):
        # autotune=True (or a dict of mx.autotune.search kwargs) runs the
        # config search on one batch borrowed from train_data before the
        # loop, applies what eager fit can use (remat, prefetch depth) and
        # leaves the full result on self.autotune_result
        if autotune:
            from .... import autotune as _autotune
            _autotune.tune_estimator(
                self, train_data,
                **(autotune if isinstance(autotune, dict) else {}))
        epochs = epochs or (None if batches else 1)
        by_event, stop = self._handlers(event_handlers, epochs, batches)

        def _dispatch(kind, *args, **kwargs):
            for h in by_event[kind]:
                getattr(h, kind)(self, *args, **kwargs)

        _dispatch("train_begin")
        step_no = 0
        while not stop.stop_training:
            _dispatch("epoch_begin")
            batch_iter = iter(train_data)
            while True:
                if not _trace._active:
                    try:
                        batch = next(batch_iter)
                    except StopIteration:
                        break
                    if stop.stop_training:
                        break
                    _dispatch("batch_begin")
                    _, label, pred, loss = self.batch_processor.fit_batch(
                        self, batch, batch_axis)
                    _dispatch("batch_end", pred=pred, label=label,
                              loss=loss,
                              num_samples=batch[0].shape[batch_axis])
                    continue
                # traced step anatomy: one span tree per step, children
                # data_wait -> h2d -> dispatch -> drain.  The drain child
                # only notes the deferred-window depth — actual fetches
                # stay at epoch boundaries, so the loop remains sync-free
                step_no += 1
                sp = _trace.span("train.step", category="train",
                                 step=step_no)
                sp.__enter__()
                try:
                    with _trace.span("train.data_wait", category="train"):
                        try:
                            batch = next(batch_iter)
                        except StopIteration:
                            break
                    if stop.stop_training:
                        break
                    with _trace.span("train.h2d", category="train"):
                        batch = _place_batch(batch)
                    _dispatch("batch_begin")
                    with _trace.span("train.dispatch", category="train"):
                        _, label, pred, loss = \
                            self.batch_processor.fit_batch(
                                self, batch, batch_axis)
                        _dispatch("batch_end", pred=pred, label=label,
                                  loss=loss,
                                  num_samples=batch[0].shape[batch_axis])
                    window = getattr(self.trainer, "_norm_window", None)
                    with _trace.span("train.drain", category="train",
                                     pending=(len(window)
                                              if window is not None
                                              else 0)):
                        pass
                finally:
                    sp.__exit__(None, None, None)
            _dispatch("epoch_end")
            if epochs is None and batches is None:
                break
        _dispatch("train_end")
        return self

    def quantize(self, calib_data, calib_mode="entropy",
                 num_calib_batches=None, exclude_layers=None,
                 exclude_layers_match=None, logger=None):
        """Post-training calibration hook: calibrate the fitted net's
        activation ranges over ``calib_data`` (typically a slice of the
        validation loader) with the contrib.quantization observers
        ('naive' abs-max, 'entropy' KL, 'percentile') and return a new
        int8 network with Dense/Conv replaced by the fused quantized
        blocks. The original ``self.net`` is untouched; the result is
        also kept on ``self.quantized_net`` — the train -> calibrate ->
        serve pipeline of docs/PERFORMANCE.md "Low-bit inference"."""
        from ....contrib.quantization import quantize_net
        self.quantized_net = quantize_net(
            self.net, calib_data=calib_data, calib_mode=calib_mode,
            num_calib_batches=num_calib_batches,
            exclude_layers=exclude_layers,
            exclude_layers_match=exclude_layers_match, logger=logger)
        return self.quantized_net

    def evaluate(self, val_data, val_metrics=None, batch_axis=0):
        metrics = val_metrics or self.val_metrics or self.train_metrics
        for m in metrics:
            m.reset()
        for batch in val_data:
            _, label, pred, loss = self.batch_processor.evaluate_batch(
                self, batch, batch_axis)
            for m in metrics:
                # dispatch on the wrapped type for deferred metrics
                if isinstance(getattr(m, "_base", m), LossMetric):
                    m.update(None, loss)
                else:
                    m.update(label, pred)
        return metrics

"""Estimator event handlers (reference: gluon/contrib/estimator/
event_handler.py)."""
from __future__ import annotations

import logging
import os
import time

import numpy as onp


class EventHandler:
    """Common base (reference event_handler.py EventHandler); handlers
    may set ``priority`` — lower runs earlier within an event."""

    priority = 0


class TrainBegin(EventHandler):
    def train_begin(self, estimator, *args, **kwargs):
        pass


class TrainEnd(EventHandler):
    def train_end(self, estimator, *args, **kwargs):
        pass


class EpochBegin(EventHandler):
    def epoch_begin(self, estimator, *args, **kwargs):
        pass


class EpochEnd(EventHandler):
    def epoch_end(self, estimator, *args, **kwargs):
        pass


class BatchBegin(EventHandler):
    def batch_begin(self, estimator, *args, **kwargs):
        pass


class BatchEnd(EventHandler):
    def batch_end(self, estimator, *args, **kwargs):
        pass


class StoppingHandler(TrainBegin, BatchEnd, EpochEnd):
    """Stop on max epoch/batch (reference: event_handler.py StoppingHandler)."""

    def __init__(self, max_epoch=None, max_batch=None):
        self.max_epoch = max_epoch
        self.max_batch = max_batch
        self.current_batch = 0
        self.current_epoch = 0
        self.stop_training = False

    def train_begin(self, estimator, *args, **kwargs):
        self.current_batch = 0
        self.current_epoch = 0

    def batch_end(self, estimator, *args, **kwargs):
        self.current_batch += 1
        if self.max_batch and self.current_batch >= self.max_batch:
            self.stop_training = True

    def epoch_end(self, estimator, *args, **kwargs):
        self.current_epoch += 1
        if self.max_epoch and self.current_epoch >= self.max_epoch:
            self.stop_training = True


class MetricHandler(EpochBegin, BatchEnd):
    def __init__(self, metrics, priority=-1000):
        self.metrics = metrics or []
        self.priority = priority

    def epoch_begin(self, estimator, *args, **kwargs):
        for m in self.metrics:
            m.reset()

    def batch_end(self, estimator, *args, **kwargs):
        pred = kwargs.get("pred")
        label = kwargs.get("label")
        loss = kwargs.get("loss")
        for m in self.metrics:
            from ...metric import Loss as LossMetric
            # deferred wrappers (EvalMetric.defer) proxy a base metric;
            # dispatch on the wrapped type
            if isinstance(getattr(m, "_base", m), LossMetric):
                m.update(None, loss)
            else:
                m.update(label, pred)


class ValidationHandler(TrainBegin, BatchEnd, EpochEnd):
    """Periodic validation (reference: event_handler.py:160)."""

    def __init__(self, val_data, eval_fn, epoch_period=1, batch_period=None,
                 priority=-1000):
        self.val_data = val_data
        self.eval_fn = eval_fn
        self.epoch_period = epoch_period
        self.batch_period = batch_period
        self.current_batch = 0
        self.current_epoch = 0
        self.priority = priority

    def batch_end(self, estimator, *args, **kwargs):
        self.current_batch += 1
        if self.batch_period and self.current_batch % self.batch_period == 0:
            self.eval_fn(self.val_data)

    def epoch_end(self, estimator, *args, **kwargs):
        self.current_epoch += 1
        if self.epoch_period and self.current_epoch % self.epoch_period == 0:
            self.eval_fn(self.val_data)


class LoggingHandler(TrainBegin, TrainEnd, EpochBegin, EpochEnd, BatchEnd):
    def __init__(self, log_interval="epoch", metrics=None, priority=float("inf")):
        self.log_interval = log_interval
        self.metrics = metrics or []
        self.priority = priority
        self.batch_index = 0
        self.logger = logging.getLogger("estimator")

    def train_begin(self, estimator, *args, **kwargs):
        self.train_start = time.time()

    def train_end(self, estimator, *args, **kwargs):
        self.logger.info("training done in %.1fs",
                         time.time() - self.train_start)

    def batch_end(self, estimator, *args, **kwargs):
        self.batch_index += 1
        if isinstance(self.log_interval, int) and \
                self.batch_index % self.log_interval == 0:
            msg = " ".join(f"{n}={v:.4f}" for m in self.metrics
                           for n, v in m.get_name_value())
            self.logger.info("[batch %d] %s", self.batch_index, msg)

    def epoch_end(self, estimator, *args, **kwargs):
        msg = " ".join(f"{n}={v:.4f}" for m in self.metrics
                       for n, v in m.get_name_value())
        from .... import telemetry
        if telemetry.active():
            tele = telemetry.summary_line()
            if tele:
                msg = (msg + " | " if msg else "") + tele
        self.logger.info("[epoch end] %s", msg)


class TelemetryHandler(TrainBegin, BatchEnd, EpochEnd, TrainEnd):
    """Drives an ``mx.telemetry.TrainingTelemetry`` reporter over the fit
    loop: per-batch JSONL step records (with the first loss value when the
    fit loop passes one), an epoch marker per epoch, and the final run
    report (kept on ``self.run_report`` after training).  Constructing the
    reporter at ``train_begin`` enables the metrics registry, so adding
    this one handler turns on the whole observability layer for a run.

    priority inf: runs last within each event, after the optimizer step
    and metric updates it is reporting on."""

    def __init__(self, path=None, interval=None, run_id=None,
                 priority=float("inf")):
        self.path = path
        self.interval = interval
        self.run_id = run_id
        self.priority = priority
        self.reporter = None
        self.run_report = None
        self.current_epoch = 0

    def train_begin(self, estimator, *args, **kwargs):
        from .... import telemetry
        self.current_epoch = 0
        self.reporter = telemetry.TrainingTelemetry(
            path=self.path, interval=self.interval, run_id=self.run_id)

    def batch_end(self, estimator, *args, **kwargs):
        if self.reporter is None:
            return
        fields = {}
        loss = kwargs.get("loss")
        # only pay the device->host loss fetch on steps the reporter
        # will actually emit — it drops the field on every other step,
        # so fetching per batch stalled the pipeline for nothing
        if loss is not None and \
                (self.reporter._steps + 1) % self.reporter._interval == 0:
            if isinstance(loss, (list, tuple)):
                loss = loss[0] if loss else None
            try:
                fields["loss"] = float(
                    loss.mean().item() if getattr(loss, "ndim", 0) else loss)
            except (TypeError, ValueError):
                pass
        self.reporter.step(**fields)

    @staticmethod
    def _drain(estimator):
        # flush device-side accumulators (deferred grad norms) into the
        # registry before the numbers are read — the epoch boundary is
        # exactly where the sync-free step loop pays its host syncs
        trainer = getattr(estimator, "trainer", None)
        if trainer is not None and hasattr(trainer, "drain_telemetry"):
            trainer.drain_telemetry()

    def epoch_end(self, estimator, *args, **kwargs):
        self.current_epoch += 1
        self._drain(estimator)
        if self.reporter is not None:
            self.reporter.mark("epoch", epoch=self.current_epoch)

    def train_end(self, estimator, *args, **kwargs):
        self._drain(estimator)
        if self.reporter is not None:
            self.run_report = self.reporter.close()
            self.reporter = None


class CheckpointHandler(TrainBegin, BatchEnd, EpochEnd):
    """Periodic model+trainer checkpointing with best-metric tracking
    (reference: event_handler.py:336).

    Robustness beyond the reference: every file is written crash-atomically
    (Block.save_parameters / Trainer.save_states) and gets a ``.sha256``
    sidecar; ``resume_from_checkpoint=True`` restores the newest checkpoint
    whose checksum validates at ``train_begin``, falling back to older ones
    when a checkpoint is torn/corrupt (each rejection is counted in
    ``mx.fault.stats()`` as ``checkpoint.rejected``)."""

    #: every on-disk artifact a checkpoint prefix may own (data + sidecars)
    _SUFFIXES = (".params", ".params.npz", ".states",
                 ".params.sha256", ".params.npz.sha256", ".states.sha256")

    def __init__(self, model_dir, model_prefix="model", monitor=None,
                 verbose=0, save_best=False, mode="auto", epoch_period=1,
                 batch_period=None, max_checkpoints=5, resume_from_checkpoint=False):
        self.model_dir = model_dir
        self.model_prefix = model_prefix
        self.monitor = monitor
        self.save_best = save_best
        self.epoch_period = epoch_period
        self.batch_period = batch_period
        self.max_checkpoints = max_checkpoints
        self.resume_from_checkpoint = resume_from_checkpoint
        self.current_epoch = 0
        self.current_batch = 0
        self.best = -onp.inf if mode == "max" else onp.inf
        self.mode = mode
        self.saved = []
        os.makedirs(model_dir, exist_ok=True)

    def _save(self, estimator, tag):
        from .... import serialization
        prefix = os.path.join(self.model_dir, f"{self.model_prefix}-{tag}")
        estimator.net.save_parameters(prefix + ".params")
        if getattr(estimator, "trainer", None) is not None:
            estimator.trainer.save_states(prefix + ".states")
        for suffix in (".params", ".params.npz", ".states"):
            if os.path.exists(prefix + suffix):
                serialization.write_checksum(prefix + suffix)
        self.saved.append(prefix)
        while len(self.saved) > self.max_checkpoints:
            old = self.saved.pop(0)
            for suffix in self._SUFFIXES:
                try:
                    os.remove(old + suffix)
                except OSError:
                    pass

    def train_begin(self, estimator, *args, **kwargs):
        if self.resume_from_checkpoint:
            self._resume(estimator)

    def _epoch_checkpoints(self):
        """(epoch, prefix) for every epoch checkpoint on disk, newest
        first."""
        import re
        pat = re.compile(re.escape(self.model_prefix) + r"-epoch(\d+)\.params$")
        found = []
        for fn in os.listdir(self.model_dir):
            m = pat.match(fn)
            if m:
                found.append((int(m.group(1)),
                              os.path.join(self.model_dir, fn[:-7])))
        return sorted(found, reverse=True)

    def _resume(self, estimator):
        """Restore the newest checkpoint that validates; walk to older ones
        past any torn/corrupt file instead of dying on it."""
        from .... import fault as _fault
        logger = logging.getLogger("estimator")
        for epoch, prefix in self._epoch_checkpoints():
            try:
                estimator.net.load_parameters(prefix + ".params")
                states = prefix + ".states"
                if os.path.exists(states) and \
                        getattr(estimator, "trainer", None) is not None:
                    estimator.trainer.load_states(states)
            except Exception as e:  # noqa: BLE001 - any torn/corrupt artifact
                _fault.record("checkpoint.rejected")
                logger.warning("checkpoint %s rejected (%s); trying older",
                               prefix, e)
                continue
            self.current_epoch = epoch
            # cleanup rotation continues from what survives on disk
            self.saved = [p for _, p in
                          sorted(self._epoch_checkpoints())][-self.max_checkpoints:]
            _fault.record("checkpoint.resume")
            logger.info("resumed from %s (epoch %d)", prefix, epoch)
            return
        logger.info("resume requested but no valid checkpoint in %s",
                    self.model_dir)

    def batch_end(self, estimator, *args, **kwargs):
        self.current_batch += 1
        if self.batch_period and self.current_batch % self.batch_period == 0:
            self._save(estimator, f"batch{self.current_batch}")

    def epoch_end(self, estimator, *args, **kwargs):
        self.current_epoch += 1
        if self.epoch_period and self.current_epoch % self.epoch_period == 0:
            self._save(estimator, f"epoch{self.current_epoch}")
        if self.save_best and self.monitor is not None:
            _, value = self.monitor.get()
            better = value > self.best if self.mode == "max" \
                else value < self.best
            if better:
                self.best = value
                self._save(estimator, "best")


class EarlyStoppingHandler(TrainBegin, EpochEnd):
    """Reference: event_handler.py:614."""

    def __init__(self, monitor, min_delta=0, patience=0, mode="auto",
                 baseline=None):
        self.monitor = monitor
        self.min_delta = min_delta
        self.patience = patience
        self.mode = mode
        self.baseline = baseline
        self.wait = 0
        self.stop_training = False
        self.best = None

    def epoch_end(self, estimator, *args, **kwargs):
        _, value = self.monitor.get()
        if self.best is None:
            self.best = value
            return
        improved = (value > self.best + self.min_delta
                    if self.mode == "max"
                    else value < self.best - self.min_delta)
        if improved:
            self.best = value
            self.wait = 0
        else:
            self.wait += 1
            if self.wait >= self.patience:
                self.stop_training = True


class ResilienceHandler(TrainBegin, BatchEnd, EpochEnd, TrainEnd):
    """Preemption-safe elastic training for the fit loop (no reference
    analog — the reference's CheckpointHandler is epoch-granular and knows
    nothing about signals).

    - ``train_begin``: installs SIGTERM/SIGINT graceful-shutdown handlers,
      builds a ``mx.resilience.TrainState`` over ``estimator.net`` /
      ``estimator.trainer`` / the given ``loader``, and (with
      ``auto_restore``) restores an existing valid bundle so the run
      continues at the exact next batch; a torn bundle is rejected by its
      checksum and counted (``checkpoint.rejected``), never half-loaded.
    - ``batch_end`` (priority -1500: after GradientUpdateHandler's
      optimizer step at -2000, before metric/logging handlers): counts the
      completed step, then — when a preemption signal arrived or the
      ``resilience.preempt`` injection fires — saves the bundle and raises
      ``Preempted``.  The in-flight step has fully finished by then, so
      the bundle resumes with bitwise-identical remaining losses.
    - ``epoch_end``/``train_end``: epoch counter; signal-handler teardown.
    """

    def __init__(self, bundle_path, loader=None, auto_restore=True,
                 priority=-1500):
        self.bundle_path = bundle_path
        self.loader = loader
        self.auto_restore = auto_restore
        self.priority = priority
        self.state = None
        self.resumed = False

    def train_begin(self, estimator, *args, **kwargs):
        from .... import fault as _fault
        from .... import resilience
        resilience.clear_preempt()
        resilience.install_signal_handlers()
        self.state = resilience.TrainState(
            net=estimator.net,
            trainer=getattr(estimator, "trainer", None),
            loader=self.loader, path=self.bundle_path)
        self.resumed = False
        if self.auto_restore and self.state.exists():
            try:
                self.state.load()
                self.resumed = True
                logging.getLogger("estimator").info(
                    "resumed TrainState bundle %s (step %d)",
                    self.bundle_path, self.state.step)
            except Exception as e:  # noqa: BLE001 - torn/corrupt bundle
                _fault.record("checkpoint.rejected")
                logging.getLogger("estimator").warning(
                    "TrainState bundle %s rejected (%s); starting fresh",
                    self.bundle_path, e)

    def batch_end(self, estimator, *args, **kwargs):
        from .... import resilience
        self.state.step += 1
        if resilience.preempt_requested(step=self.state.step):
            path = self.state.save()
            resilience.uninstall_signal_handlers()
            raise resilience.Preempted(path=path, step=self.state.step,
                                       origin="preempt")

    def epoch_end(self, estimator, *args, **kwargs):
        if self.state is not None:
            self.state.epoch += 1

    def train_end(self, estimator, *args, **kwargs):
        from .... import resilience
        resilience.uninstall_signal_handlers()


class GradientUpdateHandler(BatchEnd):
    """Applies the optimizer step at batch end (reference
    event_handler.py:722; priority -2000 so it runs before metric and
    logging handlers that read the post-step state)."""

    def __init__(self, priority=-2000):
        self.priority = priority

    def batch_end(self, estimator, *args, **kwargs):
        # the data batch size, passed by the fit loop, is the correct
        # gradient normalizer (Trainer.step sets rescale_grad = 1/n);
        # loss shapes mislead for mean-reduced losses or batch_axis != 0
        batch_size = kwargs.get("num_samples")
        if not batch_size:
            loss = kwargs.get("loss", [])
            if not isinstance(loss, (list, tuple)):
                loss = [loss]
            batch_size = sum(
                (l.shape[0] if getattr(l, "ndim", 0) else 1) for l in loss)
        estimator.trainer.step(max(batch_size, 1))

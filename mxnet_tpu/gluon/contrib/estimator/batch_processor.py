"""Pluggable per-batch logic for the Estimator.

Reference parity: gluon/contrib/estimator/batch_processor.py:28
(BatchProcessor.fit_batch/evaluate_batch hooks so users override the
minibatch step without rewriting the fit loop). The reference splits
each batch across a ctx list; here one XLA program sees the whole batch
(shard with mx.parallel for multi-device), so the hooks take the batch
directly.
"""
from __future__ import annotations

from .... import autograd

__all__ = ["BatchProcessor"]


class BatchProcessor:
    """Default minibatch step; subclass and override to customize."""

    @staticmethod
    def _get_data_and_label(batch, batch_axis=0):  # noqa: ARG004
        return batch[0], batch[1]

    def fit_batch(self, estimator, train_batch, batch_axis=0):
        """Forward + backward on one batch; the optimizer step happens in
        GradientUpdateHandler at batch_end (reference ordering)."""
        data, label = self._get_data_and_label(train_batch, batch_axis)
        with autograd.record():
            pred = estimator.net(data)
            loss = estimator.loss(pred, label)
        loss.backward()
        return [data], [label], [pred], [loss]

    def evaluate_batch(self, estimator, val_batch, batch_axis=0):
        data, label = self._get_data_and_label(val_batch, batch_axis)
        pred = estimator.val_net(data)
        loss = estimator.val_loss(pred, label)
        return [data], [label], [pred], [loss]

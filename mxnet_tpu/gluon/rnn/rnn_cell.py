"""Recurrent cells.

Reference parity: python/mxnet/gluon/rnn/rnn_cell.py (RNNCell, LSTMCell,
GRUCell, SequentialRNNCell, DropoutCell, BidirectionalCell, ResidualCell).
Single-step math matches src/operator/rnn_impl.h; unroll is a python loop
eagerly and a traced loop under hybridize.
"""
from __future__ import annotations

from ... import numpy as _np
from ... import numpy_extension as npx
from ..block import HybridBlock
from ..parameter import Parameter


class RecurrentCell(HybridBlock):
    def __init__(self):
        super().__init__()
        self._modified = False

    def state_info(self, batch_size=0):
        raise NotImplementedError

    def begin_state(self, batch_size=0, func=_np.zeros, **kwargs):
        states = []
        for info in self.state_info(batch_size):
            states.append(func(info["shape"], **kwargs))
        return states

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None, valid_length=None):
        """Reference: rnn_cell.py BaseRNNCell.unroll."""
        axis = layout.find("T")
        batch = inputs.shape[layout.find("N")]
        if begin_state is None:
            begin_state = self.begin_state(batch)
        states = begin_state
        outputs = []
        for t in range(length):
            x = inputs[(slice(None),) * axis + (t,)]
            out, states = self(x, states)
            outputs.append(out)
        if merge_outputs is None or merge_outputs:
            outputs = _np.stack(outputs, axis=axis)
        if valid_length is not None:
            outputs = npx.sequence_mask(outputs, valid_length,
                                        use_sequence_length=True,
                                        axis=axis)
        return outputs, states

    def reset(self):
        pass


class _BaseCell(RecurrentCell):
    def __init__(self, hidden_size, ngates, input_size=0,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros"):
        super().__init__()
        self._hidden_size = hidden_size
        self._input_size = input_size
        ng = ngates
        self.i2h_weight = Parameter("i2h_weight",
                                    shape=(ng * hidden_size, input_size),
                                    init=i2h_weight_initializer,
                                    allow_deferred_init=True)
        self.h2h_weight = Parameter("h2h_weight",
                                    shape=(ng * hidden_size, hidden_size),
                                    init=h2h_weight_initializer,
                                    allow_deferred_init=True)
        self.i2h_bias = Parameter("i2h_bias", shape=(ng * hidden_size,),
                                  init=i2h_bias_initializer,
                                  allow_deferred_init=True)
        self.h2h_bias = Parameter("h2h_bias", shape=(ng * hidden_size,),
                                  init=h2h_bias_initializer,
                                  allow_deferred_init=True)
        self._ng = ng

    def _ensure(self, x):
        if not self.i2h_weight._shape_known():
            self.i2h_weight._finish_deferred_init(
                (self._ng * self._hidden_size, x.shape[-1]))
        for p in (self.h2h_weight, self.i2h_bias, self.h2h_bias):
            if p._data is None:
                p._finish_deferred_init()


class RNNCell(_BaseCell):
    def __init__(self, hidden_size, activation="tanh", input_size=0, **kwargs):
        super().__init__(hidden_size, 1, input_size, **kwargs)
        self._activation = activation

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size), "__layout__": "NC"}]

    def forward(self, x, states):
        self._ensure(x)
        h = states[0] if isinstance(states, (list, tuple)) else states
        out = _np.dot(x, self.i2h_weight.data().T) + self.i2h_bias.data() + \
            _np.dot(h, self.h2h_weight.data().T) + self.h2h_bias.data()
        out = npx.activation(out, act_type=self._activation)
        return out, [out]


class LSTMCell(_BaseCell):
    def __init__(self, hidden_size, input_size=0, **kwargs):
        super().__init__(hidden_size, 4, input_size, **kwargs)

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size), "__layout__": "NC"},
                {"shape": (batch_size, self._hidden_size), "__layout__": "NC"}]

    def forward(self, x, states):
        self._ensure(x)
        h, c = states
        gates = _np.dot(x, self.i2h_weight.data().T) + self.i2h_bias.data() + \
            _np.dot(h, self.h2h_weight.data().T) + self.h2h_bias.data()
        i, f, g, o = _np.split(gates, 4, axis=-1)
        i, f, o = npx.sigmoid(i), npx.sigmoid(f), npx.sigmoid(o)
        c_new = f * c + i * _np.tanh(g)
        h_new = o * _np.tanh(c_new)
        return h_new, [h_new, c_new]


class GRUCell(_BaseCell):
    def __init__(self, hidden_size, input_size=0, **kwargs):
        super().__init__(hidden_size, 3, input_size, **kwargs)

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size), "__layout__": "NC"}]

    def forward(self, x, states):
        self._ensure(x)
        h = states[0] if isinstance(states, (list, tuple)) else states
        i2h = _np.dot(x, self.i2h_weight.data().T) + self.i2h_bias.data()
        h2h = _np.dot(h, self.h2h_weight.data().T) + self.h2h_bias.data()
        i2h_r, i2h_z, i2h_n = _np.split(i2h, 3, axis=-1)
        h2h_r, h2h_z, h2h_n = _np.split(h2h, 3, axis=-1)
        r = npx.sigmoid(i2h_r + h2h_r)
        z = npx.sigmoid(i2h_z + h2h_z)
        n = _np.tanh(i2h_n + r * h2h_n)
        h_new = (1 - z) * n + z * h
        return h_new, [h_new]


class SequentialRNNCell(RecurrentCell):
    def __init__(self):
        super().__init__()
        self._cells = []

    def add(self, cell):
        self._cells.append(cell)
        self.register_child(cell, str(len(self._cells) - 1))

    def state_info(self, batch_size=0):
        return sum([c.state_info(batch_size) for c in self._cells], [])

    def forward(self, x, states):
        next_states = []
        p = 0
        for cell in self._cells:
            n = len(cell.state_info())
            x, st = cell(x, states[p:p + n])
            next_states.extend(st)
            p += n
        return x, next_states

    def __len__(self):
        return len(self._cells)

    def __getitem__(self, i):
        return self._cells[i]


class DropoutCell(RecurrentCell):
    def __init__(self, rate, axes=()):
        super().__init__()
        self._rate = rate
        self._axes = axes

    def state_info(self, batch_size=0):
        return []

    def forward(self, x, states):
        return npx.dropout(x, p=self._rate, axes=self._axes), states


class ModifierCell(RecurrentCell):
    """Base for cells that wrap another cell (reference: rnn_cell.py:893).
    The wrapped cell's parameters belong to the wrapper's scope."""

    def __init__(self, base_cell):
        super().__init__()
        base_cell._modified = True
        self.base_cell = base_cell

    def state_info(self, batch_size=0):
        return self.base_cell.state_info(batch_size)

    def begin_state(self, batch_size=0, func=_np.zeros, **kwargs):
        self.base_cell._modified = False
        begin = self.base_cell.begin_state(batch_size, func=func, **kwargs)
        self.base_cell._modified = True
        return begin

    def reset(self):
        self.base_cell.reset()


class ResidualCell(ModifierCell):
    def forward(self, x, states):
        out, states = self.base_cell(x, states)
        return out + x, states


class ZoneoutCell(ModifierCell):
    """Zoneout (Krueger 2016): stochastically keep the previous output /
    states instead of the new ones (reference: rnn_cell.py:935)."""

    def __init__(self, base_cell, zoneout_outputs=0.0, zoneout_states=0.0):
        super().__init__(base_cell)
        self._zo, self._zs = zoneout_outputs, zoneout_states
        self._prev = None

    def reset(self):
        super().reset()
        self._prev = None

    def forward(self, x, states):
        out, new_states = self.base_cell(x, states)
        from ... import autograd
        if autograd.is_training():
            # dropout(ones, p) is 0 with prob p else 1/(1-p); rescaling by
            # (1-p) recovers the reference's {0,1} bernoulli keep-mask
            def keep_mask(p, like):
                return npx.dropout(_np.ones_like(like), p=p) * (1 - p)
            if self._zo > 0:
                mask = keep_mask(self._zo, out)
                prev = self._prev if self._prev is not None \
                    else _np.zeros_like(out)
                out = mask * out + (1 - mask) * prev
            if self._zs > 0:
                masks = [keep_mask(self._zs, ns) for ns in new_states]
                new_states = [m * ns + (1 - m) * os for m, ns, os in
                              zip(masks, new_states, states)]
            self._prev = out
        return out, new_states


class VariationalDropoutCell(ModifierCell):
    """Variational dropout (Gal & Ghahramani 2015): ONE dropout mask per
    sequence, shared across time steps, separately for inputs / states /
    outputs (reference: rnn_cell.py:1110).

    The masks are drawn at the first step and cached until ``reset()`` —
    step the cell manually => call reset() between sequences, exactly like
    the reference.
    """

    def __init__(self, base_cell, drop_inputs=0., drop_states=0.,
                 drop_outputs=0.):
        super().__init__(base_cell)
        self.drop_inputs = drop_inputs
        self.drop_states = drop_states
        self.drop_outputs = drop_outputs
        self.drop_inputs_mask = None
        self.drop_states_mask = None
        self.drop_outputs_mask = None

    def reset(self):
        super().reset()
        self.drop_inputs_mask = None
        self.drop_states_mask = None
        self.drop_outputs_mask = None

    def forward(self, x, states):
        if self.drop_states and self.drop_states_mask is None:
            self.drop_states_mask = npx.dropout(
                _np.ones_like(states[0]), p=self.drop_states)
        if self.drop_inputs and self.drop_inputs_mask is None:
            self.drop_inputs_mask = npx.dropout(
                _np.ones_like(x), p=self.drop_inputs)
        if self.drop_states:
            states = [states[0] * self.drop_states_mask] + list(states[1:])
        if self.drop_inputs:
            x = x * self.drop_inputs_mask
        out, states = self.base_cell(x, states)
        if self.drop_outputs:
            if self.drop_outputs_mask is None:
                self.drop_outputs_mask = npx.dropout(
                    _np.ones_like(out), p=self.drop_outputs)
            out = out * self.drop_outputs_mask
        return out, states


class LSTMPCell(RecurrentCell):
    """LSTM with a recurrent projection layer r_t = W_hr h_t
    (Sak 2014, https://arxiv.org/abs/1402.1128; reference:
    rnn_cell.py:1284). States are [r (projected), c (cell)]."""

    def __init__(self, hidden_size, projection_size, input_size=0,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 h2r_weight_initializer=None, i2h_bias_initializer="zeros",
                 h2h_bias_initializer="zeros"):
        super().__init__()
        self._hidden_size = hidden_size
        self._projection_size = projection_size
        self.i2h_weight = Parameter("i2h_weight",
                                    shape=(4 * hidden_size, input_size),
                                    init=i2h_weight_initializer,
                                    allow_deferred_init=True)
        self.h2h_weight = Parameter("h2h_weight",
                                    shape=(4 * hidden_size, projection_size),
                                    init=h2h_weight_initializer,
                                    allow_deferred_init=True)
        self.h2r_weight = Parameter("h2r_weight",
                                    shape=(projection_size, hidden_size),
                                    init=h2r_weight_initializer,
                                    allow_deferred_init=True)
        self.i2h_bias = Parameter("i2h_bias", shape=(4 * hidden_size,),
                                  init=i2h_bias_initializer,
                                  allow_deferred_init=True)
        self.h2h_bias = Parameter("h2h_bias", shape=(4 * hidden_size,),
                                  init=h2h_bias_initializer,
                                  allow_deferred_init=True)

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._projection_size),
                 "__layout__": "NC"},
                {"shape": (batch_size, self._hidden_size), "__layout__": "NC"}]

    def forward(self, x, states):
        if not self.i2h_weight._shape_known():
            self.i2h_weight._finish_deferred_init(
                (4 * self._hidden_size, x.shape[-1]))
        for p in (self.h2h_weight, self.h2r_weight, self.i2h_bias,
                  self.h2h_bias):
            if p._data is None:
                p._finish_deferred_init()
        r, c = states
        gates = _np.dot(x, self.i2h_weight.data().T) + self.i2h_bias.data() + \
            _np.dot(r, self.h2h_weight.data().T) + self.h2h_bias.data()
        i, f, g, o = _np.split(gates, 4, axis=-1)
        i, f, o = npx.sigmoid(i), npx.sigmoid(f), npx.sigmoid(o)
        c_new = f * c + i * _np.tanh(g)
        h_new = o * _np.tanh(c_new)
        r_new = _np.dot(h_new, self.h2r_weight.data().T)
        return r_new, [r_new, c_new]


class BidirectionalCell(RecurrentCell):
    def __init__(self, l_cell, r_cell):
        super().__init__()
        self.l_cell = l_cell
        self.r_cell = r_cell

    def state_info(self, batch_size=0):
        return self.l_cell.state_info(batch_size) + \
            self.r_cell.state_info(batch_size)

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None, valid_length=None):
        axis = layout.find("T")
        batch = inputs.shape[layout.find("N")]
        if begin_state is None:
            begin_state = self.begin_state(batch)
        nl = len(self.l_cell.state_info())
        l_out, l_states = self.l_cell.unroll(
            length, inputs, begin_state[:nl], layout, True, valid_length)
        rev = npx.sequence_reverse(inputs.swapaxes(0, axis) if axis else inputs,
                                   valid_length, valid_length is not None)
        if axis:
            rev = rev.swapaxes(0, axis)
        r_out, r_states = self.r_cell.unroll(
            length, rev, begin_state[nl:], layout, True, valid_length)
        r_out = npx.sequence_reverse(r_out.swapaxes(0, axis) if axis else r_out,
                                     valid_length, valid_length is not None)
        if axis:
            r_out = r_out.swapaxes(0, axis)
        out = _np.concatenate([l_out, r_out], axis=-1)
        return out, l_states + r_states

    def forward(self, x, states):
        raise NotImplementedError("BidirectionalCell supports unroll() only")

"""Fused RNN layers.

Reference parity: python/mxnet/gluon/rnn/rnn_layer.py:241 (RNN/LSTM/GRU
dispatching to the fused npx.rnn op — the cuDNN path of
src/operator/rnn-inl.h:601-699). Parameters are stored per layer/direction
({l|r}{i}_{i2h,h2h}_{weight,bias}) like the reference, then packed into the
flat cuDNN-layout vector npx.rnn expects; on TPU the fused op is a lax.scan
the XLA compiler pipelines.
"""
from __future__ import annotations

from ... import numpy as _np
from ... import numpy_extension as npx
from ...base import MXNetError
from ..block import HybridBlock
from ..parameter import Parameter


class _RNNLayer(HybridBlock):
    def __init__(self, mode, hidden_size, num_layers, layout, dropout,
                 bidirectional, input_size=0,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 dtype="float32", use_sequence_length=False, **kwargs):
        super().__init__()
        assert layout in ("TNC", "NTC")
        self._mode = mode
        self._hidden_size = hidden_size
        self._num_layers = num_layers
        self._layout = layout
        self._dropout = dropout
        self._dir = 2 if bidirectional else 1
        self._input_size = input_size
        self._dtype = dtype
        self._use_sequence_length = use_sequence_length
        self._gates = {"rnn_relu": 1, "rnn_tanh": 1, "lstm": 4, "gru": 3}[mode]
        ng, nh = self._gates, hidden_size
        for i in range(num_layers):
            for j in ["l", "r"][:self._dir]:
                in_sz = input_size if i == 0 else hidden_size * self._dir
                setattr(self, f"{j}{i}_i2h_weight",
                        Parameter(f"{j}{i}_i2h_weight",
                                  shape=(ng * nh, in_sz if in_sz else 0),
                                  init=i2h_weight_initializer, dtype=dtype,
                                  allow_deferred_init=True))
                setattr(self, f"{j}{i}_h2h_weight",
                        Parameter(f"{j}{i}_h2h_weight", shape=(ng * nh, nh),
                                  init=h2h_weight_initializer, dtype=dtype,
                                  allow_deferred_init=True))
                setattr(self, f"{j}{i}_i2h_bias",
                        Parameter(f"{j}{i}_i2h_bias", shape=(ng * nh,),
                                  init=i2h_bias_initializer, dtype=dtype,
                                  allow_deferred_init=True))
                setattr(self, f"{j}{i}_h2h_bias",
                        Parameter(f"{j}{i}_h2h_bias", shape=(ng * nh,),
                                  init=h2h_bias_initializer, dtype=dtype,
                                  allow_deferred_init=True))

    def state_info(self, batch_size=0):
        raise NotImplementedError

    def begin_state(self, batch_size=0, func=_np.zeros, **kwargs):
        states = []
        for info in self.state_info(batch_size):
            states.append(func(info["shape"], **kwargs))
        return states

    def _ensure_params(self, x):
        in_sz = x.shape[-1]
        ng, nh = self._gates, self._hidden_size
        for i in range(self._num_layers):
            for j in ["l", "r"][:self._dir]:
                cur = in_sz if i == 0 else nh * self._dir
                w = getattr(self, f"{j}{i}_i2h_weight")
                if not w._shape_known():
                    w._finish_deferred_init((ng * nh, cur))
                for suffix in ("h2h_weight", "i2h_bias", "h2h_bias"):
                    p = getattr(self, f"{j}{i}_{suffix}")
                    if p._data is None:
                        p._finish_deferred_init()

    def _flat_params(self):
        ws, bs = [], []
        for i in range(self._num_layers):
            for j in ["l", "r"][:self._dir]:
                ws.append(getattr(self, f"{j}{i}_i2h_weight").data().reshape(-1))
                ws.append(getattr(self, f"{j}{i}_h2h_weight").data().reshape(-1))
                bs.append(getattr(self, f"{j}{i}_i2h_bias").data())
                bs.append(getattr(self, f"{j}{i}_h2h_bias").data())
        return _np.concatenate(ws + bs, axis=0)

    def forward(self, inputs, states=None, sequence_length=None):
        if self._layout == "NTC":
            inputs = inputs.swapaxes(0, 1)
        self._ensure_params(inputs)
        batch = inputs.shape[1]
        skip_states = states is None
        if states is None:
            states = self.begin_state(batch, dtype=inputs.dtype)
        if not isinstance(states, (list, tuple)):
            states = [states]
        params = self._flat_params()
        if self._mode == "lstm":
            out, h, c = npx.rnn(inputs, params, states[0], states[1],
                                mode=self._mode,
                                state_size=self._hidden_size,
                                num_layers=self._num_layers,
                                bidirectional=self._dir == 2,
                                p=self._dropout, state_outputs=True)
            new_states = [h, c]
        else:
            out, h = npx.rnn(inputs, params, states[0], mode=self._mode,
                             state_size=self._hidden_size,
                             num_layers=self._num_layers,
                             bidirectional=self._dir == 2,
                             p=self._dropout, state_outputs=True)
            new_states = [h]
        if self._layout == "NTC":
            out = out.swapaxes(0, 1)
        return out if skip_states else (out, new_states)

    def __repr__(self):
        return (f"{type(self).__name__}({self._hidden_size}, "
                f"num_layers={self._num_layers}, bidirectional={self._dir == 2})")


class RNN(_RNNLayer):
    """Reference: rnn_layer.py RNN (mode rnn_relu/rnn_tanh)."""

    def __init__(self, hidden_size, num_layers=1, activation="relu",
                 layout="TNC", dropout=0, bidirectional=False, input_size=0,
                 **kwargs):
        super().__init__(f"rnn_{activation}", hidden_size, num_layers, layout,
                         dropout, bidirectional, input_size, **kwargs)

    def state_info(self, batch_size=0):
        return [{"shape": (self._num_layers * self._dir, batch_size,
                           self._hidden_size), "__layout__": "LNC"}]


class LSTM(_RNNLayer):
    def __init__(self, hidden_size, num_layers=1, layout="TNC", dropout=0,
                 bidirectional=False, input_size=0, **kwargs):
        super().__init__("lstm", hidden_size, num_layers, layout, dropout,
                         bidirectional, input_size, **kwargs)

    def state_info(self, batch_size=0):
        shape = (self._num_layers * self._dir, batch_size, self._hidden_size)
        return [{"shape": shape, "__layout__": "LNC"},
                {"shape": shape, "__layout__": "LNC"}]


class GRU(_RNNLayer):
    def __init__(self, hidden_size, num_layers=1, layout="TNC", dropout=0,
                 bidirectional=False, input_size=0, **kwargs):
        super().__init__("gru", hidden_size, num_layers, layout, dropout,
                         bidirectional, input_size, **kwargs)

    def state_info(self, batch_size=0):
        return [{"shape": (self._num_layers * self._dir, batch_size,
                           self._hidden_size), "__layout__": "LNC"}]

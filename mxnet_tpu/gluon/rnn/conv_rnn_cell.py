"""Convolutional recurrent cells (ConvRNN / ConvLSTM / ConvGRU, 1D/2D/3D).

Reference parity: python/mxnet/gluon/rnn/conv_rnn_cell.py (the 9-class
Conv{1,2,3}D{RNN,LSTM,GRU}Cell family over src/operator/nn/convolution.cc).

TPU-native: the i2h/h2h convolutions lower to lax.conv_general_dilated
(MXU-tiled); gate math is the same jnp elementwise tail as the dense cells,
fused by XLA. h2h convs are constrained to odd kernels with SAME padding so
the state feature map keeps its spatial shape, exactly like the reference.
"""
from __future__ import annotations

from ... import numpy as _np
from ... import numpy_extension as npx
from ..parameter import Parameter
from .rnn_cell import RecurrentCell


def _tup(v, n):
    return (v,) * n if isinstance(v, int) else tuple(v)


def _conv_out_size(dims, kernel, pad, dilate):
    return tuple((d + 2 * p - (dl * (k - 1) + 1)) + 1
                 for d, k, p, dl in zip(dims, kernel, pad, dilate))


class _BaseConvRNNCell(RecurrentCell):
    """Shared conv-gate machinery (reference: conv_rnn_cell.py:41)."""

    _gate_names: tuple = ("",)

    def __init__(self, input_shape, hidden_channels, i2h_kernel, h2h_kernel,
                 i2h_pad, i2h_dilate, h2h_dilate, i2h_weight_initializer,
                 h2h_weight_initializer, i2h_bias_initializer,
                 h2h_bias_initializer, dims, conv_layout, activation):
        super().__init__()
        if conv_layout not in ("NCW", "NCHW", "NCDHW"):
            raise ValueError(f"unsupported conv_layout {conv_layout!r} "
                             "(channel-first only)")
        self._hidden_channels = hidden_channels
        self._input_shape = tuple(input_shape)     # (C, *spatial)
        self._conv_layout = conv_layout
        self._activation = activation
        self._dims = dims
        self._i2h_kernel = _tup(i2h_kernel, dims)
        self._i2h_pad = _tup(i2h_pad, dims)
        self._i2h_dilate = _tup(i2h_dilate, dims)
        self._h2h_kernel = _tup(h2h_kernel, dims)
        if any(k % 2 == 0 for k in self._h2h_kernel):
            raise ValueError(f"h2h_kernel must be odd, got {h2h_kernel}")
        self._h2h_dilate = _tup(h2h_dilate, dims)
        self._h2h_pad = tuple(d * (k - 1) // 2 for d, k in
                              zip(self._h2h_dilate, self._h2h_kernel))
        in_channels = self._input_shape[0]
        self._state_shape = (hidden_channels,) + _conv_out_size(
            self._input_shape[1:], self._i2h_kernel, self._i2h_pad,
            self._i2h_dilate)
        ng = len(self._gate_names)
        total = ng * hidden_channels
        self.i2h_weight = Parameter(
            "i2h_weight", shape=(total, in_channels) + self._i2h_kernel,
            init=i2h_weight_initializer, allow_deferred_init=True)
        self.h2h_weight = Parameter(
            "h2h_weight", shape=(total, hidden_channels) + self._h2h_kernel,
            init=h2h_weight_initializer, allow_deferred_init=True)
        self.i2h_bias = Parameter("i2h_bias", shape=(total,),
                                  init=i2h_bias_initializer,
                                  allow_deferred_init=True)
        self.h2h_bias = Parameter("h2h_bias", shape=(total,),
                                  init=h2h_bias_initializer,
                                  allow_deferred_init=True)

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size,) + self._state_shape,
                 "__layout__": self._conv_layout}
                for _ in range(self._n_states)]

    _n_states = 1

    def _ensure(self):
        for p in (self.i2h_weight, self.h2h_weight, self.i2h_bias,
                  self.h2h_bias):
            if p._data is None:
                p._finish_deferred_init()

    def _conv_forward(self, x, h):
        ng = len(self._gate_names)
        nf = ng * self._hidden_channels
        i2h = npx.convolution(x, self.i2h_weight.data(), self.i2h_bias.data(),
                              kernel=self._i2h_kernel, pad=self._i2h_pad,
                              dilate=self._i2h_dilate, num_filter=nf,
                              layout=self._conv_layout)
        h2h = npx.convolution(h, self.h2h_weight.data(), self.h2h_bias.data(),
                              kernel=self._h2h_kernel, pad=self._h2h_pad,
                              dilate=self._h2h_dilate, num_filter=nf,
                              layout=self._conv_layout)
        return i2h, h2h

    def __repr__(self):
        return (f"{type(self).__name__}({self._input_shape[0]} -> "
                f"{self._hidden_channels}, i2h_kernel={self._i2h_kernel})")


class _ConvRNNCell(_BaseConvRNNCell):
    _gate_names = ("",)

    def forward(self, x, states):
        self._ensure()
        i2h, h2h = self._conv_forward(x, states[0])
        out = npx.activation(i2h + h2h, act_type=self._activation)
        return out, [out]


class _ConvLSTMCell(_BaseConvRNNCell):
    _gate_names = ("_i", "_f", "_c", "_o")
    _n_states = 2

    def forward(self, x, states):
        self._ensure()
        h, c = states
        i2h, h2h = self._conv_forward(x, h)
        gates = i2h + h2h
        i, f, g, o = _np.split(gates, 4, axis=1)
        i, f, o = npx.sigmoid(i), npx.sigmoid(f), npx.sigmoid(o)
        g = npx.activation(g, act_type=self._activation)
        c_new = f * c + i * g
        h_new = o * npx.activation(c_new, act_type=self._activation)
        return h_new, [h_new, c_new]


class _ConvGRUCell(_BaseConvRNNCell):
    _gate_names = ("_r", "_z", "_o")

    def forward(self, x, states):
        self._ensure()
        h = states[0]
        i2h, h2h = self._conv_forward(x, h)
        i2h_r, i2h_z, i2h_n = _np.split(i2h, 3, axis=1)
        h2h_r, h2h_z, h2h_n = _np.split(h2h, 3, axis=1)
        r = npx.sigmoid(i2h_r + h2h_r)
        z = npx.sigmoid(i2h_z + h2h_z)
        n = npx.activation(i2h_n + r * h2h_n, act_type=self._activation)
        h_new = (1 - z) * n + z * h
        return h_new, [h_new]


def _make_cell(base, name, dims, layout, doc):
    def __init__(self, input_shape, hidden_channels, i2h_kernel, h2h_kernel,
                 i2h_pad=0, i2h_dilate=1, h2h_dilate=1,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 conv_layout=layout, activation="tanh"):
        base.__init__(self, input_shape, hidden_channels, i2h_kernel,
                      h2h_kernel, i2h_pad, i2h_dilate, h2h_dilate,
                      i2h_weight_initializer, h2h_weight_initializer,
                      i2h_bias_initializer, h2h_bias_initializer, dims,
                      conv_layout, activation)
    cls = type(name, (base,), {"__init__": __init__, "__doc__": doc})
    return cls


Conv1DRNNCell = _make_cell(_ConvRNNCell, "Conv1DRNNCell", 1, "NCW",
                           "1D conv RNN cell (reference: conv_rnn_cell.py:222).")
Conv2DRNNCell = _make_cell(_ConvRNNCell, "Conv2DRNNCell", 2, "NCHW",
                           "2D conv RNN cell (reference: conv_rnn_cell.py:283).")
Conv3DRNNCell = _make_cell(_ConvRNNCell, "Conv3DRNNCell", 3, "NCDHW",
                           "3D conv RNN cell (reference: conv_rnn_cell.py:344).")
Conv1DLSTMCell = _make_cell(_ConvLSTMCell, "Conv1DLSTMCell", 1, "NCW",
                            "1D ConvLSTM (Shi 2015; reference: conv_rnn_cell.py:452).")
Conv2DLSTMCell = _make_cell(_ConvLSTMCell, "Conv2DLSTMCell", 2, "NCHW",
                            "2D ConvLSTM (Shi 2015; reference: conv_rnn_cell.py:523).")
Conv3DLSTMCell = _make_cell(_ConvLSTMCell, "Conv3DLSTMCell", 3, "NCDHW",
                            "3D ConvLSTM (Shi 2015; reference: conv_rnn_cell.py:594).")
Conv1DGRUCell = _make_cell(_ConvGRUCell, "Conv1DGRUCell", 1, "NCW",
                           "1D conv GRU cell (reference: conv_rnn_cell.py:714).")
Conv2DGRUCell = _make_cell(_ConvGRUCell, "Conv2DGRUCell", 2, "NCHW",
                           "2D conv GRU cell (reference: conv_rnn_cell.py:780).")
Conv3DGRUCell = _make_cell(_ConvGRUCell, "Conv3DGRUCell", 3, "NCDHW",
                           "3D conv GRU cell (reference: conv_rnn_cell.py:846).")

"""gluon.rnn (reference: python/mxnet/gluon/rnn/__init__.py)."""
from .rnn_cell import (  # noqa: F401
    RecurrentCell, RNNCell, LSTMCell, GRUCell, SequentialRNNCell, DropoutCell,
    ResidualCell, ZoneoutCell, BidirectionalCell,
)
from .rnn_layer import RNN, LSTM, GRU  # noqa: F401

"""gluon.rnn (reference: python/mxnet/gluon/rnn/__init__.py)."""
from .rnn_cell import (  # noqa: F401
    RecurrentCell, RNNCell, LSTMCell, GRUCell, SequentialRNNCell, DropoutCell,
    ModifierCell, ResidualCell, ZoneoutCell, BidirectionalCell,
    VariationalDropoutCell, LSTMPCell,
)
from .conv_rnn_cell import (  # noqa: F401
    Conv1DRNNCell, Conv2DRNNCell, Conv3DRNNCell,
    Conv1DLSTMCell, Conv2DLSTMCell, Conv3DLSTMCell,
    Conv1DGRUCell, Conv2DGRUCell, Conv3DGRUCell,
)
from .rnn_layer import RNN, LSTM, GRU  # noqa: F401

# 1.x names: with tracing-first cells, hybrid == regular (the reference
# split existed only because HybridRecurrentCell was the traceable base,
# gluon/rnn/rnn_cell.py HybridRecurrentCell/HybridSequentialRNNCell)
HybridRecurrentCell = RecurrentCell
HybridSequentialRNNCell = SequentialRNNCell

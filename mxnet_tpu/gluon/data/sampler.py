"""Samplers (reference: python/mxnet/gluon/data/sampler.py).

Elastic-training addition (docs/FAULT_TOLERANCE.md "Preemption & elastic
resume"): ``RandomSampler`` and ``BatchSampler`` carry ``state_dict()`` /
``load_state_dict()`` so a preempted run can resume at the exact next
batch of the interrupted epoch — the permutation is regenerated from the
recorded epoch seed and the already-consumed prefix is skipped.
"""
from __future__ import annotations

import numpy as onp


class Sampler:
    def __iter__(self):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError


class SequentialSampler(Sampler):
    def __init__(self, length, start=0):
        self._length = length
        self._start = start

    def __iter__(self):
        return iter(range(self._start, self._start + self._length))

    def __len__(self):
        return self._length


class RandomSampler(Sampler):
    """Shuffled indices; every epoch's permutation is drawn from a recorded
    per-epoch seed so it can be replayed bitwise on resume.

    ``seed=None`` (default) keeps the historical stochastic behavior (each
    epoch draws a fresh seed from numpy's global RNG) but still *records*
    the draw; a fixed ``seed`` makes epoch E's permutation a pure function
    of ``(seed, E)``.
    """

    def __init__(self, length, seed=None):
        self._length = length
        self._seed = seed
        self._epoch = 0          # epochs fully started (== index of next)
        self._epoch_seed = None  # seed of the most recently started epoch
        self._resume_seed = None

    def _draw_seed(self):
        if self._resume_seed is not None:
            s, self._resume_seed = self._resume_seed, None
            return s
        if self._seed is None:
            return int(onp.random.randint(0, 2 ** 31 - 1))
        return int(onp.random.SeedSequence(
            [int(self._seed), int(self._epoch)]).generate_state(1)[0])

    def __iter__(self):
        self._epoch_seed = self._draw_seed()
        self._epoch += 1
        indices = onp.random.RandomState(self._epoch_seed) \
            .permutation(self._length)
        return iter(indices.tolist())

    def __len__(self):
        return self._length

    def state_dict(self):
        """Replay info for the epoch currently being consumed (i.e. the
        most recent ``__iter__``)."""
        return {"epoch": self._epoch, "epoch_seed": self._epoch_seed,
                "seed": self._seed}

    def load_state_dict(self, state):
        self._epoch = max(0, int(state["epoch"]) - 1)
        self._resume_seed = state["epoch_seed"]


class FilterSampler(Sampler):
    def __init__(self, fn, dataset):
        self._indices = [i for i in range(len(dataset)) if fn(dataset[i])]

    def __iter__(self):
        return iter(self._indices)

    def __len__(self):
        return len(self._indices)


class BatchSampler(Sampler):
    """Reference: sampler.py BatchSampler (keep/discard/rollover).

    Mid-epoch resume: ``state_dict()`` records the batch cursor (set by the
    DataLoader to the number of batches actually *served* to the training
    loop, not merely generated into the prefetch queue), the rollover carry
    the epoch started with, and the inner sampler's epoch-replay state.
    After ``load_state_dict()`` the next ``__iter__`` regenerates the same
    epoch and skips the consumed prefix.
    """

    def __init__(self, sampler, batch_size, last_batch="keep"):
        self._sampler = sampler
        self._batch_size = batch_size
        self._last_batch = last_batch
        self._prev = []
        self._epoch_carry = []   # _prev as of the last epoch start (replay)
        self._cursor = 0         # batches generated this epoch
        self._resume = None

    def __iter__(self):
        skip = 0
        if self._resume is not None:
            skip = int(self._resume.get("cursor", 0))
            self._prev = list(self._resume.get("carry", []))
            self._resume = None
        self._epoch_carry = list(self._prev)
        self._cursor = 0
        batch, self._prev = self._prev, []

        def _emit(b):
            self._cursor += 1
            return self._cursor > skip

        for i in self._sampler:
            batch.append(i)
            if len(batch) == self._batch_size:
                if _emit(batch):
                    yield batch
                batch = []
        if batch:
            if self._last_batch == "keep":
                if _emit(batch):
                    yield batch
            elif self._last_batch == "discard":
                pass
            elif self._last_batch == "rollover":
                self._prev = batch
            else:
                raise ValueError(f"unknown last_batch {self._last_batch!r}")

    def resume_cursor(self):
        """Batches a pending resume will skip (0 when none is pending)."""
        return int(self._resume["cursor"]) if self._resume else 0

    def state_dict(self, cursor=None):
        inner = (self._sampler.state_dict()
                 if hasattr(self._sampler, "state_dict") else None)
        return {"cursor": self._cursor if cursor is None else int(cursor),
                "carry": list(self._epoch_carry), "sampler": inner}

    def load_state_dict(self, state):
        self._resume = {"cursor": int(state.get("cursor", 0)),
                        "carry": list(state.get("carry", []))}
        inner = state.get("sampler")
        if inner is not None:
            if not hasattr(self._sampler, "load_state_dict"):
                raise ValueError(
                    f"inner sampler {type(self._sampler).__name__} recorded "
                    "state but has no load_state_dict")
            self._sampler.load_state_dict(inner)

    def __len__(self):
        n = len(self._sampler)
        if self._last_batch == "keep":
            return (n + self._batch_size - 1) // self._batch_size
        if self._last_batch == "discard":
            return n // self._batch_size
        return (n + len(self._prev)) // self._batch_size


class IntervalSampler(Sampler):
    def __init__(self, length, interval, rollover=True):
        self._length = length
        self._interval = interval
        self._rollover = rollover

    def __iter__(self):
        for i in range(self._interval if self._rollover else 1):
            yield from range(i, self._length, self._interval)

    def __len__(self):
        return self._length

"""gluon.data.vision (reference: python/mxnet/gluon/data/vision/)."""
from . import transforms  # noqa: F401
from .datasets import (  # noqa: F401
    MNIST, FashionMNIST, CIFAR10, CIFAR100, ImageRecordDataset,
    ImageFolderDataset, ImageListDataset,
)

"""Vision transforms.

Reference parity: python/mxnet/gluon/data/vision/transforms/ (ToTensor,
Normalize, Resize, CenterCrop, RandomResizedCrop, flips, color jitter,
Cast, Compose) — each forwards to the ``npx.image.*`` operator namespace
(reference: transforms/image.py calling npx.image.to_tensor etc. over
src/operator/image/), which runs batched device kernels.  Transforms
accept HWC (single image) or NHWC (batch) input.
"""
from __future__ import annotations

import numpy as onp

from .... import numpy_extension as npx
from ...block import Block, HybridBlock
from ...nn import Sequential


class Compose(Sequential):
    """Reference: transforms Compose."""

    def __init__(self, transforms):
        super().__init__()
        self.add(*transforms)


class Cast(HybridBlock):
    def __init__(self, dtype="float32"):
        super().__init__()
        self._dtype = dtype

    def forward(self, x):
        return x.astype(self._dtype)


class ToTensor(HybridBlock):
    """HWC uint8 [0,255] -> CHW float32 [0,1] (reference: ToTensor over
    _image_to_tensor)."""

    def forward(self, x):
        return npx.image.to_tensor(x)


class Normalize(HybridBlock):
    """Channel-wise normalization on CHW/NCHW input (reference: Normalize
    over _image_normalize)."""

    def __init__(self, mean=0.0, std=1.0):
        super().__init__()
        self._mean = onp.asarray(mean, dtype=onp.float32)
        self._std = onp.asarray(std, dtype=onp.float32)

    def forward(self, x):
        return npx.image.normalize(x, self._mean, self._std)


class Resize(Block):
    """Reference: transforms Resize over _image_resize."""

    def __init__(self, size, keep_ratio=False, interpolation=1):
        super().__init__()
        self._size = size
        self._keep = keep_ratio
        self._interp = interpolation

    def forward(self, x):
        return npx.image.resize(x, self._size, self._keep, self._interp)


class CenterCrop(Block):
    """Reference: transforms CenterCrop — random_crop at fixed fractional
    position (0.5, 0.5), upsampling if the source is smaller."""

    def __init__(self, size, interpolation=1):
        super().__init__()
        self._size = (size, size) if isinstance(size, int) else tuple(size)
        self._interp = interpolation

    def forward(self, x):
        return npx.image.random_crop(x, (0.5, 0.5), (0.5, 0.5),
                                     width=self._size[0],
                                     height=self._size[1],
                                     interp=self._interp)


class RandomCrop(Block):
    """Reference: transforms RandomCrop (optional zero padding first)."""

    def __init__(self, size, pad=None, pad_value=0, interpolation=1):
        super().__init__()
        self._size = (size, size) if isinstance(size, int) else tuple(size)
        self._interp = interpolation
        self._pad = pad
        self._pad_value = pad_value

    def forward(self, x):
        if self._pad:
            from .... import numpy as _np
            p = self._pad
            pw = ((p, p), (p, p), (0, 0)) if isinstance(p, int) else p
            if x.ndim == 4:
                pw = ((0, 0),) + tuple(pw)
            x = _np.pad(x, pw, mode="constant",
                        constant_values=self._pad_value)
        return npx.image.random_crop(x, (0, 1), (0, 1),
                                     width=self._size[0],
                                     height=self._size[1],
                                     interp=self._interp)


class RandomResizedCrop(Block):
    """Reference: transforms RandomResizedCrop over
    _image_random_resized_crop."""

    def __init__(self, size, scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3),
                 interpolation=1):
        super().__init__()
        self._size = (size, size) if isinstance(size, int) else tuple(size)
        self._scale = scale
        self._ratio = ratio
        self._interp = interpolation

    def forward(self, x):
        return npx.image.random_resized_crop(
            x, width=self._size[0], height=self._size[1], area=self._scale,
            ratio=self._ratio, interp=self._interp)


class RandomFlipLeftRight(Block):
    def forward(self, x):
        return npx.image.random_flip_left_right(x)


class RandomFlipTopBottom(Block):
    def forward(self, x):
        return npx.image.random_flip_top_bottom(x)


class RandomBrightness(Block):
    def __init__(self, brightness):
        super().__init__()
        self._b = brightness

    def forward(self, x):
        return npx.image.random_brightness(x, max(0.0, 1 - self._b),
                                           1 + self._b)


class RandomContrast(Block):
    def __init__(self, contrast):
        super().__init__()
        self._c = contrast

    def forward(self, x):
        return npx.image.random_contrast(x, max(0.0, 1 - self._c),
                                         1 + self._c)


class RandomSaturation(Block):
    def __init__(self, saturation):
        super().__init__()
        self._s = saturation

    def forward(self, x):
        return npx.image.random_saturation(x, max(0.0, 1 - self._s),
                                           1 + self._s)


class RandomHue(Block):
    def __init__(self, hue):
        super().__init__()
        self._h = hue

    def forward(self, x):
        return npx.image.random_hue(x, max(0.0, 1 - self._h), 1 + self._h)


class RandomColorJitter(Block):
    def __init__(self, brightness=0, contrast=0, saturation=0, hue=0):
        super().__init__()
        self._args = (brightness, contrast, saturation, hue)

    def forward(self, x):
        return npx.image.random_color_jitter(x, *self._args)


class RandomLighting(Block):
    def __init__(self, alpha):
        super().__init__()
        self._alpha = alpha

    def forward(self, x):
        return npx.image.random_lighting(x, self._alpha)

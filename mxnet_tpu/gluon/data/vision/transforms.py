"""Vision transforms.

Reference parity: python/mxnet/gluon/data/vision/transforms/ (ToTensor,
Normalize, Resize, CenterCrop, RandomResizedCrop, RandomFlipLeftRight, Cast,
Compose). Transforms are Blocks operating on HWC uint8/float arrays.
"""
from __future__ import annotations

import numpy as onp

from .... import numpy as _np
from ....numpy.multiarray import ndarray
from ...block import Block, HybridBlock
from ...nn import Sequential


class Compose(Sequential):
    """Reference: transforms Compose."""

    def __init__(self, transforms):
        super().__init__()
        self.add(*transforms)


class Cast(HybridBlock):
    def __init__(self, dtype="float32"):
        super().__init__()
        self._dtype = dtype

    def forward(self, x):
        return x.astype(self._dtype)


class ToTensor(HybridBlock):
    """HWC uint8 [0,255] -> CHW float32 [0,1] (reference: ToTensor)."""

    def forward(self, x):
        x = x.astype("float32") / 255.0
        if x.ndim == 3:
            return x.transpose(2, 0, 1)
        return x.transpose(0, 3, 1, 2)


class Normalize(HybridBlock):
    def __init__(self, mean=0.0, std=1.0):
        super().__init__()
        self._mean = onp.asarray(mean, dtype=onp.float32)
        self._std = onp.asarray(std, dtype=onp.float32)

    def forward(self, x):
        mean = self._mean.reshape(-1, 1, 1) if self._mean.ndim else self._mean
        std = self._std.reshape(-1, 1, 1) if self._std.ndim else self._std
        return (x - _np.array(mean)) / _np.array(std)


class Resize(Block):
    """Bilinear resize HWC (reference: transforms Resize over image resize op)."""

    def __init__(self, size, keep_ratio=False, interpolation=1):
        super().__init__()
        self._size = (size, size) if isinstance(size, int) else tuple(size)

    def forward(self, x):
        import jax
        import jax.numpy as jnp
        raw = x._data if isinstance(x, ndarray) else jnp.asarray(x)
        h, w = self._size[1], self._size[0]
        out = jax.image.resize(raw.astype(jnp.float32),
                               (h, w) + tuple(raw.shape[2:]), method="bilinear")
        from ....numpy.multiarray import _wrap
        return _wrap(out.astype(raw.dtype))


class CenterCrop(Block):
    def __init__(self, size, interpolation=1):
        super().__init__()
        self._size = (size, size) if isinstance(size, int) else tuple(size)

    def forward(self, x):
        w, h = self._size
        H, W = x.shape[0], x.shape[1]
        y0 = max((H - h) // 2, 0)
        x0 = max((W - w) // 2, 0)
        return x[y0:y0 + h, x0:x0 + w]


class RandomResizedCrop(Block):
    def __init__(self, size, scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3),
                 interpolation=1):
        super().__init__()
        self._size = (size, size) if isinstance(size, int) else tuple(size)
        self._scale = scale
        self._ratio = ratio

    def forward(self, x):
        H, W = x.shape[0], x.shape[1]
        area = H * W
        scale = onp.random.uniform(*self._scale)
        ratio = onp.random.uniform(*self._ratio)
        w = int(round((area * scale * ratio) ** 0.5))
        h = int(round((area * scale / ratio) ** 0.5))
        w, h = min(w, W), min(h, H)
        x0 = onp.random.randint(0, W - w + 1)
        y0 = onp.random.randint(0, H - h + 1)
        crop = x[y0:y0 + h, x0:x0 + w]
        return Resize(self._size).forward(crop)


class RandomFlipLeftRight(Block):
    def forward(self, x):
        if onp.random.rand() < 0.5:
            return x[:, ::-1]
        return x


class RandomFlipTopBottom(Block):
    def forward(self, x):
        if onp.random.rand() < 0.5:
            return x[::-1]
        return x


class RandomBrightness(Block):
    def __init__(self, brightness):
        super().__init__()
        self._b = brightness

    def forward(self, x):
        f = 1.0 + onp.random.uniform(-self._b, self._b)
        return (x.astype("float32") * f).clip(0, 255).astype(x.dtype)


class RandomContrast(Block):
    def __init__(self, contrast):
        super().__init__()
        self._c = contrast

    def forward(self, x):
        f = 1.0 + onp.random.uniform(-self._c, self._c)
        xf = x.astype("float32")
        mean = xf.mean()
        return ((xf - mean) * f + mean).clip(0, 255).astype(x.dtype)

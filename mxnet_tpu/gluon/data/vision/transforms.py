"""Vision transforms.

Reference parity: python/mxnet/gluon/data/vision/transforms/ (ToTensor,
Normalize, Resize, CenterCrop, RandomResizedCrop, flips, color jitter,
Cast, Compose) — each forwards to the ``npx.image.*`` operator namespace
(reference: transforms/image.py calling npx.image.to_tensor etc. over
src/operator/image/), which runs batched device kernels.  Transforms
accept HWC (single image) or NHWC (batch) input.
"""
from __future__ import annotations

import numpy as onp

from .... import numpy_extension as npx
from ...block import Block, HybridBlock
from ...nn import HybridSequential, Sequential


class Compose(Sequential):
    """Reference: transforms Compose."""

    def __init__(self, transforms):
        super().__init__()
        self.add(*transforms)


class Cast(HybridBlock):
    def __init__(self, dtype="float32"):
        super().__init__()
        self._dtype = dtype

    def forward(self, x):
        return x.astype(self._dtype)


class ToTensor(HybridBlock):
    """HWC uint8 [0,255] -> CHW float32 [0,1] (reference: ToTensor over
    _image_to_tensor)."""

    def forward(self, x):
        return npx.image.to_tensor(x)


class Normalize(HybridBlock):
    """Channel-wise normalization on CHW/NCHW input (reference: Normalize
    over _image_normalize)."""

    def __init__(self, mean=0.0, std=1.0):
        super().__init__()
        self._mean = onp.asarray(mean, dtype=onp.float32)
        self._std = onp.asarray(std, dtype=onp.float32)

    def forward(self, x):
        return npx.image.normalize(x, self._mean, self._std)


class Resize(Block):
    """Reference: transforms Resize over _image_resize."""

    def __init__(self, size, keep_ratio=False, interpolation=1):
        super().__init__()
        self._size = size
        self._keep = keep_ratio
        self._interp = interpolation

    def forward(self, x):
        return npx.image.resize(x, self._size, self._keep, self._interp)


class CenterCrop(Block):
    """Reference: transforms CenterCrop — random_crop at fixed fractional
    position (0.5, 0.5), upsampling if the source is smaller."""

    def __init__(self, size, interpolation=1):
        super().__init__()
        self._size = (size, size) if isinstance(size, int) else tuple(size)
        self._interp = interpolation

    def forward(self, x):
        return npx.image.random_crop(x, (0.5, 0.5), (0.5, 0.5),
                                     width=self._size[0],
                                     height=self._size[1],
                                     interp=self._interp)


class RandomCrop(Block):
    """Reference: transforms RandomCrop (optional zero padding first)."""

    def __init__(self, size, pad=None, pad_value=0, interpolation=1):
        super().__init__()
        self._size = (size, size) if isinstance(size, int) else tuple(size)
        self._interp = interpolation
        self._pad = pad
        self._pad_value = pad_value

    def forward(self, x):
        if self._pad:
            from .... import numpy as _np
            p = self._pad
            pw = ((p, p), (p, p), (0, 0)) if isinstance(p, int) else p
            if x.ndim == 4:
                pw = ((0, 0),) + tuple(pw)
            x = _np.pad(x, pw, mode="constant",
                        constant_values=self._pad_value)
        return npx.image.random_crop(x, (0, 1), (0, 1),
                                     width=self._size[0],
                                     height=self._size[1],
                                     interp=self._interp)


class RandomResizedCrop(Block):
    """Reference: transforms RandomResizedCrop over
    _image_random_resized_crop."""

    def __init__(self, size, scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3),
                 interpolation=1):
        super().__init__()
        self._size = (size, size) if isinstance(size, int) else tuple(size)
        self._scale = scale
        self._ratio = ratio
        self._interp = interpolation

    def forward(self, x):
        return npx.image.random_resized_crop(
            x, width=self._size[0], height=self._size[1], area=self._scale,
            ratio=self._ratio, interp=self._interp)


class RandomFlipLeftRight(Block):
    def forward(self, x):
        return npx.image.random_flip_left_right(x)


class RandomFlipTopBottom(Block):
    def forward(self, x):
        return npx.image.random_flip_top_bottom(x)


class RandomBrightness(Block):
    def __init__(self, brightness):
        super().__init__()
        self._b = brightness

    def forward(self, x):
        return npx.image.random_brightness(x, max(0.0, 1 - self._b),
                                           1 + self._b)


class RandomContrast(Block):
    def __init__(self, contrast):
        super().__init__()
        self._c = contrast

    def forward(self, x):
        return npx.image.random_contrast(x, max(0.0, 1 - self._c),
                                         1 + self._c)


class RandomSaturation(Block):
    def __init__(self, saturation):
        super().__init__()
        self._s = saturation

    def forward(self, x):
        return npx.image.random_saturation(x, max(0.0, 1 - self._s),
                                           1 + self._s)


class RandomHue(Block):
    def __init__(self, hue):
        super().__init__()
        self._h = hue

    def forward(self, x):
        return npx.image.random_hue(x, max(0.0, 1 - self._h), 1 + self._h)


class RandomColorJitter(Block):
    def __init__(self, brightness=0, contrast=0, saturation=0, hue=0):
        super().__init__()
        self._args = (brightness, contrast, saturation, hue)

    def forward(self, x):
        return npx.image.random_color_jitter(x, *self._args)


class RandomLighting(Block):
    def __init__(self, alpha):
        super().__init__()
        self._alpha = alpha

    def forward(self, x):
        return npx.image.random_lighting(x, self._alpha)


class HybridCompose(HybridSequential):
    """Compose over hybridizable transforms; hybridizes immediately so the
    whole chain traces into one executable (reference:
    transforms/__init__.py:81)."""

    def __init__(self, transforms):
        super().__init__()
        for t in transforms:
            if not isinstance(t, HybridBlock):
                # a host-randomness Block would have its coin frozen
                # into the trace (reference raises the same way)
                raise ValueError(
                    f"HybridCompose requires HybridBlocks, got {type(t)}; "
                    "use Compose for host-random transforms")
        self.add(*transforms)
        self.hybridize()


class RandomApply(Block):
    """Apply `transforms` with probability `p` (host coin; reference:
    transforms/__init__.py:138 — a Sequential whose forward gates on
    random.random())."""

    def __init__(self, transforms, p=0.5):
        super().__init__()
        self.transforms = transforms
        self.p = p

    def forward(self, x):
        if self.p < onp.random.random():
            return x
        return self.transforms(x)


class HybridRandomApply(HybridBlock):
    """Traceable RandomApply: the coin is a traced draw and both branches
    are data-flow (np.where), so one compiled program covers apply and
    skip (reference: transforms/__init__.py:168 via npx.cond; on TPU a
    select is cheaper than real branching)."""

    def __init__(self, transforms, p=0.5):
        super().__init__()
        if not isinstance(transforms, HybridBlock):
            raise TypeError("HybridRandomApply requires a HybridBlock")
        self.transforms = transforms
        self.p = p

    def forward(self, x):
        from .... import numpy as _np
        from .... import random as _random
        coin = _random.uniform(0, 1, size=())
        return _np.where(coin < self.p, self.transforms(x), x)


class CropResize(HybridBlock):
    """Fixed crop then optional resize (reference: transforms/image.py:260
    over _npi.crop + image resize). HWC or NHWC."""

    def __init__(self, x, y, width, height, size=None, interpolation=None):
        super().__init__()
        self._x, self._y = x, y
        self._w, self._h = width, height
        self._size = (size, size) if isinstance(size, int) else size
        self._interp = 1 if interpolation is None else interpolation

    def forward(self, data):
        out = npx.image.crop(data, self._x, self._y, self._w, self._h)
        if self._size:
            out = npx.image.resize(out, self._size, False, self._interp)
        return out


class RandomGray(HybridBlock):
    """Convert to 3-channel luma with probability `p` (reference:
    transforms/image.py:664; that implementation's weight matrix
    broadcasts w_c * sum(RGB) — this build uses the intended BT.601
    luma replicated per channel)."""

    def __init__(self, p=0.5):
        super().__init__()
        self.p = p

    def forward(self, x):
        from .... import numpy as _np
        from .... import random as _random
        w = _np.array([0.2989, 0.5870, 0.1140], dtype="float32")
        xf = x.astype("float32")
        luma = (xf * w).sum(-1, keepdims=True)
        gray = _np.broadcast_to(luma, xf.shape)
        coin = _random.uniform(0, 1, size=())
        return _np.where(coin < self.p, gray, xf)


class Rotate(Block):
    """Rotate by a fixed angle, CHW/NCHW float32 (reference:
    transforms/image.py:144 over image.imrotate)."""

    def __init__(self, rotation_degrees, zoom_in=False, zoom_out=False):
        super().__init__()
        self._args = (rotation_degrees, zoom_in, zoom_out)

    def forward(self, x):
        from ....image import imrotate
        return imrotate(x, *self._args)


class RandomRotation(Block):
    """Rotate by a uniform random angle in `angle_limits` with
    probability `rotate_with_proba` (reference: transforms/image.py:175
    over image.random_rotate)."""

    def __init__(self, angle_limits, zoom_in=False, zoom_out=False,
                 rotate_with_proba=1.0):
        super().__init__()
        lower, upper = angle_limits
        if lower >= upper:
            raise ValueError("`angle_limits` must be an ordered tuple")
        if not 0 <= rotate_with_proba <= 1:
            raise ValueError("rotate_with_proba must be in [0, 1]")
        self._args = (angle_limits, zoom_in, zoom_out)
        self._proba = rotate_with_proba

    def forward(self, x):
        if onp.random.random() > self._proba:
            return x
        from ....image import random_rotate
        return random_rotate(x, *self._args)

"""Vision datasets.

Reference parity: python/mxnet/gluon/data/vision/datasets.py (MNIST,
FashionMNIST, CIFAR10/100, ImageRecordDataset, ImageFolderDataset).

This environment has no network egress: datasets load from local files when
present (same binary formats as the reference) and otherwise fall back to a
deterministic synthetic sample with the right shapes/dtypes so tutorials,
tests and convergence smoke-runs work offline.
"""
from __future__ import annotations

import gzip
import os
import struct

import numpy as onp

from .... import numpy as _np
from ....base import MXNetError
from ..dataset import Dataset


class _DownloadedDataset(Dataset):
    def __init__(self, root, train, transform):
        self._root = os.path.expanduser(root)
        self._train = train
        self._transform = transform
        self._data = None
        self._label = None
        self._get_data()

    def __getitem__(self, idx):
        if self._transform is not None:
            return self._transform(self._data[idx], self._label[idx])
        return self._data[idx], self._label[idx]

    def __len__(self):
        return len(self._label)


def _synthetic_images(n, shape, num_classes, seed):
    """Deterministic class-separable synthetic data: class k images have a
    distinct mean pattern, so small models actually converge on it."""
    rng = onp.random.RandomState(seed)
    labels = rng.randint(0, num_classes, size=n).astype(onp.int32)
    protos = rng.rand(num_classes, *shape).astype(onp.float32)
    imgs = protos[labels] * 160 + rng.rand(n, *shape).astype(onp.float32) * 95
    return imgs.astype(onp.uint8), labels


class MNIST(_DownloadedDataset):
    """Reference: datasets.py MNIST (idx-ubyte files)."""

    def __init__(self, root=os.path.join("~", ".mxnet", "datasets", "mnist"),
                 train=True, transform=None):
        self._namepair = (
            ("train-images-idx3-ubyte.gz", "train-labels-idx1-ubyte.gz")
            if train else
            ("t10k-images-idx3-ubyte.gz", "t10k-labels-idx1-ubyte.gz"))
        super().__init__(root, train, transform)

    def _get_data(self):
        img_path = os.path.join(self._root, self._namepair[0])
        lbl_path = os.path.join(self._root, self._namepair[1])
        if os.path.exists(img_path) and os.path.exists(lbl_path):
            with gzip.open(lbl_path, "rb") as f:
                struct.unpack(">II", f.read(8))
                label = onp.frombuffer(f.read(), dtype=onp.uint8) \
                    .astype(onp.int32)
            with gzip.open(img_path, "rb") as f:
                _, _, rows, cols = struct.unpack(">IIII", f.read(16))
                data = onp.frombuffer(f.read(), dtype=onp.uint8) \
                    .reshape(len(label), rows, cols, 1)
        else:
            n = 8192 if self._train else 1024
            data, label = _synthetic_images(n, (28, 28, 1), 10,
                                            seed=42 if self._train else 43)
        self._data = _np.array(data, dtype="uint8")
        self._label = label


class FashionMNIST(MNIST):
    def __init__(self, root=os.path.join("~", ".mxnet", "datasets",
                                         "fashion-mnist"), train=True,
                 transform=None):
        super().__init__(root, train, transform)


class CIFAR10(_DownloadedDataset):
    """Reference: datasets.py CIFAR10 (binary batches)."""

    _num_classes = 10

    def __init__(self, root=os.path.join("~", ".mxnet", "datasets", "cifar10"),
                 train=True, transform=None):
        super().__init__(root, train, transform)

    def _get_data(self):
        files = ([f"data_batch_{i}.bin" for i in range(1, 6)]
                 if self._train else ["test_batch.bin"])
        paths = [os.path.join(self._root, f) for f in files]
        if all(os.path.exists(p) for p in paths):
            data, label = [], []
            for p in paths:
                raw = onp.fromfile(p, dtype=onp.uint8).reshape(-1, 3073)
                label.append(raw[:, 0].astype(onp.int32))
                data.append(raw[:, 1:].reshape(-1, 3, 32, 32)
                            .transpose(0, 2, 3, 1))
            data = onp.concatenate(data)
            label = onp.concatenate(label)
        else:
            n = 8192 if self._train else 1024
            data, label = _synthetic_images(n, (32, 32, 3),
                                            self._num_classes,
                                            seed=44 if self._train else 45)
        self._data = _np.array(data, dtype="uint8")
        self._label = label


class CIFAR100(CIFAR10):
    _num_classes = 100

    def __init__(self, root=os.path.join("~", ".mxnet", "datasets",
                                         "cifar100"), fine_label=False,
                 train=True, transform=None):
        self._fine = fine_label
        super().__init__(root, train, transform)


class ImageRecordDataset(Dataset):
    """Reference: datasets.py ImageRecordDataset over RecordIO image packs."""

    def __init__(self, filename, flag=1, transform=None):
        from ..dataset import RecordFileDataset
        self._record = RecordFileDataset(filename)
        self._flag = flag
        self._transform = transform

    def __getitem__(self, idx):
        from ....image import imdecode
        from ....recordio import unpack
        record = self._record[idx]
        header, img_bytes = unpack(record)
        img = imdecode(img_bytes, flag=self._flag)
        label = _np.array(header.label)
        if self._transform is not None:
            return self._transform(img, label)
        return img, label

    def __len__(self):
        return len(self._record)


class ImageFolderDataset(Dataset):
    """Reference: datasets.py ImageFolderDataset (folder-per-class)."""

    def __init__(self, root, flag=1, transform=None):
        self._root = os.path.expanduser(root)
        self._flag = flag
        self._transform = transform
        self.synsets = []
        self.items = []
        for folder in sorted(os.listdir(self._root)):
            path = os.path.join(self._root, folder)
            if not os.path.isdir(path):
                continue
            label = len(self.synsets)
            self.synsets.append(folder)
            for fname in sorted(os.listdir(path)):
                if fname.lower().endswith((".jpg", ".jpeg", ".png", ".npy")):
                    self.items.append((os.path.join(path, fname), label))

    def __getitem__(self, idx):
        from ....image import imread
        path, label = self.items[idx]
        img = imread(path, self._flag)
        if self._transform is not None:
            return self._transform(img, label)
        return img, label

    def __len__(self):
        return len(self.items)


class ImageListDataset(Dataset):
    """Images named by a .lst file or an in-memory list.

    Reference: datasets.py:365 ImageListDataset — entries are either
    tab-separated ``index\\tlabel...\\trelpath`` lines (the im2rec .lst
    format, tools/im2rec.py) or ``[label, relpath]`` pairs; multi-value
    labels come back as float arrays, scalar labels as python floats.
    """

    def __init__(self, root=".", imglist=None, flag=1, transform=None):
        self._root = os.path.expanduser(root)
        self._flag = flag
        self._transform = transform
        self.items = []  # (relpath, label) in list order
        if isinstance(imglist, str):
            with open(os.path.join(self._root, imglist), "rt") as fin:
                for line in fin:
                    parts = line.strip().split("\t")
                    if len(parts) < 3:
                        raise MXNetError(
                            f"malformed .lst line (need idx\\tlabel\\t"
                            f"path): {line!r}")
                    label = [float(v) for v in parts[1:-1]]
                    self.items.append((parts[-1], label))
        else:
            for entry in imglist or []:
                label, path = entry[:-1], entry[-1]
                if len(label) == 1 and isinstance(label[0], (list, tuple)):
                    label = label[0]  # [[l0, l1], path] nested form
                self.items.append((path, [float(v) for v in label]))

    def __getitem__(self, idx):
        from ....image import imread
        relpath, label = self.items[idx]
        img = imread(os.path.join(self._root, relpath), self._flag)
        lab = label[0] if len(label) == 1 else onp.array(label, "float32")
        if self._transform is not None:
            return self._transform(img, lab)
        return img, lab

    def __len__(self):
        return len(self.items)

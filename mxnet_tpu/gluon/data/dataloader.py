"""gluon.data.DataLoader.

Reference parity: python/mxnet/gluon/data/dataloader.py (multiprocessing
workers + shared-memory NDArray pickling + prefetch queue; C++ alternative
src/io/dataloader.cc ThreadedDataLoader).

TPU-native design: worker processes/threads produce host numpy batches
(the shared-memory NDArray trick doesn't apply to device memory — SURVEY §7
hard parts); the main process converts the final batch to a device array, so
the host->HBM transfer is one contiguous copy per batch and can overlap with
compute thanks to async dispatch. num_workers>0 uses a thread pool (numpy
decode releases the GIL); a process pool is used when spawn-safe.
"""
from __future__ import annotations

import concurrent.futures as cf

import numpy as onp

from ... import numpy as _np
from ...numpy.multiarray import ndarray
from .sampler import BatchSampler, RandomSampler, SequentialSampler


def default_batchify_fn(data):
    """Stack samples (reference: dataloader.py default_batchify_fn).

    numpy samples assemble into a pooled host staging buffer
    (mx.storage, the cpu_pinned/CommCPU-merge-buffer analog) so repeated
    batches recycle one aligned block instead of re-mallocing."""
    if isinstance(data[0], ndarray):
        return _np.stack(data)
    if isinstance(data[0], (tuple, list)):
        return type(data[0])(default_batchify_fn(list(x)) for x in zip(*data))
    first = onp.asarray(data[0])
    if first.size and all(isinstance(d, onp.ndarray)
                          and d.shape == first.shape
                          and d.dtype == first.dtype for d in data):
        from ... import storage
        out = storage.pinned_array((len(data),) + first.shape, first.dtype)
        for i, d in enumerate(data):
            out[i] = d
        return _np.array(out)
    return _np.array(onp.asarray(data))


def default_mp_batchify_fn(data):
    return default_batchify_fn(data)


class DataLoader:
    """Reference: dataloader.py DataLoader."""

    def __init__(self, dataset, batch_size=None, shuffle=False, sampler=None,
                 last_batch=None, batch_sampler=None, batchify_fn=None,
                 num_workers=0, pin_memory=False, pin_device_id=0,
                 prefetch=None, thread_pool=True, timeout=120,
                 try_nopython=None):
        self._dataset = dataset
        self._pin_memory = pin_memory
        self._num_workers = max(0, num_workers)
        self._timeout = timeout
        if batch_sampler is None:
            if batch_size is None:
                raise ValueError("batch_size required when no batch_sampler")
            if sampler is None:
                sampler = (RandomSampler(len(dataset)) if shuffle
                           else SequentialSampler(len(dataset)))
            elif shuffle:
                raise ValueError("shuffle and sampler are mutually exclusive")
            batch_sampler = BatchSampler(sampler, batch_size,
                                         last_batch or "keep")
        self._batch_sampler = batch_sampler
        self._batchify_fn = batchify_fn or default_batchify_fn
        self._prefetch = max(0, prefetch if prefetch is not None
                             else 2 * self._num_workers)

    def _make_batch(self, indices):
        samples = [self._dataset[i] for i in indices]
        return self._batchify_fn(samples)

    def __iter__(self):
        if self._num_workers == 0:
            for indices in self._batch_sampler:
                yield self._make_batch(indices)
            return
        # thread-pool pipeline with bounded prefetch (the analog of
        # iter_prefetcher.h's threaded prefetch chain)
        with cf.ThreadPoolExecutor(self._num_workers) as pool:
            pending = []
            it = iter(self._batch_sampler)
            try:
                for _ in range(self._prefetch or self._num_workers):
                    pending.append(pool.submit(self._make_batch, next(it)))
            except StopIteration:
                pass
            while pending:
                fut = pending.pop(0)
                try:
                    pending.append(pool.submit(self._make_batch, next(it)))
                except StopIteration:
                    pass
                yield fut.result(timeout=self._timeout)

    def __len__(self):
        return len(self._batch_sampler)

"""gluon.data.DataLoader.

Reference parity: python/mxnet/gluon/data/dataloader.py (multiprocessing
workers + shared-memory NDArray pickling + prefetch queue; C++ alternative
src/io/dataloader.cc ThreadedDataLoader).

TPU-native design: worker processes/threads produce host numpy batches
(the shared-memory NDArray trick doesn't apply to device memory — SURVEY §7
hard parts); the main process converts the final batch to a device array, so
the host->HBM transfer is one contiguous copy per batch and can overlap with
compute thanks to async dispatch. num_workers>0 uses a thread pool (numpy
decode releases the GIL); a process pool is used when spawn-safe.
"""
from __future__ import annotations

import collections
import concurrent.futures as cf
import os
import time

import numpy as onp

from ... import config as _config
from ... import fault as _fault
from ... import numpy as _np
from ... import pipeline as _pipeline
from ... import telemetry as _telemetry
from ... import trace as _trace
from ...numpy.multiarray import ndarray
from .sampler import BatchSampler, RandomSampler, SequentialSampler


def default_batchify_fn(data):
    """Stack samples (reference: dataloader.py default_batchify_fn).

    numpy samples assemble into a pooled host staging buffer
    (mx.storage, the cpu_pinned/CommCPU-merge-buffer analog) so repeated
    batches recycle one aligned block instead of re-mallocing."""
    if isinstance(data[0], ndarray):
        return _np.stack(data)
    if isinstance(data[0], (tuple, list)):
        return type(data[0])(default_batchify_fn(list(x)) for x in zip(*data))
    first = onp.asarray(data[0])
    if first.size and all(isinstance(d, onp.ndarray)
                          and d.shape == first.shape
                          and d.dtype == first.dtype for d in data):
        from ... import storage
        from ...numpy.multiarray import _wrap
        import jax
        import jax.numpy as jnp
        out = storage.pinned_array((len(data),) + first.shape, first.dtype)
        for i, d in enumerate(data):
            out[i] = d
        # on a CPU backend jnp.asarray zero-copies the aligned pooled
        # block; the pool then recycles it under the live device array and
        # later batches overwrite earlier ones. Force a real copy there.
        # On an accelerator the host->HBM transfer already copies, and the
        # pooled staging block is exactly what we want to hand it.
        if jax.default_backend() == "cpu":
            return _wrap(jnp.array(out, copy=True))
        return _np.array(out)
    return _np.array(onp.asarray(data))


def default_mp_batchify_fn(data):
    """Worker-process batchify: stacks to HOST numpy (reference:
    dataloader.py:55 builds NDArrays in shared memory; device buffers
    cannot cross a process boundary, so workers stay numpy and the main
    process does the one host->HBM copy per batch)."""
    if isinstance(data[0], ndarray):
        data = [d.asnumpy() for d in data]
    if isinstance(data[0], (tuple, list)):
        return type(data[0])(
            default_mp_batchify_fn(list(x)) for x in zip(*data))
    return onp.stack([onp.asarray(d) for d in data])


# ---------------------------------------------------------------------------
# multiprocess workers (reference: dataloader.py:28-187 worker_loop +
# ConnectionWrapper + shared-memory NDArray rebuild over
# src/storage/cpu_shared_storage_manager.h). Transport here is
# multiprocessing.shared_memory: the worker packs ALL leaves of a batch
# into ONE shm segment at 64-byte-aligned offsets and ships a single
# ("pack", name, tree, alloc, created) spec whose tree leaves carry
# (shape, dtype, offset); the main process copies each leaf out into a
# device array.  One grant/attach/give_back per BATCH instead of per
# leaf — the per-leaf segment churn (and its per-leaf pool round trips)
# made process workers 0.25x thread throughput in BENCH_r05.  With the
# dataloader.shm_ring knob (default on) segments are pooled and reused
# across batches; otherwise each segment is unlinked after its one batch
# (the historical protocol).
# ---------------------------------------------------------------------------

_worker_state = {}


def _mp_worker_init(dataset, batchify):
    _worker_state["dataset"] = dataset
    _worker_state["batchify"] = batchify
    _worker_state["segs"] = {}  # name -> SharedMemory (attached handles)


def _grant_segment(nbytes, grants):
    """Pick a segment for one packed batch: best-fit from the parent's grant list
    (mutated: used grants are popped), else create a fresh power-of-2
    sized block — round sizes recur, so the parent's pool converges on a
    small set of reusable segments.  Attached handles are cached in
    ``_worker_state['segs']`` (LRU, bounded) so reuse costs zero
    open/mmap."""
    from multiprocessing import shared_memory
    segs = _worker_state.setdefault("segs", {})
    best = None
    for i, (name, size) in enumerate(grants):
        if size >= nbytes and (best is None or size < grants[best][1]):
            best = i
    if best is not None:
        name, size = grants.pop(best)
        shm = segs.get(name)
        if shm is None:
            try:
                shm = shared_memory.SharedMemory(name=name)
                segs[name] = shm
            except FileNotFoundError:  # parent retired it meanwhile
                shm = None
        if shm is not None:
            segs[name] = segs.pop(name)  # LRU touch
            return shm, name, size, False
    size = 1 << (max(nbytes, 1) - 1).bit_length()
    shm = shared_memory.SharedMemory(create=True, size=size)
    segs[shm.name] = shm
    while len(segs) > 64:  # stale handles accumulate only via retires
        segs.pop(next(iter(segs))).close()
    return shm, shm.name, size, True


#: leaf offsets inside a packed segment are cache-line aligned so the
#: consumer-side views copy at full memcpy speed
_PACK_ALIGN = 64


def _pack_layout(batch, leaves, offset):
    """Flatten ``batch`` into ``leaves`` ([(array, offset)], appended in
    tree order, offsets :data:`_PACK_ALIGN`-aligned) and return
    ``(tree, end)`` where the tree's leaves are ("leaf", shape, dtype,
    offset) and ``end`` is the packed payload size so far."""
    if isinstance(batch, (tuple, list)):
        parts = []
        for b in batch:
            sub, offset = _pack_layout(b, leaves, offset)
            parts.append(sub)
        return (type(batch).__name__, parts), offset
    a = onp.ascontiguousarray(onp.asarray(batch))
    offset = -(-offset // _PACK_ALIGN) * _PACK_ALIGN
    leaves.append((a, offset))
    return ("leaf", a.shape, str(a.dtype), offset), offset + a.nbytes


def _to_shm(batch, grants=None):
    """Serialize one batch into a SINGLE packed shm segment (all leaves
    at aligned offsets behind one header) so the whole batch costs one
    grant/attach/give_back round trip.  ``grants`` is the mutable list of
    (name, size) segments the parent loaned this task (ring mode; used
    grants are popped); None means a one-shot segment the parent will
    unlink after copying."""
    from multiprocessing import shared_memory
    leaves = []
    tree, total = _pack_layout(batch, leaves, 0)
    total = max(total, 1)
    if grants is None:
        shm = shared_memory.SharedMemory(create=True, size=total)
        name, size, created = shm.name, total, True
    else:
        shm, name, size, created = _grant_segment(total, grants)
    for a, off in leaves:
        onp.ndarray(a.shape, a.dtype, buffer=shm.buf, offset=off)[...] = a
    if grants is None:
        shm.close()
    return ("pack", name, tree, size, created)


def _mp_worker_task(indices, fault_step=0, grants=None, trace_ctx=None):
    # fault hooks (armed via MXNET_FAULT_SPEC, inherited by the spawned
    # worker's environment): crash = hard death with no cleanup, the
    # failure a preempted/OOM-killed worker produces; hang = the worker
    # stops producing, which the parent's heartbeat deadline must catch.
    # fault_step is the parent's global task sequence, so at=N fires
    # deterministically regardless of which worker runs the task.
    if _fault._active:
        if _fault.fire("dataloader.worker_crash", step=fault_step):
            os._exit(117)
        if _fault.fire("dataloader.worker_hang", step=fault_step):
            time.sleep(3600)
    # trace_ctx is the consumer's (trace_id, span_id): spans built here
    # ride the result tuple back and land on the parent's timeline (the
    # trace clock is CLOCK_MONOTONIC, system-wide on Linux)
    t0u = _trace.clock_us() if trace_ctx is not None else 0
    ds, bf = _worker_state["dataset"], _worker_state["batchify"]
    grants = list(grants) if grants is not None else None
    fetch = getattr(ds, "sample_batch", None)
    samples = (fetch(indices) if fetch is not None
               else [ds[i] for i in indices])
    spec = _to_shm(bf(samples), grants)
    spans = []
    if trace_ctx is not None:
        spans.append(_trace.make_span(
            "dataloader.worker_batch", t0u, _trace.clock_us() - t0u,
            tuple(trace_ctx), category="dataloader",
            samples=len(indices), task_seq=fault_step,
            worker_pid=os.getpid()))
    # leftover grants ride back so the parent can return them to the pool
    return (grants or [], spec, spans)


class _ShmRing:
    """Parent-side pool of reusable SharedMemory segments.

    Ownership protocol (overwrite-safe by construction): a segment name
    lives in exactly one place at any time — the free pool, the grant
    list of one in-flight task, or one unconsumed result spec.
    ``grant()`` moves names out best-fit against the previous batch's
    packed-segment size; ``give_back()`` returns them after the device
    copy;
    pool overflow unlinks oldest-first (``dataloader.shm_ring_max``).
    Attached parent mappings are cached so a reused segment costs zero
    open/mmap on the copy side too.
    """

    def __init__(self, max_segments):
        self._free = []       # [(size, name)] insertion order
        self._attached = {}   # name -> SharedMemory
        self._max = max(1, int(max_segments))
        self.last_sizes = []  # packed segment bytes of the latest batch

    def grant(self):
        grants = []
        for want in self.last_sizes:
            best = None
            for i, (size, _name) in enumerate(self._free):
                if size >= want and (best is None
                                     or size < self._free[best][0]):
                    best = i
            if best is not None:
                size, name = self._free.pop(best)
                grants.append((name, size))
        return grants

    def attach(self, name):
        shm = self._attached.get(name)
        if shm is None:
            from multiprocessing import shared_memory
            shm = shared_memory.SharedMemory(name=name)
            self._attached[name] = shm
        return shm

    def give_back(self, name, size):
        self._free.append((size, name))
        while len(self._free) > self._max:
            self._retire(self._free.pop(0)[1])

    def _retire(self, name):
        from multiprocessing import shared_memory
        shm = self._attached.pop(name, None)
        if shm is None:
            try:
                shm = shared_memory.SharedMemory(name=name)
            except FileNotFoundError:
                return
        shm.close()
        try:
            shm.unlink()
        except FileNotFoundError:
            pass

    def close(self):
        """Unlink every pooled segment (DataLoader.close / __del__)."""
        while self._free:
            self._retire(self._free.pop()[1])
        for name in list(self._attached):
            self._retire(name)


def _free_shm(spec, ring=None):
    """Return a batch's packed shm segment without copying (abandoned
    iterator): back into the ring, or unlinked in one-shot mode."""
    from multiprocessing import shared_memory
    _, name, _tree, alloc, _created = spec
    if ring is not None:
        ring.give_back(name, alloc)
        return
    try:
        shm = shared_memory.SharedMemory(name=name)
        shm.close()
        shm.unlink()
    except FileNotFoundError:
        pass


def _unpack_tree(tree, buf):
    """Copy every leaf of a packed segment out of ``buf`` into device
    arrays, rebuilding the original tuple/list nesting."""
    if tree[0] == "leaf":
        _, shape, dtype, off = tree
        import jax.numpy as jnp
        from ...numpy.multiarray import _wrap
        view = onp.ndarray(shape, dtype, buffer=buf, offset=off)
        # copy=True is load-bearing: a CPU backend would otherwise
        # zero-copy the mapping, which the ring reuses underneath
        out = _wrap(jnp.array(view, copy=True))
        out._data.block_until_ready()  # transfer done before reuse
        return out
    kind, parts = tree
    seq = [_unpack_tree(p, buf) for p in parts]
    return tuple(seq) if kind == "tuple" else seq


def _from_shm(spec, ring=None, sizes=None):
    from multiprocessing import shared_memory
    _, name, tree, alloc, created = spec
    if ring is not None:
        shm = ring.attach(name)
        out = _unpack_tree(tree, shm.buf)
        if sizes is not None:
            sizes.append(alloc)
        ring.give_back(name, alloc)
        if _telemetry._active:
            _telemetry.inc("dataloader.shm_created_total" if created
                           else "dataloader.shm_reused_total")
    else:
        shm = shared_memory.SharedMemory(name=name)
        try:
            out = _unpack_tree(tree, shm.buf)
        finally:
            # ... the one-shot mapping instead dies right here
            shm.close()
            shm.unlink()
    return out


class DataLoader:
    """Reference: dataloader.py DataLoader."""

    def __init__(self, dataset, batch_size=None, shuffle=False, sampler=None,
                 last_batch=None, batch_sampler=None, batchify_fn=None,
                 num_workers=0, pin_memory=False, pin_device_id=0,
                 prefetch=None, thread_pool=None, timeout=120,
                 try_nopython=None, prefetch_to_device=None,
                 device_prefetch_depth=None):
        # prefetch_to_device: None/False = off (the historical behavior);
        # True = overlap host->device transfer with compute via
        # mx.pipeline.DevicePrefetcher against the default device; a
        # jax Device / Sharding (or per-leaf sequence) targets that
        # placement (sharded training passes the step's batch shardings).
        self._dataset = dataset
        self._pin_memory = pin_memory
        self._num_workers = max(0, num_workers)
        self._timeout = timeout
        if batch_sampler is None:
            if batch_size is None:
                raise ValueError("batch_size required when no batch_sampler")
            if sampler is None:
                sampler = (RandomSampler(len(dataset)) if shuffle
                           else SequentialSampler(len(dataset)))
            elif shuffle:
                raise ValueError("shuffle and sampler are mutually exclusive")
            batch_sampler = BatchSampler(sampler, batch_size,
                                         last_batch or "keep")
        self._batch_sampler = batch_sampler
        # thread_pool=None -> mode from mx.config dataloader.worker_mode
        # ('auto' probes the per-sample cost, see _resolve_worker_mode);
        # explicit True/False keeps the historical meaning
        self._thread_pool = thread_pool
        self._user_batchify = batchify_fn
        self._prefetch = max(0, prefetch if prefetch is not None
                             else 2 * self._num_workers)
        self._proc_pool = None
        self._worker_mode_cache = None
        self._force_threads = False   # set after repeated worker crashes
        self._task_seq = 0            # global task counter (fault at=N)
        self._served = 0              # batches handed to the training loop
        self._prefetch_to_device = prefetch_to_device
        self._device_prefetch_depth = device_prefetch_depth
        self._ring = None             # _ShmRing, built lazily by _mp_pump

    def _batchify(self, mp_mode):
        if self._user_batchify is not None:
            return self._user_batchify
        return default_mp_batchify_fn if mp_mode else default_batchify_fn

    # kept as an attribute for callers/tests that introspect the loader
    @property
    def _batchify_fn(self):
        return self._batchify(self._resolve_worker_mode() == "processes"
                              and self._num_workers > 0)

    def _resolve_worker_mode(self):
        """'threads' or 'processes' for num_workers>0.

        BENCH_r05 Weak #4: the shm transport makes process workers ~4x
        slower per batch than threads for anything that releases the GIL
        (numpy decode), while GIL-bound pure-python transforms only scale
        in processes.  'auto' (the default) probes the cost of one sample
        eagerly and picks processes only above
        mx.config dataloader.mp_threshold_ms; MXNET_DATALOADER_WORKER_MODE
        overrides.  Crash fallback: after dataloader.max_respawns worker
        pool deaths the loader degrades to threads permanently.
        """
        if self._force_threads:
            return "threads"
        if self._thread_pool is not None:
            return "threads" if self._thread_pool else "processes"
        mode = _config.get("dataloader.worker_mode")
        if mode in ("threads", "processes"):
            return mode
        if mode != "auto":
            raise ValueError(f"dataloader.worker_mode {mode!r} not in "
                             "('auto', 'threads', 'processes')")
        if self._worker_mode_cache is None:
            n = min(len(self._dataset), 3)
            if n == 0:
                self._worker_mode_cache = "threads"
            else:
                t0 = time.perf_counter()
                for i in range(n):
                    self._dataset[i]
                per_ms = (time.perf_counter() - t0) * 1000.0 / n
                self._worker_mode_cache = (
                    "processes"
                    if per_ms >= _config.get("dataloader.mp_threshold_ms")
                    else "threads")
        return self._worker_mode_cache

    def _make_batch(self, indices):
        # streaming sources (mx.stream.StreamDataset) fetch whole
        # batches: the corrupt-record skip policy must be able to shrink
        # a batch, which per-item __getitem__ cannot express
        fetch = getattr(self._dataset, "sample_batch", None)
        samples = (fetch(indices) if fetch is not None
                   else [self._dataset[i] for i in indices])
        return self._batchify(False)(samples)

    def _get_proc_pool(self):
        # persistent spawn pool (reference keeps its worker pool for the
        # loader lifetime, dataloader.py:520); spawn not fork — the parent
        # holds live PJRT/XLA state that must not be forked
        if self._proc_pool is None:
            import multiprocessing as mp
            self._proc_pool = cf.ProcessPoolExecutor(
                self._num_workers,
                mp_context=mp.get_context("spawn"),
                initializer=_mp_worker_init,
                initargs=(self._dataset, self._batchify(True)))
        return self._proc_pool

    def _kill_pool(self):
        """Tear down the worker pool hard: hung workers never exit on
        their own, so terminate before shutdown."""
        pool, self._proc_pool = self._proc_pool, None
        if pool is None:
            return
        for p in list(getattr(pool, "_processes", {}).values()):
            try:
                p.terminate()
            except Exception:  # noqa: BLE001 - already-dead workers
                pass
        pool.shutdown(wait=False, cancel_futures=True)

    def __iter__(self):
        # the served-batch cursor is what TrainState bundles record: with
        # prefetching workers, batches *generated* run ahead of batches the
        # training loop has actually consumed, and resume must continue at
        # the consumed position
        self._served = (self._batch_sampler.resume_cursor()
                        if hasattr(self._batch_sampler, "resume_cursor")
                        else 0)
        src = self._iter_impl()
        pf = None
        if self._prefetch_to_device not in (None, False):
            # the served counter stays on the *consumer* side of the
            # prefetcher: batches it has buffered but not yet handed out
            # are replayed after a resume, not skipped
            target = self._prefetch_to_device
            pf = src = _pipeline.DevicePrefetcher(
                src, shardings=None if target is True else target,
                depth=self._device_prefetch_depth)
        try:
            for batch in src:
                self._served += 1
                yield batch
        finally:
            if pf is not None:
                pf.close()

    def _iter_impl(self):
        if self._num_workers == 0:
            for indices in self._batch_sampler:
                yield self._make_batch(indices)
            return
        if self._resolve_worker_mode() == "threads":
            # thread-pool pipeline with bounded prefetch (the analog of
            # iter_prefetcher.h's threaded prefetch chain)
            with cf.ThreadPoolExecutor(self._num_workers) as pool:
                yield from self._pump(pool, self._make_batch, lambda r: r,
                                      iter(self._batch_sampler))
            return
        yield from self._mp_pump()

    # -- elastic resume (docs/FAULT_TOLERANCE.md "Preemption & elastic
    # resume"): the loader's position is {epoch replay state, batches
    # served}; restoring it makes the next iteration continue at the exact
    # next batch of the interrupted epoch ------------------------------------
    def state_dict(self):
        from ...base import MXNetError
        if not hasattr(self._batch_sampler, "state_dict"):
            raise MXNetError(
                f"batch_sampler {type(self._batch_sampler).__name__} has no "
                "state_dict; implement state_dict/load_state_dict to make "
                "this DataLoader resumable")
        return self._batch_sampler.state_dict(cursor=self._served)

    def load_state_dict(self, state):
        from ...base import MXNetError
        if not hasattr(self._batch_sampler, "load_state_dict"):
            raise MXNetError(
                f"batch_sampler {type(self._batch_sampler).__name__} has no "
                "load_state_dict; cannot resume this DataLoader")
        self._batch_sampler.load_state_dict(state)

    def publish_cursor(self, **kwargs):
        """Streaming passthrough: publish the sampler's cursor at the
        CONSUMED position (``self._served``) to the shared fleet dir —
        what a surviving host resumes a dead peer's shards from.  No-op
        for non-streaming samplers."""
        publish = getattr(self._batch_sampler, "publish_cursor", None)
        if publish is None:
            return None
        kwargs.setdefault("cursor", self._served)
        return publish(**kwargs)

    def take_over_host(self, dead_rank, **kwargs):
        """Streaming passthrough: adopt this host's share of a dead
        peer's unfinished shards (see StreamSampler.take_over_host)."""
        take = getattr(self._batch_sampler, "take_over_host", None)
        return take(dead_rank, **kwargs) if take is not None else 0

    def _pump(self, pool, task, unwrap, batches, dispose=None):
        pending = []
        it = iter(batches)
        try:
            try:
                for _ in range(self._prefetch or self._num_workers):
                    pending.append(pool.submit(task, next(it)))
            except StopIteration:
                pass
            while pending:
                fut = pending.pop(0)
                try:
                    pending.append(pool.submit(task, next(it)))
                except StopIteration:
                    pass
                if _telemetry._active:
                    # batch wait = how long the training loop starves on
                    # input; queue depth = prefetch headroom at that moment
                    _telemetry.set_gauge("dataloader.queue_depth",
                                         len(pending) + 1)
                    _t0 = time.perf_counter()
                    result = fut.result(timeout=self._timeout)
                    _telemetry.observe("dataloader.wait_seconds",
                                       time.perf_counter() - _t0)
                    _telemetry.inc("dataloader.batches_total")
                    yield unwrap(result)
                else:
                    yield unwrap(fut.result(timeout=self._timeout))
        finally:
            # abandoned mid-epoch (break / islice / GC): in-flight batches
            # carry shm blocks only _from_shm would unlink — drain them
            if dispose is not None:
                for fut in pending:
                    try:
                        dispose(fut.result(timeout=self._timeout))
                    except Exception:  # noqa: BLE001 - best-effort cleanup
                        pass

    def _mp_pump(self):
        """Process-worker pipeline with crash/hang recovery.

        Worker death (BrokenProcessPool) or a missed per-batch heartbeat
        deadline (``timeout``) tears the pool down and respawns it with
        exponential backoff, re-queueing every in-flight batch in order;
        after ``mx.config dataloader.max_respawns`` pool losses the loader
        degrades to threaded workers for the rest of its life (graceful
        degradation beats an unusable input pipeline).  Every recovery
        action is counted in ``mx.fault.stats()``.
        """
        from concurrent.futures.process import BrokenProcessPool
        max_respawns = _config.get("dataloader.max_respawns")
        backoff = _config.get("dataloader.respawn_backoff")
        depth = max(1, self._prefetch or self._num_workers)
        if self._ring is None and _config.get("dataloader.shm_ring"):
            self._ring = _ShmRing(_config.get("dataloader.shm_ring_max"))
        ring = self._ring
        todo = collections.deque(self._batch_sampler)
        inflight = collections.deque()  # (future, indices, grants), oldest 1st
        crashes = 0
        try:
            while todo or inflight:
                try:
                    pool = self._get_proc_pool()
                    while todo and len(inflight) < depth:
                        indices = todo.popleft()
                        self._task_seq += 1
                        grants = ring.grant() if ring is not None else None
                        try:
                            inflight.append(
                                (pool.submit(_mp_worker_task, indices,
                                             self._task_seq, grants,
                                             (_trace.current_context()
                                              if _trace._active
                                              else None)),
                                 indices, grants))
                        except BaseException:
                            todo.appendleft(indices)
                            if ring is not None:
                                for name, size in grants:
                                    ring.give_back(name, size)
                            raise
                    fut, _, _ = inflight[0]
                    if _telemetry._active:
                        _telemetry.set_gauge("dataloader.queue_depth",
                                             len(inflight))
                        _t0 = time.perf_counter()
                        leftover, spec, wspans = \
                            fut.result(timeout=self._timeout)
                        _telemetry.observe("dataloader.wait_seconds",
                                           time.perf_counter() - _t0)
                        _telemetry.inc("dataloader.batches_total")
                    else:
                        leftover, spec, wspans = \
                            fut.result(timeout=self._timeout)
                    if wspans and _trace._active:
                        _trace.ingest(wspans)
                    inflight.popleft()
                except (BrokenProcessPool, cf.BrokenExecutor,
                        cf.TimeoutError, TimeoutError):
                    crashes += 1
                    # kill BEFORE reclaiming grants: a hung-but-alive
                    # worker could otherwise write into a segment the
                    # ring has already re-granted to a new task
                    self._kill_pool()
                    self._requeue(todo, inflight, ring)
                    if crashes > max_respawns:
                        _fault.record("dataloader.fallback_threaded")
                        self._force_threads = True
                        yield from self._threaded_remainder(todo)
                        return
                    _fault.record("dataloader.worker_respawn")
                    if _telemetry._active:
                        _telemetry.inc("dataloader.respawn_total")
                    time.sleep(backoff * (2 ** (crashes - 1)))
                    continue
                if ring is not None:
                    for name, size in leftover:
                        ring.give_back(name, size)
                    sizes = []
                    batch = _from_shm(spec, ring, sizes)
                    ring.last_sizes = sizes
                else:
                    batch = _from_shm(spec)
                yield batch
        finally:
            for fut, _, grants in inflight:
                try:
                    leftover, spec, _wspans = \
                        fut.result(timeout=self._timeout)
                    if ring is not None:
                        for name, size in leftover:
                            ring.give_back(name, size)
                    _free_shm(spec, ring)
                # CancelledError: futures we killed the pool under on a
                # previous loop pass (it subclasses BaseException)
                except (Exception, cf.CancelledError):  # noqa: BLE001
                    # a timed-out worker may still be alive and writing
                    # into its granted segments: kill the pool first so
                    # the ring never re-grants a segment under a live
                    # writer (mirrors the crash path above)
                    self._kill_pool()
                    if ring is not None and grants:
                        for name, size in grants:
                            ring.give_back(name, size)

    @staticmethod
    def _requeue(todo, inflight, ring=None):
        """Move every in-flight batch back onto the queue in order; shm
        blocks of tasks that did complete go back to the ring / are
        unlinked (their results are recomputed — a failure-path-only
        cost), and unused grants of tasks that didn't are reclaimed.
        Caller must have torn the pool down first (see _mp_pump)."""
        for fut, _, grants in inflight:
            if fut.done() and not fut.cancelled() and \
                    fut.exception() is None:
                try:
                    leftover, spec, _wspans = fut.result()
                    if ring is not None:
                        for name, size in leftover:
                            ring.give_back(name, size)
                    _free_shm(spec, ring)
                    continue
                except Exception:  # noqa: BLE001 - best-effort cleanup
                    pass
            if ring is not None and grants:
                for name, size in grants:
                    ring.give_back(name, size)
        todo.extendleft(indices for _, indices, _ in reversed(inflight))
        inflight.clear()

    def _threaded_remainder(self, todo):
        """Finish the epoch on threads after the process pool was given
        up on; the host-numpy batchify keeps batch values identical."""
        with cf.ThreadPoolExecutor(self._num_workers) as pool:
            yield from self._pump(pool, self._make_batch, lambda r: r,
                                  todo)

    def close(self):
        """Release worker pool and pooled shm segments.  Idempotent; also
        run from __del__, but deterministic teardown (tests, epoch-bounded
        scripts) should call it explicitly — unlinking pooled segments at
        GC time races interpreter shutdown."""
        self._kill_pool()
        ring, self._ring = self._ring, None
        if ring is not None:
            ring.close()

    def __del__(self):
        try:
            self.close()
        except Exception:  # noqa: BLE001 - interpreter-shutdown races
            pass

    def __len__(self):
        return len(self._batch_sampler)


class _PyBenchDataset:
    """Picklable synthetic dataset with a deliberately GIL-bound python
    transform (bench: dataloader_pytransform row)."""

    def __init__(self, n=256, dim=2048):
        rs = onp.random.RandomState(0)
        self.x = rs.rand(n, dim).astype(onp.float32)

    def __len__(self):
        return len(self.x)

    def __getitem__(self, i):
        row = self.x[i]
        acc = 0.0
        for _ in range(5):           # ~1 ms of pure-python GIL-bound work
            for v in row[:2048:1]:
                acc += float(v) * 1.0000001
        return row * onp.float32(1.0 + 0.0 * acc)

"""gluon.data.DataLoader.

Reference parity: python/mxnet/gluon/data/dataloader.py (multiprocessing
workers + shared-memory NDArray pickling + prefetch queue; C++ alternative
src/io/dataloader.cc ThreadedDataLoader).

TPU-native design: worker processes/threads produce host numpy batches
(the shared-memory NDArray trick doesn't apply to device memory — SURVEY §7
hard parts); the main process converts the final batch to a device array, so
the host->HBM transfer is one contiguous copy per batch and can overlap with
compute thanks to async dispatch. num_workers>0 uses a thread pool (numpy
decode releases the GIL); a process pool is used when spawn-safe.
"""
from __future__ import annotations

import concurrent.futures as cf

import numpy as onp

from ... import numpy as _np
from ...numpy.multiarray import ndarray
from .sampler import BatchSampler, RandomSampler, SequentialSampler


def default_batchify_fn(data):
    """Stack samples (reference: dataloader.py default_batchify_fn).

    numpy samples assemble into a pooled host staging buffer
    (mx.storage, the cpu_pinned/CommCPU-merge-buffer analog) so repeated
    batches recycle one aligned block instead of re-mallocing."""
    if isinstance(data[0], ndarray):
        return _np.stack(data)
    if isinstance(data[0], (tuple, list)):
        return type(data[0])(default_batchify_fn(list(x)) for x in zip(*data))
    first = onp.asarray(data[0])
    if first.size and all(isinstance(d, onp.ndarray)
                          and d.shape == first.shape
                          and d.dtype == first.dtype for d in data):
        from ... import storage
        from ...numpy.multiarray import _wrap
        import jax
        import jax.numpy as jnp
        out = storage.pinned_array((len(data),) + first.shape, first.dtype)
        for i, d in enumerate(data):
            out[i] = d
        # on a CPU backend jnp.asarray zero-copies the aligned pooled
        # block; the pool then recycles it under the live device array and
        # later batches overwrite earlier ones. Force a real copy there.
        # On an accelerator the host->HBM transfer already copies, and the
        # pooled staging block is exactly what we want to hand it.
        if jax.default_backend() == "cpu":
            return _wrap(jnp.array(out, copy=True))
        return _np.array(out)
    return _np.array(onp.asarray(data))


def default_mp_batchify_fn(data):
    """Worker-process batchify: stacks to HOST numpy (reference:
    dataloader.py:55 builds NDArrays in shared memory; device buffers
    cannot cross a process boundary, so workers stay numpy and the main
    process does the one host->HBM copy per batch)."""
    if isinstance(data[0], ndarray):
        data = [d.asnumpy() for d in data]
    if isinstance(data[0], (tuple, list)):
        return type(data[0])(
            default_mp_batchify_fn(list(x)) for x in zip(*data))
    return onp.stack([onp.asarray(d) for d in data])


# ---------------------------------------------------------------------------
# multiprocess workers (reference: dataloader.py:28-187 worker_loop +
# ConnectionWrapper + shared-memory NDArray rebuild over
# src/storage/cpu_shared_storage_manager.h). Transport here is
# multiprocessing.shared_memory: the worker writes each batch leaf into a
# fresh shm block and ships (name, shape, dtype); the main process copies
# it into a device array and unlinks.
# ---------------------------------------------------------------------------

_worker_state = {}


def _mp_worker_init(dataset, batchify):
    _worker_state["dataset"] = dataset
    _worker_state["batchify"] = batchify


def _to_shm(batch):
    from multiprocessing import shared_memory
    if isinstance(batch, (tuple, list)):
        return (type(batch).__name__, [_to_shm(b) for b in batch])
    a = onp.ascontiguousarray(onp.asarray(batch))
    shm = shared_memory.SharedMemory(create=True, size=max(a.nbytes, 1))
    onp.ndarray(a.shape, a.dtype, buffer=shm.buf)[...] = a
    name = shm.name
    shm.close()
    return ("arr", name, a.shape, str(a.dtype))


def _mp_worker_task(indices):
    ds, bf = _worker_state["dataset"], _worker_state["batchify"]
    return _to_shm(bf([ds[i] for i in indices]))


def _free_shm(spec):
    """Unlink a batch's shm blocks without copying (abandoned iterator)."""
    from multiprocessing import shared_memory
    if spec[0] == "arr":
        try:
            shm = shared_memory.SharedMemory(name=spec[1])
            shm.close()
            shm.unlink()
        except FileNotFoundError:
            pass
        return
    for p in spec[1]:
        _free_shm(p)


def _from_shm(spec):
    from multiprocessing import shared_memory
    if spec[0] == "arr":
        _, name, shape, dtype = spec
        shm = shared_memory.SharedMemory(name=name)
        try:
            import jax.numpy as jnp
            from ...numpy.multiarray import _wrap
            view = onp.ndarray(shape, dtype, buffer=shm.buf)
            # copy=True is load-bearing: a CPU backend would otherwise
            # zero-copy the shm mapping, which is unmapped two lines down
            out = _wrap(jnp.array(view, copy=True))
            out._data.block_until_ready()  # transfer done before unmap
        finally:
            shm.close()
            shm.unlink()
        return out
    kind, parts = spec
    seq = [_from_shm(p) for p in parts]
    return tuple(seq) if kind == "tuple" else seq


class DataLoader:
    """Reference: dataloader.py DataLoader."""

    def __init__(self, dataset, batch_size=None, shuffle=False, sampler=None,
                 last_batch=None, batch_sampler=None, batchify_fn=None,
                 num_workers=0, pin_memory=False, pin_device_id=0,
                 prefetch=None, thread_pool=True, timeout=120,
                 try_nopython=None):
        self._dataset = dataset
        self._pin_memory = pin_memory
        self._num_workers = max(0, num_workers)
        self._timeout = timeout
        if batch_sampler is None:
            if batch_size is None:
                raise ValueError("batch_size required when no batch_sampler")
            if sampler is None:
                sampler = (RandomSampler(len(dataset)) if shuffle
                           else SequentialSampler(len(dataset)))
            elif shuffle:
                raise ValueError("shuffle and sampler are mutually exclusive")
            batch_sampler = BatchSampler(sampler, batch_size,
                                         last_batch or "keep")
        self._batch_sampler = batch_sampler
        self._thread_pool = thread_pool
        if batchify_fn is None:
            batchify_fn = (default_batchify_fn
                           if thread_pool or num_workers == 0
                           else default_mp_batchify_fn)
        self._batchify_fn = batchify_fn
        self._prefetch = max(0, prefetch if prefetch is not None
                             else 2 * self._num_workers)
        self._proc_pool = None

    def _make_batch(self, indices):
        samples = [self._dataset[i] for i in indices]
        return self._batchify_fn(samples)

    def _get_proc_pool(self):
        # persistent spawn pool (reference keeps its worker pool for the
        # loader lifetime, dataloader.py:520); spawn not fork — the parent
        # holds live PJRT/XLA state that must not be forked
        if self._proc_pool is None:
            import multiprocessing as mp
            self._proc_pool = cf.ProcessPoolExecutor(
                self._num_workers,
                mp_context=mp.get_context("spawn"),
                initializer=_mp_worker_init,
                initargs=(self._dataset, self._batchify_fn))
        return self._proc_pool

    def __iter__(self):
        if self._num_workers == 0:
            for indices in self._batch_sampler:
                yield self._make_batch(indices)
            return
        if self._thread_pool:
            # thread-pool pipeline with bounded prefetch (the analog of
            # iter_prefetcher.h's threaded prefetch chain)
            with cf.ThreadPoolExecutor(self._num_workers) as pool:
                yield from self._pump(pool, self._make_batch, lambda r: r)
            return
        pool = self._get_proc_pool()
        yield from self._pump(pool, _mp_worker_task, _from_shm,
                              dispose=_free_shm)

    def _pump(self, pool, task, unwrap, dispose=None):
        pending = []
        it = iter(self._batch_sampler)
        try:
            try:
                for _ in range(self._prefetch or self._num_workers):
                    pending.append(pool.submit(task, next(it)))
            except StopIteration:
                pass
            while pending:
                fut = pending.pop(0)
                try:
                    pending.append(pool.submit(task, next(it)))
                except StopIteration:
                    pass
                yield unwrap(fut.result(timeout=self._timeout))
        finally:
            # abandoned mid-epoch (break / islice / GC): in-flight batches
            # carry shm blocks only _from_shm would unlink — drain them
            if dispose is not None:
                for fut in pending:
                    try:
                        dispose(fut.result(timeout=self._timeout))
                    except Exception:  # noqa: BLE001 - best-effort cleanup
                        pass

    def __del__(self):
        if getattr(self, "_proc_pool", None) is not None:
            self._proc_pool.shutdown(wait=False, cancel_futures=True)

    def __len__(self):
        return len(self._batch_sampler)


class _PyBenchDataset:
    """Picklable synthetic dataset with a deliberately GIL-bound python
    transform (bench: dataloader_pytransform row)."""

    def __init__(self, n=256, dim=2048):
        rs = onp.random.RandomState(0)
        self.x = rs.rand(n, dim).astype(onp.float32)

    def __len__(self):
        return len(self.x)

    def __getitem__(self, i):
        row = self.x[i]
        acc = 0.0
        for _ in range(5):           # ~1 ms of pure-python GIL-bound work
            for v in row[:2048:1]:
                acc += float(v) * 1.0000001
        return row * onp.float32(1.0 + 0.0 * acc)

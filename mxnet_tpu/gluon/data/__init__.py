"""gluon.data (reference: python/mxnet/gluon/data/__init__.py)."""
from .dataset import (  # noqa: F401
    Dataset, SimpleDataset, ArrayDataset, RecordFileDataset,
)
from .sampler import (  # noqa: F401
    Sampler, SequentialSampler, RandomSampler, BatchSampler, FilterSampler,
    IntervalSampler,
)
from .dataloader import DataLoader, default_batchify_fn  # noqa: F401
from . import vision  # noqa: F401

"""gluon.data datasets (reference: python/mxnet/gluon/data/dataset.py)."""
from __future__ import annotations

import os

from ...base import MXNetError


class Dataset:
    """Reference: dataset.py Dataset."""

    def __getitem__(self, idx):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError

    def transform(self, fn, lazy=True):
        trans = _LazyTransformDataset(self, fn)
        if lazy:
            return trans
        return SimpleDataset([trans[i] for i in range(len(trans))])

    def transform_first(self, fn, lazy=True):
        return self.transform(_TransformFirstClosure(fn), lazy)

    def filter(self, fn):
        return SimpleDataset([self[i] for i in range(len(self))
                              if fn(self[i])])

    def shard(self, num_shards, index):
        assert 0 <= index < num_shards
        length = len(self)
        shard_len = length // num_shards
        rest = length % num_shards
        start = shard_len * index + min(index, rest)
        end = start + shard_len + (index < rest)
        return SimpleDataset([self[i] for i in range(start, end)])

    def take(self, count):
        return SimpleDataset([self[i] for i in range(min(count, len(self)))])

    def sample(self, sampler):
        return _SampledDataset(self, sampler)


class _TransformFirstClosure:
    def __init__(self, fn):
        self._fn = fn

    def __call__(self, x, *args):
        if args:
            return (self._fn(x),) + args
        return self._fn(x)


class _LazyTransformDataset(Dataset):
    def __init__(self, data, fn):
        self._data = data
        self._fn = fn

    def __len__(self):
        return len(self._data)

    def __getitem__(self, idx):
        item = self._data[idx]
        if isinstance(item, tuple):
            return self._fn(*item)
        return self._fn(item)


class _SampledDataset(Dataset):
    def __init__(self, data, sampler):
        self._data = data
        self._indices = list(sampler)

    def __len__(self):
        return len(self._indices)

    def __getitem__(self, idx):
        return self._data[self._indices[idx]]


class SimpleDataset(Dataset):
    def __init__(self, data):
        self._data = data

    def __len__(self):
        return len(self._data)

    def __getitem__(self, idx):
        return self._data[idx]


class ArrayDataset(Dataset):
    """Zip of arrays (reference: dataset.py ArrayDataset)."""

    def __init__(self, *args):
        assert len(args) > 0
        self._length = len(args[0])
        self._data = []
        for a in args:
            if len(a) != self._length:
                raise MXNetError("all arrays must have the same length")
            self._data.append(a)

    def __getitem__(self, idx):
        if len(self._data) == 1:
            return self._data[0][idx]
        return tuple(d[idx] for d in self._data)

    def __len__(self):
        return self._length


class RecordFileDataset(Dataset):
    """RecordIO-backed dataset (reference: dataset.py RecordFileDataset over
    src/io/dataset.cc:61).

    Uses the native mmap reader (native/mxtpu_io.cc) when the toolchain is
    available — no .idx sidecar needed, zero-copy reads, native threaded
    prefetch via ``prefetch_iter`` — falling back to the pure-python
    IndexedRecordIO (.rec/.idx pair) otherwise.
    """

    def __init__(self, filename):
        self._native = None
        self._record = None
        try:
            from ...native import NativeRecordFile
            self._native = NativeRecordFile(filename)
        except (RuntimeError, OSError, FileNotFoundError):
            from ...recordio import IndexedRecordIO
            idx_file = os.path.splitext(filename)[0] + ".idx"
            self._record = IndexedRecordIO(idx_file, filename, "r")

    def __getitem__(self, idx):
        if self._native is not None:
            return self._native.read(idx)
        return self._record.read_idx(self._record.keys[idx])

    def __len__(self):
        if self._native is not None:
            return len(self._native)
        return len(self._record.keys)

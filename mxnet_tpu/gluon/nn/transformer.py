"""Transformer layers.

Reference parity: the reference ships fused attention *ops*
(src/operator/contrib/transformer.cc:675-828 interleaved_matmul_selfatt_qk/
valatt, encdec variants) but no Gluon transformer *layers* — those lived in
gluon-nlp (BERTEncoder/TransformerEncoderCell). This module provides the
layer family those ops exist to serve, TPU-native: attention lowers to the
Pallas flash kernel on TPU (mxnet_tpu/ops/pallas/flash_attention.py) and an
XLA dot_general composition elsewhere; sequence sharding for long context
rides mxnet_tpu.parallel.ring_attention.
"""
from __future__ import annotations

from ... import numpy as np
from ... import numpy_extension as npx
from ..block import HybridBlock
from .basic_layers import Dense, Dropout, LayerNorm


class MultiHeadAttention(HybridBlock):
    """Multi-head (self or cross) attention on (batch, seq, units).

    Reference: the op pair _contrib_interleaved_matmul_selfatt_qk/valatt
    (src/operator/contrib/transformer.cc:675-828) computed exactly this
    with explicit score materialization; here scores stay on-chip.
    """

    def __init__(self, units, num_heads, dropout=0.0, use_bias=True,
                 causal=False):
        super().__init__()
        if units % num_heads:
            raise ValueError(f"units {units} not divisible by "
                             f"num_heads {num_heads}")
        self._units = units
        self._heads = num_heads
        self._causal = causal
        self._dropout = dropout
        self.query_proj = Dense(units, use_bias=use_bias, flatten=False)
        self.key_proj = Dense(units, use_bias=use_bias, flatten=False)
        self.value_proj = Dense(units, use_bias=use_bias, flatten=False)
        self.out_proj = Dense(units, use_bias=use_bias, flatten=False)

    def forward(self, query, key=None, value=None, mask=None):
        from ...ops.attention import multi_head_attention
        key = query if key is None else key
        value = key if value is None else value
        q = self.query_proj(query)
        k = self.key_proj(key)
        v = self.value_proj(value)
        out = multi_head_attention(
            q, k, v, self._heads, mask=mask,
            dropout_p=self._dropout, causal=self._causal)
        return self.out_proj(out)

    # -- KV-cache serving surface (mx.serve) ---------------------------
    # Self-attention only: prefill writes a whole prompt into one cache
    # slot, decode_step advances every live slot by one token. Both are
    # pure in (x, cache) -> (y, cache) so hybridize()/jit can trace them
    # as cached graphs with the cache donated across steps.

    def init_cache(self, max_slots, max_seq, dtype="float32"):
        """Preallocate one (k, v) cache pair:
        (max_slots, max_seq, heads, head_dim) each.

        ``dtype="int8"`` selects quantized storage: each of k/v becomes a
        (values int8, scales float32) pair with one symmetric scale per
        (slot, row, head) — same fixed footprint at a quarter of the
        fp32 bytes (docs/SERVING.md "Low-bit weights and KV cache")."""
        d = self._units // self._heads
        shape = (max_slots, max_seq, self._heads, d)
        if str(dtype) == "int8":
            sshape = (max_slots, max_seq, self._heads, 1)
            return ((np.zeros(shape, dtype="int8"),
                     np.ones(sshape, dtype="float32")),
                    (np.zeros(shape, dtype="int8"),
                     np.ones(sshape, dtype="float32")))
        return (np.zeros(shape, dtype=dtype), np.zeros(shape, dtype=dtype))

    @staticmethod
    def _cache_is_q8(kv):
        return isinstance(kv[0], (tuple, list))

    def prefill(self, x, kv, slot):
        """Full causal self-attention over one prompt (1, L, units),
        recording projected K/V into cache slot ``slot``."""
        from ...ops.attention import (multi_head_attention,
                                      write_prefill_kv, write_prefill_kv_q8)
        q = self.query_proj(x)
        k = self.key_proj(x)
        v = self.value_proj(x)
        if self._cache_is_q8(kv):
            (kc, ks), (vc, vs) = kv
            kc, ks, vc, vs = write_prefill_kv_q8(kc, ks, vc, vs, k, v,
                                                 slot, self._heads)
            new_kv = ((kc, ks), (vc, vs))
        else:
            k_cache, v_cache = write_prefill_kv(kv[0], kv[1], k, v, slot,
                                                self._heads)
            new_kv = (k_cache, v_cache)
        out = multi_head_attention(q, k, v, self._heads, causal=True)
        return self.out_proj(out), new_kv

    def decode_step(self, x, kv, positions):
        """One cached decode step: x is (slots, 1, units), ``positions``
        (slots,) the cache row each slot's token occupies."""
        from ...ops.attention import decode_attention, decode_attention_q8
        q = self.query_proj(x)
        k = self.key_proj(x)
        v = self.value_proj(x)
        if self._cache_is_q8(kv):
            (kc, ks), (vc, vs) = kv
            out, kc, ks, vc, vs = decode_attention_q8(
                q, k, v, kc, ks, vc, vs, positions, self._heads)
            return self.out_proj(out), ((kc, ks), (vc, vs))
        out, k_cache, v_cache = decode_attention(
            q, k, v, kv[0], kv[1], positions, self._heads)
        return self.out_proj(out), (k_cache, v_cache)

    def prefill_suffix(self, x, kv, slot, start):
        """Prefix-cache suffix prefill: x (1, Ls, units) is the prompt
        *suffix*; rows [0, start) of ``slot`` already hold a copied
        prefix the suffix attends to (docs/SERVING.md
        "Prefix caching")."""
        from ...ops.attention import (suffix_prefill_attention,
                                      suffix_prefill_attention_q8)
        q = self.query_proj(x)
        k = self.key_proj(x)
        v = self.value_proj(x)
        if self._cache_is_q8(kv):
            (kc, ks), (vc, vs) = kv
            out, kc, ks, vc, vs = suffix_prefill_attention_q8(
                q, k, v, kc, ks, vc, vs, slot, start, self._heads)
            return self.out_proj(out), ((kc, ks), (vc, vs))
        out, k_cache, v_cache = suffix_prefill_attention(
            q, k, v, kv[0], kv[1], slot, start, self._heads)
        return self.out_proj(out), (k_cache, v_cache)

    def decode_multi(self, x, kv, positions):
        """k-token cached decode (the speculative-decoding verify):
        x is (slots, t, units), slot i's token j landing at cache row
        positions[i] + j with causal visibility."""
        from ...ops.attention import (decode_multi_attention,
                                      decode_multi_attention_q8)
        q = self.query_proj(x)
        k = self.key_proj(x)
        v = self.value_proj(x)
        if self._cache_is_q8(kv):
            (kc, ks), (vc, vs) = kv
            out, kc, ks, vc, vs = decode_multi_attention_q8(
                q, k, v, kc, ks, vc, vs, positions, self._heads)
            return self.out_proj(out), ((kc, ks), (vc, vs))
        out, k_cache, v_cache = decode_multi_attention(
            q, k, v, kv[0], kv[1], positions, self._heads)
        return self.out_proj(out), (k_cache, v_cache)

    def copy_cache_rows(self, kv, src_slot, src_row, dst_slot, dst_row,
                        rows):
        """Copy ``rows`` KV rows between slots — the prefix-cache block
        copy.  Works on the fp and the int8 (values, scales) layouts
        alike (scales copy with their rows)."""
        from ...ops.attention import copy_cache_rows
        return copy_cache_rows(kv, src_slot, src_row, dst_slot, dst_row,
                               rows)


class PositionwiseFFN(HybridBlock):
    """Transformer FFN block (dense → act → dense), gluon-nlp layout."""

    def __init__(self, units, hidden_size, activation="gelu", dropout=0.0,
                 use_bias=True):
        super().__init__()
        self.ffn_1 = Dense(hidden_size, use_bias=use_bias, flatten=False)
        self._activation = activation
        self.ffn_2 = Dense(units, use_bias=use_bias, flatten=False)
        self.dropout = Dropout(dropout) if dropout else None

    def forward(self, x):
        h = self.ffn_1(x)
        h = npx.leaky_relu(h, act_type="gelu") if self._activation == "gelu" \
            else npx.activation(h, act_type=self._activation)
        h = self.ffn_2(h)
        if self.dropout is not None:
            h = self.dropout(h)
        return h


def _fused_ln_residual(x, h, ln, p):
    """Route ``LN(x + dropout(h))`` through the fused Pallas kernel
    (ops/pallas/ln_residual.py) when eligible, else return None.

    Gated by mx.config ``fused_ln_residual``: 'auto' engages only on TPU
    AND when dropout is live (training mode, p > 0) — the measured-win
    case; with no dropout XLA's own residual+LN fusion is faster, and the
    kernel works only via interpret=True off-TPU. 'on' forces it
    everywhere. Feature dim must be a lane multiple (128) and the
    LayerNorm must be the default last-axis one.
    """
    import jax

    from ... import autograd, config
    from ... import random as _random
    from ...numpy.multiarray import _invoke

    mode = config.get("fused_ln_residual")
    if mode == "off" or ln._axis not in (-1, x.ndim - 1):
        return None
    on_tpu = jax.default_backend() == "tpu"
    if mode == "auto" and not on_tpu:
        return None
    if mode == "auto" and not (autograd.is_training() and float(p) > 0):
        # Measured on TPU v5lite (round 5, tools/tpu_ab.py): with dropout
        # OFF XLA's own residual+LN fusion is ~2% faster than the kernel;
        # with dropout ON the kernel wins ~5% (one VMEM pass over the
        # stream vs mask materialization + three passes). auto = only the
        # measured-win case; 'on' forces it everywhere.
        return None
    dim = x.shape[-1]
    if dim % 128 != 0:
        return None
    ch = x.shape[-1]
    for prm in (ln.gamma, ln.beta):
        if not prm._shape_known():
            prm._finish_deferred_init((ch,))
        elif prm._data is None:
            prm._finish_deferred_init()
    from ...ops.pallas.ln_residual import ln_residual_dropout

    p_eff = float(p) if autograd.is_training() else 0.0
    key = _random._next_key() if p_eff > 0 else None
    eps = ln._epsilon
    interpret = not on_tpu

    def fn(x_, h_, g_, b_):
        mask = (jax.random.bernoulli(key, 1.0 - p_eff, h_.shape)
                if p_eff > 0 else None)
        return ln_residual_dropout(x_, h_, g_, b_, p=p_eff, mask=mask,
                                   eps=eps, interpret=interpret)

    return _invoke(fn, (x, h, ln.gamma.data(), ln.beta.data()),
                   name="fused_ln_residual")


class TransformerEncoderCell(HybridBlock):
    """One encoder layer: MHA + FFN with residuals.

    pre_norm=False (post-norm) is the BERT/original-transformer layout;
    pre_norm=True is the modern LLM layout.
    """

    def __init__(self, units, hidden_size, num_heads, dropout=0.0,
                 attention_dropout=0.0, activation="gelu", pre_norm=False,
                 causal=False):
        super().__init__()
        self._pre_norm = pre_norm
        self.attention = MultiHeadAttention(units, num_heads,
                                            dropout=attention_dropout,
                                            causal=causal)
        self.attn_ln = LayerNorm()
        self.ffn = PositionwiseFFN(units, hidden_size, activation, dropout)
        self.ffn_ln = LayerNorm()
        self.dropout = Dropout(dropout) if dropout else None
        self._dropout_rate = float(dropout)

    def forward(self, x, mask=None):
        from ...parallel.mesh import constrain
        if self._pre_norm:
            h = self.attention(self.attn_ln(x), mask=mask)
            x = constrain(x + (self.dropout(h) if self.dropout else h),
                          "residual")
            h = self.ffn(self.ffn_ln(x))
            return constrain(x + h, "residual")
        h = self.attention(x, mask=mask)
        p = self._dropout_rate if self.dropout is not None else 0.0
        fused = _fused_ln_residual(x, h, self.attn_ln, p)
        if fused is not None:
            x = constrain(fused, "residual")
        else:
            x = constrain(
                self.attn_ln(x + (self.dropout(h) if self.dropout else h)),
                "residual")
        h = self.ffn(x)
        fused = _fused_ln_residual(x, h, self.ffn_ln, 0.0)
        if fused is not None:
            return constrain(fused, "residual")
        return constrain(self.ffn_ln(x + h), "residual")

    # -- KV-cache serving surface (mx.serve) ---------------------------
    # Inference-only: dropout is skipped (serving never trains) and the
    # residual stream follows the same pre/post-norm layout as forward().

    def init_cache(self, max_slots, max_seq, dtype="float32"):
        return self.attention.init_cache(max_slots, max_seq, dtype)

    def prefill(self, x, kv, slot):
        if self._pre_norm:
            h, kv = self.attention.prefill(self.attn_ln(x), kv, slot)
            x = x + h
            return x + self.ffn(self.ffn_ln(x)), kv
        h, kv = self.attention.prefill(x, kv, slot)
        x = self.attn_ln(x + h)
        return self.ffn_ln(x + self.ffn(x)), kv

    def decode_step(self, x, kv, positions):
        if self._pre_norm:
            h, kv = self.attention.decode_step(self.attn_ln(x), kv,
                                               positions)
            x = x + h
            return x + self.ffn(self.ffn_ln(x)), kv
        h, kv = self.attention.decode_step(x, kv, positions)
        x = self.attn_ln(x + h)
        return self.ffn_ln(x + self.ffn(x)), kv

    def prefill_suffix(self, x, kv, slot, start):
        if self._pre_norm:
            h, kv = self.attention.prefill_suffix(self.attn_ln(x), kv,
                                                  slot, start)
            x = x + h
            return x + self.ffn(self.ffn_ln(x)), kv
        h, kv = self.attention.prefill_suffix(x, kv, slot, start)
        x = self.attn_ln(x + h)
        return self.ffn_ln(x + self.ffn(x)), kv

    def decode_multi(self, x, kv, positions):
        if self._pre_norm:
            h, kv = self.attention.decode_multi(self.attn_ln(x), kv,
                                                positions)
            x = x + h
            return x + self.ffn(self.ffn_ln(x)), kv
        h, kv = self.attention.decode_multi(x, kv, positions)
        x = self.attn_ln(x + h)
        return self.ffn_ln(x + self.ffn(x)), kv

    def copy_cache_rows(self, kv, src_slot, src_row, dst_slot, dst_row,
                        rows):
        return self.attention.copy_cache_rows(
            kv, src_slot, src_row, dst_slot, dst_row, rows)


class TransformerDecoderCell(HybridBlock):
    """One decoder layer: causal self-attn, cross-attn, FFN (post-norm)."""

    def __init__(self, units, hidden_size, num_heads, dropout=0.0,
                 attention_dropout=0.0, activation="relu"):
        super().__init__()
        self.self_attention = MultiHeadAttention(
            units, num_heads, dropout=attention_dropout, causal=True)
        self.self_ln = LayerNorm()
        self.cross_attention = MultiHeadAttention(
            units, num_heads, dropout=attention_dropout)
        self.cross_ln = LayerNorm()
        self.ffn = PositionwiseFFN(units, hidden_size, activation, dropout)
        self.ffn_ln = LayerNorm()
        self.dropout = Dropout(dropout) if dropout else None

    def forward(self, x, mem, mem_mask=None):
        h = self.self_attention(x)
        x = self.self_ln(x + (self.dropout(h) if self.dropout else h))
        h = self.cross_attention(x, mem, mem, mask=mem_mask)
        x = self.cross_ln(x + (self.dropout(h) if self.dropout else h))
        return self.ffn_ln(x + self.ffn(x))


class TransformerEncoder(HybridBlock):
    """Stack of encoder cells."""

    def __init__(self, num_layers, units, hidden_size, num_heads,
                 dropout=0.0, attention_dropout=0.0, activation="gelu",
                 pre_norm=False, causal=False):
        super().__init__()
        self._layers = []
        for i in range(num_layers):
            cell = TransformerEncoderCell(
                units, hidden_size, num_heads, dropout, attention_dropout,
                activation, pre_norm, causal)
            setattr(self, f"layer{i}", cell)
            self._layers.append(cell)

    def forward(self, x, mask=None):
        for cell in self._layers:
            x = cell(x, mask=mask)
        return x

    # -- KV-cache serving surface (mx.serve) ---------------------------

    def init_cache(self, max_slots, max_seq, dtype="float32"):
        """One (k, v) pair per layer — the whole decode footprint,
        allocated once and donated across steps by the serve engine."""
        return [cell.init_cache(max_slots, max_seq, dtype)
                for cell in self._layers]

    def prefill(self, x, caches, slot):
        out = []
        for cell, kv in zip(self._layers, caches):
            x, kv = cell.prefill(x, kv, slot)
            out.append(kv)
        return x, out

    def decode_step(self, x, caches, positions):
        out = []
        for cell, kv in zip(self._layers, caches):
            x, kv = cell.decode_step(x, kv, positions)
            out.append(kv)
        return x, out

    def prefill_suffix(self, x, caches, slot, start):
        out = []
        for cell, kv in zip(self._layers, caches):
            x, kv = cell.prefill_suffix(x, kv, slot, start)
            out.append(kv)
        return x, out

    def decode_multi(self, x, caches, positions):
        out = []
        for cell, kv in zip(self._layers, caches):
            x, kv = cell.decode_multi(x, kv, positions)
            out.append(kv)
        return x, out

    def copy_cache_rows(self, caches, src_slot, src_row, dst_slot,
                        dst_row, rows):
        return [cell.copy_cache_rows(kv, src_slot, src_row, dst_slot,
                                     dst_row, rows)
                for cell, kv in zip(self._layers, caches)]


def valid_length_mask(valid_length, seq_len):
    """(batch,) valid lengths → (batch, 1, 1, seq) attention mask, the
    npx.sequence_mask convention lifted to attention scores."""
    ar = np.arange(seq_len).reshape(1, 1, 1, seq_len)
    return ar < valid_length.reshape(-1, 1, 1, 1)


def positional_encoding(seq_len, units, dtype="float32"):
    """Sinusoidal position table (batch-free, (seq, units))."""
    import numpy as onp
    pos = onp.arange(seq_len)[:, None]
    dim = onp.arange((units + 1) // 2)[None]
    angle = pos / onp.power(10000.0, 2 * dim / units)
    table = onp.zeros((seq_len, units), dtype=dtype)
    table[:, 0::2] = onp.sin(angle)
    table[:, 1::2] = onp.cos(angle[:, : units // 2])
    return np.array(table)

"""Basic Gluon layers.

Reference parity: python/mxnet/gluon/nn/basic_layers.py (Sequential, Dense,
Dropout, BatchNorm, LayerNorm, GroupNorm, InstanceNorm, Embedding, Flatten,
Lambda, identity/activation blocks). Ops lower through mx.npx to jnp/lax.
"""
from __future__ import annotations

import numpy as onp

from ... import numpy as _np
from ... import numpy_extension as npx
from ...amp import fp8 as _fp8_scope
from ...base import MXNetError
from ..block import Block, HybridBlock
from ..parameter import Parameter, Constant


class Sequential(Block):
    """Stack of Blocks (reference: basic_layers.py Sequential)."""

    def __init__(self):
        super().__init__()
        self._layers = []

    def add(self, *blocks):
        for block in blocks:
            idx = len(self._layers)
            self._layers.append(block)
            self.register_child(block, str(idx))

    def forward(self, x, *args):
        for block in self._children.values():
            x = block(x, *args)
            args = ()
        return x

    def __len__(self):
        return len(self._children)

    def __getitem__(self, key):
        if isinstance(key, slice):
            net = type(self)()
            net.add(*list(self._children.values())[key])
            return net
        return list(self._children.values())[key]

    def __iter__(self):
        return iter(self._children.values())

    def hybridize(self, active=True, **kwargs):
        super().hybridize(active, **kwargs)


class HybridSequential(HybridBlock):
    """Traceable Sequential (reference: basic_layers.py HybridSequential)."""

    def __init__(self):
        super().__init__()
        self._layers = []

    def add(self, *blocks):
        for block in blocks:
            idx = len(self._layers)
            self._layers.append(block)
            self.register_child(block, str(idx))

    def forward(self, x, *args):
        for block in self._children.values():
            x = block(x, *args)
            args = ()
        return x

    def __len__(self):
        return len(self._children)

    def __getitem__(self, key):
        if isinstance(key, slice):
            net = type(self)()
            net.add(*list(self._children.values())[key])
            return net
        return list(self._children.values())[key]

    def __iter__(self):
        return iter(self._children.values())


class Dense(HybridBlock):
    """Fully connected layer (reference: basic_layers.py Dense over
    src/operator/nn/fully_connected.cc). Weight layout (units, in_units)."""

    def __init__(self, units, activation=None, use_bias=True, flatten=True,
                 dtype="float32", weight_initializer=None,
                 bias_initializer="zeros", in_units=0):
        super().__init__()
        self._units = units
        self._flatten = flatten
        self.weight = Parameter("weight", shape=(units, in_units),
                                dtype=dtype, init=weight_initializer,
                                allow_deferred_init=True)
        self.bias = (Parameter("bias", shape=(units,), dtype=dtype,
                               init=bias_initializer,
                               allow_deferred_init=True)
                     if use_bias else None)
        self.act = Activation(activation) if activation else None

    def forward(self, x):
        if not self.weight._shape_known():
            in_units = (int(onp.prod(x.shape[1:])) if self._flatten
                        else x.shape[-1])
            self.weight._finish_deferred_init((self._units, in_units))
        if self.bias is not None and self.bias._data is None:
            self.bias._finish_deferred_init()
        fp8 = _fp8_scope.current()
        if fp8 is not None:
            # fp8 training scope (amp/fp8.py): sites keyed by the
            # structural name collect_params assigned; non-sites (tiny
            # or aux-owned weights) fall through to the fp dense path
            site = getattr(self.weight, "_structure_name", None)
            if site in fp8.scales:
                from ...numpy.multiarray import _wrap
                raw = _fp8_scope.dense_fp8(
                    x._data, self.weight.data()._data,
                    self.bias.data()._data if self.bias is not None
                    else None, site, flatten=self._flatten)
                out = _wrap(raw)
                return self.act(out) if self.act is not None else out
        out = npx.fully_connected(
            x, self.weight.data(),
            self.bias.data() if self.bias is not None else None,
            num_hidden=self._units,
            no_bias=self.bias is None, flatten=self._flatten)
        return self.act(out) if self.act is not None else out

    def __repr__(self):
        return f"Dense({self._units}, in={self.weight.shape[1]})"


class Activation(HybridBlock):
    def __init__(self, activation):
        super().__init__()
        self._act_type = activation

    def forward(self, x):
        return npx.activation(x, act_type=self._act_type)

    def __repr__(self):
        return f"Activation({self._act_type})"


class Dropout(HybridBlock):
    """Reference: basic_layers.py Dropout over src/operator/nn/dropout.cc."""

    def __init__(self, rate, axes=()):
        super().__init__()
        self._rate = rate
        self._axes = axes

    def forward(self, x):
        return npx.dropout(x, p=self._rate, axes=self._axes)


class BatchNorm(HybridBlock):
    """Reference: basic_layers.py BatchNorm over src/operator/nn/batch_norm.cc.

    gamma/beta trainable (unless scale/center False); moving stats are aux
    parameters mutated in place by npx.batch_norm during training — under
    hybridize this rides the cached-graph mutated-aux channel.
    """

    def __init__(self, axis=1, momentum=0.9, epsilon=1e-5, center=True,
                 scale=True, use_global_stats=False, beta_initializer="zeros",
                 gamma_initializer="ones",
                 running_mean_initializer="zeros",
                 running_variance_initializer="ones", in_channels=0, **kwargs):
        super().__init__()
        self._axis = axis
        self._momentum = momentum
        self._epsilon = epsilon
        self._center = center
        self._scale = scale
        self._use_global_stats = use_global_stats
        shape = (in_channels,)
        self.gamma = Parameter("gamma", grad_req="write" if scale else "null",
                               shape=shape, init=gamma_initializer,
                               allow_deferred_init=True)
        self.beta = Parameter("beta", grad_req="write" if center else "null",
                              shape=shape, init=beta_initializer,
                              allow_deferred_init=True)
        self.running_mean = Parameter("running_mean", grad_req="null",
                                      shape=shape,
                                      init=running_mean_initializer,
                                      allow_deferred_init=True)
        self.running_var = Parameter("running_var", grad_req="null",
                                     shape=shape,
                                     init=running_variance_initializer,
                                     allow_deferred_init=True)

    def forward(self, x):
        ch = x.shape[self._axis]
        for p in (self.gamma, self.beta, self.running_mean, self.running_var):
            if not p._shape_known():
                p._finish_deferred_init((ch,))
            elif p._data is None:
                p._finish_deferred_init()
        return npx.batch_norm(
            x, self.gamma.data(), self.beta.data(), self.running_mean.data(),
            self.running_var.data(), eps=self._epsilon,
            momentum=self._momentum, fix_gamma=not self._scale,
            use_global_stats=self._use_global_stats, axis=self._axis)


class SyncBatchNorm(BatchNorm):
    """Cross-device BatchNorm (reference: contrib SyncBatchNorm). On a
    sharded mesh the batch statistics are computed over the global batch by
    XLA automatically when the array is sharded; identical to BatchNorm."""

    def __init__(self, in_channels=0, num_devices=None, momentum=0.9,
                 epsilon=1e-5, center=True, scale=True, use_global_stats=False,
                 beta_initializer="zeros", gamma_initializer="ones",
                 running_mean_initializer="zeros",
                 running_variance_initializer="ones", **kwargs):
        super().__init__(1, momentum, epsilon, center, scale,
                         use_global_stats, beta_initializer, gamma_initializer,
                         running_mean_initializer,
                         running_variance_initializer, in_channels)


class BatchNormReLU(BatchNorm):
    """Fused BatchNorm + ReLU (reference: basic_layers.py:478 BatchNormReLU
    over the batch_norm op's act_type='relu' attr). Here the relu tail is
    applied after npx.batch_norm — XLA fuses it into the single-pass BN
    scale/shift FMA, so it is one kernel on TPU like the cuDNN fused op."""

    def forward(self, x):
        return npx.relu(super().forward(x))


class Concatenate(Sequential):
    """Run children on the SAME input, concat outputs along ``axis``
    (reference: basic_layers.py:1002)."""

    def __init__(self, axis=-1):
        super().__init__()
        self._axis = axis

    def forward(self, x):
        return _np.concatenate([block(x) for block in self._children.values()],
                               axis=self._axis)


class HybridConcatenate(HybridSequential):
    """Traceable Concatenate (reference: basic_layers.py:1034)."""

    def __init__(self, axis=-1):
        super().__init__()
        self._axis = axis

    def forward(self, x):
        return _np.concatenate([block(x) for block in self._children.values()],
                               axis=self._axis)


class LayerNorm(HybridBlock):
    """Reference: basic_layers.py LayerNorm over src/operator/nn/layer_norm.cc."""

    def __init__(self, axis=-1, epsilon=1e-5, center=True, scale=True,
                 beta_initializer="zeros", gamma_initializer="ones",
                 in_channels=0):
        super().__init__()
        self._axis = axis
        self._epsilon = epsilon
        self.gamma = Parameter("gamma", grad_req="write" if scale else "null",
                               shape=(in_channels,), init=gamma_initializer,
                               allow_deferred_init=True)
        self.beta = Parameter("beta", grad_req="write" if center else "null",
                              shape=(in_channels,), init=beta_initializer,
                              allow_deferred_init=True)

    def forward(self, x):
        ch = x.shape[self._axis]
        for p in (self.gamma, self.beta):
            if not p._shape_known():
                p._finish_deferred_init((ch,))
            elif p._data is None:
                p._finish_deferred_init()
        return npx.layer_norm(x, self.gamma.data(), self.beta.data(),
                              axis=self._axis, eps=self._epsilon)


class GroupNorm(HybridBlock):
    """Reference: basic_layers.py GroupNorm over src/operator/nn/group_norm.cc."""

    def __init__(self, num_groups=1, epsilon=1e-5, center=True, scale=True,
                 beta_initializer="zeros", gamma_initializer="ones",
                 in_channels=0):
        super().__init__()
        self._num_groups = num_groups
        self._epsilon = epsilon
        self.gamma = Parameter("gamma", grad_req="write" if scale else "null",
                               shape=(in_channels,), init=gamma_initializer,
                               allow_deferred_init=True)
        self.beta = Parameter("beta", grad_req="write" if center else "null",
                              shape=(in_channels,), init=beta_initializer,
                              allow_deferred_init=True)

    def forward(self, x):
        ch = x.shape[1]
        for p in (self.gamma, self.beta):
            if not p._shape_known():
                p._finish_deferred_init((ch,))
            elif p._data is None:
                p._finish_deferred_init()
        return npx.group_norm(x, self.gamma.data(), self.beta.data(),
                              num_groups=self._num_groups, eps=self._epsilon)


class InstanceNorm(HybridBlock):
    def __init__(self, axis=1, epsilon=1e-5, center=True, scale=False,
                 beta_initializer="zeros", gamma_initializer="ones",
                 in_channels=0):
        super().__init__()
        self._epsilon = epsilon
        self.gamma = Parameter("gamma", grad_req="write" if scale else "null",
                               shape=(in_channels,), init=gamma_initializer,
                               allow_deferred_init=True)
        self.beta = Parameter("beta", grad_req="write" if center else "null",
                              shape=(in_channels,), init=beta_initializer,
                              allow_deferred_init=True)

    def forward(self, x):
        ch = x.shape[1]
        for p in (self.gamma, self.beta):
            if not p._shape_known():
                p._finish_deferred_init((ch,))
            elif p._data is None:
                p._finish_deferred_init()
        return npx.instance_norm(x, self.gamma.data(), self.beta.data(),
                                 eps=self._epsilon)


class Embedding(HybridBlock):
    """Reference: basic_layers.py Embedding over indexing_op.cc.

    ``sparse_grad=True`` gives the weight a ``RowSparseNDArray`` gradient
    (O(batch) rows; see npx.embedding) feeding lazy_update optimizers and
    kvstore row_sparse push, plus row-sparse access via
    ``weight.row_sparse_data(ids)`` / ``kvstore.row_sparse_pull``."""

    def __init__(self, input_dim, output_dim, dtype="float32",
                 weight_initializer=None, sparse_grad=False):
        super().__init__()
        self._input_dim = input_dim
        self._output_dim = output_dim
        self._sparse_grad = sparse_grad
        self.weight = Parameter(
            "weight", shape=(input_dim, output_dim), dtype=dtype,
            init=weight_initializer,
            grad_stype="row_sparse" if sparse_grad else "default")

    def forward(self, x):
        if self.weight._data is None:
            self.weight._finish_deferred_init()
        return npx.embedding(x, self.weight.data(),
                             input_dim=self._input_dim,
                             output_dim=self._output_dim,
                             sparse_grad=self._sparse_grad)


class Flatten(HybridBlock):
    def __init__(self):
        super().__init__()

    def forward(self, x):
        return x.reshape(x.shape[0], -1)

    def __repr__(self):
        return "Flatten"


class Identity(HybridBlock):
    def forward(self, x):
        return x


class Lambda(Block):
    """Reference: basic_layers.py Lambda (wrap a function as a Block)."""

    def __init__(self, function):
        super().__init__()
        if isinstance(function, str):
            from ... import numpy as _np
            function = getattr(_np, function)
        self._func = function

    def forward(self, *args):
        return self._func(*args)


class HybridLambda(HybridBlock):
    def __init__(self, function):
        super().__init__()
        if isinstance(function, str):
            from ... import numpy as _np
            function = getattr(_np, function)
        self._func = function

    def forward(self, *args):
        return self._func(*args)

"""Pattern-fused Sequential: conv3x3+BN+ReLU triplets route through the
Pallas fused-backward composite.

Reference analog: the reference fuses conv+BN statistics via cuDNN fused
ops and pointwise fusion passes (src/operator/fusion/); here the forward
stays XLA (already fused) and the BACKWARD is the Pallas mega-kernel in
ops/pallas_conv_bwd.py which never materializes the conv-output cotangent
(round-3 profiled HBM wall).

Enabled when config 'fused_conv_bn' is "on" (opt-in; "auto" is OFF —
measured ~30% slower than XLA's conv backward on TPU v5lite, see
_fusion_active), training mode is active, and the triplet matches the
kernel's shape class; anything else falls back to the plain
child-by-child forward, so eval, CPU tests, exotic shapes and ONNX
export are unchanged.
"""
from __future__ import annotations

from .basic_layers import Activation, BatchNorm, HybridSequential
from .conv_layers import _Conv


def _fusion_active():
    from ... import config as _cfg
    from ... import autograd as _ag
    if not _ag.is_training():
        return False
    mode = str(_cfg.get("fused_conv_bn")).lower()
    if mode in ("0", "false", "off"):
        return False
    if mode in ("1", "true", "on"):
        return True
    # auto: OFF. Measured on TPU v5lite (round 5 A/B, tools/tpu_ab.py):
    # the Pallas backward is ~30% SLOWER end-to-end than XLA's own
    # conv-backward fusions (ResNet-50 bs32 bf16: 1774 vs 2550 img/s).
    # The kernel remains available via fused_conv_bn=on for shapes/chips
    # where it wins; engaging it by default is a de-optimization.
    return False


def _has_hooks(*blocks):
    return any(getattr(b, attr, None)
               for b in blocks
               for attr in ("_forward_hooks", "_forward_pre_hooks"))


def _eligible_triplet(conv, bn, act):
    from ...ops.pallas_conv_bwd import eligible
    if not (isinstance(conv, _Conv) and type(bn) is BatchNorm
            and isinstance(act, Activation)
            and getattr(act, "_act_type", None) == "relu"):
        return False
    if conv._op_name != "convolution" or conv._layout != "NCHW" \
            or conv.act is not None:
        return False
    if not (bn._scale and bn._center and not bn._use_global_stats
            and bn._axis == 1):
        return False
    if _has_hooks(conv, bn, act):
        # fused path bypasses child __call__ — keep hooks observable
        return False
    return eligible(conv._kernel, conv._strides, conv._padding,
                    conv._dilation, conv._groups, conv.bias is not None)


class FusableSequential(HybridSequential):
    """HybridSequential that detects [Conv2D 3x3/s1, BatchNorm, ReLU] runs
    and routes them through npx.fused_conv_bn_relu during training.

    Forward hooks on the three children disable fusion for that triplet
    (the fused path bypasses the child __call__)."""

    @staticmethod
    def _fits(conv, x):
        from ...ops.pallas_conv_bwd import fits_vmem
        n, c = x.shape[0], x.shape[1]
        h, w = x.shape[2], x.shape[3]
        return fits_vmem(n, h, w, c, conv._channels,
                         itemsize=x.dtype.itemsize)

    def forward(self, x, *args):
        from ... import numpy_extension as npx
        children = list(self._children.values())
        fuse = _fusion_active()
        i = 0
        while i < len(children):
            blk = children[i]
            if (fuse and i + 2 < len(children)
                    and _eligible_triplet(blk, children[i + 1],
                                          children[i + 2])
                    and self._fits(blk, x)):
                conv, bn = blk, children[i + 1]
                if not conv.weight._shape_known():
                    conv.weight._finish_deferred_init(
                        (conv._channels, x.shape[1]) + conv._kernel)
                ch = conv._channels
                for p in (bn.gamma, bn.beta, bn.running_mean,
                          bn.running_var):
                    if not p._shape_known():
                        p._finish_deferred_init((ch,))
                    elif p._data is None:
                        p._finish_deferred_init()
                x = npx.fused_conv_bn_relu(
                    x, conv.weight.data(), bn.gamma.data(), bn.beta.data(),
                    bn.running_mean.data(), bn.running_var.data(),
                    momentum=bn._momentum, eps=bn._epsilon)
                i += 3
                continue
            x = blk(x, *args)
            args = ()
            i += 1
        return x

"""Activation blocks (reference: python/mxnet/gluon/nn/activations.py)."""
from __future__ import annotations

from ... import numpy_extension as npx
from ..block import HybridBlock
from ..parameter import Parameter
from .basic_layers import Activation  # noqa: F401 (re-export)


class LeakyReLU(HybridBlock):
    def __init__(self, alpha=0.01):
        super().__init__()
        self._alpha = alpha

    def forward(self, x):
        return npx.leaky_relu(x, act_type="leaky", slope=self._alpha)


class PReLU(HybridBlock):
    def __init__(self, alpha_initializer="zeros", in_channels=1):
        super().__init__()
        self.alpha = Parameter("alpha", shape=(in_channels,),
                               init=alpha_initializer)

    def forward(self, x):
        if self.alpha._data is None:
            self.alpha._finish_deferred_init()
        return npx.leaky_relu(x, self.alpha.data(), act_type="prelu")


class ELU(HybridBlock):
    def __init__(self, alpha=1.0):
        super().__init__()
        self._alpha = alpha

    def forward(self, x):
        return npx.leaky_relu(x, act_type="elu", slope=self._alpha)


class SELU(HybridBlock):
    def forward(self, x):
        return npx.leaky_relu(x, act_type="selu")


class GELU(HybridBlock):
    def __init__(self, approximation="erf"):
        super().__init__()
        self._approx = approximation

    def forward(self, x):
        return npx.leaky_relu(x, act_type="gelu")


class SiLU(HybridBlock):
    def forward(self, x):
        return npx.activation(x, act_type="silu")


class Swish(HybridBlock):
    def __init__(self, beta=1.0):
        super().__init__()
        self._beta = beta

    def forward(self, x):
        return x * npx.sigmoid(x * self._beta)

"""Mixture-of-Experts layers (expert parallelism).

Reference parity: none (the reference has no MoE — SURVEY §2.3 marks EP
out of its scope; first-class here per the long-context/distributed brief).
Design: Switch/Top-k router + experts stored as stacked weight tensors with
a leading expert dim. Dispatch/combine are einsums over a one-hot dispatch
mask — the GSPMD-friendly formulation: shard the expert dim over an 'ep'
mesh axis (megatron_specs analog: P('ep', ...)) and XLA inserts the
all-to-alls. Capacity-factor truncation keeps shapes static for jit.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...numpy.multiarray import _invoke
from ..block import HybridBlock
from ..parameter import Parameter


class MoEDense(HybridBlock):
    """Top-k routed expert FFN on (batch, seq, units) or (tokens, units).

    forward returns (output, aux_loss) where aux_loss is the Switch
    load-balancing loss (mean over experts of fraction_tokens *
    fraction_router_prob * n_experts).
    """

    def __init__(self, units, hidden_size, num_experts, num_experts_per_tok=1,
                 capacity_factor=1.25, activation="gelu", dtype="float32"):
        super().__init__()
        if num_experts_per_tok > num_experts:
            raise ValueError(
                f"num_experts_per_tok {num_experts_per_tok} > "
                f"num_experts {num_experts}")
        self._units = units
        self._hidden = hidden_size
        self._n_exp = num_experts
        self._topk = num_experts_per_tok
        self._cap = capacity_factor
        self._act = activation
        self.gate = Parameter("gate", shape=(units, num_experts), dtype=dtype)
        self.w_in = Parameter("w_in", shape=(num_experts, units, hidden_size),
                              dtype=dtype)
        self.w_out = Parameter("w_out",
                               shape=(num_experts, hidden_size, units),
                               dtype=dtype)

    def forward(self, x):
        for p in (self.gate, self.w_in, self.w_out):
            if p._data is None:
                p._finish_deferred_init()
        n_exp, topk, cap_f, act = self._n_exp, self._topk, self._cap, self._act

        def fn(x_, gate, w_in, w_out):
            shape = x_.shape
            tokens = x_.reshape(-1, shape[-1])          # (T, d)
            T = tokens.shape[0]
            capacity = max(1, int(cap_f * T * topk / n_exp))
            logits = tokens @ gate                       # (T, E)
            probs = jax.nn.softmax(logits.astype(jnp.float32), -1)

            # top-k routing with per-expert capacity (Switch formulation)
            combine = jnp.zeros((T, n_exp, capacity), jnp.float32)
            dispatch = jnp.zeros((T, n_exp, capacity), jnp.bool_)
            remaining = probs
            position_in_expert = jnp.zeros((n_exp,), jnp.int32)
            route_count = jnp.zeros((n_exp,), jnp.float32)
            gate_sum = jnp.zeros((T,), jnp.float32)
            for _ in range(topk):
                choice = jnp.argmax(remaining, -1)               # (T,)
                gate_val = jnp.take_along_axis(
                    remaining, choice[:, None], -1)[:, 0]
                onehot = jax.nn.one_hot(choice, n_exp, dtype=jnp.int32)
                pos = position_in_expert[None, :] + \
                    (jnp.cumsum(onehot, 0) - onehot)             # (T, E)
                pos_tok = jnp.sum(pos * onehot, -1)              # (T,)
                keep = pos_tok < capacity
                pos_oh = jax.nn.one_hot(jnp.clip(pos_tok, 0, capacity - 1),
                                        capacity, dtype=jnp.float32)
                sel = (onehot.astype(jnp.float32)
                       * keep[:, None].astype(jnp.float32))
                dispatch = dispatch | (
                    sel[:, :, None] * pos_oh[:, None, :] > 0)
                combine = combine + (gate_val[:, None, None]
                                     * sel[:, :, None] * pos_oh[:, None, :])
                gate_sum = gate_sum + gate_val
                position_in_expert = position_in_expert + jnp.sum(
                    onehot * keep[:, None].astype(jnp.int32), 0)
                # pre-drop router assignments (Switch defines f_i over what
                # the router *chose*, not what survived capacity)
                route_count = route_count + jnp.sum(
                    onehot.astype(jnp.float32), 0)
                remaining = remaining * (1.0 - onehot.astype(jnp.float32))

            if topk > 1:
                # GShard top-k: renormalize combine weights over the chosen
                # experts (pre-capacity-drop), so kept gates sum to <= 1;
                # top-1 keeps the raw router prob (Switch formulation)
                combine = combine / (gate_sum[:, None, None] + 1e-9)

            # dispatch tokens to expert buffers: (E, C, d)
            exp_in = jnp.einsum("tec,td->ecd",
                                dispatch.astype(x_.dtype), tokens)
            h = jnp.einsum("ecd,edh->ech", exp_in, w_in)
            h = jax.nn.gelu(h) if act == "gelu" else jax.nn.relu(h)
            exp_out = jnp.einsum("ech,ehd->ecd", h, w_out)
            out = jnp.einsum("tec,ecd->td", combine.astype(x_.dtype),
                             exp_out)

            # load-balancing aux loss (Switch): E * sum_e f_e * P_e, with
            # f_e the PRE-capacity-drop routed fraction so the gradient
            # keeps penalizing collapse even when the hot expert overflows
            f = route_count / (T * topk)
            p_mean = jnp.mean(probs, 0)
            aux = n_exp * jnp.sum(f * p_mean)
            return out.reshape(shape), aux

        return _invoke(fn, (x, self.gate.data(), self.w_in.data(),
                            self.w_out.data()), name="moe_dense")


def moe_expert_specs(ep_axis="ep"):
    """PartitionSpecs for MoEDense params: experts sharded over `ep_axis`
    (the parallel.train.megatron_specs analog for EP)."""
    from jax.sharding import PartitionSpec as P
    return {
        "gate": P(),
        "w_in": P(ep_axis, None, None),
        "w_out": P(ep_axis, None, None),
    }

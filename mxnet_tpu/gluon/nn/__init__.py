"""gluon.nn (reference: python/mxnet/gluon/nn/__init__.py)."""
from .basic_layers import (  # noqa: F401
    Sequential, HybridSequential, Dense, Dropout, BatchNorm, BatchNormReLU,
    SyncBatchNorm, LayerNorm, GroupNorm, InstanceNorm, Embedding, Flatten,
    Identity, Lambda, HybridLambda, Concatenate, HybridConcatenate,
)
from .conv_layers import (  # noqa: F401
    Conv1D, Conv2D, Conv3D, Conv1DTranspose, Conv2DTranspose, Conv3DTranspose,
    MaxPool1D, MaxPool2D, MaxPool3D, AvgPool1D, AvgPool2D, AvgPool3D,
    GlobalMaxPool1D, GlobalMaxPool2D, GlobalMaxPool3D, GlobalAvgPool1D,
    GlobalAvgPool2D, GlobalAvgPool3D, ReflectionPad2D, DeformableConvolution,
    ModulatedDeformableConvolution, PixelShuffle1D, PixelShuffle2D,
    PixelShuffle3D,
)
from .activations import (  # noqa: F401
    Activation, LeakyReLU, PReLU, ELU, SELU, GELU, SiLU, Swish,
)
from .transformer import (  # noqa: F401
    MultiHeadAttention, PositionwiseFFN, TransformerEncoder,
    TransformerEncoderCell, TransformerDecoderCell,
)
from .moe import MoEDense  # noqa: F401
from .fuse import FusableSequential  # noqa: F401
from ..block import Block, HybridBlock, SymbolBlock  # noqa: F401

"""Convolution and pooling layers.

Reference parity: python/mxnet/gluon/nn/conv_layers.py (Conv1D/2D/3D,
Conv{2,3}DTranspose, Max/Avg/GlobalMax/GlobalAvg pooling, ReflectionPad2D)
over src/operator/nn/convolution.cc / pooling.cc (cuDNN paths).

TPU-native: convs lower to lax.conv_general_dilated (MXU-tiled by XLA);
pooling to lax.reduce_window. Default layout NCHW for reference parity — XLA
handles the internal layout assignment for TPU.
"""
from __future__ import annotations

from ... import numpy_extension as npx
from ... import numpy as _np
from ..block import HybridBlock
from ..parameter import Parameter
from .basic_layers import Activation


def _pair(x, n):
    if isinstance(x, (tuple, list)):
        return tuple(x)
    return (x,) * n


class _Conv(HybridBlock):
    def __init__(self, channels, kernel_size, strides, padding, dilation,
                 groups, layout, in_channels=0, activation=None, use_bias=True,
                 weight_initializer=None, bias_initializer="zeros",
                 op_name="convolution", adj=None, dtype="float32"):
        super().__init__()
        ndim = len(kernel_size)
        self._channels = channels
        self._in_channels = in_channels
        self._kernel = kernel_size
        self._strides = _pair(strides, ndim)
        self._padding = _pair(padding, ndim)
        self._dilation = _pair(dilation, ndim)
        self._groups = groups
        self._layout = layout
        self._op_name = op_name
        self._adj = adj
        if op_name == "convolution":
            wshape = (channels, in_channels // groups if in_channels else 0) \
                + kernel_size
        else:  # deconvolution weight is (in, out//groups, *k)
            wshape = (in_channels if in_channels else 0, channels // groups) \
                + kernel_size
        self.weight = Parameter("weight", shape=wshape, dtype=dtype,
                                init=weight_initializer,
                                allow_deferred_init=True)
        self.bias = (Parameter("bias", shape=(channels,), dtype=dtype,
                               init=bias_initializer, allow_deferred_init=True)
                     if use_bias else None)
        self.act = Activation(activation) if activation else None

    def forward(self, x):
        if not self.weight._shape_known():
            c_axis = self._layout.index("C")
            in_ch = x.shape[c_axis]
            if self._op_name == "convolution":
                shape = (self._channels, in_ch // self._groups) + self._kernel
            else:
                shape = (in_ch, self._channels // self._groups) + self._kernel
            self.weight._finish_deferred_init(shape)
        if self.bias is not None and self.bias._data is None:
            self.bias._finish_deferred_init()
        b = self.bias.data() if self.bias is not None else None
        if self._op_name == "convolution":
            out = npx.convolution(x, self.weight.data(), b,
                                  kernel=self._kernel, stride=self._strides,
                                  dilate=self._dilation, pad=self._padding,
                                  num_filter=self._channels,
                                  num_group=self._groups,
                                  no_bias=b is None, layout=self._layout)
        else:
            out = npx.deconvolution(x, self.weight.data(), b,
                                    kernel=self._kernel, stride=self._strides,
                                    dilate=self._dilation, pad=self._padding,
                                    adj=self._adj, num_filter=self._channels,
                                    num_group=self._groups,
                                    no_bias=b is None, layout=self._layout)
        return self.act(out) if self.act is not None else out

    def __repr__(self):
        return (f"{type(self).__name__}({self._channels}, "
                f"kernel_size={self._kernel}, stride={self._strides})")


class Conv1D(_Conv):
    def __init__(self, channels, kernel_size, strides=1, padding=0,
                 dilation=1, groups=1, layout="NCW", activation=None,
                 use_bias=True, weight_initializer=None,
                 bias_initializer="zeros", in_channels=0, **kwargs):
        super().__init__(channels, _pair(kernel_size, 1), strides, padding,
                         dilation, groups, layout, in_channels, activation,
                         use_bias, weight_initializer, bias_initializer)


class Conv2D(_Conv):
    def __init__(self, channels, kernel_size, strides=(1, 1), padding=(0, 0),
                 dilation=(1, 1), groups=1, layout="NCHW", activation=None,
                 use_bias=True, weight_initializer=None,
                 bias_initializer="zeros", in_channels=0, **kwargs):
        super().__init__(channels, _pair(kernel_size, 2), strides, padding,
                         dilation, groups, layout, in_channels, activation,
                         use_bias, weight_initializer, bias_initializer)


class Conv3D(_Conv):
    def __init__(self, channels, kernel_size, strides=(1, 1, 1),
                 padding=(0, 0, 0), dilation=(1, 1, 1), groups=1,
                 layout="NCDHW", activation=None, use_bias=True,
                 weight_initializer=None, bias_initializer="zeros",
                 in_channels=0, **kwargs):
        super().__init__(channels, _pair(kernel_size, 3), strides, padding,
                         dilation, groups, layout, in_channels, activation,
                         use_bias, weight_initializer, bias_initializer)


class Conv1DTranspose(_Conv):
    def __init__(self, channels, kernel_size, strides=1, padding=0,
                 output_padding=0, dilation=1, groups=1, layout="NCW",
                 activation=None, use_bias=True, weight_initializer=None,
                 bias_initializer="zeros", in_channels=0, **kwargs):
        super().__init__(channels, _pair(kernel_size, 1), strides, padding,
                         dilation, groups, layout, in_channels, activation,
                         use_bias, weight_initializer, bias_initializer,
                         op_name="deconvolution",
                         adj=_pair(output_padding, 1))


class Conv2DTranspose(_Conv):
    def __init__(self, channels, kernel_size, strides=(1, 1), padding=(0, 0),
                 output_padding=(0, 0), dilation=(1, 1), groups=1,
                 layout="NCHW", activation=None, use_bias=True,
                 weight_initializer=None, bias_initializer="zeros",
                 in_channels=0, **kwargs):
        super().__init__(channels, _pair(kernel_size, 2), strides, padding,
                         dilation, groups, layout, in_channels, activation,
                         use_bias, weight_initializer, bias_initializer,
                         op_name="deconvolution",
                         adj=_pair(output_padding, 2))


class Conv3DTranspose(_Conv):
    def __init__(self, channels, kernel_size, strides=(1, 1, 1),
                 padding=(0, 0, 0), output_padding=(0, 0, 0),
                 dilation=(1, 1, 1), groups=1, layout="NCDHW",
                 activation=None, use_bias=True, weight_initializer=None,
                 bias_initializer="zeros", in_channels=0, **kwargs):
        super().__init__(channels, _pair(kernel_size, 3), strides, padding,
                         dilation, groups, layout, in_channels, activation,
                         use_bias, weight_initializer, bias_initializer,
                         op_name="deconvolution",
                         adj=_pair(output_padding, 3))


class _Pooling(HybridBlock):
    def __init__(self, pool_size, strides, padding, ceil_mode=False,
                 global_pool=False, pool_type="max", layout="NCHW",
                 count_include_pad=True):
        super().__init__()
        self._pool_size = pool_size
        self._strides = strides if strides is not None else pool_size
        self._padding = padding
        self._global = global_pool
        self._pool_type = pool_type
        self._layout = layout
        self._count_include_pad = count_include_pad

    def forward(self, x):
        return npx.pooling(
            x, kernel=self._pool_size, stride=self._strides,
            pad=self._padding, pool_type=self._pool_type,
            global_pool=self._global, layout=self._layout,
            count_include_pad=self._count_include_pad)

    def __repr__(self):
        return (f"{type(self).__name__}(size={self._pool_size}, "
                f"stride={self._strides}, padding={self._padding})")


class MaxPool1D(_Pooling):
    def __init__(self, pool_size=2, strides=None, padding=0, layout="NCW",
                 ceil_mode=False, **kwargs):
        super().__init__(_pair(pool_size, 1), _pair(strides or pool_size, 1),
                         _pair(padding, 1), ceil_mode, False, "max", layout)


class MaxPool2D(_Pooling):
    def __init__(self, pool_size=(2, 2), strides=None, padding=0,
                 layout="NCHW", ceil_mode=False, **kwargs):
        super().__init__(_pair(pool_size, 2),
                         _pair(strides if strides is not None else pool_size, 2),
                         _pair(padding, 2), ceil_mode, False, "max", layout)


class MaxPool3D(_Pooling):
    def __init__(self, pool_size=(2, 2, 2), strides=None, padding=0,
                 layout="NCDHW", ceil_mode=False, **kwargs):
        super().__init__(_pair(pool_size, 3),
                         _pair(strides if strides is not None else pool_size, 3),
                         _pair(padding, 3), ceil_mode, False, "max", layout)


class AvgPool1D(_Pooling):
    def __init__(self, pool_size=2, strides=None, padding=0, layout="NCW",
                 ceil_mode=False, count_include_pad=True, **kwargs):
        super().__init__(_pair(pool_size, 1),
                         _pair(strides if strides is not None else pool_size, 1),
                         _pair(padding, 1), ceil_mode, False, "avg", layout,
                         count_include_pad)


class AvgPool2D(_Pooling):
    def __init__(self, pool_size=(2, 2), strides=None, padding=0,
                 layout="NCHW", ceil_mode=False, count_include_pad=True,
                 **kwargs):
        super().__init__(_pair(pool_size, 2),
                         _pair(strides if strides is not None else pool_size, 2),
                         _pair(padding, 2), ceil_mode, False, "avg", layout,
                         count_include_pad)


class AvgPool3D(_Pooling):
    def __init__(self, pool_size=(2, 2, 2), strides=None, padding=0,
                 layout="NCDHW", ceil_mode=False, count_include_pad=True,
                 **kwargs):
        super().__init__(_pair(pool_size, 3),
                         _pair(strides if strides is not None else pool_size, 3),
                         _pair(padding, 3), ceil_mode, False, "avg", layout,
                         count_include_pad)


class GlobalMaxPool1D(_Pooling):
    def __init__(self, layout="NCW", **kwargs):
        super().__init__((1,), (1,), (0,), False, True, "max", layout)


class GlobalMaxPool2D(_Pooling):
    def __init__(self, layout="NCHW", **kwargs):
        super().__init__((1, 1), (1, 1), (0, 0), False, True, "max", layout)


class GlobalMaxPool3D(_Pooling):
    def __init__(self, layout="NCDHW", **kwargs):
        super().__init__((1, 1, 1), (1, 1, 1), (0, 0, 0), False, True, "max",
                         layout)


class GlobalAvgPool1D(_Pooling):
    def __init__(self, layout="NCW", **kwargs):
        super().__init__((1,), (1,), (0,), False, True, "avg", layout)


class GlobalAvgPool2D(_Pooling):
    def __init__(self, layout="NCHW", **kwargs):
        super().__init__((1, 1), (1, 1), (0, 0), False, True, "avg", layout)


class GlobalAvgPool3D(_Pooling):
    def __init__(self, layout="NCDHW", **kwargs):
        super().__init__((1, 1, 1), (1, 1, 1), (0, 0, 0), False, True, "avg",
                         layout)


class ReflectionPad2D(HybridBlock):
    """Reference: conv_layers.py ReflectionPad2D (pad op, mode='reflect')."""

    def __init__(self, padding=0):
        super().__init__()
        self._padding = _pair(padding, 4) if not isinstance(padding, int) \
            else (padding,) * 4

    def forward(self, x):
        p = self._padding
        pad_width = ((0, 0), (0, 0), (p[0], p[1]), (p[2], p[3])) \
            if len(p) == 4 else ((0, 0), (0, 0), (p[0], p[0]), (p[1], p[1]))
        return _np.pad(x, pad_width=pad_width, mode="reflect")


class DeformableConvolution(HybridBlock):
    """2-D deformable convolution v1 (Dai 2017).

    Reference: gluon/nn/conv_layers.py:1277 over
    src/operator/contrib/deformable_convolution.cc. The offset-generating
    convolution and the deformable convolution are both in this layer; see
    ops/deformable.py for the TPU-native sampling kernel.
    """

    def __init__(self, channels, kernel_size=(1, 1), strides=(1, 1),
                 padding=(0, 0), dilation=(1, 1), groups=1,
                 num_deformable_group=1, layout="NCHW", use_bias=True,
                 in_channels=0, activation=None, weight_initializer=None,
                 bias_initializer="zeros", offset_weight_initializer="zeros",
                 offset_bias_initializer="zeros", offset_use_bias=True,
                 modulated=False):
        super().__init__()
        if layout != "NCHW":
            raise ValueError("DeformableConvolution supports NCHW only")
        kernel_size = _pair(kernel_size, 2)
        self._channels = channels
        self._kernel = kernel_size
        self._strides = _pair(strides, 2)
        self._padding = _pair(padding, 2)
        self._dilation = _pair(dilation, 2)
        self._groups = groups
        self._ndg = num_deformable_group
        self._modulated = modulated
        K = kernel_size[0] * kernel_size[1]
        self._offset_split = 2 * K * num_deformable_group
        offset_channels = (3 if modulated else 2) * K * num_deformable_group
        self._offset_channels = offset_channels
        self.offset_weight = Parameter(
            "offset_weight",
            shape=(offset_channels, in_channels // groups if in_channels
                   else 0) + kernel_size,
            init=offset_weight_initializer, allow_deferred_init=True)
        self.offset_bias = (Parameter("offset_bias", shape=(offset_channels,),
                                      init=offset_bias_initializer,
                                      allow_deferred_init=True)
                            if offset_use_bias else None)
        self.deformable_conv_weight = Parameter(
            "deformable_conv_weight",
            shape=(channels, in_channels // groups if in_channels else 0)
            + kernel_size,
            init=weight_initializer, allow_deferred_init=True)
        self.deformable_conv_bias = (
            Parameter("deformable_conv_bias", shape=(channels,),
                      init=bias_initializer, allow_deferred_init=True)
            if use_bias else None)
        self.act = Activation(activation) if activation else None

    def forward(self, x):
        in_ch = x.shape[1]
        for p, ch in ((self.offset_weight, self._offset_channels),
                      (self.deformable_conv_weight, self._channels)):
            if not p._shape_known():
                p._finish_deferred_init(
                    (ch, in_ch // self._groups) + self._kernel)
        for p in (self.offset_bias, self.deformable_conv_bias):
            if p is not None and p._data is None:
                p._finish_deferred_init()
        conv_kw = dict(kernel=self._kernel, stride=self._strides,
                       pad=self._padding, dilate=self._dilation,
                       num_group=self._groups)
        off = npx.convolution(
            x, self.offset_weight.data(),
            self.offset_bias.data() if self.offset_bias is not None else None,
            num_filter=self._offset_channels,
            no_bias=self.offset_bias is None, layout="NCHW", **conv_kw)
        b = (self.deformable_conv_bias.data()
             if self.deformable_conv_bias is not None else None)
        if self._modulated:
            offset_t = off[:, :self._offset_split]
            mask = npx.sigmoid(off[:, self._offset_split:]) * 2
            out = npx.modulated_deformable_convolution(
                x, offset_t, mask, self.deformable_conv_weight.data(), b,
                num_filter=self._channels, no_bias=b is None,
                num_deformable_group=self._ndg, **conv_kw)
        else:
            out = npx.deformable_convolution(
                x, off, self.deformable_conv_weight.data(), b,
                num_filter=self._channels, no_bias=b is None,
                num_deformable_group=self._ndg, **conv_kw)
        return self.act(out) if self.act is not None else out

    def __repr__(self):
        return (f"{type(self).__name__}({self._channels}, "
                f"kernel_size={self._kernel}, stride={self._strides}, "
                f"num_deformable_group={self._ndg})")


class ModulatedDeformableConvolution(DeformableConvolution):
    """DCN v2 (reference: conv_layers.py:1501): a learned sigmoid mask
    modulates every sampled value; the offset conv emits 3*K*ndg channels
    (2K offsets + K mask)."""

    def __init__(self, channels, kernel_size=(1, 1), strides=(1, 1),
                 padding=(0, 0), dilation=(1, 1), groups=1,
                 num_deformable_group=1, layout="NCHW", use_bias=True,
                 in_channels=0, activation=None, weight_initializer=None,
                 bias_initializer="zeros", offset_weight_initializer="zeros",
                 offset_bias_initializer="zeros", offset_use_bias=True):
        super().__init__(channels, kernel_size, strides, padding, dilation,
                         groups, num_deformable_group, layout, use_bias,
                         in_channels, activation, weight_initializer,
                         bias_initializer, offset_weight_initializer,
                         offset_bias_initializer, offset_use_bias,
                         modulated=True)


class PixelShuffle1D(HybridBlock):
    """(N, C*f, W) -> (N, C, W*f) (reference: conv_layers.py:1707)."""

    def __init__(self, factor):
        super().__init__()
        self._factor = int(factor)

    def forward(self, x):
        f = self._factor
        n, cf, w = x.shape
        x = x.reshape(n, cf // f, f, w)
        x = x.transpose(0, 1, 3, 2)          # (N, C, W, f)
        return x.reshape(n, cf // f, w * f)

    def __repr__(self):
        return f"{type(self).__name__}({self._factor})"


class PixelShuffle2D(HybridBlock):
    """(N, C*f1*f2, H, W) -> (N, C, H*f1, W*f2) (reference:
    conv_layers.py:1755)."""

    def __init__(self, factor):
        super().__init__()
        self._factors = _pair(factor, 2)

    def forward(self, x):
        f1, f2 = self._factors
        n, c, h, w = x.shape
        co = c // (f1 * f2)
        x = x.reshape(n, co, f1, f2, h, w)
        x = x.transpose(0, 1, 4, 2, 5, 3)    # (N, C, H, f1, W, f2)
        return x.reshape(n, co, h * f1, w * f2)

    def __repr__(self):
        return f"{type(self).__name__}({self._factors})"


class PixelShuffle3D(HybridBlock):
    """(N, C*f1*f2*f3, D, H, W) -> (N, C, D*f1, H*f2, W*f3) (reference:
    conv_layers.py:1818)."""

    def __init__(self, factor):
        super().__init__()
        self._factors = _pair(factor, 3)

    def forward(self, x):
        f1, f2, f3 = self._factors
        n, c, d, h, w = x.shape
        co = c // (f1 * f2 * f3)
        x = x.reshape(n, co, f1, f2, f3, d, h, w)
        x = x.transpose(0, 1, 5, 2, 6, 3, 7, 4)  # (N,C,D,f1,H,f2,W,f3)
        return x.reshape(n, co, d * f1, h * f2, w * f3)

    def __repr__(self):
        return f"{type(self).__name__}({self._factors})"

"""Bijective transformations + TransformedDistribution.

Reference parity: python/mxnet/gluon/probability/transformation/
(transformation.py Transformation/ComposeTransform/Exp/Affine/Sigmoid/...,
distributions/transformed_distribution.py). log_det_jacobian terms follow
the change-of-variables formula; everything jnp-composable.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...numpy.multiarray import ndarray, _wrap
from .distributions import Distribution


def _raw(x):
    return x._data if isinstance(x, ndarray) else jnp.asarray(x)


class Transformation:
    """Reference: transformation.py Transformation."""

    bijective = True
    sign = 1

    def __call__(self, x):
        return _wrap(self._forward(_raw(x)))

    def inv(self, y):
        return _wrap(self._inverse(_raw(y)))

    def log_det_jacobian(self, x, y):
        return _wrap(self._log_det(_raw(x), _raw(y)))

    def _forward(self, x):
        raise NotImplementedError

    def _inverse(self, y):
        raise NotImplementedError

    def _log_det(self, x, y):
        raise NotImplementedError


class ExpTransform(Transformation):
    def _forward(self, x):
        return jnp.exp(x)

    def _inverse(self, y):
        return jnp.log(y)

    def _log_det(self, x, y):
        return x


class LogTransform(Transformation):
    def _forward(self, x):
        return jnp.log(x)

    def _inverse(self, y):
        return jnp.exp(y)

    def _log_det(self, x, y):
        return -jnp.log(x)


class AffineTransform(Transformation):
    def __init__(self, loc=0.0, scale=1.0):
        self.loc = _raw(loc)
        self.scale = _raw(scale)

    def _forward(self, x):
        return self.loc + self.scale * x

    def _inverse(self, y):
        return (y - self.loc) / self.scale

    def _log_det(self, x, y):
        return jnp.broadcast_to(jnp.log(jnp.abs(self.scale)), jnp.shape(x))


class SigmoidTransform(Transformation):
    def _forward(self, x):
        return jax.nn.sigmoid(x)

    def _inverse(self, y):
        return jnp.log(y) - jnp.log1p(-y)

    def _log_det(self, x, y):
        return -jax.nn.softplus(-x) - jax.nn.softplus(x)


class SoftmaxTransform(Transformation):
    """Map reals to the simplex along the last axis (reference:
    transformation.py:264; not bijective — log is a one-sided inverse)."""

    bijective = False
    event_dim = 1

    def _forward(self, x):
        return jax.nn.softmax(x, -1)

    def _inverse(self, y):
        return jnp.log(y)


class AbsTransform(Transformation):
    bijective = False

    def _forward(self, x):
        return jnp.abs(x)

    def _inverse(self, y):
        return y


class PowerTransform(Transformation):
    def __init__(self, exponent):
        self.exponent = _raw(exponent)

    def _forward(self, x):
        return jnp.power(x, self.exponent)

    def _inverse(self, y):
        return jnp.power(y, 1.0 / self.exponent)

    def _log_det(self, x, y):
        return jnp.log(jnp.abs(self.exponent * y / x))


class ComposeTransform(Transformation):
    def __init__(self, parts):
        self.parts = list(parts)

    def _forward(self, x):
        for t in self.parts:
            x = t._forward(x)
        return x

    def _inverse(self, y):
        for t in reversed(self.parts):
            y = t._inverse(y)
        return y

    def _log_det(self, x, y):
        total = 0.0
        cur = x
        for t in self.parts:
            nxt = t._forward(cur)
            total = total + t._log_det(cur, nxt)
            cur = nxt
        return total


class TransformedDistribution(Distribution):
    """Distribution of T(X) for X ~ base (reference:
    transformed_distribution.py)."""

    def __init__(self, base, transforms, **kwargs):
        super().__init__(**kwargs)
        self.base_dist = base
        if isinstance(transforms, Transformation):
            transforms = [transforms]
        self.transforms = list(transforms)
        self.has_grad = base.has_grad

    def _batch_shape(self):
        return self.base_dist._batch_shape()

    def _sample(self, key, shape):
        x = self.base_dist._sample(key, shape)
        for t in self.transforms:
            x = t._forward(x)
        return x

    def _log_prob(self, y):
        lp = 0.0
        cur = y
        for t in reversed(self.transforms):
            x = t._inverse(cur)
            lp = lp - t._log_det(x, cur)
            cur = x
        return lp + self.base_dist._log_prob(cur)

"""Distributions.

Reference parity: python/mxnet/gluon/probability/distributions/*.py
(Distribution base distribution.py, ~25 concrete families, divergence.py KL
registry). Densities use jnp/jax.scipy; samplers use jax.random with keys
from the mx.random facade so mx.random.seed reproduces runs.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from ... import random as _random
from ...numpy.multiarray import ndarray, _wrap

__all__ = [
    "Distribution", "ExponentialFamily", "Normal", "Bernoulli", "Categorical",
    "OneHotCategorical", "Uniform", "Exponential", "Gamma", "Beta",
    "Dirichlet", "Laplace", "Cauchy", "HalfCauchy", "HalfNormal", "Chi2",
    "Poisson", "Geometric", "Binomial", "Multinomial", "NegativeBinomial",
    "MultivariateNormal", "Gumbel", "Pareto", "StudentT", "FisherSnedecor",
    "Weibull",
    "Independent", "RelaxedBernoulli", "RelaxedOneHotCategorical",
    "kl_divergence", "register_kl",
]


def _raw(x):
    return x._data if isinstance(x, ndarray) else jnp.asarray(x)


def _shape(size, base=()):
    if size is None:
        return tuple(base)
    if isinstance(size, int):
        return (size,) + tuple(base)
    return tuple(size) + tuple(base)


class Distribution:
    """Base distribution (reference: distributions/distribution.py).

    has_grad: samples are reparameterized (pathwise gradients flow).
    """

    has_grad = False
    support = None
    arg_constraints = {}

    def __init__(self, F=None, event_dim=0, validate_args=None):
        self.F = F
        self.event_dim = event_dim

    # subclasses implement _sample(key, shape) and log_prob on raw arrays
    def sample(self, size=None):
        return _wrap(self._sample(_random._next_key(), _shape(
            size, self._batch_shape())))

    def sample_n(self, n=None):
        size = (n,) if isinstance(n, int) else tuple(n or ())
        return _wrap(self._sample(_random._next_key(),
                                  size + tuple(self._batch_shape())))

    def rsample(self, size=None):
        if not self.has_grad:
            raise NotImplementedError(
                f"{type(self).__name__} has no reparameterized sampler")
        return self.sample(size)

    def log_prob(self, value):
        return _wrap(self._log_prob(_raw(value)))

    def prob(self, value):
        return _wrap(jnp.exp(self._log_prob(_raw(value))))

    def cdf(self, value):
        return _wrap(self._cdf(_raw(value)))

    def icdf(self, value):
        return _wrap(self._icdf(_raw(value)))

    @property
    def mean(self):
        return _wrap(self._mean())

    @property
    def variance(self):
        return _wrap(self._variance())

    @property
    def stddev(self):
        return _wrap(jnp.sqrt(self._variance()))

    def entropy(self):
        return _wrap(self._entropy())

    def perplexity(self):
        return _wrap(jnp.exp(self._entropy()))

    def _batch_shape(self):
        return ()

    def _cdf(self, value):
        raise NotImplementedError

    def _icdf(self, value):
        raise NotImplementedError

    def _entropy(self):
        raise NotImplementedError

    def _mean(self):
        raise NotImplementedError

    def _variance(self):
        raise NotImplementedError

    def broadcast_to(self, batch_shape):
        return self


class ExponentialFamily(Distribution):
    """Reference: distributions/exp_family.py."""


class Normal(ExponentialFamily):
    """Reference: distributions/normal.py."""

    has_grad = True

    def __init__(self, loc=0.0, scale=1.0, **kwargs):
        super().__init__(**kwargs)
        self.loc = _raw(loc)
        self.scale = _raw(scale)

    def _batch_shape(self):
        return jnp.broadcast_shapes(jnp.shape(self.loc),
                                    jnp.shape(self.scale))

    def _sample(self, key, shape):
        return self.loc + self.scale * jax.random.normal(key, shape)

    def _log_prob(self, x):
        var = self.scale ** 2
        return (-((x - self.loc) ** 2) / (2 * var)
                - jnp.log(self.scale) - 0.5 * math.log(2 * math.pi))

    def _cdf(self, x):
        return 0.5 * (1 + jax.scipy.special.erf(
            (x - self.loc) / (self.scale * math.sqrt(2.0))))

    def _icdf(self, q):
        return self.loc + self.scale * math.sqrt(2.0) * \
            jax.scipy.special.erfinv(2 * q - 1)

    def _mean(self):
        return jnp.broadcast_to(self.loc, self._batch_shape())

    def _variance(self):
        return jnp.broadcast_to(self.scale ** 2, self._batch_shape())

    def _entropy(self):
        return 0.5 + 0.5 * math.log(2 * math.pi) + jnp.log(
            jnp.broadcast_to(self.scale, self._batch_shape()))


class Laplace(Distribution):
    """Reference: distributions/laplace.py."""

    has_grad = True

    def __init__(self, loc=0.0, scale=1.0, **kwargs):
        super().__init__(**kwargs)
        self.loc = _raw(loc)
        self.scale = _raw(scale)

    def _batch_shape(self):
        return jnp.broadcast_shapes(jnp.shape(self.loc),
                                    jnp.shape(self.scale))

    def _sample(self, key, shape):
        return self.loc + self.scale * jax.random.laplace(key, shape)

    def _log_prob(self, x):
        return -jnp.abs(x - self.loc) / self.scale - jnp.log(2 * self.scale)

    def _cdf(self, x):
        z = (x - self.loc) / self.scale
        return 0.5 - 0.5 * jnp.sign(z) * jnp.expm1(-jnp.abs(z))

    def _mean(self):
        return jnp.broadcast_to(self.loc, self._batch_shape())

    def _variance(self):
        return jnp.broadcast_to(2 * self.scale ** 2, self._batch_shape())

    def _entropy(self):
        return 1 + jnp.log(2 * jnp.broadcast_to(self.scale,
                                                self._batch_shape()))


class Cauchy(Distribution):
    """Reference: distributions/cauchy.py."""

    has_grad = True

    def __init__(self, loc=0.0, scale=1.0, **kwargs):
        super().__init__(**kwargs)
        self.loc = _raw(loc)
        self.scale = _raw(scale)

    def _batch_shape(self):
        return jnp.broadcast_shapes(jnp.shape(self.loc),
                                    jnp.shape(self.scale))

    def _sample(self, key, shape):
        return self.loc + self.scale * jax.random.cauchy(key, shape)

    def _log_prob(self, x):
        z = (x - self.loc) / self.scale
        return -jnp.log(math.pi * self.scale * (1 + z ** 2))

    def _cdf(self, x):
        return jnp.arctan((x - self.loc) / self.scale) / math.pi + 0.5

    def _icdf(self, q):
        return self.loc + self.scale * jnp.tan(math.pi * (q - 0.5))

    def _entropy(self):
        return jnp.log(4 * math.pi * jnp.broadcast_to(
            self.scale, self._batch_shape()))


class HalfCauchy(Distribution):
    """Reference: distributions/half_cauchy.py."""

    has_grad = True

    def __init__(self, scale=1.0, **kwargs):
        super().__init__(**kwargs)
        self.scale = _raw(scale)

    def _batch_shape(self):
        return jnp.shape(self.scale)

    def _sample(self, key, shape):
        return jnp.abs(self.scale * jax.random.cauchy(key, shape))

    def _log_prob(self, x):
        z = x / self.scale
        lp = math.log(2 / math.pi) - jnp.log(self.scale) - jnp.log1p(z ** 2)
        return jnp.where(x >= 0, lp, -jnp.inf)

    def _cdf(self, x):
        return 2 * jnp.arctan(x / self.scale) / math.pi

    def _icdf(self, q):
        return self.scale * jnp.tan(math.pi * q / 2)


class HalfNormal(Distribution):
    """Reference: distributions/half_normal.py."""

    has_grad = True

    def __init__(self, scale=1.0, **kwargs):
        super().__init__(**kwargs)
        self.scale = _raw(scale)

    def _batch_shape(self):
        return jnp.shape(self.scale)

    def _sample(self, key, shape):
        return jnp.abs(self.scale * jax.random.normal(key, shape))

    def _log_prob(self, x):
        lp = (0.5 * math.log(2 / math.pi) - jnp.log(self.scale)
              - x ** 2 / (2 * self.scale ** 2))
        return jnp.where(x >= 0, lp, -jnp.inf)

    def _cdf(self, x):
        return jax.scipy.special.erf(x / (self.scale * math.sqrt(2.0)))

    def _mean(self):
        return self.scale * math.sqrt(2 / math.pi)

    def _variance(self):
        return self.scale ** 2 * (1 - 2 / math.pi)


class Uniform(Distribution):
    """Reference: distributions/uniform.py."""

    has_grad = True

    def __init__(self, low=0.0, high=1.0, **kwargs):
        super().__init__(**kwargs)
        self.low = _raw(low)
        self.high = _raw(high)

    def _batch_shape(self):
        return jnp.broadcast_shapes(jnp.shape(self.low),
                                    jnp.shape(self.high))

    def _sample(self, key, shape):
        return jax.random.uniform(key, shape) * (self.high - self.low) \
            + self.low

    def _log_prob(self, x):
        inside = (x >= self.low) & (x <= self.high)
        return jnp.where(inside, -jnp.log(self.high - self.low), -jnp.inf)

    def _cdf(self, x):
        return jnp.clip((x - self.low) / (self.high - self.low), 0.0, 1.0)

    def _icdf(self, q):
        return self.low + q * (self.high - self.low)

    def _mean(self):
        return (self.low + self.high) / 2

    def _variance(self):
        return (self.high - self.low) ** 2 / 12

    def _entropy(self):
        return jnp.log(self.high - self.low)


class Exponential(ExponentialFamily):
    """Reference: distributions/exponential.py."""

    has_grad = True

    def __init__(self, rate=1.0, **kwargs):
        super().__init__(**kwargs)
        self.rate = _raw(rate)

    def _batch_shape(self):
        return jnp.shape(self.rate)

    def _sample(self, key, shape):
        return jax.random.exponential(key, shape) / self.rate

    def _log_prob(self, x):
        return jnp.log(self.rate) - self.rate * x

    def _cdf(self, x):
        return -jnp.expm1(-self.rate * x)

    def _icdf(self, q):
        return -jnp.log1p(-q) / self.rate

    def _mean(self):
        return 1.0 / self.rate

    def _variance(self):
        return self.rate ** -2

    def _entropy(self):
        return 1.0 - jnp.log(self.rate)


class Gamma(ExponentialFamily):
    """Reference: distributions/gamma.py (shape/rate parameterization)."""

    has_grad = True

    def __init__(self, shape=1.0, scale=1.0, **kwargs):
        super().__init__(**kwargs)
        self.shape_p = _raw(shape)
        self.scale = _raw(scale)

    def _batch_shape(self):
        return jnp.broadcast_shapes(jnp.shape(self.shape_p),
                                    jnp.shape(self.scale))

    def _sample(self, key, shape):
        return jax.random.gamma(key, self.shape_p, shape) * self.scale

    def _log_prob(self, x):
        a = self.shape_p
        return ((a - 1) * jnp.log(x) - x / self.scale
                - jax.scipy.special.gammaln(a) - a * jnp.log(self.scale))

    def _mean(self):
        return self.shape_p * self.scale

    def _variance(self):
        return self.shape_p * self.scale ** 2

    def _entropy(self):
        a = self.shape_p
        return (a + jnp.log(self.scale) + jax.scipy.special.gammaln(a)
                + (1 - a) * jax.scipy.special.digamma(a))


class Chi2(Gamma):
    """Reference: distributions/chi2.py."""

    def __init__(self, df, **kwargs):
        super().__init__(shape=_raw(df) / 2.0, scale=2.0, **kwargs)
        self.df = _raw(df)


class Beta(ExponentialFamily):
    """Reference: distributions/beta.py."""

    has_grad = True

    def __init__(self, alpha=1.0, beta=1.0, **kwargs):
        super().__init__(**kwargs)
        self.alpha = _raw(alpha)
        self.beta = _raw(beta)

    def _batch_shape(self):
        return jnp.broadcast_shapes(jnp.shape(self.alpha),
                                    jnp.shape(self.beta))

    def _sample(self, key, shape):
        return jax.random.beta(key, self.alpha, self.beta, shape)

    def _log_prob(self, x):
        a, b = self.alpha, self.beta
        return ((a - 1) * jnp.log(x) + (b - 1) * jnp.log1p(-x)
                - (jax.scipy.special.gammaln(a) + jax.scipy.special.gammaln(b)
                   - jax.scipy.special.gammaln(a + b)))

    def _mean(self):
        return self.alpha / (self.alpha + self.beta)

    def _variance(self):
        s = self.alpha + self.beta
        return self.alpha * self.beta / (s ** 2 * (s + 1))


class Dirichlet(ExponentialFamily):
    """Reference: distributions/dirichlet.py."""

    has_grad = True

    def __init__(self, alpha, **kwargs):
        super().__init__(event_dim=1, **kwargs)
        self.alpha = _raw(alpha)

    def _batch_shape(self):
        return jnp.shape(self.alpha)[:-1]

    def _sample(self, key, shape):
        return jax.random.dirichlet(key, self.alpha, shape or None)

    def _log_prob(self, x):
        a = self.alpha
        norm = jnp.sum(jax.scipy.special.gammaln(a), -1) \
            - jax.scipy.special.gammaln(jnp.sum(a, -1))
        return jnp.sum((a - 1) * jnp.log(x), -1) - norm

    def _mean(self):
        return self.alpha / jnp.sum(self.alpha, -1, keepdims=True)


class Gumbel(Distribution):
    """Reference: distributions/gumbel.py."""

    has_grad = True

    def __init__(self, loc=0.0, scale=1.0, **kwargs):
        super().__init__(**kwargs)
        self.loc = _raw(loc)
        self.scale = _raw(scale)

    def _batch_shape(self):
        return jnp.broadcast_shapes(jnp.shape(self.loc),
                                    jnp.shape(self.scale))

    def _sample(self, key, shape):
        return self.loc + self.scale * jax.random.gumbel(key, shape)

    def _log_prob(self, x):
        z = (x - self.loc) / self.scale
        return -(z + jnp.exp(-z)) - jnp.log(self.scale)

    def _cdf(self, x):
        return jnp.exp(-jnp.exp(-(x - self.loc) / self.scale))

    def _mean(self):
        return self.loc + self.scale * 0.57721566490153286

    def _variance(self):
        return (math.pi ** 2 / 6) * self.scale ** 2


class Pareto(Distribution):
    """Reference: distributions/pareto.py."""

    has_grad = True

    def __init__(self, alpha, scale=1.0, **kwargs):
        super().__init__(**kwargs)
        self.alpha = _raw(alpha)
        self.scale = _raw(scale)

    def _batch_shape(self):
        return jnp.broadcast_shapes(jnp.shape(self.alpha),
                                    jnp.shape(self.scale))

    def _sample(self, key, shape):
        return self.scale * jax.random.pareto(key, self.alpha, shape)

    def _log_prob(self, x):
        lp = (jnp.log(self.alpha) + self.alpha * jnp.log(self.scale)
              - (self.alpha + 1) * jnp.log(x))
        return jnp.where(x >= self.scale, lp, -jnp.inf)

    def _cdf(self, x):
        return 1 - (self.scale / x) ** self.alpha


class Weibull(Distribution):
    """Reference: distributions/weibull.py (two-parameter Weibull built
    there as PowerTransform∘AffineTransform of Exponential; here the
    density/sampler are direct — same math, one fused program)."""

    has_grad = True

    def __init__(self, concentration, scale=1.0, **kwargs):
        super().__init__(**kwargs)
        self.concentration = _raw(concentration)
        self.scale = _raw(scale)

    def _batch_shape(self):
        return jnp.broadcast_shapes(jnp.shape(self.concentration),
                                    jnp.shape(self.scale))

    def _sample(self, key, shape):
        # inverse-CDF: scale * (-log U)^(1/k) — reparameterized
        u = jax.random.uniform(key, shape, jnp.result_type(float),
                               minval=jnp.finfo(jnp.float32).tiny)
        return self.scale * (-jnp.log(u)) ** (1.0 / self.concentration)

    def _log_prob(self, x):
        k, lam = self.concentration, self.scale
        z = x / lam
        # guard the x==0 boundary: (k-1)*log(0) is 0*inf=nan at k==1;
        # the density there is k/lam for k==1, 0 for k>1, +inf for k<1
        zsafe = jnp.where(x > 0, z, 1.0)
        lp = (jnp.log(k) - jnp.log(lam) + (k - 1) * jnp.log(zsafe)
              - z ** k)
        at0 = jnp.where(k == 1, jnp.log(k) - jnp.log(lam),
                        jnp.where(k > 1, -jnp.inf, jnp.inf))
        return jnp.where(x > 0, lp, jnp.where(x == 0, at0, -jnp.inf))

    def _cdf(self, x):
        return 1 - jnp.exp(-(x / self.scale) ** self.concentration)

    def _icdf(self, u):
        return self.scale * (-jnp.log1p(-u)) ** (1.0 / self.concentration)

    def _mean(self):
        return self.scale * jnp.exp(
            jax.scipy.special.gammaln(1 + 1 / self.concentration))

    def _variance(self):
        g = jax.scipy.special.gammaln
        t1 = jnp.exp(g(1 + 2 / self.concentration))
        t2 = jnp.exp(2 * g(1 + 1 / self.concentration))
        return self.scale ** 2 * (t1 - t2)

    def _entropy(self):
        k, lam = self.concentration, self.scale
        return (jnp.euler_gamma * (1 - 1 / k) + jnp.log(lam / k) + 1)


class StudentT(Distribution):
    """Reference: distributions/studentT.py."""

    has_grad = True

    def __init__(self, df, loc=0.0, scale=1.0, **kwargs):
        super().__init__(**kwargs)
        self.df = _raw(df)
        self.loc = _raw(loc)
        self.scale = _raw(scale)

    def _batch_shape(self):
        return jnp.broadcast_shapes(jnp.shape(self.df), jnp.shape(self.loc),
                                    jnp.shape(self.scale))

    def _sample(self, key, shape):
        return self.loc + self.scale * jax.random.t(key, self.df, shape)

    def _log_prob(self, x):
        v = self.df
        z = (x - self.loc) / self.scale
        return (jax.scipy.special.gammaln((v + 1) / 2)
                - jax.scipy.special.gammaln(v / 2)
                - 0.5 * jnp.log(v * math.pi) - jnp.log(self.scale)
                - (v + 1) / 2 * jnp.log1p(z ** 2 / v))


class FisherSnedecor(Distribution):
    """Reference: distributions/fishersnedecor.py (F distribution)."""

    def __init__(self, df1, df2, **kwargs):
        super().__init__(**kwargs)
        self.df1 = _raw(df1)
        self.df2 = _raw(df2)

    def _batch_shape(self):
        return jnp.broadcast_shapes(jnp.shape(self.df1),
                                    jnp.shape(self.df2))

    def _sample(self, key, shape):
        k1, k2 = jax.random.split(key)
        c1 = jax.random.chisquare(k1, self.df1, shape)
        c2 = jax.random.chisquare(k2, self.df2, shape)
        return (c1 / self.df1) / (c2 / self.df2)

    def _log_prob(self, x):
        d1, d2 = self.df1, self.df2
        lb = (jax.scipy.special.gammaln(d1 / 2)
              + jax.scipy.special.gammaln(d2 / 2)
              - jax.scipy.special.gammaln((d1 + d2) / 2))
        return (d1 / 2 * jnp.log(d1 / d2) + (d1 / 2 - 1) * jnp.log(x)
                - (d1 + d2) / 2 * jnp.log1p(d1 * x / d2) - lb)


class Poisson(ExponentialFamily):
    """Reference: distributions/poisson.py."""

    def __init__(self, rate=1.0, **kwargs):
        super().__init__(**kwargs)
        self.rate = _raw(rate)

    def _batch_shape(self):
        return jnp.shape(self.rate)

    def _sample(self, key, shape):
        return jax.random.poisson(key, self.rate, shape).astype(jnp.float32)

    def _log_prob(self, x):
        return (x * jnp.log(self.rate) - self.rate
                - jax.scipy.special.gammaln(x + 1))

    def _mean(self):
        return self.rate

    def _variance(self):
        return self.rate


class Geometric(Distribution):
    """Reference: distributions/geometric.py (#failures before success)."""

    def __init__(self, prob=None, logit=None, **kwargs):
        super().__init__(**kwargs)
        self.prob = _logit_or_prob(prob, logit)

    def _batch_shape(self):
        return jnp.shape(self.prob)

    def _sample(self, key, shape):
        u = jax.random.uniform(key, shape, minval=1e-7)
        return jnp.floor(jnp.log(u) / jnp.log1p(-self.prob))

    def _log_prob(self, x):
        return x * jnp.log1p(-self.prob) + jnp.log(self.prob)

    def _mean(self):
        return (1 - self.prob) / self.prob

    def _variance(self):
        return (1 - self.prob) / self.prob ** 2


def _logit_or_prob(prob, logit):
    if (prob is None) == (logit is None):
        raise ValueError("pass exactly one of prob / logit")
    return jax.nn.sigmoid(_raw(logit)) if prob is None else _raw(prob)


class Bernoulli(ExponentialFamily):
    """Reference: distributions/bernoulli.py."""

    def __init__(self, prob=None, logit=None, **kwargs):
        super().__init__(**kwargs)
        self.prob = _logit_or_prob(prob, logit)

    @property
    def logit(self):
        return _wrap(jnp.log(self.prob) - jnp.log1p(-self.prob))

    def _batch_shape(self):
        return jnp.shape(self.prob)

    def _sample(self, key, shape):
        return jax.random.bernoulli(key, self.prob, shape).astype(
            jnp.float32)

    def _log_prob(self, x):
        p = jnp.clip(self.prob, 1e-7, 1 - 1e-7)
        return x * jnp.log(p) + (1 - x) * jnp.log1p(-p)

    def _mean(self):
        return self.prob

    def _variance(self):
        return self.prob * (1 - self.prob)

    def _entropy(self):
        p = jnp.clip(self.prob, 1e-7, 1 - 1e-7)
        return -(p * jnp.log(p) + (1 - p) * jnp.log1p(-p))


class Binomial(Distribution):
    """Reference: distributions/binomial.py."""

    def __init__(self, n=1, prob=None, logit=None, **kwargs):
        super().__init__(**kwargs)
        self.n = _raw(n)
        self.prob = _logit_or_prob(prob, logit)

    def _batch_shape(self):
        return jnp.broadcast_shapes(jnp.shape(self.n), jnp.shape(self.prob))

    def _sample(self, key, shape):
        return jax.random.binomial(key, self.n, self.prob, shape)

    def _log_prob(self, x):
        n, p = self.n, jnp.clip(self.prob, 1e-7, 1 - 1e-7)
        logc = (jax.scipy.special.gammaln(n + 1)
                - jax.scipy.special.gammaln(x + 1)
                - jax.scipy.special.gammaln(n - x + 1))
        return logc + x * jnp.log(p) + (n - x) * jnp.log1p(-p)

    def _mean(self):
        return self.n * self.prob

    def _variance(self):
        return self.n * self.prob * (1 - self.prob)


class NegativeBinomial(Distribution):
    """Reference: distributions/negative_binomial.py."""

    def __init__(self, n, prob=None, logit=None, **kwargs):
        super().__init__(**kwargs)
        self.n = _raw(n)
        self.prob = _logit_or_prob(prob, logit)

    def _batch_shape(self):
        return jnp.broadcast_shapes(jnp.shape(self.n), jnp.shape(self.prob))

    def _sample(self, key, shape):
        k1, k2 = jax.random.split(key)
        lam = jax.random.gamma(k1, self.n, shape) \
            * (1 - self.prob) / self.prob
        return jax.random.poisson(k2, lam).astype(jnp.float32)

    def _log_prob(self, x):
        n, p = self.n, jnp.clip(self.prob, 1e-7, 1 - 1e-7)
        logc = (jax.scipy.special.gammaln(x + n)
                - jax.scipy.special.gammaln(x + 1)
                - jax.scipy.special.gammaln(n))
        return logc + n * jnp.log(p) + x * jnp.log1p(-p)

    def _mean(self):
        return self.n * (1 - self.prob) / self.prob


class Categorical(Distribution):
    """Reference: distributions/categorical.py."""

    def __init__(self, num_events=None, prob=None, logit=None, **kwargs):
        super().__init__(**kwargs)
        if prob is not None:
            self.logit = jnp.log(jnp.clip(_raw(prob), 1e-30))
        elif logit is not None:
            self.logit = _raw(logit)
        else:
            raise ValueError("pass prob or logit")
        self.num_events = self.logit.shape[-1]

    @property
    def prob(self):
        return _wrap(jax.nn.softmax(self.logit, -1))

    def _batch_shape(self):
        return jnp.shape(self.logit)[:-1]

    def _sample(self, key, shape):
        return jax.random.categorical(
            key, self.logit,
            shape=shape or None).astype(jnp.float32)

    def _log_prob(self, x):
        logp = jax.nn.log_softmax(self.logit, -1)
        return jnp.take_along_axis(
            logp, x[..., None].astype(jnp.int32), -1)[..., 0]

    def _entropy(self):
        logp = jax.nn.log_softmax(self.logit, -1)
        return -jnp.sum(jnp.exp(logp) * logp, -1)


class OneHotCategorical(Categorical):
    """Reference: distributions/one_hot_categorical.py."""

    def __init__(self, num_events=None, prob=None, logit=None, **kwargs):
        super().__init__(num_events, prob, logit, **kwargs)
        self.event_dim = 1

    def _sample(self, key, shape):
        idx = jax.random.categorical(key, self.logit, shape=shape or None)
        return jax.nn.one_hot(idx, self.num_events)

    def _log_prob(self, x):
        logp = jax.nn.log_softmax(self.logit, -1)
        return jnp.sum(logp * x, -1)


class Multinomial(Distribution):
    """Reference: distributions/multinomial.py."""

    def __init__(self, num_events=None, prob=None, logit=None,
                 total_count=1, **kwargs):
        super().__init__(event_dim=1, **kwargs)
        if prob is not None:
            self.prob_ = _raw(prob)
        else:
            self.prob_ = jax.nn.softmax(_raw(logit), -1)
        self.total_count = total_count
        self.num_events = self.prob_.shape[-1]

    def _batch_shape(self):
        return jnp.shape(self.prob_)[:-1]

    def _sample(self, key, shape):
        n = self.total_count
        idx = jax.random.categorical(
            key, jnp.log(jnp.clip(self.prob_, 1e-30)),
            shape=(n,) + tuple(shape or self._batch_shape()))
        return jnp.sum(jax.nn.one_hot(idx, self.num_events), axis=0)

    def _log_prob(self, x):
        logc = (jax.scipy.special.gammaln(jnp.sum(x, -1) + 1)
                - jnp.sum(jax.scipy.special.gammaln(x + 1), -1))
        return logc + jnp.sum(x * jnp.log(jnp.clip(self.prob_, 1e-30)), -1)


class MultivariateNormal(Distribution):
    """Reference: distributions/multivariate_normal.py."""

    has_grad = True

    def __init__(self, loc, cov=None, precision=None, scale_tril=None,
                 **kwargs):
        super().__init__(event_dim=1, **kwargs)
        self.loc = _raw(loc)
        if scale_tril is not None:
            self.scale_tril = _raw(scale_tril)
        elif cov is not None:
            self.scale_tril = jnp.linalg.cholesky(_raw(cov))
        elif precision is not None:
            self.scale_tril = jnp.linalg.cholesky(
                jnp.linalg.inv(_raw(precision)))
        else:
            raise ValueError("pass cov, precision, or scale_tril")

    @property
    def cov(self):
        return _wrap(self.scale_tril @ jnp.swapaxes(self.scale_tril, -1, -2))

    def _batch_shape(self):
        return jnp.shape(self.loc)[:-1]

    def _sample(self, key, shape):
        d = self.loc.shape[-1]
        eps = jax.random.normal(key, tuple(shape) + (d,))
        return self.loc + jnp.einsum("...ij,...j->...i", self.scale_tril, eps)

    def _log_prob(self, x):
        d = self.loc.shape[-1]
        diff = x - self.loc
        # triangular_solve needs matching batch dims
        tril = jnp.broadcast_to(
            self.scale_tril, diff.shape[:-1] + self.scale_tril.shape[-2:])
        sol = jax.scipy.linalg.solve_triangular(
            tril, diff[..., None], lower=True)[..., 0]
        maha = jnp.sum(sol ** 2, -1)
        logdet = jnp.sum(jnp.log(jnp.diagonal(self.scale_tril, axis1=-2,
                                              axis2=-1)), -1)
        return -0.5 * (d * math.log(2 * math.pi) + maha) - logdet

    def _mean(self):
        return self.loc


class Independent(Distribution):
    """Reinterpret batch dims as event dims (reference: independent.py)."""

    def __init__(self, base, reinterpreted_batch_ndims, **kwargs):
        super().__init__(**kwargs)
        self.base_dist = base
        self.ndims = reinterpreted_batch_ndims
        self.has_grad = base.has_grad
        self.event_dim = base.event_dim + reinterpreted_batch_ndims

    def _batch_shape(self):
        full = self.base_dist._batch_shape()
        return full[:len(full) - self.ndims]

    def _sample(self, key, shape):
        # shape excludes reinterpreted dims; base adds them back
        base_batch = self.base_dist._batch_shape()
        extra = base_batch[len(base_batch) - self.ndims:]
        return self.base_dist._sample(key, tuple(shape) + tuple(extra))

    def _log_prob(self, x):
        lp = self.base_dist._log_prob(x)
        for _ in range(self.ndims):
            lp = jnp.sum(lp, -1)
        return lp

    def _mean(self):
        return self.base_dist._mean()


class RelaxedBernoulli(Distribution):
    """Gumbel-sigmoid relaxation (reference: relaxed_bernoulli.py)."""

    has_grad = True

    def __init__(self, T=1.0, prob=None, logit=None, **kwargs):
        super().__init__(**kwargs)
        self.T = _raw(T)
        self.prob = _logit_or_prob(prob, logit)

    def _batch_shape(self):
        return jnp.shape(self.prob)

    def _sample(self, key, shape):
        logit = jnp.log(jnp.clip(self.prob, 1e-7)) \
            - jnp.log1p(-jnp.clip(self.prob, None, 1 - 1e-7))
        u = jax.random.uniform(key, shape, minval=1e-7, maxval=1 - 1e-7)
        noise = jnp.log(u) - jnp.log1p(-u)
        return jax.nn.sigmoid((logit + noise) / self.T)


class RelaxedOneHotCategorical(Distribution):
    """Gumbel-softmax relaxation (reference: relaxed_one_hot_categorical.py)."""

    has_grad = True

    def __init__(self, T=1.0, num_events=None, prob=None, logit=None,
                 **kwargs):
        super().__init__(event_dim=1, **kwargs)
        self.T = _raw(T)
        if prob is not None:
            self.logit = jnp.log(jnp.clip(_raw(prob), 1e-30))
        else:
            self.logit = _raw(logit)

    def _batch_shape(self):
        return jnp.shape(self.logit)[:-1]

    def _sample(self, key, shape):
        g = jax.random.gumbel(
            key, tuple(shape) + (self.logit.shape[-1],))
        return jax.nn.softmax((self.logit + g) / self.T, -1)


# ---------------------------------------------------------------------------
# KL divergence registry (reference: distributions/divergence.py)
# ---------------------------------------------------------------------------

_KL_REGISTRY = {}


def register_kl(type_p, type_q):
    def deco(fn):
        _KL_REGISTRY[(type_p, type_q)] = fn
        return fn
    return deco


def kl_divergence(p, q):
    """KL(p || q) (reference: divergence.py kl_divergence)."""
    for (tp, tq), fn in _KL_REGISTRY.items():
        if isinstance(p, tp) and isinstance(q, tq):
            return _wrap(fn(p, q))
    raise NotImplementedError(
        f"no KL registered for ({type(p).__name__}, {type(q).__name__})")


@register_kl(Normal, Normal)
def _kl_normal_normal(p, q):
    var_ratio = (p.scale / q.scale) ** 2
    t1 = ((p.loc - q.loc) / q.scale) ** 2
    return 0.5 * (var_ratio + t1 - 1 - jnp.log(var_ratio))


@register_kl(Bernoulli, Bernoulli)
def _kl_bern_bern(p, q):
    pp = jnp.clip(p.prob, 1e-7, 1 - 1e-7)
    qp = jnp.clip(q.prob, 1e-7, 1 - 1e-7)
    return (pp * (jnp.log(pp) - jnp.log(qp))
            + (1 - pp) * (jnp.log1p(-pp) - jnp.log1p(-qp)))


@register_kl(Categorical, Categorical)
def _kl_cat_cat(p, q):
    logp = jax.nn.log_softmax(p.logit, -1)
    logq = jax.nn.log_softmax(q.logit, -1)
    return jnp.sum(jnp.exp(logp) * (logp - logq), -1)


@register_kl(OneHotCategorical, OneHotCategorical)
def _kl_ohc_ohc(p, q):
    return _kl_cat_cat(p, q)


@register_kl(Uniform, Uniform)
def _kl_unif_unif(p, q):
    below = (p.low < q.low) | (p.high > q.high)
    kl = jnp.log((q.high - q.low) / (p.high - p.low))
    return jnp.where(below, jnp.inf, kl)


@register_kl(Exponential, Exponential)
def _kl_exp_exp(p, q):
    ratio = q.rate / p.rate
    return ratio - 1 - jnp.log(ratio)


@register_kl(Gamma, Gamma)
def _kl_gamma_gamma(p, q):
    ap, aq = p.shape_p, q.shape_p
    bp, bq = 1 / p.scale, 1 / q.scale
    return ((ap - aq) * jax.scipy.special.digamma(ap)
            - jax.scipy.special.gammaln(ap) + jax.scipy.special.gammaln(aq)
            + aq * (jnp.log(bp) - jnp.log(bq)) + ap * (bq - bp) / bp)


@register_kl(MultivariateNormal, MultivariateNormal)
def _kl_mvn_mvn(p, q):
    d = p.loc.shape[-1]
    q_tril = q.scale_tril
    p_tril = p.scale_tril
    logdet_q = jnp.sum(jnp.log(jnp.diagonal(q_tril, axis1=-2, axis2=-1)), -1)
    logdet_p = jnp.sum(jnp.log(jnp.diagonal(p_tril, axis1=-2, axis2=-1)), -1)
    m = jax.scipy.linalg.solve_triangular(q_tril, p_tril, lower=True)
    tr = jnp.sum(m ** 2, axis=(-2, -1))
    diff = q.loc - p.loc
    sol = jax.scipy.linalg.solve_triangular(
        q_tril, diff[..., None], lower=True)[..., 0]
    maha = jnp.sum(sol ** 2, -1)
    return logdet_q - logdet_p + 0.5 * (tr + maha - d)

"""gluon.probability — distributions, transformations, stochastic blocks.

Reference parity: python/mxnet/gluon/probability/ (6.5k LoC: ~30
distributions in distributions/, transformations in transformation/,
StochasticBlock in block/). TPU-native: densities/samplers are jnp +
jax.random compositions (fully jittable, explicit PRNG keys via the global
mx.random facade), so everything traces into hybridized blocks.
"""
from . import constraint  # noqa: F401
from .distributions import *  # noqa: F401,F403
from .distributions import kl_divergence, register_kl  # noqa: F401
from .transformation import (  # noqa: F401
    Transformation, ExpTransform, AffineTransform, SigmoidTransform,
    LogTransform, AbsTransform, PowerTransform, ComposeTransform,
    SoftmaxTransform, TransformedDistribution,
)
from .domain_map import biject_to, domain_map, transform_to  # noqa: F401
from .stochastic_block import (  # noqa: F401
    StochasticBlock, StochasticBlockGrad, StochasticSequential,
)

"""Distribution support constraints.

Reference parity: python/mxnet/gluon/probability/distributions/
constraint.py (Constraint base + ~25 region classes + the
dependent_property decorator; validation flows through the
_npx_constraint_check op). Here ``check`` evaluates the region predicate
with jnp and validates through npx.constraint_check — eager calls raise
ValueError immediately; traced calls return the value with the predicate
deferred to the caller (the reference's op raises at engine sync the
same way).
"""
from __future__ import annotations

import jax.numpy as jnp

from ... import numpy_extension as npx
from ...numpy.multiarray import ndarray

__all__ = [
    "Constraint", "Real", "Boolean", "Interval", "OpenInterval",
    "HalfOpenInterval", "IntegerInterval", "IntegerOpenInterval",
    "IntegerHalfOpenInterval", "GreaterThan", "GreaterThanEq", "LessThan",
    "LessThanEq", "IntegerGreaterThan", "IntegerGreaterThanEq",
    "IntegerLessThan", "IntegerLessThanEq", "Positive", "NonNegative",
    "PositiveInteger", "NonNegativeInteger", "UnitInterval", "Simplex",
    "LowerTriangular", "LowerCholesky", "PositiveDefinite", "Cat", "Stack",
    "is_dependent", "dependent", "dependent_property",
]


def _raw(x):
    return x._data if isinstance(x, ndarray) else jnp.asarray(x)


class Constraint:
    """A region over which a variable is valid. ``check(value)`` returns
    the value when every element lies in the region, raises ValueError
    otherwise (deferred to sync under a trace)."""

    def _condition(self, v):
        raise NotImplementedError

    def _message(self):
        return f"Constraint violated: value must satisfy {type(self).__name__}"

    def check(self, value):
        npx.constraint_check(self._condition(_raw(value)), self._message())
        return value

    def __repr__(self):
        return type(self).__name__


class _Dependent(Constraint):
    """Support depends on other variables; cannot validate standalone
    (reference constraint.py:53)."""

    def check(self, value):
        raise ValueError("Cannot validate dependent constraint")


def is_dependent(constraint):
    return isinstance(constraint, _Dependent)


class _DependentProperty(property, _Dependent):
    """@property that reads as a _Dependent constraint on the class
    (reference constraint.py:66: Uniform.support pattern)."""


dependent = _Dependent()
dependent_property = _DependentProperty


class Real(Constraint):
    def _condition(self, v):
        return v == v  # noqa: PLR0124 — NaN check

    def _message(self):
        return "Constraint violated: value should be a real tensor"


class Boolean(Constraint):
    def _condition(self, v):
        return (v == 0) | (v == 1)

    def _message(self):
        return "Constraint violated: value should be either 0 or 1"


class _Bounded(Constraint):
    """Shared machinery for (open/half-open/closed, integer) intervals
    and one-sided bounds: subclasses declare comparison ops."""

    integer = False

    def __init__(self, lower_bound=None, upper_bound=None):
        self._lower_bound = lower_bound
        self._upper_bound = upper_bound

    def _cmp_lower(self, v):  # closed by default
        return v >= self._lower_bound

    def _cmp_upper(self, v):
        return v <= self._upper_bound

    def _condition(self, v):
        cond = True
        if self.integer:
            cond = v % 1 == 0
        if self._lower_bound is not None:
            cond = cond & self._cmp_lower(v)
        if self._upper_bound is not None:
            cond = cond & self._cmp_upper(v)
        return cond

    def _message(self):
        kind = "integer in " if self.integer else ""
        return (f"Constraint violated: value should be {kind}"
                f"{type(self).__name__}"
                f"({self._lower_bound}, {self._upper_bound})")


class Interval(_Bounded):
    """[lower, upper]"""


class OpenInterval(_Bounded):
    """(lower, upper)"""

    def _cmp_lower(self, v):
        return v > self._lower_bound

    def _cmp_upper(self, v):
        return v < self._upper_bound


class HalfOpenInterval(_Bounded):
    """[lower, upper)"""

    def _cmp_upper(self, v):
        return v < self._upper_bound


class IntegerInterval(Interval):
    integer = True


class IntegerOpenInterval(OpenInterval):
    integer = True


class IntegerHalfOpenInterval(HalfOpenInterval):
    integer = True


class GreaterThan(_Bounded):
    def __init__(self, lower_bound):
        super().__init__(lower_bound=lower_bound)

    def _cmp_lower(self, v):
        return v > self._lower_bound


class GreaterThanEq(_Bounded):
    def __init__(self, lower_bound):
        super().__init__(lower_bound=lower_bound)


class LessThan(_Bounded):
    def __init__(self, upper_bound):
        super().__init__(upper_bound=upper_bound)

    def _cmp_upper(self, v):
        return v < self._upper_bound


class LessThanEq(_Bounded):
    def __init__(self, upper_bound):
        super().__init__(upper_bound=upper_bound)


class IntegerGreaterThan(GreaterThan):
    integer = True


class IntegerGreaterThanEq(GreaterThanEq):
    integer = True


class IntegerLessThan(LessThan):
    integer = True


class IntegerLessThanEq(LessThanEq):
    integer = True


class Positive(GreaterThan):
    def __init__(self):
        super().__init__(0)


class NonNegative(GreaterThanEq):
    def __init__(self):
        super().__init__(0)


class PositiveInteger(IntegerGreaterThan):
    def __init__(self):
        super().__init__(0)


class NonNegativeInteger(IntegerGreaterThanEq):
    def __init__(self):
        super().__init__(0)


class UnitInterval(Interval):
    def __init__(self):
        super().__init__(0, 1)


class Simplex(Constraint):
    def _condition(self, v):
        return jnp.all(v >= 0, axis=-1) & (jnp.abs(v.sum(-1) - 1) < 1e-6)

    def _message(self):
        return ("Constraint violated: trailing axis should be "
                "non-negative and sum to 1")


class LowerTriangular(Constraint):
    def _condition(self, v):
        return jnp.all(jnp.tril(v) == v, axis=(-2, -1))

    def _message(self):
        return "Constraint violated: value should be lower-triangular"


class LowerCholesky(Constraint):
    def _condition(self, v):
        tri = jnp.all(jnp.tril(v) == v, axis=(-2, -1))
        diag = jnp.all(jnp.diagonal(v, axis1=-2, axis2=-1) > 0, axis=-1)
        return tri & diag

    def _message(self):
        return ("Constraint violated: value should be lower-triangular "
                "with positive diagonal")


class PositiveDefinite(Constraint):
    def _condition(self, v):
        sym = jnp.all(jnp.abs(v - jnp.swapaxes(v, -1, -2)) < 1e-6,
                      axis=(-2, -1))
        # symmetric PD <=> all eigenvalues of (v + v^T)/2 positive;
        # eigvalsh has TPU/CPU lowerings everywhere (unlike geev)
        eig = jnp.all(
            jnp.linalg.eigvalsh((v + jnp.swapaxes(v, -1, -2)) / 2) > 0,
            axis=-1)
        return sym & eig

    def _message(self):
        return "Constraint violated: value should be positive-definite"


class Cat(Constraint):
    """Apply constraints[i] to segments of `lengths[i]` along `dim`
    (reference constraint.py Cat)."""

    def __init__(self, constraints, dim=0, lengths=None):
        self.constraints = list(constraints)
        self.dim = dim
        self.lengths = list(lengths) if lengths is not None \
            else [1] * len(self.constraints)
        if len(self.lengths) != len(self.constraints):
            raise ValueError("constraints and lengths must align")

    def check(self, value):
        v = _raw(value)
        start = 0
        for cons, length in zip(self.constraints, self.lengths):
            seg = jnp.take(v, jnp.arange(start, start + length),
                           axis=self.dim)
            cons.check(seg)
            start += length
        return value


class Stack(Constraint):
    """Apply constraints[i] to slice i along `dim`
    (reference constraint.py Stack)."""

    def __init__(self, constraints, dim=0):
        self.constraints = list(constraints)
        self.dim = dim

    def check(self, value):
        v = _raw(value)
        for i, cons in enumerate(self.constraints):
            cons.check(jnp.take(v, i, axis=self.dim))
        return value

"""Constraint → transformation registry (biject_to / transform_to).

Reference parity: python/mxnet/gluon/probability/transformation/
domain_map.py (a type-keyed registry mapping support constraints to
bijections from unconstrained space; used for variational parameter
reparameterization). Same registration set: Positive → Exp,
GreaterThan(Eq) → Exp∘Affine(lb, 1), LessThan → Exp∘Affine(ub, −1),
Interval/HalfOpenInterval → Sigmoid (unit) or Sigmoid∘Affine(lb, width).
"""
from __future__ import annotations

from numbers import Number

from . import constraint as C
from .transformation import (AffineTransform, ComposeTransform, ExpTransform,
                             SigmoidTransform)


class domain_map:  # noqa: N801 — reference-parity name
    """Registry from Constraint types to transformation factories."""

    def __init__(self):
        self._storage = {}

    def register(self, constraint, factory=None):
        if factory is None:  # decorator mode
            return lambda f: self.register(constraint, f)
        if isinstance(constraint, C.Constraint):
            constraint = type(constraint)
        if not (isinstance(constraint, type)
                and issubclass(constraint, C.Constraint)):
            raise TypeError(
                f"expected a Constraint subclass or instance, "
                f"got {constraint!r}")
        self._storage[constraint] = factory
        return factory

    def __call__(self, constraint):
        factory = self._storage.get(type(constraint))
        if factory is None:
            raise NotImplementedError(
                f"Cannot transform {type(constraint).__name__} constraints")
        return factory(constraint)


biject_to = domain_map()
transform_to = domain_map()


@biject_to.register(C.Positive)
@biject_to.register(C.NonNegative)
@transform_to.register(C.Positive)
@transform_to.register(C.NonNegative)
def _to_positive(constraint):  # noqa: ARG001
    return ExpTransform()


@biject_to.register(C.GreaterThan)
@biject_to.register(C.GreaterThanEq)
@transform_to.register(C.GreaterThan)
@transform_to.register(C.GreaterThanEq)
def _to_greater_than(constraint):
    return ComposeTransform([ExpTransform(),
                             AffineTransform(constraint._lower_bound, 1)])


@biject_to.register(C.LessThan)
@biject_to.register(C.LessThanEq)
@transform_to.register(C.LessThan)
@transform_to.register(C.LessThanEq)
def _to_less_than(constraint):
    return ComposeTransform([ExpTransform(),
                             AffineTransform(constraint._upper_bound, -1)])


@biject_to.register(C.Interval)
@biject_to.register(C.HalfOpenInterval)
@biject_to.register(C.OpenInterval)
@biject_to.register(C.UnitInterval)
@transform_to.register(C.Interval)
@transform_to.register(C.HalfOpenInterval)
@transform_to.register(C.OpenInterval)
@transform_to.register(C.UnitInterval)
def _to_interval(constraint):
    lb, ub = constraint._lower_bound, constraint._upper_bound
    if (isinstance(lb, Number) and lb == 0
            and isinstance(ub, Number) and ub == 1):
        return SigmoidTransform()
    return ComposeTransform([SigmoidTransform(),
                             AffineTransform(lb, ub - lb)])

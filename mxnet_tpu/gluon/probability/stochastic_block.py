"""StochasticBlock — blocks that accumulate auxiliary (e.g. KL) losses.

Reference parity: python/mxnet/gluon/probability/block/stochastic_block.py
(StochasticBlock.add_loss / collectLoss decorator; used for VAEs where the
forward adds a KL term collected by the trainer).
"""
from __future__ import annotations

import functools

from ..block import HybridBlock


class StochasticBlock(HybridBlock):
    """HybridBlock whose forward can stash intermediate losses.

    Decorate forward with ``StochasticBlock.collectLoss``; inside, call
    ``self.add_loss(term)``. After calling the block, read ``block.losses``.
    """

    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        self._losses = []
        self._flag = False

    def add_loss(self, loss):
        self._losses.append(loss)

    @staticmethod
    def collectLoss(forward_fn):
        @functools.wraps(forward_fn)
        def wrapped(self, *args, **kwargs):
            self._losses = []
            out = forward_fn(self, *args, **kwargs)
            self._flag = True
            return out
        return wrapped

    @property
    def losses(self):
        if not self._flag:
            raise ValueError(
                "call the block (with a @StochasticBlock.collectLoss "
                "forward) before reading losses")
        return self._losses


class StochasticBlockGrad(StochasticBlock):
    """Kept for API parity (reference exports both names)."""


class StochasticSequential(StochasticBlock):
    """Stack StochasticBlocks; child losses bubble up (reference:
    block/stochastic_block.py:87)."""

    def add(self, *blocks):
        for block in blocks:
            self.register_child(block)

    @StochasticBlock.collectLoss
    def forward(self, x, *args):
        for block in self._children.values():
            x = block(x, *args)
            args = []
            if isinstance(x, (tuple, list)):
                args, x = list(x[1:]), x[0]
            # collect NOW: the next call to a weight-shared block rebinds
            # its _losses, and index alignment with layers must hold even
            # for calls that added nothing
            if hasattr(block, "_losses"):
                self.add_loss(list(block._losses))
        if args:
            x = tuple([x] + args)
        return x

    def __getitem__(self, i):
        return list(self._children.values())[i]

    def __len__(self):
        return len(self._children)

"""StochasticBlock — blocks that accumulate auxiliary (e.g. KL) losses.

Reference parity: python/mxnet/gluon/probability/block/stochastic_block.py
(StochasticBlock.add_loss / collectLoss decorator; used for VAEs where the
forward adds a KL term collected by the trainer).
"""
from __future__ import annotations

import functools

from ..block import HybridBlock


class StochasticBlock(HybridBlock):
    """HybridBlock whose forward can stash intermediate losses.

    Decorate forward with ``StochasticBlock.collectLoss``; inside, call
    ``self.add_loss(term)``. After calling the block, read ``block.losses``.
    """

    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        self._losses = []
        self._flag = False

    def add_loss(self, loss):
        self._losses.append(loss)

    @staticmethod
    def collectLoss(forward_fn):
        @functools.wraps(forward_fn)
        def wrapped(self, *args, **kwargs):
            self._losses = []
            out = forward_fn(self, *args, **kwargs)
            self._flag = True
            return out
        return wrapped

    @property
    def losses(self):
        if not self._flag:
            raise ValueError(
                "call the block (with a @StochasticBlock.collectLoss "
                "forward) before reading losses")
        return self._losses


class StochasticBlockGrad(StochasticBlock):
    """Kept for API parity (reference exports both names)."""

"""gluon.loss (reference: python/mxnet/gluon/loss.py).

All losses are HybridBlocks over mx.np ops; per-element weighting and batch
axis handling mirror the reference's _apply_weighting/_reshape_like helpers.
"""
from __future__ import annotations

import jax.numpy as jnp

from .. import numpy as _np
from .. import numpy_extension as npx
from .block import HybridBlock


def _apply_weighting(loss, weight=None, sample_weight=None):
    if sample_weight is not None:
        loss = loss * sample_weight
    if weight is not None:
        loss = loss * weight
    return loss


def _reshape_like(pred, label):
    return label.reshape(pred.shape)


class Loss(HybridBlock):
    """Base loss (reference: loss.py:56)."""

    def __init__(self, weight=None, batch_axis=0, **kwargs):
        super().__init__()
        self._weight = weight
        self._batch_axis = batch_axis

    def _mean(self, loss):
        axes = tuple(i for i in range(loss.ndim) if i != self._batch_axis)
        return loss.mean(axis=axes) if axes else loss


class L2Loss(Loss):
    def __init__(self, weight=1.0, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis)

    def forward(self, pred, label, sample_weight=None):
        loss = _np.square(label.reshape(pred.shape) - pred)
        loss = _apply_weighting(loss, self._weight / 2, sample_weight)
        return self._mean(loss)


class L1Loss(Loss):
    def __init__(self, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis)

    def forward(self, pred, label, sample_weight=None):
        loss = _np.abs(label.reshape(pred.shape) - pred)
        loss = _apply_weighting(loss, self._weight, sample_weight)
        return self._mean(loss)


class HuberLoss(Loss):
    def __init__(self, rho=1, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis)
        self._rho = rho

    def forward(self, pred, label, sample_weight=None):
        err = _np.abs(label.reshape(pred.shape) - pred)
        loss = _np.where(err > self._rho,
                         err - 0.5 * self._rho,
                         (0.5 / self._rho) * _np.square(err))
        loss = _apply_weighting(loss, self._weight, sample_weight)
        return self._mean(loss)


class SigmoidBinaryCrossEntropyLoss(Loss):
    """Reference: loss.py SigmoidBCELoss (numerically stable logits form)."""

    def __init__(self, from_sigmoid=False, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis)
        self._from_sigmoid = from_sigmoid

    def forward(self, pred, label, sample_weight=None, pos_weight=None):
        label = label.reshape(pred.shape)
        if not self._from_sigmoid:
            if pos_weight is None:
                loss = _np.maximum(pred, 0) - pred * label + \
                    _np.log(1 + _np.exp(-_np.abs(pred)))
            else:
                log_weight = 1 + (pos_weight - 1) * label
                loss = pred - pred * label + log_weight * (
                    _np.log(1 + _np.exp(-_np.abs(pred)))
                    + _np.maximum(-pred, 0))
        else:
            eps = 1e-12
            if pos_weight is None:
                loss = -(_np.log(pred + eps) * label
                         + _np.log(1 - pred + eps) * (1 - label))
            else:
                loss = -(_np.log(pred + eps) * label * pos_weight
                         + _np.log(1 - pred + eps) * (1 - label))
        loss = _apply_weighting(loss, self._weight, sample_weight)
        return self._mean(loss)


SigmoidBCELoss = SigmoidBinaryCrossEntropyLoss


class SoftmaxCrossEntropyLoss(Loss):
    """Reference: loss.py SoftmaxCrossEntropyLoss (sparse or dense labels)."""

    def __init__(self, axis=-1, sparse_label=True, from_logits=False,
                 weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis)
        self._axis = axis
        self._sparse_label = sparse_label
        self._from_logits = from_logits

    def forward(self, pred, label, sample_weight=None):
        if not self._from_logits and self._sparse_label:
            # fused path: logsumexp - pick, no (N, V) f32 log-softmax
            # (ops/xent.py; measured win on the TPU HBM roofline)
            from ..numpy.multiarray import _invoke
            from ..ops.xent import sparse_softmax_xent
            axis = self._axis
            # dispatch under the op's own name: "softmax_cross_entropy"
            # sits in amp FP32_OPS, which would cast the logits to f32 and
            # re-materialize exactly the (N, V) array this path avoids;
            # the op accumulates in f32 internally so the cast is redundant
            loss = _invoke(lambda x, l: sparse_softmax_xent(x, l, axis),
                           (pred, label), name="sparse_softmax_xent")
        else:
            if not self._from_logits:
                pred = npx.log_softmax(pred, axis=self._axis)
            if self._sparse_label:
                loss = -npx.pick(pred, label, axis=self._axis)
            else:
                label = label.reshape(pred.shape)
                loss = -(pred * label).sum(axis=self._axis)
        loss = _apply_weighting(loss, self._weight, sample_weight)
        return self._mean(loss)


SoftmaxCELoss = SoftmaxCrossEntropyLoss


class KLDivLoss(Loss):
    def __init__(self, from_logits=True, axis=-1, weight=None, batch_axis=0,
                 **kwargs):
        super().__init__(weight, batch_axis)
        self._from_logits = from_logits
        self._axis = axis

    def forward(self, pred, label, sample_weight=None):
        if not self._from_logits:
            pred = npx.log_softmax(pred, axis=self._axis)
        loss = label * (_np.log(label + 1e-12) - pred)
        loss = _apply_weighting(loss, self._weight, sample_weight)
        return self._mean(loss)


class CTCLoss(Loss):
    """Reference: loss.py CTCLoss over src/operator/nn/ctc_loss.cc (WarpCTC).
    TPU-native: optax.ctc_loss (XLA-lowered dynamic programming)."""

    def __init__(self, layout="NTC", label_layout="NT", weight=None, **kwargs):
        super().__init__(weight, 0)
        self._layout = layout
        self._label_layout = label_layout

    def forward(self, pred, label, pred_lengths=None, label_lengths=None,
                sample_weight=None):
        import optax
        from ..block import _flatten_args
        from ..parameter import Parameter  # noqa: F401

        if self._layout == "TNC":
            pred = pred.swapaxes(0, 1)
        if self._label_layout == "TN":
            label = label.swapaxes(0, 1)

        def fn(logits, labels):
            b, t = logits.shape[0], logits.shape[1]
            lp = (jnp.zeros((b, t)) if pred_lengths is None else
                  jnp.arange(t)[None, :] >=
                  jnp.asarray(pred_lengths._data if hasattr(pred_lengths, "_data")
                              else pred_lengths)[:, None]).astype(jnp.float32)
            ln = labels.shape[1]
            if label_lengths is not None:
                ll = jnp.asarray(label_lengths._data
                                 if hasattr(label_lengths, "_data")
                                 else label_lengths)
                lpad = (jnp.arange(ln)[None, :] >= ll[:, None]).astype(jnp.float32)
            else:
                lpad = (labels == 0).astype(jnp.float32)
            return optax.ctc_loss(logits, lp, labels.astype(jnp.int32), lpad,
                                  blank_id=0)
        from ..numpy.multiarray import _invoke
        loss = _invoke(fn, (pred, label), name="ctc_loss")
        return _apply_weighting(loss, self._weight, sample_weight)


class HingeLoss(Loss):
    def __init__(self, margin=1, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis)
        self._margin = margin

    def forward(self, pred, label, sample_weight=None):
        loss = _np.maximum(self._margin - pred * label.reshape(pred.shape), 0)
        loss = _apply_weighting(loss, self._weight, sample_weight)
        return self._mean(loss)


class SquaredHingeLoss(Loss):
    def __init__(self, margin=1, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis)
        self._margin = margin

    def forward(self, pred, label, sample_weight=None):
        loss = _np.square(_np.maximum(
            self._margin - pred * label.reshape(pred.shape), 0))
        loss = _apply_weighting(loss, self._weight, sample_weight)
        return self._mean(loss)


class LogisticLoss(Loss):
    def __init__(self, weight=None, batch_axis=0, label_format="signed",
                 **kwargs):
        super().__init__(weight, batch_axis)
        self._label_format = label_format

    def forward(self, pred, label, sample_weight=None):
        label = label.reshape(pred.shape)
        if self._label_format == "signed":
            label = (label + 1.0) / 2.0
        loss = _np.maximum(pred, 0) - pred * label + \
            _np.log(1 + _np.exp(-_np.abs(pred)))
        loss = _apply_weighting(loss, self._weight, sample_weight)
        return self._mean(loss)


class TripletLoss(Loss):
    def __init__(self, margin=1, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis)
        self._margin = margin

    def forward(self, pred, positive, negative, sample_weight=None):
        positive = positive.reshape(pred.shape)
        negative = negative.reshape(pred.shape)
        loss = _np.sum(_np.square(pred - positive)
                       - _np.square(pred - negative),
                       axis=tuple(range(1, pred.ndim)))
        loss = _np.maximum(loss + self._margin, 0)
        loss = _apply_weighting(loss, self._weight, sample_weight)
        return loss


class CosineEmbeddingLoss(Loss):
    def __init__(self, weight=None, batch_axis=0, margin=0, **kwargs):
        super().__init__(weight, batch_axis)
        self._margin = margin

    def forward(self, input1, input2, label, sample_weight=None):
        eps = 1e-12
        dot = _np.sum(input1 * input2, axis=-1)
        n1 = _np.sqrt(_np.sum(_np.square(input1), axis=-1) + eps)
        n2 = _np.sqrt(_np.sum(_np.square(input2), axis=-1) + eps)
        cos = dot / (n1 * n2)
        label = label.reshape(cos.shape)
        loss = _np.where(label == 1, 1 - cos,
                         _np.maximum(cos - self._margin, 0))
        return _apply_weighting(loss, self._weight, sample_weight)


class PoissonNLLLoss(Loss):
    def __init__(self, weight=None, from_logits=True, batch_axis=0,
                 compute_full=False, **kwargs):
        super().__init__(weight, batch_axis)
        self._from_logits = from_logits
        self._compute_full = compute_full

    def forward(self, pred, target, sample_weight=None, epsilon=1e-08):
        target = target.reshape(pred.shape)
        if self._from_logits:
            loss = _np.exp(pred) - target * pred
        else:
            loss = pred - target * _np.log(pred + epsilon)
        if self._compute_full:
            stirling = target * _np.log(target + 1e-12) - target \
                + 0.5 * _np.log(2 * _np.pi * (target + 1e-12))
            loss = loss + _np.where(target > 1, stirling,
                                    _np.zeros_like(target))
        loss = _apply_weighting(loss, self._weight, sample_weight)
        return loss.mean()


class SDMLLoss(Loss):
    """Batchwise Smoothed Deep Metric Learning loss (Bonadiman 2019,
    arXiv:1905.12786; reference: gluon/loss.py:902).

    Aligned minibatches x1/x2: (x1[i], x2[i]) are positive pairs, all
    cross-row pairs act as in-batch negatives; KL between the softmax of
    negative pairwise euclidean distances and a smoothed identity matrix.
    """

    def __init__(self, smoothing_parameter=0.3, weight=1., batch_axis=0,
                 **kwargs):
        super().__init__(weight, batch_axis)
        self.kl_loss = KLDivLoss(from_logits=True)
        self.smoothing_parameter = smoothing_parameter

    def forward(self, x1, x2):
        batch_size = x1.shape[0]
        if batch_size < 2:
            raise ValueError(
                "SDMLLoss needs batch_size >= 2 (in-batch negatives); "
                f"got {batch_size}")
        # pairwise squared-euclidean distance matrix (B, B)
        diffs = _np.expand_dims(x1, 1) - _np.expand_dims(x2, 0)
        distances = (diffs ** 2).sum(axis=2)
        # smoothed identity labels (Pereyra 2017 label smoothing)
        gold = _np.eye(batch_size)
        labels = gold * (1 - self.smoothing_parameter) + \
            (1 - gold) * self.smoothing_parameter / (batch_size - 1)
        log_probabilities = npx.log_softmax(-distances, axis=1)
        return self.kl_loss(log_probabilities, labels)

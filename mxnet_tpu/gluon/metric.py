"""gluon.metric (reference: python/mxnet/gluon/metric.py, 1.9k LoC).

EvalMetric registry + the common metrics. Metrics are host-side bookkeeping
(pure frontend in the reference too); arrays are fetched via asnumpy().
"""
from __future__ import annotations

import numpy as onp

from ..base import MXNetError, _Registry
from ..numpy.multiarray import ndarray

_registry = _Registry("metric")
register = _registry.register


def _as_np(x):
    return x.asnumpy() if isinstance(x, ndarray) else onp.asarray(x)


def check_label_shapes(labels, preds, shape=False):
    if not shape:
        if len(labels) != len(preds):
            raise MXNetError(
                f"label/pred count mismatch: {len(labels)} vs {len(preds)}")


class EvalMetric:
    """Base metric (reference: metric.py EvalMetric)."""

    def __init__(self, name, output_names=None, label_names=None, **kwargs):
        self.name = str(name)
        self.output_names = output_names
        self.label_names = label_names
        self._kwargs = kwargs
        self.reset()

    def reset(self):
        self.num_inst = 0
        self.sum_metric = 0.0

    def update(self, labels, preds):
        raise NotImplementedError

    def get(self):
        if self.num_inst == 0:
            return self.name, float("nan")
        return self.name, self.sum_metric / self.num_inst

    def get_name_value(self):
        name, value = self.get()
        if not isinstance(name, list):
            name = [name]
        if not isinstance(value, list):
            value = [value]
        return list(zip(name, value))

    def update_dict(self, label, pred):
        self.update(list(label.values()), list(pred.values()))

    def __str__(self):
        return f"EvalMetric: {dict(self.get_name_value())}"


@register("acc")
@register()
class Accuracy(EvalMetric):
    def __init__(self, axis=1, name="accuracy", **kwargs):
        super().__init__(name, **kwargs)
        self.axis = axis

    def update(self, labels, preds):
        labels = labels if isinstance(labels, (list, tuple)) else [labels]
        preds = preds if isinstance(preds, (list, tuple)) else [preds]
        check_label_shapes(labels, preds)
        for label, pred in zip(labels, preds):
            label, pred = _as_np(label), _as_np(pred)
            if pred.ndim > label.ndim:
                pred = pred.argmax(self.axis)
            pred = pred.astype(onp.int64).flat
            label = label.astype(onp.int64).flat
            n = len(label)
            self.sum_metric += float((onp.asarray(pred[:n]) ==
                                      onp.asarray(label[:n])).sum())
            self.num_inst += n


@register("top_k_accuracy")
@register()
class TopKAccuracy(EvalMetric):
    def __init__(self, top_k=1, name="top_k_accuracy", **kwargs):
        super().__init__(f"{name}_{top_k}", **kwargs)
        self.top_k = top_k

    def update(self, labels, preds):
        labels = labels if isinstance(labels, (list, tuple)) else [labels]
        preds = preds if isinstance(preds, (list, tuple)) else [preds]
        for label, pred in zip(labels, preds):
            label, pred = _as_np(label), _as_np(pred)
            idx = onp.argsort(pred, axis=-1)[:, -self.top_k:]
            label = label.astype(onp.int64).reshape(-1, 1)
            self.sum_metric += float((idx == label).any(axis=-1).sum())
            self.num_inst += label.shape[0]


@register()
class MAE(EvalMetric):
    def __init__(self, name="mae", **kwargs):
        super().__init__(name, **kwargs)

    def update(self, labels, preds):
        for label, pred in zip(labels, preds):
            label, pred = _as_np(label), _as_np(pred)
            self.sum_metric += float(onp.abs(label.reshape(pred.shape)
                                             - pred).mean()) * label.shape[0]
            self.num_inst += label.shape[0]


@register()
class MSE(EvalMetric):
    def __init__(self, name="mse", **kwargs):
        super().__init__(name, **kwargs)

    def update(self, labels, preds):
        for label, pred in zip(labels, preds):
            label, pred = _as_np(label), _as_np(pred)
            self.sum_metric += float(((label.reshape(pred.shape) - pred) ** 2)
                                     .mean()) * label.shape[0]
            self.num_inst += label.shape[0]


@register()
class RMSE(MSE):
    def __init__(self, name="rmse", **kwargs):
        super().__init__(name, **kwargs)

    def get(self):
        if self.num_inst == 0:
            return self.name, float("nan")
        return self.name, (self.sum_metric / self.num_inst) ** 0.5


@register("ce")
@register()
class CrossEntropy(EvalMetric):
    def __init__(self, eps=1e-12, name="cross-entropy", **kwargs):
        super().__init__(name, **kwargs)
        self.eps = eps

    def update(self, labels, preds):
        for label, pred in zip(labels, preds):
            label, pred = _as_np(label), _as_np(pred)
            label = label.ravel().astype(onp.int64)
            prob = pred[onp.arange(label.shape[0]), label]
            self.sum_metric += float((-onp.log(prob + self.eps)).sum())
            self.num_inst += label.shape[0]


@register()
class Perplexity(CrossEntropy):
    def __init__(self, ignore_label=None, axis=-1, name="perplexity", **kwargs):
        super().__init__(name=name, **kwargs)
        self.ignore_label = ignore_label

    def get(self):
        if self.num_inst == 0:
            return self.name, float("nan")
        return self.name, float(onp.exp(self.sum_metric / self.num_inst))


@register()
class F1(EvalMetric):
    def __init__(self, name="f1", average="macro", **kwargs):
        super().__init__(name, **kwargs)
        self.average = average
        self.reset_stats()

    def reset_stats(self):
        self._tp = self._fp = self._fn = 0.0

    def reset(self):
        super().reset()
        if hasattr(self, "_tp"):
            self.reset_stats()
        else:
            self.reset_stats()

    def update(self, labels, preds):
        for label, pred in zip(labels, preds):
            label, pred = _as_np(label), _as_np(pred)
            if pred.ndim > 1:
                pred = pred.argmax(axis=-1)
            pred = pred.ravel()
            label = label.ravel()
            self._tp += float(((pred == 1) & (label == 1)).sum())
            self._fp += float(((pred == 1) & (label == 0)).sum())
            self._fn += float(((pred == 0) & (label == 1)).sum())
            self.num_inst += label.shape[0]

    def get(self):
        prec = self._tp / max(self._tp + self._fp, 1e-12)
        rec = self._tp / max(self._tp + self._fn, 1e-12)
        f1 = 2 * prec * rec / max(prec + rec, 1e-12)
        return self.name, f1


@register()
class MCC(EvalMetric):
    def __init__(self, name="mcc", **kwargs):
        super().__init__(name, **kwargs)
        self._tp = self._fp = self._fn = self._tn = 0.0

    def reset(self):
        super().reset()
        self._tp = self._fp = self._fn = self._tn = 0.0

    def update(self, labels, preds):
        for label, pred in zip(labels, preds):
            label, pred = _as_np(label), _as_np(pred)
            if pred.ndim > 1:
                pred = pred.argmax(axis=-1)
            pred, label = pred.ravel(), label.ravel()
            self._tp += float(((pred == 1) & (label == 1)).sum())
            self._fp += float(((pred == 1) & (label == 0)).sum())
            self._fn += float(((pred == 0) & (label == 1)).sum())
            self._tn += float(((pred == 0) & (label == 0)).sum())
            self.num_inst += label.shape[0]

    def get(self):
        tp, fp, fn, tn = self._tp, self._fp, self._fn, self._tn
        denom = ((tp + fp) * (tp + fn) * (tn + fp) * (tn + fn)) ** 0.5
        mcc = (tp * tn - fp * fn) / denom if denom > 0 else 0.0
        return self.name, mcc


@register()
class PearsonCorrelation(EvalMetric):
    def __init__(self, name="pearsonr", **kwargs):
        super().__init__(name, **kwargs)
        self._labels, self._preds = [], []

    def reset(self):
        super().reset()
        self._labels, self._preds = [], []

    def update(self, labels, preds):
        for label, pred in zip(labels, preds):
            self._labels.append(_as_np(label).ravel())
            self._preds.append(_as_np(pred).ravel())
            self.num_inst += 1

    def get(self):
        if not self._labels:
            return self.name, float("nan")
        l = onp.concatenate(self._labels)
        p = onp.concatenate(self._preds)
        return self.name, float(onp.corrcoef(l, p)[0, 1])


@register("loss")
class Loss(EvalMetric):
    def __init__(self, name="loss", **kwargs):
        super().__init__(name, **kwargs)

    def update(self, _, preds):
        preds = preds if isinstance(preds, (list, tuple)) else [preds]
        for pred in preds:
            loss = float(_as_np(pred).sum())
            self.sum_metric += loss
            self.num_inst += _as_np(pred).size


class CompositeEvalMetric(EvalMetric):
    def __init__(self, metrics=None, name="composite", **kwargs):
        super().__init__(name, **kwargs)
        self.metrics = metrics or []

    def add(self, metric):
        self.metrics.append(create(metric))

    def reset(self):
        for m in getattr(self, "metrics", []):
            m.reset()

    def update(self, labels, preds):
        for m in self.metrics:
            m.update(labels, preds)

    def get(self):
        names, values = [], []
        for m in self.metrics:
            name, value = m.get()
            names.append(name)
            values.append(value)
        return names, values


class CustomMetric(EvalMetric):
    def __init__(self, feval, name="custom", allow_extra_outputs=False, **kwargs):
        super().__init__(f"custom({name})", **kwargs)
        self._feval = feval

    def update(self, labels, preds):
        for label, pred in zip(labels, preds):
            v = self._feval(_as_np(label), _as_np(pred))
            if isinstance(v, tuple):
                s, n = v
                self.sum_metric += s
                self.num_inst += n
            else:
                self.sum_metric += v
                self.num_inst += 1


def np(numpy_feval, name="custom", allow_extra_outputs=False):
    return CustomMetric(numpy_feval, name, allow_extra_outputs)


def create(metric, *args, **kwargs):
    if isinstance(metric, EvalMetric):
        return metric
    if callable(metric):
        return CustomMetric(metric, *args, **kwargs)
    if isinstance(metric, list):
        return CompositeEvalMetric([create(m) for m in metric])
    return _registry.get(metric)(*args, **kwargs)

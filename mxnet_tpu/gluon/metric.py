"""gluon.metric (reference: python/mxnet/gluon/metric.py, 1.9k LoC).

EvalMetric registry + the common metrics. Metrics are host-side bookkeeping
(pure frontend in the reference too); arrays are fetched via asnumpy().
"""
from __future__ import annotations

import numpy as onp

from ..base import MXNetError, _Registry
from ..numpy.multiarray import ndarray

_registry = _Registry("metric")
register = _registry.register


def _as_np(x):
    return x.asnumpy() if isinstance(x, ndarray) else onp.asarray(x)


def _raw_dev(x):
    """Batch leaf as a jax array with NO host fetch (shape/dtype are
    host-side metadata; values stay device futures)."""
    import jax.numpy as jnp
    if isinstance(x, ndarray):
        return x._data
    return jnp.asarray(x)


def check_label_shapes(labels, preds, shape=False):
    if not shape:
        if len(labels) != len(preds):
            raise MXNetError(
                f"label/pred count mismatch: {len(labels)} vs {len(preds)}")


class EvalMetric:
    """Base metric (reference: metric.py EvalMetric)."""

    def __init__(self, name, output_names=None, label_names=None, **kwargs):
        self.name = str(name)
        self.output_names = output_names
        self.label_names = label_names
        self._kwargs = kwargs
        self.reset()

    def reset(self):
        self.num_inst = 0
        self.sum_metric = 0.0

    def update(self, labels, preds):
        raise NotImplementedError

    def get(self):
        if self.num_inst == 0:
            return self.name, float("nan")
        return self.name, self.sum_metric / self.num_inst

    def get_name_value(self):
        name, value = self.get()
        if not isinstance(name, list):
            name = [name]
        if not isinstance(value, list):
            value = [value]
        return list(zip(name, value))

    def update_dict(self, label, pred):
        self.update(list(label.values()), list(pred.values()))

    def defer(self, window=None):
        """Sync-free view of this metric for the hot step loop.

        Metrics that define ``_device_stats`` (Accuracy, Loss, MSE/RMSE,
        MAE) accumulate per-batch (sum, count) as device scalars pushed
        through a bounded ``mx.pipeline.DeferredWindow``; the host
        ``float()`` happens only when ``get()``/``drain()`` runs (epoch
        boundaries) or the window overflows.  Metrics without device
        stats fall back to the eager update.  The wrapper shares state
        with ``self``: draining folds into this metric's accumulators.
        """
        return _DeferredMetric(self, window)

    def __str__(self):
        return f"EvalMetric: {dict(self.get_name_value())}"


class _DeferredMetric:
    """Duck-typed EvalMetric wrapper created by ``EvalMetric.defer()``.

    Not a subclass on purpose: every read-style attribute (name, axis,
    sum_metric, ...) proxies to the wrapped metric, so handler code that
    introspects metrics keeps working; only update/get/reset interpose.
    """

    def __init__(self, base, window=None):
        from .. import pipeline as _pipeline
        self._base = base
        self._window = _pipeline.DeferredWindow(window)

    def _apply(self, stats):
        s, n = stats
        self._base.sum_metric += s
        self._base.num_inst += int(n)

    def update(self, labels, preds):
        dev = getattr(self._base, "_device_stats", None)
        if dev is None:
            self._base.update(labels, preds)
            return
        labels = labels if isinstance(labels, (list, tuple)) else [labels]
        preds = preds if isinstance(preds, (list, tuple)) else [preds]
        if len(labels) < len(preds):  # Loss-style metrics ignore labels
            labels = list(labels) + [None] * (len(preds) - len(labels))
        for label, pred in zip(labels, preds):
            self._window.push(dev(label, pred), self._apply)

    def update_dict(self, label, pred):
        self.update(list(label.values()), list(pred.values()))

    def drain(self):
        """Fold every deferred batch into the wrapped metric (one host
        sync per buffered batch, off the hot path)."""
        self._window.drain()

    def get(self):
        self.drain()
        return self._base.get()

    def get_name_value(self):
        self.drain()
        return self._base.get_name_value()

    def reset(self):
        # buffered stats belong to the interval being reset: drop them
        # WITHOUT fetching (reset must not become a host sync)
        self._window.clear()
        self._base.reset()

    def __getattr__(self, name):
        return getattr(self._base, name)

    def __str__(self):
        return f"EvalMetric: {dict(self.get_name_value())}"


@register("acc")
@register()
class Accuracy(EvalMetric):
    def __init__(self, axis=1, name="accuracy", **kwargs):
        super().__init__(name, **kwargs)
        self.axis = axis

    def update(self, labels, preds):
        labels = labels if isinstance(labels, (list, tuple)) else [labels]
        preds = preds if isinstance(preds, (list, tuple)) else [preds]
        check_label_shapes(labels, preds)
        for label, pred in zip(labels, preds):
            label, pred = _as_np(label), _as_np(pred)
            if pred.ndim > label.ndim:
                pred = pred.argmax(self.axis)
            pred = pred.astype(onp.int64).flat
            label = label.astype(onp.int64).flat
            n = len(label)
            self.sum_metric += float((onp.asarray(pred[:n]) ==
                                      onp.asarray(label[:n])).sum())
            self.num_inst += n

    def _device_stats(self, label, pred):
        import jax.numpy as jnp
        label, pred = _raw_dev(label), _raw_dev(pred)
        if pred.ndim > label.ndim:
            pred = pred.argmax(self.axis)
        pred = pred.astype(jnp.int32).ravel()
        label = label.astype(jnp.int32).ravel()
        n = int(label.shape[0])
        return (pred[:n] == label[:n]).sum(), n


@register("top_k_accuracy")
@register()
class TopKAccuracy(EvalMetric):
    def __init__(self, top_k=1, name="top_k_accuracy", **kwargs):
        super().__init__(f"{name}_{top_k}", **kwargs)
        self.top_k = top_k

    def update(self, labels, preds):
        labels = labels if isinstance(labels, (list, tuple)) else [labels]
        preds = preds if isinstance(preds, (list, tuple)) else [preds]
        for label, pred in zip(labels, preds):
            label, pred = _as_np(label), _as_np(pred)
            idx = onp.argsort(pred, axis=-1)[:, -self.top_k:]
            label = label.astype(onp.int64).reshape(-1, 1)
            self.sum_metric += float((idx == label).any(axis=-1).sum())
            self.num_inst += label.shape[0]


@register()
class MAE(EvalMetric):
    def __init__(self, name="mae", **kwargs):
        super().__init__(name, **kwargs)

    def update(self, labels, preds):
        for label, pred in zip(labels, preds):
            label, pred = _as_np(label), _as_np(pred)
            self.sum_metric += float(onp.abs(label.reshape(pred.shape)
                                             - pred).mean()) * label.shape[0]
            self.num_inst += label.shape[0]

    def _device_stats(self, label, pred):
        import jax.numpy as jnp
        label, pred = _raw_dev(label), _raw_dev(pred)
        n = int(label.shape[0])
        return jnp.abs(label.reshape(pred.shape) - pred).mean() * n, n


@register()
class MSE(EvalMetric):
    def __init__(self, name="mse", **kwargs):
        super().__init__(name, **kwargs)

    def update(self, labels, preds):
        for label, pred in zip(labels, preds):
            label, pred = _as_np(label), _as_np(pred)
            self.sum_metric += float(((label.reshape(pred.shape) - pred) ** 2)
                                     .mean()) * label.shape[0]
            self.num_inst += label.shape[0]

    def _device_stats(self, label, pred):
        label, pred = _raw_dev(label), _raw_dev(pred)
        n = int(label.shape[0])
        return ((label.reshape(pred.shape) - pred) ** 2).mean() * n, n


@register()
class RMSE(MSE):
    def __init__(self, name="rmse", **kwargs):
        super().__init__(name, **kwargs)

    def get(self):
        if self.num_inst == 0:
            return self.name, float("nan")
        return self.name, (self.sum_metric / self.num_inst) ** 0.5


@register("ce")
@register()
class CrossEntropy(EvalMetric):
    def __init__(self, eps=1e-12, name="cross-entropy", **kwargs):
        super().__init__(name, **kwargs)
        self.eps = eps

    def update(self, labels, preds):
        for label, pred in zip(labels, preds):
            label, pred = _as_np(label), _as_np(pred)
            label = label.ravel().astype(onp.int64)
            prob = pred[onp.arange(label.shape[0]), label]
            self.sum_metric += float((-onp.log(prob + self.eps)).sum())
            self.num_inst += label.shape[0]


@register()
class Perplexity(CrossEntropy):
    def __init__(self, ignore_label=None, axis=-1, name="perplexity", **kwargs):
        super().__init__(name=name, **kwargs)
        self.ignore_label = ignore_label

    def get(self):
        if self.num_inst == 0:
            return self.name, float("nan")
        return self.name, float(onp.exp(self.sum_metric / self.num_inst))


@register()
class F1(EvalMetric):
    def __init__(self, name="f1", average="macro", **kwargs):
        super().__init__(name, **kwargs)
        self.average = average
        self.reset_stats()

    def reset_stats(self):
        self._tp = self._fp = self._fn = 0.0

    def reset(self):
        super().reset()
        if hasattr(self, "_tp"):
            self.reset_stats()
        else:
            self.reset_stats()

    def update(self, labels, preds):
        for label, pred in zip(labels, preds):
            label, pred = _as_np(label), _as_np(pred)
            if pred.ndim > 1:
                pred = pred.argmax(axis=-1)
            pred = pred.ravel()
            label = label.ravel()
            self._tp += float(((pred == 1) & (label == 1)).sum())
            self._fp += float(((pred == 1) & (label == 0)).sum())
            self._fn += float(((pred == 0) & (label == 1)).sum())
            self.num_inst += label.shape[0]

    beta = 1.0  # F1 == Fbeta(beta=1); Fbeta overrides per instance

    def get(self):
        prec = self._tp / max(self._tp + self._fp, 1e-12)
        rec = self._tp / max(self._tp + self._fn, 1e-12)
        b2 = self.beta ** 2
        fb = (1 + b2) * prec * rec / max(b2 * prec + rec, 1e-12)
        return self.name, fb


@register()
class MCC(EvalMetric):
    def __init__(self, name="mcc", **kwargs):
        super().__init__(name, **kwargs)
        self._tp = self._fp = self._fn = self._tn = 0.0

    def reset(self):
        super().reset()
        self._tp = self._fp = self._fn = self._tn = 0.0

    def update(self, labels, preds):
        for label, pred in zip(labels, preds):
            label, pred = _as_np(label), _as_np(pred)
            if pred.ndim > 1:
                pred = pred.argmax(axis=-1)
            pred, label = pred.ravel(), label.ravel()
            self._tp += float(((pred == 1) & (label == 1)).sum())
            self._fp += float(((pred == 1) & (label == 0)).sum())
            self._fn += float(((pred == 0) & (label == 1)).sum())
            self._tn += float(((pred == 0) & (label == 0)).sum())
            self.num_inst += label.shape[0]

    def get(self):
        tp, fp, fn, tn = self._tp, self._fp, self._fn, self._tn
        denom = ((tp + fp) * (tp + fn) * (tn + fp) * (tn + fn)) ** 0.5
        mcc = (tp * tn - fp * fn) / denom if denom > 0 else 0.0
        return self.name, mcc


@register()
class PearsonCorrelation(EvalMetric):
    def __init__(self, name="pearsonr", **kwargs):
        super().__init__(name, **kwargs)
        self._labels, self._preds = [], []

    def reset(self):
        super().reset()
        self._labels, self._preds = [], []

    def update(self, labels, preds):
        for label, pred in zip(labels, preds):
            self._labels.append(_as_np(label).ravel())
            self._preds.append(_as_np(pred).ravel())
            self.num_inst += 1

    def get(self):
        if not self._labels:
            return self.name, float("nan")
        l = onp.concatenate(self._labels)
        p = onp.concatenate(self._preds)
        return self.name, float(onp.corrcoef(l, p)[0, 1])


@register()
class Fbeta(F1):
    """F-beta over the binary confusion counts (reference metric.py:816:
    Fbeta = (1+b^2) * P * R / (b^2 * P + R); beta=1 reduces to F1 — the
    formula itself lives on F1.get, parameterized by ``beta``)."""

    def __init__(self, name="fbeta", beta=1, **kwargs):
        super().__init__(name=name, **kwargs)
        self.beta = float(beta)


@register()
class BinaryAccuracy(EvalMetric):
    """Accuracy of binary / multilabel scores against a threshold
    (reference metric.py:877)."""

    def __init__(self, name="binary_accuracy", threshold=0.5, **kwargs):
        super().__init__(name, **kwargs)
        self.threshold = threshold

    def update(self, labels, preds):
        for label, pred in zip(labels, preds):
            label, pred = _as_np(label), _as_np(pred)
            hard = (pred > self.threshold).astype(label.dtype)
            self.sum_metric += float((hard.ravel() == label.ravel()).sum())
            self.num_inst += label.size


@register()
class MeanPairwiseDistance(EvalMetric):
    """Mean per-sample Lp distance over the trailing axes
    (reference metric.py:1202)."""

    def __init__(self, name="mpd", p=2, **kwargs):
        super().__init__(name, **kwargs)
        self.p = p

    def update(self, labels, preds):
        for label, pred in zip(labels, preds):
            label, pred = _as_np(label), _as_np(pred)
            if label.ndim == 1:  # one vector = one sample
                label, pred = label[None], pred[None]
            diff = (onp.abs(pred - label) ** self.p).reshape(
                label.shape[0], -1).sum(axis=1) ** (1.0 / self.p)
            self.sum_metric += float(diff.sum())
            self.num_inst += label.shape[0]


@register()
class MeanCosineSimilarity(EvalMetric):
    """Mean cosine similarity along the last axis
    (reference metric.py:1269)."""

    def __init__(self, name="cos_sim", eps=1e-8, **kwargs):
        super().__init__(name, **kwargs)
        self.eps = eps

    def update(self, labels, preds):
        for label, pred in zip(labels, preds):
            label, pred = _as_np(label), _as_np(pred)
            if label.ndim == 1:
                label, pred = label[None], pred[None]
            num = (label * pred).sum(axis=-1)
            den = onp.maximum(
                onp.linalg.norm(label, axis=-1)
                * onp.linalg.norm(pred, axis=-1), self.eps)
            sim = num / den
            self.sum_metric += float(sim.sum())
            self.num_inst += sim.size


@register()
class PCC(EvalMetric):
    """Multiclass Pearson correlation from a K x K confusion matrix
    (reference metric.py:1595 — the discrete multiclass MCC:
    (c*s - t.p) / sqrt((s^2 - p.p)(s^2 - t.t)))."""

    def __init__(self, name="pcc", **kwargs):
        super().__init__(name, **kwargs)
        self._cm = onp.zeros((0, 0), dtype=onp.float64)

    def reset(self):
        super().reset()
        self._cm = onp.zeros((0, 0), dtype=onp.float64)

    def _grow(self, k):
        if k > self._cm.shape[0]:
            cm = onp.zeros((k, k), dtype=onp.float64)
            n = self._cm.shape[0]
            cm[:n, :n] = self._cm
            self._cm = cm

    def update(self, labels, preds):
        for label, pred in zip(labels, preds):
            label, pred = _as_np(label), _as_np(pred)
            if pred.ndim > 1:
                pred = pred.argmax(axis=-1)
            label = label.ravel().astype(onp.int64)
            pred = pred.ravel().astype(onp.int64)
            if label.size and (label.min() < 0 or pred.min() < 0):
                raise MXNetError(
                    "PCC requires non-negative class ids (negative "
                    "ignore-markers would wrap into the confusion matrix)")
            k = int(max(label.max(), pred.max())) + 1
            self._grow(k)
            onp.add.at(self._cm, (label, pred), 1.0)
            self.num_inst += label.shape[0]

    def get(self):
        if self.num_inst == 0:
            return self.name, float("nan")
        cm = self._cm
        s = cm.sum()
        c = onp.trace(cm)
        t = cm.sum(axis=1)  # true-class totals
        p = cm.sum(axis=0)  # predicted totals
        den = onp.sqrt(max(s * s - (p @ p), 0.0)) * \
            onp.sqrt(max(s * s - (t @ t), 0.0))
        if den <= 0:
            return self.name, 0.0
        return self.name, float((c * s - t @ p) / den)


@register("loss")
class Loss(EvalMetric):
    def __init__(self, name="loss", **kwargs):
        super().__init__(name, **kwargs)

    def update(self, _, preds):
        preds = preds if isinstance(preds, (list, tuple)) else [preds]
        for pred in preds:
            loss = float(_as_np(pred).sum())
            self.sum_metric += loss
            self.num_inst += _as_np(pred).size

    def _device_stats(self, _label, pred):
        pred = _raw_dev(pred)
        return pred.sum(), int(pred.size)


@register()
class Torch(Loss):
    """Named Loss alias kept for torch-criterion scripts
    (reference metric.py:1745)."""

    def __init__(self, name="torch", **kwargs):
        super().__init__(name=name, **kwargs)


class CompositeEvalMetric(EvalMetric):
    def __init__(self, metrics=None, name="composite", **kwargs):
        super().__init__(name, **kwargs)
        self.metrics = metrics or []

    def add(self, metric):
        self.metrics.append(create(metric))

    def reset(self):
        for m in getattr(self, "metrics", []):
            m.reset()

    def update(self, labels, preds):
        for m in self.metrics:
            m.update(labels, preds)

    def get(self):
        names, values = [], []
        for m in self.metrics:
            name, value = m.get()
            names.append(name)
            values.append(value)
        return names, values


class CustomMetric(EvalMetric):
    def __init__(self, feval, name="custom", allow_extra_outputs=False, **kwargs):
        super().__init__(f"custom({name})", **kwargs)
        self._feval = feval

    def update(self, labels, preds):
        for label, pred in zip(labels, preds):
            v = self._feval(_as_np(label), _as_np(pred))
            if isinstance(v, tuple):
                s, n = v
                self.sum_metric += s
                self.num_inst += n
            else:
                self.sum_metric += v
                self.num_inst += 1


def np(numpy_feval, name="custom", allow_extra_outputs=False):
    return CustomMetric(numpy_feval, name, allow_extra_outputs)


def create(metric, *args, **kwargs):
    if isinstance(metric, EvalMetric):
        return metric
    if callable(metric):
        return CustomMetric(metric, *args, **kwargs)
    if isinstance(metric, list):
        return CompositeEvalMetric([create(m) for m in metric])
    return _registry.get(metric)(*args, **kwargs)

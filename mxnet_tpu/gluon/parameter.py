"""gluon.Parameter.

Reference parity: python/mxnet/gluon/parameter.py:47-570 (lazy/deferred
initialization, per-context data/grad arrays, grad_req, constant params).

TPU-native design: a Parameter owns one ndarray (whose jax.Array may be
*sharded* across a device mesh — the analog of the reference's per-context
copies list is a single sharded array; ``list_data()`` returns per-device
views for KVStore compatibility). Shapes with 0 entries are deferred and
completed at first forward from input shapes, exactly like the reference.
"""
from __future__ import annotations

import jax.numpy as jnp

from ..base import MXNetError, np_dtype
from ..context import current_context
from .. import initializer as init_mod
from ..numpy.multiarray import ndarray, _wrap


class DeferredInitializationError(MXNetError):
    pass


class Parameter:
    """A trainable (or auxiliary) tensor of a Block."""

    def __init__(self, name="weight", grad_req="write", shape=None,
                 dtype="float32", lr_mult=1.0, wd_mult=1.0, init=None,
                 allow_deferred_init=False, differentiable=True,
                 stype="default", grad_stype="default"):
        self._name = name
        self._shape = tuple(shape) if shape is not None else None
        self.dtype = np_dtype(dtype) or jnp.float32
        self.lr_mult = lr_mult
        self.wd_mult = wd_mult
        self.init = init
        self.allow_deferred_init = allow_deferred_init
        if not differentiable:
            grad_req = "null"
        self._grad_req = grad_req
        self._stype = stype
        self._grad_stype = grad_stype
        self._data = None
        self._deferred_init = None  # (initializer, ctx)
        self._structure_name = None  # set by Block registration
        self._sharding = None        # optional jax.sharding spec

    # -- naming ------------------------------------------------------------
    @property
    def name(self):
        return self._structure_name or self._name

    @name.setter
    def name(self, v):
        self._name = v

    # -- shape (with deferred unknown dims as 0/-1) ------------------------
    @property
    def shape(self):
        return self._shape

    @shape.setter
    def shape(self, new_shape):
        if self._shape is None:
            self._shape = tuple(new_shape)
            return
        if len(self._shape) != len(new_shape) or any(
                s not in (0, -1) and s != n for s, n in zip(self._shape, new_shape)):
            raise MXNetError(
                f"cannot update shape {self._shape} -> {tuple(new_shape)} for {self.name}")
        self._shape = tuple(new_shape)

    @property
    def grad_req(self):
        return self._grad_req

    @grad_req.setter
    def grad_req(self, req):
        self._grad_req = req
        if self._data is not None:
            if req == "null":
                self._data._grad = None
                self._data._grad_req = "null"
            else:
                self._data.attach_grad(req)

    def _shape_known(self):
        return self._shape is not None and all(s > 0 for s in self._shape)

    # -- initialization ----------------------------------------------------
    def initialize(self, init=None, ctx=None, default_init=None,
                   force_reinit=False, device=None):
        """Reference: parameter.py Parameter.initialize (lazy when shape
        unknown)."""
        if self._data is not None and not force_reinit:
            return
        ctx = device if device is not None else ctx
        initializer = init or self.init or default_init or init_mod.Uniform()
        if isinstance(initializer, str):
            initializer = init_mod.create(initializer)
        if not self._shape_known():
            if not self.allow_deferred_init:
                raise DeferredInitializationError(
                    f"parameter {self.name} has unknown shape {self._shape}; "
                    "run a forward pass to infer it")
            self._deferred_init = (initializer, ctx)
            return
        self._init_impl(initializer, ctx)

    def _init_impl(self, initializer, ctx):
        arr = _wrap(jnp.zeros(self._shape, self.dtype))
        initializer(self.name, arr)
        if ctx is not None:
            arr = arr.as_in_ctx(ctx if not isinstance(ctx, (list, tuple)) else ctx[0])
        self._data = arr
        if self._grad_req != "null":
            self._data.attach_grad(self._grad_req)
        self._deferred_init = None

    def _finish_deferred_init(self, inferred_shape=None):
        if inferred_shape is not None:
            self.shape = inferred_shape
        if self._deferred_init is None:
            if self._data is None:
                raise DeferredInitializationError(
                    f"parameter {self.name} not initialized; call "
                    ".initialize() before forward")
            return
        initializer, ctx = self._deferred_init
        self._init_impl(initializer, ctx)

    # -- access ------------------------------------------------------------
    def data(self, ctx=None):
        if self._data is None:
            if self._deferred_init is not None:
                raise DeferredInitializationError(
                    f"parameter {self.name} pending deferred init; run a "
                    "forward pass first")
            raise MXNetError(
                f"parameter {self.name} not initialized; call .initialize()")
        return self._data

    def list_data(self):
        return [self._data]

    def grad(self, ctx=None):
        if self._data is None or self._data.grad is None:
            raise MXNetError(f"parameter {self.name} has no gradient buffer "
                             f"(grad_req={self._grad_req!r})")
        return self._data.grad

    def list_grad(self):
        return [self.grad()]

    def row_sparse_data(self, row_id):
        """Rows of this parameter for the given ids as a RowSparseNDArray
        (reference: parameter.py row_sparse_data over kvstore
        PullRowSparse) — the sparse-embedding pull path."""
        from ..ndarray.sparse import RowSparseNDArray
        import jax.numpy as jnp
        from ..numpy.multiarray import _wrap
        src = self.data()
        ids = (row_id._data if isinstance(row_id, ndarray)
               else jnp.asarray(row_id))
        from ..ndarray.sparse import _IDX
        ids = jnp.unique(ids).astype(_IDX)
        return RowSparseNDArray(_wrap(src._data[ids]), _wrap(ids), src.shape)

    def list_ctx(self):
        return [self._data.ctx] if self._data is not None else [current_context()]

    def set_data(self, data):
        if not isinstance(data, ndarray):
            from ..numpy import array
            data = array(data)
        if self._data is None:
            self.shape = data.shape
            self._data = data.astype(self.dtype)
            if self._grad_req != "null":
                self._data.attach_grad(self._grad_req)
        else:
            self._data._rebind(data._data.astype(self.dtype))

    def zero_grad(self):
        if self._data is not None and self._data.grad is not None:
            self._data.zero_grad()

    def reset_ctx(self, ctx):
        if self._data is not None:
            self._data = self._data.as_in_ctx(ctx)
            if self._grad_req != "null":
                self._data.attach_grad(self._grad_req)

    reset_device = reset_ctx

    def cast(self, dtype):
        self.dtype = np_dtype(dtype)
        if self._data is not None:
            self._data = self._data.astype(self.dtype)
            if self._grad_req != "null":
                self._data.attach_grad(self._grad_req)

    # -- sharding (TPU-native addition) ------------------------------------
    def shard(self, sharding):
        """Place this parameter with an explicit jax.sharding. With a mesh,
        this is how tensor-parallel layouts are declared."""
        import jax
        self._sharding = sharding
        if self._data is not None:
            self._data._rebind(jax.device_put(self._data._data, sharding))

    def var(self):
        return self._data

    def __repr__(self):
        return (f"Parameter {self.name} (shape={self._shape}, "
                f"dtype={jnp.dtype(self.dtype).name})")


class Constant(Parameter):
    """Non-differentiable constant parameter (reference: parameter.py
    Constant)."""

    def __init__(self, value, name="const"):
        if not isinstance(value, ndarray):
            from ..numpy import array
            value = array(value)
        self._value = value
        super().__init__(name=name, grad_req="null", shape=value.shape,
                         dtype=value.dtype,
                         init=init_mod.Constant(0.0), differentiable=False)

    def _init_impl(self, initializer, ctx):
        self._data = self._value.copy()
        self._deferred_init = None

"""mx.log — logging helpers (colored level tags, one-call setup).

Reference parity: python/mxnet/log.py (CRITICAL..NOTSET constants,
``getLogger``/``get_logger`` returning a logger with a colored
``LEVEL MMDD HH:MM:SS file:line] msg`` formatter). The reference colors by
escape codes only when the stream is a tty; same here.
"""
from __future__ import annotations

import logging
import sys
import warnings

CRITICAL = logging.CRITICAL
ERROR = logging.ERROR
WARNING = logging.WARNING
INFO = logging.INFO
DEBUG = logging.DEBUG
NOTSET = logging.NOTSET

_LEVEL_CHAR = {
    logging.CRITICAL: "C", logging.ERROR: "E", logging.WARNING: "W",
    logging.INFO: "I", logging.DEBUG: "D",
}
# red for warning+, green for info, blue below
_LEVEL_COLOR = {
    logging.CRITICAL: "\x1b[31m", logging.ERROR: "\x1b[31m",
    logging.WARNING: "\x1b[31m", logging.INFO: "\x1b[32m",
}


class _Formatter(logging.Formatter):
    """``LEVEL date file:line] message``, colored on ttys."""

    def __init__(self, colored=True):
        super().__init__(datefmt="%m%d %H:%M:%S")
        self._colored = colored

    def format(self, record):
        char = _LEVEL_CHAR.get(record.levelno, "U")
        label = f"{char} {self.formatTime(record, self.datefmt)} " \
                f"{record.filename}:{record.lineno}]"
        if self._colored:
            color = _LEVEL_COLOR.get(record.levelno, "\x1b[34m")
            label = f"{color}{label}\x1b[0m"
        return f"{label} {record.getMessage()}"


def get_logger(name=None, filename=None, filemode=None, level=WARNING):
    """Return a logger configured with the mxnet formatter.

    Idempotent per name: an already-configured logger keeps its handler.
    `filename` switches to a FileHandler (mode `filemode`, default 'a').
    """
    logger = logging.getLogger(name)
    if getattr(logger, "_mx_log_configured", False):
        return logger
    if filename:
        handler = logging.FileHandler(filename, filemode or "a")
        colored = False
    else:
        handler = logging.StreamHandler(sys.stderr)
        colored = getattr(sys.stderr, "isatty", lambda: False)()
    handler.setFormatter(_Formatter(colored))
    logger.addHandler(handler)
    logger.setLevel(level)
    logger._mx_log_configured = True
    return logger


def getLogger(name=None, filename=None, filemode=None, level=WARNING):
    """Deprecated alias of :func:`get_logger` (reference keeps both)."""
    warnings.warn("getLogger is deprecated, use get_logger instead",
                  DeprecationWarning, stacklevel=2)
    return get_logger(name, filename, filemode, level)

"""mx.fault — deterministic fault injection + resilience event accounting.

Reference parity: none — the reference's failure story is "async errors
rethrow at the next sync point".  Production TPU training (preemptible
slices, flaky data pipelines, bf16 overflow) needs the failure paths to be
*testable*, so this module provides the chaos harness the resilience
machinery is validated against:

- **Injection points** are named call sites threaded through the stack
  (see ``POINTS``).  A disabled point costs one module-attribute read at
  the call site (``_active`` is False unless a spec is installed), so the
  eager dispatch hot path stays at pre-fault-framework cost.
- **Specs** arm points deterministically: by call count (``at=N``, the
  point's Nth probe fires) or by seeded probability (``prob=0.3``),
  optionally bounded (``times=K``).  Spec syntax (also via the
  ``MXNET_FAULT_SPEC`` env alias of the ``fault.spec`` config knob)::

      point:key=val,key=val[;point2:...]
      e.g.  dataloader.worker_crash:at=2
            invoke.nan_output:prob=0.05,seed=7,times=1

- **Events** count both injected faults and the recovery actions they
  provoke (worker respawns, skipped non-finite steps, checkpoint
  rejections...).  ``stats()`` returns the table; ``log_stats()`` emits
  it through ``mx.log`` so chaos tests and operators see exactly what
  fired and what recovered.

Spawned DataLoader worker processes re-import this module and re-read
``MXNET_FAULT_SPEC`` from their inherited environment, so worker-side
points (``dataloader.worker_crash``/``worker_hang``) arm in the worker
while parent-side state stays untouched.
"""
from __future__ import annotations

import random as _pyrandom
import threading

from . import config as _config
from . import telemetry as _telemetry
from .base import MXNetError

__all__ = ["POINTS", "configure", "clear", "active", "armed", "fire",
           "record", "stats", "reset_stats", "log_stats", "describe"]

#: every injection point threaded through the stack -> what arming it proves
POINTS = {
    "dataloader.worker_crash":
        "a multiprocess DataLoader worker dies mid-task (os._exit): the "
        "loader respawns the pool with backoff, bounded by "
        "dataloader.max_respawns, then degrades to threaded workers",
    "dataloader.worker_hang":
        "a worker stops producing (sleeps past the loader timeout): the "
        "heartbeat deadline treats it as dead and the respawn path runs",
    "pipeline.prefetch_stall":
        "a DevicePrefetcher's background thread wedges between batches "
        "(probed at the top of its loop, holding neither the source nor a "
        "batch): the consumer's stall deadline fires, a replacement "
        "thread takes over the same source iterator, and batch order is "
        "preserved",
    "invoke.nan_output":
        "an eager op returns all-NaN: the Trainer non-finite guard "
        "(trainer.skip_nonfinite) skips the step and counts it",
    "kvstore.collective_timeout":
        "a blocking dist collective never completes: the watchdog raises "
        "a structured CollectiveTimeout instead of hanging",
    "serialization.torn_write":
        "a checkpoint's bytes are silently truncated on disk: checksum "
        "validation rejects it and auto-resume picks the previous one",
    "resilience.preempt":
        "the cluster preempts this worker (SIGTERM analog, probed once "
        "per step): the in-flight step finishes, the TrainState bundle "
        "is written, and training stops with the resume sentinel",
    "autotune.trial_oom":
        "a measured autotune trial exhausts device memory (probed once "
        "per trial, before its step compiles): the candidate is recorded "
        "as oom in autotune.* telemetry and the search continues to the "
        "next grid point",
    "fleet.host_loss":
        "a peer host vanishes mid-run (its heartbeat lease expires with "
        "no clean exit; probed once per step): the fleet supervisor "
        "re-plans the mesh over the surviving devices, restores the "
        "last valid bundle bitwise, and continues at a smaller dp",
    "fleet.slow_host":
        "a host falls past fleet.slow_fraction of the step deadline but "
        "keeps making progress (probed once per step): the watchdog "
        "marks it a straggler (fleet.stragglers gauge) without killing "
        "it — slow, not wedged",
    "fleet.lease_lost":
        "this host's own heartbeat lease cannot be renewed "
        "(coordination service or lease dir unreachable): renewals are "
        "counted as failures, /healthz turns red, and the heartbeat "
        "keeps retrying",
    "blackbox.torn_bundle":
        "the host dies mid-write of a postmortem bundle (the just-"
        "written blackbox-<rank>-<step>.json is truncated after its "
        "checksum landed): verify_checksum rejects it, latest_bundle "
        "and tools/postmortem.py skip it, and the fleet merge proceeds "
        "on the surviving bundles",
    "stream.torn_record":
        "one streamed record's payload is corrupted in flight (probed "
        "per record read, before checksum verification): the per-record "
        "crc32 rejects it and stream.on_corrupt picks the path — 'skip' "
        "drops it with stream.records_skipped_total, 'raise' escalates "
        "a structured CorruptRecord the blackbox recorder carries into "
        "the postmortem bundle",
    "stream.shard_unreadable":
        "a shard archive cannot be opened (probed once per open "
        "attempt): bounded retry-with-backoff (stream.open_retries / "
        "stream.open_backoff) counts stream.open_retries_total, and "
        "exhausting the budget escalates a WorkerLost-style "
        "ShardUnreadable — a structured failure, never a hang",
    "serve.replica_crash":
        "a serving replica's host dies mid-stream (probed once per "
        "fleet supervisor tick): the mx.servefleet router marks the "
        "replica dead, its KV slots are gone, and every incomplete "
        "request re-dispatches to a survivor under its idempotency "
        "key, re-prefilling from the original prompt — no accepted "
        "request is dropped or double-completed",
    "serve.replica_stall":
        "a serving replica's step loop wedges while its lease stays "
        "fresh (probed once per fleet supervisor tick): after "
        "servefleet.stall_deadline without decode progress the "
        "supervisor declares it dead, re-dispatches its requests, and "
        "then drains the already-dispatched device work — any late "
        "completion racing the re-dispatch is suppressed by the "
        "idempotency ledger",
    "serve.prefix_evict":
        "a hot cached prefix is force-evicted from the radix index "
        "between a request's admission-time match and the KV row copy "
        "(probed once per prefix-cache hit): the engine falls back to "
        "a full prefill of the whole prompt — the output stays token-"
        "for-token identical, only the reuse saving is lost, counted "
        "in serve.prefix_misses_total",
    "insight.drift":
        "one observed step-time sample is stretched 3x (probed at "
        "every insight drift-feed sample): the EWMA+MAD detector must "
        "raise an insight.drift event within insight.drift_window "
        "samples, count insight.drift_events_total, and flip the "
        "/healthz insight provider to degraded",
}

_lock = threading.Lock()
_specs: dict[str, "_Spec"] = {}
_stats: dict[str, int] = {}
#: hot-path gate — call sites read this one attribute when deciding
#: whether to probe; False keeps every hook a no-op branch
_active = False


class _Spec:
    """One armed point: fires by call count and/or seeded probability."""

    __slots__ = ("point", "prob", "at", "times", "fired", "calls", "_rng")

    def __init__(self, point, prob=None, at=None, times=None, seed=0):
        self.point = point
        self.prob = prob
        self.at = at
        self.times = times
        self.fired = 0
        self.calls = 0
        # per-point stream: reproducible regardless of arming order
        self._rng = _pyrandom.Random(hash((point, seed)) & 0xFFFFFFFF)

    def probe(self, step=None):
        """Decide one probe.  ``step`` overrides the point's own call
        counter with an externally-maintained sequence number — the
        DataLoader passes its global task sequence so ``at=N`` stays
        deterministic across worker processes and pool respawns."""
        self.calls += 1
        if self.times is not None and self.fired >= self.times:
            return False
        hit = False
        if self.at is not None:
            hit = (step if step is not None else self.calls) == self.at
        if not hit and self.prob is not None:
            hit = self._rng.random() < self.prob
        if hit:
            self.fired += 1
        return hit


def _parse(spec_str):
    """``point:k=v,k=v;point2:...`` -> {point: _Spec}."""
    specs = {}
    for part in filter(None, (p.strip() for p in spec_str.split(";"))):
        point, _, argstr = part.partition(":")
        point = point.strip()
        if point not in POINTS:
            raise MXNetError(
                f"unknown fault injection point {point!r}; known: "
                f"{sorted(POINTS)}")
        kwargs = {}
        for item in filter(None, (a.strip() for a in argstr.split(","))):
            key, _, val = item.partition("=")
            key = {"p": "prob", "at_step": "at", "max": "times"}.get(key, key)
            if key == "prob":
                kwargs["prob"] = float(val)
            elif key in ("at", "times", "seed"):
                kwargs[key] = int(val)
            else:
                raise MXNetError(
                    f"fault spec {part!r}: unknown key {key!r} "
                    "(use prob=, at=, times=, seed=)")
        if "prob" not in kwargs and "at" not in kwargs:
            raise MXNetError(
                f"fault spec {part!r} needs a trigger: prob= or at=")
        specs[point] = _Spec(point, **kwargs)
    return specs


def configure(spec=None):
    """Install a fault spec (string, or None to re-read the ``fault.spec``
    config knob / ``MXNET_FAULT_SPEC`` env).  Replaces any previous spec."""
    global _active
    if spec is None:
        spec = _config.get("fault.spec") or ""
    with _lock:
        _specs.clear()
        _specs.update(_parse(spec) if spec else {})
        _active = bool(_specs)
    return sorted(_specs)


def clear():
    """Disarm every point (stats are kept; see ``reset_stats``)."""
    global _active
    with _lock:
        _specs.clear()
        _active = False


def active():
    """True when any point is armed (the hot-path gate)."""
    return _active


def armed(point):
    """True when this specific point is armed — lets recovery paths that
    normally only exist multi-process (e.g. the dist watchdog) engage for
    single-process chaos tests."""
    return _active and point in _specs


def fire(point, step=None):
    """Probe an armed point.  Returns True when the fault should happen
    now; counts both the probe and the injection.  ``step`` substitutes
    an external sequence number for the point's own call counter (see
    ``_Spec.probe``)."""
    if not _active:
        return False
    spec = _specs.get(point)
    if spec is None:
        return False
    with _lock:
        hit = spec.probe(step)
    if hit:
        record("injected." + point)
    return hit


def record(event, n=1):
    """Count a fault or recovery event (recovery code calls this even when
    injection is off — real faults are counted identically).  Every event
    also mirrors into ``mx.telemetry`` (``fault.events_total{event=...}``)
    when the metrics registry is enabled, so run reports and the
    Prometheus exposition carry the resilience picture."""
    with _lock:
        _stats[event] = _stats.get(event, 0) + n
    if _telemetry._active:
        _telemetry.inc("fault.events_total", n, event=event)


def stats():
    """Snapshot of every counter: ``injected.<point>`` plus recovery
    events (``dataloader.worker_respawn``, ``trainer.nonfinite_skip``,
    ``checkpoint.rejected``, ...)."""
    with _lock:
        return dict(sorted(_stats.items()))


def reset_stats():
    with _lock:
        _stats.clear()


def describe():
    """Human-readable table of points and any armed spec."""
    lines = []
    for point in sorted(POINTS):
        spec = _specs.get(point)
        state = "off"
        if spec is not None:
            parts = []
            if spec.at is not None:
                parts.append(f"at={spec.at}")
            if spec.prob is not None:
                parts.append(f"prob={spec.prob}")
            if spec.times is not None:
                parts.append(f"times={spec.times}")
            state = ",".join(parts) + f" (fired {spec.fired}/{spec.calls})"
        lines.append(f"{point} [{state}]: {POINTS[point]}")
    return "\n".join(lines)


def log_stats(level=None):
    """Emit the stats table through ``mx.log`` (chaos tests assert on the
    counters via ``stats()``; operators read this)."""
    from . import log as _log
    logger = _log.get_logger("mxnet_tpu.fault")
    snap = stats()
    if not snap:
        logger.info("fault: no events recorded")
        return snap
    width = max(map(len, snap))
    table = "\n".join(f"  {k:<{width}} {v}" for k, v in snap.items())
    logger.log(level if level is not None else _log.INFO,
               "fault event counters:\n%s", table)
    return snap


# arm from the environment at import so spawned DataLoader workers (which
# re-import the package) inherit the spec without any explicit handshake
if _config.get("fault.spec"):
    configure()
